#!/usr/bin/env python
"""Headline benchmark: batched ed25519 signature verification throughput
plus latency + tile-path records.

Mirrors the reference's north-star benchmark (BASELINE.json config #2: a
fixed batch of single-sig transfers through the verify hot path; reference
CPU throughput 30 K verifies/s/core, FPGA 1 M verifies/s/card —
src/wiredancer/README.md:100-104).  Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

vs_baseline is measured throughput / 1e6 (the 1 M verifies/s/chip target).

Record layout (round 4):
  value / runs_*        device-resident compute throughput, median of reps
  value_fresh           fresh-upload throughput: every iteration re-uploads
                        the txn bytes host->device (falsifiability record
                        for the ingest wall; this container's TUNNEL moves
                        ~10-25 MB/s where real PCIe moves GB/s).  Round 6:
                        driven through the double-buffered PackedIngest
                        engine (ingest_nbuf rotating blobs, ingest_depth
                        dispatch-ahead) so pack+upload overlaps verify
  device_batch_ms_*     device-side per-batch latency by a fori_loop slope:
                        one jitted graph runs K batches as ONE dispatch
                        (carried data dependence), timed at two K values —
                        (T2-T1)/(K2-K1) cancels RTT + dispatch overhead and
                        CANNOT go negative from per-dispatch jitter alone.
                        Round 6: reps whose slope exceeds 1.5x the min are
                        CONTENDED (multi-tenant chip); the protocol
                        re-measures until >=3 clean reps (or flags) and
                        emits device_batch_ms_max_clean + clean_reps
  p99_batch_ms          host-observed batch-256 latency through the async
                        VerifyPipeline (includes the tunnel RTT), with the
                        breakdown: coalesce_ms_* (batching window) and
                        rtt_floor_ms (pure round-trip floor)
  pipe_vps              tile-path throughput via the native BURST data
                        plane (parse+dedup+bucket in C, fresh bytes up)
  pipe_host_us_txn      host-side burst-path cost per txn vs a no-op device
  mp_vps / mp_tiles     multi-process topology throughput: source -> N
                        round-robin verify tile PROCESSES over tango rings
                        (set FDTPU_BENCH_MP=0 to skip)

Measurement notes (hard-won, do not regress):
  * ``block_until_ready()`` does NOT await remote completion on this
    container's tunneled TPU; only a device->host fetch (``np.asarray``)
    truly synchronizes.  Throughput therefore uses pipelined dispatch of
    all iterations followed by ONE final fetch of the last output.
  * This host has ONE CPU core: anything host-bound (parse, process
    benches) reflects single-core performance by construction.
"""

import json
import os
import sys
import time

import numpy as np


def measure_throughput(verifier, args, iters: int) -> float:
    """Verifies/sec with pipelined dispatch and one true final sync."""
    t0 = time.perf_counter()
    ok = None
    for _ in range(iters):
        ok = verifier(*args)
    np.asarray(ok)  # in-order device queue: draining the last drains all
    dt = time.perf_counter() - t0
    return args[2].shape[0] * iters / dt


def measure_throughput_median(verifier, args, iters: int, reps: int):
    """Repeated-run protocol for the shared chip's ±20-30% run-to-run
    variance: the headline is the MEDIAN of `reps` measurements."""
    runs = sorted(measure_throughput(verifier, args, iters)
                  for _ in range(reps))
    return runs[len(runs) // 2], runs


def measure_throughput_fresh(verifier, args, iters: int,
                             nbuf: int = 3, depth: int = 2,
                             stats: dict | None = None) -> float:
    """Fresh-upload throughput: re-upload every input byte each iteration
    (the falsifiable ingest-inclusive record — VERDICT r3 weak #3), via
    the PACKED single-blob dispatch (round 5) driven through the
    DOUBLE-BUFFERED ingest engine (round 6): `nbuf` rotating host blobs,
    batch k+1 packs + device_puts while batch k verifies, inflight window
    `depth` with backpressure (models.verifier.PackedIngest — wiredancer's
    async DMA push, wd_f1.h:85-113).  Message columns are trimmed to the
    batch's true maximum length — the bytes a wire-honest ingest moves.
    The serial (fetch-per-batch) baseline and the overlap factor are
    recorded same-session by tools/exp_r6_overlap.py."""
    host = [np.asarray(a) for a in args]
    ml = int(host[1].max())
    eng = verifier.make_ingest(ml=ml, nbuf=nbuf, depth=depth)
    eng.submit(*host)                       # compile + warm
    eng.drain()
    eng.pack_ns = eng.pack_txns = 0         # exclude warmup from pack stat
    t0 = time.perf_counter()
    for _ in range(iters):
        eng.submit(*host)
    eng.drain()
    dt = time.perf_counter() - t0
    if stats is not None:
        # host-side pack cost rides along (BENCH ingest_pack_us_txn): the
        # single-concatenate _pack_into pass, measured inside the engine
        stats["pack_us_txn"] = eng.pack_us_txn
        stats["backpressure_waits"] = eng.backpressure_waits
    return args[2].shape[0] * iters / dt


def measure_device_batch_ms(batch: int, maxlen: int,
                            k1: int = 4, k2: int = 36,
                            reps: int = 5, min_clean: int = 3,
                            max_reps: int = 15) -> dict:
    """Device-side per-batch verify time: ONE dispatch runs K batches in a
    jitted lax.fori_loop whose carry feeds each batch's output back into
    the next input byte (no hoisting possible); (T(k2)-T(k1))/(k2-k1)
    cancels the tunnel RTT and the per-dispatch host overhead.  Unlike the
    r3 protocol (two pipelined dispatch chains), both timings are single
    dispatches, so per-dispatch jitter cannot produce a negative slope."""
    import jax
    import jax.numpy as jnp

    from firedancer_tpu.ops import ed25519 as ed

    za = (jnp.zeros((batch, maxlen), jnp.uint8),
          jnp.zeros((batch,), jnp.int32),
          jnp.zeros((batch, 64), jnp.uint8),
          jnp.zeros((batch, 32), jnp.uint8))

    def make(k):
        @jax.jit
        def f(msgs, lens, sigs, pubs):
            def body(_, m):
                ok = ed.verify_batch(m, lens, sigs, pubs)
                return m.at[0, 0].set(m[0, 0] ^ ok[0].astype(jnp.uint8))
            return jax.lax.fori_loop(0, k, body, msgs)[0, 0]
        return f

    f1, f2 = make(k1), make(k2)
    np.asarray(f1(*za))  # compile + warm
    np.asarray(f2(*za))

    def one_slope():
        ts = []
        for f in (f1, f2):
            t0 = time.perf_counter()
            np.asarray(f(*za))
            ts.append(time.perf_counter() - t0)
        return (ts[1] - ts[0]) / (k2 - k1) * 1e3

    # Clean/contended separation (VERDICT r5 Next #6): a rep whose slope
    # exceeds 1.5x the observed minimum saw external load mid-window (the
    # chip is multi-tenant).  Re-measure until >= min_clean clean reps so
    # the max_clean record describes THIS kernel, not a neighbor's job;
    # if max_reps runs dry first, `flagged` marks the record suspect.
    slopes = [one_slope() for _ in range(reps)]
    def clean(ss):
        mn = min(ss)
        return [s for s in ss if s <= 1.5 * mn]
    while len(clean(slopes)) < min_clean and len(slopes) < max_reps:
        slopes.append(one_slope())
    cl = sorted(clean(slopes))
    slopes.sort()
    return {"p50_ms": slopes[len(slopes) // 2], "max_ms": slopes[-1],
            "min_ms": slopes[0], "reps": len(slopes), "k": (k1, k2),
            "contended": len(slopes) - len(cl),
            "max_clean_ms": cl[-1],
            "clean_reps": len(cl),
            "flagged": len(cl) < min_clean}


def _gen_payload_array(n_txn: int, seed: int = 7) -> np.ndarray:
    """Unique-tag txn payloads built by numpy template stamping (the
    burst source's trick): uniqueness defeats dedup, the invalid sigs
    cost the fixed-shape device graph nothing.  Returns the stamped
    (n_txn, L) array — every row one wire txn of identical length."""
    from firedancer_tpu.ballet import txn as txn_lib

    rng = np.random.default_rng(seed)
    pub = rng.bytes(32)
    msg = txn_lib.build_unsigned(
        [pub], rng.bytes(32), [(1, bytes([0]), bytes(8))],
        extra_accounts=[rng.bytes(32)])
    tpl = np.frombuffer(txn_lib.assemble([rng.bytes(64)], msg),
                        np.uint8).copy()
    L = len(tpl)
    arr = np.tile(tpl, (n_txn, 1))
    tags = rng.integers(1, 1 << 63, size=n_txn, dtype=np.uint64)
    arr[:, 1:9] = tags.view(np.uint8).reshape(n_txn, 8)
    arr[:, L - 8:] = np.arange(n_txn, dtype=np.uint64).view(
        np.uint8).reshape(n_txn, 8)
    return arr


def _gen_payloads(n_txn: int, seed: int = 7):
    """Python list-of-bytes form (the pre-round-7 protocol, kept as the
    before/after baseline for the packed generator below)."""
    arr = _gen_payload_array(n_txn, seed)
    return [arr[i].tobytes() for i in range(n_txn)]


def _gen_payloads_packed(n_txn: int, seed: int = 7):
    """(buf, offsets) burst-window form with NO per-row .tobytes() loop:
    the stamped array IS the contiguous buffer (equal-length rows), the
    int64 offsets are an arange.  This is what the ring rx scratch hands
    the tile — the list-of-bytes detour was bench-only overhead."""
    arr = _gen_payload_array(n_txn, seed)
    L = arr.shape[1]
    offs = np.arange(n_txn + 1, dtype=np.int64) * L
    return np.ascontiguousarray(arr).reshape(-1), offs


def measure_p99_ms(verify_fn, batch: int, msg_maxlen: int, reps: int) -> dict:
    """Host-observed batch latency through VerifyPipeline at a fixed
    offered load, with the coalesce/dispatch decomposition."""
    from firedancer_tpu.disco.pipeline import VerifyPipeline

    if hasattr(verify_fn, "dispatch_blob"):
        np.asarray(verify_fn.dispatch_blob(
            np.zeros((batch, msg_maxlen + 100), np.uint8)))
    else:
        np.asarray(verify_fn(
            np.zeros((batch, msg_maxlen), np.uint8),
            np.zeros((batch,), np.int32),
            np.zeros((batch, 64), np.uint8),
            np.zeros((batch, 32), np.uint8)))
    pipe = VerifyPipeline(verify_fn, batch=batch, msg_maxlen=msg_maxlen)
    payloads = _gen_payloads(batch * reps, seed=42)
    for i in range(0, len(payloads), batch):
        pipe.submit_burst(payloads[i:i + batch])
    pipe.flush()
    snap = pipe.metrics.snapshot()
    return {
        "p50_ms": snap["batch_ns_p50"] / 1e6,
        "p99_ms": snap["batch_ns_p99"] / 1e6,
        "coalesce_p50_ms": snap["coalesce_ns_p50"] / 1e6,
        "coalesce_p99_ms": snap["coalesce_ns_p99"] / 1e6,
        "batches": snap["batches"],
        # fdtrace compile/occupancy records: recompiles seen on THIS
        # pipeline (warmup above pre-traces the shape, so >0 here means
        # an unexpected bucket recompile) and mean dispatched-lane fill
        "compile_cnt": snap["compile_cnt"],
        "compile_ms": snap["compile_ns"] / 1e6,
        "fill_pct": round(100.0 * snap["lanes_filled"]
                          / max(snap["lanes_dispatched"], 1), 1),
    }


def measure_dual_lane(verify_fn, bulk_batch: int, maxlen: int, n_bulk: int,
                      lat_shapes=(16, 64, 256), deadline_us: int = 2000,
                      n_probes: int = 64, lat_max_inflight: int = 4,
                      chunk: int | None = None,
                      max_inflight: int = 16) -> dict:
    """Mixed-load dual-lane record (round 9): latency-class probe txns
    interleave with a bulk firehose through ONE pipeline, and the two
    lanes report separately — `lat_p99_ms` from the low-latency lane's
    admit->verdict histogram, `bulk_vps` from the throughput lane — so a
    latency win can't hide a throughput regression or vice versa.

    Two legs over identical traffic:
      single  the pre-PR shape: probes ride the bulk bucket (lat=False),
              their latency is the bulk batch's e2e p99
      dual    probes take the deadline-driven small-shape lane (lat=True)

    Every shape (bulk + lat ladder) is compiled OUTSIDE the timed window
    and mark_warm'd, so `compile_cnt` > 0 here means a compile landed on
    the hot path — the no-compile-storm gate ci.sh asserts on.

    The drive loop submits bulk in `chunk`-txn windows and services the
    deadline (`dispatch_due`) between windows; `max_inflight` is kept
    deep enough that the driver never blocks in harvest — a blocked
    driver can't service deadlines and would inflate lat p99 with its
    own stall, not the lane's."""
    from firedancer_tpu.disco.pipeline import VerifyPipeline

    packed = hasattr(verify_fn, "dispatch_blob")
    shapes = sorted(set(int(s) for s in lat_shapes)) + [bulk_batch]
    for b in shapes:
        if packed:
            np.asarray(verify_fn.dispatch_blob(
                np.zeros((b, maxlen + 100), np.uint8)))
        else:
            np.asarray(verify_fn(
                np.zeros((b, maxlen), np.uint8),
                np.zeros((b,), np.int32),
                np.zeros((b, 64), np.uint8),
                np.zeros((b, 32), np.uint8)))

    buf, offs = _gen_payloads_packed(n_bulk, seed=21)
    probes = _gen_payloads(max(1, n_probes), seed=23)
    chunk = chunk or max(1, bulk_batch // 8)
    n_iter = (n_bulk + chunk - 1) // chunk
    probe_every = max(1, n_iter // len(probes))

    def leg(dual: bool) -> dict:
        pipe = VerifyPipeline(
            verify_fn, batch=bulk_batch, msg_maxlen=maxlen,
            tcache_depth=1 << 21, max_inflight=max_inflight,
            lat_shapes=(lat_shapes if dual else None),
            deadline_us=deadline_us, lat_max_inflight=lat_max_inflight)
        pipe.mark_warm([(b, maxlen) for b in shapes])
        sent = it = 0
        t0 = time.perf_counter()
        for i in range(0, n_bulk, chunk):
            if it % probe_every == 0 and sent < len(probes):
                pipe.submit(probes[sent], lat=dual)
                sent += 1
            pipe.submit_burst(packed=(buf, offs[i:i + chunk + 1]))
            pipe.dispatch_due()
            it += 1
        pipe.flush()
        dt = time.perf_counter() - t0
        return {"dt": dt, "snap": pipe.metrics.snapshot(), "probes": sent}

    base = leg(False)
    dual = leg(True)
    sb, sd = base["snap"], dual["snap"]
    return {
        "lat_p99_ms": sd["lat_e2e_ns_p99"] / 1e6,
        "lat_p50_ms": sd["lat_e2e_ns_p50"] / 1e6,
        "lat_vps": sd["lat_txns"] / dual["dt"],
        "bulk_vps": (sd["txns_in"] - sd["lat_txns"]) / dual["dt"],
        "single_p99_ms": sb["e2e_ns_p99"] / 1e6,
        "single_vps": sb["txns_in"] / base["dt"],
        "lat_txns": sd["lat_txns"],
        "lat_spill_cnt": sd["lat_spill"],
        "lat_batches": sd["lat_batches"],
        "lat_deadline_closes": sd["lat_deadline_closes"],
        "compile_cnt": sb["compile_cnt"] + sd["compile_cnt"],
        "deadline_us": deadline_us,
        "lat_shapes": [int(s) for s in lat_shapes],
        "probes": dual["probes"],
    }


def measure_pipe_vps(verify_fn, batch: int, maxlen: int, n_txn: int) -> float:
    """Tile-path throughput via the BURST data plane: native parse ->
    inline dedup -> bucket fill -> async dispatch -> ordered harvest,
    fresh bytes device-bound every batch.

    Bursts enter PRE-PACKED as (buf, offsets) windows — the verify tile's
    actual input shape (the ring rx scratch from fd_ring_rx_burst is
    consumed zero-copy); feeding python byte lists instead re-paid a
    join+slice per burst that the real tile never does."""
    from firedancer_tpu.disco.pipeline import VerifyPipeline

    buf, offs = _gen_payloads_packed(n_txn)
    if hasattr(verify_fn, "dispatch_blob"):  # warm the packed-blob graph
        np.asarray(verify_fn.dispatch_blob(
            np.zeros((batch, maxlen + 100), np.uint8)))
    else:
        np.asarray(verify_fn(
            np.zeros((batch, maxlen), np.uint8),
            np.zeros((batch,), np.int32),
            np.zeros((batch, 64), np.uint8),
            np.zeros((batch, 32), np.uint8)))
    pipe = VerifyPipeline(verify_fn, batch=batch, msg_maxlen=maxlen,
                          tcache_depth=1 << 21, max_inflight=16,
                          n_buffers=int(os.environ.get(
                              "FDTPU_BENCH_NBUF", 3)))
    chunk = batch  # one submit per device batch (c1024 measured 110 K/s,
    # c4096 152 K/s, c=batch 222 K/s at batch 16384)
    t0 = time.perf_counter()
    for i in range(0, n_txn, chunk):
        pipe.submit_burst(packed=(buf, offs[i:i + chunk + 1]))
    pipe.flush()
    dt = time.perf_counter() - t0
    assert pipe.metrics.txns_in == n_txn
    return n_txn / dt


def measure_pipe_host_us(batch: int, maxlen: int, n_txn: int,
                         packed: bool = False) -> float:
    """Host-side burst-path cost alone (native parse -> dedup -> bucket
    fill) with a no-op device: microseconds per txn on this ONE core.
    The reference budgets ~30 us/txn/core (33 verify cores for 1M/s,
    bench-icelake-80core.toml).  packed=True feeds (buf, offsets)
    windows instead of python byte lists — the before/after pair for
    the round-7 packed payload generator."""
    from firedancer_tpu.disco.pipeline import VerifyPipeline

    if packed:
        buf, offs = _gen_payloads_packed(n_txn, seed=11)
    else:
        payloads = _gen_payloads(n_txn, seed=11)

    def fake(m, l, s, p):
        return np.ones((np.asarray(m).shape[0],), bool)

    pipe = VerifyPipeline(fake, batch=batch, msg_maxlen=maxlen,
                          tcache_depth=1 << 21, max_inflight=8)
    chunk = 1024
    t0 = time.perf_counter()
    for i in range(0, n_txn, chunk):
        if packed:
            pipe.submit_burst(packed=(buf, offs[i:i + chunk + 1]))
        else:
            pipe.submit_burst(payloads[i:i + chunk])
    pipe.flush()
    return (time.perf_counter() - t0) / n_txn * 1e6


def measure_pipe_host_us_rows(batch: int, n_txn: int) -> float:
    """Round-8 zero-repack host path with a no-op device: wire txns
    pre-stamped into packed rows (the dcache chunk layout) go tag-gather
    -> dedup query -> dispatch_blob as VIEWS — zero payload copies
    between ring rx and device dispatch.  FDTPU_INGEST_LEGACY_PACK=1
    routes the SAME wires through the legacy (buf, offsets)
    parse+scatter path instead (the pre-round-8 tile host plane), so the
    two readings A/B one knob on one workload."""
    from firedancer_tpu.disco.pipeline import VerifyPipeline
    from firedancer_tpu.models.verifier import use_legacy_pack
    from firedancer_tpu.tango.ring import PACKED_ROW_EXTRA, packed_row_ml

    arr = _gen_payload_array(n_txn, seed=13)
    nblk = max(1, len(arr) // batch)
    n_txn = nblk * batch
    arr = arr[:n_txn]

    class _Fake:
        def __call__(self, m, l, s, p):
            return np.ones((np.asarray(m).shape[0],), bool)

        def dispatch_blob(self, blob, maxlen=None):
            return np.ones((blob.shape[0],), bool)

    if use_legacy_pack():
        buf = np.ascontiguousarray(arr).reshape(-1)
        offs = np.arange(n_txn + 1, dtype=np.int64) * arr.shape[1]
        pipe = VerifyPipeline(_Fake(), batch=batch, msg_maxlen=256,
                              tcache_depth=1 << 21, max_inflight=8)
        t0 = time.perf_counter()
        for i in range(0, n_txn, batch):
            pipe.submit_burst(packed=(buf, offs[i:i + batch + 1]))
        pipe.flush()
        return (time.perf_counter() - t0) / n_txn * 1e6

    # views-on lane: stamp rows ONCE (the producer tile does this into
    # the dcache; it is generation, not part of the rx->dispatch hop),
    # then the timed loop only touches views of the arena
    ml = packed_row_ml(256)
    stride = ml + PACKED_ROW_EXTRA
    L = arr.shape[1]
    msk = L - 65  # wire = 0x01 | sig64 | msg
    rows = np.zeros((nblk, batch, stride), np.uint8)
    flat = rows.reshape(n_txn, stride)
    flat[:, :msk] = arr[:, 65:]
    flat[:, ml:ml + 64] = arr[:, 1:65]
    flat[:, ml + 96:ml + 100] = np.full(
        (n_txn, 1), msk, np.int32).view(np.uint8)
    pipe = VerifyPipeline(_Fake(), buckets=[(batch, ml)],
                          tcache_depth=1 << 21, max_inflight=8)
    t0 = time.perf_counter()
    for k in range(nblk):
        pipe.submit_packed_rows(rows[k])
    pipe.harvest(block=True)
    return (time.perf_counter() - t0) / n_txn * 1e6


def measure_hostpath_packed_egress(batch: int, n_txn: int):
    """Round-11 packed verdict egress arm: the views workload of
    measure_pipe_host_us_rows with egress_packed=True, so each harvested
    frag leaves the pipeline as ONE PackedVerdicts arena instead of k
    per-txn bytes objects (the form the verify tile publishes downstream
    as a single frag).  Returns (us/txn, identical) where identical is
    the egress bit-identity gate: packed arenas' wires() vs the legacy
    per-txn list on a fixed mixed-verdict, mixed-length seed."""
    from firedancer_tpu.disco.pipeline import VerifyPipeline
    from firedancer_tpu.tango.ring import PACKED_ROW_EXTRA, packed_row_ml

    arr = _gen_payload_array(n_txn, seed=13)
    nblk = max(1, len(arr) // batch)
    n_txn = nblk * batch
    arr = arr[:n_txn]

    class _Fake:
        def __call__(self, m, l, s, p):
            return np.ones((np.asarray(m).shape[0],), bool)

        def dispatch_blob(self, blob, maxlen=None):
            return np.ones((blob.shape[0],), bool)

    ml = packed_row_ml(256)
    stride = ml + PACKED_ROW_EXTRA
    L = arr.shape[1]
    msk = L - 65  # wire = 0x01 | sig64 | msg
    rows = np.zeros((nblk, batch, stride), np.uint8)
    flat = rows.reshape(n_txn, stride)
    flat[:, :msk] = arr[:, 65:]
    flat[:, ml:ml + 64] = arr[:, 1:65]
    flat[:, ml + 96:ml + 100] = np.full(
        (n_txn, 1), msk, np.int32).view(np.uint8)
    pipe = VerifyPipeline(_Fake(), buckets=[(batch, ml)],
                          tcache_depth=1 << 21, max_inflight=8,
                          egress_packed=True)
    t0 = time.perf_counter()
    for k in range(nblk):
        pipe.submit_packed_rows(rows[k])
    pipe.harvest(block=True)
    us = (time.perf_counter() - t0) / n_txn * 1e6
    return us, _egress_packed_identical()


def _egress_packed_identical() -> bool:
    """Egress bit-identity gate: packed-arena wires == the legacy
    per-txn egress bytes, same order and same metrics, on fixed
    mixed-length frags with deterministic mixed verdicts and a
    resubmitted frag (cross-frag dedup exercised).  Runs whichever
    finish path is loaded (C kernel or NumPy fallback)."""
    from firedancer_tpu.disco.pipeline import VerifyPipeline
    from firedancer_tpu.tango.ring import PACKED_ROW_EXTRA, packed_row_ml

    ml = packed_row_ml(256)
    stride = ml + PACKED_ROW_EXTRA
    rng = np.random.default_rng(17)
    n = 64
    frags = []
    for _ in range(4):
        rows = np.zeros((n, stride), np.uint8)
        lens = rng.integers(0, ml + 1, n)
        for i in range(n):
            li = int(lens[i])
            rows[i, :li] = rng.integers(0, 256, li, dtype=np.uint8)
            rows[i, ml:ml + 64] = rng.integers(0, 256, 64, dtype=np.uint8)
            rows[i, ml] = 1 + (i % 251)   # tags never the dead-lane 0
            rows[i, ml + 96:ml + 100] = np.frombuffer(
                li.to_bytes(4, "little"), np.uint8)
        frags.append(rows)
    frags.append(frags[0])                # cross-frag dups

    class _Mixed:
        def __call__(self, m, l, s, p):
            return np.ones((np.asarray(m).shape[0],), bool)

        def dispatch_blob(self, blob, maxlen=None):
            # deterministic mixed verdicts off a signature byte
            return (blob[:, blob.shape[1] - 100 + 1] & 3) != 0

    def run(packed: bool):
        pipe = VerifyPipeline(_Mixed(), buckets=[(n, ml)],
                              tcache_depth=1 << 12, max_inflight=0,
                              egress_packed=packed)
        wires = []
        for rows in frags:
            for out in pipe.submit_packed_rows(rows):
                wires += out.wires() if packed else [out[0]]
        s = dict(pipe.metrics.snapshot())
        return wires, {k: s[k] for k in ("txns_in", "dedup_drop",
                                         "verify_fail", "verify_pass",
                                         "torn_drop", "torn_txns")}

    pw, pm = run(True)
    lw, lm = run(False)
    return bool(pw == lw and pw and pm == lm)


def measure_mp_vps(n_verify: int, batch: int, duration_s: float,
                   packed: bool = False) -> dict:
    """Multi-process topology throughput (VERDICT r3 #2): burst source ->
    N round-robin verify tile PROCESSES -> dedup -> sink, all over tango
    shared-memory rings, every verify tile dispatching real device
    batches.  Measures verify-tile txn intake per second of steady state.
    NOTE this host has ONE core: N processes timeshare it, so N>1 shows
    the architecture scaling shape, not a core-parallel speedup."""
    from firedancer_tpu.app import config as app_config
    from firedancer_tpu.disco.run import TopoRun
    from firedancer_tpu.utils import aot

    # AOT-prime the verify-tile executable (VERDICT r4 #2): children load
    # the serialized artifact in ~1 s each instead of re-tracing the graph
    # (minutes under N-child contention on this 1-core host — the round-4
    # 240 s boot timeout).  aot_require below makes any miss loud.
    aot_dir = os.environ.get(
        "FDTPU_AOT_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".aot"))
    # packed-wire mode verifies dcache rows at the chunk-aligned message
    # width (stride = ml + 100 is a whole number of chunks), so its AOT
    # executable is keyed on that ml, not the raw 256 maxlen
    from firedancer_tpu.tango.ring import packed_row_ml
    ml = packed_row_ml(256) if packed else 256
    aot_ok = aot.ensure_verify_packed(aot_dir, batch, ml) is not None
    if not aot_ok:
        # backend can't round-trip executables (XLA:CPU artifact quirk):
        # fall back to jit boot from the shared XLA cache, pre-compiled here
        import jax
        import jax.numpy as jnp

        from firedancer_tpu.ops import ed25519 as ed
        if packed:
            import functools
            jax.jit(functools.partial(ed.verify_blob, maxlen=ml, ml=ml))(
                jnp.zeros((batch, ml + 100), jnp.uint8)).block_until_ready()
        else:
            jax.jit(ed.verify_batch)(
                jnp.zeros((batch, 256), jnp.uint8),
                jnp.zeros((batch,), jnp.int32),
                jnp.zeros((batch, 64), jnp.uint8),
                jnp.zeros((batch, 32), jnp.uint8)).block_until_ready()

    cfg = app_config.load(None)
    cfg["topology"] = "verify-bench"
    cfg["layout"]["verify_tile_count"] = n_verify
    cfg["development"]["source_count"] = 0  # count=0 -> unbounded
    cfg["layout"]["affinity"] = os.environ.get("FDTPU_BENCH_AFFINITY", "")
    if packed:
        cfg["development"]["packed_wire"] = 1
        cfg["development"]["burst_splits"] = max(2, n_verify)
    t = cfg["tiles"]["verify"]
    t["batch"] = batch
    t["msg_maxlen"] = 256
    t["tcache_depth"] = 1 << 20
    # a big-batch dispatch under N-process contention on a 1-core host
    # legitimately outlasts any sane hang deadline — disable the
    # GuardedVerifier watchdog so the bench never host-falls-back
    t["supervision"] = {"device_deadline_s": 0.0}
    if aot_ok:
        t["aot_dir"] = aot_dir
        t["aot_require"] = True
    spec = app_config.build_topology(cfg)
    if not packed:
        for ts in spec.tiles:
            if ts.kind == "source":
                ts.cfg["burst_n"] = 2048  # numpy firehose (one publish/loop)

    def verify_tiles(run):
        return {ts.name: run.metrics(ts.name) for ts in spec.tiles
                if ts.kind == "verify"}

    run = TopoRun(spec)
    try:
        t_boot = time.monotonic()
        run.wait_ready(timeout=240)
        # steady state gate (round-7 regression diagnosis): the old
        # predicate (txn_in_cnt > 0) opened the measure window while a
        # tile could still be compiling/warming its first device batch —
        # those seconds of zero intake dragged the reported vps.  Require
        # every tile to have COMPLETED >= 1 device batch (batch_cnt) so
        # compile + first-dispatch warmup sit outside the window.
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if all(v.get("txn_in_cnt", 0) > 0 and v.get("batch_cnt", 0) >= 1
                   for v in verify_tiles(run).values()):
                break
            time.sleep(1.0)
        ready_s = time.monotonic() - t_boot
        s0 = verify_tiles(run)
        t0 = time.monotonic()
        time.sleep(duration_s)
        s1 = verify_tiles(run)
        dt = time.monotonic() - t0
        per = {k: (s1[k].get("txn_in_cnt", 0)
                   - s0[k].get("txn_in_cnt", 0)) / dt for k in s1}
        return {"vps": sum(per.values()), "tiles": n_verify,
                "per_tile": [round(per[k], 1) for k in sorted(per)],
                "ready_s": round(ready_s, 1), "packed": packed,
                "torn": sum(v.get("torn_drop_cnt", 0)
                            for v in s1.values())}
    finally:
        run.close()


def measure_mc_vps(batch: int, iters: int, ml: int = 64) -> dict:
    """Multi-chip serving throughput (round 7): the SAME fresh-ingest
    engine (PackedIngest rotation) over a mesh-mode SigVerifier — one
    device_put per rotation splits the packed blob P("dp", None) across
    every visible device, the donated shard_map step verifies the row
    shards.  Runs in-process against all visible devices (a real slice
    when attached); requires >= 2 devices — single-device hosts go
    through _mc_subprocess's 8-virtual-device CPU mesh instead.

    The sharded verdict is bit-checked against the single-chip engine on
    a mixed valid/invalid batch before timing: a multichip lane that
    drifts from the single-chip bits is a wrong answer fast, not a
    record."""
    import jax

    from firedancer_tpu.models.verifier import (
        SigVerifier, VerifierConfig, make_example_batch)
    from firedancer_tpu.parallel import mesh as pm

    n = len(jax.devices())
    if n < 2:
        raise RuntimeError(f"multichip lane needs >= 2 devices, have {n}")
    cfg = VerifierConfig(batch=batch, msg_maxlen=ml)
    args = make_example_batch(batch, ml, valid=True, seed=5)
    single = SigVerifier(cfg)
    sharded = SigVerifier(cfg, mesh=pm.make_mesh(n))

    # bit-identity gate on a mixed batch (every 7th sig tampered)
    sigs = np.array(args[2])
    sigs[::7, 3] ^= 0xA5
    ref = np.asarray(single.packed_dispatch(args[0], args[1], sigs, args[3]))
    got = np.asarray(sharded.packed_dispatch(args[0], args[1], sigs, args[3]))
    identical = bool((ref == got).all()) and not bool(ref[::7].any())

    single_vps = measure_throughput_fresh(single, args, iters)
    mc_vps = measure_throughput_fresh(sharded, args, iters)
    return {"vps": mc_vps, "devices": n,
            "vs_single": mc_vps / max(single_vps, 1e-9),
            "single_vps": single_vps, "identical": identical,
            "platform": jax.default_backend()}


def _mc_subprocess(batch: int, iters: int) -> dict:
    """Single-device fallback for the multichip lane: a child bench
    process with XLA's 8-virtual-CPU-device flag runs the IDENTICAL
    SPMD program a v5e-8 slice executes over ICI (parallel/mesh.py's
    contract) and prints measure_mc_vps's dict as its one JSON line.
    A subprocess because the device count is fixed at backend init —
    the parent's backend is already up.  Failure records an mc_vps of
    -1 with the error; the bench line itself is never lost."""
    import subprocess

    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    env["FDTPU_BENCH_MC_ONLY"] = "1"
    env["FDTPU_BENCH_MC_FORCE_CPU"] = "1"  # config'd pre-init in main()
    env["FDTPU_BENCH_MC_BATCH"] = str(batch)
    env["FDTPU_BENCH_MC_ITERS"] = str(iters)
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            capture_output=True, text=True,
            timeout=float(os.environ.get("FDTPU_BENCH_MC_TIMEOUT", 1500)))
        if out.returncode:
            raise RuntimeError(
                f"rc={out.returncode}: {out.stderr.strip()[-160:]}")
        return json.loads(out.stdout.strip().splitlines()[-1])
    except Exception as e:  # timeout, crash, bad JSON — record, don't die
        return {"vps": -1.0, "devices": 0, "vs_single": 0.0,
                "identical": False, "platform": "subprocess",
                "error": str(e)[:160]}


def _net_topology_spec(packed: bool):
    """quic_server -> verify -> dedup -> sink over loopback; `packed`
    flips the quic tile to packed-row publication with the matching
    packed_wire verify consumer (the production [quic] packed_publish
    shape)."""
    from firedancer_tpu.disco.topo import TopoBuilder
    from firedancer_tpu.tango.ring import PACKED_ROW_EXTRA, packed_row_ml

    batch = 16
    vcfg = dict(batch=batch, msg_maxlen=256, flush_age_ns=50_000_000)
    qcfg = dict(port=0)
    b = TopoBuilder(f"netvps{'p' if packed else ''}{os.getpid()}",
                    wksp_mb=32)
    if packed:
        ml = packed_row_ml(256)
        vcfg.update(packed_wire=1, buckets=[[batch, ml]])
        qcfg.update(packed_publish=1, packed_rows=batch, packed_ml=ml,
                    packed_flush_age_ns=20_000_000)
        b.link("quic_verify", depth=16, mtu=batch * (ml + PACKED_ROW_EXTRA))
    else:
        b.link("quic_verify", depth=256, mtu=1280)
    return (
        b.link("verify_dedup", depth=256, mtu=1280)
        .link("dedup_sink", depth=256, mtu=1280)
        .tile("quic_server", "quic_server", outs=["quic_verify"], **qcfg)
        .tile("verify", "verify", ins=["quic_verify"],
              outs=["verify_dedup"], **vcfg)
        .tile("dedup", "dedup", ins=["verify_dedup"], outs=["dedup_sink"],
              tcache_depth=1 << 20)
        .tile("sink", "sink", ins=["dedup_sink"])
        .build()
    )


def measure_net_vps(duration_s: float, packed: bool = False) -> dict:
    """e2e front-door lane (round 10): a live QUIC client over loopback
    drives the quic_server tile -> verify -> dedup -> sink topology.
    Phase 1 replays a FIXED mixed valid/invalid txn set and measures
    chunked packet->verdict latency (send a verify batch, wait for its
    verdicts at the sink); its pass/sink counts are the packed-vs-legacy
    bit-identity probe — both modes must produce the exact same verdict
    stream.  Phase 2 firehoses a cycling txn pool for duration_s and
    reports verify-lane verdicts/sec.  The full QUIC handshake/AEAD/
    stream machinery is in the path: this is the wire number, not the
    device number."""
    from firedancer_tpu.ballet import txn as txn_lib
    from firedancer_tpu.disco.run import TopoRun
    from firedancer_tpu.ops import ed25519 as ed
    from firedancer_tpu.waltz.quic import QuicConfig, QuicEndpoint
    from firedancer_tpu.waltz.udpsock import UdpSock

    rng = np.random.default_rng(17)
    pool = []
    for _ in range(4):
        s = rng.bytes(32)
        pub, _, _ = ed.keypair_from_seed(s)
        pool.append((s, pub))
    blockhash, program = rng.bytes(32), rng.bytes(32)

    def mk(i):
        s, pub = pool[i % 4]
        msg = txn_lib.build_unsigned(
            [pub], blockhash, [(1, bytes([0]), i.to_bytes(8, "little"))],
            extra_accounts=[program])
        return txn_lib.assemble([ed.sign(s, msg)], msg)

    CH = 16                      # one verify batch per latency chunk
    n_fix = 16 * CH
    fixed = [mk(i) for i in range(n_fix)]
    for j in range(0, n_fix, 8):     # every 8th: tampered sig, must FAIL
        t = bytearray(fixed[j])
        t[1 + 10] ^= 0x40
        fixed[j] = bytes(t)
    exp_pass_chunk = CH - 2
    cycle = [mk(10_000 + i) for i in range(512)]

    spec = _net_topology_spec(packed)
    run = TopoRun(spec)
    sock = None
    try:
        run.wait_ready(timeout=420)
        port = int(run.metrics("quic_server")["bound_port"])
        sock = UdpSock(bind_ip="127.0.0.1", burst=256, mutable=True)
        ep = QuicEndpoint(
            QuicConfig(identity_seed=os.urandom(32)), sock.aio())
        conn = ep.connect(("127.0.0.1", port), now=time.monotonic())

        def pump():
            now = time.monotonic()
            pkts = sock.recv_burst()
            if pkts:
                ep.rx(pkts, now)
            ep.service(now)

        deadline = time.monotonic() + 120
        while not conn.handshake_done:
            if time.monotonic() > deadline:
                raise RuntimeError("net bench: handshake timed out")
            pump()
            time.sleep(0.002)

        def send(t, dl):
            while conn.send_txn(t) is None:
                if time.monotonic() > dl:
                    raise RuntimeError("net bench: send stalled")
                pump()

        def sink_cnt():
            return run.metrics("sink")["frag_cnt"]

        # phase 1: chunked packet->verdict latency over the fixed set
        lats = []
        done = sink_cnt()
        for c in range(0, n_fix, CH):
            t0 = time.monotonic()
            dl = t0 + 60
            for t in fixed[c : c + CH]:
                send(t, dl)
            done += exp_pass_chunk
            while sink_cnt() < done:
                if time.monotonic() > dl:
                    raise RuntimeError(
                        f"net bench: chunk {c // CH} verdicts missing "
                        f"({sink_cnt()}/{done})")
                pump()
            lats.append((time.monotonic() - t0) * 1e3)
        lats.sort()
        fixed_sink = sink_cnt()
        fixed_pass = int(run.metrics("verify")["verify_pass_cnt"])

        # phase 2: firehose throughput (cycling pool; dedup drops the
        # repeats downstream, the verify lane still proves every verdict)
        v0 = int(run.metrics("verify")["verify_pass_cnt"])
        p0 = int(run.metrics("quic_server")["pkt_rx_cnt"])
        t0 = time.monotonic()
        stop = t0 + duration_s
        i = 0
        while time.monotonic() < stop:
            if conn.send_txn(cycle[i % len(cycle)]) is None:
                pump()
                continue
            i += 1
            if i % 64 == 0:
                pump()
        tail = time.monotonic() + 2.0   # drain the in-flight tail
        while time.monotonic() < tail:
            pump()
            time.sleep(0.005)
        dt = time.monotonic() - t0
        v1 = int(run.metrics("verify")["verify_pass_cnt"])
        qm = run.metrics("quic_server")
        return {
            "vps": (v1 - v0) / dt,
            # server-side datagram rate over the firehose window — the
            # syscall+crypto front-door number (vps measures verdicts)
            "pps": (int(qm["pkt_rx_cnt"]) - p0) / dt,
            "p50_ms": lats[len(lats) // 2],
            "p99_ms": lats[min(len(lats) - 1, int(len(lats) * 0.99))],
            "txns": int(v1 - v0),
            "fixed_pass": fixed_pass,
            "fixed_sink": int(fixed_sink),
            # backend attribution: with the .so present every packet must
            # ride the C burst engine (crypto_fallback == 0 is the gate)
            "crypto_native": int(qm["crypto_native_cnt"]),
            "crypto_fallback": int(qm["crypto_fallback_cnt"]),
            "packed": packed,
        }
    finally:
        if sock is not None:
            sock.close()
        run.close()


def measure_quic_crypto(burst: int = 256, pkt_len: int = 1200,
                        iters: int = 8) -> dict:
    """Packet-protection micro-lane (round 16): us/pkt for one
    decrypt_burst call over a full recvmmsg-sized burst of txn-MTU
    packets — the C engine and the NumPy fallback, same jobs, outputs
    parity-checked before timing.  This isolates the AEAD+header-
    protection cost from the socket/reassembly path measured by net_pps."""
    from firedancer_tpu.waltz import quic_crypto as qc

    secret = bytes(range(32))
    hdr = bytes.fromhex("c300000001088394c8f03e5157080000449e")
    backends = {"fallback": qc.CryptoBackend(native=False)}
    if qc._native_lib() is not None:
        backends["native"] = qc.CryptoBackend(native=True)

    def mk_jobs(be, slot):
        jobs, bufs = [], []
        for i in range(burst):
            payload = bytes((i + j) & 0xFF for j in range(pkt_len))
            buf = bytearray(hdr + i.to_bytes(4, "big") + payload
                            + bytes(16))
            pn_off = len(hdr)
            be.encrypt_burst([(buf, pn_off, i, pkt_len, slot)])
            bufs.append(buf)
            jobs.append((buf, 0, pn_off, len(buf), slot, i))
        return jobs, bufs

    out = {}
    ref = None
    for name, be in backends.items():
        slot = be.key_new(secret[:16], secret[16:28], secret[:16])
        try:
            jobs, bufs = mk_jobs(be, slot)
            res = be.decrypt_burst(jobs)
            assert all(ok and pn == i
                       for i, (ok, pn, _, _) in enumerate(res)), name
            pts = [bytes(b) for b in bufs]
            if ref is None:
                ref = pts
            elif pts != ref:
                return {"error": "backend plaintext mismatch"}
            best = float("inf")
            for _ in range(iters):
                jobs, _ = mk_jobs(be, slot)
                t0 = time.perf_counter()
                be.decrypt_burst(jobs)
                best = min(best, time.perf_counter() - t0)
            out[name] = best * 1e6 / burst
        finally:
            be.key_free(slot)
    return out


def measure_autotune(timeout_s: float = 240.0) -> dict:
    """Closed-loop tuner lane (round 11): boot the verify-bench topology
    deliberately mis-tuned (a 0.9 s coalesce flush against the 2 ms SLO),
    arm [autotune], and report how long the policy loop took to drive the
    topology back to a healthy burn rate.  The record is policy evidence:
    converge_s (periods-to-healthy in seconds), decisions applied, and
    do-no-harm reverts — a revert in this scenario means the rule set
    moved a knob the wrong way."""
    import shutil
    import tempfile
    import threading

    from firedancer_tpu.app import config as config_mod
    from firedancer_tpu.disco.run import TopoRun
    from firedancer_tpu.utils import aot

    batch, maxlen = 64, 256
    cfg = config_mod.load(None)
    cfg["name"] = "fdtpu_bench_at"
    cfg["topology"] = "verify-bench"
    cfg["layout"]["verify_tile_count"] = 1
    cfg["development"]["source_count"] = 2_000_000  # outlives the window
    cfg["tiles"]["verify"]["batch"] = batch
    cfg["tiles"]["verify"]["msg_maxlen"] = maxlen
    aot_dir = os.environ.get("FDTPU_CI_AOT_DIR", "/tmp/fdtpu_aot_ci")
    if aot.ensure_verify(aot_dir, batch, maxlen) is not None:
        cfg["tiles"]["verify"]["aot_dir"] = aot_dir
    cfg["tiles"]["verify"]["flush_age_ns"] = 900_000_000
    cfg["autotune"] = dict(cfg["autotune"], enabled=1, period_s=0.3,
                           cooldown_periods=1)
    spec = config_mod.build_topology(cfg)

    flight_dir = tempfile.mkdtemp(prefix="fdtpu_bench_at_")
    run = TopoRun(spec, metrics_port=0, flight_dir=flight_dir, config=cfg)
    sup = None
    try:
        run.wait_ready(timeout=300)
        tn = run.autotuner
        assert tn is not None and tn.enabled
        sup = threading.Thread(target=run.supervise,
                               kwargs={"poll_s": 0.05}, daemon=True)
        sup.start()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if tn.converge_s > 0 and tn.decision_cnt >= 1:
                break
            if run.poll() is not None:
                raise RuntimeError("a tile died under autotune")
            time.sleep(0.2)
        if tn.converge_s <= 0:
            raise RuntimeError(
                f"loop never converged in {timeout_s:.0f}s "
                f"({tn.decision_cnt} decisions)")
        return {"converge_s": tn.converge_s,
                "decisions": tn.decision_cnt,
                "revert_cnt": tn.revert_cnt}
    finally:
        run.halt()
        if sup is not None:
            sup.join(15)
        run.close()
        shutil.rmtree(flight_dir, ignore_errors=True)


def measure_drain(timeout_s: float = 240.0) -> dict:
    """Drain/rolling-restart lane (round 12): boot the verify-bench
    topology under live load, issue a graceful rolling_restart of the
    verify tile, and report the two costs that make rolling maintenance
    honest: drain_flush_ms (DRAIN command -> the tile's in-flight device
    work flushed, from the drain_flush_ns gauge the drained incarnation
    leaves behind) and restart_gap_ms (DRAIN command -> first verdict
    published by the NEW incarnation).  Zero-loss is asserted, not
    recorded: a fast gap that dropped frags is a wrong answer."""
    import shutil
    import tempfile
    import threading

    from firedancer_tpu.app import config as config_mod
    from firedancer_tpu.disco.run import SupervisionPolicy, TopoRun
    from firedancer_tpu.utils import aot

    batch, maxlen = 64, 256
    aot_dir = os.environ.get("FDTPU_CI_AOT_DIR", "/tmp/fdtpu_aot_ci")
    if aot.ensure_verify(aot_dir, batch, maxlen) is None:
        raise RuntimeError("AOT unusable on this backend (drain lane "
                           "needs fast respawn to measure the gap)")

    man_dir = tempfile.mkdtemp(prefix="fdtpu_bench_drman_")
    cfg = config_mod.load(None)
    cfg["name"] = "fdtpu_bench_dr"
    cfg["topology"] = "verify-bench"
    cfg["layout"]["verify_tile_count"] = 1
    cfg["development"]["source_count"] = 2_000_000  # outlives the window
    cfg["tiles"]["verify"]["batch"] = batch
    cfg["tiles"]["verify"]["msg_maxlen"] = maxlen
    cfg["tiles"]["verify"]["aot_dir"] = aot_dir
    cfg["tiles"]["verify"]["aot_require"] = 1
    cfg["supervision"] = dict(cfg.get("supervision") or {},
                              restart_policy="respawn",
                              drain_timeout_s=timeout_s,
                              drain_manifest_dir=man_dir)
    policy = SupervisionPolicy.from_cfg(cfg)
    spec = config_mod.build_topology(cfg)
    run = TopoRun(spec, metrics_port=0, policy=policy, config=cfg)
    sup = None
    try:
        run.wait_ready(timeout=300)
        sup = threading.Thread(target=run.supervise,
                               kwargs={"poll_s": 0.05}, daemon=True)
        sup.start()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if run.metrics("sink")["frag_cnt"] >= 200:
                break
            time.sleep(0.05)
        if run.metrics("sink")["frag_cnt"] < 200:
            raise RuntimeError("no live load to restart under")

        nb = int(run.jt.tile_spec("verify:0").cfg.get("n_buffers", 3))
        t0 = time.monotonic()
        ok = run.rolling_restart("verify:0", {"n_buffers": nb + 1})
        if not ok:
            raise RuntimeError("drain fell back to crash semantics")
        # first NEW-incarnation verdict closes the gap.  The old
        # incarnation is joined before rolling_restart returns and the
        # metrics shm persists across the respawn, so any out_frag_cnt
        # increment past this snapshot is the successor publishing (the
        # sink counter can't serve here: the drain flush itself advances
        # it, which would close the gap while gen=1 is still booting)
        v0 = int(run.metrics("verify:0")["out_frag_cnt"])
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if int(run.metrics("verify:0")["out_frag_cnt"]) > v0:
                break
            time.sleep(0.002)
        gap_ms = (time.monotonic() - t0) * 1e3
        if int(run.metrics("verify:0")["out_frag_cnt"]) <= v0:
            raise RuntimeError("no verdicts after restart: gap unbounded")
        # the drained incarnation's flush cost survives in its gauge
        # (tile metrics shm persists across respawn)
        flush_ms = run.metrics("verify:0").get("drain_flush_ns", 0) / 1e6
        # zero-loss gate: under continuous load the sink lags published
        # by the in-flight window, so equality is only meaningful after
        # a quiesce — the topology drain parks the source first, then
        # every downstream tile flushes to its admission snapshot
        if not run.drain():
            sigs = {n: run.jt.cnc[n].signal_query() for n in run.procs}
            raise RuntimeError(
                f"post-measure quiesce drain failed (cnc sigs: {sigs})")
        src = run.metrics("source")
        snk = run.metrics("sink")
        if run.metrics("dedup")["dup_drop_cnt"] != 0:
            raise RuntimeError("duplicate verdicts across the restart")
        if snk["frag_cnt"] != src["out_frag_cnt"]:
            raise RuntimeError(
                f"lost verdicts across restart: sink {snk['frag_cnt']} "
                f"!= published {src['out_frag_cnt']}")
        return {"drain_flush_ms": flush_ms, "restart_gap_ms": gap_ms}
    finally:
        run.halt()
        if sup is not None:
            sup.join(15)
        run.close()
        shutil.rmtree(man_dir, ignore_errors=True)


def measure_fleet(n_hosts: int = 2, n_txn: int = 400) -> dict:
    """Fleet fault-tolerance lane (round 17): boot an n-host fleet (each
    host a full supervisor + topology + capture ledger), SIGKILL one
    host's whole process group mid-load, and report what fleet-scale
    maintenance actually costs: fleet_failover_ms (host-loss detection ->
    steering re-converged + adoption commanded) plus the two invariants
    as RECORDED gates — fleet_dup_verdicts / fleet_lost_verdicts vs the
    injected txn universe, which must both be 0 (bench_diff enforces
    them lower-is-better, so any regression from 0 fails the diff)."""
    import shutil
    import tempfile

    from firedancer_tpu.app import config as config_mod
    from firedancer_tpu.disco import faultinject
    from firedancer_tpu.disco import fleet as fleet_mod
    from firedancer_tpu.utils import aot

    batch, maxlen = 64, 256
    aot_dir = os.environ.get("FDTPU_CI_AOT_DIR", "/tmp/fdtpu_aot_ci")
    if aot.ensure_verify(aot_dir, batch, maxlen) is None:
        raise RuntimeError("AOT unusable on this backend (fleet lane "
                           "needs fast host boots)")
    cfg = config_mod.load(None)
    cfg["name"] = "fdtpu_bench_fl"
    cfg["topology"] = "verify-bench"
    cfg["layout"]["verify_tile_count"] = 1
    cfg["development"]["source_count"] = n_txn
    cfg["development"]["source_extra"] = {"rate_ns": 10_000_000}
    cfg["tiles"]["verify"]["batch"] = batch
    cfg["tiles"]["verify"]["msg_maxlen"] = maxlen
    cfg["tiles"]["verify"]["aot_dir"] = aot_dir
    cfg["tiles"]["verify"]["aot_require"] = 1
    cfg["fleet"] = dict(cfg.get("fleet") or {}, hosts=n_hosts,
                        digest_period_s=0.2)
    kill_idx = n_hosts - 1
    env = {"FDTPU_FAULTS":
           f"fleet=host_kill:{kill_idx},after_capture:80,boot:0"}
    faults = faultinject.fleet_faults(env, cfg, 0)
    workdir = tempfile.mkdtemp(prefix="fdtpu_bench_fleet_")
    uni = fleet_mod.stream_universe(
        [fleet_mod.host_stream_spec(cfg, i) for i in range(n_hosts)])
    fr = fleet_mod.FleetRun(cfg, workdir, faults=faults)
    try:
        fr.wait_ready(timeout=420)
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            fr.poll()
            if fr.lost and len(set(fr.ledger())) >= len(uni):
                break
            time.sleep(0.1)
        led = fr.ledger()
        if not fr.lost:
            raise RuntimeError("host_kill fault never fired")
        dup = len(led) - len(set(led))
        lost = len(set(uni)) - len(set(led) & set(uni))
        return {"fleet_hosts": n_hosts,
                "fleet_failover_ms": fr.failover_ms[kill_idx],
                "fleet_dup_verdicts": dup,
                "fleet_lost_verdicts": lost}
    finally:
        fr.close()
        shutil.rmtree(workdir, ignore_errors=True)


def measure_shred_recover(n_sets: int = 32, k: int = 32, c: int = 32,
                          sz: int = 1019, reps: int = 5) -> dict:
    """Round 13: the batched turbine shred lane.

    Arm 1 — batched FEC recover: `n_sets` erasure-damaged RS sets (ragged
    erasure patterns, so several reconstruction matrices are live at
    once) recovered in ONE fused device dispatch (reedsol.recover_batch)
    vs the per-set recover() loop, bit-identity asserted against the
    host golden model before timing.  Arm 2 — batched merkle admission:
    a burst of real shreds' roots walked in one batched sha256 graph
    (bmtree.batch_walk_roots) vs the per-shred host walk.

    On CPU both arms prove wiring + bit-identity; the speedups are
    stamped wiring-only (same contract as the antipa/autotune lanes)."""
    import jax

    from firedancer_tpu.ballet import bmtree, shred as shred_lib
    from firedancer_tpu.ballet import reedsol as rs
    from firedancer_tpu.ops import ed25519 as ed

    rng = np.random.default_rng(1234)
    n = k + c
    sets = []
    for i in range(n_sets):
        data = rng.integers(0, 256, (k, sz), dtype=np.uint8)
        parity = rs.encode(data, c, device=False)
        full = [np.ascontiguousarray(r) for r in np.vstack([data, parity])]
        # ragged erasure storm: i % c erasures per set, parity-heavy
        shreds = list(full)
        for e in range(i % c):
            shreds[(3 * e + i) % n] = None
        sets.append((shreds, k, sz))

    golden = rs.recover_batch(sets, device=False)
    got = rs.recover_batch(sets)                      # warm + gate
    for g, w in zip(golden, got):
        if isinstance(g, ValueError) or isinstance(w, ValueError):
            raise RuntimeError(f"bench sets must all recover: {g} / {w}")
        if not all(np.array_equal(a, b) for a, b in zip(g, w)):
            raise RuntimeError("batched recover != host golden model")
    for s_, k_, sz_ in sets[:2]:
        rs.recover(s_, k_, sz_)                       # warm per-set path

    def _med(fn, inner):
        vals = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            vals.append((time.perf_counter() - t0) / inner)
        return sorted(vals)[len(vals) // 2]

    t_batch = _med(lambda: rs.recover_batch(sets), n_sets)
    t_loop = _med(
        lambda: [rs.recover(s_, k_, sz_) for s_, k_, sz_ in sets], n_sets)

    # merkle admission arm: a real FEC set's shreds, batched walk vs the
    # per-shred host walk (device twin bit-gated first)
    seed = b"\x01" * 32
    fs = shred_lib.make_fec_set(
        bytes(rng.integers(0, 256, 4096, dtype=np.uint8)), slot=7,
        parent_off=1, version=3, fec_set_idx=0,
        sign_fn=lambda root: ed.sign(seed, root), data_cnt=8, code_cnt=8)
    shreds_p = [shred_lib.parse(r) for r in fs.data_shreds + fs.code_shreds]
    B, ml, D = len(shreds_p), 1228 - 64, 15
    leaf = np.zeros((B, ml), np.uint8)
    lens = np.zeros((B,), np.int32)
    idxs = np.zeros((B,), np.int32)
    proofs = np.zeros((B, D, bmtree.MERKLE_NODE_SZ), np.uint8)
    depths = np.zeros((B,), np.int32)
    for j, s in enumerate(shreds_p):
        ld = s.merkle_leaf_data()
        leaf[j, :len(ld)] = np.frombuffer(ld, np.uint8)
        lens[j], idxs[j] = len(ld), s.tree_index()
        for d, node in enumerate(s.proof_nodes()):
            proofs[j, d] = np.frombuffer(node, np.uint8)
        depths[j] = s.merkle_proof_len
    walk = bmtree.batch_walk_roots_jit()
    roots = np.asarray(walk(leaf, lens, idxs, proofs, depths))
    for j, s in enumerate(shreds_p):
        if bytes(roots[j]) != s.merkle_root():
            raise RuntimeError("batched merkle walk != host walk")
    m_iters = 24

    def _m():
        for _ in range(m_iters):
            np.asarray(walk(leaf, lens, idxs, proofs, depths))
    t_merkle = _med(_m, B * m_iters)

    return {
        "shred_batch": n_sets,
        "shred_geometry": f"{k}:{c}@{sz}",
        "shred_recover_us_set": round(t_batch * 1e6, 2),
        "shred_recover_us_set_loop": round(t_loop * 1e6, 2),
        "shred_batch_vs_perset": round(t_loop / max(t_batch, 1e-12), 2),
        "shred_rps": round(n / t_batch, 1),
        "shred_merkle_vps": round(1.0 / max(t_merkle, 1e-12), 1),
        "shred_recover_cache": dict(zip(
            ("hits", "misses", "maxsize", "currsize"),
            rs.recover_cache_info())),
        "shred_wiring_only": jax.default_backend() != "tpu",
    }


def measure_leader(lanes: int = 8, hashes_per_tick: int = 64,
                   n_txn: int = 256, reps: int = 5) -> dict:
    """Round 14: the leader lane — device-batched PoH + fee-priority pack.

    Arm 1 — PoH span engine: `lanes` concurrent tick spans (each a
    chained [mixin, remainder] pair, the tick-close shape) hashed in ONE
    device dispatch via ballet.poh_engine, bit-gated against the host
    hashlib chain (entry.next_hash via host_spans) before timing; the
    serial baseline is the same spans through a lanes=1 engine one at a
    time.  Arm 2 — pack: per-txn host cost of the fee-priority heap
    (insert + schedule + done over parseable single-signer txns).  Arm 3
    — the satellite-1 sha256 fast path: fixed-32 message schedule vs the
    generic length-dispatched sha256 at the same (N, 32) batch.

    On CPU every arm proves wiring + bit-identity; speedups are stamped
    wiring-only (leader_wiring_only=1, an int so the BENCH loader keeps
    it)."""
    import jax
    import jax.numpy as jnp

    from firedancer_tpu.ballet import entry as entry_lib
    from firedancer_tpu.ballet import pack as pack_lib
    from firedancer_tpu.ballet import poh_engine as pe
    from firedancer_tpu.ballet import txn as txn_lib
    from firedancer_tpu.ops.sha256 import sha256, sha256_fixed32

    rng = np.random.default_rng(77)

    # ---- arm 1: batched tick spans, bit-gated vs the host chain
    def tick_specs(seed: int):
        out = []
        for i in range(lanes):
            start = bytes(rng.bytes(32)) if seed < 0 else \
                hashlib_bytes(seed * lanes + i)
            mix = hashlib_bytes(seed * lanes + i + 104729)
            out.append((start, [(1, mix), (hashes_per_tick - 1, None)]))
        return out

    def hashlib_bytes(i: int) -> bytes:
        import hashlib
        return hashlib.sha256(i.to_bytes(8, "little")).digest()

    eng = pe.PohEngine(lanes=lanes, steps=2, max_hashes=hashes_per_tick)
    eng.warm()
    specs = tick_specs(1)
    golden = pe.host_spans(specs, steps=2)
    outs = [eng.split_verdict(v) for v in eng.submit_lanes(specs)]
    outs += [eng.split_verdict(v) for v in eng.drain()]
    planes = outs[0]
    for li in range(lanes):
        for si in range(2):
            if bytes(planes[li, si]) != bytes(golden[li, si]):
                raise RuntimeError("poh engine != host chain golden")

    serial = pe.PohEngine(lanes=1, steps=2, max_hashes=hashes_per_tick)
    serial.warm()

    def _med(fn, inner):
        vals = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            vals.append((time.perf_counter() - t0) / inner)
        return sorted(vals)[len(vals) // 2]

    def _batch():
        for v in eng.submit_lanes(specs):
            pass
        eng.drain()

    def _serial():
        for start, steps in specs:
            for v in serial.submit_lanes([(start, steps)]):
                pass
        serial.drain()

    t_tick = _med(_batch, lanes)            # s per tick span
    t_serial = _med(_serial, lanes)

    # ---- arm 2: pack heap per-txn host cost (insert + schedule + done)
    payloads = []
    for i in range(n_txn):
        signer = bytes([i % 250, 1 + i // 250]) + bytes(30)
        msg = txn_lib.build_unsigned(
            [signer], b"\x11" * 32,
            [(1, bytes([0]), i.to_bytes(8, "little"))],
            extra_accounts=[b"\x07" * 32], readonly_unsigned_cnt=1)
        pay = txn_lib.assemble([b"\x5a" * 64], msg)
        payloads.append((pay, txn_lib.parse(pay)))

    def _pack(native=None):
        p = pack_lib.Pack(bank_tile_cnt=1, max_txn_per_microblock=31,
                          native=native)
        for pay, parsed in payloads:
            p.insert(pay, parsed)
        got = 0
        while True:
            mb = p.schedule(0)
            if mb is None:
                if p.pending:            # block budget hit: next block
                    p.end_block()
                    continue
                break
            got += len(mb.txns)
            p.done(0)
        if got != n_txn:
            raise RuntimeError(f"pack scheduled {got}/{n_txn}")
    pack_native = int(pack_lib.Pack(bank_tile_cnt=1).native)
    t_pack = _med(_pack, n_txn)          # auto path: native when it builds
    t_pack_py = _med(lambda: _pack(native=False), n_txn)

    # ---- arm 2b (round 15): splice re-hash (mixin region only, per-step
    # hash caps) vs re-hashing the whole tick — the PohDevTile spec-miss
    # cost this round removes
    mb_cap = min(8, hashes_per_tick - 1)
    tail = mb_cap + 1
    P = hashes_per_tick - tail
    sp = pe.PohEngine(lanes=1, steps=tail, max_hashes=tail,
                      step_caps=(1,) * mb_cap + (tail,))
    sp.warm()
    full = pe.PohEngine(lanes=1, steps=2, max_hashes=hashes_per_tick)
    full.warm()
    head = hashlib_bytes(9999)
    mix = hashlib_bytes(4242)
    mid = entry_lib.next_hash(head, P, None) if P else head
    sp_steps = [(1, mix)] + [(0, None)] * (mb_cap - 1) + [(tail - 1, None)]
    full_steps = [(P + 1, mix), (tail - 1, None)]
    sv = sp.submit_lanes([(mid, sp_steps)]) + sp.drain()
    spl = sp.split_verdict(sv[-1])
    gold = pe.host_spans([(mid, sp_steps)], steps=tail)
    if bytes(spl[0, mb_cap]) != bytes(gold[0, mb_cap]):
        raise RuntimeError("splice engine != host chain golden")
    fv = full.submit_lanes([(head, full_steps)]) + full.drain()
    if bytes(full.split_verdict(fv[-1])[0, 1]) != bytes(spl[0, mb_cap]):
        raise RuntimeError("splice end != full-tick chain end")

    def _splice():
        sp.submit_lanes([(mid, sp_steps)])
        sp.drain()

    def _full():
        full.submit_lanes([(head, full_steps)])
        full.drain()

    t_splice = _med(_splice, 1)
    t_full = _med(_full, 1)

    # ---- arm 3: satellite-1 fixed-32 sha path vs the generic kernel
    m32 = rng.integers(0, 256, (lanes * hashes_per_tick, 32), dtype=np.uint8)
    lens32 = np.full((len(m32),), 32, np.int32)
    fixed_j = jax.jit(sha256_fixed32)
    a = np.asarray(fixed_j(jnp.asarray(m32)))                  # warm + gate
    b = np.asarray(sha256(jnp.asarray(m32), jnp.asarray(lens32)))
    if not np.array_equal(a, b):
        raise RuntimeError("sha256_fixed32 != generic sha256")
    t_fixed = _med(lambda: np.asarray(fixed_j(jnp.asarray(m32))), 1)
    t_gen = _med(lambda: np.asarray(
        sha256(jnp.asarray(m32), jnp.asarray(lens32))), 1)

    st = eng.stats()
    return {
        "poh_lanes": lanes,
        "poh_hashes_per_tick": hashes_per_tick,
        "poh_hps": round(hashes_per_tick / max(t_tick, 1e-12), 1),
        "poh_us_tick": round(t_tick * 1e6, 2),
        "poh_batch_vs_serial": round(t_serial / max(t_tick, 1e-12), 2),
        "pack_txn_us": round(t_pack * 1e6, 3),
        "pack_txn_us_fallback": round(t_pack_py * 1e6, 3),
        "pack_native": pack_native,
        "poh_splice_us": round(t_splice * 1e6, 2),
        "poh_splice_vs_full": round(t_full / max(t_splice, 1e-12), 2),
        "poh_sha_fixed_vs_generic": round(t_gen / max(t_fixed, 1e-12), 2),
        "poh_engine_dispatches": st["dispatches"],
        "leader_wiring_only": int(jax.default_backend() != "tpu"),
    }


def measure_upload_mbps() -> float:
    import jax

    blob = np.zeros((4 << 20,), np.uint8)
    jax.device_put(blob).block_until_ready()      # warm path
    t0 = time.perf_counter()
    jax.device_put(blob).block_until_ready()
    dt = time.perf_counter() - t0
    return len(blob) / dt / 1e6


def main():
    if os.environ.get("FDTPU_BENCH_MC_FORCE_CPU"):
        # the _mc_subprocess child: pin the CPU backend BEFORE first
        # device query (the env var alone loses to the baked-in TPU
        # plugin registration) so --xla_force_host_platform_device_count
        # yields the 8-virtual-device mesh
        import jax
        jax.config.update("jax_platforms", "cpu")
    from firedancer_tpu.utils import xla_cache
    xla_cache.enable()
    if os.environ.get("FDTPU_BENCH_MC_ONLY"):
        # child mode: run ONLY the multichip lane and print its dict
        print(json.dumps(measure_mc_vps(
            int(os.environ.get("FDTPU_BENCH_MC_BATCH", 128)),
            int(os.environ.get("FDTPU_BENCH_MC_ITERS", 4)))))
        return
    from firedancer_tpu.models.verifier import (
        SigVerifier,
        VerifierConfig,
        make_example_batch,
    )

    batch = int(os.environ.get("FDTPU_BENCH_BATCH", 32768))
    mode = os.environ.get("FDTPU_BENCH_MODE", "strict")
    iters = int(os.environ.get("FDTPU_BENCH_ITERS", 24))
    msm_m = int(os.environ.get("FDTPU_BENCH_MSM_M", 8))
    cfg = VerifierConfig(batch=batch, msg_maxlen=128)
    verifier = SigVerifier(cfg, mode=mode, msm_m=msm_m)
    args = make_example_batch(batch, cfg.msg_maxlen, valid=True, sign_pool=64)

    from firedancer_tpu.ops.ed25519 import _pallas_ok
    _pallas_ok_headline = _pallas_ok(batch)

    # warmup / compile + correctness gate (true fetch)
    ok = verifier(*args)
    if not bool(np.asarray(ok).all()):
        print(
            json.dumps({"error": "correctness check failed in warmup"}),
            file=sys.stderr,
        )
        sys.exit(1)

    reps = int(os.environ.get("FDTPU_BENCH_REPS", 5))
    vps, runs = measure_throughput_median(verifier, args, iters, reps)
    fresh_iters = max(2, iters // 6)
    ingest_nbuf = int(os.environ.get("FDTPU_BENCH_NBUF", 3))
    ingest_depth = int(os.environ.get("FDTPU_BENCH_DEPTH", 2))
    fresh_stats = {}
    fresh_vps = measure_throughput_fresh(verifier, args, fresh_iters,
                                         nbuf=ingest_nbuf,
                                         depth=ingest_depth,
                                         stats=fresh_stats)

    # latency tier: batch-256 bucket
    lat_batch = int(os.environ.get("FDTPU_BENCH_LAT_BATCH", 256))
    lat_reps = int(os.environ.get("FDTPU_BENCH_LAT_REPS", 48))
    lat_verifier = SigVerifier(VerifierConfig(batch=lat_batch, msg_maxlen=128))
    lat = measure_p99_ms(lat_verifier, lat_batch, 128, lat_reps)
    dev = measure_device_batch_ms(lat_batch, 128)

    # round 9: dual-lane mixed-load tier — latency probes beside a bulk
    # firehose, per-lane records (FDTPU_BENCH_DUAL=0 skips)
    dual = {}
    if os.environ.get("FDTPU_BENCH_DUAL", "1") != "0":
        import jax

        from firedancer_tpu.ops import ed25519 as ed
        dl_bulk = int(os.environ.get("FDTPU_BENCH_DUAL_BATCH", 2048))
        try:
            dual = measure_dual_lane(
                jax.jit(ed.verify_batch), dl_bulk, 128, dl_bulk * 12,
                lat_shapes=(16, 64, 256),
                deadline_us=int(os.environ.get(
                    "FDTPU_BENCH_DUAL_DEADLINE_US", 2000)),
                n_probes=int(os.environ.get("FDTPU_BENCH_DUAL_PROBES", 64)))
        except Exception as e:  # record the failure, never lose the line
            dual = {"error": str(e)[:160]}

    # tile path (burst data plane); the device leg rides the packed
    # single-blob dispatch (same verdict contract, 1 upload RPC per batch)
    pipe_batch = int(os.environ.get("FDTPU_BENCH_PIPE_BATCH", 16384))
    pipe_verifier = SigVerifier(
        VerifierConfig(batch=pipe_batch, msg_maxlen=128))
    pipe_vps = measure_pipe_vps(pipe_verifier, pipe_batch,
                                128, pipe_batch * 6)
    pipe_host_us = measure_pipe_host_us(pipe_batch, 128, pipe_batch * 4)
    pipe_host_us_parse = measure_pipe_host_us(pipe_batch, 128,
                                              pipe_batch * 4, packed=True)
    # round 8: the zero-repack rows lane (FDTPU_INGEST_LEGACY_PACK=1
    # flips it to the legacy parse+scatter path for the A/B)
    pipe_host_us_packed = measure_pipe_host_us_rows(pipe_batch,
                                                    pipe_batch * 4)
    # round 11: the packed verdict EGRESS arm (one arena frag per
    # harvest) + its bit-identity gate vs the legacy per-txn list
    hostpath_us, egress_identical = measure_hostpath_packed_egress(
        pipe_batch, pipe_batch * 4)
    upload_mbps = measure_upload_mbps()

    # multichip tier: real slice in-process when >= 2 devices are
    # attached, else the 8-virtual-device CPU mesh in a subprocess
    # (FDTPU_BENCH_MC=0 skips)
    mc = {"vps": 0.0, "devices": 0, "vs_single": 0.0, "identical": False,
          "platform": ""}
    if os.environ.get("FDTPU_BENCH_MC", "1") != "0":
        import jax
        mc_batch = int(os.environ.get("FDTPU_BENCH_MC_BATCH", 128))
        mc_iters = int(os.environ.get("FDTPU_BENCH_MC_ITERS", 4))
        try:
            if len(jax.devices()) > 1:
                mc = measure_mc_vps(mc_batch, mc_iters)
            else:
                mc = _mc_subprocess(mc_batch, mc_iters)
        except Exception as e:
            mc = {"vps": -1.0, "devices": 0, "vs_single": 0.0,
                  "identical": False, "platform": "",
                  "error": str(e)[:160]}

    # multi-process topology tier
    # default 2 verify tiles: this container has ONE core, so every extra
    # tile process is pure timesharing overhead (measured: 2 tiles 102 K/s,
    # 4 tiles 74 K/s).  Raise FDTPU_BENCH_MP on real multi-core hosts.
    mp = {"vps": 0.0, "tiles": 0}
    mp_tiles = int(os.environ.get("FDTPU_BENCH_MP", 2))
    mp_packed = os.environ.get("FDTPU_BENCH_MP_PACKED", "1") != "0"
    if mp_tiles:
        try:
            mp = measure_mp_vps(mp_tiles, 2048,
                                float(os.environ.get(
                                    "FDTPU_BENCH_MP_SECS", 30)),
                                packed=mp_packed)
        except Exception as e:  # record the failure, never lose the line
            mp = {"vps": -1.0, "tiles": mp_tiles, "error": str(e)[:120]}

    # round 10: e2e wire front-door lane — loopback QUIC client ->
    # quic_server -> verify, legacy AND packed-publish, with the fixed-set
    # verdict counts as the bit-identity gate (FDTPU_BENCH_NET=0 skips)
    net, netp = {"vps": 0.0}, {}
    if os.environ.get("FDTPU_BENCH_NET", "1") != "0":
        net_secs = float(os.environ.get("FDTPU_BENCH_NET_SECS", 10))
        try:
            net = measure_net_vps(net_secs, packed=False)
            netp = measure_net_vps(net_secs, packed=True)
        except Exception as e:  # record the failure, never lose the line
            net = dict(net, error=str(e)[:160])

    # round 16: packet-protection micro-lane — one burst-decrypt call per
    # recvmmsg burst, C engine vs the bit-identical NumPy fallback.  Own
    # knob, not FDTPU_BENCH_NET: no topology boots, runs in seconds even
    # on a 1-core host, so the us/pkt series accrues every round
    qcr = {}
    if os.environ.get("FDTPU_BENCH_QUIC_CRYPTO", "1") != "0":
        try:
            qcr = measure_quic_crypto()
        except Exception as e:
            qcr = {"error": str(e)[:120]}

    # round 10: antipa halved-verify A/B — the in-kernel-divstep chain vs
    # the strict chain at equal batch, parity-gated before timing; this is
    # the standing evidence line for the [verify] mode = "antipa" knob
    # (FDTPU_BENCH_ANTIPA=0 skips)
    ant = {}
    if os.environ.get("FDTPU_BENCH_ANTIPA", "1") != "0":
        import jax

        from firedancer_tpu.ops import ed25519 as ed
        try:
            ab = int(os.environ.get("FDTPU_BENCH_ANTIPA_BATCH", 2048))
            a_iters = max(2, iters // 6)
            a_args = make_example_batch(ab, 128, valid=True, sign_pool=64)
            s_fn = jax.jit(ed.verify_batch)
            a_fn = jax.jit(ed.verify_batch_antipa)
            ok_s = np.asarray(s_fn(*a_args))
            ok_a = np.asarray(a_fn(*a_args))
            if not (ok_s.all() and (ok_a == ok_s).all()):
                raise RuntimeError("antipa/strict verdict mismatch")

            def _ant_vps(fn):
                vals = []
                for _ in range(reps):
                    t0 = time.perf_counter()
                    ok = None
                    for _ in range(a_iters):
                        ok = fn(*a_args)
                    np.asarray(ok)
                    vals.append(ab * a_iters / (time.perf_counter() - t0))
                return sorted(vals)[len(vals) // 2]

            s_vps = _ant_vps(s_fn)
            a_vps = _ant_vps(a_fn)
            ant = {"antipa_vps": round(a_vps, 1),
                   "antipa_strict_vps": round(s_vps, 1),
                   "antipa_vs_strict": round(a_vps / s_vps, 3),
                   "antipa_batch": ab,
                   # both arms on the XLA fallback = wiring check, not a
                   # kernel verdict (same contract as tools/exp_r9_divstep)
                   "antipa_wiring_only": not ed._pallas_ok(ab)}
        except Exception as e:  # record the failure, never lose the line
            ant = {"antipa_error": str(e)[:160]}

    # round 11: closed-loop tuner lane — opt-in (FDTPU_BENCH_AUTOTUNE=1:
    # it boots a whole topology), converge/decision/revert policy record;
    # on CPU the numbers prove the sense->decide->actuate plumbing only
    at = {}
    if os.environ.get("FDTPU_BENCH_AUTOTUNE", "0") == "1":
        import jax
        try:
            r = measure_autotune()
            at = {"autotune_converge_s": round(r["converge_s"], 2),
                  "autotune_decisions": r["decisions"],
                  "autotune_revert_cnt": r["revert_cnt"],
                  "autotune_wiring_only": jax.default_backend() != "tpu"}
        except Exception as e:  # record the failure, never lose the line
            at = {"autotune_error": str(e)[:160]}

    # round 12: drain/rolling-restart lane — opt-in (FDTPU_BENCH_DRAIN=1:
    # it boots a whole topology and restarts the verify tile mid-load);
    # both fields lower-is-better, zero-loss asserted inside the lane
    dr = {}
    if os.environ.get("FDTPU_BENCH_DRAIN", "0") == "1":
        try:
            r = measure_drain()
            dr = {"drain_flush_ms": round(r["drain_flush_ms"], 3),
                  "restart_gap_ms": round(r["restart_gap_ms"], 1)}
        except Exception as e:  # record the failure, never lose the line
            dr = {"drain_error": str(e)[:160]}

    # round 17: fleet fault-tolerance lane — opt-in (FDTPU_BENCH_FLEET=1:
    # it boots a whole multi-host fleet and SIGKILLs a host mid-load);
    # failover lower-is-better, dup/lost verdicts MUST stay 0
    fl = {}
    if os.environ.get("FDTPU_BENCH_FLEET", "0") == "1":
        try:
            r = measure_fleet()
            fl = {"fleet_hosts": r["fleet_hosts"],
                  "fleet_failover_ms": round(r["fleet_failover_ms"], 1),
                  "fleet_dup_verdicts": r["fleet_dup_verdicts"],
                  "fleet_lost_verdicts": r["fleet_lost_verdicts"]}
        except Exception as e:  # record the failure, never lose the line
            fl = {"fleet_error": str(e)[:160]}

    # round 13: batched turbine shred lane — fused multi-set RS recover +
    # batched merkle admission, bit-gated vs host golden models inside the
    # lane (FDTPU_BENCH_SHRED=0 skips)
    sh = {}
    if os.environ.get("FDTPU_BENCH_SHRED", "1") != "0":
        try:
            sh = measure_shred_recover(
                n_sets=int(os.environ.get("FDTPU_BENCH_SHRED_SETS", 32)),
                reps=max(2, reps // 2))
        except Exception as e:  # record the failure, never lose the line
            sh = {"shred_error": str(e)[:160]}

    # round 14: leader lane — device PoH spans + fee-priority pack, every
    # arm bit-gated vs host goldens inside the lane (FDTPU_BENCH_LEADER=0
    # skips)
    ld = {}
    if os.environ.get("FDTPU_BENCH_LEADER", "1") != "0":
        try:
            ld = measure_leader(
                lanes=int(os.environ.get("FDTPU_BENCH_LEADER_LANES", 8)),
                reps=max(2, reps // 2))
        except Exception as e:  # record the failure, never lose the line
            ld = {"leader_error": str(e)[:160]}

    # tunnel RTT floor
    import jax.numpy as jnp
    tiny = jnp.zeros((8,), jnp.uint32) + 1
    np.asarray(tiny)
    rtts = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(tiny + 1)
        rtts.append(time.perf_counter() - t0)
    rtt_ms = sorted(rtts)[len(rtts) // 2] * 1e3

    print(
        json.dumps(
            {
                "metric": "ed25519_verify_throughput",
                "value": round(vps, 1),
                "unit": "verifies/sec/chip",
                "vs_baseline": round(vps / 1e6, 4),
                "mode": mode,
                "runs_min": round(runs[0], 1),
                "runs_max": round(runs[-1], 1),
                "runs_n": len(runs),
                "value_fresh": round(fresh_vps, 1),
                "p50_batch_ms": round(lat["p50_ms"], 3),
                "p99_batch_ms": round(lat["p99_ms"], 3),
                "coalesce_p50_ms": round(lat["coalesce_p50_ms"], 3),
                "coalesce_p99_ms": round(lat["coalesce_p99_ms"], 3),
                "p99_target_ms": 2.0,
                "rtt_floor_ms": round(rtt_ms, 3),
                "compile_cnt": lat["compile_cnt"],
                "compile_ms": round(lat["compile_ms"], 1),
                "fill_pct": lat["fill_pct"],
                "p99_minus_rtt_ms": round(
                    max(0.0, lat["p99_ms"] - rtt_ms), 3),
                "device_batch_ms_p50": round(dev["p50_ms"], 3),
                "device_batch_ms_min": round(dev["min_ms"], 3),
                "device_batch_ms_max": round(dev["max_ms"], 3),
                "device_batch_ms_max_clean": round(dev["max_clean_ms"], 3),
                "device_batch_clean_reps": dev["clean_reps"],
                "device_batch_contended_reps": dev["contended"],
                **({"device_batch_flagged": True}
                   if dev["flagged"] else {}),
                "ingest_nbuf": ingest_nbuf,
                "ingest_depth": ingest_depth,
                "ingest_pack_us_txn": round(
                    fresh_stats.get("pack_us_txn", 0.0), 3),
                # label = which STRICT kernel ran (rlc mode has its own
                # msm path and is labelled as such)
                "kernel": ("rlc" if mode != "strict" else
                           "fused" if (_pallas_ok_headline
                                       and not os.environ.get(
                                           "FDTPU_NO_FUSED"))
                           else "split"),
                "pipe_vps": round(pipe_vps, 1),
                "pipe_vs_bench": round(pipe_vps / vps, 3),
                "pipe_vs_fresh": round(pipe_vps / max(fresh_vps, 1e-9), 3),
                "pipe_host_us_txn": round(pipe_host_us, 2),
                "pipe_host_us_txn_parse": round(pipe_host_us_parse, 2),
                "pipe_host_us_txn_packed": round(pipe_host_us_packed, 2),
                # round 11: one-pass C submit/harvest + packed arena
                # egress; the identity bool gates the egress rewire
                "hostpath_us_txn": round(hostpath_us, 2),
                "egress_packed_identical": bool(egress_identical),
                "hostpath_native": bool(os.environ.get(
                    "FDTPU_INGEST_NATIVE_HOSTPATH", "1") != "0"),
                "pipe_hostpath_legacy": bool(os.environ.get(
                    "FDTPU_INGEST_LEGACY_PACK", "0") == "1"),
                "mp_vps": round(mp["vps"], 1),
                "mp_tiles": mp["tiles"],
                "mp_packed": mp.get("packed", False),
                "mp_torn_drops": mp.get("torn", 0),
                # multi-tile host scaling verdict: < 1.0 means the mp
                # topology moves FEWER txns than one in-process tile path
                "mp_vs_pipe": round(
                    max(mp["vps"], 0.0) / max(pipe_vps, 1e-9), 3),
                **({"mp_vs_pipe_flag": True}
                   if 0.0 <= mp["vps"] < pipe_vps else {}),
                "mp_vps_per_tile": mp.get("per_tile", []),
                **({"mp_ready_s": mp["ready_s"]} if "ready_s" in mp
                   else {}),
                **({"mp_error": mp["error"]} if "error" in mp else {}),
                "mc_vps": round(mc["vps"], 1),
                "mc_devices": mc["devices"],
                "mc_vs_single": round(mc.get("vs_single", 0.0), 3),
                "mc_identical": mc.get("identical", False),
                "mc_platform": mc.get("platform", ""),
                **({"mc_error": mc["error"]} if "error" in mc else {}),
                "upload_mbps": round(upload_mbps, 1),
                "lat_batch": lat_batch,
                "lat_batches_measured": lat["batches"],
                # round-9 dual-lane mixed-load tier: per-lane records so a
                # latency win can't hide a bulk regression (or vice versa)
                **({
                    "lat_p99_ms": round(dual["lat_p99_ms"], 3),
                    "lat_p50_ms": round(dual["lat_p50_ms"], 3),
                    "lat_vps": round(dual["lat_vps"], 1),
                    "dual_bulk_vps": round(dual["bulk_vps"], 1),
                    "single_lane_p99_ms": round(dual["single_p99_ms"], 3),
                    "lat_vs_single": round(
                        dual["single_p99_ms"]
                        / max(dual["lat_p99_ms"], 1e-9), 1),
                    "lat_spill_cnt": dual["lat_spill_cnt"],
                    "lat_deadline_closes": dual["lat_deadline_closes"],
                    "lat_compile_cnt": dual["compile_cnt"],
                    "lat_deadline_us": dual["deadline_us"],
                } if dual and "error" not in dual else {}),
                **({"dual_error": dual["error"]}
                   if "error" in dual else {}),
                # round-10 antipa A/B: higher antipa_vs_strict = the
                # halved chain pays for its divstep (land bar: >= 1.05)
                **ant,
                # round-11 closed-loop tuner: lower converge_s is better;
                # reverts in this scenario mean a rule stepped wrong
                **at,
                # round-12 drain lane: cost of a zero-loss rolling restart
                **dr,
                # round-17 fleet lane: host-loss failover cost + the two
                # exactly-once invariants recorded as enforced zeros
                **fl,
                # round-13 shred lane: batched recover vs per-set loop
                # (shred_batch_vs_perset >= 3 is the land bar on device;
                # wiring-only on CPU), batched merkle walk rate
                **sh,
                # round-14 leader lane: device PoH hash rate / tick cost
                # (~1 M hash/s is the device land bar; wiring-only on
                # CPU), pack per-txn host cost, batched-vs-serial spans
                **ld,
                # round-10 wire front-door lane: loopback packet->verdict
                "net_vps": round(net.get("vps", 0.0), 1),
                "net_pps": round(net.get("pps", 0.0), 1),
                "net_p50_ms": round(net.get("p50_ms", 0.0), 3),
                "net_p99_ms": round(net.get("p99_ms", 0.0), 3),
                "net_txns": net.get("txns", 0),
                # round-16 burst packet protection: with the .so present
                # the e2e lane must never touch the fallback path
                "net_crypto_fallback": net.get("crypto_fallback", -1),
                **({"quic_crypto_us_pkt": round(qcr["native"], 2)}
                   if "native" in qcr else {}),
                **({"quic_crypto_us_pkt_fallback":
                    round(qcr["fallback"], 2)} if "fallback" in qcr else {}),
                **({"quic_crypto_error": qcr["error"]}
                   if "error" in qcr else {}),
                "net_packed_vps": round(netp.get("vps", 0.0), 1),
                # identical = the packed-publish quic tile produced the
                # exact verdict stream of the legacy per-txn path on the
                # mixed valid/invalid fixed set
                "net_packed_identical": bool(
                    netp
                    and netp.get("fixed_pass", -1) == net.get("fixed_pass")
                    and netp.get("fixed_sink", -1) == net.get("fixed_sink")
                    and net.get("fixed_pass", 0) > 0),
                **({"net_error": net["error"]} if "error" in net else {}),
            }
        )
    )


if __name__ == "__main__":
    main()
