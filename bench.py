#!/usr/bin/env python
"""Headline benchmark: batched ed25519 signature verification throughput.

Mirrors the reference's north-star benchmark (BASELINE.json config #2: a
fixed 4096-txn batch of single-sig transfers through the verify hot path;
reference CPU throughput 30 K verifies/s/core, FPGA 1 M verifies/s/card —
src/wiredancer/README.md:100-104).  Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline is measured throughput / 1e6 (the 1 M verifies/s/chip target,
equal to the reference FPGA card's throughput).
"""

import json
import os
import sys
import time

import jax
import numpy as np


def main():
    from firedancer_tpu.utils import xla_cache
    xla_cache.enable()  # rlc graphs compile slowly cold; the cache is primed
    from firedancer_tpu.models.verifier import (
        SigVerifier,
        VerifierConfig,
        make_example_batch,
    )

    batch = int(os.environ.get("FDTPU_BENCH_BATCH", 4096))
    mode = os.environ.get("FDTPU_BENCH_MODE", "strict")
    cfg = VerifierConfig(batch=batch, msg_maxlen=128)
    verifier = SigVerifier(cfg, mode=mode, msm_m=8)
    args = make_example_batch(batch, cfg.msg_maxlen, valid=True, sign_pool=64)

    # warmup / compile
    ok = verifier(*args)
    if not bool(np.asarray(ok).all()):
        print(
            json.dumps({"error": "correctness check failed in warmup"}),
            file=sys.stderr,
        )
        sys.exit(1)

    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        ok = verifier(*args)
    ok.block_until_ready()
    dt = time.perf_counter() - t0

    vps = batch * iters / dt
    print(
        json.dumps(
            {
                "metric": "ed25519_verify_throughput",
                "value": round(vps, 1),
                "unit": "verifies/sec/chip",
                "vs_baseline": round(vps / 1e6, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
