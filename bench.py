#!/usr/bin/env python
"""Headline benchmark: batched ed25519 signature verification throughput
plus p99 verify-batch latency.

Mirrors the reference's north-star benchmark (BASELINE.json config #2: a
fixed 4096-txn batch of single-sig transfers through the verify hot path;
reference CPU throughput 30 K verifies/s/core, FPGA 1 M verifies/s/card —
src/wiredancer/README.md:100-104).  Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

vs_baseline is measured throughput / 1e6 (the 1 M verifies/s/chip target,
equal to the reference FPGA card's throughput).  The same line carries the
second BASELINE.md headline as extra keys: p99 batch latency through
VerifyPipeline (target < 2 ms, "p99_batch_ms"/"p99_target_ms").

Measurement notes (hard-won, do not regress):
  * ``block_until_ready()`` does NOT await remote completion on this
    container's tunneled TPU; only a device->host fetch (``np.asarray``)
    truly synchronizes.  Throughput therefore uses pipelined dispatch of
    all iterations followed by ONE final fetch of the last output — device
    execution is in-order, so draining the last result drains them all.
  * Latency is measured per-batch with a fetch inside the timed region
    (that IS the verify tile's round trip: the host needs the pass bits).
"""

import json
import os
import sys
import time

import numpy as np


def measure_throughput(verifier, args, iters: int) -> float:
    """Verifies/sec with pipelined dispatch and one true final sync."""
    t0 = time.perf_counter()
    ok = None
    for _ in range(iters):
        ok = verifier(*args)
    np.asarray(ok)  # in-order device queue: draining the last drains all
    dt = time.perf_counter() - t0
    return args[2].shape[0] * iters / dt


def measure_throughput_median(verifier, args, iters: int, reps: int):
    """Repeated-run protocol for the shared chip's ±20-30% run-to-run
    variance: the headline is the MEDIAN of `reps` measurements; min/max
    ride along so the spread is visible in the record."""
    runs = sorted(measure_throughput(verifier, args, iters)
                  for _ in range(reps))
    return runs[len(runs) // 2], runs


def measure_device_batch_ms(verify_fn, batch: int, maxlen: int,
                            reps: int = 5) -> dict:
    """DEVICE-side per-batch verify time by slope: drain N1 then N2
    pipelined dispatches; (T2-T1)/(N2-N1) cancels the tunnel RTT and
    per-dispatch host overhead, leaving on-die compute + queueing.  The
    median/max over `reps` slope measurements is the honest device-side
    latency record this environment permits (no per-batch percentiles
    without paying an RTT per sample)."""
    za = (np.zeros((batch, maxlen), np.uint8), np.zeros((batch,), np.int32),
          np.zeros((batch, 64), np.uint8), np.zeros((batch, 32), np.uint8))
    np.asarray(verify_fn(*za))            # compile + warm
    n1, n2 = 4, 20
    slopes = []
    for _ in range(reps):
        ts = []
        for n in (n1, n2):
            t0 = time.perf_counter()
            ok = None
            for _ in range(n):
                ok = verify_fn(*za)
            np.asarray(ok)
            ts.append(time.perf_counter() - t0)
        slopes.append((ts[1] - ts[0]) / (n2 - n1) * 1e3)
    slopes.sort()
    return {"p50_ms": slopes[len(slopes) // 2], "max_ms": slopes[-1],
            "reps": reps}


def measure_p99_ms(verify_fn, batch: int, msg_maxlen: int, reps: int) -> dict:
    """p99 batch latency through VerifyPipeline at a fixed offered load.

    The offered load is unique-but-invalid signatures: the verify graph is
    fixed-shape and data-independent (every lane computes the full check
    regardless of validity — ref fd_ed25519_verify has early-outs, ours by
    design does not), so latency is identical to valid traffic while
    skipping ~batch*reps host-side python-int signings.  Uniqueness keeps
    the tcache pre-dedup from short-circuiting submits.  Correctness of the
    verifier itself is asserted in the throughput section (valid sigs).
    """
    from firedancer_tpu.ballet import txn as txn_lib
    from firedancer_tpu.disco.pipeline import VerifyPipeline

    rng = np.random.default_rng(42)
    blockhash = rng.bytes(32)
    program = rng.bytes(32)
    # compile the bucket's graph OUTSIDE the timed region: the first flush
    # would otherwise record minutes of XLA compile as a "batch latency"
    np.asarray(verify_fn(
        np.zeros((batch, msg_maxlen), np.uint8),
        np.zeros((batch,), np.int32),
        np.zeros((batch, 64), np.uint8),
        np.zeros((batch, 32), np.uint8)))
    pipe = VerifyPipeline(verify_fn, batch=batch, msg_maxlen=msg_maxlen)

    n = batch * reps
    pub = rng.bytes(32)
    for i in range(n):
        msg = txn_lib.build_unsigned(
            [pub], blockhash, [(1, bytes([0]), i.to_bytes(8, "little"))],
            extra_accounts=[program])
        payload = txn_lib.assemble([rng.bytes(64)], msg)
        pipe.submit(payload)
    pipe.flush()
    snap = pipe.metrics.snapshot()
    return {
        "p50_ms": snap["batch_ns_p50"] / 1e6,
        "p99_ms": snap["batch_ns_p99"] / 1e6,
        "batches": snap["batches"],
    }


def measure_pipe_vps(verify_fn, batch: int, maxlen: int, n_txn: int) -> float:
    """Tile-path throughput: drive the ASYNC VerifyPipeline exactly as
    the verify tile does (parse -> pre-dedup -> bucket -> non-blocking
    dispatch -> ordered harvest) and count verifies/sec including all
    host-side costs.  The VERDICT r2 #3 'done' bar: this number within
    ~20%% of the raw-batch headline means the bench survives into the
    product path."""
    from firedancer_tpu.ballet import txn as txn_lib
    from firedancer_tpu.disco.pipeline import VerifyPipeline

    rng = np.random.default_rng(7)
    blockhash = rng.bytes(32)
    program = rng.bytes(32)
    pub = rng.bytes(32)
    payloads = []
    for i in range(n_txn):
        msg = txn_lib.build_unsigned(
            [pub], blockhash, [(1, bytes([0]), i.to_bytes(8, "little"))],
            extra_accounts=[program])
        payloads.append(txn_lib.assemble([rng.bytes(64)], msg))
    # compile outside the timed region
    np.asarray(verify_fn(
        np.zeros((batch, maxlen), np.uint8), np.zeros((batch,), np.int32),
        np.zeros((batch, 64), np.uint8), np.zeros((batch, 32), np.uint8)))
    pipe = VerifyPipeline(verify_fn, batch=batch, msg_maxlen=maxlen,
                          tcache_depth=1 << 21, max_inflight=8)
    t0 = time.perf_counter()
    for p in payloads:
        pipe.submit(p)
    pipe.flush()
    dt = time.perf_counter() - t0
    return n_txn / dt


def measure_pipe_host_us(batch: int, maxlen: int, n_txn: int) -> float:
    """Host-side cost of the tile path alone (parse -> dedup -> bucket
    fill), with a no-op device: microseconds per txn.  Separates the
    tile's own CPU cost from the tunnel-upload wall (see upload_mbps) —
    the reference provisions 33 verify tiles/cores for 1M/s
    (bench-icelake-80core.toml), i.e. ~30 us/txn/core of host work is
    par for the architecture."""
    from firedancer_tpu.ballet import txn as txn_lib
    from firedancer_tpu.disco.pipeline import VerifyPipeline

    rng = np.random.default_rng(11)
    blockhash, program, pub = rng.bytes(32), rng.bytes(32), rng.bytes(32)
    payloads = []
    for i in range(n_txn):
        msg = txn_lib.build_unsigned(
            [pub], blockhash, [(1, bytes([0]), i.to_bytes(8, "little"))],
            extra_accounts=[program])
        payloads.append(txn_lib.assemble([rng.bytes(64)], msg))

    def fake(m, l, s, p):
        return np.ones((np.asarray(m).shape[0],), bool)

    pipe = VerifyPipeline(fake, batch=batch, msg_maxlen=maxlen,
                          tcache_depth=1 << 21, max_inflight=8)
    t0 = time.perf_counter()
    for p in payloads:
        pipe.submit(p)
    pipe.flush()
    return (time.perf_counter() - t0) / n_txn * 1e6


def measure_upload_mbps() -> float:
    """Host->device transfer bandwidth (the tunnel's ingest wall: a real
    deployment's PCIe/DMA moves GB/s; this environment's tunnel is the
    binding constraint on any path that must upload fresh txn bytes)."""
    import jax

    blob = np.zeros((4 << 20,), np.uint8)
    jax.device_put(blob).block_until_ready()      # warm path
    t0 = time.perf_counter()
    jax.device_put(blob).block_until_ready()
    dt = time.perf_counter() - t0
    return len(blob) / dt / 1e6


def main():
    from firedancer_tpu.utils import xla_cache
    xla_cache.enable()  # verify graphs compile slowly cold; cache is primed
    from firedancer_tpu.models.verifier import (
        SigVerifier,
        VerifierConfig,
        make_example_batch,
    )

    # 32k lanes: throughput saturates ~68-73 K/s between 32k and 64k while
    # latency and compile time keep growing (docs/perf_ceiling.md table)
    batch = int(os.environ.get("FDTPU_BENCH_BATCH", 32768))
    mode = os.environ.get("FDTPU_BENCH_MODE", "strict")
    # 24 iters amortize the ~15 ms/dispatch tunnel overhead below the noise
    iters = int(os.environ.get("FDTPU_BENCH_ITERS", 24))
    cfg = VerifierConfig(batch=batch, msg_maxlen=128)
    verifier = SigVerifier(cfg, mode=mode, msm_m=8)
    args = make_example_batch(batch, cfg.msg_maxlen, valid=True, sign_pool=64)

    # warmup / compile + correctness gate (true fetch)
    ok = verifier(*args)
    if not bool(np.asarray(ok).all()):
        print(
            json.dumps({"error": "correctness check failed in warmup"}),
            file=sys.stderr,
        )
        sys.exit(1)

    reps = int(os.environ.get("FDTPU_BENCH_REPS", 5))
    vps, runs = measure_throughput_median(verifier, args, iters, reps)

    # p99 latency bucket: a smaller batch sized for latency, not throughput
    lat_batch = int(os.environ.get("FDTPU_BENCH_LAT_BATCH", 256))
    lat_reps = int(os.environ.get("FDTPU_BENCH_LAT_REPS", 48))
    lat_verifier = SigVerifier(VerifierConfig(batch=lat_batch, msg_maxlen=128))
    lat = measure_p99_ms(lat_verifier, lat_batch, 128, lat_reps)
    dev = measure_device_batch_ms(lat_verifier, lat_batch, 128)

    # tile-path throughput through the async VerifyPipeline (a large
    # bucket so device time dominates host parse)
    pipe_batch = int(os.environ.get("FDTPU_BENCH_PIPE_BATCH", 4096))
    pipe_verifier = SigVerifier(
        VerifierConfig(batch=pipe_batch, msg_maxlen=128))
    pipe_vps = measure_pipe_vps(pipe_verifier, pipe_batch, 128,
                                pipe_batch * 6)
    pipe_host_us = measure_pipe_host_us(pipe_batch, 128, pipe_batch * 2)
    upload_mbps = measure_upload_mbps()

    # round-trip floor of this environment (tunneled TPU: ~100-150 ms);
    # batch latency cannot go below it, so report it alongside for an
    # honest read of the device-side latency
    import jax.numpy as jnp
    tiny = jnp.zeros((8,), jnp.uint32) + 1
    np.asarray(tiny)
    rtts = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(tiny + 1)
        rtts.append(time.perf_counter() - t0)
    rtt_ms = sorted(rtts)[len(rtts) // 2] * 1e3

    print(
        json.dumps(
            {
                "metric": "ed25519_verify_throughput",
                "value": round(vps, 1),
                "unit": "verifies/sec/chip",
                "vs_baseline": round(vps / 1e6, 4),
                "runs_min": round(runs[0], 1),
                "runs_max": round(runs[-1], 1),
                "runs_n": len(runs),
                "p50_batch_ms": round(lat["p50_ms"], 3),
                "p99_batch_ms": round(lat["p99_ms"], 3),
                "p99_target_ms": 2.0,
                "rtt_floor_ms": round(rtt_ms, 3),
                "p99_minus_rtt_ms": round(max(0.0, lat["p99_ms"] - rtt_ms), 3),
                "device_batch_ms_p50": round(dev["p50_ms"], 3),
                "device_batch_ms_max": round(dev["max_ms"], 3),
                "pipe_vps": round(pipe_vps, 1),
                "pipe_vs_bench": round(pipe_vps / vps, 3),
                "pipe_host_us_txn": round(pipe_host_us, 1),
                "upload_mbps": round(upload_mbps, 1),
                "lat_batch": lat_batch,
                "lat_batches_measured": lat["batches"],
            }
        )
    )


if __name__ == "__main__":
    main()
