"""Round-3 VM syscall breadth (VERDICT r2 missing #7; registry parity
with src/flamenco/vm/fd_vm_syscalls.c:200-260): curve25519 group ops,
secp256k1_recover, sysvar getters, return data, memmove, stack height.

Syscall handlers are exercised directly against a Vm with scratch input
memory (the dispatch plumbing is covered by the existing interpreter
tests); cross-checks go against the host curve/secp implementations."""

import hashlib

from firedancer_tpu.flamenco import vm as vm_mod
from firedancer_tpu.flamenco.vm import (
    CURVE25519_EDWARDS, CURVE25519_RISTRETTO, CURVE_OP_ADD, CURVE_OP_MUL,
    CURVE_OP_SUB, Vm, _sc_curve_group_op, _sc_curve_multiscalar_mul,
    _sc_curve_validate_point, _sc_get_clock_sysvar, _sc_get_return_data,
    _sc_get_stack_height, _sc_memmove, _sc_secp256k1_recover,
    _sc_set_return_data)
from firedancer_tpu.ops import ed25519 as ed
from firedancer_tpu.ops import ristretto255 as ris

MM_INPUT = 0x4_0000_0000


def _vm(size=4096):
    return Vm(b"\x95" + bytes(7), input_mem=bytearray(size))


def _w(vm, off, data):
    vm.mem_write_bytes(MM_INPUT + off, bytes(data))
    return MM_INPUT + off


def _r(vm, off, n):
    return vm.mem_read_bytes(MM_INPUT + off, n)


def _ed_point(k):
    return ed._compress_host(ed._scalar_mul_base_host(k))


def test_curve_validate_point():
    vm = _vm()
    good = _ed_point(7)
    va = _w(vm, 0, good)
    assert _sc_curve_validate_point(vm, CURVE25519_EDWARDS, va) == 0
    # find a y with no curve point (x^2 = u/v non-square): ~half of all y
    bad = None
    for y in range(2, 40):
        enc = y.to_bytes(32, "little")
        if ed._decompress_host(enc) is None:
            bad = enc
            break
    assert bad is not None
    _w(vm, 0, bad)
    assert _sc_curve_validate_point(vm, CURVE25519_EDWARDS, va) == 1

    # ristretto: the identity encoding (all zeros) validates
    _w(vm, 0, ris.Point.identity().encode())
    assert _sc_curve_validate_point(vm, CURVE25519_RISTRETTO, va) == 0
    _w(vm, 0, b"\x01" + b"\xff" * 31)
    assert _sc_curve_validate_point(vm, CURVE25519_RISTRETTO, va) == 1
    assert _sc_curve_validate_point(vm, 9, va) == 1  # unknown curve


def test_curve_group_op_edwards_matches_host():
    vm = _vm()
    a, b = _ed_point(11), _ed_point(22)
    va = _w(vm, 0, a)
    vb = _w(vm, 32, b)
    out = MM_INPUT + 64
    assert _sc_curve_group_op(vm, CURVE25519_EDWARDS, CURVE_OP_ADD,
                              va, vb, out) == 0
    assert _r(vm, 64, 32) == _ed_point(33)
    assert _sc_curve_group_op(vm, CURVE25519_EDWARDS, CURVE_OP_SUB,
                              vb, va, out) == 0
    assert _r(vm, 64, 32) == _ed_point(11)
    # mul: left operand is the scalar
    k = 5
    vs = _w(vm, 96, k.to_bytes(32, "little"))
    assert _sc_curve_group_op(vm, CURVE25519_EDWARDS, CURVE_OP_MUL,
                              vs, va, out) == 0
    assert _r(vm, 64, 32) == _ed_point(55)
    # invalid point rejected
    bad = next(y.to_bytes(32, "little") for y in range(2, 40)
               if ed._decompress_host(y.to_bytes(32, "little")) is None)
    _w(vm, 0, bad)
    assert _sc_curve_group_op(vm, CURVE25519_EDWARDS, CURVE_OP_ADD,
                              va, vb, out) == 1


def test_curve_msm_matches_sum():
    vm = _vm()
    ks = [3, 9, 14]
    pts = [_ed_point(2), _ed_point(5), _ed_point(8)]
    sva = _w(vm, 0, b"".join(k.to_bytes(32, "little") for k in ks))
    pva = _w(vm, 96, b"".join(pts))
    out = MM_INPUT + 256
    assert _sc_curve_multiscalar_mul(
        vm, CURVE25519_EDWARDS, sva, pva, 3, out) == 0
    want = 3 * 2 + 9 * 5 + 14 * 8
    assert _r(vm, 256, 32) == _ed_point(want)
    assert _sc_curve_multiscalar_mul(
        vm, CURVE25519_EDWARDS, sva, pva, 0, out) == 1


def test_curve_group_op_ristretto():
    vm = _vm()
    p = ris.Point.identity()
    # build 2B and 3B from the identity via decode of known encodings:
    # use scalar-mul of a decoded valid point (the encoding of [k]B is
    # produced by the library itself)
    import secrets as _s
    base = None
    for _ in range(100):
        cand = ris.decode(_s.token_bytes(32))
        if cand is not None:
            base = cand
            break
    assert base is not None
    two = base.mul(2)
    va = _w(vm, 0, base.encode())
    vb = _w(vm, 32, base.encode())
    out = MM_INPUT + 64
    assert _sc_curve_group_op(vm, CURVE25519_RISTRETTO, CURVE_OP_ADD,
                              va, vb, out) == 0
    assert _r(vm, 64, 32) == two.encode()
    vs = _w(vm, 96, (3).to_bytes(32, "little"))
    assert _sc_curve_group_op(vm, CURVE25519_RISTRETTO, CURVE_OP_MUL,
                              vs, va, out) == 0
    assert _r(vm, 64, 32) == base.mul(3).encode()


def test_secp256k1_recover_roundtrip():
    from firedancer_tpu.ballet import secp256k1 as secp
    vm = _vm()
    secret = 0x1234567890ABCDEF1234
    h = hashlib.sha256(b"recover me").digest()
    r, s, recid = secp.sign(h, secret)
    hva = _w(vm, 0, h)
    sva = _w(vm, 32, r.to_bytes(32, "big") + s.to_bytes(32, "big"))
    out = MM_INPUT + 128
    assert _sc_secp256k1_recover(vm, hva, recid, sva, out) == 0
    got = _r(vm, 128, 64)
    want = secp._mul(secret, (secp._GX, secp._GY))
    assert got == want[0].to_bytes(32, "big") + want[1].to_bytes(32, "big")
    # corrupted sig fails cleanly
    assert _sc_secp256k1_recover(vm, hva, 9, sva, out) == 1


def test_memmove_overlap_and_return_data():
    vm = _vm()
    _w(vm, 0, b"abcdefgh")
    _sc_memmove(vm, MM_INPUT + 2, MM_INPUT, 6)   # overlapping forward
    assert _r(vm, 0, 8) == b"ababcdef"

    data_va = _w(vm, 100, b"hello-return")
    assert _sc_set_return_data(vm, data_va, 12) == 0
    out_va = MM_INPUT + 200
    prog_va = MM_INPUT + 300
    n = _sc_get_return_data(vm, out_va, 12, prog_va)
    assert n == 12 and _r(vm, 200, 12) == b"hello-return"
    assert _sc_get_stack_height(vm) == 1  # no txn ctx: top level


def test_sysvar_getters_through_execution():
    """A deployed program calling sol_get_clock_sysvar sees the bank's
    clock account bytes (the executor threads xid into the txn ctx)."""
    import struct

    from firedancer_tpu.ballet import txn as txn_lib
    from firedancer_tpu.flamenco import genesis as gen_mod
    from firedancer_tpu.flamenco import sysvar
    from firedancer_tpu.flamenco.runtime import Runtime
    from firedancer_tpu.flamenco.types import SYSVAR_CLOCK_ID, Account
    from tests.test_sbpf_vm import _mini_elf
    from firedancer_tpu.ballet.sbpf import asm
    from firedancer_tpu.flamenco.types import BPF_LOADER_ID

    # program: call sol_get_clock_sysvar(r1=heap) then store the slot
    # (first 8 bytes of the clock sysvar) into its first account's data
    prog_src = """
        mov r6, r1
        lddw r1, 0x300000000
        syscall sol_get_clock_sysvar
        jne r0, 0, +5
        lddw r1, 0x300000000
        ldxdw r2, [r1+0]
        stxdw [r6+90], r2
        mov r0, 0
        exit
        mov r0, 1
        exit"""
    elf = _mini_elf(asm(prog_src))

    faucet_seed = (1).to_bytes(32, "little")
    faucet_pk = ed.keypair_from_seed(faucet_seed)[0]
    g = gen_mod.create(faucet_pk, creation_time=1_700_000_000,
                       slots_per_epoch=32)
    prog_pk = ed.keypair_from_seed((5).to_bytes(32, "little"))[0]
    data_pk = ed.keypair_from_seed((6).to_bytes(32, "little"))[0]
    g.accounts[prog_pk] = Account(lamports=1, data=elf, owner=BPF_LOADER_ID,
                                  executable=True)
    g.accounts[data_pk] = Account(lamports=1, data=bytes(8), owner=prog_pk)
    rt = Runtime(g)
    b = rt.new_bank(3)
    msg = txn_lib.build_unsigned(
        [faucet_pk], rt.root_hash, [(2, bytes([1]), b"")],
        extra_accounts=[data_pk, prog_pk], readonly_unsigned_cnt=1)
    payload = txn_lib.assemble([ed.sign(faucet_seed, msg)], msg)
    res = b.execute_txn(payload)
    assert res.ok, res.err
    stored = rt.accdb.load(b.xid, data_pk).data
    clock = rt.accdb.load(b.xid, SYSVAR_CLOCK_ID).data
    assert stored == clock[:8]
    assert struct.unpack("<Q", stored)[0] == 3  # the bank's slot


# ---- round-5 additions: registry parity with the reference's actually-
# registered set (fd_vm_syscalls.c:218-270) plus the two trivial
# newer-Agave getters


def test_alloc_free_bump_allocator():
    from firedancer_tpu.flamenco.vm import MM_HEAP, _sc_alloc_free

    vm = _vm()
    a1 = _sc_alloc_free(vm, 10, 0)
    a2 = _sc_alloc_free(vm, 7, 0)
    assert a1 == MM_HEAP and a2 == MM_HEAP + 16     # 8-aligned bump
    assert _sc_alloc_free(vm, 0, a1) == 0           # free: no-op
    a3 = _sc_alloc_free(vm, 1, 0)
    assert a3 == MM_HEAP + 24                       # free didn't reclaim
    assert _sc_alloc_free(vm, 1 << 40, 0) == 0      # OOM -> NULL
    vm.mem_write_bytes(a1, b"x" * 10)               # allocation is usable


def test_remaining_compute_units_and_aliases():
    from firedancer_tpu.flamenco.executor import (BorrowedAccount, InstrCtx,
                                                  TxnCtx)
    from firedancer_tpu.flamenco.vm import (_sc_remaining_compute_units,
                                            SYSCALLS)

    vm = _vm()
    # the LIVE VM meter wins (the txctx tally is stale mid-execution)
    vm.cu = 1234
    assert _sc_remaining_compute_units(vm) == 1234
    vm.cu = -5                      # mid-fault: clamps to zero
    assert _sc_remaining_compute_units(vm) == 0
    names = {sc.name for sc in SYSCALLS.values()}
    assert {"custom_panic", "sol_alloc_free_", "sol_get_fees_sysvar",
            "sol_get_last_restart_slot",
            "sol_get_processed_sibling_instruction"} <= names


def test_processed_sibling_instruction_two_phase():
    import struct

    from firedancer_tpu.flamenco.executor import (BorrowedAccount, InstrCtx,
                                                  TxnCtx)
    from firedancer_tpu.flamenco.vm import \
        _sc_get_processed_sibling_instruction

    vm = _vm()
    pk_a, pk_b = bytes([1]) * 32, bytes([2]) * 32
    tx = TxnCtx(accounts=[])
    # two completed siblings at height 1, most recent last
    tx.instr_trace = [
        (1, pk_a, [(pk_b, True, False)], b"first"),
        (1, pk_b, [(pk_a, False, True)], b"second!"),
        (2, pk_a, [], b"nested"),                   # different height
    ]
    tx.instr_stack = [pk_a]                         # current height 1
    vm.ictx = InstrCtx(tx, pk_a, [], b"")

    meta = _w(vm, 0, bytes(16))
    pid = _w(vm, 16, bytes(32))
    data = _w(vm, 48, bytes(32))
    accts = _w(vm, 96, bytes(64))
    # phase 1: learn lengths of sibling 0 (the most recent: "second!")
    assert _sc_get_processed_sibling_instruction(
        vm, 0, meta, pid, data, accts) == 1
    dlen, alen = struct.unpack("<QQ", _r(vm, 0, 16))
    assert (dlen, alen) == (7, 1)
    # phase 2: buffers declared at the true lengths -> payload copied
    assert _sc_get_processed_sibling_instruction(
        vm, 0, meta, pid, data, accts) == 1
    assert _r(vm, 16, 32) == pk_b
    assert _r(vm, 48, 7) == b"second!"
    am = _r(vm, 96, 34)
    assert am[:32] == pk_a and am[32] == 0 and am[33] == 1
    # index 1 = the earlier sibling; index 2 = not found
    assert _sc_get_processed_sibling_instruction(
        vm, 1, meta, pid, data, accts) == 1
    dlen, _ = struct.unpack("<QQ", _r(vm, 0, 16))
    assert dlen == 5
    assert _sc_get_processed_sibling_instruction(
        vm, 2, meta, pid, data, accts) == 0
    # parent boundary: after an entry BELOW the current height, earlier
    # same-height entries are invisible (they belong to another parent)
    tx.instr_trace = [
        (2, pk_a, [], b"under-parent-A"),
        (1, pk_a, [], b"parent-A-done"),     # boundary
        (2, pk_b, [], b"under-parent-B"),
    ]
    tx.instr_stack = [pk_b, pk_a]            # current height 2
    assert _sc_get_processed_sibling_instruction(
        vm, 0, meta, pid, data, accts) == 1
    dlen, _ = struct.unpack("<QQ", _r(vm, 0, 16))
    assert dlen == len(b"under-parent-B")
    assert _sc_get_processed_sibling_instruction(
        vm, 1, meta, pid, data, accts) == 0  # A's subtree hidden


def test_instr_trace_recorded_by_executor():
    """The executor records completed instructions (height, program,
    metas, data) — the trace sol_get_processed_sibling_instruction
    introspects."""
    import json
    import os

    from firedancer_tpu.flamenco import fixtures as fxmod

    with open(os.path.join(os.path.dirname(__file__), "fixtures",
                           "instr_fixtures.json")) as f:
        fx = next(x for x in json.load(f)
                  if x["name"] == "system_transfer_ok_999")
    err, txctx = fxmod.execute(fx)
    assert err is None
    assert len(txctx.instr_trace) == 1
    height, prog, metas, data = txctx.instr_trace[0]
    assert height == 1 and prog == bytes.fromhex(fx["program_id"])
    assert data == bytes.fromhex(fx["data"])
    assert len(metas) == len(fx["instr_accounts"])
