"""funk fork-tree database tests (ref behaviors: src/funk/fd_funk.h:1-62
concept doc; src/funk/test_funk_txn.c fork semantics)."""

import pytest

from firedancer_tpu.funk import Funk, FunkTxnError


def test_root_read_write():
    f = Funk()
    f.write(None, b"k1", b"v1")
    assert f.read(None, b"k1") == b"v1"
    f.remove(None, b"k1")
    assert f.read(None, b"k1") is None


def test_fork_isolation_and_publish():
    f = Funk()
    f.write(None, b"acct", b"genesis")
    f.txn_prepare("slot1a")
    f.txn_prepare("slot1b")
    f.write("slot1a", b"acct", b"fork-a")
    f.write("slot1b", b"acct", b"fork-b")
    # isolation: each fork sees its own value; root unchanged
    assert f.read("slot1a", b"acct") == b"fork-a"
    assert f.read("slot1b", b"acct") == b"fork-b"
    assert f.read(None, b"acct") == b"genesis"
    # publish fork a: root takes its value, fork b dies
    f.txn_publish("slot1a")
    assert f.read(None, b"acct") == b"fork-a"
    assert not f.txn_is_prepared("slot1b")
    assert not f.txn_is_prepared("slot1a")


def test_ancestry_chain_resolution():
    f = Funk()
    f.write(None, b"a", b"0")
    f.write(None, b"b", b"0")
    f.txn_prepare(1)
    f.write(1, b"a", b"1")
    f.txn_prepare(2, parent_xid=1)
    f.write(2, b"b", b"2")
    # leaf sees nearest delta then ancestors then root
    assert f.read(2, b"a") == b"1"
    assert f.read(2, b"b") == b"2"
    # frozen parent rejects writes
    with pytest.raises(FunkTxnError):
        f.write(1, b"a", b"nope")
    # publishing the leaf folds the whole chain
    assert f.txn_publish(2) == 2
    assert f.read(None, b"a") == b"1"
    assert f.read(None, b"b") == b"2"


def test_tombstones_and_keys():
    f = Funk()
    f.write(None, b"x", b"1")
    f.write(None, b"y", b"2")
    f.txn_prepare("t")
    f.remove("t", b"x")
    f.write("t", b"z", b"3")
    assert f.read("t", b"x") is None
    assert f.read(None, b"x") == b"1"
    assert set(f.keys("t")) == {b"y", b"z"}
    f.txn_publish("t")
    assert set(f.keys()) == {b"y", b"z"}


def test_publish_preserves_descendants_prunes_uncles():
    f = Funk()
    f.txn_prepare("s1")
    f.write("s1", b"k", b"s1")
    f.txn_prepare("s2", parent_xid="s1")
    f.write("s2", b"k", b"s2")
    f.txn_prepare("s2x", parent_xid="s1")   # competing child of s1
    f.txn_prepare("other")                  # competing root fork
    f.txn_publish("s1")
    # s2/s2x survive re-parented to root; other died
    assert f.txn_is_prepared("s2") and f.txn_is_prepared("s2x")
    assert not f.txn_is_prepared("other")
    assert f.read(None, b"k") == b"s1"
    assert f.read("s2", b"k") == b"s2"
    f.txn_publish("s2")
    assert f.read(None, b"k") == b"s2"
    assert not f.txn_is_prepared("s2x")


def test_cancel_subtree():
    f = Funk()
    f.txn_prepare(1)
    f.txn_prepare(2, parent_xid=1)
    f.txn_prepare(3, parent_xid=2)
    f.txn_cancel(2)
    assert f.txn_is_prepared(1)
    assert not f.txn_is_prepared(2) and not f.txn_is_prepared(3)
    # parent unfrozen again
    f.write(1, b"k", b"v")
    assert f.read(1, b"k") == b"v"


def test_checkpoint_restore(tmp_path):
    f = Funk()
    for i in range(100):
        f.write(None, i.to_bytes(4, "little"), bytes([i % 256]) * 8)
    p = str(tmp_path / "funk.ckpt")
    f.checkpoint(p)
    g = Funk.restore(p)
    assert g.record_cnt == 100
    for i in range(100):
        assert g.read(None, i.to_bytes(4, "little")) == bytes([i % 256]) * 8


def test_errors():
    f = Funk()
    with pytest.raises(FunkTxnError):
        f.read("nope", b"k")
    with pytest.raises(FunkTxnError):
        f.txn_publish("nope")
    f.txn_prepare("a")
    with pytest.raises(FunkTxnError):
        f.txn_prepare("a")
    with pytest.raises(FunkTxnError):
        f.write(None, b"k", b"v")  # root write with txns in flight
