"""funk fork-tree database tests (ref behaviors: src/funk/fd_funk.h:1-62
concept doc; src/funk/test_funk_txn.c fork semantics)."""

import pytest

from firedancer_tpu.funk import Funk, FunkTxnError


def test_root_read_write():
    f = Funk()
    f.write(None, b"k1", b"v1")
    assert f.read(None, b"k1") == b"v1"
    f.remove(None, b"k1")
    assert f.read(None, b"k1") is None


def test_fork_isolation_and_publish():
    f = Funk()
    f.write(None, b"acct", b"genesis")
    f.txn_prepare("slot1a")
    f.txn_prepare("slot1b")
    f.write("slot1a", b"acct", b"fork-a")
    f.write("slot1b", b"acct", b"fork-b")
    # isolation: each fork sees its own value; root unchanged
    assert f.read("slot1a", b"acct") == b"fork-a"
    assert f.read("slot1b", b"acct") == b"fork-b"
    assert f.read(None, b"acct") == b"genesis"
    # publish fork a: root takes its value, fork b dies
    f.txn_publish("slot1a")
    assert f.read(None, b"acct") == b"fork-a"
    assert not f.txn_is_prepared("slot1b")
    assert not f.txn_is_prepared("slot1a")


def test_ancestry_chain_resolution():
    f = Funk()
    f.write(None, b"a", b"0")
    f.write(None, b"b", b"0")
    f.txn_prepare(1)
    f.write(1, b"a", b"1")
    f.txn_prepare(2, parent_xid=1)
    f.write(2, b"b", b"2")
    # leaf sees nearest delta then ancestors then root
    assert f.read(2, b"a") == b"1"
    assert f.read(2, b"b") == b"2"
    # frozen parent rejects writes
    with pytest.raises(FunkTxnError):
        f.write(1, b"a", b"nope")
    # publishing the leaf folds the whole chain
    assert f.txn_publish(2) == 2
    assert f.read(None, b"a") == b"1"
    assert f.read(None, b"b") == b"2"


def test_tombstones_and_keys():
    f = Funk()
    f.write(None, b"x", b"1")
    f.write(None, b"y", b"2")
    f.txn_prepare("t")
    f.remove("t", b"x")
    f.write("t", b"z", b"3")
    assert f.read("t", b"x") is None
    assert f.read(None, b"x") == b"1"
    assert set(f.keys("t")) == {b"y", b"z"}
    f.txn_publish("t")
    assert set(f.keys()) == {b"y", b"z"}


def test_publish_preserves_descendants_prunes_uncles():
    f = Funk()
    f.txn_prepare("s1")
    f.write("s1", b"k", b"s1")
    f.txn_prepare("s2", parent_xid="s1")
    f.write("s2", b"k", b"s2")
    f.txn_prepare("s2x", parent_xid="s1")   # competing child of s1
    f.txn_prepare("other")                  # competing root fork
    f.txn_publish("s1")
    # s2/s2x survive re-parented to root; other died
    assert f.txn_is_prepared("s2") and f.txn_is_prepared("s2x")
    assert not f.txn_is_prepared("other")
    assert f.read(None, b"k") == b"s1"
    assert f.read("s2", b"k") == b"s2"
    f.txn_publish("s2")
    assert f.read(None, b"k") == b"s2"
    assert not f.txn_is_prepared("s2x")


def test_cancel_subtree():
    f = Funk()
    f.txn_prepare(1)
    f.txn_prepare(2, parent_xid=1)
    f.txn_prepare(3, parent_xid=2)
    f.txn_cancel(2)
    assert f.txn_is_prepared(1)
    assert not f.txn_is_prepared(2) and not f.txn_is_prepared(3)
    # parent unfrozen again
    f.write(1, b"k", b"v")
    assert f.read(1, b"k") == b"v"


def test_checkpoint_restore(tmp_path):
    f = Funk()
    for i in range(100):
        f.write(None, i.to_bytes(4, "little"), bytes([i % 256]) * 8)
    p = str(tmp_path / "funk.ckpt")
    f.checkpoint(p)
    g = Funk.restore(p)
    assert g.record_cnt == 100
    for i in range(100):
        assert g.read(None, i.to_bytes(4, "little")) == bytes([i % 256]) * 8


def test_errors():
    f = Funk()
    with pytest.raises(FunkTxnError):
        f.read("nope", b"k")
    with pytest.raises(FunkTxnError):
        f.txn_publish("nope")
    f.txn_prepare("a")
    with pytest.raises(FunkTxnError):
        f.txn_prepare("a")
    with pytest.raises(FunkTxnError):
        f.write(None, b"k", b"v")  # root write with txns in flight


# ------------------------------------------------- partitions (fd_funk_part)


def test_partitions_assign_iterate_and_survive_checkpoint(tmp_path):
    from firedancer_tpu.funk import PART_NULL, Funk

    fk = Funk(part_cnt=4)
    keys = [bytes([i]) * 32 for i in range(20)]
    for k in keys:
        fk.write(None, k, b"v" + k[:1])
    # default: everything unassigned
    assert fk.part_of(keys[0]) == PART_NULL
    assert sorted(fk.part_keys(PART_NULL)) == sorted(keys)

    fk.repartition()
    got = [fk.part_keys(p) for p in range(4)]
    assert sorted(sum(got, [])) == sorted(keys)  # disjoint, complete
    assert fk.part_keys(PART_NULL) == []

    # explicit set overrides; out-of-range rejected
    fk.part_set(keys[0], 3)
    assert fk.part_of(keys[0]) == 3
    import pytest as _pytest
    with _pytest.raises(ValueError):
        fk.part_set(keys[0], 7)

    # publish of a tombstone drops the partition tag
    fk.txn_prepare("t1")
    fk.remove("t1", keys[0])
    fk.txn_publish("t1")
    assert fk.part_of(keys[0]) == PART_NULL

    # tags survive checkpoint/restore
    p = str(tmp_path / "funk.ckpt")
    fk.checkpoint(p)
    fk2 = Funk.restore(p)
    assert fk2.part_of(keys[1]) == fk.part_of(keys[1])


def test_concurrent_readers_vs_publisher():
    """The reference's test_funk_concur shape: reader threads resolving
    through fork ancestry while the writer publishes forks out from under
    them.  Every read must return a value consistent with SOME published
    state — never a torn mid-fold view (key present with a stale conflict)
    and never an internal exception."""
    import threading

    from firedancer_tpu.funk import Funk, FunkTxnError

    fk = Funk()
    KEY = b"k" * 32
    fk.write(None, KEY, (0).to_bytes(8, "little"))
    stop = threading.Event()
    errors = []

    def reader():
        last = 0
        try:
            while not stop.is_set():
                raw = fk.read(None, KEY)
                if raw is None:
                    errors.append("key vanished")
                    return
                v = int.from_bytes(raw, "little")
                if v < last:  # published values are monotone
                    errors.append(f"went backwards {last} -> {v}")
                    return
                last = v
        except Exception as e:  # pragma: no cover
            errors.append(repr(e))

    threads = [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for i in range(1, 300):
            xid = ("slot", i)
            fk.txn_prepare(xid)
            fk.write(xid, KEY, i.to_bytes(8, "little"))
            # competing fork that always dies at publish
            dead = ("fork", i)
            fk.txn_prepare(dead)
            fk.write(dead, KEY, (10_000_000 + i).to_bytes(8, "little"))
            fk.txn_publish(xid)
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert errors == [], errors
    assert int.from_bytes(fk.read(None, KEY), "little") == 299
