"""Replay determinism: a leader-produced block (entries -> signed FEC
shreds -> blockstore) replays on an independent follower Runtime to the
IDENTICAL bank hash (ref behaviors: src/flamenco/runtime block eval +
src/disco/replay; the ledger-conformance property, SURVEY.md §4.7)."""

import pytest

from firedancer_tpu.ballet import entry as entry_lib
from firedancer_tpu.ballet import shred as shred_lib
from firedancer_tpu.ballet import txn as txn_lib
from firedancer_tpu.flamenco import genesis as gen_mod
from firedancer_tpu.flamenco import replay as replay_mod
from firedancer_tpu.flamenco import system_program as sysprog
from firedancer_tpu.flamenco.blockstore import Blockstore
from firedancer_tpu.flamenco.runtime import Runtime
from firedancer_tpu.flamenco.types import Account, SYSTEM_PROGRAM_ID
from firedancer_tpu.ops import ed25519 as ed


def _keypair(i):
    seed = i.to_bytes(32, "little")
    return seed, ed.keypair_from_seed(seed)[0]


@pytest.fixture()
def setup():
    faucet_seed, faucet_pk = _keypair(1)
    g = gen_mod.create(faucet_pk, creation_time=1_700_000_000,
                       slots_per_epoch=32)
    return g, (faucet_seed, faucet_pk)


def _make_block(g, faucet, n_txn=8):
    """Leader side: execute txns, build PoH entries for slot 1."""
    faucet_seed, faucet_pk = faucet
    leader_rt = Runtime(g)
    bank = leader_rt.new_bank(1)
    poh = bytes(32)
    entries = []
    for i in range(n_txn):
        dest = b"\xd7" + bytes(15) + i.to_bytes(16, "little")
        msg = txn_lib.build_unsigned(
            [faucet_pk], g.genesis_hash(),
            [(2, bytes([0, 1]), sysprog.ix_transfer(1000 + i))],
            extra_accounts=[dest, SYSTEM_PROGRAM_ID],
            readonly_unsigned_cnt=1)
        payload = txn_lib.assemble([ed.sign(faucet_seed, msg)], msg)
        res = bank.execute_txn(payload)
        assert res.ok, res.err
        mix = entry_lib.txn_mixin([payload])
        poh = entry_lib.next_hash(poh, 1, mix)
        entries.append(entry_lib.Entry(1, poh, [payload]))
    poh = entry_lib.next_hash(poh, 4, None)
    entries.append(entry_lib.Entry(4, poh, []))  # closing tick
    bank_hash = bank.freeze(poh)
    leader_rt.publish(1)
    return entries, bank_hash, leader_rt


def test_replay_matches_leader_bank_hash(setup):
    g, faucet = setup
    entries, leader_hash, _ = _make_block(g, faucet)

    follower = Runtime(g)
    res = replay_mod.replay_slot(follower, 1, entries, bytes(32),
                                 expected_bank_hash=leader_hash)
    assert res.ok, res.err
    assert res.bank_hash == leader_hash
    assert res.txn_cnt == 8 and res.txn_fail_cnt == 0
    follower.publish(1)
    assert follower.root_hash == leader_hash


def test_replay_through_shreds_and_blockstore(setup):
    g, faucet = setup
    entries, leader_hash, _ = _make_block(g, faucet)
    id_seed, _ = _keypair(9)
    batch = entry_lib.serialize_batch(entries)
    fs = shred_lib.make_fec_set(
        batch, slot=1, parent_off=1, version=1, fec_set_idx=0,
        sign_fn=lambda root: ed.sign(id_seed, root),
        data_cnt=32, code_cnt=32, slot_complete=True)

    bs = Blockstore()
    for raw in fs.data_shreds[5:] + fs.code_shreds:  # 5 erasures
        bs.insert_shred(raw)
    got = bs.slot_entries(1)
    assert got is not None

    follower = Runtime(g)
    res = replay_mod.replay_slot(follower, 1, got, bytes(32),
                                 expected_bank_hash=leader_hash)
    assert res.ok and res.bank_hash == leader_hash


def test_replay_rejects_tampered_block(setup):
    g, faucet = setup
    entries, leader_hash, _ = _make_block(g, faucet)
    # tamper: drop a txn but keep the (now wrong) poh chain entry hashes
    bad = [entry_lib.Entry(e.num_hashes, e.hash, list(e.txns))
           for e in entries]
    bad[3] = entry_lib.Entry(bad[3].num_hashes, bad[3].hash, [])
    follower = Runtime(g)
    res = replay_mod.replay_slot(follower, 1, bad, bytes(32),
                                 expected_bank_hash=leader_hash)
    assert not res.ok and "poh" in res.err

    # tamper consistently: recompute poh for the altered block -> poh ok
    # but the bank hash must now differ from the leader's
    poh = bytes(32)
    rebuilt = []
    for e in entries[:4]:
        mix = None if e.is_tick else entry_lib.txn_mixin(e.txns)
        poh = entry_lib.next_hash(poh, e.num_hashes, mix)
        rebuilt.append(entry_lib.Entry(e.num_hashes, poh, list(e.txns)))
    poh = entry_lib.next_hash(poh, 4, None)
    rebuilt.append(entry_lib.Entry(4, poh, []))
    follower2 = Runtime(g)
    res = replay_mod.replay_slot(follower2, 1, rebuilt, bytes(32),
                                 expected_bank_hash=leader_hash)
    assert not res.ok and "bank hash" in res.err
    # the rejected block must leave no trace in shared recency state:
    # its bank hash must NOT be usable as a recent blockhash afterwards
    assert not follower2.blockhash_queue.is_recent(res.bank_hash)


def test_multi_fec_slot_entries_parse_all_batches(setup):
    """A slot cut into multiple FEC sets carries one counted entry batch
    per set; slot_entries must parse them ALL (dropping trailing batches
    silently truncates the block and breaks the follower's poh chain)."""
    g, faucet = setup
    entries, leader_hash, _ = _make_block(g, faucet)
    id_seed, _ = _keypair(9)
    mid = len(entries) // 2
    bs = Blockstore()
    b0 = entry_lib.serialize_batch(entries[:mid])
    fs0 = shred_lib.make_fec_set(
        b0, slot=1, parent_off=1, version=1, fec_set_idx=0,
        sign_fn=lambda root: ed.sign(id_seed, root),
        data_cnt=8, code_cnt=8, slot_complete=False)
    b1 = entry_lib.serialize_batch(entries[mid:])
    fs1 = shred_lib.make_fec_set(
        b1, slot=1, parent_off=1, version=1, fec_set_idx=8,
        sign_fn=lambda root: ed.sign(id_seed, root),
        data_cnt=8, code_cnt=8, slot_complete=True)
    for raw in (fs0.data_shreds + [fs0.code_shreds[0]]
                + fs1.data_shreds + [fs1.code_shreds[0]]):
        bs.insert_shred(raw)
    got = bs.slot_entries(1)
    assert got is not None
    assert len(got) == len(entries)
    assert [e.hash for e in got] == [e.hash for e in entries]

    follower = Runtime(g)
    res = replay_mod.replay_slot(follower, 1, got, bytes(32),
                                 expected_bank_hash=leader_hash)
    assert res.ok and res.bank_hash == leader_hash


def test_blockstore_retention_never_evicts_insert_target():
    """At capacity, a shred for a slot OLDER than the retention window is
    dropped — it must not evict a newer slot, and insert_shred must never
    keep writing into a meta it just evicted."""
    bs = Blockstore(max_slots=1)
    id_seed, _ = _keypair(9)
    batch = entry_lib.serialize_batch(
        [entry_lib.Entry(1, b"\x22" * 32, [])])
    new = shred_lib.make_fec_set(
        batch, slot=10, parent_off=1, version=1, fec_set_idx=0,
        sign_fn=lambda root: ed.sign(id_seed, root),
        data_cnt=4, code_cnt=4, slot_complete=True)
    for raw in new.data_shreds:
        bs.insert_shred(raw)
    assert 10 in bs.slots
    old = shred_lib.make_fec_set(
        batch, slot=9, parent_off=1, version=1, fec_set_idx=0,
        sign_fn=lambda root: ed.sign(id_seed, root),
        data_cnt=4, code_cnt=4, slot_complete=True)
    assert bs.insert_shred(old.data_shreds[0]) is False
    assert 10 in bs.slots and 9 not in bs.slots  # newer slot survives
    # a NEWER slot still evicts the older one
    newer = shred_lib.make_fec_set(
        batch, slot=11, parent_off=1, version=1, fec_set_idx=0,
        sign_fn=lambda root: ed.sign(id_seed, root),
        data_cnt=4, code_cnt=4, slot_complete=True)
    bs.insert_shred(newer.data_shreds[0])
    assert 11 in bs.slots and 10 not in bs.slots


def test_slot_archive_survives_eviction_and_reopen(setup, tmp_path):
    """The disk archive (fd_blockstore RocksDB role): completed slots are
    persisted at completion, served after eviction, and the index rebuilds
    from the file on reopen — including tolerance of a torn final record."""
    from firedancer_tpu.flamenco.blockstore import SlotArchive

    g, faucet = setup
    entries, _, _ = _make_block(g, faucet, n_txn=2)
    batch = entry_lib.serialize_batch(entries)
    id_seed, _ = _keypair(9)

    path = str(tmp_path / "slots.fdar")
    bs = Blockstore(max_slots=2, archive=SlotArchive(path))
    for slot in (1, 2, 3, 4):  # retention window is 2: slots 1-2 evict
        fs = shred_lib.make_fec_set(
            batch, slot=slot, parent_off=1, version=1, fec_set_idx=0,
            sign_fn=lambda root: ed.sign(id_seed, root),
            data_cnt=8, code_cnt=8, slot_complete=True)
        for raw in fs.data_shreds + fs.code_shreds[:1]:
            bs.insert_shred(raw)  # geometry arrives with a code shred
    assert 1 not in bs.slots  # evicted from memory
    assert bs.slot_data(1) == batch  # served from the archive
    assert bs.archive.parent(3) == 2

    bs.archive.close()
    arch = SlotArchive(path)  # reopen: index rebuilt by scan
    assert arch.slots() == [1, 2, 3, 4]
    assert arch.get(2) == batch

    # torn final record (crashed writer): scan stops cleanly, data intact
    arch.close()
    with open(path, "ab") as f:
        f.write(b"\x01\x02\x03")
    arch2 = SlotArchive(path)
    assert arch2.slots() == [1, 2, 3, 4]
    assert arch2.get(4) == batch
    arch2.close()


def test_blockhash_recency_is_per_fork(setup):
    """ADVICE r3 (medium): a bank hash registered on one fork must not
    satisfy recency on a competing fork — recency follows each bank's
    ancestor chain (per-bank blockhash_queue, as Agave keeps it)."""
    g, (faucet_seed, faucet_pk) = setup
    rt = Runtime(g)

    fork_a = rt.new_bank(1)
    hash_a = fork_a.freeze(b"\x01" * 32)          # registers on fork A only

    def transfer(recent):
        dest = b"\xd8" + bytes(31)
        msg = txn_lib.build_unsigned(
            [faucet_pk], recent,
            [(2, bytes([0, 1]), sysprog.ix_transfer(1234))],
            extra_accounts=[dest, SYSTEM_PROGRAM_ID],
            readonly_unsigned_cnt=1)
        return txn_lib.assemble([ed.sign(faucet_seed, msg)], msg)

    # competing fork off the same root: fork A's hash is NOT recent there
    fork_b = rt.new_bank(2)
    res = fork_b.execute_txn(transfer(hash_a))
    assert not res.ok and "blockhash" in res.err

    # a descendant of fork A inherits its queue: the same txn executes
    child_a = rt.new_bank(3, parent_slot=1)
    res = child_a.execute_txn(transfer(hash_a))
    assert res.ok, res.err

    # rooting fork A folds its recency window into the runtime queue:
    # banks opened off the new root now accept the hash
    rt.publish(1)
    after_root = rt.new_bank(4)
    res = after_root.execute_txn(transfer(hash_a))
    assert res.ok, res.err


def test_blockstore_root_check_gates_at_the_door(setup):
    """With a root_check configured, a shred failing the leader-signature
    gate must leave NO trace: no slot metadata, no stored raw bytes, no
    last_set_idx pin, no eviction pressure (code-review r5: the gate must
    run before any bookkeeping commits)."""
    g, faucet = setup
    entries, _, _ = _make_block(g, faucet)
    batch = entry_lib.serialize_batch(entries)
    good_seed, good_pub = _keypair(9)
    evil_seed, _ = _keypair(66)

    def root_check(slot, root, sig):
        return ed.verify_one_host(sig, root, good_pub)

    bs = Blockstore(root_check=root_check)

    # self-consistent set signed by the WRONG key, flagged slot-complete
    evil = shred_lib.make_fec_set(
        batch, slot=7, parent_off=1, version=1, fec_set_idx=0,
        sign_fn=lambda root: ed.sign(evil_seed, root),
        data_cnt=4, code_cnt=4, slot_complete=True)
    for raw in evil.data_shreds:
        assert bs.insert_shred(raw) is False
    assert 7 not in bs.slots          # no _SlotMeta created
    assert bs.sig_reject_cnt == len(evil.data_shreds)

    # honest set for the same slot completes normally afterwards
    good = shred_lib.make_fec_set(
        batch, slot=7, parent_off=1, version=1, fec_set_idx=0,
        sign_fn=lambda root: ed.sign(good_seed, root),
        data_cnt=4, code_cnt=4, slot_complete=True)
    done = False
    for raw in good.data_shreds:
        done = bs.insert_shred(raw) or done
    assert done and bs.slot_complete(7)
