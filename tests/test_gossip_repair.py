"""Gossip CRDS protocol + shred repair protocol (ref behaviors:
src/flamenco/gossip/fd_gossip.c, src/flamenco/repair/fd_repair.c).

Library-level: two GossipNodes exchange push/pull traffic through an
in-memory "network" until their CRDS tables converge; a RepairClient
recovers a dropped shred from a RepairServer over the blockstore."""

import random

from firedancer_tpu.ballet import entry as entry_lib
from firedancer_tpu.ballet import shred as shred_lib
from firedancer_tpu.flamenco import gossip, repair
from firedancer_tpu.flamenco.blockstore import Blockstore
from firedancer_tpu.ops import ed25519 as ed


def _identity(i):
    seed = i.to_bytes(32, "little")
    pub = ed.keypair_from_seed(seed)[0]
    return seed, pub


def _host_verify(sig, msg, pub):
    """Host verifier for protocol sig checks (tests drive the same
    canonical path the stack uses: ops.ed25519.verify_one_host)."""
    return ed.verify_one_host(sig, msg, pub)


def _mk_node(i, port):
    seed, pub = _identity(i)
    contact = gossip.contact_info_body("127.0.0.1", port, port + 1, port + 2)
    return gossip.GossipNode(
        pub, lambda m, s=seed: ed.sign(s, m), _host_verify, contact,
        rng=random.Random(i))


def test_crds_value_roundtrip_and_verify():
    seed, pub = _identity(1)
    v = gossip.make_value(lambda m: ed.sign(seed, m), pub,
                          gossip.KIND_LOWEST_SLOT, b"\x01" * 8)
    raw = v.serialize()
    v2, off = gossip.CrdsValue.deserialize(raw)
    assert v2 == v and off == len(raw)
    crds = gossip.Crds(_host_verify)
    assert crds.upsert(v)
    assert not crds.upsert(v)  # not newer
    forged = gossip.CrdsValue(bytes(64), pub, v.kind,
                              v.wallclock_ms + 1, v.body)
    assert not crds.upsert(forged)  # bad signature


def test_gossip_convergence():
    """Node B knows only an entrypoint push from A; after a few exchanged
    rounds both tables match (contact info + a vote value)."""
    a, b = _mk_node(1, 8000), _mk_node(2, 9000)
    a.publish(gossip.KIND_VOTE, b"vote-from-a")
    b.publish(gossip.KIND_VOTE, b"vote-from-b")
    # bootstrap: b receives a push of a's table (the entrypoint path)
    for v in a.crds.values():
        b.crds.upsert(v)

    inboxes = {8000: a, 9000: b}
    for _ in range(4):
        for node in (a, b):
            for payload, (ip, port) in node.tick():
                target = inboxes[port]
                for rp, raddr in target.handle(payload, ("127.0.0.1", 0)):
                    node.handle(rp, raddr)
    assert {v.digest() for v in a.crds.values()} == \
           {v.digest() for v in b.crds.values()}
    assert len(a.crds.peers()) == 2
    # both votes visible on both nodes
    kinds = [(k, v.body) for (k, _), v in a.crds.table.items()
             if k == gossip.KIND_VOTE]
    assert sorted(b_ for _, b_ in kinds) == [b"vote-from-a", b"vote-from-b"]


def test_repair_roundtrip():
    """Server answers a signed window-index request with the exact shred;
    client matches it by nonce and the blockstore completes the slot."""
    id_seed, id_pub = _identity(3)
    entries = [entry_lib.Entry(1, bytes([i]) * 32, []) for i in range(3)]
    batch = entry_lib.serialize_batch(entries)
    fs = shred_lib.make_fec_set(
        batch, slot=7, parent_off=1, version=1, fec_set_idx=0,
        sign_fn=lambda root: ed.sign(id_seed, root),
        data_cnt=32, code_cnt=32, slot_complete=True)

    server_bs = Blockstore()
    for raw in fs.data_shreds + fs.code_shreds:
        server_bs.insert_shred(raw)
    server = repair.RepairServer(_host_verify, server_bs.shred_raw,
                                 server_bs.highest_shred)

    client_bs = Blockstore()
    for raw in fs.data_shreds[:31] + fs.code_shreds[:0]:
        client_bs.insert_shred(raw)
    assert not client_bs.slot_complete(7)
    missing = client_bs.missing_indices(7, 31)
    assert missing == [31]

    cl = repair.RepairClient(lambda m: ed.sign(id_seed, m), id_pub)
    req = cl.request_shred(7, 31)
    resp = server.handle(req.serialize())
    assert resp is not None
    raw = cl.handle_response(resp)
    assert raw == fs.data_shreds[31]

    # forged request is refused
    bad = repair.RepairRequest(bytes(64), id_pub, repair.REQ_WINDOW_INDEX,
                               9, 7, 0)
    assert server.handle(bad.serialize()) is None

    # highest-window + orphan paths
    req = cl.request_highest(7)
    shred_raw, nonce = repair.decode_response(server.handle(req.serialize()))
    assert shred_lib.parse(shred_raw).idx == 31


def test_gossip_ping_gates_push_and_prune_flow_control():
    """fd_gossip liveness + flood control: (a) pushes only flow to peers
    that answered a signed ping token; (b) repeated duplicate pushes of an
    origin draw a signed PRUNE, after which the pusher skips that origin."""
    a, b = _mk_node(1, 8000), _mk_node(2, 9000)
    # b knows a's contact but has NOT validated it: no pushes yet
    for v in a.crds.values():
        b.crds.upsert(v)
    out = b.tick()
    kinds = [gossip.decode(p)[0] for p, _ in out]
    assert gossip.MSG_PING in kinds
    assert gossip.MSG_PUSH not in kinds

    # complete the handshake: ping -> pong -> validated
    ping = next(p for p, _ in out
                if gossip.decode(p)[0] == gossip.MSG_PING)
    (pong, _), = a.handle(ping, ("127.0.0.1", 9000))
    assert b.handle(pong, ("127.0.0.1", 8000)) == []
    assert list(b._validated)  # a is validated now

    b.publish(gossip.KIND_VOTE, b"fresh-vote")
    out = b.tick()
    assert any(gossip.decode(p)[0] == gossip.MSG_PUSH for p, _ in out)

    # duplicate floods -> prune: feed a the same push repeatedly
    push = next(p for p, _ in out
                if gossip.decode(p)[0] == gossip.MSG_PUSH)
    src = ("127.0.0.1", 9000)
    a.handle(push, src)  # fresh the first time
    replies = []
    for _ in range(gossip.GossipNode.PRUNE_DUP_THRESHOLD):
        replies += a.handle(push, src)
    assert replies, "expected a PRUNE after repeated duplicates"
    prune_pkt = replies[-1][0]
    mtype, (frm, origins, sig) = gossip.decode(prune_pkt)
    assert mtype == gossip.MSG_PRUNE and b.identity in origins

    # the pusher honors the prune: that origin stops flowing to a
    b.handle(prune_pkt, src)
    assert b.identity in b._pruned_by[a.identity]
    b.publish(gossip.KIND_VOTE, b"post-prune-vote")
    out = b.tick()
    for p, _ in out:
        mt, data = gossip.decode(p)
        if mt == gossip.MSG_PUSH:
            assert all(v.origin != b.identity for v in data)


def test_gossip_purge_expires_stale_values():
    a, _ = _mk_node(1, 8000), None
    now = int(__import__("time").time() * 1000)
    a.crds.purge(now)
    assert len(a.crds.values()) >= 1  # own contact survives
    a.crds.purge(now + a.crds.max_age_ms + 10_000)
    assert a.crds.values() == []  # everything stale is swept


def test_bloom_pull_and_duplicate_shred():
    """CrdsBloom pull exchange: responder returns exactly the values the
    requester's filter misses; duplicate-shred evidence round-trips."""
    from firedancer_tpu.flamenco.gossip import (
        KIND_DUPLICATE_SHRED, CrdsBloom, duplicate_shred_body,
        duplicate_shred_parse)

    a, b = _mk_node(1, 8000), _mk_node(2, 9000)

    # seed b with values a doesn't have
    for i in range(80):
        b.publish(KIND_DUPLICATE_SHRED,
                  duplicate_shred_body(100 + i, i, b"x" * 10, b"y" * 10))

    # bloom of a's digests misses all of b's new values
    f = CrdsBloom.sized_for(128)
    for d in a.crds.digests():
        f.add(d)
    from firedancer_tpu.flamenco.gossip import encode_pull_req_bloom, decode
    replies = b.handle(encode_pull_req_bloom(f), ("1.2.3.4", 9))
    assert replies
    mtype, vals = decode(replies[0][0])
    got = {v.digest() for v in vals}
    assert got and all(d not in f for d in got)
    # no value a already has is re-sent
    assert not (got & a.crds.digests())

    # false-negative impossibility: everything in the filter is excluded
    f2 = CrdsBloom.sized_for(128)
    for v in b.crds.values():
        f2.add(v.digest())
    assert b.handle(encode_pull_req_bloom(f2), ("1.2.3.4", 9)) == []

    slot, idx, sa, sb = duplicate_shred_parse(
        duplicate_shred_body(7, 3, b"abc", b"defg"))
    assert (slot, idx, sa, sb) == (7, 3, b"abc", b"defg")


def test_repair_planner_closes_gaps_with_retries():
    """RepairPlanner drives a gappy blockstore to completion against a
    full server: interior gaps -> window-index, unknown tail -> highest,
    retry/backoff on dropped responses, stake-weighted peer pick."""
    id_seed, id_pub = _identity(4)
    entries = [entry_lib.Entry(1, bytes([i]) * 32, []) for i in range(3)]
    batch = entry_lib.serialize_batch(entries)
    fs = shred_lib.make_fec_set(
        batch, slot=9, parent_off=1, version=1, fec_set_idx=0,
        sign_fn=lambda root: ed.sign(id_seed, root),
        data_cnt=32, code_cnt=32, slot_complete=True)

    server_bs = Blockstore()
    for raw in fs.data_shreds + fs.code_shreds:
        server_bs.insert_shred(raw)
    server = repair.RepairServer(_host_verify, server_bs.shred_raw,
                                 server_bs.highest_shred)

    client_bs = Blockstore()
    # interior gaps at 5, 17; tail unknown past 20
    for i, raw in enumerate(fs.data_shreds[:21]):
        if i not in (5, 17):
            client_bs.insert_shred(raw)

    cl = repair.RepairClient(lambda m: ed.sign(id_seed, m), id_pub)
    clock = [0]
    planner = repair.RepairPlanner(cl, now_ms=lambda: clock[0])
    peers = [(b"peer1", ("10.0.0.1", 8008), 100),
             (b"peer2", ("10.0.0.2", 8008), 1)]

    drop_first = True
    for round_i in range(40):
        if client_bs.slot_complete(9):
            break
        reqs = planner.plan(client_bs, [9], peers)
        clock[0] += repair.RepairPlanner.RETRY_MS + 1
        for req, peer in reqs:
            if drop_first:          # first round all responses are lost
                continue
            resp = server.handle(req.serialize())
            if resp is None:
                continue
            raw = cl.handle_response(resp)
            if raw is not None:
                sh = shred_lib.parse(raw)
                client_bs.insert_shred(raw)
                planner.on_shred(sh.slot, sh.idx)
        drop_first = False
    assert client_bs.slot_complete(9)
    # retried keys recorded more than one try (responses were dropped)
    assert not planner.given_up
