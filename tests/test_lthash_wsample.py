"""lthash homomorphism + blake3 XOF + wsample distribution tests."""

import numpy as np
import pytest

import jax.numpy as jnp

from firedancer_tpu.ballet import lthash, wsample
from firedancer_tpu.ballet.chacha20 import ChaCha20Rng
from firedancer_tpu.ops.blake3 import blake3


def test_blake3_xof_prefix_property():
    # XOF: longer outputs extend shorter ones bit-for-bit
    for data in (b"", b"abc", bytes(range(200))):
        h32 = blake3(data, 32)
        h64 = blake3(data, 64)
        h2048 = blake3(data, 2048)
        assert h64[:32] == h32
        assert h2048[:64] == h64
        assert len(h2048) == 2048


def test_lthash_homomorphic():
    a = lthash.hash_account(b"account-a-v1")
    b = lthash.hash_account(b"account-b-v1")
    a2 = lthash.hash_account(b"account-a-v2")

    # order independence: (0 + a + b - a + a2) == (0 + b + a2)
    s1 = lthash.zero()
    for op, v in [(lthash.add, a), (lthash.add, b), (lthash.sub, a), (lthash.add, a2)]:
        s1 = op(s1, v)
    s2 = lthash.add(lthash.add(lthash.zero(), b), a2)
    assert np.array_equal(s1, s2)
    assert lthash.fini(s1) == lthash.fini(s2)
    assert len(lthash.fini(s1)) == 32


def test_lthash_mix_batch_matches_host():
    rng = np.random.default_rng(3)
    adds = rng.integers(0, 1 << 16, size=(17, lthash.LANES), dtype=np.uint16)
    subs = rng.integers(0, 1 << 16, size=(9, lthash.LANES), dtype=np.uint16)
    state = rng.integers(0, 1 << 16, size=(lthash.LANES,), dtype=np.uint16)

    host = state.copy()
    for v in adds:
        host = lthash.add(host, v)
    for v in subs:
        host = lthash.sub(host, v)

    dev = np.asarray(
        lthash.mix_batch(jnp.asarray(state), jnp.asarray(adds), jnp.asarray(subs))
    )
    assert np.array_equal(host, dev)


def test_wsample_distribution():
    ws = wsample.WSample([1, 0, 3, 6])
    rng = ChaCha20Rng(bytes(range(32)))
    counts = [0, 0, 0, 0]
    n = 20_000
    for _ in range(n):
        counts[ws.sample(rng)] += 1
    assert counts[1] == 0
    # expected proportions 0.1, 0, 0.3, 0.6 within 3 sigma
    for i, p in [(0, 0.1), (2, 0.3), (3, 0.6)]:
        sigma = (n * p * (1 - p)) ** 0.5
        assert abs(counts[i] - n * p) < 4 * sigma, (i, counts)


def test_wsample_without_replacement():
    ws = wsample.WSample([5, 1, 9, 2, 7])
    rng = ChaCha20Rng(b"\x07" * 32)
    drawn = [ws.sample_and_remove(rng) for _ in range(5)]
    assert sorted(drawn) == [0, 1, 2, 3, 4]  # a permutation: each exactly once
    with pytest.raises(ValueError):
        # all weight consumed
        ws.sample(rng) if ws.total == 0 else (_ for _ in ()).throw(ValueError)


def test_wsample_determinism():
    r1, r2 = ChaCha20Rng(b"\x01" * 32), ChaCha20Rng(b"\x01" * 32)
    w1, w2 = wsample.WSample([3, 1, 4, 1, 5]), wsample.WSample([3, 1, 4, 1, 5])
    assert [w1.sample(r1) for _ in range(100)] == [w2.sample(r2) for _ in range(100)]


def test_wsample_rejects_bad_weights():
    with pytest.raises(ValueError):
        wsample.WSample([0, 0])
    with pytest.raises(ValueError):
        wsample.WSample([-1, 2])
