"""alt_bn128 (bn254) G1/G2/pairing tests.

Parity surface: src/ballet/bn254/fd_bn254.h (g1/g2 check, compress,
decompress, g1 add/mult, pairing) and the alt_bn128 syscall ABI the
reference backs with it (test vectors are EIP-196/197 arithmetic
identities recomputed from the curve equations — independent of any
implementation's serialization quirks).
"""

import pytest

from firedancer_tpu.ballet import bn254 as bn

G1 = bn.G1_GEN
G2 = bn.G2_GEN


def enc_pair(g1, g2):
    return bn.encode_g1(g1) + bn.encode_g2(g2)


def test_g1_add_known():
    # 2G computed two ways: add and double formula agree, on curve
    two_g = bn._add(G1, G1)
    x, y = two_g
    assert (y * y - x * x * x - 3) % bn.P == 0
    assert bn._add(two_g, G1) == bn._mul(3, G1)


def test_g1_syscall_encodings():
    two_g = bn.g1_add(bn.encode_g1(G1), bn.encode_g1(G1))
    assert bn.decode_g1(two_g) == bn._mul(2, G1)
    five_g = bn.g1_scalar_mul(bn.encode_g1(G1), (5).to_bytes(32, "big"))
    assert bn.decode_g1(five_g) == bn._mul(5, G1)
    # identity encodings
    assert bn.g1_add(bytes(64), bn.encode_g1(G1)) == bn.encode_g1(G1)
    assert bn.g1_scalar_mul(bn.encode_g1(G1), bn.N.to_bytes(32, "big")) \
        == bytes(64)


def test_g1_rejects_off_curve():
    bad = bytearray(bn.encode_g1(G1))
    bad[63] ^= 1
    with pytest.raises(bn.Bn254Error):
        bn.decode_g1(bytes(bad))
    with pytest.raises(bn.Bn254Error):
        bn.decode_g1(bn.P.to_bytes(32, "big") + bytes(32))


def test_g2_decode_roundtrip_and_membership():
    b = bn.encode_g2(G2)
    assert bn.decode_g2(b) == G2
    assert bn.g2_subgroup_check(G2)
    q5 = bn.g2_scalar_mul(5, G2)
    assert bn.g2_subgroup_check(q5)
    bad = bytearray(b)
    bad[127] ^= 1
    with pytest.raises(bn.Bn254Error):
        bn.decode_g2(bytes(bad))


def test_pairing_bilinearity():
    a, b = 6, 13
    e1 = bn.pairing(bn._mul(a, G1), bn.g2_scalar_mul(b, G2))
    e2 = bn.pairing(bn._mul(b, G1), bn.g2_scalar_mul(a, G2))
    e3 = bn._f12_pow(bn.pairing(G1, G2), a * b)
    assert e1 == e2 == e3
    assert bn.pairing(G1, G2) != bn._F12_ONE


def test_pairing_check_accepts_and_rejects():
    neg_g1 = (G1[0], (-G1[1]) % bn.P)
    assert bn.pairing_check(enc_pair(G1, G2) + enc_pair(neg_g1, G2))
    # e(aP, bQ) * e(-abP, Q) == 1
    a, b = 3, 9
    ab_neg = bn._mul(a * b, G1)
    ab_neg = (ab_neg[0], (-ab_neg[1]) % bn.P)
    assert bn.pairing_check(
        enc_pair(bn._mul(a, G1), bn.g2_scalar_mul(b, G2))
        + enc_pair(ab_neg, G2))
    assert not bn.pairing_check(enc_pair(G1, G2))
    # identity pairs are skipped (empty product == 1)
    assert bn.pairing_check(bytes(192))
    assert bn.pairing_check(b"")
    with pytest.raises(bn.Bn254Error):
        bn.pairing_check(bytes(191))


def test_compression_roundtrips():
    for k in (1, 2, 7, 123456789):
        g1b = bn.encode_g1(bn._mul(k, G1))
        assert bn.g1_decompress(bn.g1_compress(g1b)) == g1b
        g2b = bn.encode_g2(bn.g2_scalar_mul(k, G2))
        assert bn.g2_decompress(bn.g2_compress(g2b)) == g2b
    assert bn.g1_compress(bytes(64)) == bytes(32)
    assert bn.g1_decompress(bytes(32)) == bytes(64)
    assert bn.g2_compress(bytes(128)) == bytes(64)
    assert bn.g2_decompress(bytes(64)) == bytes(128)


def test_frobenius_consistency():
    """w^(p^6) must be -w (the easy-part conjugation identity) and the
    p-power Frobenius must fix Fp while having order 12."""
    w6 = bn._WFROB[6]
    neg_w = bn._f12()
    neg_w[1] = bn.P - 1
    assert w6 == neg_w
    w12 = bn._f12_frob(bn._WFROB[0], 11)
    assert bn._f12_frob(w12, 1) == bn._WFROB[0]


def test_decompress_rejects_residual_flag_bits():
    """Only bit 7 is the parity flag; bit 6 set pushes x >= 2^254 > p and
    must reject (it previously aliased to a valid point)."""
    c = bytearray(bn.g1_compress(bn.encode_g1(G1)))
    c[0] |= 0x40
    with pytest.raises(bn.Bn254Error):
        bn.g1_decompress(bytes(c))
    c2 = bytearray(bn.g2_compress(bn.encode_g2(G2)))
    c2[0] |= 0x40
    with pytest.raises(bn.Bn254Error):
        bn.g2_decompress(bytes(c2))


class _StubVm:
    """Minimal mem/meter interface for exercising the syscall entry
    points."""

    def __init__(self):
        self.mem = {}
        self.cu = 1 << 30

    def _consume(self, n):
        self.cu -= n

    def mem_read_bytes(self, va, n):
        return bytes(self.mem.get(va, b"")[:n]).ljust(n, b"\0")

    def mem_write_bytes(self, va, data):
        self.mem[va] = bytes(data)


def test_alt_bn128_syscalls():
    from firedancer_tpu.flamenco import vm as vmmod

    vm = _StubVm()
    vm.mem[0x100] = bn.encode_g1(G1) + bn.encode_g1(G1)
    assert vmmod._sc_alt_bn128_group_op(vm, 0, 0x100, 128, 0x200) == 0
    assert bn.decode_g1(vm.mem[0x200]) == bn._mul(2, G1)

    # SUB: (2G) - G == G
    vm.mem[0x100] = vm.mem[0x200] + bn.encode_g1(G1)
    assert vmmod._sc_alt_bn128_group_op(vm, 1, 0x100, 128, 0x210) == 0
    assert bn.decode_g1(vm.mem[0x210]) == G1

    # MUL
    vm.mem[0x100] = bn.encode_g1(G1) + (7).to_bytes(32, "big")
    assert vmmod._sc_alt_bn128_group_op(vm, 2, 0x100, 96, 0x220) == 0
    assert bn.decode_g1(vm.mem[0x220]) == bn._mul(7, G1)

    # PAIRING: e(G1,G2) e(-G1,G2) == 1 -> 32-byte BE 1
    neg_g1 = (G1[0], (-G1[1]) % bn.P)
    vm.mem[0x100] = enc_pair(G1, G2) + enc_pair(neg_g1, G2)
    assert vmmod._sc_alt_bn128_group_op(vm, 3, 0x100, 384, 0x230) == 0
    assert vm.mem[0x230] == (1).to_bytes(32, "big")

    # off-curve input -> error return 1, result untouched
    vm.mem[0x100] = b"\x01" * 128
    assert vmmod._sc_alt_bn128_group_op(vm, 0, 0x100, 128, 0x240) == 1
    assert 0x240 not in vm.mem

    # compression roundtrip through the syscall
    vm.mem[0x100] = bn.encode_g1(G1)
    assert vmmod._sc_alt_bn128_compression(vm, 0, 0x100, 64, 0x300) == 0
    vm.mem[0x310] = vm.mem[0x300]
    assert vmmod._sc_alt_bn128_compression(vm, 1, 0x310, 32, 0x320) == 0
    assert vm.mem[0x320] == bn.encode_g1(G1)

    # over-length group-op input errors (upstream InvalidInputData parity)
    vm.mem[0x100] = bn.encode_g1(G1) * 3
    assert vmmod._sc_alt_bn128_group_op(vm, 0, 0x100, 192, 0x400) == 0x1
    assert 0x400 not in vm.mem
    assert vmmod._sc_alt_bn128_group_op(vm, 2, 0x100, 128, 0x400) == 0x1

    # compression requires the exact input length
    assert vmmod._sc_alt_bn128_compression(vm, 0, 0x100, 63, 0x400) == 0x1
    assert vmmod._sc_alt_bn128_compression(vm, 1, 0x310, 0, 0x400) == 0x1

    # op-dependent metering: pairing charges base + per-pair on top of the
    # flat table cost
    neg_g1 = (G1[0], (-G1[1]) % bn.P)
    vm.mem[0x100] = enc_pair(G1, G2) + enc_pair(neg_g1, G2)
    cu0 = vm.cu
    assert vmmod._sc_alt_bn128_group_op(vm, 3, 0x100, 384, 0x500) == 0
    assert cu0 - vm.cu == (vmmod._BN_PAIRING_BASE_COST - 334
                           + 2 * vmmod._BN_PAIRING_PAIR_COST)
