"""App layer: TOML config layering, env overlay, topology materialization,
CLI actions (ref behaviors: src/app/fdctl config.c + main1.c action table)."""

import json

import pytest

from firedancer_tpu.app import config as config_mod
from firedancer_tpu.app import fdtpuctl


def test_default_config_builds_ingest_topology():
    cfg = config_mod.load()
    spec = config_mod.build_topology(cfg)
    kinds = {t.kind for t in spec.tiles}
    assert {"net", "quic", "verify", "dedup", "pack", "sink"} <= kinds
    assert "bank" not in kinds  # no genesis configured -> ingest-only


def test_config_overlay_and_env(tmp_path):
    p = tmp_path / "user.toml"
    p.write_text("""
[layout]
verify_tile_count = 3
[tiles.verify]
batch = 128
""")
    cfg = config_mod.load(str(p), environ={
        "FDTPU_LAYOUT_VERIFY_TILE_COUNT": "4",
        "FDTPU_TILES_VERIFY_MSG_MAXLEN": "512",
    })
    assert cfg["layout"]["verify_tile_count"] == 4      # env wins
    assert cfg["tiles"]["verify"]["batch"] == 128       # file wins
    assert cfg["tiles"]["verify"]["msg_maxlen"] == 512  # env nested
    spec = config_mod.build_topology(cfg)
    verifies = [t for t in spec.tiles if t.kind == "verify"]
    assert len(verifies) == 4
    assert verifies[1].cfg["round_robin_idx"] == 1
    assert verifies[1].cfg["batch"] == 128


def test_full_topology_with_consensus(tmp_path):
    cfg = config_mod.load()
    cfg["consensus"]["genesis_path"] = str(tmp_path / "g.bin")
    cfg["consensus"]["identity_path"] = str(tmp_path / "id.json")
    spec = config_mod.build_topology(cfg)
    kinds = {t.kind for t in spec.tiles}
    assert {"net", "quic", "verify", "dedup", "pack", "bank", "poh",
            "shred", "sign", "store"} <= kinds


def test_keys_roundtrip_and_topo_print(tmp_path, capsys):
    kpath = str(tmp_path / "id.json")
    assert fdtpuctl.main(["keys", "new", kpath]) == 0
    pub_hex = capsys.readouterr().out.strip()
    assert len(bytes.fromhex(pub_hex)) == 32
    assert fdtpuctl.main(["keys", "pubkey", kpath]) == 0
    assert capsys.readouterr().out.strip() == pub_hex

    assert fdtpuctl.main(["topo"]) == 0
    out = capsys.readouterr().out
    assert "quic_verify" in out and "kind=verify" in out

    assert fdtpuctl.main(["version"]) == 0


def test_verify_bench_topology():
    cfg = config_mod.load()
    cfg["topology"] = "verify-bench"
    cfg["development"]["source_count"] = 100
    spec = config_mod.build_topology(cfg)
    kinds = [t.kind for t in spec.tiles]
    assert kinds.count("source") == 1 and "sink" in kinds


def test_mem_report(capsys):
    from firedancer_tpu.app import fdtpuctl

    assert fdtpuctl.main(["mem"]) == 0
    out = capsys.readouterr().out
    assert "TOTAL" in out and "mcache" in out


def test_tile_profiling_hook(tmp_path, monkeypatch):
    """FDTPU_PROFILE_DIR makes every tile dump a cProfile .pstats at exit
    (the fddev-flame hook, src/app/fddev/flame.c role)."""
    import os
    import pstats

    from firedancer_tpu.disco.run import TopoRun
    from firedancer_tpu.disco.topo import TopoBuilder

    prof_dir = str(tmp_path / "prof")
    monkeypatch.setenv("FDTPU_PROFILE_DIR", prof_dir)
    spec = (
        TopoBuilder(f"flame{os.getpid()}", wksp_mb=8)
        .link("src_sink", depth=64, mtu=1280)
        .tile("source", "source", outs=["src_sink"], count=8, keys=1)
        .tile("sink", "sink", ins=["src_sink"])
        .build()
    )
    import time
    with TopoRun(spec) as run:
        run.wait_ready(timeout=300)
        deadline = time.monotonic() + 60
        while (time.monotonic() < deadline
               and run.metrics("sink")["frag_cnt"] < 8):
            time.sleep(0.05)
        assert run.metrics("sink")["frag_cnt"] == 8
    # teardown flushed the profiles
    files = sorted(os.listdir(prof_dir))
    assert files == ["sink.pstats", "source.pstats"]
    st = pstats.Stats(os.path.join(prof_dir, "source.pstats"))
    assert st.total_calls > 0


def test_fdtpudbg_ps_and_stack(tmp_path):
    """fddbg analogue: list a running topology's tiles and trigger a
    non-disruptive faulthandler stack dump (the tile survives it)."""
    import os
    import time

    from firedancer_tpu.app.fdtpudbg import main as dbg_main
    from firedancer_tpu.disco.run import TopoRun
    from firedancer_tpu.disco.topo import TopoBuilder

    name = f"dbg{os.getpid()}"
    spec = (TopoBuilder(name, wksp_mb=4)
            .link("a_b", depth=16, mtu=256)
            .tile("src", "source", outs=["a_b"], count=0, keys=1)
            .tile("snk", "sink", ins=["a_b"])
            .build())
    with TopoRun(spec) as run:
        run.wait_ready(timeout=120)
        assert dbg_main(["ps", name]) == 0
        assert dbg_main(["stack", name]) == 0
        time.sleep(0.5)
        # non-disruptive: the tiles are still alive and flowing
        assert run.poll() is None
        assert run.metrics("snk")["frag_cnt"] >= 0
    assert dbg_main(["ps", f"definitely-missing-{name}"]) == 1


def test_netmux_blackhole_topology_registration():
    """VERDICT r4 #10: netmux fans N net tiles into one link; blackhole
    terminates the tail without reading payloads."""
    cfg = config_mod.load()
    cfg["layout"]["net_tile_count"] = 2
    cfg["development"]["sink_kind"] = "blackhole"
    spec = config_mod.build_topology(cfg).validate()
    kinds = [t.kind for t in spec.tiles]
    assert kinds.count("net") == 2
    assert "netmux" in kinds and "blackhole" in kinds
    mux = next(t for t in spec.tiles if t.kind == "netmux")
    assert len(mux.in_links) == 2 and len(mux.out_links) == 1


def test_netmux_blackhole_vtables():
    from firedancer_tpu.disco.tiles import BlackholeTile, NetmuxTile

    class Metrics:
        def __init__(self):
            self.c = {}

        def add(self, k, n=1):
            self.c[k] = self.c.get(k, 0) + n

    class Ctx:
        def __init__(self):
            self.metrics = Metrics()
            self.pub = []

        def publish(self, payload, sig=0):
            self.pub.append((bytes(payload), sig))

    ctx = Ctx()
    NetmuxTile().on_frag(ctx, 0, {"sig": 7}, b"payload")
    assert ctx.pub == [(b"payload", 7)]

    ctx2 = Ctx()
    bh = BlackholeTile()
    assert bh.before_frag(ctx2, 0, 5, 9) is True  # filter: never reads
    assert not ctx2.pub  # drop counted by the mux's in_filt_cnt slot


def test_monitor_follow_renders_dashboard(capsys):
    """--follow repaints in place: drive one frame against a freshly
    created (idle) topology."""
    import types

    from firedancer_tpu.disco import topo as topo_mod
    cfg = config_mod.load()
    cfg["name"] = "montest"
    spec = config_mod.build_topology(cfg)
    jt = topo_mod.create(spec)
    try:
        args = types.SimpleNamespace(interval=0.01, count=1, follow=True)
        rc = fdtpuctl._monitor_follow(spec, jt, args)
        assert rc == 0
        out = capsys.readouterr().out
        assert "fdtpu monitor" in out and "TILE" in out and "LINK" in out
        assert "verify:0" in out
    finally:
        jt.unlink()
