"""secp256k1 curve + precompile, bn254 G1 syscall ops, epoch rewards, and
shredcap archives (ref behaviors: src/ballet/secp256k1, src/ballet/bn254,
src/flamenco/rewards, src/flamenco/shredcap)."""

import hashlib
import os

import pytest

from firedancer_tpu.ballet import bn254
from firedancer_tpu.ballet import secp256k1 as secp
from firedancer_tpu.ballet import entry as entry_lib
from firedancer_tpu.ballet import shred as shred_lib
from firedancer_tpu.ballet.keccak256 import keccak256
from firedancer_tpu.flamenco import rewards, shredcap
from firedancer_tpu.flamenco.blockstore import Blockstore
from firedancer_tpu.ops import ed25519 as ed

# ----------------------------------------------------------------- secp256k1


def test_secp256k1_sign_verify_recover_roundtrip():
    for i in range(3):
        sec = (int.from_bytes(hashlib.sha256(b"k%d" % i).digest(), "big")
               % secp.N) or 1
        pub = secp._mul(sec, (secp._GX, secp._GY))
        h = hashlib.sha256(b"message %d" % i).digest()
        r, s, recid = secp.sign(h, sec)
        assert secp.verify(h, r, s, pub)
        assert secp.recover(h, r, s, recid) == pub
        assert not secp.verify(hashlib.sha256(b"no").digest(), r, s, pub)
        bad = secp.recover(h, r, s, recid ^ 1)
        assert bad != pub  # wrong recid recovers a different key


def test_secp256k1_known_eth_address():
    # the classic: private key 1 -> eth address 0x7e5f...bdf
    pub = secp._mul(1, (secp._GX, secp._GY))
    assert secp.eth_address(pub).hex() == \
        "7e5f4552091a69125d5dfcb7b8c2659029395bdf"
    round = secp.pubkey_parse(secp.pubkey_serialize(pub))
    assert round == pub
    with pytest.raises(ValueError):
        secp.pubkey_parse(b"\x01" * 64)  # not on curve


def test_secp256k1_precompile_executes():
    from firedancer_tpu.flamenco.precompiles import (
        build_secp256k1_ix_data,
        secp256k1_verify_execute,
    )

    sec = 0xC0FFEE
    pub = secp._mul(sec, (secp._GX, secp._GY))
    msg = b"transfer 100 wrapped-eth"
    r, s, recid = secp.sign(keccak256(msg), sec)
    sig = r.to_bytes(32, "big") + s.to_bytes(32, "big")
    addr = secp.eth_address(pub)

    class _Ictx:
        data = build_secp256k1_ix_data([(sig, recid, addr, msg)])

    secp256k1_verify_execute(_Ictx())  # must not raise

    from firedancer_tpu.flamenco.system_program import InstrError

    class _Bad:
        data = build_secp256k1_ix_data(
            [(sig, recid, b"\x00" * 20, msg)])  # wrong address

    with pytest.raises(InstrError):
        secp256k1_verify_execute(_Bad())


# --------------------------------------------------------------------- bn254


def test_bn254_g1_ops():
    g = (1, 2)  # the standard G1 generator
    gb = bn254.encode_g1(g)
    # G + G == [2]G
    two_g = bn254.g1_add(gb, gb)
    assert two_g == bn254.g1_scalar_mul(gb, (2).to_bytes(32, "big"))
    # [n]G == identity
    ident = bn254.g1_scalar_mul(gb, bn254.N.to_bytes(32, "big"))
    assert ident == bytes(64)
    # identity is the neutral element
    assert bn254.g1_add(gb, bytes(64)) == gb
    with pytest.raises(bn254.Bn254Error):
        bn254.decode_g1(b"\x01" * 64)  # off curve
    # vacuous product over zero pairs is one (ark/upstream semantics;
    # the "gated stub raises" expectation predates the real pairing)
    assert bn254.pairing_check(b"") is True
    with pytest.raises(bn254.Bn254Error):
        bn254.pairing_check(b"\x00" * 191)  # not a multiple of 192


# ------------------------------------------------------------------- rewards


def test_inflation_schedule_tapers_to_terminal():
    assert rewards.inflation_rate(0) == pytest.approx(0.08)
    assert rewards.inflation_rate(1) == pytest.approx(0.08 * 0.85)
    assert rewards.inflation_rate(50) == pytest.approx(0.015)  # floor


def test_epoch_rewards_pro_rata_and_commission():
    v1, v2 = b"\x01" * 32, b"\x02" * 32
    s1, s2, s3 = b"\x0a" * 32, b"\x0b" * 32, b"\x0c" * 32
    stakes = [(s1, v1, 3_000_000), (s2, v1, 1_000_000), (s3, v2, 4_000_000)]
    credits = {v1: 100, v2: 100}
    commission = {v1: 10, v2: 0}
    out = rewards.calculate_epoch_rewards(
        stakes, credits, commission,
        capitalization=500_000_000_000_000,
        epoch_start_slot=0, slots_in_epoch=432_000)
    assert len(out) == 3
    by_stake = {r.stake_pubkey: r for r in out}
    # pro-rata by stake (same credits): s1 earns 3x s2's total
    tot1 = by_stake[s1].stake_reward + by_stake[s1].vote_reward
    tot2 = by_stake[s2].stake_reward + by_stake[s2].vote_reward
    assert abs(tot1 - 3 * tot2) <= 3
    # 10% commission routed to the vote account
    assert by_stake[s1].vote_reward == pytest.approx(tot1 * 0.10, abs=2)
    assert by_stake[s3].vote_reward == 0
    # distribution conserves the computed total
    ledger: dict[bytes, int] = {}
    issued = rewards.distribute(
        out, lambda pk, lam: ledger.__setitem__(pk, ledger.get(pk, 0) + lam))
    assert issued == sum(r.stake_reward + r.vote_reward for r in out)
    assert ledger[s1] == by_stake[s1].stake_reward
    assert ledger[v1] == by_stake[s1].vote_reward + by_stake[s2].vote_reward


def test_epoch_rewards_zero_credit_votes_earn_nothing():
    out = rewards.calculate_epoch_rewards(
        [(b"\x0a" * 32, b"\x01" * 32, 1_000_000)],
        vote_credits={}, vote_commission={},
        capitalization=1_000_000_000,
        epoch_start_slot=0, slots_in_epoch=432_000)
    assert out == []


# ------------------------------------------------------------------ shredcap


def test_shredcap_roundtrip_and_replay(tmp_path):
    id_seed = (3).to_bytes(32, "little")
    batch = entry_lib.serialize_batch([entry_lib.Entry(1, b"\x33" * 32, [])])
    fs = shred_lib.make_fec_set(
        batch, slot=5, parent_off=1, version=1, fec_set_idx=0,
        sign_fn=lambda root: ed.sign(id_seed, root),
        data_cnt=4, code_cnt=4, slot_complete=True)
    path = str(tmp_path / "cap.shredcap")
    with shredcap.ShredCapWriter(path) as w:
        for raw in fs.data_shreds + fs.code_shreds:
            w.append(5, raw)
        assert w.record_cnt == 8

    recs = list(shredcap.iter_shreds(path))
    assert len(recs) == 8
    assert all(slot == 5 for slot, _ in recs)
    assert recs[0][1] == fs.data_shreds[0]

    bs = Blockstore()
    n = shredcap.replay_into(path, bs.insert_shred)
    assert n == 8
    assert bs.slot_complete(5)
    got = bs.slot_entries(5)
    assert got is not None and got[0].hash == b"\x33" * 32

    # torn final record is tolerated (capture process died mid-write)
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[:-7])
    assert len(list(shredcap.iter_shreds(path))) == 7

    with pytest.raises(ValueError):
        list(shredcap.iter_shreds(__file__))  # not an archive
