"""funk under parallel replay load (VERDICT r4 weak #6: the global RLock
was 'untested at scale' because fork workers hold their own view).

Two records:
  1. correctness under REAL concurrency: reader THREADS hammering
     read()/ancestry walks while a writer publishes fork txns — the
     RLock's actual contention case inside one tile process;
  2. a measured throughput record for the lock under that load, printed
     for the perf log (this is a 1-core host: the number documents lock
     overhead, not parallel speedup).
"""

import threading
import time

from firedancer_tpu.funk.funk import Funk


def _fill(funk, xid, n, tag):
    for i in range(n):
        funk.write(xid, b"k%06d" % i, b"%s-%06d" % (tag, i))


def test_concurrent_readers_vs_publishing_writer():
    funk = Funk()
    root = None
    funk.txn_prepare(b"base", root)
    _fill(funk, b"base", 500, b"v0")
    funk.txn_publish(b"base")

    stop = threading.Event()
    errors: list[str] = []
    reads = [0, 0, 0, 0]

    def reader(slot_i):
        while not stop.is_set():
            for i in range(0, 500, 7):
                v = funk.read(None, b"k%06d" % i)
                if v is None:
                    errors.append(f"k{i} vanished")
                    return
                # value must be a CONSISTENT generation (prefix v<N>-)
                if not v.startswith(b"v") or b"-" not in v:
                    errors.append(f"torn read {v!r}")
                    return
                reads[slot_i] += 1

    threads = [threading.Thread(target=reader, args=(i,), daemon=True)
               for i in range(4)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    # writer: a chain of fork txns re-writing every key, each published
    # while the readers walk ancestry
    for gen in range(1, 6):
        xid = b"gen%04d" % gen
        funk.txn_prepare(xid, None)
        _fill(funk, xid, 500, b"v%d" % gen)
        funk.txn_publish(xid)
    stop.set()
    for t in threads:
        t.join(10)
    dt = time.perf_counter() - t0
    assert not errors, errors[:3]
    total = sum(reads)
    assert total > 0
    # the throughput record (lock-overhead documentation, 1-core host)
    print(f"\nfunk read throughput under publish load: "
          f"{total / dt:,.0f} reads/s across 4 threads, "
          f"5 publishes of 500 keys in {dt:.2f}s")
    # every key must have landed on the final generation
    for i in range(0, 500, 50):
        assert funk.read(None, b"k%06d" % i).startswith(b"v5-")


def test_fork_branches_read_isolated_under_load():
    """Competing unpublished forks keep isolated views while a reader
    thread walks the published root — the replay tile's real shape."""
    funk = Funk()
    funk.txn_prepare(b"r", None)
    _fill(funk, b"r", 200, b"root")
    funk.txn_publish(b"r")
    funk.txn_prepare(b"a", None)
    funk.txn_prepare(b"b", None)
    _fill(funk, b"a", 200, b"forkA")
    _fill(funk, b"b", 200, b"forkB")

    stop = threading.Event()
    bad = []

    def root_reader():
        while not stop.is_set():
            v = funk.read(None, b"k%06d" % 7)
            if v is not None and not v.startswith(b"root-"):
                bad.append(v)
                return

    th = threading.Thread(target=root_reader, daemon=True)
    th.start()
    for _ in range(200):
        assert funk.read(b"a", b"k%06d" % 7).startswith(b"forkA-")
        assert funk.read(b"b", b"k%06d" % 7).startswith(b"forkB-")
    stop.set()               # reader's invariant holds only pre-publish
    th.join(10)
    assert not bad, bad[:2]
    funk.txn_publish(b"a")   # fork A wins; B's subtree drops
    assert funk.read(None, b"k%06d" % 7).startswith(b"forkA-")
