"""The REAL ed25519 conformance corpora (round 4, VERDICT missing #3):
Wycheproof (133), CCTV / "Taming the many EdDSAs" (914), and the Zcash
signature-malleability set (396), extracted verbatim from the reference's
generated tables (tools/extract_crypto_corpora.py; ref
src/ballet/ed25519/test_ed25519_wycheproof.c, test_ed25519_cctv.c,
test_ed25519_signature_malleability_should_{pass,fail}.bin).

Expected bits are the reference's consensus-exact expectations.  Every
vector runs through verify_one_host (fast tier) and through the batched
device graph (slow tier) — pass/fail bits must match exactly.
"""

import json
import os

import numpy as np
import pytest

from firedancer_tpu.ops import ed25519 as ed

_GOLDEN = os.path.join(os.path.dirname(__file__), "golden")
CORPORA = ("wycheproof_ed25519", "cctv_ed25519", "malleability_ed25519")


def _load(name):
    with open(os.path.join(_GOLDEN, name + ".json")) as f:
        return [
            (f"{name}:{v['tc_id']}", bytes.fromhex(v["msg"]),
             bytes.fromhex(v["sig"]), bytes.fromhex(v["pub"]), v["ok"])
            for v in json.load(f)
        ]


@pytest.fixture(scope="module")
def vectors():
    vs = []
    for name in CORPORA:
        vs += _load(name)
    assert len(vs) == 133 + 914 + 396
    return vs


def test_corpora_sizes_and_content():
    wy, cc, mal = (_load(n) for n in CORPORA)
    assert len(wy) == 133 and len(cc) == 914 and len(mal) == 396
    # the corpora carry both polarities
    for vs in (wy, cc, mal):
        oks = {v[4] for v in vs}
        assert oks == {True, False}, "corpus lost a polarity"


def test_real_corpora_host_verifier(vectors):
    for label, msg, sig, pub, expected in vectors:
        assert ed.verify_one_host(sig, msg, pub) is expected, label


@pytest.mark.slow
def test_real_corpora_device_batch(vectors):
    """Every vector through the batched device graph (XLA CPU in the test
    tier; Pallas on a real chip via FDTPU_TEST_TPU=1) — consensus-exact
    pass/fail bits against the reference's expectations."""
    import jax

    maxlen = 128
    short = [v for v in vectors if len(v[1]) <= maxlen]
    long = [v for v in vectors if len(v[1]) > maxlen]
    assert len(long) <= 8  # 3 known long-msg vectors ride verify_one

    batch = 1536
    assert len(short) <= batch
    msgs = np.zeros((batch, maxlen), dtype=np.uint8)
    lens = np.zeros((batch,), dtype=np.int32)
    sigs = np.zeros((batch, 64), dtype=np.uint8)
    pubs = np.zeros((batch, 32), dtype=np.uint8)
    pad = short[0]
    rows = short + [pad] * (batch - len(short))
    for i, (_l, msg, sig, pub, _e) in enumerate(rows):
        msgs[i, : len(msg)] = np.frombuffer(msg, dtype=np.uint8)
        lens[i] = len(msg)
        sigs[i] = np.frombuffer(sig, dtype=np.uint8)
        pubs[i] = np.frombuffer(pub, dtype=np.uint8)
    ok = np.asarray(jax.jit(ed.verify_batch)(msgs, lens, sigs, pubs))
    mism = [(rows[i][0], bool(ok[i]), rows[i][4])
            for i in range(batch) if bool(ok[i]) is not rows[i][4]]
    assert not mism, mism[:10]

    for label, msg, sig, pub, expected in long:
        assert ed.verify_one(sig, msg, pub) is expected, label
