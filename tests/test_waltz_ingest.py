"""Network ingest path tests: UDP sockets aio, TPU reassembly, and the
net -> quic -> verify -> sink topology over real datagrams (the analogue of
the reference's loopback/netns ingest tests, SURVEY.md §4.4)."""

import os
import time

import numpy as np

from firedancer_tpu.disco.run import TopoRun
from firedancer_tpu.disco.topo import TopoBuilder
from firedancer_tpu.disco.tpu_reasm import TpuReasm
from firedancer_tpu.waltz.aio import Pkt
from firedancer_tpu.waltz.udpsock import UdpSock


def test_udpsock_roundtrip():
    a, b = UdpSock(bind_ip="127.0.0.1"), UdpSock(bind_ip="127.0.0.1")
    try:
        pkts = [Pkt(bytes([i]) * (i + 1), ("127.0.0.1", b.port))
                for i in range(10)]
        assert a.send_burst(pkts) == 10
        got = []
        deadline = time.monotonic() + 5
        while len(got) < 10 and time.monotonic() < deadline:
            got += b.recv_burst()
        assert sorted(p.payload for p in got) == sorted(p.payload for p in pkts)
    finally:
        a.close()
        b.close()


def test_native_pkteng_burst_roundtrip():
    """C++ recvmmsg/sendmmsg engine (waltz.pkteng over native/pkteng.cpp)
    speaks the same burst contract as UdpSock, including interop."""
    from firedancer_tpu.waltz.pkteng import NativeUdpSock

    a = NativeUdpSock(bind_ip="127.0.0.1")
    b = NativeUdpSock(bind_ip="127.0.0.1")
    c = UdpSock(bind_ip="127.0.0.1")
    try:
        pkts = [Pkt(bytes([i]) * (i + 10), ("127.0.0.1", b.port))
                for i in range(100)]
        assert a.send_burst(pkts) == 100
        got = []
        deadline = time.monotonic() + 5
        while len(got) < 100 and time.monotonic() < deadline:
            got += b.recv_burst()
        assert sorted(p.payload for p in got) == \
            sorted(p.payload for p in pkts)
        assert got[0].addr[0] == "127.0.0.1"
        # native -> python-socket interop
        a.send_burst([Pkt(b"cross", ("127.0.0.1", c.port))])
        deadline = time.monotonic() + 5
        seen = []
        while not seen and time.monotonic() < deadline:
            seen = [p for p in c.recv_burst() if p.payload == b"cross"]
        assert seen
    finally:
        a.close()
        b.close()
        c.close()


def test_tpu_reasm_streams():
    out = []
    r = TpuReasm(depth=2, publish_fn=out.append)
    r.prepare(("c1", 1))
    r.append(("c1", 1), b"hello ")
    r.append(("c1", 1), b"world")
    r.publish(("c1", 1))
    assert out == [b"hello world"]
    # FIFO eviction: 2 slots, opening a 3rd evicts the oldest
    r.prepare(("c1", 2))
    r.prepare(("c2", 1))
    r.prepare(("c2", 2))
    assert not r.append(("c1", 2), b"x")       # evicted
    assert r.metrics["evict_cnt"] == 1
    # oversize stream dropped
    r.prepare(("c3", 1))
    assert not r.append(("c3", 1), b"z" * 1300)
    assert r.metrics["oversz_cnt"] == 1
    # datagram fast path
    assert r.publish_datagram(b"txn")
    assert out[-1] == b"txn"


def _make_txns(n: int, keys: int = 4, seed: int = 7) -> list[bytes]:
    from firedancer_tpu.ballet import txn as txn_lib
    from firedancer_tpu.ops import ed25519 as ed
    rng = np.random.default_rng(seed)
    pool = []
    for _ in range(keys):
        s = rng.bytes(32)
        pub, _, _ = ed.keypair_from_seed(s)
        pool.append((s, pub))
    blockhash, program = rng.bytes(32), rng.bytes(32)
    out = []
    for i in range(n):
        s, pub = pool[i % keys]
        msg = txn_lib.build_unsigned(
            [pub], blockhash, [(1, bytes([0]), i.to_bytes(8, "little"))],
            extra_accounts=[program])
        out.append(txn_lib.assemble([ed.sign(s, msg)], msg))
    return out


def test_udp_ingest_topology():
    """Real UDP datagrams -> net tile -> quic tile (legacy TPU reasm) ->
    verify -> sink; every distinct valid txn must arrive."""
    n = 24
    spec = (
        TopoBuilder(f"net{os.getpid()}", wksp_mb=16)
        .link("net_quic", depth=256, mtu=1500)
        .link("quic_verify", depth=256, mtu=1280)
        .link("verify_sink", depth=256, mtu=1280)
        .tile("net", "net", outs=["net_quic"], ports={0: "net_quic"})
        .tile("quic", "quic", ins=["net_quic"], outs=["quic_verify"])
        .tile("verify", "verify", ins=["quic_verify"], outs=["verify_sink"],
              batch=16, msg_maxlen=256, flush_age_ns=50_000_000)
        .tile("sink", "sink", ins=["verify_sink"])
        .build()
    )
    txns = _make_txns(n)
    with TopoRun(spec) as run:
        run.wait_ready(timeout=420)
        port = run.metrics("net")["bound_port"]
        assert port != 0
        tx = UdpSock(bind_ip="127.0.0.1")
        try:
            deadline = time.monotonic() + 120
            sent = 0
            while time.monotonic() < deadline:
                if sent < n:
                    # drip + re-send tolerant loop: UDP may drop; txns are
                    # deduped downstream so resending is harmless... but to
                    # keep counters exact we send each once (loopback does
                    # not drop under this tiny load)
                    tx.send_burst([Pkt(txns[sent], ("127.0.0.1", port))])
                    sent += 1
                if run.metrics("sink")["frag_cnt"] == n:
                    break
                time.sleep(0.01)
            assert run.metrics("sink")["frag_cnt"] == n
            assert run.metrics("quic")["reasm_pub_cnt"] == n
            assert run.metrics("verify")["verify_pass_cnt"] == n
            assert run.poll() is None
        finally:
            tx.close()


def test_xring_kernel_bypass_rx():
    """TPACKET_V3 ring on loopback: UDP datagrams sent with a plain socket
    must surface through the mmap'd ring with correct payload/src, no
    per-packet syscalls (ref fd_xsk ring semantics; needs CAP_NET_RAW —
    skipped where the container forbids packet sockets)."""
    import socket as pysock
    import time as _t

    from firedancer_tpu.waltz.pkteng import XRing

    tx = pysock.socket(pysock.AF_INET, pysock.SOCK_DGRAM)
    tx.bind(("127.0.0.1", 0))
    rx = pysock.socket(pysock.AF_INET, pysock.SOCK_DGRAM)
    rx.bind(("127.0.0.1", 0))  # a real listener so the kernel doesn't ICMP
    port = rx.getsockname()[1]
    try:
        ring = XRing("lo", udp_port=port)
    except OSError as e:  # pragma: no cover - restricted sandboxes
        pytest.skip(f"AF_PACKET ring unavailable: {e}")
    try:
        sent = [b"xring-%03d" % i for i in range(40)]
        for b in sent:
            tx.sendto(b, ("127.0.0.1", port))
        got = []
        deadline = _t.monotonic() + 3.0
        while len(got) < len(sent) and _t.monotonic() < deadline:
            ring.poll(50)
            got += ring.recv_burst()
        payloads = sorted(p.payload for p in got)
        assert payloads == sorted(sent), (len(got), len(sent))
        srcport = tx.getsockname()[1]
        assert all(p.addr == ("127.0.0.1", srcport) for p in got)
    finally:
        ring.close()
        tx.close()
        rx.close()
