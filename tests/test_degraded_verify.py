"""Graceful-degradation conformance on REAL verify graphs: the CPU
ed25519 fallback must be bit-identical to the device path (that is the
whole contract that makes degraded mode safe to serve from), a
bit-flipped packed row must yield a failed verdict — not a crash or a
torn drop — on BOTH paths, and the GuardedVerifier must flip to the
fallback and recover against a live SigVerifier.

Small always-primed shape (16, 256); defers to the slow tier on a cold
cache (conftest PRIMED_ONLY_MODULES)."""

import numpy as np

from firedancer_tpu.disco import faultinject
from firedancer_tpu.disco.pipeline import GuardedVerifier
from firedancer_tpu.models.verifier import (SigVerifier, VerifierConfig,
                                            host_verify_arrays,
                                            host_verify_blob)
from firedancer_tpu.ops import ed25519 as ed

BATCH, ML = 16, 256


def _verifier():
    return SigVerifier(VerifierConfig(batch=BATCH, msg_maxlen=ML))


def _mixed_corpus(v, seed=5):
    """Valid batch with scripted invalid lanes: flipped sig, flipped pub,
    flipped msg byte, truncated len, all-zero sig+pub."""
    msgs, lens, sigs, pubs = (np.asarray(a).copy()
                              for a in v.example_args(seed=seed))
    sigs[1, 40] ^= 0x42                      # bad signature
    pubs[3, 7] ^= 0x01                       # bad pubkey
    msgs[5, int(lens[5]) // 2] ^= 0x80       # message tampered
    lens[7] = max(1, int(lens[7]) - 1)       # wrong length
    sigs[9, :] = 0                           # all-zero sig + pub (the
    pubs[9, :] = 0                           # degenerate small-order case)
    return msgs, lens, sigs, pubs


def _pack_blob(msgs, lens, sigs, pubs):
    n = msgs.shape[0]
    blob = np.zeros((n, ML + ed.PACKED_EXTRA), np.uint8)
    blob[:, :ML] = msgs[:, :ML]
    blob[:, ML:ML + 64] = sigs
    blob[:, ML + 64:ML + 96] = pubs
    blob[:, ML + 96:ML + 100] = (
        lens.astype(np.int32).reshape(-1, 1).view(np.uint8))
    return blob


def test_host_fallback_bit_identical_to_device():
    v = _verifier()
    msgs, lens, sigs, pubs = _mixed_corpus(v)
    dev = np.asarray(v(msgs, lens, sigs, pubs)).astype(bool)
    host = np.asarray(host_verify_arrays(msgs, lens, sigs, pubs))
    assert dev.shape == host.shape == (BATCH,)
    assert dev.sum() == BATCH - 5            # the scripted lanes fail
    assert np.array_equal(dev, host), \
        f"device {dev.tolist()} != host {host.tolist()}"


def test_corrupt_packed_row_fails_both_paths():
    # satellite: a packed row corrupted in flight (the fault injector's
    # frags_view flips dcache bytes in place; here the same single-bit
    # flip applied directly to the blob) must come back as a FAILED
    # verdict on the device path and the CPU fallback alike — never a
    # crash, never a torn/partial verdict for the other rows
    v = _verifier()
    msgs, lens, sigs, pubs = (np.asarray(a).copy()
                              for a in v.example_args(seed=6))
    blob = _pack_blob(msgs, lens, sigs, pubs)
    clean_dev = np.asarray(v.dispatch_blob(blob.copy())).astype(bool)
    assert clean_dev.all()

    k = 4
    blob[k, int(lens[k]) // 3] ^= 0x10       # one bit, inside the message
    dev = np.asarray(v.dispatch_blob(blob.copy())).astype(bool)
    host = np.asarray(host_verify_blob(blob))
    expect = clean_dev.copy()
    expect[k] = False
    assert np.array_equal(dev, expect)
    assert np.array_equal(host, dev), \
        f"device {dev.tolist()} != host {host.tolist()}"


def test_guarded_verifier_degrades_and_recovers_live():
    # persistent injected dispatch failure -> CPU fallback serves
    # bit-identical verdicts; once the fault clears, the reprobe restores
    # the device path (reprobe_s=0 probes on the next dispatch)
    v = _verifier()
    msgs, lens, sigs, pubs = _mixed_corpus(v, seed=7)
    ref = np.asarray(v(msgs, lens, sigs, pubs)).astype(bool)

    fault = faultinject.FaultInjector("verify:0", {"fail_dispatch_n": 2})
    g = GuardedVerifier(v, fail_threshold=2, retries=0, reprobe_s=0.0,
                        fault=fault)
    for i in range(2):                       # injected failures -> fallback
        ok = np.asarray(g(msgs, lens, sigs, pubs))
        assert np.array_equal(ok, ref)
    assert g.degraded and g.device_fail_cnt == 2
    assert g.fallback_lanes == 2 * BATCH

    ok = np.asarray(g(msgs, lens, sigs, pubs))  # fault spent: probe succeeds
    assert np.array_equal(ok, ref)
    assert not g.degraded and g.reprobe_cnt == 1

    ok = np.asarray(g(msgs, lens, sigs, pubs))  # healthy device path again
    assert np.array_equal(ok, ref)
    assert g.fallback_lanes == 2 * BATCH        # no further fallback

    # packed surface rides the same guard (SigVerifier has dispatch_blob)
    blob = _pack_blob(msgs, lens, sigs, pubs)
    assert np.array_equal(np.asarray(g.dispatch_blob(blob)), ref)
