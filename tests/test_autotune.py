"""Closed-loop autotuner unit tests (fast tier, no device graphs):
knob-pod shm round-trips, bounded/clamped step arithmetic, the policy
loop's hysteresis + one-action-in-flight + do-no-harm revert +
quarantine semantics, relax-toward-baseline, decision-log mirroring and
rendering, metric families, and the strict config validation that
protects the `[autotune]` section (and its siblings) from typos.

Everything live (real topology, shm actuation through a tile's mux
housekeeping) lives in tools/chaos_smoke.py --autotune."""

import json
import os

import pytest

from firedancer_tpu.disco import autotune as at
from firedancer_tpu.disco import topo as topo_mod
from firedancer_tpu.disco.topo import TopoBuilder

# -- knob pods ----------------------------------------------------------------


def _pod_spec(tag: str):
    return (
        TopoBuilder(f"at{tag}{os.getpid()}", wksp_mb=8)
        .link("a_b", depth=64, mtu=256)
        .tile("source", "source", outs=["a_b"], count=1)
        .tile("v:0", "verify", ins=["a_b"])
        .build()
    )


def test_pod_footprint_uniform_and_padded():
    # one u64 gen + POD_SLOTS f64 values fits, padded to a fixed size so
    # the deterministic layout replay never depends on tile kind
    assert at.pod_footprint() == 128
    assert 8 + at.POD_SLOTS * 8 <= at.pod_footprint()
    assert all(len(v) <= at.POD_SLOTS for v in at.KNOBS.values())


def test_knob_names_globally_unique():
    seen = []
    for names in at.KNOBS.values():
        seen += list(names)
    assert len(seen) == len(set(seen))
    assert set(seen) == set(at.KNOB_SPECS)


def test_knob_pod_roundtrip_across_joins():
    spec = _pod_spec("rt")
    jt = topo_mod.create(spec)
    jt2 = None
    try:
        pod = jt.knobs["v:0"]
        assert pod.gen == 0 and pod.read_set() == {}
        pod.write("flush_age_ns", 5e8)
        # the gen counter is the publish barrier: a staged write leaves
        # gen unchanged, so a gen-polling mux does not pick it up yet
        assert pod.gen == 0
        pod.commit()
        pod.write("max_inflight", 16)
        pod.commit()
        # a separately-joined view (what a respawned tile's mux sees)
        # observes the same generation and the same armed set
        jt2 = topo_mod.join(spec)
        p2 = jt2.knobs["v:0"]
        assert p2.gen == 2
        assert p2.read_set() == {"flush_age_ns": 5e8, "max_inflight": 16.0}
        # untouched tile's pod stays silent
        assert jt2.knobs["source"].read_set() == {}
    finally:
        # drop the local pod views before the workspaces unmap
        pod = p2 = None  # noqa: F841
        import gc
        gc.collect()
        if jt2 is not None:
            jt2.close()
        jt.close()
        jt.unlink()


def test_mux_binds_pod_with_generation_zero():
    # a fresh mux starts at generation-seen 0, so a respawned tile
    # re-applies the accumulated knob set at its first housekeeping
    from firedancer_tpu.disco.mux import Mux

    spec = _pod_spec("mx")
    jt = topo_mod.create(spec)
    try:
        jt.knobs["v:0"].write("max_inflight", 32)
        jt.knobs["v:0"].commit()

        class _Vt:
            pass

        m = Mux(jt, "v:0", _Vt())
        assert m._knob_pod is not None
        assert m._knob_gen == 0 and m._knob_pod.gen == 1
        m = None  # noqa: F841 - release dcache views before unmap
        import gc
        gc.collect()
    finally:
        jt.close()
        jt.unlink()


# -- step arithmetic ----------------------------------------------------------


def _tuner(cfg=None, tiles=None, sense=None, apply=None, **kw):
    cfg = dict({"enabled": 1, "cooldown_periods": 0}, **(cfg or {}))
    tiles = tiles if tiles is not None else \
        [("verify:0", "verify", {"flush_age_ns": 1.0e9})]
    return at.Autotuner(None, cfg, target_ms=2.0, tiles=tiles,
                        sense_fn=sense, apply_fn=apply or (lambda *a: None),
                        **kw)


def test_step_value_bounded_and_clamped():
    tn = _tuner()
    # float knob: multiplicative step
    new, _ = tn._step_value("pps_per_source", 1000.0, +1)
    assert new == 1250.0
    # int knob moves at least 1 even when the fraction rounds to 0
    new, _ = tn._step_value("lat_max_inflight", 1.0, +1)
    assert new == 2.0
    # clamped at both ends
    assert tn._step_value("deadline_us", 250.0, -1)[0] == 200.0
    assert tn._step_value("deadline_us", 49_000.0, +1)[0] == 50_000.0
    # pinned at the clamp: no move
    assert tn._step_value("flush_age_ns", 2.0e9, +1)[0] == 2.0e9


def test_bounds_override_and_unknown_knob_rejected():
    tn = _tuner({"bounds": {"flush_age_ns": [1e6, 5e8, 0.25]}})
    assert tn.bounds["flush_age_ns"][1:4] == (1e6, 5e8, 0.25)
    assert tn._step_value("flush_age_ns", 4.8e8, +1)[0] == 5e8
    with pytest.raises(ValueError, match="unknown knob"):
        _tuner({"bounds": {"flushage": [1, 2]}})


def test_initial_values_seed_from_tile_cfg():
    tn = _tuner(tiles=[("verify:0", "verify",
                        {"flush_age_ns": 7e8,
                         "latency": {"deadline_us": 900,
                                     "max_inflight": 3}})])
    assert tn.current[("verify:0", "flush_age_ns")] == 7e8
    assert tn.current[("verify:0", "deadline_us")] == 900
    assert tn.current[("verify:0", "lat_max_inflight")] == 3
    # unset knob falls back to its spec default
    assert tn.current[("verify:0", "max_inflight")] == 8


# -- the policy loop ----------------------------------------------------------


def _const_sense(**kw):
    base = {"burn": 0.0, "trend": "flat", "n": 32, "bottleneck": "none",
            "reason": "", "shedding": False}
    base.update(kw)
    return lambda tn: dict(base)


def test_hysteresis_deadband_no_action():
    moves = []
    tn = _tuner(sense=_const_sense(burn=0.2),
                apply=lambda *a: moves.append(a))
    for _ in range(6):
        tn.step()
    assert moves == [] and tn.decision_cnt == 0
    assert tn.converged_at == 2  # resting under burn_hi IS converged
    assert tn.converge_s == 2 * tn.period_s


def test_one_action_in_flight_and_convergence():
    state = {"flush": 1.6e9}

    def sense(tn):
        return dict(_const_sense()(tn),
                    burn=min(max((state["flush"] - 2e8) / 1.4e9, 0), 1))

    def apply(tile, knob, value):
        state[knob.split("_")[0]] = value if knob == "flush_age_ns" else 0
        if knob == "flush_age_ns":
            state["flush"] = value

    tn = _tuner(sense=sense, apply=apply)
    tn.step()
    assert tn.decision_cnt == 1 and tn._last is not None
    tn.step()   # watch active: the loop only measures
    assert tn.decision_cnt == 1, "acted while an action was in flight"
    for _ in range(8):
        tn.step()
    assert tn.converged_at is not None
    assert state["flush"] < 1.6e9
    assert tn.revert_cnt == 0
    # every applied move inside its clamp
    for d in tn.decisions:
        _, lo, hi, _, _, _ = at.KNOB_SPECS[d["knob"]]
        assert lo <= float(d["new"]) <= hi


def test_do_no_harm_revert_and_quarantine():
    state = {"flush": 1.0e9}

    def sense(tn):
        return dict(_const_sense()(tn),
                    burn=min(max((state["flush"] - 2e8) / 1.4e9, 0), 1))

    def apply(tile, knob, value):
        if knob == "flush_age_ns":
            state["flush"] = value

    tn = _tuner({"poison": "coalesce_flush"}, sense=sense, apply=apply)
    for _ in range(8):
        tn.step()
    assert tn.revert_cnt == 1
    assert state["flush"] == 1.0e9, "revert must restore the exact value"
    assert tn.current[("verify:0", "flush_age_ns")] == 1.0e9
    fired = [d for d in tn.decisions if d["rule"] == "coalesce_flush"]
    assert len(fired) == 1, "quarantine must stop the poisoned rule"
    assert tn._cooldown["coalesce_flush"] > tn.period
    rev = [d for d in tn.decisions if d["outcome"] == "reverted"]
    assert len(rev) == 1 and rev[0]["rule"] == "do_no_harm"


def test_clamped_rule_records_and_cools_down():
    tn = _tuner({"cooldown_periods": 3},
                tiles=[("verify:0", "verify", {"flush_age_ns": 200_000})],
                sense=_const_sense(burn=1.0))
    tn.step()   # flush already AT the lo clamp: no actuation, one record
    assert tn.clamp_cnt == 1
    assert tn.decisions[0]["outcome"] == "clamped"
    assert tn.decisions[0]["old"] == tn.decisions[0]["new"] == 200_000
    assert tn._last is None, "a clamped non-move must not open a watch"
    tn.step()   # coalesce_flush cooling: the NEXT rule acts
    assert tn.decisions[1]["rule"] == "lat_deadline"
    assert tn.decisions[1]["outcome"] == "applied"


def test_rate_knobs_left_unarmed_are_skipped():
    # operator runs without a net rate limiter (pps 0 = off): autotune
    # must never arm one on its own
    moves = []
    tn = _tuner(tiles=[("net", "net", {"pps_per_source": 0})],
                sense=_const_sense(burn=1.0),
                apply=lambda *a: moves.append(a))
    for _ in range(4):
        tn.step()
    assert moves == [] and tn.decision_cnt == 0


def test_relax_walks_back_toward_baseline_without_overshoot():
    calls = []
    tn = _tuner({"relax_after": 2}, sense=_const_sense(burn=0.0),
                apply=lambda t, k, v: calls.append((k, v)))
    tn.current[("verify:0", "flush_age_ns")] = 3.2e9 / 2  # displaced
    while tn.current[("verify:0", "flush_age_ns")] != 1.0e9:
        before = tn.decision_cnt
        for _ in range(8):
            tn.step()
        assert tn.decision_cnt > before, "relax stalled short of baseline"
    assert all(k == "flush_age_ns" and v <= 1.6e9 for k, v in calls)
    assert tn.current[("verify:0", "flush_age_ns")] == 1.0e9  # never past
    assert all(d["rule"] == "relax" for d in tn.decisions)


def test_respawn_last_resort_maxes_window():
    class _Run:
        respawned = []

        def respawn(self, name):
            self.respawned.append(name)

    tn = _tuner({"respawn_after": 3}, sense=_const_sense(burn=1.0))
    run = _Run()
    tn.run = run
    for _ in range(12):
        tn.step()
    # fires ONCE: with the window already maxed, a second respawn would
    # just crash-loop the tile to no effect
    assert run.respawned == ["verify:0"]
    assert tn.current[("verify:0", "max_inflight")] == \
        at.KNOB_SPECS["max_inflight"][2]
    resp = [d for d in tn.decisions if d["outcome"] == "respawned"]
    assert len(resp) == 1 and resp[0]["old"] == 8


# -- decision log -------------------------------------------------------------


def test_decision_log_mirror_and_torn_line_skip(tmp_path):
    tn = _tuner(sense=_const_sense(burn=1.0), log_dir=str(tmp_path))
    tn.step()
    path = tmp_path / at.LOG_NAME
    assert path.exists()
    with open(path, "a") as f:
        f.write('{"period": 2, "rule": "tor')   # torn tail (crash mid-write)
    decs = at.load_decisions(str(path))
    assert len(decs) == 1
    assert decs[0]["rule"] == tn.decisions[0]["rule"]
    assert decs[0]["old"] and decs[0]["new"]


def test_render_decisions_table():
    assert at.render_decisions([]) == "no autotune decisions recorded"
    decs = [{"period": 3, "rule": "coalesce_flush", "tile": "verify:0",
             "knob": "flush_age_ns", "old": 1.0e9, "new": 5.0e8,
             "outcome": "applied", "burn": 0.57, "trend": "flat",
             "bottleneck": "src_verify|verify:0", "reason": ""},
            {"period": 5, "rule": "do_no_harm", "tile": "verify:0",
             "knob": "flush_age_ns", "old": 5.0e8, "new": 1.0e9,
             "outcome": "reverted", "burn": 0.9, "trend": "rising",
             "bottleneck": "", "reason": "slow consumer dedup"}]
    out = at.render_decisions(decs)
    assert "coalesce_flush" in out and "reverted" in out
    assert "1,000,000,000" in out and "500,000,000" in out
    assert "slow consumer dedup" in out
    assert out.splitlines()[-1] == "2 decisions, 1 reverted"


def test_families_export():
    tn = _tuner(sense=_const_sense(burn=1.0))
    tn.step()
    fams = tn.families()
    names = [f[0] for f in fams]
    assert "fdtpu_autotune_decision_cnt" in names
    assert "fdtpu_autotune_revert_cnt" in names
    assert "fdtpu_autotune_clamp_cnt" in names
    assert "fdtpu_autotune_converged" in names
    knobs = [f for f in fams if f[0] == "fdtpu_autotune_knob"]
    assert {k[3]["knob"] for k in knobs} == set(at.KNOBS["verify"])
    assert all(k[3]["tile"] == "verify:0" for k in knobs)


# -- strict config validation (the typo fixtures) -----------------------------


def _load_toml(tmp_path, text):
    from firedancer_tpu.app import config as config_mod
    p = tmp_path / "fdtpu.toml"
    p.write_text(text)
    return config_mod.load(str(p))


def test_config_strict_rejects_typo_with_suggestion(tmp_path):
    with pytest.raises(ValueError) as ei:
        _load_toml(tmp_path, "[latency]\ndeadline_uss = 500\n")
    msg = str(ei.value)
    assert "unknown key 'deadline_uss' in [latency]" in msg
    assert "did you mean 'deadline_us'?" in msg
    assert "valid keys:" in msg and "max_inflight" in msg


@pytest.mark.parametrize("section,key,near", [
    ("verify", "moed", "mode"),
    ("supervision", "max_restart", "max_restarts"),
    ("observability", "flight_max_bundle", "flight_max_bundles"),
    ("autotune", "burnhi", "burn_hi"),
])
def test_config_strict_covers_all_guarded_sections(tmp_path, section, key,
                                                   near):
    with pytest.raises(ValueError) as ei:
        _load_toml(tmp_path, f"[{section}]\n{key} = 1\n")
    msg = str(ei.value)
    assert f"unknown key {key!r} in [{section}]" in msg
    assert f"did you mean {near!r}?" in msg


def test_config_strict_allows_known_subtables(tmp_path):
    cfg = _load_toml(tmp_path, "\n".join([
        "[supervision.heartbeat_stale]", "verify = 30",
        "[autotune.bounds]", "flush_age_ns = [1e6, 1e9]",
        "[autotune]", "enabled = 1",
    ]))
    assert cfg["autotune"]["enabled"] == 1
    assert cfg["autotune"]["bounds"]["flush_age_ns"] == [1e6, 1e9]


def test_config_strict_validates_bounds_knobs(tmp_path):
    with pytest.raises(ValueError, match="unknown knob 'flush_age_nss'"):
        _load_toml(tmp_path,
                   "[autotune.bounds]\nflush_age_nss = [1e6, 1e9]\n")
    with pytest.raises(ValueError, match=r"\[lo, hi\]"):
        _load_toml(tmp_path, "[autotune.bounds]\nflush_age_ns = [1e6]\n")


def test_config_default_toml_passes_its_own_strictness():
    from firedancer_tpu.app import config as config_mod
    cfg = config_mod.load(None)
    assert cfg["autotune"]["enabled"] == 0        # default-off
    assert cfg["observability"]["flight_max_bundles"] == 16


# -- flight recorder integration ---------------------------------------------


def test_flightrec_rotate_keeps_newest(tmp_path):
    from firedancer_tpu.disco import flightrec
    import time as time_mod
    for i in range(5):
        d = tmp_path / f"app-crash-2026010{i}T000000-1"
        d.mkdir()
        (d / "manifest.json").write_text("{}")
        os.utime(d, (i, i))
    (tmp_path / "not-a-bundle").mkdir()           # no manifest: ignored
    assert flightrec.rotate(str(tmp_path), 2) == 3
    left = sorted(p.name for p in tmp_path.iterdir() if p.is_dir())
    assert left == ["app-crash-20260103T000000-1",
                    "app-crash-20260104T000000-1", "not-a-bundle"]
    assert flightrec.rotate(str(tmp_path), 2) == 0
    assert flightrec.rotate(str(tmp_path), 0) == 0    # 0 = unbounded
    assert flightrec.rotate(str(tmp_path / "gone"), 2) == 0
    del time_mod


def test_flightrec_bundle_carries_autotune_history(tmp_path):
    from firedancer_tpu.disco import flightrec
    spec = _pod_spec("fr")
    jt = topo_mod.create(spec)
    try:
        decs = [{"period": 1, "rule": "coalesce_flush", "tile": "v:0",
                 "knob": "flush_age_ns", "old": 1e9, "new": 5e8,
                 "outcome": "applied", "burn": 0.6, "trend": "flat",
                 "bottleneck": "", "reason": ""}]
        path = flightrec.write_bundle(str(tmp_path), jt, reason="degrade",
                                      tile="v:0", autotune=decs)
        b = flightrec.load_bundle(path)
        assert b["autotune"] == decs
        rendered = flightrec.render_bundle(path)
        assert "autotune decision history:" in rendered
        assert "coalesce_flush" in rendered
        # a bundle written without a tuner renders without the section
        p2 = flightrec.write_bundle(str(tmp_path), jt, reason="sigusr2")
        assert json.loads(
            (tmp_path / os.path.basename(p2) / "manifest.json")
            .read_text())["reason"] == "sigusr2"
        assert "autotune decision history" not in flightrec.render_bundle(p2)
    finally:
        jt.close()
        jt.unlink()
