"""JSON-RPC service + client (ref behaviors: the dev RPC client
src/app/fddev/rpc_client/fd_rpc_client.c and the RPC surface the
validator serves): unit round-trip against a fake provider, then a live
bank tile serving RPC inside a topology — queries answered from runtime
state and sendTransaction executing a real funded transfer."""

import os
import time

import pytest

from firedancer_tpu.ballet import txn as txn_lib
from firedancer_tpu.flamenco import genesis as gen_mod
from firedancer_tpu.flamenco.rpc import RpcClient, RpcError, RpcServer
from firedancer_tpu.ops import ed25519 as ed


class _FakeProvider:
    def slot(self):
        return 7

    def blockhash(self):
        return b"\x42" * 32

    def balance(self, pk):
        return 1234 if pk == b"\x01" * 32 else 0

    def txn_count(self):
        return 99


def test_rpc_roundtrip_unit():
    srv = RpcServer(_FakeProvider(), port=0)
    try:
        cl = RpcClient(f"http://127.0.0.1:{srv.port}")
        assert cl.get_health() == "ok"
        assert cl.get_slot() == 7
        assert cl.get_latest_blockhash() == b"\x42" * 32
        assert cl.get_balance(b"\x01" * 32) == 1234
        assert cl.get_balance(b"\x02" * 32) == 0
        assert cl.get_transaction_count() == 99
        sig = cl.send_transaction(b"\x01" + bytes(64) + b"payload")
        assert sig == bytes(64).hex()
        assert srv.drain() == [b"\x01" + bytes(64) + b"payload"]
        with pytest.raises(RpcError) as e:
            cl.call("noSuchMethod")
        assert e.value.code == -32601
        with pytest.raises(RpcError):
            cl.call("getBalance", [])  # missing param
    finally:
        srv.close()


def test_bank_tile_serves_rpc(tmp_path):
    from firedancer_tpu.disco.run import TopoRun
    from firedancer_tpu.disco.topo import TopoBuilder
    from firedancer_tpu.flamenco.system_program import ix_transfer
    from firedancer_tpu.flamenco.types import SYSTEM_PROGRAM_ID, Account

    payer_seed = (7).to_bytes(32, "little")
    payer_pk = ed.keypair_from_seed(payer_seed)[0]
    dest_pk = b"\xd7" + bytes(31)
    faucet_pk = ed.keypair_from_seed((99).to_bytes(32, "little"))[0]
    g = gen_mod.create(faucet_pk, creation_time=1_700_000_000,
                       slots_per_epoch=32)
    g.accounts[payer_pk] = Account(lamports=1_000_000_000)
    gpath = str(tmp_path / "genesis.bin")
    g.write(gpath)

    spec = (
        TopoBuilder(f"rpc{os.getpid()}", wksp_mb=16)
        .link("null_bank", depth=64, mtu=1280)
        .tile("source", "source", outs=["null_bank"], count=1, keys=1)
        .tile("bank", "bank", ins=["null_bank"], genesis_path=gpath,
              rpc_port=0, slot_txn_max=4)
        .build()
    )
    # the source emits ONE unfunded txn (fails execution harmlessly);
    # RPC is the only meaningful txn source in this topology
    with TopoRun(spec) as run:
        run.wait_ready(timeout=420)
        deadline = time.monotonic() + 60
        port = 0
        while time.monotonic() < deadline and not port:
            port = run.metrics("bank")["rpc_port"]
            time.sleep(0.05)
        assert port
        cl = RpcClient(f"http://127.0.0.1:{port}")
        assert cl.get_health() == "ok"
        assert cl.get_slot() >= 1
        assert cl.get_balance(payer_pk) == 1_000_000_000
        assert cl.get_transaction_count() == 0
        bh = cl.get_latest_blockhash()

        msg = txn_lib.build_unsigned(
            [payer_pk], bh,
            [(2, bytes([0, 1]), ix_transfer(250_000))],
            extra_accounts=[dest_pk, SYSTEM_PROGRAM_ID],
            readonly_unsigned_cnt=1)
        raw = txn_lib.assemble([ed.sign(payer_seed, msg)], msg)
        sig_hex = cl.send_transaction(raw)
        assert sig_hex == raw[1:65].hex()

        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if cl.get_transaction_count() >= 1:
                break
            time.sleep(0.05)
        assert cl.get_transaction_count() == 1
        assert cl.get_balance(dest_pk) == 250_000
        assert cl.get_balance(payer_pk) < 1_000_000_000 - 250_000  # + fee

        # a FORGED txn (garbage signature) must be rejected by the bank's
        # RPC-side signature check, never executed
        msg2 = txn_lib.build_unsigned(
            [payer_pk], bh,
            [(2, bytes([0, 1]), ix_transfer(100_000))],
            extra_accounts=[dest_pk, SYSTEM_PROGRAM_ID],
            readonly_unsigned_cnt=1)
        forged = txn_lib.assemble([b"\xab" * 64], msg2)
        cl.send_transaction(forged)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if run.metrics("bank")["txn_fail_cnt"] >= 1:
                break
            time.sleep(0.05)
        assert run.metrics("bank")["txn_fail_cnt"] >= 1
        assert cl.get_transaction_count() == 1  # not executed
        assert cl.get_balance(dest_pk) == 250_000  # unchanged
        assert run.poll() is None
