"""Turbine shred distribution (VERDICT r2 missing #1; ref
src/disco/shred/fd_shred_dest.c + fd_stake_ci.c).

Library tier: tree consistency — every node, computing independently
from the same stake view, agrees on one root per shred, a unique parent
for every node, and full coverage within fanout^2 + fanout.

Topology tier: a 3-node cluster (leader + 2 unstaked followers) where
the leader sends each shred ONLY to its Turbine root and the followers
retransmit to their children — both followers assemble the complete slot
with repair disabled, purely from turbine traffic."""

import os
import socket
import time

from firedancer_tpu.ballet import entry as entry_lib
from firedancer_tpu.ballet import shred as shred_lib
from firedancer_tpu.disco.shred_dest import (
    NO_DEST, Dest, ShredDest, StakeCI, shred_seed, sort_dests)
from firedancer_tpu.ops import ed25519 as ed


def _mk_dests(n_staked, n_unstaked, base_port=7000):
    dests = []
    for i in range(n_staked + n_unstaked):
        seed = (40 + i).to_bytes(32, "little")
        pk = ed.keypair_from_seed(seed)[0]
        stake = (n_staked - i) * 1_000 if i < n_staked else 0
        dests.append(Dest(pk, stake, "127.0.0.1", base_port + i))
    return sort_dests(dests)


def _leaders_const(pk):
    return lambda slot: pk


def test_tree_consistency_all_nodes_agree():
    """Each node computes the tree independently; together the edges form
    one spanning tree per shred: leader -> root -> ... covering all."""
    dests = _mk_dests(6, 3)
    leader = dests[0].pubkey
    fanout = 3
    shreds = []
    for idx in (0, 1, 7, 40):
        s = shred_lib.Shred(
            raw=b"", signature=b"", variant=shred_lib.TYPE_MERKLE_DATA,
            slot=11, idx=idx, version=1, fec_set_idx=0)
        shreds.append(s)

    views = {d.pubkey: ShredDest(dests, _leaders_const(leader), d.pubkey)
             for d in dests}
    leader_view = views[leader]

    for s in shreds:
        root_idx = leader_view.compute_first([s])[0]
        assert root_idx != NO_DEST
        root_pk = dests[root_idx].pubkey
        assert root_pk != leader

        # gather each non-leader node's children claims
        children_of = {}
        for d in dests:
            if d.pubkey == leader:
                continue
            kids = views[d.pubkey].compute_children([s], fanout)[0]
            children_of[d.pubkey] = {dests[i].pubkey for i in kids}

        # every non-leader node except the root has exactly one parent
        parent_count = {d.pubkey: 0 for d in dests if d.pubkey != leader}
        for pk, kids in children_of.items():
            assert pk not in kids  # no self-loop
            for k in kids:
                parent_count[k] += 1
        assert parent_count[root_pk] == 0
        others = [pk for pk in parent_count if pk != root_pk]
        assert all(parent_count[pk] == 1 for pk in others), parent_count
        # n=8 non-leader nodes <= fanout^2+fanout+1: all covered
        covered = {root_pk} | set().union(*children_of.values())
        assert covered == set(parent_count)


def test_seed_and_weighting():
    # seed layout: 45-byte packed struct (fd_shred_dest.c:26-31)
    s1 = shred_seed(5, 9, True, b"\x11" * 32)
    s2 = shred_seed(5, 9, False, b"\x11" * 32)
    s3 = shred_seed(5, 10, True, b"\x11" * 32)
    assert len({s1, s2, s3}) == 3

    # stake-weighted root choice: a 100x stake dest should be root far
    # more often across many shreds
    seed_a, seed_b = (b"\xaa" * 32), (b"\xbb" * 32)
    pk_big = ed.keypair_from_seed(seed_a)[0]
    pk_sml = ed.keypair_from_seed(seed_b)[0]
    pk_lead = ed.keypair_from_seed(b"\xcc" * 32)[0]
    dests = sort_dests([
        Dest(pk_big, 100_000, "10.0.0.1", 1),
        Dest(pk_sml, 1_000, "10.0.0.2", 2),
        Dest(pk_lead, 10, "10.0.0.3", 3),
    ])
    sd = ShredDest(dests, _leaders_const(pk_lead), pk_lead)
    wins = {pk_big: 0, pk_sml: 0}
    for idx in range(200):
        s = shred_lib.Shred(
            raw=b"", signature=b"", variant=shred_lib.TYPE_MERKLE_DATA,
            slot=3, idx=idx, version=1, fec_set_idx=0)
        root = dests[sd.compute_first([s])[0]].pubkey
        wins[root] += 1
    assert wins[pk_big] > 150, wins


def test_stake_ci_view():
    ident = ed.keypair_from_seed(b"\x01" * 32)[0]
    other = ed.keypair_from_seed(b"\x02" * 32)[0]
    ci = StakeCI(ident, slots_per_epoch=100)
    assert ci.sdest_for(5, _leaders_const(other)) is None  # no stakes yet
    ci.set_stakes(0, {ident: 50, other: 100})
    ci.set_contact(other, "1.2.3.4", 99)
    sd = ci.sdest_for(5, _leaders_const(other))
    assert sd is not None
    assert sd.dests[0].pubkey == other  # higher stake sorts first
    assert sd.dests[0].addr == ("1.2.3.4", 99)
    # epoch history bounded: epoch 5 evicts epoch <= 3
    ci.set_stakes(5, {ident: 1})
    assert 0 not in ci.stakes


def _free_port():
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_three_node_turbine_topology(tmp_path):
    """Leader (test process) -> root follower -> other follower: both
    follower blockstores assemble the slot from turbine traffic alone."""
    from firedancer_tpu.disco.run import TopoRun
    from firedancer_tpu.disco.topo import TopoBuilder
    from firedancer_tpu.waltz.aio import Pkt
    from firedancer_tpu.waltz.udpsock import UdpSock

    lead_seed = (91).to_bytes(32, "little")
    lead_pk = ed.keypair_from_seed(lead_seed)[0]
    b_pk = ed.keypair_from_seed((92).to_bytes(32, "little"))[0]
    c_pk = ed.keypair_from_seed((93).to_bytes(32, "little"))[0]
    port_b, port_c = _free_port(), _free_port()

    stakes_cfg = {
        lead_pk.hex(): [1_000, "", 0],           # leader: staked, no tvu
        b_pk.hex(): [0, "127.0.0.1", port_b],
        c_pk.hex(): [0, "127.0.0.1", port_c],
    }

    def follower(tb, name, pk, port):
        net_link = f"net_{name}"
        store_link = f"{name}_store"
        (tb.link(net_link, depth=256, mtu=1280)
           .link(store_link, depth=256, mtu=1280)
           .tile(f"net{name}", "net", outs=[net_link],
                 ports={port: net_link})
           .tile(f"shred{name}", "shred", ins=[net_link],
                 outs=[store_link], net_ins=[net_link],
                 turbine=dict(identity=pk.hex(), fanout=2, port=0,
                              slots_per_epoch=32, stakes=stakes_cfg))
           .tile(f"store{name}", "store", ins=[store_link]))
        return tb

    tb = TopoBuilder(f"turbine{os.getpid()}", wksp_mb=16)
    follower(tb, "b", b_pk, port_b)
    follower(tb, "c", c_pk, port_c)
    spec = tb.build()

    # leader side, in-process: one slot of entries -> FEC set -> send each
    # shred ONLY to its computed turbine root
    entries = [entry_lib.Entry(1, bytes([i]) * 32, []) for i in range(4)]
    batch = entry_lib.serialize_batch(entries)
    fs = shred_lib.make_fec_set(
        batch, slot=7, parent_off=1, version=1, fec_set_idx=0,
        sign_fn=lambda root: ed.sign(lead_seed, root),
        data_cnt=32, code_cnt=32, slot_complete=True)
    dests = sort_dests([
        Dest(lead_pk, 1_000, "", 0),
        Dest(b_pk, 0, "127.0.0.1", port_b),
        Dest(c_pk, 0, "127.0.0.1", port_c)])
    sd = ShredDest(dests, _leaders_const(lead_pk), lead_pk)

    with TopoRun(spec) as run:
        run.wait_ready(timeout=420)
        sock = UdpSock(bind_port=0)
        raws = fs.data_shreds + fs.code_shreds
        shreds = [shred_lib.parse(r) for r in raws]
        roots = sd.compute_first(shreds)
        n_to_b = sum(1 for r in roots if dests[r].pubkey == b_pk)
        assert 0 < n_to_b < len(raws)  # both followers serve as roots
        pkts = [Pkt(raw, dests[r].addr) for raw, r in zip(raws, roots)]
        # send a couple of times: UDP on loopback is reliable but the
        # follower socks may still be draining their first burst
        deadline = time.monotonic() + 60
        done = False
        while time.monotonic() < deadline and not done:
            sock.send_burst(pkts)
            time.sleep(0.5)
            done = all(
                run.metrics(f"store{n}").get("complete_slot", 0) == 7
                for n in ("b", "c"))
        sock.close()
        mb = run.metrics("storeb")
        mc = run.metrics("storec")
        sb = run.metrics("shredb")
        sc = run.metrics("shredc")
        diag = {"storeb": mb, "storec": mc, "shredb": sb, "shredc": sc,
                "netb": run.metrics("netb"), "netc": run.metrics("netc")}
        print("TURBINE-DIAG", diag, flush=True)
        assert mb.get("complete_slot") == 7, diag
        assert mc.get("complete_slot") == 7, diag
        # the non-root follower got its shreds via retransmission
        assert sb.get("turbine_tx_cnt", 0) > 0, diag
        assert sc.get("turbine_tx_cnt", 0) > 0, diag
