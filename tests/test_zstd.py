"""From-scratch zstd decoder vs libzstd (ref: src/ballet/zstd/test_zstd.c —
theirs round-trips reference frames through fd_zstd; ours decodes frames
produced by libzstd (the `zstandard` package) across compression levels,
block types and stream shapes."""

import random

import pytest

zstandard = pytest.importorskip("zstandard")

from firedancer_tpu.ballet import zstd as fz


def _roundtrip(payload: bytes, level: int = 3, **kw):
    comp = zstandard.ZstdCompressor(level=level, **kw).compress(payload)
    out = fz.decompress(comp)
    assert out == payload, (len(out), len(payload), level)


def test_empty_and_tiny():
    _roundtrip(b"")
    _roundtrip(b"a")
    _roundtrip(b"abc" * 2)


def test_rle_heavy():
    _roundtrip(b"\x00" * 100_000)
    _roundtrip(b"ab" * 50_000)


def test_text_like_all_levels():
    words = [b"the", b"quick", b"brown", b"validator", b"verifies",
             b"signatures", b"on", b"tpu", b"hardware", b"fast"]
    rng = random.Random(1)
    payload = b" ".join(rng.choice(words) for _ in range(20_000))
    for level in (1, 3, 9, 19):
        _roundtrip(payload, level=level)


def test_incompressible_random():
    rng = random.Random(2)
    payload = bytes(rng.getrandbits(8) for _ in range(70_000))
    _roundtrip(payload)  # raw blocks path


def test_structured_binary():
    # account-data-like payload: repetitive 128B records with varying tails
    rng = random.Random(3)
    recs = []
    for i in range(2_000)	:
        recs.append(i.to_bytes(8, "little") + b"\x00" * 88
                    + bytes(rng.getrandbits(8) for _ in range(32)))
    _roundtrip(b"".join(recs), level=6)


def test_multi_frame_and_skippable():
    a = zstandard.ZstdCompressor(level=3).compress(b"frame-one " * 100)
    b = zstandard.ZstdCompressor(level=9).compress(b"frame-two " * 100)
    skip = (0x184D2A50).to_bytes(4, "little") + (5).to_bytes(4, "little") \
        + b"xxxxx"
    out = fz.decompress(a + skip + b)
    assert out == b"frame-one " * 100 + b"frame-two " * 100


def test_checksum_frame_parses():
    c = zstandard.ZstdCompressor(level=3)
    # write_checksum forces the content-checksum trailer
    comp = zstandard.ZstdCompressor(
        level=3, write_checksum=True).compress(b"checksummed " * 500)
    assert fz.decompress(comp) == b"checksummed " * 500


def test_long_match_window():
    # matches reaching far back across block boundaries
    rng = random.Random(4)
    base = bytes(rng.getrandbits(8) for _ in range(40_000))
    payload = base + b"filler" * 30_000 + base  # long-range repeat
    _roundtrip(payload, level=19)


def test_garbage_rejected():
    with pytest.raises(fz.ZstdError):
        fz.decompress(b"\x00\x01\x02\x03\x04\x05\x06\x07")
    with pytest.raises(fz.ZstdError):
        fz.decompress(b"(\xb5/\xfd" + b"\xff" * 4)  # magic + garbage
    good = zstandard.ZstdCompressor().compress(b"x" * 1000)
    with pytest.raises(fz.ZstdError):
        fz.decompress(good[:-3])  # truncated


def test_max_output_enforced():
    comp = zstandard.ZstdCompressor().compress(b"\x00" * 1_000_000)
    with pytest.raises(fz.ZstdError):
        fz.decompress(comp, max_output=1000)
