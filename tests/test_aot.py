"""AOT executable store (utils/aot.py): the warm-boot artifact behind the
multi-process verify topology (VERDICT r4 #2).  Mechanics are tested with a
tiny graph — the verify-graph integration is exercised by the bench's
measure_mp_vps and tests/test_topo_run.py."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from firedancer_tpu.utils import aot


def _tiny_compiled():
    def f(x, y):
        return (x * 2 + y).sum(axis=0)

    args = (jnp.zeros((8, 16), jnp.float32), jnp.ones((8, 16), jnp.float32))
    return jax.jit(f).lower(*args).compile(), args


def test_roundtrip(tmp_path):
    if jax.default_backend() == "cpu":
        pytest.skip("this jaxlib's XLA:CPU AOT loader rejects artifacts "
                    "across machine-feature sets; the TPU path is covered "
                    "by bench.py measure_mp_vps")
    compiled, args = _tiny_compiled()
    k = aot.key("tiny", 8, 16)
    path = aot.save(str(tmp_path), k, compiled)
    assert path.endswith(k)
    fn = aot.load(str(tmp_path), k)
    assert fn is not None
    got = np.asarray(fn(*args))
    want = np.asarray(compiled(*args))
    np.testing.assert_array_equal(got, want)


def test_key_varies_by_shape_and_backend():
    assert aot.key("verify", 2048, 256) != aot.key("verify", 1024, 256)
    assert jax.default_backend() in aot.key("verify", 2048, 256)


def test_load_miss_returns_none(tmp_path):
    assert aot.load(str(tmp_path), "nope.aotx") is None


def test_load_corrupt_returns_none(tmp_path):
    p = tmp_path / "bad.aotx"
    p.write_bytes(b"\x80\x04 definitely not a pickled executable")
    assert aot.load(str(tmp_path), "bad.aotx") is None


def test_verify_tile_aot_require_fails_loudly(tmp_path):
    """A verify tile told to boot AOT-only must die with a clear error on
    a store miss, not silently cold-compile for minutes."""
    from firedancer_tpu.disco.tiles import VerifyTile

    class Ctx:
        cfg = {"batch": 16, "msg_maxlen": 256, "aot_dir": str(tmp_path),
               "aot_require": True}

    with pytest.raises(RuntimeError, match="refusing to cold-compile"):
        VerifyTile().init(Ctx())
