"""AOT executable store (utils/aot.py): the warm-boot artifact behind the
multi-process verify topology (VERDICT r4 #2).  Mechanics are tested with a
tiny graph — the verify-graph integration is exercised by the bench's
measure_mp_vps and tests/test_topo_run.py."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from firedancer_tpu.utils import aot


def _tiny_compiled():
    def f(x, y):
        return (x * 2 + y).sum(axis=0)

    args = (jnp.zeros((8, 16), jnp.float32), jnp.ones((8, 16), jnp.float32))
    return jax.jit(f).lower(*args).compile(), args


def test_roundtrip(tmp_path):
    if jax.default_backend() == "cpu":
        pytest.skip("this jaxlib's XLA:CPU AOT loader rejects artifacts "
                    "across machine-feature sets; the TPU path is covered "
                    "by bench.py measure_mp_vps")
    compiled, args = _tiny_compiled()
    k = aot.key("tiny", 8, 16)
    path = aot.save(str(tmp_path), k, compiled)
    assert path.endswith(k)
    fn = aot.load(str(tmp_path), k)
    assert fn is not None
    got = np.asarray(fn(*args))
    want = np.asarray(compiled(*args))
    np.testing.assert_array_equal(got, want)


def test_key_varies_by_shape_and_backend():
    assert aot.key("verify", 2048, 256) != aot.key("verify", 1024, 256)
    assert jax.default_backend() in aot.key("verify", 2048, 256)


def test_load_miss_returns_none(tmp_path):
    assert aot.load(str(tmp_path), "nope.aotx") is None


def test_load_corrupt_returns_none(tmp_path):
    p = tmp_path / "bad.aotx"
    p.write_bytes(b"\x80\x04 definitely not a pickled executable")
    assert aot.load(str(tmp_path), "bad.aotx") is None


def _keyed(monkeypatch, tmp_path):
    """Isolate the HMAC master key under tmp_path (no ~/.cache writes)."""
    monkeypatch.setenv(aot._KEY_ENV, str(tmp_path / "master.key"))


def _fake_artifact(tmp_path, k="fake.aotx", blob=b"not-a-real-executable"):
    """A correctly-framed artifact: MAGIC | hmac(store_key, blob) | blob.
    The blob is not a valid pickle payload, but authentication runs FIRST
    — these tests only care which frames reach the unpickler at all."""
    import hashlib
    import hmac

    tag = hmac.new(aot._store_key(str(tmp_path)), blob,
                   hashlib.sha256).digest()
    (tmp_path / k).write_bytes(aot._MAGIC + tag + blob)
    return k


def test_load_refuses_unsigned_legacy_pickle(monkeypatch, tmp_path):
    """A raw pickle (pre-HMAC store, or attacker-planted) is refused
    without ever reaching pickle.loads — unpickling hostile bytes is code
    execution."""
    import pickle

    _keyed(monkeypatch, tmp_path)

    class Boom:
        def __reduce__(self):
            return (pytest.fail, ("unsigned pickle was deserialized!",))

    (tmp_path / "legacy.aotx").write_bytes(pickle.dumps(Boom()))
    assert aot.load(str(tmp_path), "legacy.aotx") is None


def test_load_refuses_tampered_blob(monkeypatch, tmp_path):
    import pickle

    _keyed(monkeypatch, tmp_path)

    class Boom:
        def __reduce__(self):
            return (pytest.fail, ("tampered pickle was deserialized!",))

    k = _fake_artifact(tmp_path, blob=pickle.dumps(Boom()))
    raw = bytearray((tmp_path / k).read_bytes())
    raw[-1] ^= 0x01                          # flip one payload bit
    (tmp_path / k).write_bytes(bytes(raw))
    assert aot.load(str(tmp_path), k) is None
    raw = bytearray((tmp_path / k).read_bytes())
    raw[-1] ^= 0x01                          # restore payload ...
    raw[len(aot._MAGIC)] ^= 0x01             # ... corrupt the tag instead
    (tmp_path / k).write_bytes(bytes(raw))
    assert aot.load(str(tmp_path), k) is None


def test_well_signed_frame_reaches_unpickler(monkeypatch, tmp_path):
    """The positive control for the two refusal tests: an authentic frame
    gets PAST the HMAC gate (then fails pickle/deserialize gracefully)."""
    _keyed(monkeypatch, tmp_path)
    k = _fake_artifact(tmp_path)             # authentic tag, garbage blob
    assert aot.load(str(tmp_path), k) is None  # graceful: no exception


def test_store_key_binds_store_path(monkeypatch, tmp_path):
    """An artifact copied between stores re-verifies only under the same
    directory: the store realpath is mixed into the per-store key."""
    _keyed(monkeypatch, tmp_path)
    a, b = tmp_path / "a", tmp_path / "b"
    a.mkdir(), b.mkdir()
    assert aot._store_key(str(a)) != aot._store_key(str(b))
    k = _fake_artifact(a)
    (b / k).write_bytes((a / k).read_bytes())
    import hashlib
    import hmac

    raw = (b / k).read_bytes()
    hlen = len(aot._MAGIC) + 32
    tag, blob = raw[len(aot._MAGIC):hlen], raw[hlen:]
    assert not hmac.compare_digest(
        tag, hmac.new(aot._store_key(str(b)), blob,
                      hashlib.sha256).digest())


def test_master_key_created_0600_and_stable(monkeypatch, tmp_path):
    import os
    import stat

    _keyed(monkeypatch, tmp_path)
    k1 = aot._master_key()
    k2 = aot._master_key()
    assert k1 == k2 and len(k1) >= 32
    mode = os.stat(tmp_path / "master.key").st_mode
    assert stat.S_IMODE(mode) == 0o600


def test_verify_tile_aot_require_fails_loudly(tmp_path):
    """A verify tile told to boot AOT-only must die with a clear error on
    a store miss, not silently cold-compile for minutes."""
    from firedancer_tpu.disco.tiles import VerifyTile

    class Ctx:
        cfg = {"batch": 16, "msg_maxlen": 256, "aot_dir": str(tmp_path),
               "aot_require": True}

    with pytest.raises(RuntimeError, match="refusing to cold-compile"):
        VerifyTile().init(Ctx())
