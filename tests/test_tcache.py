"""Dedup cache semantics (ref src/tango/tcache/fd_tcache.c)."""

from firedancer_tpu.tango.tcache import TCache


def test_duplicate_detection():
    tc = TCache(4)
    assert not tc.insert(11)
    assert tc.insert(11)
    assert tc.query(11)
    assert not tc.query(22)


def test_eviction_order():
    tc = TCache(3)
    for t in (1, 2, 3):
        tc.insert(t)
    tc.insert(4)  # evicts 1
    assert not tc.query(1)
    assert all(tc.query(t) for t in (2, 3, 4))


def test_zero_tag_never_cached():
    tc = TCache(2)
    assert not tc.insert(0)
    assert not tc.insert(0)
    assert not tc.query(0)


def test_reset():
    tc = TCache(2)
    tc.insert(5)
    tc.reset()
    assert not tc.query(5)
