"""Full leader pipeline: source -> verify -> dedup -> pack -> bank -> poh ->
shred (keyguard-signed merkle FEC sets) -> store (blockstore recovery).

The multi-process analogue of the reference's fddev single-node cluster
(SURVEY.md §3.3): asserts executed txns flow into PoH entries, get shredded
into signed FEC sets, and reassemble into complete slots in the blockstore —
with PoH chain integrity checked end-to-end on the stored entries."""

import os
import time

from firedancer_tpu.disco import keyguard
from firedancer_tpu.disco.run import TopoRun
from firedancer_tpu.disco.topo import TopoBuilder
from firedancer_tpu.flamenco import genesis as gen_mod
from firedancer_tpu.ops import ed25519 as ed


def _wait(pred, timeout_s, what=""):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise TimeoutError(f"timed out waiting for {what}")


def test_leader_pipeline_end_to_end(tmp_path):
    n = 16
    seeds = [i.to_bytes(32, "little") for i in range(201, 205)]
    faucet_pk = ed.keypair_from_seed((99).to_bytes(32, "little"))[0]
    g = gen_mod.create(faucet_pk, creation_time=1_700_000_000,
                       slots_per_epoch=32)
    from firedancer_tpu.flamenco.types import Account
    for s in seeds:
        g.accounts[ed.keypair_from_seed(s)[0]] = Account(
            lamports=1_000_000_000)
    gpath = str(tmp_path / "genesis.bin")
    g.write(gpath)

    id_seed = (7).to_bytes(32, "little")
    id_pub = ed.keypair_from_seed(id_seed)[0]
    kpath = str(tmp_path / "identity.json")
    keyguard.keypair_write(kpath, id_seed, id_pub)

    spec = (
        TopoBuilder(f"leader{os.getpid()}", wksp_mb=32)
        .link("src_verify", depth=128, mtu=1280)
        .link("verify_dedup", depth=128, mtu=1280)
        .link("dedup_pack", depth=128, mtu=1280)
        .link("pack_bank", depth=128, mtu=1280)
        .link("bank_poh", depth=128, mtu=1280)
        .link("poh_shred", depth=256, mtu=2048)
        .link("shred_sign", depth=16, mtu=128)
        .link("sign_shred", depth=16, mtu=128)
        .link("shred_store", depth=512, mtu=1280)
        .tile("source", "source", outs=["src_verify"], count=n,
              executable=True, seeds=[s.hex() for s in seeds],
              blockhash=g.genesis_hash().hex())
        .tile("verify", "verify", ins=["src_verify"], outs=["verify_dedup"],
              batch=16, msg_maxlen=256, flush_age_ns=50_000_000)
        .tile("dedup", "dedup", ins=["verify_dedup"], outs=["dedup_pack"])
        .tile("pack", "pack", ins=["dedup_pack"], outs=["pack_bank"])
        .tile("bank", "bank", ins=["pack_bank"], outs=["bank_poh"],
              genesis_path=gpath, slot_txn_max=8)
        .tile("poh", "poh", ins=["bank_poh"], outs=["poh_shred"],
              hashes_per_tick=4, ticks_per_slot=4)
        .tile("shred", "shred", ins=["poh_shred"],
              outs=["shred_sign", "shred_store"])
        .tile("sign", "sign", ins=["shred_sign"], outs=["sign_shred"],
              key_path=kpath)
        .tile("store", "store", ins=["shred_store"])
        .build()
    )
    with TopoRun(spec) as run:
        run.wait_ready(timeout=420)
        _wait(lambda: run.metrics("poh")["mixin_cnt"] >= n, 240,
              f"{n} txns mixed into poh")
        _wait(lambda: run.metrics("store")["complete_slot"] >= 1, 120,
              "a complete slot in the blockstore")
        m_shred = run.metrics("shred")
        m_sign = run.metrics("sign")
        m_store = run.metrics("store")
        assert m_shred["fec_set_cnt"] >= 1
        assert m_sign["sign_cnt"] == m_shred["fec_set_cnt"]
        assert m_sign["refuse_cnt"] == 0
        assert m_store["parse_fail_cnt"] == 0
        assert m_store["shred_store_cnt"] >= 64  # one 32:32 FEC set
        assert run.poll() is None


def test_leader_bench_chain_reverifies(tmp_path):
    """Round 14 leader lane conformance: the leader-bench topology
    (source -> verify -> leader_pack -> poh_dev -> sink) must produce an
    entry stream whose PoH chain re-verifies bit-exactly from the seed —
    host golden (entry.verify_chain, which recomputes every mixin from
    the entries' own txns) AND the batched device ladder
    (poh.verify_entries_fit)."""
    import numpy as np

    from firedancer_tpu.app import config as app_config
    from firedancer_tpu.ballet import entry as entry_lib
    from firedancer_tpu.ballet import poh as poh_lib

    cap = str(tmp_path / "entries.bin")
    cfg = app_config.load(None)
    cfg["topology"] = "leader-bench"
    cfg["development"]["source_count"] = 0        # unbounded source
    cfg["leader"].update(hashes_per_tick=4, ticks_per_slot=4,
                         mb_per_tick=3, mixin_txn_max=8, capture_path=cap)
    cfg["tiles"]["verify"].update(batch=16, flush_age_ns=50_000_000)
    spec = app_config.build_topology(cfg)

    with TopoRun(spec) as run:
        run.wait_ready(timeout=560)
        _wait(lambda: run.metrics("poh_dev")["mixin_cnt"] >= 4, 240,
              "4 microblock mixins in the chain")
        _wait(lambda: run.metrics("poh_dev")["recheck_ok_cnt"] >= 8, 60,
              "recheck lanes retiring")
        pd = run.metrics("poh_dev")
        assert pd["recheck_fail_cnt"] == 0
        assert pd["parse_fail_cnt"] == 0
        assert run.metrics("leader_pack")["parse_fail_cnt"] == 0
        assert run.poll() is None

    # offline re-verification from the capture (sig | len | payload)
    entries = []
    buf = open(cap, "rb").read()
    off = 0
    while off + 12 <= len(buf):
        ln = int.from_bytes(buf[off + 8:off + 12], "little")
        e, _ = entry_lib.Entry.deserialize(buf[off + 12:off + 12 + ln])
        entries.append(e)
        off += 12 + ln
    assert len(entries) >= 16
    assert any(not e.is_tick for e in entries)
    start = bytes(32)                             # default seed_hash
    assert entry_lib.verify_chain(start, entries)

    # device ladder over the same stream: one batch, bucketed max_hashes
    n = len(entries)
    starts = np.zeros((n, 32), np.uint8)
    nums = np.zeros((n,), np.int32)
    mixins = np.zeros((n, 32), np.uint8)
    has = np.zeros((n,), np.bool_)
    prev = start
    for i, e in enumerate(entries):
        starts[i] = np.frombuffer(prev, np.uint8)
        nums[i] = e.num_hashes
        if not e.is_tick:
            mixins[i] = np.frombuffer(entry_lib.txn_mixin(e.txns), np.uint8)
            has[i] = True
        prev = e.hash
    got = np.asarray(poh_lib.verify_entries_fit(
        starts, nums, mixins, has, max_hashes=4))
    for i, e in enumerate(entries):
        assert bytes(got[i]) == e.hash


def test_store_reassembles_verifiable_entries(tmp_path):
    """Single-process version: shred a slot of entries through the real
    FEC path and verify blockstore reassembly + PoH chain integrity."""
    from firedancer_tpu.ballet import entry as entry_lib
    from firedancer_tpu.ballet import shred as shred_lib
    from firedancer_tpu.flamenco.blockstore import Blockstore

    id_seed = (7).to_bytes(32, "little")
    h = bytes(32)
    entries = []
    for i in range(5):
        h = entry_lib.next_hash(h, 3, None)
        entries.append(entry_lib.Entry(3, h, []))
    batch = entry_lib.serialize_batch(entries)
    fs = shred_lib.make_fec_set(
        batch, slot=3, parent_off=1, version=1, fec_set_idx=0,
        sign_fn=lambda root: ed.sign(id_seed, root),
        data_cnt=32, code_cnt=32, slot_complete=True)

    bs = Blockstore()
    # drop 10 data shreds: erasure recovery must reconstruct them
    for raw in fs.data_shreds[10:] + fs.code_shreds:
        bs.insert_shred(raw)
    assert bs.slot_complete(3)
    got = bs.slot_entries(3)
    assert [e.hash for e in got] == [e.hash for e in entries]
    assert entry_lib.verify_chain(bytes(32), got)
