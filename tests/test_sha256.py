"""SHA-256 / PoH / bmtree vs hashlib golden model (the cocotb-style
golden-model pattern, SURVEY.md §4.10)."""

import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from firedancer_tpu.ops.sha256 import sha256, sha256_fixed32, sha256_fixed64


def _golden(msgs, lens):
    return np.stack(
        [
            np.frombuffer(hashlib.sha256(bytes(m[:l])).digest(), dtype=np.uint8)
            for m, l in zip(msgs, lens)
        ]
    )


def test_known_vectors():
    msgs = np.zeros((3, 64), dtype=np.uint8)
    lens = np.array([0, 3, 56], dtype=np.int32)
    msgs[1, :3] = list(b"abc")
    msgs[2, :56] = list(b"abcdbcdecdefdefgefghfghighijhijkijkljklmklmnlmnomnopnopq")
    out = np.asarray(jax.jit(sha256)(jnp.asarray(msgs), jnp.asarray(lens)))
    np.testing.assert_array_equal(out, _golden(msgs, lens))


def test_random_lengths():
    rng = np.random.default_rng(7)
    batch, maxlen = 64, 200
    msgs = rng.integers(0, 256, (batch, maxlen), dtype=np.uint8)
    lens = rng.integers(0, maxlen + 1, (batch,), dtype=np.int32)
    out = np.asarray(sha256(jnp.asarray(msgs), jnp.asarray(lens)))
    np.testing.assert_array_equal(out, _golden(msgs, lens))


def test_fixed_shapes():
    rng = np.random.default_rng(8)
    m32 = rng.integers(0, 256, (16, 32), dtype=np.uint8)
    m64 = rng.integers(0, 256, (16, 64), dtype=np.uint8)
    np.testing.assert_array_equal(
        np.asarray(sha256_fixed32(jnp.asarray(m32))),
        _golden(m32, [32] * 16),
    )
    np.testing.assert_array_equal(
        np.asarray(sha256_fixed64(jnp.asarray(m64))),
        _golden(m64, [64] * 16),
    )


class TestPoh:
    def test_append_matches_serial(self):
        from firedancer_tpu.ballet import poh

        rng = np.random.default_rng(9)
        st = rng.integers(0, 256, (4, 32), dtype=np.uint8)
        out = np.asarray(poh.append(jnp.asarray(st), 5))
        for lane in range(4):
            h = bytes(st[lane])
            for _ in range(5):
                h = hashlib.sha256(h).digest()
            assert bytes(out[lane]) == h

    def test_mixin(self):
        from firedancer_tpu.ballet import poh

        rng = np.random.default_rng(10)
        st = rng.integers(0, 256, (3, 32), dtype=np.uint8)
        mx = rng.integers(0, 256, (3, 32), dtype=np.uint8)
        out = np.asarray(poh.mixin(jnp.asarray(st), jnp.asarray(mx)))
        for lane in range(3):
            assert bytes(out[lane]) == hashlib.sha256(
                bytes(st[lane]) + bytes(mx[lane])
            ).digest()

    def test_entry_verify(self):
        from firedancer_tpu.ballet import poh

        rng = np.random.default_rng(11)
        batch, max_hashes = 6, 8
        starts = rng.integers(0, 256, (batch, 32), dtype=np.uint8)
        nums = rng.integers(1, max_hashes + 1, (batch,), dtype=np.int32)
        mixins = rng.integers(0, 256, (batch, 32), dtype=np.uint8)
        has_mix = rng.integers(0, 2, (batch,)).astype(bool)
        # golden ends
        ends = np.zeros((batch, 32), dtype=np.uint8)
        for i in range(batch):
            h = bytes(starts[i])
            for _ in range(int(nums[i]) - 1):
                h = hashlib.sha256(h).digest()
            if has_mix[i]:
                h = hashlib.sha256(h + bytes(mixins[i])).digest()
            else:
                h = hashlib.sha256(h).digest()
            ends[i] = np.frombuffer(h, dtype=np.uint8)
        ok = np.asarray(
            poh.entry_verify(
                jnp.asarray(starts), jnp.asarray(nums), jnp.asarray(mixins),
                jnp.asarray(has_mix), jnp.asarray(ends), max_hashes,
            )
        )
        assert ok.all()
        # corrupt one end hash
        ends[2, 0] ^= 1
        ok = np.asarray(
            poh.entry_verify(
                jnp.asarray(starts), jnp.asarray(nums), jnp.asarray(mixins),
                jnp.asarray(has_mix), jnp.asarray(ends), max_hashes,
            )
        )
        assert not ok[2] and ok[[0, 1, 3, 4, 5]].all()


class TestBmtree:
    @pytest.mark.parametrize("n,node_sz", [(1, 32), (2, 32), (5, 32), (8, 20), (11, 20)])
    def test_commit_matches_numpy(self, n, node_sz):
        from firedancer_tpu.ballet import bmtree

        rng = np.random.default_rng(n)
        maxlen = 40
        data = rng.integers(0, 256, (n, maxlen), dtype=np.uint8)
        lens = rng.integers(1, maxlen + 1, (n,), dtype=np.int32)
        root = np.asarray(
            bmtree.commit(jnp.asarray(data), jnp.asarray(lens), node_sz)
        )
        leaves = [bytes(data[i][: lens[i]]) for i in range(n)]
        levels = bmtree.np_tree(leaves, node_sz)
        assert bytes(root) == levels[-1][0]

    def test_proofs(self):
        from firedancer_tpu.ballet import bmtree

        rng = np.random.default_rng(3)
        leaves = [bytes(rng.integers(0, 256, (30,), dtype=np.uint8)) for _ in range(7)]
        levels = bmtree.np_tree(leaves, 20)
        root = levels[-1][0]
        for i, leaf in enumerate(leaves):
            proof = bmtree.np_proof(levels, i)
            assert bmtree.np_verify_proof(leaf, i, proof, root, 20)
            assert not bmtree.np_verify_proof(leaf + b"x", i, proof, root, 20)
