"""AF_XDP XSK tier end-to-end (VERDICT r4 #6): umem/ring setup, the
assembled redirect program loaded into the REAL kernel (maps created via
bpf(2), program attached with an XDP bpf_link), and packets flowing
veth -> XDP redirect -> XSK rings -> recv_burst inside a network
namespace.  Skips cleanly where the environment lacks AF_XDP, bpf(2) or
netns privileges."""

import ctypes
import multiprocessing as mp
import os
import socket
import struct
import subprocess
import time

import pytest

from firedancer_tpu.waltz.xsk import AF_XDP, XskSock, XskUnavailable

NS = "fdtpu-xsk-test"
HOST_IP, NS_IP, PORT = "10.77.31.1", "10.77.31.2", 9123


def _have_af_xdp() -> bool:
    try:
        s = socket.socket(AF_XDP, socket.SOCK_RAW, 0)
        s.close()
        return True
    except OSError:
        return False


def _ip(*args) -> bool:
    return subprocess.run(("ip",) + args, capture_output=True).returncode == 0


def test_xsk_socket_setup_and_rings():
    if not _have_af_xdp():
        pytest.skip("no AF_XDP in this kernel/container")
    xs = XskSock("lo", frames=64)
    try:
        assert xs.recv_burst() == []      # no traffic; rings operational
    finally:
        xs.close()


def _ns_receiver(conn):
    """Child: enter the netns, bind an XSK to the veth, install the
    redirect program, then report every received payload."""
    try:
        libc = ctypes.CDLL(None, use_errno=True)
        fd = os.open(f"/var/run/netns/{NS}", os.O_RDONLY)
        if libc.setns(fd, 0x40000000) != 0:  # CLONE_NEWNET
            raise OSError(ctypes.get_errno(), "setns")
        os.close(fd)
        subprocess.run(("ip", "link", "set", "lo", "up"), check=True)

        from firedancer_tpu.waltz.ebpf import KernelXdp
        xs = XskSock("vxn", queue=0, frames=64)
        kx = KernelXdp()
        kx.install_redirect("vxn", [(NS_IP, PORT)], {0: xs.fileno()})
        conn.send(("ready", None))
        got = []
        deadline = time.monotonic() + 8.0
        while time.monotonic() < deadline and len(got) < 3:
            for pkt in xs.recv_burst():
                got.append((pkt.payload, pkt.addr[0]))
            time.sleep(0.01)
        conn.send(("got", got))
    except Exception as e:
        conn.send(("error", f"{type(e).__name__}: {e}"))


def test_packets_flow_veth_to_xsk_in_netns():
    if not _have_af_xdp():
        pytest.skip("no AF_XDP in this kernel/container")
    if os.geteuid() != 0 or not _ip("netns", "add", NS):
        pytest.skip("netns privileges unavailable")
    try:
        assert _ip("link", "add", "vxh", "type", "veth",
                   "peer", "name", "vxn")
        assert _ip("link", "set", "vxn", "netns", NS)
        assert _ip("addr", "add", f"{HOST_IP}/24", "dev", "vxh")
        assert _ip("link", "set", "vxh", "up")
        assert _ip("-n", NS, "addr", "add", f"{NS_IP}/24", "dev", "vxn")
        assert _ip("-n", NS, "link", "set", "vxn", "up")

        ctx = mp.get_context("fork")   # inherit module state, then setns
        parent, child = ctx.Pipe()
        p = ctx.Process(target=_ns_receiver, args=(child,), daemon=True)
        p.start()
        kind, detail = parent.recv() if parent.poll(20) else ("timeout", "")
        if kind == "error" and (
                "Operation not permitted" in detail
                or "XskUnavailable" in detail):
            pytest.skip(f"kernel refused XDP/XSK in netns: {detail}")
        assert kind == "ready", (kind, detail)

        tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        tx.bind((HOST_IP, 0))
        for i in range(20):              # redundancy over the veth
            for m in (b"fdtpu-xsk-0", b"fdtpu-xsk-1", b"fdtpu-xsk-2"):
                tx.sendto(m, (NS_IP, PORT))
            time.sleep(0.05)
            if parent.poll(0):
                break
        kind, got = parent.recv() if parent.poll(12) else ("timeout", [])
        tx.close()
        p.join(5)
        assert kind == "got", (kind, got)
        payloads = {g[0] for g in got}
        assert {b"fdtpu-xsk-0", b"fdtpu-xsk-1", b"fdtpu-xsk-2"} <= payloads
        assert all(g[1] == HOST_IP for g in got)
    finally:
        subprocess.run(("ip", "link", "del", "vxh"), capture_output=True)
        subprocess.run(("ip", "netns", "del", NS), capture_output=True)


def _ns_tile_receiver(conn):
    """Child: inside the netns, run the REAL NetTile (backend=xsk) and
    QuicTile vtables over the XSK data path — NIC -> XSK -> net tile ->
    quic tile, stub mux plumbing."""
    try:
        libc = ctypes.CDLL(None, use_errno=True)
        fd = os.open(f"/var/run/netns/{NS}", os.O_RDONLY)
        if libc.setns(fd, 0x40000000) != 0:
            raise OSError(ctypes.get_errno(), "setns")
        os.close(fd)
        subprocess.run(("ip", "link", "set", "lo", "up"), check=True)

        from firedancer_tpu.disco.tiles import NetTile, QuicTile

        class Metrics:
            def set(self, *a): pass

            def add(self, *a): pass

        published = []

        class NetCtx:
            cfg = {"backend": "xsk", "ports": {PORT: "net_quic"},
                   "xsk": {"ifname": "vxn", "ip": NS_IP, "queue": 0}}
            metrics = Metrics()

            def out_index(self, link): return 0

            def publish(self, payload, sig=0, out=0):
                published.append(bytes(payload))

        class QuicCtx:
            cfg = {}
            metrics = Metrics()
            txns = []

            def publish(self, payload, sig=0):
                QuicCtx.txns.append(bytes(payload))

        net, quic = NetTile(), QuicTile()
        nctx, qctx = NetCtx(), QuicCtx()
        net.init(nctx)
        quic.init(qctx)
        conn.send(("ready", None))
        deadline = time.monotonic() + 8.0
        while time.monotonic() < deadline and len(QuicCtx.txns) < 1:
            net.after_credit(nctx)
            while published:
                quic.on_frag(qctx, 0, None, published.pop(0))
            time.sleep(0.01)
        net.fini(nctx)
        conn.send(("got", QuicCtx.txns))
    except Exception as e:
        conn.send(("error", f"{type(e).__name__}: {e}"))


def test_xsk_feeds_net_and_quic_tiles_in_netns():
    """VERDICT r4 #6's done-bar, literally: packets flow NIC -> XSK ->
    quic tile in a netns.  The datagram is a wire-valid txn so the quic
    tile's reasm republishes it."""
    if not _have_af_xdp():
        pytest.skip("no AF_XDP in this kernel/container")
    if os.geteuid() != 0 or not _ip("netns", "add", NS):
        pytest.skip("netns privileges unavailable")
    try:
        assert _ip("link", "add", "vxh", "type", "veth",
                   "peer", "name", "vxn")
        assert _ip("link", "set", "vxn", "netns", NS)
        assert _ip("addr", "add", f"{HOST_IP}/24", "dev", "vxh")
        assert _ip("link", "set", "vxh", "up")
        assert _ip("-n", NS, "addr", "add", f"{NS_IP}/24", "dev", "vxn")
        assert _ip("-n", NS, "link", "set", "vxn", "up")

        # a wire-valid signed txn as the datagram
        from firedancer_tpu.ballet import txn as txn_lib
        from firedancer_tpu.ops import ed25519 as ed
        seed = (3).to_bytes(32, "little")
        pub, _, _ = ed.keypair_from_seed(seed)
        msg = txn_lib.build_unsigned(
            [pub], bytes(32), [(1, bytes([0]), b"xsk")],
            extra_accounts=[bytes([9]) * 32])
        payload = txn_lib.assemble([ed.sign(seed, msg)], msg)

        ctx = mp.get_context("fork")
        parent, child = ctx.Pipe()
        p = ctx.Process(target=_ns_tile_receiver, args=(child,), daemon=True)
        p.start()
        kind, detail = parent.recv() if parent.poll(30) else ("timeout", "")
        if kind == "error":
            pytest.skip(f"kernel refused XSK tile boot: {detail}")
        assert kind == "ready"

        tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        tx.bind((HOST_IP, 0))
        for _ in range(40):
            tx.sendto(payload, (NS_IP, PORT))
            time.sleep(0.05)
            if parent.poll(0):
                break
        kind, txns = parent.recv() if parent.poll(12) else ("timeout", [])
        tx.close()
        p.join(5)
        assert kind == "got" and payload in txns, (kind, len(txns))
    finally:
        subprocess.run(("ip", "link", "del", "vxh"), capture_output=True)
        subprocess.run(("ip", "netns", "del", NS), capture_output=True)
