"""Round-11 native host-path fast lane: one-pass C submit/harvest.

Falsifiable contracts, all CPU, no device verifier (verdicts injected):

  1. BIT IDENTITY — the C kernel (fd_hostpath_submit_rows +
     fd_hostpath_finish_rows), the NumPy fallback, and an independent
     per-txn reference model produce byte-identical wires, identical
     survivor order, and identical metrics across equal-length, ragged,
     all-dup, all-fail, zero-pass, intra-frag-dup, and dead-lane frags.
  2. PACKED EGRESS IDENTITY — egress_packed=True ships the SAME bytes
     (PackedVerdicts.wires()) the legacy per-txn list carries, and the
     DedupTile packed consumer republishes exactly those wires with the
     per-txn path's tags and dup verdicts.
  3. NO-.so FALLBACK — with the native library unloadable the pipeline
     imports, runs, and matches the reference model (pure-Python tcache).
  4. RAGGED MEMORY — the fallback arena build stages at most ~_NP_PAD_CAP
     padded bytes at a time: one long-tail row must not inflate the
     harvest footprint to k * Lmax, and a tiny pad cap is bit-identical.
"""

import tracemalloc

import numpy as np
import pytest

from firedancer_tpu.disco import pipeline as pl
from firedancer_tpu.disco.pipeline import PackedVerdicts, VerifyPipeline
from firedancer_tpu.tango.ring import PACKED_ROW_EXTRA, packed_row_ml

ML = packed_row_ml(256)          # 284
STRIDE = ML + PACKED_ROW_EXTRA   # 384


class _VerdictFn:
    """Packed verifier double: replays a scripted verdict per dispatch
    (row i of dispatch j passes iff script[j][i])."""

    def __init__(self, script):
        self.script = list(script)
        self.calls = 0

    def __call__(self, m, ln, s, p):
        return np.ones(m.shape[0], bool)

    def dispatch_blob(self, blob, maxlen=None):
        ok = np.zeros(blob.shape[0], bool)
        want = self.script[self.calls]
        self.calls += 1
        ok[:len(want)] = want
        return ok


def _mk_rows(n, lens, seed, nrows=None, dup_pairs=(), dead=()):
    """Packed rows with deterministic payload/sig bytes; dup_pairs=(a,b)
    copies a's tag onto b, dead=i zeroes i's tag (padding lane)."""
    rng = np.random.default_rng(seed)
    nrows = n if nrows is None else nrows
    rows = np.zeros((nrows, STRIDE), np.uint8)
    for i in range(n):
        L = int(lens[i])
        rows[i, :L] = rng.integers(0, 256, L, dtype=np.uint8)
        rows[i, ML:ML + 64] = rng.integers(0, 256, 64, dtype=np.uint8)
        # distinct nonzero tags by default (16-bit lane id, LE)
        rows[i, ML:ML + 2] = [(i + 1) & 0xFF, (i + 1) >> 8]
        rows[i, ML + 96:ML + 100] = np.frombuffer(
            L.to_bytes(4, "little"), np.uint8)
    for a, b in dup_pairs:
        rows[b, ML:ML + 8] = rows[a, ML:ML + 8]
    for i in dead:
        rows[i, ML:ML + 8] = 0
    return rows


def _ref_run(frags):
    """Independent reference: the pre-round-11 per-txn assembly and exact
    FD_TCACHE semantics (query-only at submit, insert on pass) over a
    set model — valid while nothing evicts (tag count << depth)."""
    seen = set()
    wires, m = [], dict(txns_in=0, dedup_drop=0, verify_fail=0,
                        verify_pass=0)
    for rows, n, ok in frags:
        tags = [int.from_bytes(bytes(rows[i, ML:ML + 8]), "little")
                for i in range(n)]
        dup = [t != 0 and t in seen for t in tags]
        m["txns_in"] += n
        m["dedup_drop"] += sum(dup)
        out = []
        for i in range(n):
            if tags[i] == 0 or dup[i]:
                continue
            if not ok[i]:
                m["verify_fail"] += 1
                continue
            if tags[i] in seen:          # intra-frag dup (insert-time)
                m["dedup_drop"] += 1
                continue
            seen.add(tags[i])
            m["verify_pass"] += 1
            L = min(max(int.from_bytes(
                bytes(rows[i, ML + 96:ML + 100]), "little", signed=True),
                0), ML)
            out.append(b"\x01" + bytes(rows[i, ML:ML + 64])
                       + bytes(rows[i, :L]))
        wires.append(out)
    return wires, m


def _pipe_run(frags, native, egress_packed=False, allow_fallback=False):
    fn = _VerdictFn([ok for _, _, ok in frags])
    pipe = VerifyPipeline(fn, buckets=[(max(r.shape[0] for r, _, _ in
                                            frags), ML)],
                          tcache_depth=1 << 12, max_inflight=0,
                          native_hostpath=native,
                          egress_packed=egress_packed)
    if native and pipe._hp is None and not allow_fallback:
        pytest.skip("native hostpath library unavailable")
    wires = []
    for rows, n, _ in frags:
        passed = pipe.submit_packed_rows(rows, n=n)
        if egress_packed:
            out = []
            for pv in passed:
                assert isinstance(pv, PackedVerdicts)
                ws = pv.wires()
                assert len(ws) == pv.k == len(pv.tags)
                # tags must be each wire's sig low-64 (what dedup keys on)
                for w, t in zip(ws, pv.tags):
                    assert int.from_bytes(w[1:9], "little") == int(t)
                out += ws
            wires.append(out)
        else:
            wires.append([w for w, _ in passed])
    s = dict(pipe.metrics.snapshot())
    return wires, {k: s[k] for k in ("txns_in", "dedup_drop",
                                     "verify_fail", "verify_pass")}


def _sweep_frags():
    """The property sweep: one frag set exercising every shape class."""
    n = 24
    rng = np.random.default_rng(11)
    eq = _mk_rows(n, [100] * n, seed=1)
    ragged = _mk_rows(n, rng.integers(0, ML + 1, n), seed=2)
    mixed = _mk_rows(n, rng.integers(1, ML, n), seed=3,
                     dup_pairs=((0, 5), (1, 9)), dead=(7,))
    padded = _mk_rows(10, [64] * 10, seed=4, nrows=n)
    ok_all = np.ones(n, bool)
    ok_none = np.zeros(n, bool)
    ok_mix = rng.random(n) < 0.7
    return [
        (eq, n, ok_all),                 # equal-length, all pass
        (ragged, n, ok_mix),             # ragged, mixed verdicts
        (ragged, n, ok_all),             # resubmit: all-dup frag
        (mixed, n, ok_mix),              # intra-frag dups + dead lane
        (eq, n, ok_none),                # all-fail... but eq tags are
        (padded, 10, ok_all),            # n < nrows zero padding
        (padded, 10, ok_none),           # zero-pass resubmit (all dup)
    ]


def test_bit_identity_native_vs_fallback_vs_reference():
    """Contract 1: three independent implementations, one answer."""
    frags = _sweep_frags()
    ref_w, ref_m = _ref_run(frags)
    nat_w, nat_m = _pipe_run(frags, native=True)
    np_w, np_m = _pipe_run(frags, native=False)
    assert nat_w == ref_w
    assert np_w == ref_w
    assert nat_m == ref_m
    assert np_m == ref_m


@pytest.mark.parametrize("native", [True, False])
def test_packed_egress_bit_identity(native):
    """Contract 2 (pipeline half): PackedVerdicts carries the exact bytes
    the legacy per-txn egress would, same order, same tags."""
    frags = _sweep_frags()
    legacy_w, legacy_m = _pipe_run(frags, native=native)
    packed_w, packed_m = _pipe_run(frags, native=native,
                                   egress_packed=True)
    assert packed_w == legacy_w
    assert packed_m == legacy_m


def test_native_lib_unavailable_falls_back(monkeypatch):
    """Contract 3: no .so -> pure-Python tcache + NumPy finish, same
    wires and metrics as the reference model."""
    def _boom():
        raise OSError("native library unavailable")

    monkeypatch.setattr(pl.native_mod, "lib", _boom)
    frags = _sweep_frags()
    # knob on, load fails -> fallback must carry the day
    wires, m = _pipe_run(frags, native=True, allow_fallback=True)
    ref_w, ref_m = _ref_run(frags)
    assert wires == ref_w
    assert m == ref_m


def test_np_finish_long_tail_chunked(monkeypatch):
    """Contract 4: one ml-length row among 2048 short ones must not
    stage a (k, 65+Lmax) padded block — peak stays well under the
    unchunked build's footprint, and a tiny pad cap is bit-identical."""
    n = 2048
    lens = np.full(n, 8)
    lens[-1] = ML                        # the long tail
    rows = _mk_rows(n, lens, seed=9)
    ok = np.ones(n, bool)

    def run(cap=None):
        if cap is not None:
            monkeypatch.setattr(VerifyPipeline, "_NP_PAD_CAP", cap)
        pipe = VerifyPipeline(_VerdictFn([ok]), buckets=[(n, ML)],
                              tcache_depth=1 << 13, max_inflight=0,
                              native_hostpath=False)
        return pipe, pipe.submit_packed_rows(rows, n=n)

    pipe, _ = run()                      # warm shapes/scratch
    pipe2 = VerifyPipeline(_VerdictFn([ok]), buckets=[(n, ML)],
                           tcache_depth=1 << 13, max_inflight=0,
                           native_hostpath=False)
    tracemalloc.start()
    passed = pipe2.submit_packed_rows(rows, n=n)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert len(passed) == n
    # unchunked: padded wires + bool mask + fancy-index row copy, all
    # (k, 65+Lmax)-ish ~ 3 * n * (65 + ML) bytes
    naive = 3 * n * (65 + ML)
    assert peak < naive // 2, \
        f"ragged build staged ~{peak} B (unchunked ~{naive} B)"
    _, tiny = run(cap=4096)
    assert [w for w, _ in tiny] == [w for w, _ in passed]
