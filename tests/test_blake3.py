"""BLAKE3 tests against the official test vectors.

Vectors from github.com/BLAKE3-team/BLAKE3/test_vectors/test_vectors.json
(input bytes are i % 251).  Host numpy tree implementation is checked
directly; the JAX single-chunk batch path is checked against both the
vectors (lengths <= 1024) and the host model on random lengths.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from firedancer_tpu.ops import blake3 as b3

VECTORS = {
    0: "af1349b9f5f9a1a6a0404dea36dcc9499bcb25c9adc112b7cc9a93cae41f3262",
    1: "2d3adedff11b61f14c886e35afa036736dcd87a74d27b5c1510225d0f592e213",
    2: "7b7015bb92cf0b318037702a6cdd81dee41224f734684c2c122cd6359cb1ee63",
    63: "e9bc37a594daad83be9470df7f7b3798297c3d834ce80ba85d6e207627b7db7b",
    64: "4eed7141ea4a5cd4b788606bd23f46e212af9cacebacdc7d1f4c6dc7f2511b98",
    65: "de1e5fa0be70df6d2be8fffd0e99ceaa8eb6e8c93a63f2d8d1c30ecb6b263dee",
    127: "d81293fda863f008c09e92fc382a81f5a0b4a1251cba1634016a0f86a6bd640d",
    128: "f17e570564b26578c33bb7f44643f539624b05df1a76c81f30acd548c44b45ef",
    129: "683aaae9f3c5ba37eaaf072aed0f9e30bac0865137bae68b1fde4ca2aebdcb12",
    1023: "10108970eeda3eb932baac1428c7a2163b0e924c9a9e25b35bba72b28f70bd11",
    1024: "42214739f095a406f3fc83deb889744ac00df831c10daa55189b5d121c855af7",
    1025: "d00278ae47eb27b34faecf67b4fe263f82d5412916c1ffd97c8cb7fb814b8444",
    2048: "e776b6028c7cd22a4d0ba182a8bf62205d2ef576467e838ed6f2529b85fba24a",
    2049: "5f4d72f40d7a5f82b15ca2b2e44b1de3c2ef86c426c95c1af0b6879522563030",
    3072: "b98cb0ff3623be03326b373de6b9095218513e64f1ee2edd2525c7ad1e5cffd2",
    3073: "7124b49501012f81cc7f11ca069ec9226cecb8a2c850cfe644e327d22d3e1cd3",
}


def _inp(n):
    return bytes(i % 251 for i in range(n))


def test_host_blake3_official_vectors():
    for n, want in VECTORS.items():
        assert b3.blake3(_inp(n)).hex() == want, f"len {n}"


def test_batch_matches_vectors_single_chunk():
    lens = [n for n in VECTORS if n <= 1024]
    P = 1024
    msgs = np.zeros((len(lens), P), dtype=np.uint8)
    for i, n in enumerate(lens):
        msgs[i, :n] = np.frombuffer(_inp(n), dtype=np.uint8)
    out = np.asarray(
        b3.blake3_batch(jnp.asarray(msgs), jnp.asarray(lens, dtype=jnp.int32))
    )
    for i, n in enumerate(lens):
        assert out[i].tobytes().hex() == VECTORS[n], f"len {n}"


def test_batch_differential_random_lens():
    rng = np.random.default_rng(7)
    B, P = 32, 256
    lens = rng.integers(0, P + 1, size=B).astype(np.int32)
    msgs = np.zeros((B, P), dtype=np.uint8)
    for i, n in enumerate(lens):
        msgs[i, :n] = rng.integers(0, 256, size=n, dtype=np.uint8)
    out = np.asarray(b3.blake3_batch(jnp.asarray(msgs), jnp.asarray(lens)))
    for i, n in enumerate(lens):
        assert out[i].tobytes() == b3.blake3(msgs[i, :n].tobytes()), f"lane {i} len {n}"


def test_batch_rejects_bad_shapes():
    with pytest.raises(AssertionError):
        b3.blake3_batch(jnp.zeros((2, 100), dtype=jnp.uint8), jnp.zeros(2, jnp.int32))
    with pytest.raises(AssertionError):
        b3.blake3_batch(jnp.zeros((2, 2048), dtype=jnp.uint8), jnp.zeros(2, jnp.int32))
