"""Replay the instruction-fixture corpus (round 4, VERDICT missing #2) —
the run-test-vectors analogue: >= 100 instruction fixtures with
reference-derived expectations through the native-program registry.
Regenerate with tools/gen_instr_fixtures.py."""

import json
import os

from firedancer_tpu.flamenco.fixtures import replay, replay_file

_PATH = os.path.join(os.path.dirname(__file__), "fixtures",
                     "instr_fixtures.json")


def test_corpus_size_and_coverage():
    with open(_PATH) as f:
        fixtures = json.load(f)
    assert len(fixtures) >= 100
    programs = {fx["program_id"] for fx in fixtures}
    assert len(programs) >= 3          # system, vote, stake at minimum
    oks = {fx["expect"].get("ok", True) for fx in fixtures}
    assert oks == {True, False}        # both polarities present


def test_replay_all_fixtures():
    results = replay_file(_PATH)
    fails = [r for r in results if not r.passed]
    assert not fails, [(r.name, r.detail) for r in fails[:10]]


def test_replayer_detects_wrong_expectation():
    """The replayer itself must be falsifiable: a fixture with a wrong
    post-balance fails."""
    with open(_PATH) as f:
        fx = next(f0 for f0 in json.load(f)
                  if f0["name"].startswith("system_transfer_ok"))
    fx["expect"]["post"][0]["lamports"] += 1
    assert not replay(fx).passed
