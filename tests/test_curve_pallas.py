"""Pallas dsm kernel: geometry-level unit checks (CPU) + device parity.

The full kernel-vs-host parity run lives in tools/exp_pallas_dsm_check.py
(needs the real TPU; Mosaic has no CPU backend).  What CAN be checked on
CPU is the (22, blk) sublane-geometry field arithmetic the kernel is
built from — _mulw/_sqrw/_wr/_reduce44 are plain jnp and run anywhere —
against python-int ground truth, including the magnitude edge cases the
in-kernel lazy-add discipline relies on.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from firedancer_tpu.ops import curve25519 as cv
from firedancer_tpu.ops import curve_pallas as cp
from firedancer_tpu.ops import f25519 as fe


def _to_limbs(vals):
    return jnp.asarray(
        np.stack([fe._to_limbs_py(v) for v in vals], axis=1))


def _from_limbs(arr):
    a = np.asarray(arr)
    return [fe._from_limbs_py(a[:, i]) % fe.P for i in range(a.shape[1])]


@pytest.fixture
def vals():
    rng = np.random.default_rng(7)
    out = [int.from_bytes(rng.bytes(32), "little") % fe.P for _ in range(8)]
    # edge values: 0, 1, p-1, 2^255-20 (max canonical), high-limb-heavy
    out[:4] = [0, 1, fe.P - 1, 2**255 - 20]
    return out


def test_mulw_matches_int(vals):
    a = _to_limbs(vals)
    b = _to_limbs(list(reversed(vals)))
    got = _from_limbs(cp._mulw(a, b))
    want = [(x * y) % fe.P for x, y in zip(vals, reversed(vals))]
    assert got == want


def test_sqrw_matches_int(vals):
    a = _to_limbs(vals)
    got = _from_limbs(cp._sqrw(a))
    assert got == [x * x % fe.P for x in vals]


def test_mulw_lazy_inputs_exact(vals):
    """One unreduced add on each operand (the kernel's lazy-add pattern)
    must stay uint32-exact through the MAC ladder."""
    a = _to_limbs(vals)
    b = _to_limbs(list(reversed(vals)))
    got = _from_limbs(cp._mulw(a + a, b + b))
    want = [(4 * x * y) % fe.P for x, y in zip(vals, reversed(vals))]
    assert got == want


def test_doublew_matches_host(vals):
    from firedancer_tpu.ops import ed25519 as ed

    pts = [ed._scalar_mul_base_host(3 * i + 1) for i in range(4)]
    aff = []
    for p in pts:
        zi = pow(p[2], fe.P - 2, fe.P)
        aff.append((p[0] * zi % fe.P, p[1] * zi % fe.P))
    P4 = cp._Pt(
        _to_limbs([a[0] for a in aff]), _to_limbs([a[1] for a in aff]),
        _to_limbs([1] * 4), _to_limbs([a[0] * a[1] % fe.P for a in aff]))
    bias = fe._limb_const(fe._BIAS_PY, 2)
    got = cp._doublew(P4, bias)
    gz = _from_limbs(got.Z)
    gx = [x * pow(z, fe.P - 2, fe.P) % fe.P
          for x, z in zip(_from_limbs(got.X), gz)]
    gy = [y * pow(z, fe.P - 2, fe.P) % fe.P
          for y, z in zip(_from_limbs(got.Y), gz)]
    for i, p in enumerate(pts):
        d = ed._pt_add_host(p, p)
        zi = pow(d[2], fe.P - 2, fe.P)
        assert gx[i] == d[0] * zi % fe.P
        assert gy[i] == d[1] * zi % fe.P


def test_dsm_tail_q_matches_xla_and_compressed_check():
    """Round-4 tail parity (interpret mode): dsm_tail_q's in-kernel
    projective y-compare + Q planes agree with the XLA double-scalar-mul
    and the full compressed-R acceptance (valid + tampered lanes)."""
    from firedancer_tpu.models.verifier import make_example_batch
    from firedancer_tpu.ops import ed25519 as ed
    from firedancer_tpu.ops import scalar25519 as sc
    from firedancer_tpu.ops import sha512 as sh

    B = 8
    msgs, lens, sigs, pubs = make_example_batch(B, 64, True, sign_pool=4)
    sigs = np.asarray(sigs).copy()
    sigs[3, 5] ^= 0xFF          # one tampered lane
    sigs = jnp.asarray(sigs)
    r_bytes, s_bytes = sigs[:, :32], sigs[:, 32:]

    _ok_a, a_pt = cv.decompress(pubs)
    pre = jnp.concatenate([r_bytes, pubs, msgs], axis=1)
    digest = sh.sha512(pre, lens + 64)

    _ok_s, wins = cp.reduce_recode(s_bytes, digest, blk=B, interpret=True)
    y_r, _sign, _small = ed._parse_r_bytes(r_bytes)
    ok_y, qx, qz = cp.dsm_tail_q(wins, a_pt, y_r, blk=B, interpret=True)
    got = np.asarray(ed._compressed_r_check(qx, None, qz, r_bytes,
                                            ok_y=ok_y))

    # XLA reference: same Q via cv, full compressed check
    k_limbs = sc.reduce_512(digest)
    q = cv.double_scalar_mul_base(
        cv.scalar_windows(s_bytes), sc.limbs_to_windows(k_limbs),
        cv.neg(a_pt))
    want = np.asarray(ed._compressed_r_check(q.X, q.Y, q.Z, r_bytes))
    assert (got == want).all()
    assert want.tolist() == [True] * 3 + [False] + [True] * 4


def test_fused_tail_matches_xla_acceptance():
    """Round-5 fused kernel (decompress+recode+dsm+y-compare in one
    pallas_call, interpret mode) must reproduce the XLA path's per-lane
    acceptance bits across adversarial lanes: tampered sig, non-canonical
    S, undecompressable A, small-order A."""
    from firedancer_tpu.models.verifier import make_example_batch
    from firedancer_tpu.ops import ed25519 as ed
    from firedancer_tpu.ops import scalar25519 as sc
    from firedancer_tpu.ops import sha512 as sh

    B = 8
    msgs, lens, sigs, pubs = make_example_batch(B, 64, True, sign_pool=8)
    sigs = np.asarray(sigs).copy()
    pubs = np.asarray(pubs).copy()
    sigs[1, 5] ^= 0xFF                       # tampered R
    sigs[2, 32:] = 0xFF                      # non-canonical S (>= L)
    pubs[3] = np.frombuffer(bytes([0x07] * 32), np.uint8)   # no sqrt
    pubs[4] = np.frombuffer(bytes(31) + bytes([0x80]), np.uint8)  # y=0+sign
    pubs[5] = np.frombuffer(bytes([1]) + bytes(31), np.uint8)  # identity
    sigs, pubs = jnp.asarray(sigs), jnp.asarray(pubs)
    r_bytes, s_bytes = sigs[:, :32], sigs[:, 32:]

    pre = jnp.concatenate([r_bytes, pubs, msgs], axis=1)
    digest = sh.sha512(pre, lens + 64)
    parsed_r = ed._parse_r_bytes(r_bytes)
    ok_k, qx, qz = cp.verify_tail_fused(
        pubs, s_bytes, digest, parsed_r[0], blk=B, interpret=True)
    got = np.asarray(ed._compressed_r_check(
        qx, None, qz, r_bytes, ok_y=ok_k, parsed_r=parsed_r))

    # XLA reference path (exact verify_batch semantics)
    ok_a, a_pt = cv.decompress(pubs)
    ok_a = ok_a & ~cv.is_small_order_affine(a_pt)
    ok_s = sc.is_canonical(s_bytes)
    q = cv.double_scalar_mul_base(
        cv.scalar_windows(s_bytes),
        sc.limbs_to_windows(sc.reduce_512(digest)), cv.neg(a_pt))
    want = np.asarray(
        ok_s & ok_a & ed._compressed_r_check(q.X, q.Y, q.Z, r_bytes))
    assert got.tolist() == want.tolist()
    assert want.tolist() == [True, False, False, False, False, False,
                             True, True]


def test_signed_windows_ext_preserves_value_128bit():
    """The carry-out window (round-6 p16 path): full-width 128-bit
    scalars over 32 windows can carry into window 32; the ext recode
    appends it rather than overflowing in place."""
    rng = np.random.default_rng(23)
    vals = [int.from_bytes(rng.bytes(16), "little") for _ in range(16)]
    vals[0] = (1 << 128) - 1            # worst case: all windows recode
    vals[1] = 0
    w = np.zeros((32, len(vals)), np.uint32)
    for b, v in enumerate(vals):
        for i in range(32):
            w[i, b] = (v >> (4 * i)) & 0xF
    mags, sgns = cp.signed_windows_ext(jnp.asarray(w))
    mags, sgns = np.asarray(mags), np.asarray(sgns)
    assert mags.shape == (33, len(vals))
    assert mags.max() <= 8
    for b, v in enumerate(vals):
        got = sum(int(mags[i, b]) * (-1) ** int(sgns[i, b]) * 16**i
                  for i in range(33))
        assert got == v, (b, hex(v))


def test_msm_p16_matches_legacy_and_xla():
    """Round-6 select redesign: msm(select="p16") must agree with the
    legacy kernel and the XLA reference ON THE GROUP ELEMENT (the signed
    chain takes a different op path, so projective coords differ while
    the affine point must not), including full-width 128-bit scalars at
    nwin=32 — the signed-recode carry-out case."""
    rng = np.random.default_rng(31)
    m, blk, n = 2, 8, 16
    # points: [k]B for random k via the trusted XLA comb
    kb = np.zeros((n, 32), np.uint8)
    kb[:, :8] = rng.integers(0, 256, size=(n, 8), dtype=np.uint8)
    pts = cv.scalar_mul_base(cv.scalar_windows(jnp.asarray(kb)))
    # scalars: full 128-bit with the top nibble forced >= 8 so the
    # recode carries out of window 31
    sb = np.zeros((n, 32), np.uint8)
    sb[:, :16] = rng.integers(0, 256, size=(n, 16), dtype=np.uint8)
    sb[:, 15] |= 0x80
    wins = cv.scalar_windows(jnp.asarray(sb))[:32]

    def aff(p):
        X, Y, Z = (fe._from_limbs_py(list(np.asarray(t))) % fe.P
                   for t in (p.X, p.Y, p.Z))
        zi = pow(Z, fe.P - 2, fe.P)
        return (X * zi) % fe.P, (Y * zi) % fe.P

    ref = aff(cv.msm(wins, pts, m=m, nwin=32))
    leg = aff(cp.msm(wins, pts, m=m, nwin=32, blk=blk, interpret=True))
    p16 = aff(cp.msm(wins, pts, m=m, nwin=32, blk=blk, interpret=True,
                     select="p16"))
    assert leg == ref
    assert p16 == ref
