"""Real-Solana conformance anchoring (round 4, VERDICT #3): the program
and sysvar ids are the REAL chain constants, and a hand-assembled
wire-format transfer (bytes written out per the Solana tx spec, not via
our builders) parses, sigverifies, and executes to the right balances.

Ref: the program registry src/flamenco/runtime/program/ and the id
constants in src/flamenco/fd_flamenco_base.h / fd_types.h.
"""

import hashlib
import struct

from firedancer_tpu.ballet import base58
from firedancer_tpu.ballet import txn as txn_lib
from firedancer_tpu.flamenco import genesis as gen_mod
from firedancer_tpu.flamenco import types as T
from firedancer_tpu.flamenco.runtime import Runtime
from firedancer_tpu.ops import ed25519 as ed


def _b58_independent(s: str) -> bytes:
    """Base58 decode written independently of ballet.base58 (plain int
    arithmetic) so the id constants are cross-checked against a second
    implementation, not just round-tripped through one."""
    alpha = "123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz"
    n = 0
    for c in s:
        n = n * 58 + alpha.index(c)
    raw = n.to_bytes((n.bit_length() + 7) // 8, "big")
    pad = len(s) - len(s.lstrip("1"))
    return (b"\x00" * pad + raw).rjust(32, b"\x00")[-32:] if len(raw) <= 32 \
        else raw


KNOWN = {
    "11111111111111111111111111111111": T.SYSTEM_PROGRAM_ID,
    "Vote111111111111111111111111111111111111111": T.VOTE_PROGRAM_ID,
    "Stake11111111111111111111111111111111111111": T.STAKE_PROGRAM_ID,
    "Config1111111111111111111111111111111111111": T.CONFIG_PROGRAM_ID,
    "ComputeBudget111111111111111111111111111111": T.COMPUTE_BUDGET_PROGRAM_ID,
    "AddressLookupTab1e1111111111111111111111111":
        T.ADDRESS_LOOKUP_TABLE_PROGRAM_ID,
    "BPFLoader2111111111111111111111111111111111": T.BPF_LOADER_ID,
    "BPFLoaderUpgradeab1e11111111111111111111111":
        T.BPF_LOADER_UPGRADEABLE_ID,
    "Ed25519SigVerify111111111111111111111111111": T.ED25519_PRECOMPILE_ID,
    "KeccakSecp256k11111111111111111111111111111": T.SECP256K1_PRECOMPILE_ID,
    "SysvarC1ock11111111111111111111111111111111": T.SYSVAR_CLOCK_ID,
    "SysvarRent111111111111111111111111111111111": T.SYSVAR_RENT_ID,
    "SysvarEpochSchedu1e111111111111111111111111":
        T.SYSVAR_EPOCH_SCHEDULE_ID,
    "SysvarRecentB1ockHashes11111111111111111111":
        T.SYSVAR_RECENT_BLOCKHASHES_ID,
    "NativeLoader1111111111111111111111111111111": T.NATIVE_LOADER_ID,
}


def test_program_ids_are_the_real_constants():
    for b58, got in KNOWN.items():
        assert got == _b58_independent(b58), b58
        assert base58.encode(got) == b58


def test_vote_id_known_bytes():
    """One fully-literal anchor: the vote program id's raw bytes."""
    assert T.VOTE_PROGRAM_ID.hex() == (
        "0761481d357474bb7c4d7624ebd3bdb3d8355e73d11043fc0da3538000000000")


def _hand_assembled_transfer(sender_seed: bytes, dest: bytes,
                             lamports: int, blockhash: bytes) -> bytes:
    """Byte-for-byte wire layout of a mainnet/devnet-style legacy transfer
    (what `solana transfer` emits), written out field by field:

        u8  sig_cnt (1)  | sig[64]
        u8  num_required_signatures (1)
        u8  num_readonly_signed (0)
        u8  num_readonly_unsigned (1)
        cu16 account_cnt (3) | sender | dest | system_program
        blockhash[32]
        cu16 instr_cnt (1)
        u8 program_idx (2) | cu16 acct_cnt (2) | idx 0,1
        cu16 data_len (12) | u32 2 (Transfer) | u64 lamports
    """
    sender_pub, _, _ = ed.keypair_from_seed(sender_seed)
    msg = bytes([1, 0, 1, 3]) + sender_pub + dest + T.SYSTEM_PROGRAM_ID \
        + blockhash + bytes([1, 2, 2, 0, 1, 12]) \
        + struct.pack("<IQ", 2, lamports)
    sig = ed.sign(sender_seed, msg)
    return bytes([1]) + sig + msg


def test_real_format_transfer_parses_verifies_executes():
    sender_seed = hashlib.sha256(b"real-id-conformance").digest()
    sender_pub, _, _ = ed.keypair_from_seed(sender_seed)
    dest = b"\xd9" + bytes(31)

    g = gen_mod.create(sender_pub, creation_time=1_700_000_000,
                       slots_per_epoch=32)
    rt = Runtime(g)
    payload = _hand_assembled_transfer(
        sender_seed, dest, 123_456, g.genesis_hash())

    # parse: python + native parsers agree on the real layout
    t = txn_lib.parse(payload)
    assert t.signature_cnt == 1 and t.acct_addr_cnt == 3
    addrs = t.account_addrs(payload)
    assert addrs[2] == T.SYSTEM_PROGRAM_ID
    import numpy as np
    from firedancer_tpu.ballet import txn_native as tn
    msgs = np.zeros((4, 256), np.uint8)
    lens = np.zeros((4,), np.int32)
    sigs = np.zeros((4, 64), np.uint8)
    pubs = np.zeros((4, 32), np.uint8)
    r = tn.parse_burst([payload], msgs, lens, sigs, pubs, 0, None)
    assert r.err[0] == tn.OK

    # sigverify (host reference verifier — consensus rules)
    assert ed.verify_one_host(t.signatures(payload)[0], t.message(payload),
                              sender_pub)

    # execute: routes to the real system program id, moves lamports
    bank = rt.new_bank(1)
    res = bank.execute_txn(payload)
    assert res.ok, res.err
    assert rt.balance(dest, slot=1) == 123_456


def test_sysvar_accounts_live_at_real_addresses():
    sender_seed = hashlib.sha256(b"sysvar-addr").digest()
    sender_pub, _, _ = ed.keypair_from_seed(sender_seed)
    g = gen_mod.create(sender_pub, creation_time=1_700_000_000,
                       slots_per_epoch=32)
    rt = Runtime(g)
    bank = rt.new_bank(1)
    for sid in (T.SYSVAR_CLOCK_ID, T.SYSVAR_RENT_ID,
                T.SYSVAR_EPOCH_SCHEDULE_ID, T.SYSVAR_RECENT_BLOCKHASHES_ID):
        acct = rt.accdb.load(bank.xid, sid)
        assert acct is not None, base58.encode(sid)
        assert acct.data, base58.encode(sid)
