"""Runtime core tests: system/vote programs, executor phases, bank lthash
chaining, fork publish, leader schedule (ref behaviors: src/flamenco/runtime,
src/flamenco/leaders)."""

import numpy as np
import pytest

from firedancer_tpu.ballet import txn as txn_lib
from firedancer_tpu.flamenco import genesis as gen_mod
from firedancer_tpu.flamenco import system_program as sysprog
from firedancer_tpu.flamenco import vote_program as voteprog
from firedancer_tpu.flamenco.runtime import Runtime
from firedancer_tpu.flamenco.types import (Account, SYSTEM_PROGRAM_ID,
                                           VOTE_PROGRAM_ID)
from firedancer_tpu.flamenco.vote_program import VoteState
from firedancer_tpu.ops import ed25519 as ed


def _keypair(seed_int: int):
    seed = seed_int.to_bytes(32, "little")
    pub, _, _ = ed.keypair_from_seed(seed)
    return seed, pub


def _signed_txn(signers, message):
    return txn_lib.assemble([ed.sign(s, message) for s, _ in signers], message)


@pytest.fixture(scope="module")
def chain():
    faucet_seed, faucet_pk = _keypair(1)
    node_seed, node_pk = _keypair(2)
    vote_seed, vote_pk = _keypair(3)
    g = gen_mod.create(
        faucet_pk, faucet_lamports=10_000_000_000,
        bootstrap_validators=[(node_pk, vote_pk, 1_000_000)],
        slots_per_epoch=32, creation_time=1_700_000_000)
    return {
        "genesis": g,
        "faucet": (faucet_seed, faucet_pk),
        "node": (node_seed, node_pk),
        "vote": (vote_seed, vote_pk),
    }


def test_genesis_boot_and_balances(chain):
    rt = Runtime(chain["genesis"])
    assert rt.balance(chain["faucet"][1]) == 10_000_000_000
    va = rt.accdb.load(None, chain["vote"][1])
    assert va is not None and va.owner == VOTE_PROGRAM_ID
    vs = VoteState.deserialize(va.data)
    assert vs.node_pubkey == chain["node"][1]


def test_transfer_and_fees(chain):
    rt = Runtime(chain["genesis"])
    faucet_seed, faucet_pk = chain["faucet"]
    _, dest_pk = _keypair(9)
    b = rt.new_bank(1)
    msg = txn_lib.build_unsigned(
        [faucet_pk], rt.root_hash[:32],
        [(2, bytes([0, 1]), sysprog.ix_transfer(1_000_000))],
        extra_accounts=[dest_pk, SYSTEM_PROGRAM_ID],
        readonly_unsigned_cnt=1)
    res = b.execute_txn(_signed_txn([chain["faucet"]], msg))
    assert res.ok, res.err
    assert rt.balance(dest_pk, slot=1) == 1_000_000
    assert rt.balance(faucet_pk, slot=1) == 10_000_000_000 - 1_000_000 - 5000
    # root unchanged until publish
    assert rt.balance(dest_pk) == 0
    b.freeze(poh_hash=b"\x11" * 32)
    rt.publish(1)
    assert rt.balance(dest_pk) == 1_000_000


def test_failed_txn_charges_fee_only(chain):
    rt = Runtime(chain["genesis"])
    faucet_seed, faucet_pk = chain["faucet"]
    _, dest_pk = _keypair(10)
    b = rt.new_bank(1)
    # transfer more than the faucet holds -> instruction fails
    msg = txn_lib.build_unsigned(
        [faucet_pk], rt.root_hash[:32],
        [(2, bytes([0, 1]), sysprog.ix_transfer(99_000_000_000))],
        extra_accounts=[dest_pk, SYSTEM_PROGRAM_ID],
        readonly_unsigned_cnt=1)
    res = b.execute_txn(_signed_txn([chain["faucet"]], msg))
    assert not res.ok and "insufficient" in res.err
    assert res.fee == 5000
    assert rt.balance(faucet_pk, slot=1) == 10_000_000_000 - 5000
    assert rt.balance(dest_pk, slot=1) == 0


def test_create_account_and_assign(chain):
    rt = Runtime(chain["genesis"])
    new_seed, new_pk = _keypair(11)
    owner = bytes(range(32))
    b = rt.new_bank(1)
    msg = txn_lib.build_unsigned(
        [chain["faucet"][1], new_pk], rt.root_hash[:32],
        [(2, bytes([0, 1]), sysprog.ix_create_account(2_000_000, 64, owner))],
        extra_accounts=[SYSTEM_PROGRAM_ID],
        readonly_unsigned_cnt=1)
    res = b.execute_txn(_signed_txn([chain["faucet"], (new_seed, new_pk)], msg))
    assert res.ok, res.err
    a = rt.accdb.load(b.xid, new_pk)
    assert a.lamports == 2_000_000 and len(a.data) == 64 and a.owner == owner


def test_vote_txn_updates_tower(chain):
    rt = Runtime(chain["genesis"])
    node_seed, node_pk = chain["node"]
    vote_pk = chain["vote"][1]
    b = rt.new_bank(1)
    msg = txn_lib.build_unsigned(
        [node_pk], rt.root_hash[:32],
        [(2, bytes([1]), voteprog.ix_vote([1, 2, 3]))],
        extra_accounts=[vote_pk, VOTE_PROGRAM_ID],
        readonly_unsigned_cnt=1)
    res = b.execute_txn(_signed_txn([chain["node"]], msg))
    assert res.ok, res.err
    vs = VoteState.deserialize(rt.accdb.load(b.xid, vote_pk).data)
    assert [s for s, _ in vs.votes] == [1, 2, 3]
    assert vs.votes[0][1] == 3  # doubled twice by deeper votes


def test_bank_hash_chain_and_forks(chain):
    rt = Runtime(chain["genesis"])
    faucet = chain["faucet"]
    _, a_pk = _keypair(20)
    _, b_pk = _keypair(21)

    def transfer_txn(dest_pk, amt, bh):
        msg = txn_lib.build_unsigned(
            [faucet[1]], bh[:32],
            [(2, bytes([0, 1]), sysprog.ix_transfer(amt))],
            extra_accounts=[dest_pk, SYSTEM_PROGRAM_ID],
            readonly_unsigned_cnt=1)
        return _signed_txn([faucet], msg)

    b1 = rt.new_bank(1)
    assert b1.execute_txn(transfer_txn(a_pk, 111, rt.root_hash)).ok
    h1 = b1.freeze(b"\x22" * 32)
    # competing fork at slot 2a/2b off slot 1
    b2a = rt.new_bank(2, parent_slot=1)
    b2b = rt.new_bank(3, parent_slot=1)
    assert b2a.execute_txn(transfer_txn(b_pk, 222, h1)).ok
    assert b2b.execute_txn(transfer_txn(b_pk, 333, h1)).ok
    h2a = b2a.freeze(b"\x33" * 32)
    b2b.freeze(b"\x44" * 32)
    assert h2a != h1 and h2a != b2b.hash
    # identical re-execution produces an identical bank hash (determinism)
    rt2 = Runtime(chain["genesis"])
    c1 = rt2.new_bank(1)
    assert c1.execute_txn(transfer_txn(a_pk, 111, rt2.root_hash)).ok
    assert c1.freeze(b"\x22" * 32) == h1
    # root fork 2a: fork 2b dies, balances land
    rt.publish(1)
    rt.publish(2)
    assert rt.balance(b_pk) == 222
    assert 3 not in rt.banks


def test_leader_schedule_deterministic_and_weighted(chain):
    from firedancer_tpu.flamenco.leaders import leader_schedule
    pk_a, pk_b = b"\xaa" * 32, b"\xbb" * 32
    s1 = leader_schedule(5, {pk_a: 900, pk_b: 100}, 4000)
    s2 = leader_schedule(5, {pk_b: 100, pk_a: 900}, 4000)
    assert s1 == s2  # insertion-order independent
    frac_a = sum(1 for x in s1 if x == pk_a) / len(s1)
    assert 0.8 < frac_a < 0.98  # stake-weighted
    # 4-slot rotation
    for i in range(0, 4000, 4):
        assert len(set(s1[i:i + 4])) == 1
    assert leader_schedule(6, {pk_a: 900, pk_b: 100}, 4000) != s1


def test_lamport_conservation_guard(chain):
    """A buggy program that mints lamports must be caught by the
    conservation check (fd_runtime's collected-fees accounting invariant)."""
    from firedancer_tpu.flamenco import executor as ex_mod

    def evil(ictx):
        ictx.account(0).acct.lamports += 777
        ictx.account(0).touch()

    evil_id = b"\xee" * 32
    ex_mod.register_program(evil_id, evil)
    try:
        rt = Runtime(chain["genesis"])
        b = rt.new_bank(1)
        msg = txn_lib.build_unsigned(
            [chain["faucet"][1]], rt.root_hash[:32],
            [(1, bytes([0]), b"")], extra_accounts=[evil_id])
        res = b.execute_txn(_signed_txn([chain["faucet"]], msg))
        assert not res.ok and "balances changed" in res.err
    finally:
        del ex_mod.NATIVE_PROGRAMS[evil_id]


def test_duplicate_account_rejected(chain):
    """A txn listing the same address twice must not load it as two
    independent accounts (last-store-wins would mint lamports)."""
    rt = Runtime(chain["genesis"])
    faucet_seed, faucet_pk = chain["faucet"]
    b = rt.new_bank(1)
    msg = txn_lib.build_unsigned(
        [faucet_pk], rt.root_hash,
        [(2, bytes([0, 1]), sysprog.ix_transfer(1000))],
        extra_accounts=[faucet_pk, SYSTEM_PROGRAM_ID],
        readonly_unsigned_cnt=1)
    res = b.execute_txn(_signed_txn([(faucet_seed, faucet_pk)], msg))
    assert not res.ok and "twice" in res.err
    assert rt.balance(faucet_pk) == 10_000_000_000  # fee not even charged


def test_stale_blockhash_rejected(chain):
    rt = Runtime(chain["genesis"])
    faucet_seed, faucet_pk = chain["faucet"]
    _, dest_pk = _keypair(11)
    b = rt.new_bank(1)
    msg = txn_lib.build_unsigned(
        [faucet_pk], b"\x5a" * 32,  # never registered
        [(2, bytes([0, 1]), sysprog.ix_transfer(1000))],
        extra_accounts=[dest_pk, SYSTEM_PROGRAM_ID],
        readonly_unsigned_cnt=1)
    res = b.execute_txn(_signed_txn([(faucet_seed, faucet_pk)], msg))
    assert not res.ok and "blockhash" in res.err


def test_malformed_instr_data_is_txn_error(chain):
    """Truncated system ix data must fail the txn, not raise out of the
    executor (one adversarial packet must never kill a bank tile)."""
    import struct as _struct
    rt = Runtime(chain["genesis"])
    faucet_seed, faucet_pk = chain["faucet"]
    _, dest_pk = _keypair(12)
    b = rt.new_bank(1)
    msg = txn_lib.build_unsigned(
        [faucet_pk], rt.root_hash,
        [(2, bytes([0, 1]), _struct.pack("<I", 0))],  # CreateAccount, no body
        extra_accounts=[dest_pk, SYSTEM_PROGRAM_ID],
        readonly_unsigned_cnt=1)
    res = b.execute_txn(_signed_txn([(faucet_seed, faucet_pk)], msg))
    assert not res.ok and res.fee == 5000  # fee charged, effects rolled back
