"""End-to-end ed25519 verify_batch tests: RFC 8032 vectors, golden-model
differential (valid + mutated), and the reference's edge-case rule set
(small order, non-canonical S — the cases the wycheproof/CCTV corpora cover,
ref src/ballet/ed25519/test_ed25519_wycheproof.c)."""

import secrets

import jax.numpy as jnp
import numpy as np

import tests.golden.ed25519_golden as g
from firedancer_tpu.ops import ed25519 as ed

MAXLEN = 128


def run_verify(cases):
    """cases: list of (msg, sig, pubkey) -> list[bool]"""
    n = len(cases)
    msgs = np.zeros((n, MAXLEN), dtype=np.uint8)
    lens = np.zeros((n,), dtype=np.int32)
    sigs = np.zeros((n, 64), dtype=np.uint8)
    pubs = np.zeros((n, 32), dtype=np.uint8)
    for i, (m, s, p) in enumerate(cases):
        msgs[i, : len(m)] = np.frombuffer(m, dtype=np.uint8)
        lens[i] = len(m)
        sigs[i] = np.frombuffer(s, dtype=np.uint8)
        pubs[i] = np.frombuffer(p, dtype=np.uint8)
    out = ed.verify_batch(
        jnp.asarray(msgs), jnp.asarray(lens), jnp.asarray(sigs), jnp.asarray(pubs)
    )
    return list(np.asarray(out))


# RFC 8032 §7.1 test vectors 1-3 (public standard vectors)
RFC_VECTORS = [
    (
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
        "",
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b",
    ),
    (
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
        "72",
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00",
    ),
    (
        "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
        "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
        "af82",
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
        "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a",
    ),
]


def test_rfc8032_vectors():
    cases = []
    for _, pub, msg, sig in RFC_VECTORS:
        cases.append((bytes.fromhex(msg), bytes.fromhex(sig), bytes.fromhex(pub)))
    assert run_verify(cases) == [True] * len(cases)


def test_sign_matches_golden_and_verifies():
    cases = []
    for i in range(8):
        seed = secrets.token_bytes(32)
        msg = secrets.token_bytes(i * 13)
        sig = ed.sign(seed, msg)
        assert sig == g.sign(seed, msg)  # host signer vs golden model
        pub, _, _ = ed.keypair_from_seed(seed)
        assert pub == g.public_key(seed)
        cases.append((msg, sig, pub))
    assert run_verify(cases) == [True] * 8


def test_rejects_mutations():
    seed = secrets.token_bytes(32)
    msg = b"firedancer-tpu differential corpus"
    sig = ed.sign(seed, msg)
    pub, _, _ = ed.keypair_from_seed(seed)

    cases = [(msg, sig, pub)]
    # flip one bit in each of: msg, R, S, pubkey
    cases.append((msg[:-1] + bytes([msg[-1] ^ 1]), sig, pub))
    cases.append((msg, bytes([sig[0] ^ 1]) + sig[1:], pub))
    cases.append((msg, sig[:33] + bytes([sig[33] ^ 1]) + sig[34:], pub))
    cases.append((msg, sig, bytes([pub[0] ^ 1]) + pub[1:]))
    got = run_verify(cases)
    want = [g.verify(m, s, p) for m, s, p in cases]
    assert got == want
    assert got[0] is True or got[0] == True  # noqa: E712
    assert got[1:] == [False] * 4


def test_rejects_noncanonical_s():
    seed = secrets.token_bytes(32)
    msg = b"malleability"
    sig = ed.sign(seed, msg)
    pub, _, _ = ed.keypair_from_seed(seed)
    s = int.from_bytes(sig[32:], "little")
    # s + L is the classic malleability mutation — verifies under non-strict
    # rules, MUST be rejected here (and by the reference)
    mal = sig[:32] + (s + ed.L).to_bytes(32, "little")
    assert run_verify([(msg, mal, pub)]) == [False]
    assert g.verify(msg, mal, pub) is False


def test_rejects_small_order_pubkey_and_r():
    small = bytes.fromhex(
        "26e8958fc2b227b045c3f489f2ef98f0d5dfac05d3c63339b13802886d53fc05"
    )
    seed = secrets.token_bytes(32)
    msg = b"small order"
    sig = ed.sign(seed, msg)
    pub, _, _ = ed.keypair_from_seed(seed)
    cases = [
        (msg, sig, small),             # small-order pubkey
        (msg, small + sig[32:], pub),  # small-order R
        (msg, sig, bytes(32)),         # invalid (all-zero y=0? y=0 dec fails or small)
    ]
    got = run_verify(cases)
    assert got == [False, False, False]
    assert [g.verify(m, s, p) for m, s, p in cases] == [False, False, False]


def test_mixed_batch_isolation():
    """Invalid entries must not poison valid lanes in the same batch."""
    good = []
    for i in range(4):
        seed = secrets.token_bytes(32)
        msg = secrets.token_bytes(40 + i)
        sig = ed.sign(seed, msg)
        pub, _, _ = ed.keypair_from_seed(seed)
        good.append((msg, sig, pub))
    bad = [
        (b"x", secrets.token_bytes(64), secrets.token_bytes(32)),
        (b"y", bytes(64), bytes(32)),
    ]
    cases = [good[0], bad[0], good[1], bad[1], good[2], good[3]]
    got = run_verify(cases)
    want = [g.verify(m, s, p) for m, s, p in cases]
    assert got == want
    assert got[0] and got[2] and got[4] and got[5]
