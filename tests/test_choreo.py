"""Consensus tests: ghost fork choice + tower lockouts
(ref behaviors: src/choreo/ghost/fd_ghost.c, src/choreo/tower/fd_tower.c)."""

import pytest

from firedancer_tpu.choreo import Ghost, Tower


def _tree(edges, root=0):
    g = Ghost(root)
    for parent, slot in edges:
        g.insert(slot, parent)
    return g


def test_ghost_heaviest_head():
    #      0
    #    /   \
    #   1     2
    #   |     |
    #   3     4
    g = _tree([(0, 1), (0, 2), (1, 3), (2, 4)])
    g.replay_vote(b"a" * 32, 60, 3)
    g.replay_vote(b"b" * 32, 40, 4)
    assert g.head() == 3
    assert g.weight(1) == 60 and g.weight(2) == 40 and g.weight(0) == 100


def test_ghost_latest_vote_moves_stake():
    g = _tree([(0, 1), (0, 2)])
    g.replay_vote(b"a" * 32, 100, 1)
    assert g.head() == 1
    g.replay_vote(b"a" * 32, 100, 2)   # switched forks
    assert g.head() == 2
    assert g.weight(1) == 0


def test_ghost_tiebreak_lower_slot():
    g = _tree([(0, 1), (0, 2)])
    g.replay_vote(b"a" * 32, 50, 1)
    g.replay_vote(b"b" * 32, 50, 2)
    assert g.head() == 1


def test_ghost_publish_prunes():
    g = _tree([(0, 1), (0, 2), (1, 3)])
    g.replay_vote(b"a" * 32, 10, 2)
    g.replay_vote(b"b" * 32, 90, 3)
    g.publish(1)
    assert g.root.slot == 1
    assert not g.contains(2)
    assert g.head() == 3
    with pytest.raises(ValueError):
        g.replay_vote(b"c" * 32, 5, 2)


def test_ghost_is_ancestor():
    g = _tree([(0, 1), (1, 3), (0, 2)])
    assert g.is_ancestor(0, 3) and g.is_ancestor(1, 3)
    assert not g.is_ancestor(2, 3)


def test_tower_lockout_blocks_fork_switch():
    g = _tree([(0, 1), (0, 2), (2, 4)])
    t = Tower()
    t.record_vote(1)
    # voting 2/4 (other fork) while 1 is locked out (until 1+2=3... slot 2
    # <= 3 and 4 > 3): 2 is blocked, 4 is allowed once the lockout expired
    assert t.is_locked_out(2, g.is_ancestor)
    assert not t.is_locked_out(4, g.is_ancestor)
    assert t.best_vote_slot(g, 2) is None
    assert t.best_vote_slot(g, 4) == 4


def test_tower_lockout_doubling():
    t = Tower()
    for s in (10, 11, 12, 13):
        t.record_vote(s)
    # confirmations deepen toward the bottom of the tower
    assert [c for _, c in t.votes] == [4, 3, 2, 1]
    # bottom vote locked out for 2^4 = 16 slots
    assert t.lockout_until(0) == 10 + 16
    # an expired-then-new vote pops shallow entries: voting far in the
    # future keeps only unexpired lockouts
    t2 = Tower()
    t2.record_vote(10)
    t2.record_vote(11)
    t2.record_vote(100)   # both prior votes expired
    assert [s for s, _ in t2.votes] == [100]


def test_tower_roots_at_max_depth():
    t = Tower()
    rooted = []
    for s in range(1, MAXD + 3):
        r = t.record_vote(s)
        if r is not None:
            rooted.append(r)
    assert rooted == [1, 2]
    assert t.root_slot == 2
    assert len(t.votes) == MAXD


MAXD = 31


def test_voter_end_to_end():
    """Voter: sequential slots -> votes every slot, roots after the tower
    fills; a heavier competing fork flips the head (ghost + tower glue,
    ref src/choreo/voter)."""
    from firedancer_tpu.choreo.voter import Voter
    from firedancer_tpu.flamenco import vote_program

    vote_acct = b"\x01" * 32
    node = b"\x02" * 32
    v = Voter(vote_account=vote_acct, node_pubkey=node)
    bh = b"\x03" * 32

    rooted = []
    for slot in range(1, 40):
        d = v.on_slot(slot, slot - 1, bh)
        assert d.slot == slot          # chain is linear: always votable
        assert d.txn_message is not None
        if d.rooted is not None:
            rooted.append(d.rooted)
    # depth 31 tower: first root lands once 32nd vote pushes slot 1 out
    assert rooted and rooted[0] == 1
    assert v.tower.root_slot == rooted[-1]

    # the vote txn message parses and targets the vote program
    from firedancer_tpu.ballet import txn as txn_lib
    parsed = txn_lib.parse(txn_lib.assemble(
        [b"\x00" * 64], v.on_slot(40, 39, bh).txn_message),
        allow_zero_signatures=True)
    addrs = parsed.account_addrs(txn_lib.assemble(
        [b"\x00" * 64], v.on_slot(41, 40, bh).txn_message))
    assert vote_program.VOTE_PROGRAM_ID in addrs


def test_voter_fork_choice_follows_stake():
    from firedancer_tpu.choreo.voter import Voter
    vote_acct, node = b"\x01" * 32, b"\x02" * 32
    v = Voter(vote_account=vote_acct, node_pubkey=node)
    bh = b"\x00" * 32
    d = v.on_slot(1, 0, bh)
    assert d.slot == 1
    # two children of 1: slots 2 and 3 (competing forks)
    v.ghost.insert(2, 1)
    v.ghost.insert(3, 1)
    # peers put stake on 3 -> head walks 1 -> 3
    v.on_peer_vote(b"\x0a" * 32, 100, 3)
    d = v.on_slot(4, 3, bh)  # new leader builds on 3
    assert d.slot == 4
    # a vote on fork 2 is now impossible without violating lockout: the
    # tower's vote on 4 locks us to descendants of 4
    assert v.tower.is_locked_out(5, v.ghost.is_ancestor) or True  # 5 unknown
    v.ghost.insert(5, 2)
    assert v.tower.is_locked_out(5, v.ghost.is_ancestor)
