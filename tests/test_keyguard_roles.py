"""Keyguard role-payload authorization (ref: fd_keyguard_payload_authorize
semantics, src/disco/keyguard/fd_keyguard.h:4-23): the per-role accepted
payload sets must be mutually disjoint so a compromised tile of one role
cannot obtain a signature meaningful to another role's verifiers."""

from firedancer_tpu.ballet import txn as txn_lib
from firedancer_tpu.disco.keyguard import (
    ROLE_GOSSIP,
    ROLE_LEADER,
    ROLE_TLS,
    ROLE_VOTER,
    role_payload_ok,
)
from firedancer_tpu.flamenco.types import SYSTEM_PROGRAM_ID, VOTE_PROGRAM_ID
from firedancer_tpu.ops import ed25519 as ed


def _message(program_id: bytes, n_ix: int = 1,
             version: int = txn_lib.VLEGACY) -> bytes:
    pub = ed.keypair_from_seed(b"\x07" * 32)[0]
    ixs = [(1, bytes([0]), b"\x01\x02\x03")] * n_ix
    return txn_lib.build_unsigned([pub], b"\x42" * 32, ixs,
                                  extra_accounts=[program_id],
                                  version=version)


def test_leader_accepts_only_merkle_roots():
    assert role_payload_ok(ROLE_LEADER, b"\x01" * 32)
    assert role_payload_ok(ROLE_LEADER, b"\x01" * 20)
    assert not role_payload_ok(ROLE_LEADER, b"\x01" * 31)
    assert not role_payload_ok(ROLE_LEADER, b"")
    assert not role_payload_ok(ROLE_LEADER, _message(VOTE_PROGRAM_ID))


def test_voter_accepts_only_vote_program_messages():
    assert role_payload_ok(ROLE_VOTER, _message(VOTE_PROGRAM_ID))
    # a transfer (system program) message must be refused: signing it
    # would let the voter role move funds from the identity account
    assert not role_payload_ok(ROLE_VOTER, _message(SYSTEM_PROGRAM_ID))
    assert not role_payload_ok(ROLE_VOTER, b"\x01" * 32)  # leader shape
    assert not role_payload_ok(ROLE_VOTER, b"not a message")


def test_gossip_excludes_other_roles_shapes():
    assert role_payload_ok(ROLE_GOSSIP, b"some crds value preimage")
    assert not role_payload_ok(ROLE_GOSSIP, b"\x01" * 32)  # leader shape
    assert not role_payload_ok(ROLE_GOSSIP, b"\x01" * 20)  # leader shape
    # a txn message smuggled through the gossip role must be refused
    assert not role_payload_ok(ROLE_GOSSIP, _message(SYSTEM_PROGRAM_ID))
    assert not role_payload_ok(ROLE_GOSSIP, _message(VOTE_PROGRAM_ID))
    # TLS CertificateVerify-shaped content must be refused
    tls_shaped = b"\x20" * 64 + b"TLS 1.3, server CertificateVerify\x00" + b"h" * 32
    assert not role_payload_ok(ROLE_GOSSIP, tls_shaped)
    assert not role_payload_ok(ROLE_GOSSIP, b"")
    assert not role_payload_ok(ROLE_GOSSIP, b"x" * 1233)


def test_versioned_messages_covered_by_filters():
    """V0 (versioned) txn messages must be treated as txn messages too:
    refused for GOSSIP (else a compromised gossip tile signs a V0
    transfer), accepted for VOTER when they target the vote program."""
    v0_transfer = _message(SYSTEM_PROGRAM_ID, version=txn_lib.V0)
    assert not role_payload_ok(ROLE_GOSSIP, v0_transfer)
    v0_vote = _message(VOTE_PROGRAM_ID, version=txn_lib.V0)
    assert role_payload_ok(ROLE_VOTER, v0_vote)
    assert not role_payload_ok(ROLE_VOTER, v0_transfer)


def test_tls_accepts_only_certverify_content():
    content = b"\x20" * 64 + b"TLS 1.3, client CertificateVerify\x00" + b"h" * 32
    assert role_payload_ok(ROLE_TLS, content)
    assert not role_payload_ok(ROLE_TLS, b"h" * 32)
    assert not role_payload_ok(ROLE_TLS, b"\x20" * 64 + b"x" * 70)


def test_unknown_role_refused():
    assert not role_payload_ok(0, b"x")
    assert not role_payload_ok(99, b"\x01" * 32)
