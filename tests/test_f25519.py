"""Differential tests of the batched GF(2^255-19) limb arithmetic against
python big-int ground truth (the cocotb-vs-golden-model pattern the reference
uses for its FPGA backend, src/wiredancer/sim/*/test.py)."""

import secrets

import jax.numpy as jnp
import numpy as np
import pytest

from firedancer_tpu.ops import f25519 as fe

P = fe.P
BATCH = 64


def rand_ints(n, below=P, rng_bits=256):
    out = []
    for _ in range(n):
        v = secrets.randbits(rng_bits) % below
        out.append(v)
    # pin down interesting edge values
    edges = [0, 1, 2, 19, P - 1, P - 2, P - 19, 2**255 - 20, (P + 1) // 2]
    out[: len(edges)] = [e % below for e in edges]
    return out


def pack(vals):
    """python ints -> (22, N) limb array"""
    return jnp.stack([jnp.asarray(fe._to_limbs_py(v % (1 << 264))) for v in vals], axis=1)


def unpack(limbs):
    return [fe.to_int(np.asarray(limbs[:, i])) for i in range(limbs.shape[1])]


@pytest.fixture(scope="module")
def ab():
    a = rand_ints(BATCH)
    b = list(reversed(rand_ints(BATCH)))
    return a, b


def test_bias_is_multiple_of_p():
    assert fe._from_limbs_py(fe._BIAS_PY) % P == 0


def test_roundtrip(ab):
    a, _ = ab
    la = pack(a)
    assert unpack(la) == [x % P for x in a]


def test_add(ab):
    a, b = ab
    got = unpack(fe.add(pack(a), pack(b)))
    assert got == [(x + y) % P for x, y in zip(a, b)]


def test_sub(ab):
    a, b = ab
    got = unpack(fe.sub(pack(a), pack(b)))
    assert got == [(x - y) % P for x, y in zip(a, b)]


def test_neg(ab):
    a, _ = ab
    got = unpack(fe.neg(pack(a)))
    assert got == [(-x) % P for x in a]


def test_mul(ab):
    a, b = ab
    got = unpack(fe.mul(pack(a), pack(b)))
    assert got == [(x * y) % P for x, y in zip(a, b)]


def test_mul_magnitude_invariant(ab):
    a, b = ab
    out = fe.mul(pack(a), pack(b))
    assert fe.max_limb(out) <= 4106
    assert int(jnp.max(out[fe.NLIMB - 1])) <= 31


def test_mul_accepts_lazy_inputs(ab):
    a, b = ab
    la, lb = pack(a), pack(b)
    lazy = fe.add_nr(la, lb)  # one lazy add level
    got = unpack(fe.mul(lazy, lazy))
    assert got == [((x + y) * (x + y)) % P for x, y in zip(a, b)]


def test_sqr(ab):
    a, _ = ab
    got = unpack(fe.sqr(pack(a)))
    assert got == [x * x % P for x in a]


def test_mul_small(ab):
    a, _ = ab
    got = unpack(fe.mul_small(pack(a), 12345))
    assert got == [x * 12345 % P for x in a]


def test_canonical_of_noncanonical():
    vals = [P, P + 1, P + 18, 2**255 - 20, 0, 1]
    got = unpack(fe.canonical(pack(vals)))
    assert got == [v % P for v in vals]


def test_eq_and_is_zero():
    a = [5, 7, P - 1, 0, P]
    b = [5, 8, P - 1, P, 0]  # P ≡ 0
    m = fe.eq(pack(a), pack(b))
    assert list(np.asarray(m)) == [True, False, True, True, True]
    z = fe.is_zero(pack([0, P, 1, 2 * P % (1 << 264)]))
    assert list(np.asarray(z)) == [True, True, False, True]


def test_inv(ab):
    a, _ = ab
    nz = [x if x % P else 1 for x in a]
    got = unpack(fe.inv(pack(nz)))
    assert got == [pow(x, P - 2, P) for x in nz]


def test_sqrt_ratio():
    import tests.golden.ed25519_golden as g

    us = rand_ints(32)
    vs = [v if v % P else 1 for v in reversed(rand_ints(32))]
    ok, x = fe.sqrt_ratio(pack(us), pack(vs))
    ok = list(np.asarray(ok))
    xs = unpack(x)
    for i, (u, v) in enumerate(zip(us, vs)):
        g_ok, g_x = g.sqrt_ratio(u, v)
        assert ok[i] == g_ok, i
        if g_ok:
            # sqrt is unique up to sign; fd_f25519_sqrt_ratio pins the sign
            # via the candidate-root recipe, same as the golden model
            assert xs[i] in (g_x, (-g_x) % P), i


def test_bytes_roundtrip():
    raw = [secrets.token_bytes(32) for _ in range(16)]
    arr = jnp.asarray(np.frombuffer(b"".join(raw), dtype=np.uint8).reshape(16, 32))
    limbs = fe.from_bytes(arr)
    expect = [int.from_bytes(r, "little") & ((1 << 255) - 1) for r in raw]
    assert unpack(limbs) == [e % P for e in expect]
    back = np.asarray(fe.to_bytes(limbs))
    for i, e in enumerate(expect):
        assert int.from_bytes(back[i].tobytes(), "little") == e % P


def test_pow_const_small():
    a = [3, 5, 7, 11]
    got = unpack(fe.pow_const(pack(a), 65537))
    assert got == [pow(x, 65537, P) for x in a]
