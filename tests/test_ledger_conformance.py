"""Ledger conformance round trip (ref: src/app/ledger/main.c +
contrib/ledger-tests): a leader-produced multi-slot ledger, archived as
shreds, replays offline to identical per-slot bank hashes — through both
the library driver and the `fdtpuctl ledger replay` CLI."""

import json

from firedancer_tpu.ballet import entry as entry_lib
from firedancer_tpu.ballet import shred as shred_lib
from firedancer_tpu.ballet import txn as txn_lib
from firedancer_tpu.flamenco import genesis as gen_mod
from firedancer_tpu.flamenco import shredcap as shredcap_mod
from firedancer_tpu.flamenco import system_program as sysprog
from firedancer_tpu.flamenco.ledger import replay_ledger
from firedancer_tpu.flamenco.runtime import Runtime
from firedancer_tpu.flamenco.types import SYSTEM_PROGRAM_ID
from firedancer_tpu.ops import ed25519 as ed

N_SLOTS = 6


def _keypair(i):
    seed = i.to_bytes(32, "little")
    return seed, ed.keypair_from_seed(seed)[0]


def _build_ledger(tmp_path):
    """Leader side: N_SLOTS linear slots of transfers -> shredcap archive.
    Returns (genesis, shredcap path, {slot: bank_hash})."""
    faucet_seed, faucet_pk = _keypair(1)
    id_seed, _ = _keypair(9)
    g = gen_mod.create(faucet_pk, creation_time=1_700_000_000,
                       slots_per_epoch=32)
    leader = Runtime(g)
    poh = bytes(32)
    hashes = {}
    fec_sets = {}
    for slot in range(1, N_SLOTS + 1):
        bank = leader.new_bank(slot)
        entries = []
        for i in range(4):
            dest = b"\xd7" + bytes(13) + slot.to_bytes(2, "little") \
                + i.to_bytes(16, "little")
            msg = txn_lib.build_unsigned(
                [faucet_pk], leader.root_hash,
                [(2, bytes([0, 1]), sysprog.ix_transfer(1000 + i))],
                extra_accounts=[dest, SYSTEM_PROGRAM_ID],
                readonly_unsigned_cnt=1)
            payload = txn_lib.assemble([ed.sign(faucet_seed, msg)], msg)
            res = bank.execute_txn(payload)
            assert res.ok, res.err
            poh = entry_lib.next_hash(poh, 1, entry_lib.txn_mixin([payload]))
            entries.append(entry_lib.Entry(1, poh, [payload]))
        poh = entry_lib.next_hash(poh, 4, None)
        entries.append(entry_lib.Entry(4, poh, []))
        hashes[slot] = bank.freeze(poh)
        leader.publish(slot)
        fec_sets[slot] = shred_lib.make_fec_set(
            entry_lib.serialize_batch(entries), slot=slot, parent_off=1,
            version=1, fec_set_idx=0,
            sign_fn=lambda root: ed.sign(id_seed, root),
            data_cnt=16, code_cnt=16, slot_complete=True)

    cap_path = str(tmp_path / "ledger.shredcap")
    with shredcap_mod.ShredCapWriter(cap_path) as w:
        # interleave slots round-robin: capture order is wire order, and
        # the driver must not depend on slot-contiguous records
        shreds = {s: list(fs.data_shreds + fs.code_shreds)
                  for s, fs in fec_sets.items()}
        while any(shreds.values()):
            for s in list(shreds):
                if shreds[s]:
                    w.append(s, shreds[s].pop(0))
    return g, cap_path, hashes


def test_ledger_replay_roundtrip(tmp_path):
    g, cap_path, hashes = _build_ledger(tmp_path)
    follower = Runtime(g)
    out_cap = str(tmp_path / "replay.capture")
    report = replay_ledger(follower, cap_path, capture_path=out_cap)
    assert report.slots_complete == N_SLOTS
    assert report.slots_ok == N_SLOTS, [r.err for r in report.results]
    for r in report.results:
        assert r.bank_hash == hashes[r.slot], r.slot
    # the produced capture round-trips as the expected reference
    follower2 = Runtime(g)
    report2 = replay_ledger(follower2, cap_path,
                            expected_capture_path=out_cap)
    assert report2.ok


def test_ledger_cli_and_divergence(tmp_path):
    from firedancer_tpu.app.fdtpuctl import main

    g, cap_path, hashes = _build_ledger(tmp_path)
    gen_path = str(tmp_path / "genesis.bin")
    g.write(gen_path)
    out_cap = str(tmp_path / "a.capture")
    rc = main(["ledger", "replay", gen_path, cap_path,
               "--capture", out_cap])
    assert rc == 0

    # tamper the expected capture: conformance must fail with a pinpointed
    # first divergence
    from firedancer_tpu.flamenco import capture as capture_mod
    recs = capture_mod.read(out_cap)
    recs[2]["bank_hash"] = "00" * 32
    bad_cap = str(tmp_path / "bad.capture")
    import gzip
    with gzip.open(bad_cap, "wt") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    rc = main(["ledger", "replay", gen_path, cap_path,
               "--expected", bad_cap])
    assert rc == 1
