"""Random-linear-combination batch verification (ops.ed25519.verify_batch_rlc)
and the mod-L helpers behind it, against python-int golden math.

Mirrors the reference's batch-verify surface (fd_ed25519_verify_batch_
single_msg, src/ballet/ed25519/fd_ed25519_user.c:231-311) — ours trades the
fail-fast 16-sig batch for an n-sig single-bit fast path + strict fallback.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from firedancer_tpu.models.verifier import SigVerifier, VerifierConfig, make_example_batch
from firedancer_tpu.ops import ed25519 as ed
from firedancer_tpu.ops import scalar25519 as sc

L = sc.L
BATCH = 16


def _rand_limbs(rng, nlimb, batch, bound):
    vals = [int(rng.integers(0, 2**63)) % bound for _ in range(batch)]
    arr = np.zeros((nlimb, batch), dtype=np.int32)
    for b, v in enumerate(vals):
        # widen with extra randomness to cover the full range
        v = (v * int(rng.integers(1, 2**62)) + int(rng.integers(0, 2**62))) % bound
        vals[b] = v
        for i in range(nlimb):
            arr[i, b] = (v >> (12 * i)) & 0xFFF
    return jnp.asarray(arr), vals


def test_mul_mod_l_matches_int():
    rng = np.random.default_rng(7)
    a, av = _rand_limbs(rng, 22, 8, L)
    b, bv = _rand_limbs(rng, 11, 8, 1 << 128)
    out = sc.mul_mod_l(a, b)
    for i in range(8):
        assert sc.to_int(out[:, i]) == (av[i] * bv[i]) % L


def test_sum_mod_l_matches_int():
    rng = np.random.default_rng(8)
    for n in (5, 8, 64):
        a, av = _rand_limbs(rng, 22, n, L)
        out = sc.sum_mod_l(a, axis=0)
        assert sc.to_int(out) == sum(av) % L


@pytest.fixture(scope="module")
def batch_args():
    return make_example_batch(BATCH, 96, valid=True, sign_pool=BATCH)


# verify_batch_rlc runs under jit in every production path (SigVerifier
# compiles it); calling it EAGERLY also trips a jaxlib CPU-compiler
# segfault on the per-primitive scan compile, so tests jit it too
_rlc_jit = None


def _rlc(*args, m):
    global _rlc_jit
    import functools
    import jax as _jax
    if _rlc_jit is None:
        _rlc_jit = _jax.jit(functools.partial(ed.verify_batch_rlc, m=m))
    return _rlc_jit(*args)


def _z(rng, batch=BATCH):
    return jnp.asarray(rng.integers(0, 256, size=(batch, 16), dtype=np.uint8))


def test_rlc_accepts_valid_batch(batch_args):
    rng = np.random.default_rng(11)
    ok, pre = _rlc(*batch_args, _z(rng), m=4)
    assert bool(ok)
    assert np.asarray(pre).all()


def test_rlc_rejects_single_forgery(batch_args):
    msgs, lens, sigs, pubs = batch_args
    rng = np.random.default_rng(12)
    bad = np.asarray(sigs).copy()
    bad[7, 40] ^= 1  # corrupt S of one sig (stays canonical w.h.p.)
    ok, _ = _rlc(msgs, lens, jnp.asarray(bad), pubs, _z(rng), m=4)
    assert not bool(ok)


def test_rlc_rejects_bad_precheck(batch_args):
    msgs, lens, sigs, pubs = batch_args
    rng = np.random.default_rng(13)
    bad = np.asarray(sigs).copy()
    bad[3, 32:] = 0xFF  # S >= L: non-canonical
    ok, pre = _rlc(msgs, lens, jnp.asarray(bad), pubs, _z(rng), m=4)
    assert not bool(ok)
    assert not np.asarray(pre)[3]


def test_verifier_fallback_bits(batch_args):
    """SigVerifier rlc mode: clean batch -> all True; dirty batch -> exact
    per-sig bits from the strict fallback."""
    msgs, lens, sigs, pubs = batch_args
    v = SigVerifier(VerifierConfig(batch=BATCH, msg_maxlen=96),
                    mode="rlc", msm_m=4)
    bits = np.asarray(v(msgs, lens, sigs, pubs))
    assert bits.all()
    bad = np.asarray(sigs).copy()
    bad[5, 2] ^= 0x40  # corrupt R
    bits = np.asarray(v(msgs, lens, jnp.asarray(bad), pubs))
    assert not bits[5] and bits.sum() == BATCH - 1


def test_verifier_split_descent_localizes_bad_sigs(batch_args):
    """With the batch check failing, the binary-split descent must accept
    passing subtrees wholesale and produce exact bits for the leaf holding
    the corruption — one hostile lane must not strict-verify everyone
    (the round-1 DoS shape)."""
    msgs, lens, sigs, pubs = batch_args
    v = SigVerifier(VerifierConfig(batch=BATCH, msg_maxlen=96),
                    mode="rlc", msm_m=4)
    v._SPLIT_LEAF = 16  # force two split levels at this batch size
    calls = {"strict": 0}
    orig = v._fn

    def counting_fn(*a):
        calls["strict"] += 1
        return orig(*a)

    v._fn = counting_fn
    bad = np.asarray(sigs).copy()
    bad[BATCH - 3, 40] ^= 1  # corrupt S in the LAST leaf's range
    bits = np.asarray(v(msgs, lens, jnp.asarray(bad), pubs))
    expect = np.ones(BATCH, bool)
    expect[BATCH - 3] = False
    assert (bits == expect).all()
    # only the one leaf containing the bad sig went strict
    assert calls["strict"] == 1


def test_rlc_recode_kernel_matches_xla_reference():
    """Round-4 kernel parity: cpal.rlc_recode (the VMEM-resident RLC
    scalar chain) against the scalar25519 XLA reference, bit-exact,
    including non-canonical s lanes (interpret mode on CPU)."""
    import jax.numpy as jnp
    import numpy as np

    from firedancer_tpu.ops import curve_pallas as cpal
    from firedancer_tpu.ops import scalar25519 as sc

    rng = np.random.default_rng(0)
    B = 8  # tiny block: interpret mode is slow
    s = rng.integers(0, 256, (B, 32), dtype=np.uint8)
    s[: B // 2, 31] &= 0x0F              # half canonical, half not
    d = rng.integers(0, 256, (B, 64), dtype=np.uint8)
    z = rng.integers(0, 256, (B, 16), dtype=np.uint8)

    ok, ww, zw, zs = cpal.rlc_recode(
        jnp.asarray(s), jnp.asarray(d), jnp.asarray(z), blk=B,
        interpret=True)
    ok, ww, zw, zs = map(np.asarray, (ok, ww, zw, zs))

    ok_ref = np.asarray(sc.is_canonical(jnp.asarray(s)))
    k = sc.reduce_512(jnp.asarray(d))
    zl = sc.bytes_to_limbs(jnp.asarray(z), 11)
    sl = sc.bytes_to_limbs(jnp.asarray(s), 22)
    w_ref = np.asarray(sc.limbs_to_windows(sc.mul_mod_l(k, zl)))
    zs_ref = np.asarray(sc.mul_mod_l(sl, zl))
    zw_ref = np.asarray(sc.limbs_to_windows(
        jnp.concatenate([zl, jnp.zeros_like(zl[:11])], axis=0)))[:32]

    assert (ok == ok_ref).all()
    assert (ww == w_ref).all()
    assert (zw == zw_ref).all()
    assert (zs == zs_ref).all()
