"""REAL Agave-captured wire bytes through the type layer (VERDICT r4
missing #2 / next-round #5): the reference vendors captured gossip
packets, a vote transaction and a vote account (src/flamenco/types/
fixtures/*.bin, decoded in the sibling .yml files); those bytes are the
golden corpus here.  Every packet must decode through the Agave-wire
CRDS schemas (flamenco/crds_types.py), re-encode BYTE-EXACTLY, and
surface the field values the reference's decoder documents."""

import os

from firedancer_tpu.ballet import base58
from firedancer_tpu.ballet import txn as txn_lib
from firedancer_tpu.flamenco import bincode as bc
from firedancer_tpu.flamenco import crds_types as ct

DIR = os.path.join(os.path.dirname(__file__), "golden", "agave")


def _load(name: str) -> bytes:
    with open(os.path.join(DIR, name), "rb") as f:
        return f.read()


def _roundtrip(name: str):
    raw = _load(name)
    variant, v = ct.decode_msg(raw)
    assert ct.encode_msg(variant, v) == raw
    return variant, v


def test_pull_req_roundtrip():
    variant, v = _roundtrip("gossip_pull_req.bin")
    assert variant == "pull_req"
    flt = v["filter"]
    # gossip_pull_req.yml: 3 bloom keys, mask_bits 6
    assert flt["mask_bits"] == 6
    assert flt["filter"]["keys"] == [
        1017661136073509108, 9141639801749198208, 2457319821573164756]
    kind, ci = v["value"]["data"]
    assert kind == "contact_info_v1"


def test_contact_info_v1():
    variant, v = _roundtrip("gossip_pull_resp_contact_info.bin")
    assert variant == "pull_resp"
    kind, ci = v["crds"][0]["data"]
    assert kind == "contact_info_v1"
    # values from gossip_pull_resp_contact_info.yml
    assert base58.encode(ci["id"]) == \
        "9Diwct7c6braQnne86jutswAW4iZmPfcg6VHVp4FBrLn"
    ipkind, _ip = ci["gossip"]["addr"]
    assert ipkind == "ip4"


def test_contact_info_v2_varint_compact():
    """The v2 contact info exercises every exotic encoding at once:
    varint wallclock, varint version fields, compact (shortvec) addr and
    socket tables."""
    variant, v = _roundtrip("gossip_pull_resp_contact_info_v2.bin")
    # the capture's push_msg carries [v1, v2] — the v2 value is second
    kind, ci = v["crds"][1]["data"]
    assert kind == "contact_info_v2"
    assert base58.encode(ci["from"]) == \
        "Hm5NNNZpBgAo5j3gRwJtkHXihpLzdCyP3WRWHLzcPSup"
    assert len(ci["addrs"]) >= 1
    assert ci["addrs"][0][0] == "ip4"
    assert len(ci["sockets"]) >= 1


def test_node_instance():
    variant, v = _roundtrip("gossip_pull_resp_node_instance.bin")
    kind, ni = v["crds"][0]["data"]
    assert kind == "node_instance"
    assert ni["token"] != 0


def test_snapshot_hashes():
    variant, v = _roundtrip("gossip_pull_resp_snapshot_hashes.bin")
    kind, sh = v["crds"][0]["data"]
    assert kind == "snapshot_hashes"
    assert len(sh["hashes"]) >= 1
    assert all(len(h["hash"]) == 32 for h in sh["hashes"])


def test_version():
    variant, v = _roundtrip("gossip_pull_resp_version.bin")
    kind, ver = v["crds"][0]["data"]
    assert kind in ("version_v1", "version_v2")


def test_push_vote_embedded_txn():
    """The gossip vote carries a full wire transaction; the embedded-txn
    combinator must delimit it exactly and the payload must parse as a
    valid vote txn."""
    variant, v = _roundtrip("gossip_push_vote.bin")
    assert variant == "push_msg"
    kind, vote = v["crds"][0]["data"]
    assert kind == "vote"
    parsed = txn_lib.parse(bytes(vote["txn"]))
    assert parsed.signature_cnt >= 1


def test_txn_vote_parses():
    """The capture is (wire txn | reference-parsed struct dump); the
    partial parser must delimit the 440-byte wire txn exactly and its
    first signature matches txn_vote.yml."""
    raw = _load("txn_vote.bin")
    parsed, used = txn_lib.parse(raw, partial=True)
    assert used == 440 and parsed.signature_cnt == 2
    assert base58.encode(parsed.signatures(raw)[0]) == (
        "2yGd7N4nJJP3Mpjr7JguB8xnCRiMRYLeqPePCjZUqU8KX5JaeqhE18fQQqV7"
        "n6X99joo17wwgb28hgd68FXdz7e")


def test_vote_account_state():
    """Agave vote-account data decodes via VOTE_STATE_VERSIONED with the
    .yml's documented field values."""
    raw = _load("vote_account.bin")
    kind, st = bc.loads(bc.VOTE_STATE_VERSIONED, raw, exact=False)
    assert kind == "current"
    assert base58.encode(st["node_pubkey"]) == \
        "7QsvAtWRqjhQRjd7BzGVT29x5KrUFqZA1T8pVrHGdxeP"
    assert base58.encode(st["authorized_withdrawer"]) == \
        "9frWPHZmLVAkZBUZveujokPi2sQRTucnztr3vnCveZBQ"
    assert st["commission"] == 0
    assert len(st["votes"]) == 1
    assert st["votes"][0]["lockout"]["slot"] == 1
    assert st["votes"][0]["lockout"]["confirmation_count"] == 1
    assert st["root_slot"] == 0
    av = st["authorized_voters"]
    assert len(av) == 1 and av[0]["epoch"] == 0
    assert base58.encode(av[0]["pubkey"]) == \
        "9frWPHZmLVAkZBUZveujokPi2sQRTucnztr3vnCveZBQ"


def test_agave_vote_account_through_snapshot_restore(tmp_path):
    """End-to-end: the REAL Agave vote-account bytes ride an Agave-layout
    snapshot archive (append-vec record -> zstd tar), restore into the
    account db, decode via the type layer, and banking resumes on top —
    genuine foreign account state flowing through snapshot -> runtime
    (VERDICT r4 #5's reachable core in an offline container)."""
    import io
    import struct
    import tarfile

    import zstandard

    from firedancer_tpu.flamenco import genesis as gen_mod
    from firedancer_tpu.flamenco import snapshot_manifest as man
    from firedancer_tpu.flamenco import system_program as sysprog
    from firedancer_tpu.flamenco.runtime import Runtime
    from firedancer_tpu.flamenco.types import (SYSTEM_PROGRAM_ID,
                                               VOTE_PROGRAM_ID)
    from firedancer_tpu.ops import ed25519 as ed

    vote_data = _load("vote_account.bin")
    vote_pk = base58.decode("7QsvAtWRqjhQRjd7BzGVT29x5KrUFqZA1T8pVrHGdxeP",
                            want_len=32)

    faucet_seed = (7).to_bytes(32, "little")
    faucet_pk = ed.keypair_from_seed(faucet_seed)[0]
    g = gen_mod.create(faucet_pk, creation_time=1_700_000_000,
                       slots_per_epoch=32)
    gh = g.genesis_hash()
    slot, bank_hash = 5, b"\x5a" * 32

    def record(pk, lamports, data, owner, execu, rent_epoch=0):
        out = struct.pack("<QQ32s", 0, len(data), pk)
        out += struct.pack("<QQ32sB7x", lamports, rent_epoch, owner, execu)
        out += bytes(32)
        out += data + bytes((8 - len(data) % 8) % 8)
        return out

    vec = (record(faucet_pk, 10**15, b"", SYSTEM_PROGRAM_ID, 0)
           + record(vote_pk, 27_074_400, vote_data, VOTE_PROGRAM_ID, 0))
    manifest = {
        "bank": man.default_bank(slot, bank_hash, b"\xcd" * 32, [gh],
                                 genesis_creation_time=g.creation_time,
                                 slots_per_epoch=32),
        "accounts_db": man.default_accounts_db(
            slot, [(slot, 0, len(vec))], bank_hash),
        "lamports_per_signature": 5000,
    }
    tar_buf = io.BytesIO()
    with tarfile.open(fileobj=tar_buf, mode="w") as tar:
        for name, data in (("version", b"1.2.0"),
                           (f"snapshots/{slot}/{slot}",
                            man.encode_manifest(manifest)),
                           (f"accounts/{slot}.0", vec)):
            ti = tarfile.TarInfo(name)
            ti.size = len(data)
            tar.addfile(ti, io.BytesIO(data))
    path = str(tmp_path / "agave_vote.tar.zst")
    with open(path, "wb") as f:
        f.write(zstandard.ZstdCompressor(level=3).compress(
            tar_buf.getvalue()))

    rt = Runtime.from_snapshot(g, path)
    acct = rt.accdb.load(None, vote_pk)
    assert acct is not None and acct.owner == VOTE_PROGRAM_ID
    kind, st = bc.loads(bc.VOTE_STATE_VERSIONED, acct.data, exact=False)
    assert kind == "current"
    assert base58.encode(st["node_pubkey"]) == \
        "7QsvAtWRqjhQRjd7BzGVT29x5KrUFqZA1T8pVrHGdxeP"

    # a slot replays on top of the restored state
    b = rt.new_bank(slot + 1)
    dest = ed.keypair_from_seed((8).to_bytes(32, "little"))[0]
    msg = txn_lib.build_unsigned(
        [faucet_pk], gh, [(2, bytes([0, 1]), sysprog.ix_transfer(1234))],
        extra_accounts=[dest, SYSTEM_PROGRAM_ID],
        readonly_unsigned_cnt=1)
    res = b.execute_txn(txn_lib.assemble([ed.sign(faucet_seed, msg)], msg))
    assert res.ok
    b.freeze(b"\x11" * 32)
    rt.publish(slot + 1)
    assert rt.balance(dest) == 1234
