"""QUIC + TLS 1.3 + AES-GCM + X.509 stack tests (the analogue of the
reference's quic unit/conformance tests, src/waltz/quic/tests/, its TLS
tests src/waltz/tls/test_tls.c, and the AES CAVP vectors
src/ballet/aes/test_aes.c — known-answer vectors + live handshakes over
in-memory and real-UDP transports)."""

import os
import time

import pytest

from firedancer_tpu.ballet.aes import (
    AesGcm,
    _Ghash,
    _gmul_bit,
    aes_ecb_mask,
    aes_encrypt_block,
    aes_key_expand,
)
from firedancer_tpu.ballet.x509 import (
    cert_create,
    cert_pubkey,
    cert_verify_self_signed,
)
from firedancer_tpu.ops.ed25519 import keypair_from_seed, sign, verify_one_host
from firedancer_tpu.waltz import tls as tls_mod
from firedancer_tpu.waltz.aio import Aio, Pkt
from firedancer_tpu.waltz.quic import (
    QuicConfig,
    QuicEndpoint,
    dec_varint,
    enc_varint,
    initial_keys,
)
from firedancer_tpu.waltz.tls import APP, HANDSHAKE, TlsEndpoint, TlsError
from firedancer_tpu.waltz.udpsock import UdpSock

# --------------------------------------------------------------------- AES


def test_aes_fips197_known_answers():
    rk = aes_key_expand(bytes.fromhex("000102030405060708090a0b0c0d0e0f"))
    ct = aes_encrypt_block(rk, bytes.fromhex("00112233445566778899aabbccddeeff"))
    assert ct.hex() == "69c4e0d86a7b0430d8cdb78070b4c55a"
    rk = aes_key_expand(
        bytes.fromhex(
            "000102030405060708090a0b0c0d0e0f101112131415161718191a1b1c1d1e1f"
        )
    )
    ct = aes_encrypt_block(rk, bytes.fromhex("00112233445566778899aabbccddeeff"))
    assert ct.hex() == "8ea2b7ca516745bfeafc49904b496089"


def test_ghash_table_matches_bitwise():
    import random

    r = random.Random(1234)
    h = r.getrandbits(128)
    g = _Ghash(h)
    for _ in range(32):
        z = r.getrandbits(128)
        g.acc = 0
        g.update_block(z.to_bytes(16, "big"))
        assert g.acc == _gmul_bit(z, h)


def test_aes_gcm_nist_vectors():
    # NIST GCM spec test cases 3 & 4 (AES-128)
    key = bytes.fromhex("feffe9928665731c6d6a8f9467308308")
    iv = bytes.fromhex("cafebabefacedbaddecaf888")
    pt = bytes.fromhex(
        "d9313225f88406e5a55909c5aff5269a86a7a9531534f7da2e4c303d8a318a72"
        "1c3c0c95956809532fcf0e2449a6b525b16aedf5aa0de657ba637b391aafd255"
    )
    g = AesGcm(key)
    out = g.encrypt(iv, pt)
    assert out[:-16].hex() == (
        "42831ec2217774244b7221b784d0d49ce3aa212f2c02a4e035c17e2329aca12e"
        "21d514b25466931c7d8f6a5aac84aa051ba30b396a0aac973d58e091473f5985"
    )
    assert out[-16:].hex() == "4d5c2af327cd64a62cf35abd2ba6fab4"
    aad = bytes.fromhex("feedfacedeadbeeffeedfacedeadbeefabaddad2")
    out4 = g.encrypt(iv, pt[:60], aad)
    assert out4[-16:].hex() == "5bc94fbc3221a5db94fae95ae7121a47"
    assert g.decrypt(iv, out4, aad) == pt[:60]
    # tag tamper -> None
    assert g.decrypt(iv, out4[:-1] + bytes([out4[-1] ^ 1]), aad) is None
    # empty plaintext, empty aad (test case 1 shape)
    g0 = AesGcm(bytes(16))
    out0 = g0.encrypt(bytes(12), b"")
    assert out0.hex() == "58e2fccefa7e3061367f1d57a4e7455a"


# -------------------------------------------------------------------- x509


def test_x509_roundtrip_and_self_signature():
    seed = bytes.fromhex(
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60"
    )
    pub, _, _ = keypair_from_seed(seed)
    der = cert_create(seed, pub)
    assert cert_pubkey(der) == pub
    assert cert_verify_self_signed(der)
    bad = bytearray(der)
    bad[-1] ^= 1
    assert not cert_verify_self_signed(bytes(bad))
    with pytest.raises(ValueError):
        cert_pubkey(b"\x30\x03\x02\x01\x00")


def test_host_verifier_rfc8032():
    seed = bytes.fromhex(
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60"
    )
    pub, _, _ = keypair_from_seed(seed)
    sig = sign(seed, b"")
    assert sig.hex().startswith("e5564300c360ac72")
    assert verify_one_host(sig, b"", pub)
    assert not verify_one_host(sig, b"x", pub)


# --------------------------------------------------------------------- TLS


def _pump(cl, sv, rounds=6, frag=0):
    for _ in range(rounds):
        for lvl, m in cl.take_outbox():
            if frag:
                for i in range(0, len(m), frag):
                    sv.feed(lvl, m[i : i + frag])
            else:
                sv.feed(lvl, m)
        for lvl, m in sv.take_outbox():
            if frag:
                for i in range(0, len(m), frag):
                    cl.feed(lvl, m[i : i + frag])
            else:
                cl.feed(lvl, m)
        if cl.complete and sv.complete:
            return


def test_tls_handshake_mutual_auth():
    cl = TlsEndpoint(
        is_server=False, identity_seed=os.urandom(32), transport_params=b"C"
    )
    sv = TlsEndpoint(
        is_server=True, identity_seed=os.urandom(32), transport_params=b"S"
    )
    _pump(cl, sv)
    assert cl.complete and sv.complete
    assert cl.secrets[HANDSHAKE] == sv.secrets[HANDSHAKE]
    assert cl.secrets[APP] == sv.secrets[APP]
    assert cl.peer_pubkey == sv.pubkey
    assert sv.peer_pubkey == cl.pubkey
    assert cl.peer_transport_params == b"S"
    assert sv.peer_transport_params == b"C"


def test_tls_handshake_fragmented_delivery():
    cl = TlsEndpoint(is_server=False, identity_seed=os.urandom(32))
    sv = TlsEndpoint(is_server=True, identity_seed=os.urandom(32))
    _pump(cl, sv, frag=1)
    assert cl.complete and sv.complete


def test_tls_no_client_cert():
    cl = TlsEndpoint(is_server=False, identity_seed=os.urandom(32))
    sv = TlsEndpoint(
        is_server=True, identity_seed=os.urandom(32), require_client_cert=False
    )
    _pump(cl, sv)
    assert cl.complete and sv.complete
    assert sv.peer_pubkey is None
    assert cl.peer_pubkey == sv.pubkey


def test_tls_supported_versions_no_substring_match():
    from firedancer_tpu.waltz.tls import _offers_tls13

    assert _offers_tls13(b"\x02\x03\x04")
    assert _offers_tls13(b"\x04\x7f\x1c\x03\x04")
    # 0x0304 spanning two entries (0x0103, 0x0400) must NOT match
    assert not _offers_tls13(b"\x04\x01\x03\x04\x00")
    assert not _offers_tls13(b"")


def test_tls_handshake_buffer_bounded():
    """A claimed 16 MB handshake message must be refused, not buffered
    (unauthenticated memory exhaustion)."""
    sv = TlsEndpoint(is_server=True, identity_seed=os.urandom(32))
    sv.feed(0, b"\x01\xff\xff\xff")  # ClientHello claiming 2^24-1 bytes
    with pytest.raises(TlsError):
        for _ in range(20):
            sv.feed(0, b"\x00" * 8192)


def test_tls_tampered_finished_rejected():
    cl = TlsEndpoint(is_server=False, identity_seed=os.urandom(32))
    sv = TlsEndpoint(is_server=True, identity_seed=os.urandom(32))
    for lvl, m in cl.take_outbox():
        sv.feed(lvl, m)
    flight = sv.take_outbox()
    # flip a byte inside the server Finished (the last handshake message)
    with pytest.raises(TlsError):
        for lvl, m in flight:
            if m[0] == 20:  # Finished
                m = m[:-1] + bytes([m[-1] ^ 1])
            cl.feed(lvl, m)


# -------------------------------------------------------------------- QUIC


def test_quic_initial_keys_rfc9001():
    dcid = bytes.fromhex("8394c8f03e515708")
    rx, tx = initial_keys(dcid, is_server=False)
    assert tx.aead.rk == aes_key_expand(
        bytes.fromhex("1f369613dd76d5467730efcbe3b1a22d")
    )
    assert tx.iv.hex() == "fa044b2f42a3fd3b46fb255c"
    assert tx.hp.hex() == "9f50449e04a0e810283a1e9933adedd2"
    assert rx.iv.hex() == "0ac1493ca1905853b0bba03e"
    # server view swaps
    srx, stx = initial_keys(dcid, is_server=True)
    assert srx.iv == tx.iv and stx.iv == rx.iv


def test_varint_roundtrip():
    for v in (0, 63, 64, 16383, 16384, 2**30 - 1, 2**30, 2**62 - 1):
        b = enc_varint(v)
        got, n = dec_varint(b, 0)
        assert got == v and n == len(b)


def _mem_pair(server_cfg=None, client_cfg=None):
    c2s, s2c = [], []
    cl = QuicEndpoint(
        client_cfg or QuicConfig(identity_seed=os.urandom(32)),
        Aio(lambda p: c2s.extend(p) or len(p)),
    )
    sv = QuicEndpoint(
        server_cfg
        or QuicConfig(identity_seed=os.urandom(32), is_server=True),
        Aio(lambda p: s2c.extend(p) or len(p)),
    )
    return cl, sv, c2s, s2c


def test_quic_handshake_and_txn_streams():
    cl, sv, c2s, s2c = _mem_pair()
    got, done = [], []
    sv.on_stream = lambda conn, sid, data: got.append(data)
    cl.on_handshake_complete = lambda conn: done.append("c")
    sv.on_handshake_complete = lambda conn: done.append("s")
    now = 0.0
    conn = cl.connect(("10.0.0.2", 9001))
    # every client datagram containing an Initial packet must be >= 1200B
    assert len(c2s[0].payload) >= 1200
    sent = False
    for _ in range(30):
        now += 0.01
        if c2s:
            pkts, c2s[:] = list(c2s), []
            sv.rx(pkts, now)
        if s2c:
            pkts, s2c[:] = list(s2c), []
            cl.rx(pkts, now)
        if conn.handshake_done and not sent:
            sent = True
            for t in range(20):
                assert conn.send_txn(b"txn-%03d" % t + bytes(100)) is not None
            cl.service(now)
        if len(got) >= 20:
            break
    assert "c" in done and "s" in done
    assert len(got) == 20
    assert got[0][:7] == b"txn-000" and len(got[0]) == 107
    # mutual cert identity: server learned the client's ed25519 key
    sconn = list(sv.conns.values())[0]
    assert sconn.tls.peer_pubkey == cl.conns[conn.scid].tls.pubkey


def test_quic_lossy_transport_retransmits():
    cl, sv, c2s, s2c = _mem_pair()
    got = []
    sv.on_stream = lambda conn, sid, data: got.append(data)
    conn = cl.connect(("10.0.0.3", 9001))
    drop = [0]
    sent = [False]
    now = 0.0

    def _lossy(pkts):
        keep = []
        for p in pkts:
            drop[0] += 1
            if drop[0] % 3 != 0:  # drop every 3rd datagram
                keep.append(p)
        return keep

    for i in range(600):
        now += 0.05
        if c2s:
            pkts, c2s[:] = list(c2s), []
            sv.rx(_lossy(pkts), now)
        if s2c:
            pkts, s2c[:] = list(s2c), []
            cl.rx(_lossy(pkts), now)
        if conn.handshake_done and not sent[0]:
            sent[0] = True
            for t in range(5):
                conn.send_txn(b"lossy-%d" % t)
        cl.service(now)
        sv.service(now)
        if len(got) >= 5:
            break
    assert len(got) >= 5


def test_quic_bad_packet_ignored():
    cl, sv, c2s, s2c = _mem_pair()
    now = 1.0
    sv.rx([Pkt(b"\xff" + os.urandom(40), ("z", 1))], now)  # garbage long hdr
    sv.rx([Pkt(os.urandom(3), ("z", 1))], now)  # runt
    assert sv.conns == {}
    # valid-looking initial for unknown version is dropped
    sv.rx([Pkt(b"\xc0\x00\x00\x00\x05" + bytes(60), ("z", 1))], now)
    assert sv.metrics["conn_created"] == 0
    # truncated header claiming a huge dcid len must not raise (one bad
    # datagram must never kill the ingest tile)
    before = sv.metrics["pkt_malformed"]
    sv.rx([Pkt(b"\xc0\x00\x00\x00\x01\xff" + bytes(10), ("z", 1))], now)
    assert sv.metrics["pkt_malformed"] == before + 1
    assert sv.conns == {}


def test_quic_spoofed_initial_creates_no_conn():
    """1200B of garbage with an Initial-shaped header must cost the server
    only one failed AEAD check — no conn state, no TLS endpoint."""
    cl, sv, c2s, s2c = _mem_pair()
    pkt = bytearray()
    pkt += b"\xc3" + (1).to_bytes(4, "big")  # long hdr, Initial, pn_len=4
    pkt += bytes([8]) + os.urandom(8)  # dcid
    pkt += bytes([8]) + os.urandom(8)  # scid
    pkt += b"\x00"  # empty token
    pkt += enc_varint(1180) + os.urandom(1180)
    sv.rx([Pkt(bytes(pkt), ("z", 1))], 1.0)
    assert sv.conns == {} and sv.metrics["conn_created"] == 0
    assert sv.metrics["pkt_undecryptable"] == 1


def test_quic_forged_header_cannot_redirect_conn():
    """A garbage long-header packet naming a live conn's CID (cleartext,
    so observable) must not change where we address that conn."""
    cl, sv, c2s, s2c = _mem_pair()
    now = 0.0
    conn = cl.connect(("10.0.0.9", 9001))
    for _ in range(10):
        now += 0.01
        if c2s:
            pkts, c2s[:] = list(c2s), []
            sv.rx(pkts, now)
        if s2c:
            pkts, s2c[:] = list(s2c), []
            cl.rx(pkts, now)
        if conn.handshake_done:
            break
    assert conn.handshake_done
    good_dcid = conn.dcid
    evil = bytearray()
    evil += b"\xe3" + (1).to_bytes(4, "big")  # long hdr, Handshake type
    evil += bytes([8]) + conn.scid  # dcid = the client conn's CID
    evil += bytes([8]) + b"EVILCID9"[:8]  # attacker scid
    evil += enc_varint(40) + os.urandom(40)
    cl.rx([Pkt(bytes(evil), ("6.6.6.6", 666))], now)
    assert conn.dcid == good_dcid  # unauthenticated packet changed nothing


def test_quic_idle_timeout_reaps_conns():
    cl, sv, c2s, s2c = _mem_pair(
        server_cfg=QuicConfig(
            identity_seed=os.urandom(32), is_server=True, idle_timeout=0.5
        )
    )
    closed = []
    sv.on_conn_closed = lambda conn: closed.append(conn.uid)
    now = 0.0
    conn = cl.connect(("10.0.0.4", 9001))
    for _ in range(10):
        now += 0.01
        if c2s:
            pkts, c2s[:] = list(c2s), []
            sv.rx(pkts, now)
        if s2c:
            pkts, s2c[:] = list(s2c), []
            cl.rx(pkts, now)
        if conn.handshake_done:
            break
    assert sv.conns
    sv.service(now + 10.0)  # way past idle timeout
    assert sv.conns == {} and closed


def test_quic_ack_span_bounded_against_hostile_ranges():
    """A peer ACK claiming a 2^61-wide range must not spin the event loop
    (the hostile-ACK DoS the reference guards with bounded conn state)."""
    from firedancer_tpu.waltz.quic import _PnSpace, _SentPkt, _ack_span

    sp = _PnSpace()
    for pn in (1, 5, 900):
        sp.sent[pn] = _SentPkt([], 0.0, True)
    t0 = time.monotonic()
    _ack_span(sp, 0, 1 << 61)
    assert time.monotonic() - t0 < 1.0
    assert sp.sent == {}


def test_quic_rx_pn_state_bounded():
    from firedancer_tpu.waltz.quic import _PnSpace

    sp = _PnSpace()
    for pn in range(0, 100_000, 2):  # gappy: worst case for range tracking
        sp.rx_pns.add(pn)
        sp.largest_rx = pn
        sp.prune()
    assert len(sp.rx_pns) <= 1025
    assert sp.rx_floor >= 100_000 - 2 - 1024


def test_quic_server_tile_topology():
    """QUIC client -> quic_server tile -> verify-less sink link: boots the
    tile in a real multi-process topology and delivers txns over live QUIC
    (the reference's quic-tile integration test shape,
    src/app/fdctl/run/tiles/fd_quic.c + SURVEY.md §4.4)."""
    from firedancer_tpu.disco.run import TopoRun
    from firedancer_tpu.disco.topo import TopoBuilder

    n = 8
    spec = (
        TopoBuilder(f"quicsrv{os.getpid()}", wksp_mb=16)
        .link("quic_sink", depth=256, mtu=1280)
        .tile("quic_server", "quic_server", outs=["quic_sink"], port=0)
        .tile("sink", "sink", ins=["quic_sink"])
        .build()
    )
    with TopoRun(spec) as run:
        run.wait_ready(timeout=120)
        port = run.metrics("quic_server")["bound_port"]
        assert port != 0
        csock = UdpSock(bind_ip="127.0.0.1", burst=256)
        try:
            cl = QuicEndpoint(
                QuicConfig(identity_seed=os.urandom(32)), csock.aio()
            )
            conn = cl.connect(("127.0.0.1", port), now=time.monotonic())
            deadline = time.monotonic() + 60
            sent = False
            while time.monotonic() < deadline:
                now = time.monotonic()
                pkts = csock.recv_burst()
                if pkts:
                    cl.rx(pkts, now)
                if conn.handshake_done and not sent:
                    sent = True
                    for t in range(n):
                        conn.send_txn(b"tile-txn-%d" % t)
                cl.service(now)
                if run.metrics("sink")["frag_cnt"] == n:
                    break
                time.sleep(0.002)
            assert run.metrics("sink")["frag_cnt"] == n
            assert run.metrics("quic_server")["reasm_pub_cnt"] == n
            assert run.poll() is None
        finally:
            csock.close()


def test_quic_over_real_udp_sockets():
    """Live client->server over loopback UDP (the reference's netns/loopback
    integration pattern, SURVEY.md §4.4)."""
    ssock = UdpSock(bind_ip="127.0.0.1", burst=256)
    csock = UdpSock(bind_ip="127.0.0.1", burst=256)
    try:
        sv = QuicEndpoint(
            QuicConfig(identity_seed=os.urandom(32), is_server=True),
            ssock.aio(),
        )
        cl = QuicEndpoint(
            QuicConfig(identity_seed=os.urandom(32)), csock.aio()
        )
        got = []
        sv.on_stream = lambda conn, sid, data: got.append(data)
        conn = cl.connect(("127.0.0.1", ssock.port), now=time.monotonic())
        deadline = time.monotonic() + 20
        sent = False
        while time.monotonic() < deadline and len(got) < 10:
            now = time.monotonic()
            spkts = ssock.recv_burst()
            if spkts:
                sv.rx(spkts, now)
            cpkts = csock.recv_burst()
            if cpkts:
                cl.rx(cpkts, now)
            if conn.handshake_done and not sent:
                sent = True
                for t in range(10):
                    conn.send_txn(b"udp-txn-%d" % t)
            cl.service(now)
            sv.service(now)
            time.sleep(0.001)
        assert len(got) == 10
        assert sorted(got)[0] == b"udp-txn-0"
    finally:
        ssock.close()
        csock.close()


# ---------------------------------------------------------------- security
# Regression tests for the off-path attack surface: frame-type-per-level
# validation (RFC 9000 §12.4), pre-handshake stream gating, the 3x
# anti-amplification limit (§8.1) and PTO backoff (RFC 9002 §6.2).


def _forge_initial(dcid: bytes, scid: bytes, frames: bytes, pn: int = 0,
                   pad_to: int = 0) -> bytes:
    """Craft a client Initial packet for arbitrary frames, valid under the
    dcid-derived Initial keys (what any off-path attacker can do)."""
    from firedancer_tpu.ballet.aes import aes_encrypt_block
    from firedancer_tpu.waltz.quic import QUIC_VERSION

    _, tx = initial_keys(dcid, is_server=False)
    payload = frames
    if len(payload) < 4:
        payload += bytes(4 - len(payload))
    hdr = (b"\xc3" + QUIC_VERSION.to_bytes(4, "big")
           + bytes([len(dcid)]) + dcid + bytes([len(scid)]) + scid
           + enc_varint(0))  # empty token
    overhead = len(hdr) + 2 + 4 + 16  # len varint (2B) + pn + tag
    if pad_to and overhead + len(payload) < pad_to:
        payload += bytes(pad_to - overhead - len(payload))
    length = 4 + len(payload) + 16
    hdr += (length | 0x4000).to_bytes(2, "big")
    pn_bytes = pn.to_bytes(4, "big")
    header = hdr + pn_bytes
    ct = tx.aead.encrypt(tx.nonce(pn), payload, header)
    pkt = bytearray(header + ct)
    pn_off = len(hdr)
    sample = bytes(pkt[pn_off + 4:pn_off + 20])
    mask = aes_encrypt_block(tx.hp_rk, sample)
    pkt[0] ^= mask[0] & 0x0F
    for i in range(4):
        pkt[pn_off + i] ^= mask[1 + i]
    return bytes(pkt)


def test_initial_stream_frame_rejected():
    """STREAM frames are 1-RTT-only: an off-path forged Initial carrying
    one must never reach on_stream (it killed the conn instead)."""
    cl, sv, c2s, s2c = _mem_pair()
    got = []
    sv.on_stream = lambda conn, sid, data: got.append(data)
    # STREAM frame: type 0x0F (off+len+fin), sid 2, off 0, len 5, "evil!"
    frame = bytes([0x0F]) + enc_varint(2) + enc_varint(0) + enc_varint(5) \
        + b"evil!"
    pkt = _forge_initial(os.urandom(8), os.urandom(8), frame, pad_to=1200)
    sv.rx([Pkt(pkt, ("6.6.6.6", 666))], 1.0)
    assert got == []
    assert sv.conns == {}  # protocol violation tore the conn down


def test_handshake_done_from_initial_rejected():
    cl, sv, c2s, s2c = _mem_pair()
    pkt = _forge_initial(os.urandom(8), os.urandom(8), b"\x1e", pad_to=1200)
    sv.rx([Pkt(pkt, ("6.6.6.6", 667))], 1.0)
    assert sv.conns == {}


def test_amplification_capped_at_3x():
    """A spoofed-source Initial must draw at most 3x its bytes from the
    server, across the whole PTO/idle lifetime of the induced conn."""
    cl, sv, c2s, s2c = _mem_pair()
    # legit-looking CRYPTO-less Initial: PING + padding (decrypts fine,
    # creates conn state, but the 'client' never answers)
    pkt = _forge_initial(os.urandom(8), os.urandom(8), b"\x01", pad_to=1200)
    rx_bytes = len(pkt)
    now = 1.0
    sv.rx([Pkt(pkt, ("6.6.6.6", 668))], now)
    for _ in range(400):  # 20 simulated seconds of PTO/idle servicing
        now += 0.05
        sv.service(now)
    sent = sum(len(p.payload) for p in s2c)
    assert sent <= 3 * rx_bytes, (sent, rx_bytes)
    assert sv.conns == {}  # idle/PTO teardown happened


def test_pto_backoff_bounds_retransmits():
    """Exponential PTO backoff: an unanswered conn must produce O(max_pto)
    retransmit rounds, not one every fixed 150ms until idle timeout."""
    cl, sv, c2s, s2c = _mem_pair()
    conn = cl.connect(("10.9.9.9", 9))  # server never answers
    now = 0.0
    for _ in range(600):  # 30 simulated seconds
        now += 0.05
        cl.service(now)
    # crypto flight is 1-2 packets; with backoff the retrans metric stays
    # small (<= max_pto rounds x packets), where fixed-interval PTO would
    # emit ~66 rounds before the idle timeout
    assert cl.metrics["retrans"] <= (cl.cfg.max_pto + 1) * 3
    assert conn.closed or cl.conns == {}


# ----------------------------------------------------------------- retry


def test_quic_retry_handshake_completes():
    """With server-side Retry enabled (ref fd_quic.c:1175-1260), the
    handshake round-trips through the token exchange and completes; the
    server mints exactly one Retry and creates conn state only after the
    token comes back."""
    sv_cfg = QuicConfig(identity_seed=os.urandom(32), is_server=True,
                        retry=True)
    cl, sv, c2s, s2c = _mem_pair(server_cfg=sv_cfg)
    got, done = [], []
    sv.on_stream = lambda conn, sid, data: got.append(data)
    sv.on_handshake_complete = lambda conn: done.append("s")
    now = 0.0
    conn = cl.connect(("10.0.0.7", 9007))
    # first flight: server answers with ONLY a Retry, zero conn state
    pkts, c2s[:] = list(c2s), []
    sv.rx(pkts, now)
    assert sv.conns == {} and sv.metrics["conn_created"] == 0
    assert sv.metrics["retry_tx"] == 1
    sent = False
    for _ in range(40):
        now += 0.01
        if s2c:
            pkts, s2c[:] = list(s2c), []
            cl.rx(pkts, now)
        if c2s:
            pkts, c2s[:] = list(c2s), []
            sv.rx(pkts, now)
        if conn.handshake_done and not sent:
            sent = True
            assert conn.send_txn(b"post-retry-txn") is not None
            cl.service(now)
        if got:
            break
    assert conn.handshake_done and "s" in done
    assert got == [b"post-retry-txn"]
    assert sv.metrics["retry_tx"] == 1
    assert sv.metrics["retry_token_accept"] == 1
    assert conn.token  # the client presented the token


def test_quic_retry_tokenless_initial_creates_no_state():
    """A VALID (properly keyed) Initial from a spoofed source elicits one
    Retry datagram and nothing else: no conn, no TLS endpoint — the
    VERDICT's attack shape (attacker forces conn state + handshake
    crypto per spoofed Initial) is closed."""
    sv_cfg = QuicConfig(identity_seed=os.urandom(32), is_server=True,
                        retry=True)
    cl, sv, c2s, s2c = _mem_pair(server_cfg=sv_cfg)
    cl.connect(("10.0.0.8", 9008))
    assert c2s
    for _ in range(5):  # replay the same Initial from 5 "sources"
        sv.rx([Pkt(c2s[0].payload, ("spoof", 1))], 0.0)
    assert sv.conns == {} and sv._initial_conns == {}
    assert sv.metrics["conn_created"] == 0
    assert sv.metrics["retry_tx"] == 5  # stateless: one Retry per Initial


def test_quic_retry_token_bound_to_address():
    """A token minted for one source address fails from another (the AAD
    binding), and a garbage token is rejected."""
    sv_cfg = QuicConfig(identity_seed=os.urandom(32), is_server=True,
                        retry=True)
    cl, sv, c2s, s2c = _mem_pair(server_cfg=sv_cfg)
    conn = cl.connect(("10.0.0.9", 9009))
    first_initial = c2s[0].payload
    c2s[:] = []
    sv.rx([Pkt(first_initial, ("1.2.3.4", 55))], 0.0)  # retry to 1.2.3.4
    assert sv.metrics["retry_tx"] == 1
    retry_pkt = s2c[-1].payload
    s2c[:] = []
    cl.rx([Pkt(retry_pkt, ("10.0.0.9", 9009))], 0.0)   # client applies it
    assert conn.token
    tokened_initial = c2s[-1].payload
    # replayed from a DIFFERENT source: token fails to open, no state
    sv.rx([Pkt(tokened_initial, ("6.6.6.6", 66))], 0.0)
    assert sv.conns == {} and sv.metrics["retry_token_reject"] == 1
    # from the minted address: accepted
    sv.rx([Pkt(tokened_initial, ("1.2.3.4", 55))], 0.0)
    assert sv.metrics["retry_token_accept"] == 1
    assert len(sv.conns) == 1


def test_quic_retry_tampered_tag_ignored():
    """A Retry whose integrity tag doesn't verify must not rekey the
    client (an off-path attacker could otherwise stall the handshake)."""
    sv_cfg = QuicConfig(identity_seed=os.urandom(32), is_server=True,
                        retry=True)
    cl, sv, c2s, s2c = _mem_pair(server_cfg=sv_cfg)
    conn = cl.connect(("10.0.0.10", 9010))
    sv.rx([Pkt(c2s[0].payload, ("10.0.0.10", 9010))], 0.0)
    retry_pkt = bytearray(s2c[-1].payload)
    retry_pkt[-1] ^= 1                                  # break the tag
    cl.rx([Pkt(bytes(retry_pkt), ("10.0.0.10", 9010))], 0.0)
    assert not conn.token                               # not applied
    assert cl.metrics["pkt_malformed"] >= 1

