"""Bottleneck attribution + SLO engine + flight recorder tests.

The live test runs three Mux loops as THREADS over one created topology
(the test_observability pattern): an artificially slow sink consumer
must backpressure the middle tile, charge the sink's fseq slow diag,
and come out of `attrib.bottleneck` as THE named bottleneck link — the
tentpole's acceptance scenario, in the fast tier.
"""

import json
import os
import sys
import threading
import time

import numpy as np

from firedancer_tpu.disco import attrib
from firedancer_tpu.disco import flightrec
from firedancer_tpu.disco import metrics as metrics_mod
from firedancer_tpu.disco import slo
from firedancer_tpu.disco import topo as topo_mod
from firedancer_tpu.disco import trace as trace_mod
from firedancer_tpu.disco.mux import Mux
from firedancer_tpu.disco.topo import TopoBuilder
from firedancer_tpu.tango.fctl import Fctl
from firedancer_tpu.tango.ring import Cnc, FSeq
from firedancer_tpu.utils.hist import Histf

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))


def _wait(pred, timeout_s, what=""):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise TimeoutError(f"timed out waiting for {what}")


# -- Histf edge cases --------------------------------------------------------

def test_histf_empty_percentile_is_zero():
    h = Histf(100, 10e9)
    assert h.percentile(0.50) == 0.0
    assert h.percentile(0.99) == 0.0
    assert h.count() == 0 and h.overflow_cnt() == 0


def test_histf_overflow_only():
    h = Histf(100, 10e9)
    h.sample(1e12)          # way past max_val: lands in the overflow slot
    h.sample(2e12)
    assert h.count() == 2 and h.overflow_cnt() == 2
    # percentile clamps to the top finite edge: the histogram can only
    # say "at least max_val", never invent a value past its range
    assert h.percentile(0.50) == float(h.edges[-1])
    assert h.percentile(0.99) == float(h.edges[-1])


def test_histf_single_sample():
    h = Histf(100, 10e9)
    h.sample(5_000)
    # every quantile of a one-sample distribution is that sample's bucket
    edge = float(h.edges[np.searchsorted(h.edges, 5_000)])
    for q in (0.01, 0.50, 0.99, 1.0):
        assert h.percentile(q) == edge
    assert h.overflow_cnt() == 0


# -- Fctl stall accounting ---------------------------------------------------

def test_fctl_stall_attribution_counters():
    app = f"fctlat{os.getpid()}"
    spec = (
        TopoBuilder(app, wksp_mb=8)
        .link("a_b", depth=4, mtu=64)
        .tile("src", "sink", outs=["a_b"])
        .tile("dst", "sink", ins=["a_b"])
        .build()
    )
    jt = topo_mod.create(spec)
    try:
        mc = jt.links["a_b"].mcache
        fseq = jt.fseq[("dst", "a_b")]
        fctl = Fctl(cr_max=4).rx_add(fseq)
        seq = mc.seq0()
        fseq.update(seq)
        while fctl.consume(1):          # drain every credit
            mc.publish(0)
            seq += 1
            fctl.tx_cr_update(seq)
        assert fctl.backp_cnt == 1      # entered backpressure once
        assert fctl.backp_exit_cnt == 0
        time.sleep(0.002)               # measurable stall
        fseq.update(seq)                # consumer catches up
        assert fctl.tx_cr_update(seq) > 0
        assert fctl.backp_exit_cnt == 1
        assert fctl.stall_ns >= 2_000_000, \
            f"stall_ns lost the wait: {fctl.stall_ns}"
    finally:
        jt.close()
        jt.unlink()


# -- exposition conformance --------------------------------------------------

def test_prometheus_render_extra_families_and_escaping():
    app = f"expo{os.getpid()}"
    spec = (
        TopoBuilder(app, wksp_mb=8)
        .link("a_b", depth=64, mtu=256)
        .tile("src", "sink", outs=["a_b"])
        .tile("dst", "sink", ins=["a_b"])
        .build()
    )
    jt = topo_mod.create(spec)
    try:
        jt.metrics["src"].add("out_frag_cnt", 3)
        jt.metrics["dst"].add("in_frag_cnt", 3)
        extra = [
            ("fdtpu_link_lag", "gauge", "consumer seq lag",
             {"link": "a_b", "producer": "src", "consumer": "dst"}, 7),
            ("fdtpu_link_lag", "gauge", "consumer seq lag",
             {"link": "a_b", "producer": "src", "consumer": "dst2"}, 9),
            ("fdtpu_link_note", "counter", "label escaping probe",
             {"who": 'we"ird\\name\nnewline'}, 1),
        ]
        body = metrics_mod.prometheus_render(jt.metrics, extra=extra)
        # one HELP + one TYPE per family, even across tiles/links
        for fam in ("fdtpu_out_frag_cnt", "fdtpu_in_frag_cnt",
                    "fdtpu_link_lag"):
            assert body.count(f"# TYPE {fam} ") == 1, fam
            assert body.count(f"# HELP {fam} ") == 1, fam
        assert 'consumer="dst"} 7' in body
        assert 'consumer="dst2"} 9' in body
        # escaped per the text exposition format: \\ then \" then \n
        assert 'who="we\\"ird\\\\name\\nnewline"' in body
        assert "\nnewline" not in body.split('who="')[1].split("}")[0]
        # declarations precede their samples
        assert body.index("# TYPE fdtpu_link_lag ") \
            < body.index('fdtpu_link_lag{')
    finally:
        jt.close()
        jt.unlink()


# -- SLO engine over synthetic spans ----------------------------------------

def _spans(rows):
    recs = np.zeros(len(rows), dtype=trace_mod.TRACE_REC_DTYPE)
    for i, r in enumerate(rows):
        for k, v in r.items():
            recs[i][k] = v
    return recs


def test_slo_stage_stats_budgets_and_burn_trend():
    us = 1_000
    spans = {
        "q": _spans([{"kind": trace_mod.KIND_STAGE, "ts": t * us,
                      "dur": 20 * us} for t in range(10)]),
        "v": _spans(
            [{"kind": trace_mod.KIND_FRAG, "ts": t * us, "dur": 5 * us,
              "hop_ns": 30 * us} for t in range(10)]
            + [{"kind": trace_mod.KIND_DEVICE, "ts": t * us,
                "dur": 5_000 * us} for t in range(10)]),
        # sink ages: first half under the 2ms target, second half over
        "s": _spans(
            [{"kind": trace_mod.KIND_FRAG, "ts": t * us, "dur": us,
              "age_ns": 500 * us} for t in range(10)]
            + [{"kind": trace_mod.KIND_FRAG, "ts": (100 + t) * us,
                "dur": us, "age_ns": 9_000 * us} for t in range(10)]),
    }
    kind_of = {"q": "quic_server", "v": "verify", "s": "sink"}
    stats = {r["stage"]: r for r in slo.stage_stats(spans, kind_of, 2.0)}
    assert stats["wire"]["n"] == 10 and stats["wire"]["ok"], \
        "20us wire p99 fits the 100us wire budget"
    assert stats["ring-wait"]["n"] == 10 and stats["ring-wait"]["ok"]
    assert stats["device"]["n"] == 10 and not stats["device"]["ok"], \
        "5ms device p99 must bust the 0.7ms device budget"
    assert stats["publish"]["n"] == 0 and stats["publish"]["ok"], \
        "a stage with no samples cannot fail"

    b = slo.burn(spans, kind_of, 2.0)
    assert b["n"] == 20
    assert abs(b["rate"] - 0.5) < 1e-9
    assert b["trend"] == "up" and b["rate_second"] > b["rate_first"]

    table = slo.render_table(slo.stage_stats(spans, kind_of, 2.0), b, 2.0)
    assert "device" in table and "OVER" in table
    assert "burn rate: 50.0%" in table and "trend up" in table


def test_slo_burn_falls_back_to_verify_ages():
    # no terminal tile in the topology: the verify tile's own age stamps
    # still grade the chain up to dispatch admission
    us = 1_000
    spans = {"v": _spans([{"kind": trace_mod.KIND_BURST, "ts": t * us,
                           "dur": us, "age_ns": 9_000 * us}
                          for t in range(4)])}
    b = slo.burn(spans, {"v": "verify"}, 2.0)
    assert b["n"] == 4 and b["rate"] == 1.0


# -- the acceptance scenario: slow consumer -> named bottleneck --------------

class _SrcVt:
    """Publishes n frags from after_credit, a few per loop pass."""

    def __init__(self, n):
        self.n = n
        self.sent = 0

    def after_credit(self, ctx):
        for _ in range(min(8, self.n - self.sent)):
            ctx.publish(bytes([self.sent & 0xFF]) * 32, sig=self.sent)
            self.sent += 1


class _FwdVt:
    def on_frag(self, ctx, iidx, meta, payload):
        ctx.publish(payload, sig=int(meta["sig"]))


class _SlowSinkVt:
    """The artificially slow consumer: 2ms per frag."""

    def on_frag(self, ctx, iidx, meta, payload):
        time.sleep(0.002)


def test_bottleneck_names_slow_consumer_link():
    n = 400
    spec = (
        TopoBuilder(f"attr{os.getpid()}", wksp_mb=8)
        # wide first hop so src never stalls; narrow second hop so the
        # slow sink pins mid in _wait_credit
        .link("a_b", depth=1024, mtu=256)
        .link("b_c", depth=16, mtu=256)
        .tile("src", "sink", outs=["a_b"])
        .tile("mid", "sink", ins=["a_b"], outs=["b_c"])
        .tile("snk", "sink", ins=["b_c"])
        .build()
    )
    jt = topo_mod.create(spec)
    try:
        muxes = {"src": Mux(jt, "src", _SrcVt(n)),
                 "mid": Mux(jt, "mid", _FwdVt()),
                 "snk": Mux(jt, "snk", _SlowSinkVt())}
        threads = [threading.Thread(target=m.run, daemon=True)
                   for m in muxes.values()]
        for t in threads:
            t.start()
        _wait(lambda: jt.metrics["snk"].get("in_frag_cnt") >= 32,
              30, "the slow sink to be mid-stream")

        prev = attrib.link_sample(jt)
        time.sleep(0.6)
        cur = attrib.link_sample(jt)

        link, reason = attrib.bottleneck(prev, cur)
        assert link == "mid->snk (b_c)", f"verdict blamed {link}: {reason}"
        assert "slow consumer snk" in reason, reason

        # the producer charged the sink's fseq slow diag (the fd_fctl
        # receiver-diag contract)
        assert jt.fseq[("snk", "b_c")].diag(FSeq.DIAG_SLOW_CNT) > 0
        # mid spent real wall time backpressured; gauges flowed at
        # housekeeping
        assert cur["tiles"]["mid"]["backp_ns"] > 0
        assert cur["tiles"]["mid"]["out"]["b_c"]["occ_hwm"] > 0

        # the terminal frame renders, verdict line included
        frame = attrib.render_top(spec, prev, cur)
        assert any(ln.startswith("bottleneck: mid->snk (b_c)")
                   for ln in frame), frame[-1]
        assert any(ln.startswith("TILE") for ln in frame)

        # /metrics extra families carry the producer->consumer labels
        fams = attrib.link_families(jt)
        names = {f[0] for f in fams}
        assert {"fdtpu_link_lag", "fdtpu_link_slow_cnt",
                "fdtpu_link_occ_hwm", "fdtpu_link_frag_rate"} <= names
        slow = [f for f in fams if f[0] == "fdtpu_link_slow_cnt"
                and f[3]["consumer"] == "snk"]
        assert slow and slow[0][3]["producer"] == "mid"
        assert slow[0][4] > 0
        body = metrics_mod.prometheus_render(jt.metrics, extra=fams)
        assert body.count("# TYPE fdtpu_link_slow_cnt ") == 1
        assert 'fdtpu_link_slow_cnt{link="b_c",producer="mid",' \
               'consumer="snk"}' in body

        for cnc in jt.cnc.values():
            cnc.signal(Cnc.SIGNAL_HALT)
        for t in threads:
            t.join(10)
            assert not t.is_alive()
        # regime accounting closes the books: all four regimes flushed
        msnap = jt.metrics["mid"].snapshot()
        assert msnap["busy_ns"] > 0 and msnap["backp_ns"] > 0
        assert msnap["house_ns"] > 0
    finally:
        jt.close()
        jt.unlink()


# -- flight recorder ---------------------------------------------------------

def test_flight_bundle_roundtrip_and_render(tmp_path):
    spec = (
        TopoBuilder(f"fltr{os.getpid()}", wksp_mb=8)
        .link("a_b", depth=64, mtu=256)
        .tile("src", "sink", outs=["a_b"])
        .tile("mid", "verify", ins=["a_b"])
        .build()
    )
    jt = topo_mod.create(spec)
    try:
        t0 = time.monotonic_ns()
        for i in range(5):
            jt.trace["mid"].record(trace_mod.KIND_FRAG, t0 + i, 1_000,
                                   hop_ns=2_000, age_ns=3_000, seq=i)
        jt.trace["mid"].record(trace_mod.KIND_DEVICE, t0 + 9, 400_000)
        jt.metrics["mid"].add("in_frag_cnt", 5)
        jt.metrics["mid"].hist_sample("in_hop_ns", 2_000)
        jt.cnc["mid"].signal(Cnc.SIGNAL_FAIL)
        jt.fseq[("mid", "a_b")].diag_add(FSeq.DIAG_SLOW_CNT, 3)

        cfg = {"observability": {"slo_target_ms": 2.0},
               "secret": object()}   # default=str must absorb this
        path = flightrec.write_bundle(
            str(tmp_path), jt, reason="crash", tile="mid",
            restarts={"mid": 2}, config=cfg,
            events=["00:00:01 spawn mid gen=0 pid=1",
                    "00:00:02 tile mid failed (restarts=2)"])

        b = flightrec.load_bundle(path)
        assert b["manifest"]["reason"] == "crash"
        assert b["manifest"]["tile"] == "mid"
        assert b["manifest"]["tiles"]["mid"]["cnc"] == "FAIL"
        assert b["manifest"]["tiles"]["mid"]["restarts"] == 2
        assert len(b["spans"]["mid"]) == 6
        assert b["spans"]["mid"].dtype == trace_mod.TRACE_REC_DTYPE
        assert b["metrics"]["mid"]["slots"]["in_frag_cnt"] == 5
        assert b["links"]["links"]["a_b|mid"]["slow"] == 3
        assert b["links"]["links"]["a_b|mid"]["producer"] == "src"
        assert len(b["events"]) == 2

        out = flightrec.render_bundle(path)
        assert "reason crash" in out and "tile mid" in out
        assert "bottleneck at death:" in out
        assert "slow consumer mid" in out   # the bundled diag drove it
        assert "final spans of mid:" in out
        assert "device" in out              # the final span listing
        assert "stage budget vs 2 ms" in out
        # a second bundle in the same second gets a disambiguated dir
        path2 = flightrec.write_bundle(str(tmp_path), jt, reason="crash",
                                       tile="mid")
        assert path2 != path and os.path.isdir(path2)
    finally:
        jt.close()
        jt.unlink()


# -- log context -------------------------------------------------------------

def test_log_context_tags_records(capsys):
    import logging

    from firedancer_tpu.utils import log as log_mod
    logger = log_mod.boot(level="DEBUG")
    try:
        log_mod.set_context("verify:0", 0)
        log_mod.notice("hello")
        assert " verify:0 hello" in capsys.readouterr().err
        log_mod.set_context("verify:0", 3)   # post-respawn generation
        log_mod.notice("again")
        assert " verify:0#3 again" in capsys.readouterr().err
        log_mod.set_context("", 0)           # supervisor default
        log_mod.notice("sup")
        assert " - sup" in capsys.readouterr().err
    finally:
        log_mod.set_context("", 0)
        logger.handlers.clear()
        logging.shutdown()


# -- bench_diff --------------------------------------------------------------

def _bench_file(d, n, value, metric="vps", unit="verifies/sec"):
    p = d / f"BENCH_r{n:02d}.json"
    p.write_text(json.dumps({
        "n": n, "rc": 0,
        "parsed": {"metric": metric, "value": value, "unit": unit}}))


def test_bench_diff_flags_regressions(tmp_path, capsys):
    import bench_diff

    _bench_file(tmp_path, 1, 100_000.0)
    _bench_file(tmp_path, 2, 104_000.0)
    _bench_file(tmp_path, 3, 90_000.0)   # -13.5%: regression
    rc = bench_diff.main(["--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 3
    assert "REGRESSION vps" in out and "r02 -> r03" in out

    # within threshold -> clean exit
    _bench_file(tmp_path, 4, 89_000.0)   # -1.1% vs r03
    assert bench_diff.main(["--root", str(tmp_path)]) == 0

    # lower-is-better metrics regress UPWARD
    for f in tmp_path.glob("BENCH_r*.json"):
        f.unlink()
    _bench_file(tmp_path, 1, 1_000.0, metric="e2e_latency", unit="ns")
    _bench_file(tmp_path, 2, 1_200.0, metric="e2e_latency", unit="ns")
    rc = bench_diff.main(["--root", str(tmp_path)])
    assert rc == 3
    assert "REGRESSION e2e_latency" in capsys.readouterr().out

    # nothing to diff is not an error (fresh clone)
    assert bench_diff.main(["--root", str(tmp_path),
                            "--glob", "NOPE_r*.json"]) == 0
