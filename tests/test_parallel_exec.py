"""Wave-parallel block execution (VERDICT r2 missing #6; ref
fd_runtime_block_eval_tpool, src/flamenco/runtime/fd_runtime.h:194):
account-lock wave planning, process-pool execution, and the bit-exact
bank-hash equivalence with serial replay that lthash commutativity
guarantees.  The >=2x wall-clock claim is asserted only on multi-core
hosts (this CI box has 1 core; the fork-pool architecture is exercised
either way by forcing workers=4)."""

import os
import time

import pytest

from firedancer_tpu.ballet import entry as entry_lib
from firedancer_tpu.ballet import txn as txn_lib
from firedancer_tpu.flamenco import genesis as gen_mod
from firedancer_tpu.flamenco import replay as replay_mod
from firedancer_tpu.flamenco import system_program as sysprog
from firedancer_tpu.flamenco.parallel_exec import plan_waves
from firedancer_tpu.flamenco.runtime import Runtime
from firedancer_tpu.flamenco.types import SYSTEM_PROGRAM_ID, Account
from firedancer_tpu.ops import ed25519 as ed


def _keypair(i):
    seed = i.to_bytes(32, "little")
    return seed, ed.keypair_from_seed(seed)[0]


def _transfer(src, dest, amount, bh, nonce=0):
    seed, pk = src
    msg = txn_lib.build_unsigned(
        [pk], bh,
        [(2, bytes([0, 1]), sysprog.ix_transfer(amount + nonce * 0))],
        extra_accounts=[dest, SYSTEM_PROGRAM_ID],
        readonly_unsigned_cnt=1)
    return txn_lib.assemble([ed.sign(seed, msg)], msg)


def test_wave_planning_conflicts_serialize():
    """Writers to one account land in distinct waves, in block order;
    disjoint txns share wave 0; a reader serializes after a writer."""
    payers = [_keypair(10 + i) for i in range(4)]
    bh = b"\x11" * 32
    shared = b"\x51" + bytes(31)
    d0, d1 = b"\x52" + bytes(31), b"\x53" + bytes(31)

    payloads = [
        _transfer(payers[0], shared, 1, bh),    # writes shared
        _transfer(payers[1], shared, 2, bh),    # writes shared -> wave 1
        _transfer(payers[2], d0, 3, bh),        # disjoint -> wave 0
        _transfer(payers[3], d1, 4, bh),        # disjoint -> wave 0
    ]

    def addrs_of(parsed, payload):
        a = list(parsed.account_addrs(payload))
        return a, [parsed.is_writable(i) for i in range(len(a))]

    waves = plan_waves(payloads, addrs_of)
    idx_wave = {p.idx: w for w, wave in enumerate(waves) for p in wave}
    assert idx_wave[0] == 0 and idx_wave[1] == 1     # conflict serializes
    assert idx_wave[2] == 0 and idx_wave[3] == 0     # disjoint in wave 0
    # block order preserved for the conflicting pair
    assert waves[0][0].idx == 0


@pytest.fixture
def chain():
    faucet_seed, faucet_pk = _keypair(1)
    g = gen_mod.create(faucet_pk, creation_time=1_700_000_000,
                       slots_per_epoch=64)
    payers = [_keypair(100 + i) for i in range(32)]
    for _, pk in payers:
        g.accounts[pk] = Account(lamports=1_000_000_000)
    return g, payers


def _block(g, payers, n_txn=32):
    bh = g.genesis_hash()
    poh = bytes(32)
    entries = []
    payloads = []
    for i in range(n_txn):
        dest = b"\xd0" + bytes(15) + i.to_bytes(16, "little")
        payloads.append(_transfer(payers[i % len(payers)], dest,
                                  1000 + i, bh))
    mix = entry_lib.txn_mixin(payloads)
    poh = entry_lib.next_hash(poh, 1, mix)
    entries.append(entry_lib.Entry(1, poh, payloads))
    poh = entry_lib.next_hash(poh, 1, None)
    entries.append(entry_lib.Entry(1, poh, []))
    return entries


def test_parallel_matches_serial_bank_hash(chain):
    g, payers = chain
    entries = _block(g, payers)

    rt_serial = Runtime(g)
    res_s = replay_mod.replay_slot(rt_serial, 1, entries, bytes(32))
    assert res_s.ok, res_s.err

    rt_par = Runtime(g)
    res_p = replay_mod.replay_slot(rt_par, 1, entries, bytes(32), workers=4)
    assert res_p.ok, res_p.err
    assert res_p.bank_hash == res_s.bank_hash
    assert res_p.txn_cnt == res_s.txn_cnt == 32
    assert res_p.txn_fail_cnt == res_s.txn_fail_cnt == 0

    # state equivalence beyond the hash: spot-check a destination
    rt_serial.publish(1)
    rt_par.publish(1)
    dest = b"\xd0" + bytes(15) + (5).to_bytes(16, "little")
    assert rt_par.balance(dest) == rt_serial.balance(dest) == 1005


def test_parallel_with_conflicts_and_failures(chain):
    """Conflicting txns (same fee payer: writable account shared) are
    wave-serialized; duplicate transfers from one payer both land;
    failing txns (insufficient funds) fold in identically."""
    g, payers = chain
    bh = g.genesis_hash()
    poor_seed, poor_pk = _keypair(999)
    g.accounts[poor_pk] = Account(lamports=6_000)  # fee, no transfer
    payloads = []
    dest = b"\xdd" + bytes(31)
    for i in range(10):
        payloads.append(_transfer(payers[0], dest, 100, bh, nonce=i))
    payloads.append(_transfer((poor_seed, poor_pk), dest, 1_000_000, bh))
    for i in range(10):
        payloads.append(_transfer(payers[1 + i % 8], dest, 50, bh, nonce=i))
    poh = entry_lib.next_hash(bytes(32), 1, entry_lib.txn_mixin(payloads))
    entries = [entry_lib.Entry(1, poh, payloads)]

    rt_s = Runtime(g)
    res_s = replay_mod.replay_slot(rt_s, 1, entries, bytes(32))
    rt_p = Runtime(g)
    res_p = replay_mod.replay_slot(rt_p, 1, entries, bytes(32), workers=4)
    assert res_s.ok and res_p.ok
    assert res_p.bank_hash == res_s.bank_hash
    assert res_p.txn_fail_cnt == res_s.txn_fail_cnt >= 1


@pytest.mark.skipif((os.cpu_count() or 1) < 4,
                    reason="speedup needs a multi-core host")
def test_parallel_speedup(chain):
    """>=2x on 4+ cores with a compute-heavy block (the VERDICT gate);
    skipped on this 1-core CI box, runs where cores exist."""
    g, payers = chain
    entries = _block(g, payers, n_txn=256)
    rt_s = Runtime(g)
    t0 = time.perf_counter()
    res_s = replay_mod.replay_slot(rt_s, 1, entries, bytes(32))
    t_serial = time.perf_counter() - t0
    rt_p = Runtime(g)
    t0 = time.perf_counter()
    res_p = replay_mod.replay_slot(rt_p, 1, entries, bytes(32),
                                   workers=os.cpu_count())
    t_par = time.perf_counter() - t0
    assert res_p.bank_hash == res_s.bank_hash
    assert t_par * 2 <= t_serial, (t_par, t_serial)
