"""Agave-layout snapshot manifest (VERDICT r2 missing #2): the bincode
type surface (fd_solana_manifest, fd_types.h:905-1229) and a
golden-fixture restore — an archive built INDEPENDENTLY of snapshot.save
from the schema layer restores into funk and resumes banking."""

import io
import struct
import tarfile

import pytest

from firedancer_tpu.flamenco import bincode as bc
from firedancer_tpu.flamenco import genesis as gen_mod
from firedancer_tpu.flamenco import snapshot as snap
from firedancer_tpu.flamenco import snapshot_manifest as man
from firedancer_tpu.flamenco.runtime import Runtime
from firedancer_tpu.flamenco.types import SYSTEM_PROGRAM_ID, Account
from firedancer_tpu.ops import ed25519 as ed


def test_fixed_size_layouts():
    """Wire sizes the reference documents as fixed (fd_types.h):
    fee_calculator 8, rent 17, epoch_schedule 33, delegation 64,
    bank_hash_stats 40, incremental persistence 88, acc_vec 16."""
    assert len(bc.encode(man.FEE_CALCULATOR,
                         {"lamports_per_signature": 1})) == 8
    assert len(bc.encode(man.RENT, {"lamports_per_uint8_year": 1,
                                    "exemption_threshold": 2.0,
                                    "burn_percent": 50})) == 17
    assert len(bc.encode(man.EPOCH_SCHEDULE, {
        "slots_per_epoch": 32, "leader_schedule_slot_offset": 32,
        "warmup": False, "first_normal_epoch": 0,
        "first_normal_slot": 0})) == 33
    assert len(bc.encode(man.DELEGATION, {
        "voter_pubkey": bytes(32), "stake": 1, "activation_epoch": 0,
        "deactivation_epoch": 2**64 - 1,
        "warmup_cooldown_rate": 0.25})) == 64
    assert len(bc.encode(man.BANK_HASH_STATS, {
        "num_updated_accounts": 0, "num_removed_accounts": 0,
        "num_lamports_stored": 0, "total_data_len": 0,
        "num_executable_accounts": 0})) == 40
    assert len(bc.encode(man.INCREMENTAL_PERSISTENCE, {
        "full_slot": 1, "full_hash": bytes(32), "full_capitalization": 2,
        "incremental_hash": bytes(32),
        "incremental_capitalization": 3})) == 88
    assert len(bc.encode(man.SNAPSHOT_ACC_VEC, {"id": 1,
                                                "file_sz": 2})) == 16


def test_manifest_roundtrip_with_trailing_options():
    bank = man.default_bank(7, b"\x11" * 32, b"\x22" * 32,
                            [b"\x33" * 32, b"\x44" * 32],
                            genesis_creation_time=1000,
                            slots_per_epoch=32)
    # populate the dynamic sections so the roundtrip exercises them
    bank["stakes"]["vote_accounts"] = [{
        "key": b"\x55" * 32, "stake": 9_000,
        "value": {"lamports": 1_000, "data": list(b"votedata"),
                  "owner": b"\x66" * 32, "executable": False,
                  "rent_epoch": 0}}]
    bank["stakes"]["stake_delegations"] = [{
        "account": b"\x77" * 32,
        "delegation": {"voter_pubkey": b"\x55" * 32, "stake": 9_000,
                       "activation_epoch": 0,
                       "deactivation_epoch": 2**64 - 1,
                       "warmup_cooldown_rate": 0.25}}]
    bank["stakes"]["stake_history"] = [{
        "epoch": 0, "effective": 9_000, "activating": 0,
        "deactivating": 0}]
    m = {
        "bank": bank,
        "accounts_db": man.default_accounts_db(7, [(7, 0, 1234)],
                                               b"\x11" * 32),
        "lamports_per_signature": 5000,
    }
    raw = man.encode_manifest(m)
    got = man.decode_manifest(raw)
    assert got["bank"]["slot"] == 7
    assert got["bank"]["stakes"]["vote_accounts"][0]["stake"] == 9_000
    assert bytes(got["bank"]["hash"]) == b"\x11" * 32
    assert got["accounts_db"]["storages"][0]["account_vecs"][0][
        "file_sz"] == 1234
    assert "incremental_snapshot_persistence" not in got

    # trailing options present (upstream's stream framing)
    m2 = dict(m)
    m2["incremental_snapshot_persistence"] = {
        "full_slot": 5, "full_hash": b"\x01" * 32,
        "full_capitalization": 10, "incremental_hash": b"\x02" * 32,
        "incremental_capitalization": 2}
    m2["epoch_account_hash"] = b"\x03" * 32
    got2 = man.decode_manifest(man.encode_manifest(m2))
    assert got2["incremental_snapshot_persistence"]["full_slot"] == 5
    assert bytes(got2["epoch_account_hash"]) == b"\x03" * 32

    # unknown trailing bytes are rejected, not silently skipped
    with pytest.raises(bc.BincodeError):
        man.decode_manifest(raw + b"\x01\x02")


def test_golden_fixture_restore_resumes_banking(tmp_path):
    """Build the archive BY HAND from the schema layer (not snapshot.save):
    tar(version, bincode manifest, append-vec with the fd_solana_account_hdr
    record shape) -> zstd -> Runtime.from_snapshot executes a transfer."""
    import zstandard

    faucet_seed = (99).to_bytes(32, "little")
    faucet_pk = ed.keypair_from_seed(faucet_seed)[0]
    g = gen_mod.create(faucet_pk, creation_time=1_700_000_000,
                       slots_per_epoch=32)
    gh = g.genesis_hash()
    slot, bank_hash = 3, b"\xab" * 32

    # append-vec: faucet + one extra account, hand-packed records
    def record(pk, lamports, data, owner, execu, rent_epoch=0):
        out = struct.pack("<QQ32s", 0, len(data), pk)
        out += struct.pack("<QQ32sB7x", lamports, rent_epoch, owner, execu)
        out += bytes(32)                       # stored account hash
        out += data + bytes((8 - len(data) % 8) % 8)
        return out

    extra_pk = ed.keypair_from_seed((50).to_bytes(32, "little"))[0]
    vec = (record(faucet_pk, 10**15, b"", SYSTEM_PROGRAM_ID, 0)
           + record(extra_pk, 777, b"\x01\x02\x03", SYSTEM_PROGRAM_ID, 0))

    manifest = {
        "bank": man.default_bank(slot, bank_hash, b"\xcd" * 32, [gh],
                                 genesis_creation_time=g.creation_time,
                                 slots_per_epoch=32),
        "accounts_db": man.default_accounts_db(
            slot, [(slot, 0, len(vec))], bank_hash),
        "lamports_per_signature": 5000,
    }

    tar_buf = io.BytesIO()
    with tarfile.open(fileobj=tar_buf, mode="w") as tar:
        def add(name, data):
            ti = tarfile.TarInfo(name)
            ti.size = len(data)
            tar.addfile(ti, io.BytesIO(data))
        add("version", b"1.2.0")
        add(f"snapshots/{slot}/{slot}", man.encode_manifest(manifest))
        add(f"accounts/{slot}.0", vec)
    path = str(tmp_path / "agave_layout.tar.zst")
    with open(path, "wb") as f:
        f.write(zstandard.ZstdCompressor(level=3).compress(
            tar_buf.getvalue()))

    rt = Runtime.from_snapshot(g, path)
    assert rt.root_slot == slot and rt.root_hash == bank_hash
    assert rt.balance(extra_pk) == 777

    # banking resumes on the restored state
    from firedancer_tpu.ballet import txn as txn_lib
    from firedancer_tpu.flamenco import system_program as sysprog
    b = rt.new_bank(slot + 1)
    msg = txn_lib.build_unsigned(
        [faucet_pk], gh, [(2, bytes([0, 1]), sysprog.ix_transfer(4444))],
        extra_accounts=[extra_pk, SYSTEM_PROGRAM_ID],
        readonly_unsigned_cnt=1)
    payload = txn_lib.assemble([ed.sign(faucet_seed, msg)], msg)
    res = b.execute_txn(payload)
    assert res.ok, res.err
    assert rt.accdb.load(b.xid, extra_pk).lamports == 777 + 4444


def test_size_mismatch_rejected(tmp_path):
    """An append-vec shorter than the manifest's declared file_sz must be
    refused (fd_snapshot_restore.c:338-360)."""
    import zstandard

    faucet_pk = ed.keypair_from_seed((99).to_bytes(32, "little"))[0]
    g = gen_mod.create(faucet_pk, creation_time=1)
    manifest = {
        "bank": man.default_bank(1, b"\x01" * 32, bytes(32), [bytes(32)]),
        "accounts_db": man.default_accounts_db(1, [(1, 0, 9999)],
                                               b"\x01" * 32),
        "lamports_per_signature": 5000,
    }
    tar_buf = io.BytesIO()
    with tarfile.open(fileobj=tar_buf, mode="w") as tar:
        for name, data in (("version", b"1.2.0"),
                           ("snapshots/1/1", man.encode_manifest(manifest)),
                           ("accounts/1.0", b"short")):
            ti = tarfile.TarInfo(name)
            ti.size = len(data)
            tar.addfile(ti, io.BytesIO(data))
    path = str(tmp_path / "bad.tar.zst")
    with open(path, "wb") as f:
        f.write(zstandard.ZstdCompressor().compress(tar_buf.getvalue()))
    with pytest.raises(ValueError, match="manifest says"):
        snap.load(path)
