"""Pack scheduler tests: cost model, priority order, account-conflict
scheduling across bank lanes, and block-limit accounting (the contracts of
src/ballet/pack/fd_pack.c / fd_pack_cost.h)."""

import numpy as np
import pytest

from firedancer_tpu.ballet import pack, txn as txn_lib


def _mk_txn(
    signer: bytes,
    writable_extra: list[bytes] = (),
    readonly_extra: list[bytes] = (),
    program: bytes = b"\x07" * 32,
    data: bytes = b"\x00" * 8,
    cu_price: int | None = None,
):
    """One-signer txn: accounts = [signer(w)] + writable_extra + readonly_extra
    + [program(r)]."""
    extra = list(writable_extra) + list(readonly_extra) + [program]
    n_accts = 1 + len(extra)
    prog_idx = n_accts - 1
    instrs = [(prog_idx, bytes([0]), data)]
    if cu_price is not None:
        cb = pack.COMPUTE_BUDGET_PROG_ID
        extra = list(writable_extra) + list(readonly_extra) + [program, cb]
        n_accts = 1 + len(extra)
        prog_idx = n_accts - 2
        instrs = [
            (prog_idx, bytes([0]), data),
            (n_accts - 1, b"", bytes([3]) + cu_price.to_bytes(8, "little")),
        ]
    msg = txn_lib.build_unsigned(
        [signer],
        b"\x11" * 32,
        instrs,
        extra_accounts=extra,
        readonly_unsigned_cnt=len(readonly_extra) + (2 if cu_price is not None else 1),
    )
    payload = txn_lib.assemble([b"\x5a" * 64], msg)
    return payload, txn_lib.parse(payload)


def _acct(i: int) -> bytes:
    return bytes([i]) * 32


def test_cost_model_components():
    payload, parsed = _mk_txn(_acct(1), data=b"\x00" * 40)
    c = pack.compute_cost(parsed, payload)
    # 1 sig + 1 writable acct + 40/4 data + 1 BPF instr default CU
    want = (
        pack.COST_PER_SIGNATURE
        + pack.COST_PER_WRITABLE_ACCT
        + 40 // pack.INV_COST_PER_INSTR_DATA_BYTE
        + pack.DEFAULT_INSTR_COMPUTE_UNITS
    )
    assert c.total == want
    assert not c.is_simple_vote


def test_cost_model_builtin_and_vote():
    vote_prog = pack.VOTE_PROG_ID
    payload, parsed = _mk_txn(_acct(2), program=vote_prog, data=b"\x00" * 4)
    c = pack.compute_cost(parsed, payload)
    assert c.is_simple_vote
    assert c.total == (
        pack.COST_PER_SIGNATURE
        + pack.COST_PER_WRITABLE_ACCT
        + 1
        + pack.BUILTIN_COSTS[vote_prog]
    )


def test_priority_order_by_reward_per_cost():
    p = pack.Pack(bank_tile_cnt=1)
    lo_payload, lo_parsed = _mk_txn(_acct(1))
    hi_payload, hi_parsed = _mk_txn(_acct(2), cu_price=5_000_000)
    assert p.insert(lo_payload, lo_parsed)
    assert p.insert(hi_payload, hi_parsed)
    mb = p.schedule(0)
    assert mb is not None
    # the paying txn schedules first
    assert mb.txns[0].payload == hi_payload


def test_conflicting_writes_serialize_across_banks():
    p = pack.Pack(bank_tile_cnt=2, max_txn_per_microblock=1)
    shared = _acct(9)
    pay_a, parsed_a = _mk_txn(_acct(1), writable_extra=[shared])
    pay_b, parsed_b = _mk_txn(_acct(2), writable_extra=[shared])
    p.insert(pay_a, parsed_a)
    p.insert(pay_b, parsed_b)

    mb0 = p.schedule(0)
    assert mb0 is not None
    # bank 1 cannot run the other txn: write-write conflict on `shared`
    assert p.schedule(1) is None
    assert p.metrics["delayed_conflict"] >= 1
    p.done(0)
    mb1 = p.schedule(1)
    assert mb1 is not None
    assert mb1.txns[0].payload == pay_b


def test_read_read_parallel_ok():
    p = pack.Pack(bank_tile_cnt=2, max_txn_per_microblock=1)
    shared_ro = _acct(8)
    pay_a, pa = _mk_txn(_acct(1), readonly_extra=[shared_ro])
    pay_b, pb = _mk_txn(_acct(2), readonly_extra=[shared_ro])
    p.insert(pay_a, pa)
    p.insert(pay_b, pb)
    assert p.schedule(0) is not None
    assert p.schedule(1) is not None  # shared read does not conflict


def test_write_read_conflict():
    p = pack.Pack(bank_tile_cnt=2, max_txn_per_microblock=1)
    shared = _acct(7)
    pay_w, pw = _mk_txn(_acct(1), writable_extra=[shared])
    pay_r, pr = _mk_txn(_acct(2), readonly_extra=[shared])
    p.insert(pay_w, pw)
    p.insert(pay_r, pr)
    first = p.schedule(0)
    assert first is not None
    assert p.schedule(1) is None  # w-r conflict either direction
    p.done(0)
    assert p.schedule(1) is not None


def test_intra_microblock_conflicts_rejected():
    # consensus: txns within one entry/microblock must be non-conflicting,
    # so 4 writers of one account serialize into 4 microblocks
    p = pack.Pack(bank_tile_cnt=1, max_txn_per_microblock=8)
    shared = _acct(6)
    for i in range(4):
        pay, pr = _mk_txn(_acct(10 + i), writable_extra=[shared])
        p.insert(pay, pr)
    emitted = 0
    while True:
        mb = p.schedule(0)
        if mb is None:
            break
        assert len(mb.txns) == 1
        emitted += 1
        p.done(0)
    assert emitted == 4


def test_block_cost_limit_respected():
    p = pack.Pack(bank_tile_cnt=1, max_txn_per_microblock=1000)
    # each ~201k CU; 48M/201k ~ 238 txns max per block
    n = 260
    for i in range(n):
        pay, pr = _mk_txn(bytes([i % 250, i // 250]) + b"\x00" * 30)
        p.insert(pay, pr)
    total = 0
    scheduled = 0
    while True:
        mb = p.schedule(0)
        if mb is None:
            break
        scheduled += len(mb.txns)
        total += sum(h.cost.total for h in mb.txns)
        p.done(0)
    assert total <= pack.MAX_COST_PER_BLOCK
    assert scheduled < n  # some txns held for the next block
    leftovers = p.pending
    assert leftovers == n - scheduled
    # next block: remaining txns become schedulable again
    p.end_block()
    assert p.schedule(0) is not None


def test_per_account_write_cost_limit():
    p = pack.Pack(bank_tile_cnt=1, max_txn_per_microblock=1000)
    hot = _acct(5)
    for i in range(80):  # 80 * ~201k > 12M per-acct limit
        pay, pr = _mk_txn(bytes([i]) + b"\x01" * 31, writable_extra=[hot])
        p.insert(pay, pr)
    got = 0
    while True:
        mb = p.schedule(0)
        if mb is None:
            break
        got += sum(h.cost.total for h in mb.txns)
        p.done(0)
    assert got <= pack.MAX_WRITE_COST_PER_ACCT


def test_deterministic_priority_pin():
    # consensus-adjacent: identical inserts must schedule in the exact
    # same order every run (heap tie-break is insertion seq, no dict/hash
    # iteration order anywhere) — a reordering regression shows up as a
    # different microblock stream for the same input
    def build():
        p = pack.Pack(bank_tile_cnt=1, max_txn_per_microblock=8)
        order = [(1, 400_000), (2, 100_000), (3, 400_000), (4, None),
                 (5, 7_000_000)]
        ids = {}
        for i, price in order:
            pay, pr = _mk_txn(_acct(i), cu_price=price)
            ids[pay] = i
            assert p.insert(pay, pr)
        got = []
        while True:
            mb = p.schedule(0)
            if mb is None:
                break
            got.extend(ids[h.payload] for h in mb.txns)
            p.done(0)
        return got

    first = build()
    # price 7M > 400k == 400k (seq tie: insert order, 1 before 3) > 100k
    # > priceless
    assert first == [5, 1, 3, 2, 4]
    assert build() == first


def test_max_pending_cap_with_vote_bypass():
    p = pack.Pack(bank_tile_cnt=1, max_pending=2)
    for i in range(2):
        pay, pr = _mk_txn(_acct(1 + i))
        assert p.insert(pay, pr)
    # heap full: regular txns bounce...
    pay, pr = _mk_txn(_acct(3))
    assert not p.insert(pay, pr)
    assert p.metrics["dropped_heap_full"] == 1
    # ...but simple votes bypass the cap (consensus liveness: a flooded
    # leader must keep voting lanes open, fd_pack's vote reservation)
    vpay, vpr = _mk_txn(_acct(4), program=pack.VOTE_PROG_ID, data=b"\x00" * 4)
    assert p.insert(vpay, vpr)
    assert p.metrics["vote_inserted"] == 1
    assert p.pending == 3


def test_vote_cost_limit_is_continue_not_break():
    # the vote block budget is a per-class carve-out: hitting it must NOT
    # stop regular txns from scheduling in the same block
    p = pack.Pack(bank_tile_cnt=1, max_txn_per_microblock=1000)
    vote_cost = pack.compute_cost(
        *reversed(_mk_txn(_acct(200), program=pack.VOTE_PROG_ID,
                          data=b"\x00" * 4))).total
    n_votes = pack.MAX_VOTE_COST_PER_BLOCK // vote_cost + 5
    for i in range(n_votes):
        pay, pr = _mk_txn(bytes([i % 250, 1 + i // 250]) + b"\x02" * 30,
                          program=pack.VOTE_PROG_ID, data=b"\x00" * 4)
        assert p.insert(pay, pr)
    reg_pay, reg_pr = _mk_txn(_acct(199))
    assert p.insert(reg_pay, reg_pr)
    vote_total = 0
    saw_regular = False
    while True:
        mb = p.schedule(0)
        if mb is None:
            break
        for h in mb.txns:
            if h.cost.is_simple_vote:
                vote_total += h.cost.total
            elif h.payload == reg_pay:
                saw_regular = True
        p.done(0)
    assert vote_total <= pack.MAX_VOTE_COST_PER_BLOCK
    assert saw_regular  # regular txn rode along despite the vote cap


def test_insert_rejects_bank_misuse():
    p = pack.Pack(bank_tile_cnt=1)
    pay, pr = _mk_txn(_acct(1))
    p.insert(pay, pr)
    assert p.schedule(0) is not None
    with pytest.raises(ValueError):
        p.schedule(0)  # still busy
    with pytest.raises(ValueError):
        p.end_block()  # busy bank
    p.done(0)
    p.end_block()
