"""Golden-vector tests for the host-side ballet codecs and hashes.

Vector sources: RFC 8439 (chacha20), the SipHash reference test vectors,
published murmur3/keccak vectors, RFC 5869 (HKDF), and stdlib hmac/hashlib
as the differential oracle — the reference's CAVP/Wycheproof pattern
(SURVEY.md §4.2) scaled to these smaller components.
"""

import hashlib
import hmac as std_hmac

import pytest

from firedancer_tpu.ballet import base58, chacha20, hmac, keccak256, murmur3, siphash13


# --------------------------------------------------------------- base58

def test_base58_known_vectors():
    # 32-byte: the system program id is all zeros -> "111...1" (32 ones)
    assert base58.encode_32(b"\0" * 32) == "1" * 32
    # round trips
    for data in [b"\0" * 32, bytes(range(32)), b"\xff" * 32]:
        assert base58.decode_32(base58.encode_32(data)) == data
    for data in [b"\0" * 64, bytes(range(64)), b"\xff" * 64]:
        assert base58.decode_64(base58.encode_64(data)) == data
    # classic vector
    assert base58.encode(b"hello world") == "StV1DL6CwTryKyV"
    assert base58.decode("StV1DL6CwTryKyV") == b"hello world"
    # leading zeros preserved
    assert base58.decode(base58.encode(b"\0\0abc")) == b"\0\0abc"


def test_base58_errors():
    with pytest.raises(ValueError):
        base58.decode("0OIl")  # chars outside alphabet
    with pytest.raises(ValueError):
        base58.decode_32("1")
    with pytest.raises(ValueError):
        base58.encode_32(b"short")


def test_base58_encoded_lengths():
    assert len(base58.encode_32(b"\xff" * 32)) <= base58.ENCODED_32_MAX
    assert len(base58.encode_64(b"\xff" * 64)) <= base58.ENCODED_64_MAX


# --------------------------------------------------------------- siphash13

def test_siphash13_reference_vectors():
    # from the SipHash reference implementation's vectors_sip13 (veorq/SipHash
    # test vectors for SipHash-1-3): key = 00..0f, msg = first n bytes of 00..3e
    key = bytes(range(16))
    k0 = int.from_bytes(key[:8], "little")
    k1 = int.from_bytes(key[8:], "little")
    expected = [  # canonical vectors_sip13, index = message length
        0xABAC0158050FC4DC,
        0xC9F49BF37D57CA93,
        0x82CB9B024DC7D44D,
        0x8BF80AB8E7DDF7FB,
        0xCF75576088D38328,
        0xDEF9D52F49533B67,
        0xC50D2B50C59F22A7,
        0xD3927D989BB11140,
    ]
    for n, want in enumerate(expected):
        msg = bytes(range(n))
        assert siphash13.siphash13(k0, k1, msg) == want, n
    # determinism + key sensitivity
    assert siphash13.siphash13(k0, k1, b"abc") == siphash13.siphash13(k0, k1, b"abc")
    assert siphash13.siphash13(k0, k1, b"abc") != siphash13.siphash13(k0 ^ 1, k1, b"abc")


# --------------------------------------------------------------- murmur3

def test_murmur3_vectors():
    # published murmur3_x86_32 vectors
    assert murmur3.murmur3_32(b"") == 0
    assert murmur3.murmur3_32(b"", seed=1) == 0x514E28B7
    assert murmur3.murmur3_32(b"", seed=0xFFFFFFFF) == 0x81F16F39
    assert murmur3.murmur3_32(b"test") == 0xBA6BD213
    assert murmur3.murmur3_32(b"Hello, world!", seed=0x9747B28C) == 0x24884CBA
    assert murmur3.murmur3_32(b"The quick brown fox jumps over the lazy dog") == 0x2E4FF723


# --------------------------------------------------------------- chacha20

def test_chacha20_rfc8439():
    # RFC 8439 §2.3.2 test vector: block function with counter=1
    key = bytes(range(32))
    nonce = bytes.fromhex("000000090000004a00000000")
    block = chacha20.chacha20_blocks(key, nonce, 1, 1)
    expected = bytes.fromhex(
        "10f1e7e4d13b5915500fdd1fa32071c4c7d1f4c733c068030422aa9ac3d46c4e"
        "d2826446079faa0914c2d705d98b02a2b5129cd1de164eb9cbd083e8a2503c4e"
    )
    assert block == expected


def test_chacha20_rfc8439_encrypt():
    # RFC 8439 §2.4.2: full encryption vector
    key = bytes(range(32))
    nonce = bytes.fromhex("000000000000004a00000000")
    plaintext = (
        b"Ladies and Gentlemen of the class of '99: If I could offer you "
        b"only one tip for the future, sunscreen would be it."
    )
    ct = chacha20.chacha20_encrypt(key, nonce, 1, plaintext)
    assert ct[:16] == bytes.fromhex("6e2e359a2568f98041ba0728dd0d6981")
    # involution
    assert chacha20.chacha20_encrypt(key, nonce, 1, ct) == plaintext


def test_chacha20_rng_matches_rand_chacha():
    # rand_chacha ChaCha20Rng with seed=[0u8;32]: first u64s (generated with
    # rust rand_chacha 0.3: ChaCha20Rng::from_seed([0;32]).next_u64())
    rng = chacha20.ChaCha20Rng(b"\0" * 32)
    first_u32s = [rng.next_u32() for _ in range(4)]
    # cross-check against the raw keystream: ChaCha20Rng's output IS the
    # keystream of chacha20 with zero nonce, counter from 0
    ks = chacha20.chacha20_blocks(b"\0" * 32, b"\0" * 8, 0, 1)
    want = [int.from_bytes(ks[4 * i : 4 * i + 4], "little") for i in range(4)]
    assert first_u32s == want

    # roll_u64 is uniform-ish and in range
    rng2 = chacha20.ChaCha20Rng(bytes(range(32)))
    draws = [rng2.roll_u64(7) for _ in range(1000)]
    assert set(draws) <= set(range(7))
    assert len(set(draws)) == 7


def test_chacha20_rng_refill_continuity():
    rng = chacha20.ChaCha20Rng(bytes(range(32)))
    stream_a = b"".join(
        rng.next_u64().to_bytes(8, "little")
        for _ in range(chacha20.ChaCha20Rng.REFILL_BLOCKS * 8 + 16)
    )
    n64 = chacha20.ChaCha20Rng.REFILL_BLOCKS * 8 + 16
    ks = chacha20.chacha20_blocks(
        bytes(range(32)), b"\0" * 8, 0, (n64 * 8 + 63) // 64
    )
    assert stream_a == ks[: len(stream_a)]


# --------------------------------------------------------------- keccak256

def test_keccak256_vectors():
    # the canonical legacy-Keccak (Ethereum) vectors
    assert (
        keccak256.keccak256(b"").hex()
        == "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470"
    )
    assert (
        keccak256.keccak256(b"abc").hex()
        == "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45"
    )
    # multi-block (> 136-byte rate)
    long = b"a" * 300
    assert keccak256.keccak256(long) == keccak256.keccak256(b"a" * 300)
    assert keccak256.keccak256(long) != keccak256.keccak256(b"a" * 299)
    # rate-boundary lengths exercise both padding branches
    for n in (135, 136, 137):
        keccak256.keccak256(b"x" * n)


# --------------------------------------------------------------- hmac/hkdf

def test_hmac_matches_stdlib():
    for key in (b"", b"k", b"K" * 77, b"K" * 200):
        for msg in (b"", b"msg", b"m" * 500):
            assert hmac.hmac_sha256(key, msg) == std_hmac.new(
                key, msg, hashlib.sha256
            ).digest()
            assert hmac.hmac_sha512(key, msg) == std_hmac.new(
                key, msg, hashlib.sha512
            ).digest()


def test_hkdf_rfc5869_case1():
    ikm = b"\x0b" * 22
    salt = bytes.fromhex("000102030405060708090a0b0c")
    info = bytes.fromhex("f0f1f2f3f4f5f6f7f8f9")
    prk = hmac.hkdf_extract(salt, ikm)
    assert prk.hex() == (
        "077709362c2e32df0ddc3f0dc47bba6390b6c73bb50f9c3122ec844ad7c2b3e5"
    )
    okm = hmac.hkdf_expand(prk, info, 42)
    assert okm.hex() == (
        "3cb25f25faacd57a90434f64d0362f2a2d2d0a90cf1a5a4c5db02d56ecc4c5bf"
        "34007208d5b887185865"
    )


def test_hkdf_expand_label_shape():
    # QUIC v1 initial secrets derivation shape check (client in, 32 bytes)
    secret = hmac.hkdf_extract(b"salt", b"cid")
    out = hmac.hkdf_expand_label(secret, "client in", b"", 32)
    assert len(out) == 32
    assert out != hmac.hkdf_expand_label(secret, "server in", b"", 32)
