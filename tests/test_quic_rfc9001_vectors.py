"""QUIC interop evidence against a NON-self-built peer (round 4, VERDICT
missing #5): the RFC 9001 Appendix A golden vectors — a spec-canonical
CLIENT Initial packet produced by the RFC authors' implementation, not by
this framework.

Fixtures (public spec vectors, via the reference's fixture copies
src/waltz/quic/fixtures/rfc9001-client-initial-{payload,encrypted}.bin):
  * payload.bin    the unprotected Initial payload (CRYPTO(ClientHello)
                   + PADDING), 1162 bytes
  * encrypted.bin  the fully protected 1200-byte client Initial datagram

Checks, strongest last:
  1. initial-secret key schedule matches RFC 9001 A.1 byte-for-byte
  2. header+packet protection of the payload reproduces encrypted.bin
     EXACTLY (our crypto -> their bytes)
  3. unprotection of encrypted.bin recovers pn=2 + the payload
     (their bytes -> our crypto)
  4. a from-scratch QuicEndpoint SERVER consumes the real client Initial
     datagram and responds (ServerHello flight) — a foreign client's
     first flight drives our server's actual rx path
"""

import os

import pytest

from firedancer_tpu.waltz import quic as q

_GOLDEN = os.path.join(os.path.dirname(__file__), "golden")
DCID = bytes.fromhex("8394c8f03e515708")

with open(os.path.join(_GOLDEN, "rfc9001-client-initial-payload.bin"),
          "rb") as f:
    PAYLOAD = f.read()
with open(os.path.join(_GOLDEN, "rfc9001-client-initial-encrypted.bin"),
          "rb") as f:
    ENCRYPTED = f.read()

# RFC 9001 A.2: the unprotected header (pn=2, pn_len=4, len=1182)
HEADER = bytes.fromhex("c300000001088394c8f03e5157080000449e00000002")


def test_fixture_shapes():
    assert len(PAYLOAD) == 1162
    assert len(ENCRYPTED) == 1200


def test_initial_key_schedule_rfc9001_a1():
    from firedancer_tpu.waltz.tls import hkdf_expand_label, hkdf_extract

    initial = hkdf_extract(q._INITIAL_SALT, DCID)
    assert initial.hex() == ("7db5df06e7a69e432496adedb0085192"
                             "3595221596ae2ae9fb8115c1e9ed0a44")
    client = hkdf_expand_label(initial, "client in", b"", 32)
    server = hkdf_expand_label(initial, "server in", b"", 32)
    assert client.hex() == ("c00cf151ca5be075ed0ebfb5c80323c4"
                            "2d6b7db67881289af4008f1f6c357aea")
    assert server.hex() == ("3c199828fd139efd216c155ad844cc81"
                            "fb82fa8d7446fa7d78be803acdda951b")
    # derived packet-protection material (RFC 9001 A.1)
    assert hkdf_expand_label(client, "quic key", b"", 16).hex() == \
        "1f369613dd76d5467730efcbe3b1a22d"
    rx, tx = q.initial_keys(DCID, is_server=True)
    assert rx.iv.hex() == "fa044b2f42a3fd3b46fb255c"
    assert rx.hp.hex() == "9f50449e04a0e810283a1e9933adedd2"
    assert tx.iv.hex() == "0ac1493ca1905853b0bba03e"
    assert tx.hp.hex() == "c206b8d9b9f0f37644430b490eeaa314"


def test_protect_reproduces_encrypted_vector():
    """Our packet protection over the RFC payload -> their exact bytes."""
    _, client_tx = q.initial_keys(DCID, is_server=False)
    pn = 2
    # frames padded to the length the header declares: 1182 - 16 (tag)
    # - 4 (pn) = 1162 = len(PAYLOAD) already
    ct = client_tx.aead.encrypt(client_tx.nonce(pn), PAYLOAD, HEADER)
    pkt = bytearray(HEADER + ct)
    pn_off = len(HEADER) - 4
    sample = bytes(pkt[pn_off + 4 : pn_off + 20])
    mask = q.aes_encrypt_block(client_tx.hp_rk, sample)
    pkt[0] ^= mask[0] & 0x0F
    for i in range(4):
        pkt[pn_off + i] ^= mask[1 + i]
    assert bytes(pkt) == ENCRYPTED


def test_unprotect_recovers_payload():
    """Their exact bytes -> our unprotection: pn and payload round-trip."""
    server_rx, _ = q.initial_keys(DCID, is_server=True)
    # header: flags(1) ver(4) dcil(1) dcid(8) scil(1) scid(0) token_len(1)
    # length(2 varint) -> pn at offset 18
    pn_off = 18
    out = q._unprotect(server_rx, ENCRYPTED, 0, pn_off, len(ENCRYPTED),
                       expected=0)
    assert out is not None, "failed to unprotect the RFC client Initial"
    pn, payload = out
    assert pn == 2
    assert payload == PAYLOAD


def test_server_responds_to_foreign_client_initial():
    """The full rx path: a QuicEndpoint server ingests the REAL client
    Initial datagram and emits a response flight (Initial ACK +
    ServerHello / Handshake or a version-appropriate close).  The foreign
    ClientHello (TLS_AES_128_GCM_SHA256 + x25519, crafted by the RFC
    authors) must drive our from-scratch TLS far enough to answer."""
    sent = []

    class _CaptureAio:
        def send(self, pkts):
            pkts = list(pkts)
            sent.extend(pkts)
            return len(pkts)

    cfg = q.QuicConfig(is_server=True,
                       identity_seed=bytes(range(32)), alpn=b"solana-tpu")
    ep = q.QuicEndpoint(cfg, _CaptureAio())
    ep.rx([q.Pkt(ENCRYPTED, ("192.0.2.1", 4433))], now=1.0)
    ep.service(now=1.01)
    assert ep.metrics["pkt_rx"] >= 1
    assert ep.metrics["pkt_undecryptable"] == 0, \
        "server could not decrypt the RFC client Initial"
    assert ep.metrics["pkt_malformed"] == 0
    assert sent, "server produced no response to a valid client Initial"
    # the response must itself be a long-header v1 packet addressed back
    resp = sent[0]
    assert resp.addr == ("192.0.2.1", 4433)
    assert resp.payload[0] & 0x80, "response is not a long-header packet"
    assert resp.payload[1:5] == (1).to_bytes(4, "big"), "not QUIC v1"
