"""Unit tests for the util-layer equivalents (wksp/pod/rng/tpool/scratch,
ref src/util/) and the tango extras (tempo/fctl/lru, ref src/tango/) —
the reference's colocated test_* pattern (SURVEY.md §4.1)."""

import os
import threading

import pytest

from firedancer_tpu.tango.fctl import Fctl
from firedancer_tpu.tango.lru import Lru
from firedancer_tpu.tango import tempo
from firedancer_tpu.utils import pod
from firedancer_tpu.utils.rng import Rng
from firedancer_tpu.utils.scratch import Scratch, ScratchError
from firedancer_tpu.utils.tpool import TPool
from firedancer_tpu.utils.wksp import Wksp, WkspError

# ---------------------------------------------------------------------- tempo


def test_tempo_clocks_and_lazy():
    t0 = tempo.tickcount()
    w0 = tempo.wallclock()
    assert t0 > 0 and w0 > 1_000_000_000
    rate = tempo.tick_per_ns()
    assert 0.5 < rate < 2.0  # perf_counter_ns is ns-scaled
    assert 1_000_000 <= tempo.lazy_default(1) <= 100_000_000
    assert tempo.lazy_default(1 << 30) == 100_000_000
    amin = tempo.async_min(1_000_000, event_cnt=4)
    assert amin & (amin - 1) == 0  # power of two
    import random
    r = random.Random(7)
    for _ in range(50):
        d = tempo.async_reload(r, amin)
        assert amin <= d < 2 * amin


# ----------------------------------------------------------------------- fctl


class _FakeFseq:
    def __init__(self, seq=0):
        self.seq = seq
        self.slow = 0

    def query(self):
        return self.seq

    def diag_add(self, idx, delta=1):
        self.slow += delta


def test_fctl_credit_accounting():
    rx1, rx2 = _FakeFseq(), _FakeFseq()
    f = Fctl(cr_max=64).rx_add(rx1).rx_add(rx2)
    assert f.rx_cnt == 2
    # producer at seq 0: full credits
    assert f.cr_query(0) == 64
    # slowest consumer 60 behind caps credits at 4
    rx1.seq, rx2.seq = 4, 32
    assert f.cr_query(64) == 4
    # consume into backpressure
    f.cr_avail = 2
    assert f.consume(2)
    assert not f.consume(1)
    assert f.in_backp and f.backp_cnt == 1
    # housekeeping refresh: consumers caught up -> resume
    rx1.seq = rx2.seq = 64
    assert f.tx_cr_update(64) == 64
    assert not f.in_backp
    # backpressured refresh below resume threshold charges the slow diag
    rx1.seq = 0
    f.cr_avail = 0
    f.in_backp = True
    f.tx_cr_update(64)
    assert rx1.slow >= 1


# ------------------------------------------------------------------------ lru


def test_lru_eviction_order():
    lru = Lru(3)
    assert lru.upsert("a", 1) is None
    assert lru.upsert("b", 2) is None
    assert lru.upsert("c", 3) is None
    lru.touch("a")  # a is now MRU; b is LRU
    evicted = lru.upsert("d", 4)
    assert evicted == ("b", 2)
    assert "a" in lru and "d" in lru and len(lru) == 3
    assert lru.oldest()[0] == "c"
    assert lru.remove("c") and not lru.remove("zz")
    # upsert of an existing key refreshes without eviction
    assert lru.upsert("a", 10) is None
    assert lru.get("a") == 10


# ------------------------------------------------------------------------ pod


def test_pod_roundtrip_and_query():
    tree = {
        "tile": {
            "verify": {"batch": 4096, "lazy": -7, "rate": 0.5},
            "name": "verify0",
        },
        "key": b"\x01\x02",
        "on": True,
    }
    blob = pod.encode(tree)
    assert pod.decode(blob) == {
        "tile": {"verify": {"batch": 4096, "lazy": -7, "rate": 0.5},
                 "name": "verify0"},
        "key": b"\x01\x02",
        "on": 1,
    }
    assert pod.query(blob, "tile.verify.batch") == 4096
    assert pod.query(blob, "tile.verify.lazy") == -7
    assert pod.query(blob, "tile.name") == "verify0"
    assert pod.query(blob, "key") == b"\x01\x02"
    assert pod.query(blob, "tile.verify.nope", 99) == 99
    assert pod.query(blob, "key.sub", "d") == "d"  # descends through leaf
    with pytest.raises(TypeError):
        pod.encode({"bad": object()})


# ------------------------------------------------------------------------ rng


def test_rng_deterministic_and_uniform():
    a, b = Rng(seq=1), Rng(seq=1)
    assert [a.ulong() for _ in range(8)] == [b.ulong() for _ in range(8)]
    assert Rng(seq=2).ulong() != Rng(seq=1).ulong()
    # O(1) jump: constructing at idx=5 matches stepping 5 times
    c = Rng(seq=9)
    for _ in range(5):
        c.ulong()
    assert c.ulong() == Rng(seq=9, idx=5).ulong()
    r = Rng(seq=3)
    rolls = [r.roll(10) for _ in range(2000)]
    assert set(rolls) == set(range(10))
    f = [r.float01() for _ in range(100)]
    assert all(0.0 <= x < 1.0 for x in f)
    xs = list(range(20))
    Rng(seq=4).shuffle(xs)
    assert sorted(xs) == list(range(20)) and xs != list(range(20))


# ---------------------------------------------------------------------- tpool


def test_tpool_exec_all():
    with TPool(4) as tp:
        out = [0] * 100
        tp.exec_all_rrobin(lambda i: out.__setitem__(i, i * i), 0, 100)
        assert out == [i * i for i in range(100)]
        hits = []
        lock = threading.Lock()

        def block(lo, hi):
            with lock:
                hits.append((lo, hi))

        tp.exec_all_block(block, 0, 10)
        covered = sorted(x for lo, hi in hits for x in range(lo, hi))
        assert covered == list(range(10))
        assert tp.map(lambda x: x + 1, [1, 2, 3]) == [2, 3, 4]


def test_tpool_propagates_exceptions():
    with TPool(2) as tp:
        tp.exec(lambda: 1 / 0)
        with pytest.raises(ZeroDivisionError):
            tp.wait()
        # pool still usable afterwards
        tp.exec_all_rrobin(lambda i: None, 0, 4)


# --------------------------------------------------------------------- scratch


def test_scratch_frames():
    s = Scratch(sz=256, frame_max=4)
    with pytest.raises(ScratchError):
        s.alloc(8)  # outside a frame
    s.push()
    a = s.alloc(100)
    a[:3] = b"abc"
    used_outer = s.used()
    with s:  # nested frame via context manager
        b = s.alloc(100)
        b[:3] = b"xyz"
        assert s.used() > used_outer
    assert s.used() == used_outer  # pop rewound
    assert bytes(a[:3]) == b"abc"
    with pytest.raises(ScratchError):
        s.alloc(1000)  # exhausted
    s.pop()
    with pytest.raises(ScratchError):
        s.pop()


# ----------------------------------------------------------------------- wksp


def test_wksp_alloc_free_tags():
    with Wksp(f"fdtpu_wt_{os.getpid()}", data_sz=1 << 16) as ws:
        g1 = ws.alloc(100, tag=7)
        g2 = ws.alloc(200, tag=7)
        g3 = ws.alloc(50, tag=9)
        assert g1 != g2 != g3
        ws.laddr(g1)[:5] = b"hello"
        assert bytes(ws.laddr(g1)[:5]) == b"hello"
        assert sorted(ws.gaddr_of(7)) == sorted([g1, g2])
        used, free = ws.usage()
        assert used == 350
        # free + refill reuses the hole
        ws.free(g1)
        g4 = ws.alloc(100, tag=1)
        assert g4 == g1  # first fit lands in the freed hole
        assert ws.tag_free(7) == 1  # g2 only
        with pytest.raises(WkspError):
            ws.laddr(g2)
        with pytest.raises(WkspError):
            ws.free(12345)


def test_wksp_checkpt_restore(tmp_path):
    path = str(tmp_path / "w.ckpt")
    with Wksp(f"fdtpu_wc_{os.getpid()}", data_sz=1 << 16) as ws:
        g1 = ws.alloc(64, tag=3)
        g2 = ws.alloc(32, tag=5)
        ws.laddr(g1)[:8] = b"fundata1"
        ws.laddr(g2)[:8] = b"fundata2"
        ws.checkpt(path)
        parts = ws.partitions()
    with Wksp(f"fdtpu_wr_{os.getpid()}", data_sz=1 << 16) as ws2:
        ws2.alloc(16, tag=1)  # pre-existing state is replaced
        ws2.restore(path)
        assert ws2.partitions() == parts  # gaddrs preserved
        assert bytes(ws2.laddr(g1)[:8]) == b"fundata1"
        assert bytes(ws2.laddr(g2)[:8]) == b"fundata2"
    with Wksp(f"fdtpu_ws_{os.getpid()}", data_sz=128, ) as small:
        with pytest.raises(WkspError):
            small.restore(path)


def test_wksp_out_of_space():
    with Wksp(f"fdtpu_wo_{os.getpid()}", data_sz=1024) as ws:
        ws.alloc(900)
        with pytest.raises(WkspError):
            ws.alloc(900)
