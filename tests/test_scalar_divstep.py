"""In-kernel Bernstein-Yang divstep halving (round 9, ROADMAP item 4).

sc.halve_scalar must be EXACTLY the batched transcription of the
Python reference below (fixed 250-iteration divstep + 24-round binary
Lagrange polish), and every output pair must satisfy the Antipa
contract: u == v*k (mod L) with u, |v| < 2^128 (the 32-window budget
of cv.double_scalar_mul_halved).  The adversarial edges cover the
fast- and slow-converging extremes of the divstep hull: tiny k
(v = 1 exactly), k = L-1, powers of two (premultiply-aligned), and
inverses of small scalars (the classic euclid worst directions).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from firedancer_tpu.ops import ed25519 as ed
from firedancer_tpu.ops import scalar25519 as sc

L = sc.L


def _halve_model(k: int):
    """Host reference of the device kernel, step-exact (see
    sc.halve_scalar's module comment for the derivation)."""
    n1 = sc.DIVSTEP_ITERS
    f, g = L, (pow(2, n1, L) * k) % L
    bf, bg, delta = 0, 1, 1
    for _ in range(n1):
        if delta > 0 and g & 1:
            delta, f, g, bf, bg = 1 - delta, g, (g - f) >> 1, 2 * bg, bg - bf
        else:
            b = g & 1
            delta, f, g, bf, bg = (1 + delta, f, (g + b * f) >> 1,
                                   2 * bf, bg + b * bf)

    def nrm(a, b):
        return max(abs(a), abs(b))

    F, G = (f, bf), (g, bg)
    for _ in range(sc.LAGRANGE_ITERS):
        if nrm(*F) < nrm(*G):
            F, G = G, F
        t = min(max(0, nrm(*F).bit_length() - nrm(*G).bit_length()), 31)
        sG = (G[0] << t, G[1] << t)
        P = (F[0] - sG[0], F[1] - sG[1])
        M = (F[0] + sG[0], F[1] + sG[1])
        C = P if nrm(*P) <= nrm(*M) else M
        if nrm(*C) < nrm(*F):
            F = C
    u, v = F if nrm(*F) <= nrm(*G) else G
    if u < 0:
        u, v = -u, -v
    return u, v


def _edge_scalars():
    ks = [0, 1, 2, 3, L - 1, L - 2, (1 << 127) - 1, 1 << 127, 1 << 128]
    ks += [pow(x, L - 2, L) for x in (2, 3, 5, 7, 11, 97)]   # slow euclid
    ks += [pow(2, j, L) for j in (1, 63, 125, 126, 127, 128, 251)]
    ks += [pow(2, sc.DIVSTEP_ITERS, L)]   # premultiply-aligned
    return ks


def _k_limbs(ks):
    kb = np.zeros((len(ks), 32), np.uint8)
    for i, k in enumerate(ks):
        kb[i] = np.frombuffer(k.to_bytes(32, "little"), np.uint8)
    return sc.bytes_to_limbs(jnp.asarray(kb), 22)


def _limbs_int(a, col):
    return sum(int(a[i, col]) << (12 * i) for i in range(22))


def _check_lanes(ks, u_l, av_l, v_pos):
    for i, k in enumerate(ks):
        u = _limbs_int(u_l, i)
        av = _limbs_int(av_l, i)
        v = av if v_pos[i] else -av
        mu, mv = _halve_model(k)
        assert (u, v) == (mu, mv), f"model mismatch k={hex(k)}"
        assert 0 <= u < (1 << 128), f"u bound: {u.bit_length()} bits"
        assert 0 < av < (1 << 128) or (k == 0 and (u, v) == (0, 1))
        assert u % L == (v * k) % L, f"invariant k={hex(k)}"
        if 0 < k < (1 << 127):
            # euclid returns (k, 1) here; the divstep pair need not be
            # identical, but must still be a legal half-pair
            assert max(u, av).bit_length() <= 128


def test_halve_scalar_matches_model_and_bounds():
    rng = np.random.default_rng(907)
    ks = _edge_scalars()
    ks += [int.from_bytes(rng.bytes(32), "little") % L for _ in range(40)]
    # non-canonical 256-bit strings, reduced mod L like the digest path
    ks += [(int.from_bytes(rng.bytes(32), "little") | (1 << 255)) % L
           for _ in range(8)]
    u_l, av_l, v_pos = jax.jit(sc.halve_scalar)(_k_limbs(ks))
    _check_lanes(ks, np.asarray(u_l), np.asarray(av_l), np.asarray(v_pos))


def test_halve_scalar_agrees_with_host_half_gcd():
    """Same contract as ed._halve_scalar_host (the round-6 reference):
    both produce valid (u, v) pairs for the same k — pairs may differ,
    but both must satisfy the invariant the verify equation consumes."""
    rng = np.random.default_rng(11)
    ks = [int.from_bytes(rng.bytes(32), "little") % L for _ in range(16)]
    u_l, av_l, v_pos = sc.halve_scalar(_k_limbs(ks))
    u_l, av_l, v_pos = np.asarray(u_l), np.asarray(av_l), np.asarray(v_pos)
    for i, k in enumerate(ks):
        hu, hv = ed._halve_scalar_host(k)
        assert hu % L == (k * hv) % L
        u = _limbs_int(u_l, i)
        v = _limbs_int(av_l, i) * (1 if v_pos[i] else -1)
        assert u % L == (k * v) % L


@pytest.mark.slow
def test_halve_scalar_bounds_sweep():
    """Wide randomized certification sweep of the 2^128 window budget
    (the empirical bound docs/perf_ceiling.md round 10 records)."""
    rng = np.random.default_rng(5151)
    fn = jax.jit(sc.halve_scalar)
    for _ in range(4):
        kb = rng.integers(0, 256, size=(2048, 32), dtype=np.uint8)
        kb[:, 31] &= 0x0F
        u_l, av_l, _ = fn(sc.bytes_to_limbs(jnp.asarray(kb), 22))
        for a in (np.asarray(u_l), np.asarray(av_l)):
            assert np.abs(a[11:]).max() == 0          # nothing >= 2^132
            assert int(a[10].max()) < (1 << 8)        # < 2^128 exactly
