"""Fork-aware replay wired to choreo (VERDICT r2 missing #3; ref
src/disco/tvu/fd_tvu.c + src/choreo/ghost/fd_ghost.c): two competing
forks in the blockstore; peer votes landing in replayed blocks move
ghost's head to the heavier fork; the follower's tower votes there and
eventually ROOTS it — the minority fork's bank is discarded."""

import pytest

from firedancer_tpu.ballet import entry as entry_lib
from firedancer_tpu.ballet import shred as shred_lib
from firedancer_tpu.ballet import txn as txn_lib
from firedancer_tpu.choreo.voter import Voter
from firedancer_tpu.flamenco import genesis as gen_mod
from firedancer_tpu.flamenco import system_program as sysprog
from firedancer_tpu.flamenco import vote_program
from firedancer_tpu.flamenco.blockstore import Blockstore
from firedancer_tpu.flamenco.replay import ForkReplay
from firedancer_tpu.flamenco.runtime import Runtime
from firedancer_tpu.flamenco.types import SYSTEM_PROGRAM_ID, Account
from firedancer_tpu.ops import ed25519 as ed


def _keypair(i):
    seed = i.to_bytes(32, "little")
    return seed, ed.keypair_from_seed(seed)[0]


def _tick_block(poh: bytes, n_ticks: int = 2):
    """A block of bare ticks: PoH-valid, no txns."""
    entries = []
    for _ in range(n_ticks):
        poh = entry_lib.next_hash(poh, 1, None)
        entries.append(entry_lib.Entry(1, poh, []))
    return entries, poh


def _txn_block(poh: bytes, payloads):
    entries = []
    for payload in payloads:
        mix = entry_lib.txn_mixin([payload])
        poh = entry_lib.next_hash(poh, 1, mix)
        entries.append(entry_lib.Entry(1, poh, [payload]))
    poh = entry_lib.next_hash(poh, 1, None)
    entries.append(entry_lib.Entry(1, poh, []))
    return entries, poh


def _store_block(bs, slot, parent, entries, sign_seed):
    fs = shred_lib.make_fec_set(
        entry_lib.serialize_batch(entries), slot=slot,
        parent_off=slot - parent, version=1, fec_set_idx=0,
        sign_fn=lambda root: ed.sign(sign_seed, root),
        data_cnt=32, code_cnt=32, slot_complete=True)
    for raw in fs.data_shreds + fs.code_shreds:
        bs.insert_shred(raw)


@pytest.fixture
def world():
    faucet_seed, faucet_pk = _keypair(1)
    peer_seed, peer_pk = _keypair(2)       # high-stake peer validator
    me_seed, me_pk = _keypair(3)           # this follower's identity
    g = gen_mod.create(faucet_pk, creation_time=1_700_000_000,
                       slots_per_epoch=64)
    g.accounts[peer_pk] = Account(lamports=10_000_000)
    g.stakes = {peer_pk: 1_000_000, me_pk: 1}
    return g, (faucet_seed, faucet_pk), (peer_seed, peer_pk), (me_seed, me_pk)


def _peer_vote_txn(peer, slot, blockhash):
    """A parseable (not necessarily executable) vote txn from the peer:
    the replay vote-counting path reads the ix, not the execution."""
    peer_seed, peer_pk = peer
    vote_acct = _keypair(40)[1]
    msg = txn_lib.build_unsigned(
        [peer_pk], blockhash,
        [(2, bytes([1]), vote_program.ix_vote([slot]))],
        extra_accounts=[vote_acct, vote_program.VOTE_PROGRAM_ID],
        readonly_unsigned_cnt=1)
    return txn_lib.assemble([ed.sign(peer_seed, msg)], msg)


def test_two_forks_head_switches_and_roots(world):
    g, faucet, peer, me = world
    rt = Runtime(g)
    bs = Blockstore()
    voter = Voter(vote_account=_keypair(41)[1], node_pubkey=me[1])
    fr = ForkReplay(rt, bs, voter, bytes(32))
    lead_seed = (9).to_bytes(32, "little")
    gh = g.genesis_hash()

    # fork A: slot 1 off the root (the follower sees it first)
    ents_a, _ = _tick_block(bytes(32))
    _store_block(bs, 1, 0, ents_a, lead_seed)
    events = fr.drain()
    assert [r.slot for r, _ in events] == [1]
    # no peer stake observed yet -> head is the lone fork; tower votes it
    assert fr.head == 1
    assert events[0][1].slot == 1

    # fork B: slot 2 off the root, then slot 3 carrying the heavy peer's
    # vote for slot 2
    ents_b2, poh_b2 = _tick_block(bytes(32))
    _store_block(bs, 2, 0, ents_b2, lead_seed)
    ents_b3, poh_b3 = _txn_block(poh_b2, [_peer_vote_txn(peer, 2, gh)])
    _store_block(bs, 3, 2, ents_b3, lead_seed)
    fr.drain()
    # the peer's million-lamport vote outweighs our 1: head jumps to B
    assert fr.head == 3
    assert voter.ghost.weight(2) >= 1_000_000

    # extend fork B until the follower's tower roots; the tower needs
    # MAX_LOCKOUT_HISTORY deep confirmation (apply_vote_slot)
    poh = poh_b3
    parent = 3
    for slot in range(4, 44):
        ents, poh = _tick_block(poh)
        _store_block(bs, slot, parent, ents, lead_seed)
        parent = slot
    fr.drain()
    assert fr.head == 43
    assert rt.root_slot > 0, "tower never rooted"
    # the root is on fork B: slot 1 is not an ancestor of the root
    assert rt.root_slot >= 2
    assert 1 not in rt.banks          # minority fork bank discarded
    assert 1 not in fr.replayed


def test_dead_fork_does_not_halt_others(world):
    g, faucet, peer, me = world
    rt = Runtime(g)
    bs = Blockstore()
    voter = Voter(vote_account=_keypair(41)[1], node_pubkey=me[1])
    fr = ForkReplay(rt, bs, voter, bytes(32))
    lead_seed = (9).to_bytes(32, "little")

    # fork A slot 1: PoH-corrupt block (entry hash garbage)
    bad = [entry_lib.Entry(1, b"\xee" * 32, [])]
    _store_block(bs, 1, 0, bad, lead_seed)
    # its child slot 2 on the same fork
    ents2, _ = _tick_block(b"\xee" * 32)
    _store_block(bs, 2, 1, ents2, lead_seed)
    # healthy fork B slot 3 off the root
    ents3, _ = _tick_block(bytes(32))
    _store_block(bs, 3, 0, ents3, lead_seed)

    events = fr.drain()
    by_slot = {r.slot: r for r, _ in events}
    assert not by_slot[1].ok and "poh" in by_slot[1].err
    assert not by_slot[2].ok and by_slot[2].err == "dead parent"
    assert by_slot[3].ok
    assert fr.head == 3
    assert fr.dead == {1, 2}


def test_fork_banks_isolate_state(world):
    """Competing forks write DIFFERENT accounts; only the rooted fork's
    writes reach the funk root."""
    g, faucet, peer, me = world
    faucet_seed, faucet_pk = faucet
    rt = Runtime(g)
    bs = Blockstore()
    voter = Voter(vote_account=_keypair(41)[1], node_pubkey=me[1])
    fr = ForkReplay(rt, bs, voter, bytes(32))
    lead_seed = (9).to_bytes(32, "little")
    gh = g.genesis_hash()
    dest_a = b"\xa1" + bytes(31)
    dest_b = b"\xb1" + bytes(31)

    def transfer(dest, amount, bh):
        msg = txn_lib.build_unsigned(
            [faucet_pk], bh,
            [(2, bytes([0, 1]), sysprog.ix_transfer(amount))],
            extra_accounts=[dest, SYSTEM_PROGRAM_ID],
            readonly_unsigned_cnt=1)
        return txn_lib.assemble([ed.sign(faucet_seed, msg)], msg)

    ents_a, _ = _txn_block(bytes(32), [transfer(dest_a, 111, gh)])
    _store_block(bs, 1, 0, ents_a, lead_seed)
    ents_b, poh_b = _txn_block(bytes(32), [transfer(dest_b, 222, gh)])
    _store_block(bs, 2, 0, ents_b, lead_seed)
    # heavy peer votes fork B; extend it to rooting depth
    ents_b3, poh = _txn_block(poh_b, [_peer_vote_txn(peer, 2, gh)])
    _store_block(bs, 3, 2, ents_b3, lead_seed)
    parent = 3
    for slot in range(4, 44):
        ents, poh = _tick_block(poh)
        _store_block(bs, slot, parent, ents, lead_seed)
        parent = slot
    fr.drain()
    assert rt.root_slot >= 2
    # rooted fork B's write is in the root; fork A's never landed
    assert rt.balance(dest_b) == 222
    assert rt.balance(dest_a) == 0
