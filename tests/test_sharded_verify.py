"""The dp-mesh serving path (round 7) on the virtual 8-device CPU mesh:
sharded packed dispatch, the masked-padding contract for uneven batches,
the sharded PackedIngest rotation, and sharded-RLC parity — every case
bit-checked against the single-chip engine (verify is lane-parallel, so
real lanes must match EXACTLY; "close" is wrong)."""

import numpy as np
import pytest

import jax

from firedancer_tpu.models.verifier import (
    SigVerifier,
    VerifierConfig,
    make_example_batch,
)
from firedancer_tpu.parallel import mesh as pm

N_DEV = 8
B, ML = 64, 96


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"need {N_DEV} devices")
    return pm.make_mesh(N_DEV)


@pytest.fixture(scope="module")
def batch():
    """Mixed-verdict batch: valid sigs with lanes 3, 17, 40 tampered."""
    msgs, lens, sigs, pubs = make_example_batch(B, ML, True, seed=7)
    sigs = np.array(sigs)
    for i in (3, 17, 40):
        sigs[i, 5] ^= 0xFF
    return msgs, lens, sigs, pubs


@pytest.fixture(scope="module")
def single():
    return SigVerifier(VerifierConfig(batch=B, msg_maxlen=ML))


@pytest.fixture(scope="module")
def sharded(mesh):
    return SigVerifier(VerifierConfig(batch=B, msg_maxlen=ML), mesh=mesh)


def test_packed_dispatch_bit_identity(single, sharded, batch):
    ref = np.asarray(single.packed_dispatch(*batch))
    got = np.asarray(sharded.packed_dispatch(*batch))
    assert got.shape == (B,)
    assert (ref == got).all()
    assert not got[3] and not got[17] and not got[40]
    assert got.sum() == B - 3


def test_uneven_batch_pads_and_masks(single, sharded, batch):
    # 36 rows pad to 40 on the 8-mesh; the 4 padding lanes are masked
    # False on device and trimmed from the verdict
    msgs, lens, sigs, pubs = batch
    n = 36
    ref = np.asarray(single._fn(msgs[:n], lens[:n], sigs[:n], pubs[:n]))
    got = np.asarray(sharded.packed_dispatch(
        msgs[:n], lens[:n], sigs[:n], pubs[:n]))
    assert got.shape == (n,)
    assert (ref == got).all()


def test_strict_four_array_mesh(single, sharded, batch):
    ref = np.asarray(single(*batch))
    got = np.asarray(sharded(*batch))
    assert (ref == got).all()


def test_sharded_ingest_rotation(mesh, batch):
    """The multichip fresh-ingest engine: 5 rotations through 3 buffers
    with a different tampered lane per rotation — verdict streams must
    match the single-chip engine batch for batch (the no-torn-buffer
    invariant holding per shard)."""
    msgs, lens, sigs, pubs = batch
    eng = SigVerifier(VerifierConfig(batch=B, msg_maxlen=ML),
                      mesh=mesh).make_ingest(nbuf=3)
    ref = SigVerifier(VerifierConfig(batch=B, msg_maxlen=ML)).make_ingest(
        nbuf=3)
    outs, routs = [], []
    for r in range(5):
        s2 = np.array(sigs)
        s2[(r * 7) % B, 9] ^= 0x55
        outs += eng.submit(msgs, lens, s2, pubs)
        routs += ref.submit(msgs, lens, s2, pubs)
    outs += eng.drain()
    routs += ref.drain()
    assert len(outs) == 5
    for o, r in zip(outs, routs):
        assert o.shape == (B,)
        assert (o == r).all()
    assert eng.dispatches == 5
    assert eng.pack_txns == 5 * B


def test_sharded_rlc_parity(mesh):
    good = make_example_batch(B, ML, True, seed=11)
    rl_single = SigVerifier(VerifierConfig(batch=B, msg_maxlen=ML),
                            mode="rlc", msm_m=2)
    rl_mesh = SigVerifier(VerifierConfig(batch=B, msg_maxlen=ML),
                          mode="rlc", msm_m=2, mesh=mesh)
    assert np.asarray(rl_mesh(*good)).all()
    assert np.asarray(rl_single(*good)).all()

    # one tampered sig: the sharded batch check fails, the strict descent
    # localizes lane 5 — exact bits either way
    bad_sigs = np.array(good[2])
    bad_sigs[5, 3] ^= 1
    got = np.asarray(rl_mesh(good[0], good[1], bad_sigs, good[3]))
    ref = np.asarray(rl_single(good[0], good[1], bad_sigs, good[3]))
    assert (got == ref).all()
    assert not got[5] and got.sum() == B - 1


def test_pad_rows():
    a = np.arange(36 * 4, dtype=np.uint8).reshape(36, 4)
    p = pm.pad_rows(a, 8)
    assert p.shape == (40, 4)
    assert (p[:36] == a).all() and not p[36:].any()
    assert pm.pad_rows(a, 4) is a  # already divisible: no copy


def test_rlc_divisibility_validation(mesh):
    # 36 doesn't split 8 ways; 40 splits into 5-lane shards that m=2
    # can't tile — both must fail loudly at construction
    with pytest.raises(ValueError, match="split"):
        SigVerifier(VerifierConfig(batch=36, msg_maxlen=ML), mode="rlc",
                    msm_m=2, mesh=mesh)
    with pytest.raises(ValueError, match="split"):
        SigVerifier(VerifierConfig(batch=40, msg_maxlen=ML), mode="rlc",
                    msm_m=2, mesh=mesh)


def test_pipeline_dp_shards_validation(mesh):
    from firedancer_tpu.disco.pipeline import VerifyPipeline

    sv = SigVerifier(VerifierConfig(batch=B, msg_maxlen=ML), mesh=mesh)

    def fake(m, l, s, p):
        return np.ones((np.asarray(m).shape[0],), bool)

    # bucket batch not divisible by the mesh
    with pytest.raises(ValueError, match="not divisible"):
        VerifyPipeline(sv, buckets=[(36, ML)], dp_shards=N_DEV)
    # verifier shard count disagrees with the topology's dp_shards
    with pytest.raises(ValueError, match="shards"):
        VerifyPipeline(sv, buckets=[(B, ML)], dp_shards=4)
    # a shardless verify_fn is accepted (n_shards defaults to dp_shards)
    VerifyPipeline(fake, buckets=[(B, ML)], dp_shards=N_DEV)


def test_mesh_requires_dp_axis():
    from jax.sharding import Mesh
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("need 2 devices")
    m = Mesh(np.array(devs[:2]), ("tp",))
    with pytest.raises(ValueError, match="dp"):
        SigVerifier(VerifierConfig(batch=B, msg_maxlen=ML), mesh=m)
