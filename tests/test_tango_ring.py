"""Tests for the native tango fabric (C++ mcache/dcache/fseq/cnc).

Mirrors the reference's tango test strategy (SURVEY.md §4.4): in-process
produce/consume assertions plus a REAL multi-process test over named shared
memory — one producer process, two consumer processes, overrun accounting —
the analogue of src/tango/test_ipc_full.
"""

import multiprocessing as mp

import numpy as np
import pytest

from firedancer_tpu.tango.ring import (
    Cnc,
    Dcache,
    FSeq,
    MCache,
    Workspace,
    ctl,
)


@pytest.fixture()
def ws():
    w = Workspace("fdtpu_test_ring", 1 << 20, create=True)
    yield w
    w.close()
    w.unlink()


def test_mcache_publish_query(ws):
    mc = MCache.new(ws, depth=8, seq0=100)
    assert mc.seq_query() == 100
    rc, _ = mc.query(100)
    assert rc == -1  # not yet published

    seq = mc.publish(sig=0xDEAD, chunk=3, sz=17, ctl_=ctl(origin=5))
    assert seq == 100
    rc, m = mc.query(100)
    assert rc == 0
    assert m["sig"] == 0xDEAD and m["chunk"] == 3 and m["sz"] == 17
    assert m["ctl"] == ctl(origin=5)

    # consumer that fell a full lap behind sees overrun
    for _ in range(8):
        mc.publish(sig=1)
    rc, _ = mc.query(100)
    assert rc == 1


def test_mcache_burst(ws):
    mc = MCache.new(ws, depth=16)
    for i in range(10):
        mc.publish(sig=i)
    metas, rc = mc.consume_burst(0, 32)
    assert len(metas) == 10 and rc == -1  # caught up
    assert list(metas["sig"]) == list(range(10))
    metas, rc = mc.consume_burst(0, 4)
    assert len(metas) == 4 and rc == 0  # burst full


def test_dcache_roundtrip(ws):
    mc = MCache.new(ws, depth=4)
    dc = Dcache.new(ws, mtu=1232, depth=4)
    chunk = dc.chunk0
    payload = bytes(range(200))
    nxt = dc.write(chunk, payload)
    seq = mc.publish(sig=7, chunk=chunk, sz=len(payload))
    rc, m = mc.query(seq)
    assert rc == 0
    assert dc.read(m["chunk"], m["sz"]) == payload
    assert nxt > chunk

    # compact ring wraps before overflowing the region
    for _ in range(1000):
        assert nxt * dc.chunk_sz + 1232 <= dc.data_sz
        nxt = dc.write(nxt, b"x" * 1232)


def test_fseq_cnc(ws):
    fs = FSeq.new(ws, seq0=5)
    assert fs.query() == 5
    fs.update(9)
    assert fs.query() == 9
    fs.diag_add(FSeq.DIAG_OVRNP_CNT, 3)
    assert fs.diag(FSeq.DIAG_OVRNP_CNT) == 3

    cn = Cnc.new(ws)
    assert cn.signal_query() == Cnc.SIGNAL_BOOT
    cn.signal(Cnc.SIGNAL_RUN)
    assert cn.signal_query() == Cnc.SIGNAL_RUN
    cn.heartbeat(12345)
    assert cn.heartbeat_query() == 12345


# ---------------------------------------------------------------------------
# multi-process: producer + 2 consumers over named shm, reliable flow control

N_FRAGS = 5000
DEPTH = 64


def _layout(name):
    """Each process rebuilds the identical layout deterministically."""
    ws = Workspace(name, 1 << 20, create=False)
    mc = MCache.join(ws, ws.alloc(MCache.footprint(DEPTH)))
    fseqs = [FSeq.join(ws, ws.alloc(64)) for _ in range(2)]
    return ws, mc, fseqs


def _producer(name):
    ws, mc, fseqs = _layout(name)
    sent = 0
    while sent < N_FRAGS:
        # reliable-consumer credit check (fd_mux.c:233-310 credit logic)
        lo = min(f.query() for f in fseqs)
        if sent - lo >= DEPTH - 1:
            continue  # no credits: would overrun slowest consumer
        mc.publish(sig=sent * 3 + 1)
        sent += 1
    ws.close()


def _consumer(name, idx, q):
    ws, mc, fseqs = _layout(name)
    fs = fseqs[idx]
    seq = 0
    acc = 0
    while seq < N_FRAGS:
        metas, rc = mc.consume_burst(seq, 32)
        for m in metas:
            acc += int(m["sig"])
        seq += len(metas)
        assert rc != 1, "reliable consumer overran"
        fs.update(seq)
    q.put((idx, acc))
    ws.close()


def test_multiprocess_reliable_flow():
    name = "fdtpu_test_mp_ring"
    ws = Workspace(name, 1 << 20, create=True)
    try:
        mc = MCache.new(ws, DEPTH)
        fs = [FSeq.new(ws) for _ in range(2)]
        assert mc.off is not None and fs  # layout materialized

        ctx = mp.get_context("fork")
        q = ctx.Queue()
        cons = [
            ctx.Process(target=_consumer, args=(name, i, q)) for i in range(2)
        ]
        prod = ctx.Process(target=_producer, args=(name,))
        for c in cons:
            c.start()
        prod.start()
        want = sum(i * 3 + 1 for i in range(N_FRAGS))
        results = [q.get(timeout=60) for _ in range(2)]
        for _, acc in results:
            assert acc == want
        prod.join(10)
        for c in cons:
            c.join(10)
        assert prod.exitcode == 0 and all(c.exitcode == 0 for c in cons)
    finally:
        ws.close()
        ws.unlink()


def test_unreliable_consumer_detects_overrun(ws):
    mc = MCache.new(ws, depth=4)
    for i in range(10):
        mc.publish(sig=i)
    # consumer at 0 is 10 behind a depth-4 ring: overrun; resync
    rc, _ = mc.query(0)
    assert rc == 1
    resync = mc.seq_query()
    assert resync == 10
    metas, rc = mc.consume_burst(resync - 4, 4)
    assert len(metas) == 4 and list(metas["sig"]) == [6, 7, 8, 9]


def test_rx_burst_drops_frag_wider_than_buffer(ws):
    """A frag whose sz exceeds the ENTIRE rx buffer must be consumed and
    counted as filtered, not wedge the input forever with rc=0 and zero
    progress (ADVICE r4: hostile/buggy in-process producer contract)."""
    from firedancer_tpu.tango.ring import FRAG_META_DTYPE, rx_burst, tx_burst

    mc = MCache.new(ws, depth=8)
    dc = Dcache.new(ws, mtu=512, depth=8)
    payloads = [b"x" * 400, b"ok", b"fine"]  # first exceeds the 64B rx buf
    starts = np.array([0, 400, 402], np.int64)
    lens = np.array([400, 2, 4], np.int32)
    sigs = np.array([1, 2, 3], np.uint64)
    tx_burst(mc, dc, 0, b"".join(payloads), starts, lens, sigs)

    buf = np.zeros(64, np.uint8)
    metas = np.zeros(8, dtype=FRAG_META_DTYPE)
    offs = np.zeros(9, np.int64)
    rc, consumed, kept, filt = rx_burst(mc, dc, 0, 8, buf, metas, offs)
    assert rc == -1 and consumed == 3  # caught up: all three consumed
    assert filt == 1 and kept == 2     # oversized frag dropped, not wedged
    assert bytes(buf[offs[0]:offs[1]]) == b"ok"
    assert bytes(buf[offs[1]:offs[2]]) == b"fine"
