"""Randomized robustness sweeps — the in-tree analogue of the reference's
libFuzzer harnesses (SURVEY.md §4.3: fuzz_txn_parse, fuzz_quic,
fuzz_sbpf_loader, ...): every parser that touches untrusted bytes must
survive arbitrary input with a controlled exception or a clean reject,
never a crash, hang, or unbounded allocation.

Deterministic seeds (CI-reproducible); each harness also mutates VALID
inputs, which reaches far deeper than pure noise (the corpus-mutation
idea behind the reference's seed corpora in corpus/)."""

import os
import random

import pytest

from firedancer_tpu.ballet import shred as shred_lib
from firedancer_tpu.ballet import txn as txn_lib
from firedancer_tpu.ballet.x509 import cert_create, cert_pubkey
from firedancer_tpu.ops import ed25519 as ed
from firedancer_tpu.utils import pod
from firedancer_tpu.waltz import tls as tls_mod
from firedancer_tpu.waltz.aio import Pkt
from firedancer_tpu.waltz.quic import QuicConfig, QuicEndpoint, dec_varint

R = random.Random(0xFD_7031)


def _mutations(valid: bytes, n: int):
    """Yield n mutated copies of a valid input."""
    for _ in range(n):
        b = bytearray(valid)
        for _ in range(R.randint(1, 8)):
            op = R.randint(0, 2)
            if op == 0 and b:
                b[R.randrange(len(b))] ^= 1 << R.randint(0, 7)
            elif op == 1 and b:
                del b[R.randrange(len(b))]
            else:
                b.insert(R.randint(0, len(b)), R.randint(0, 255))
        yield bytes(b)


def _valid_txn() -> bytes:
    seed = R.randbytes(32)
    pub, _, _ = ed.keypair_from_seed(seed)
    msg = txn_lib.build_unsigned(
        [pub], R.randbytes(32), [(1, bytes([0]), R.randbytes(12))],
        extra_accounts=[R.randbytes(32)])
    return txn_lib.assemble([ed.sign(seed, msg)], msg)


def test_fuzz_txn_parse():
    valid = _valid_txn()
    assert txn_lib.parse(valid)
    for blob in _mutations(valid, 400):
        try:
            txn_lib.parse(blob)
        except txn_lib.TxnParseError:
            pass
    for _ in range(400):
        try:
            txn_lib.parse(R.randbytes(R.randint(0, 300)))
        except txn_lib.TxnParseError:
            pass


def test_fuzz_shred_parse():
    batch = b"\x01" + bytes(40)
    fs = shred_lib.make_fec_set(
        batch, slot=3, parent_off=1, version=1, fec_set_idx=0,
        sign_fn=lambda root: ed.sign(b"\x05" * 32, root),
        data_cnt=4, code_cnt=4)
    valid = fs.data_shreds[0]
    for blob in _mutations(valid, 300):
        try:
            shred_lib.parse(blob)
        except shred_lib.ShredParseError:
            pass


def test_fuzz_quic_datagrams():
    """Random and mutated datagrams at a live server endpoint: no packet
    may raise out of rx() (the one-bad-datagram-kills-the-tile class)."""
    sv = QuicEndpoint(
        QuicConfig(identity_seed=bytes(32), is_server=True),
        type("A", (), {"send": staticmethod(lambda pkts: len(pkts))})(),
    )
    now = 1.0
    for _ in range(600):
        blob = R.randbytes(R.randint(0, 1400))
        sv.rx([Pkt(blob, ("f", 1))], now)
    # initial-shaped headers with garbage bodies
    for _ in range(200):
        hdr = bytes([0xC0 | R.randint(0, 63)]) + (1).to_bytes(4, "big")
        blob = hdr + R.randbytes(R.randint(0, 1300))
        sv.rx([Pkt(blob, ("f", 2))], now)
    assert sv.conns == {}


def test_fuzz_tls_handshake_bytes():
    for _ in range(300):
        sv = tls_mod.TlsEndpoint(is_server=True, identity_seed=bytes(32))
        try:
            sv.feed(0, R.randbytes(R.randint(4, 600)))
        except tls_mod.TlsError:
            pass


def test_fuzz_x509_parse():
    seed = b"\x07" * 32
    pub, _, _ = ed.keypair_from_seed(seed)
    valid = cert_create(seed, pub)
    assert cert_pubkey(valid) == pub
    for blob in _mutations(valid, 300):
        try:
            cert_pubkey(blob)
        except ValueError:
            pass


def test_fuzz_pod_decode():
    valid = pod.encode({"a": {"b": 1}, "c": "x", "d": b"\x01"})
    for blob in _mutations(valid, 300):
        try:
            pod.decode(blob)
            pod.query(blob, "a.b")
        except (ValueError, UnicodeDecodeError, IndexError):
            pass
    for _ in range(200):
        try:
            pod.decode(R.randbytes(R.randint(0, 100)))
        except (ValueError, UnicodeDecodeError, IndexError):
            pass


def test_fuzz_varint():
    for _ in range(200):
        b = R.randbytes(R.randint(1, 9))
        try:
            v, n = dec_varint(b, 0)
            assert 0 <= v < 1 << 62 and 1 <= n <= 8
        except IndexError:
            pass
