"""Double-buffered ingest engine invariants (round 6 tentpole).

Three contracts, each falsifiable on CPU:

  1. NO TORN BUFFER — a rotating packed blob is never repacked while its
     dispatch is inflight (on backends where device_put aliases host
     memory, an early repack would corrupt the batch the device is still
     reading).
  2. BACKPRESSURE — the inflight window is bounded at `depth`: submit()
     retires the oldest verdict(s) rather than running ahead, and when
     every buffer is pinned it blocks on a harvest before repacking.
  3. BIT-IDENTICAL — verdicts through the overlapped engine equal the
     serial packed_dispatch verdicts batch for batch, including across
     buffer reuse (stale-padding regression: reused blobs must be
     re-zeroed or a shorter batch would see the previous batch's bytes).

The pipeline-level pool (disco.pipeline._Bucket) carries the same
invariant: a flushed blob returns to the rotation only after _finish()
materializes its verdict.
"""

import numpy as np
import pytest

from firedancer_tpu.models.verifier import (
    SigVerifier,
    VerifierConfig,
    make_example_batch,
)

BATCH = 64
ML = 96


@pytest.fixture(scope="module")
def verifier():
    return SigVerifier(VerifierConfig(batch=BATCH, msg_maxlen=ML))


@pytest.fixture(scope="module")
def batches(verifier):
    """Three distinct batches with mixed verdicts + per-batch serial
    reference bits."""
    out = []
    for seed, valid in ((1, True), (2, False), (3, True)):
        args = [np.asarray(a) for a in make_example_batch(
            BATCH, ML, valid=valid, sign_pool=8, seed=seed)]
        if valid:  # flip a couple of sig bytes for a mixed verdict
            args[2] = args[2].copy()
            args[2][seed, 0] ^= 0xFF
        ref = np.asarray(verifier.packed_dispatch(*args, ml=ML))
        assert ref.any() != ref.all()  # genuinely mixed
        out.append((args, ref))
    return out


def test_no_repack_while_inflight(verifier, batches):
    """Contract 1: _pack_into never targets a buffer whose dispatch is
    still in the inflight window."""
    eng = verifier.make_ingest(ml=ML, nbuf=2, depth=1)
    orig = eng._pack_into

    def guarded(buf, *a):
        pinned = {id(eng._bufs[b]) for _, b in eng._inflight}
        assert id(buf) not in pinned, "repacked an inflight buffer"
        return orig(buf, *a)

    eng._pack_into = guarded
    for i in range(8):
        eng.submit(*batches[i % 3][0])
    eng.drain()
    assert eng.dispatches == 8


def test_backpressure_bounds_window(verifier, batches):
    """Contract 2a: depth bounds the steady-state window; every submit
    past the window retires exactly the overflow, in dispatch order."""
    eng = verifier.make_ingest(ml=ML, nbuf=4, depth=2)
    retired = []
    for i in range(9):
        out = eng.submit(*batches[i % 3][0])
        assert eng.inflight_depth <= 2
        retired += out
    retired += eng.drain()
    assert len(retired) == 9
    for i, ok in enumerate(retired):  # dispatch order preserved
        np.testing.assert_array_equal(ok, batches[i % 3][1])


def test_backpressure_when_all_buffers_pinned(verifier, batches):
    """Contract 2b: depth >= nbuf exhausts the free ring first; submit
    must then block on the oldest harvest (counted) instead of tearing."""
    eng = verifier.make_ingest(ml=ML, nbuf=2, depth=4)
    eng.submit(*batches[0][0])
    eng.submit(*batches[1][0])
    assert eng.backpressure_waits == 0
    out = eng.submit(*batches[2][0])  # no free buffer: forced harvest
    assert eng.backpressure_waits == 1
    assert len(out) == 1
    np.testing.assert_array_equal(out[0], batches[0][1])
    eng.drain()


def test_overlapped_bit_identical_to_serial(verifier, batches):
    """Contract 3: rotated-buffer verdicts == serial verdicts, batch for
    batch, across enough submissions that every buffer is reused."""
    eng = verifier.make_ingest(ml=ML, nbuf=3, depth=2)
    got = []
    for i in range(9):
        got += eng.submit(*batches[i % 3][0])
    got += eng.drain()
    assert len(got) == 9
    for i, ok in enumerate(got):
        np.testing.assert_array_equal(ok, batches[i % 3][1])


def test_reused_buffer_is_rezeroed(verifier):
    """Stale-padding regression: a long-message batch followed by a
    short-message batch through the SAME rotation must not leak the long
    batch's bytes into the short batch's zero-padded columns."""
    long_args = [np.asarray(a) for a in make_example_batch(
        BATCH, ML, valid=True, sign_pool=8, seed=11)]
    short = [np.asarray(a) for a in make_example_batch(
        BATCH, 32, valid=True, sign_pool=8, seed=12)]
    # widen the short batch's msgs to ML columns with zero padding
    wide = np.zeros((BATCH, ML), np.uint8)
    wide[:, :32] = short[0]
    short_args = [wide, short[1], short[2], short[3]]
    ref = np.asarray(verifier.packed_dispatch(*short_args, ml=ML))
    assert ref.all()
    eng = verifier.make_ingest(ml=ML, nbuf=2, depth=1)
    for _ in range(3):  # cycle both buffers through the long batch
        eng.submit(*long_args)
    eng.drain()
    eng.submit(*short_args)
    (ok,) = eng.drain()
    np.testing.assert_array_equal(ok, ref)


def test_engine_param_validation(verifier):
    with pytest.raises(ValueError):
        verifier.make_ingest(nbuf=1)
    with pytest.raises(ValueError):
        verifier.make_ingest(nbuf=2, depth=0)
    rlc = SigVerifier(VerifierConfig(batch=BATCH, msg_maxlen=ML),
                      mode="rlc")
    with pytest.raises(ValueError):
        rlc.make_ingest()


def test_bucket_pool_rotation_zeroed():
    """Pipeline-level pool: reset() rotates a FREE blob in (fresh while
    the pool is dry, reused-and-rezeroed after release())."""
    from firedancer_tpu.disco.pipeline import _Bucket

    bk = _Bucket(4, 32, packed=True, n_buffers=2)
    first = bk.arr
    first[:] = 7
    bk.reset()                    # first still pinned under its dispatch
    assert bk.arr is not first
    bk.release(first)             # verdict materialized
    bk.reset()
    assert bk.arr is first        # reused from the pool
    assert not bk.arr.any()       # and re-zeroed
    # views rebind to the active blob
    bk.msgs[0, 0] = 1
    assert bk.arr[0, 0] == 1


def test_pipeline_packed_pool_bounded():
    """The pool never exceeds n_buffers even if more blobs are released
    (age-flush bursts): excess blobs fall to the GC."""
    from firedancer_tpu.disco.pipeline import _Bucket

    bk = _Bucket(4, 32, packed=True, n_buffers=2)
    blobs = []
    for _ in range(4):
        blobs.append(bk.arr)
        bk.reset()
    for b in blobs:
        bk.release(b)
    assert len(bk._pool) == 2
