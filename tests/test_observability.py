"""fdtrace observability tests: span chains through a live 3-tile
pipeline, the /metrics + /healthz scrape round trip, Histf -> Prometheus
le-bucket invariants, and compile-event accounting on forced bucket
recompiles.

The pipeline test runs three Mux loops as THREADS over one created
topology (not spawned processes): the span/metrics machinery under test
is identical, and staying in-process keeps this module in the fast tier.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from firedancer_tpu.disco import metrics as metrics_mod
from firedancer_tpu.disco import topo as topo_mod
from firedancer_tpu.disco import trace as trace_mod
from firedancer_tpu.disco.mux import Mux
from firedancer_tpu.disco.topo import TopoBuilder
from firedancer_tpu.tango.ring import Cnc
from firedancer_tpu.utils.hist import Histf


def _wait(pred, timeout_s, what=""):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise TimeoutError(f"timed out waiting for {what}")


# -- span chain through a live pipeline -------------------------------------

class _SrcVt:
    """Publishes n frags from after_credit (outside frag context, so each
    frag STARTS a span chain: tsorig = its own tspub)."""

    def __init__(self, n):
        self.n = n
        self.sent = 0

    def after_credit(self, ctx):
        while self.sent < self.n:
            ctx.publish(bytes([self.sent]) * 32, sig=self.sent)
            self.sent += 1


class _FwdVt:
    def on_frag(self, ctx, iidx, meta, payload):
        ctx.publish(payload, sig=int(meta["sig"]))


class _SinkVt:
    def on_frag(self, ctx, iidx, meta, payload):
        pass


def test_span_chain_three_tiles():
    n = 8
    spec = (
        TopoBuilder(f"obs{os.getpid()}", wksp_mb=8)
        .link("a_b", depth=64, mtu=256)
        .link("b_c", depth=64, mtu=256)
        .tile("src", "sink", outs=["a_b"])
        .tile("mid", "sink", ins=["a_b"], outs=["b_c"])
        .tile("snk", "sink", ins=["b_c"])
        .build()
    )
    jt = topo_mod.create(spec)
    try:
        muxes = {"src": Mux(jt, "src", _SrcVt(n)),
                 "mid": Mux(jt, "mid", _FwdVt()),
                 "snk": Mux(jt, "snk", _SinkVt())}
        threads = [threading.Thread(target=m.run, daemon=True)
                   for m in muxes.values()]
        for t in threads:
            t.start()
        _wait(lambda: jt.metrics["snk"].get("in_frag_cnt") == n,
              30, f"{n} frags at the sink")
        for cnc in jt.cnc.values():
            cnc.signal(Cnc.SIGNAL_HALT)
        for t in threads:
            t.join(10)
            assert not t.is_alive()

        spans = {}
        for name in ("mid", "snk"):
            cur, recs = jt.trace[name].snapshot()
            frag = recs[recs["kind"] == trace_mod.KIND_FRAG]
            assert len(frag) == n, f"{name}: {len(frag)} frag spans"
            # single-writer monotonic clock: span starts never go backward
            assert np.all(np.diff(frag["ts"].astype(np.int64)) >= 0)
            spans[name] = recs

        # chain age: at the sink the frag is two hops old, so the
        # origin-relative age must be >= the last hop's latency
        snk = spans["snk"]
        assert np.all(snk["age_ns"].astype(np.int64)
                      >= snk["hop_ns"].astype(np.int64))
        # src -> mid is one hop: the chain originated at src's publish
        mid = spans["mid"]
        assert np.all(mid["age_ns"].astype(np.int64)
                      >= mid["hop_ns"].astype(np.int64))

        # the sink's shm in_hop_ns histogram is fed from the SAME hop
        # measurements the spans carry: rebuilding it from span hop_ns
        # must agree bucket-for-bucket (spans whose stamp raced the
        # consumer's clock capture record hop 0 and may be unsampled)
        edges, counts, hsum = jt.metrics["snk"].hist_snapshot("in_hop_ns")
        h = Histf(100, 10e9)
        for v in snk["hop_ns"]:
            if int(v):
                h.sample(int(v))
        zeros = int(np.sum(snk["hop_ns"] == 0))
        diff = counts.astype(np.int64) - h.counts.astype(np.int64)
        assert np.all(diff >= 0)
        assert int(diff.sum()) <= zeros

        # Chrome trace export is valid and loadable
        doc = trace_mod.chrome_trace(spans)
        blob = json.dumps(doc)
        back = json.loads(blob)
        xs = [e for e in back["traceEvents"] if e["ph"] == "X"]
        assert len(xs) == 2 * n
        assert all(e["dur"] > 0 and "frag" in e["name"] for e in xs)
        names = {e["args"]["name"] for e in back["traceEvents"]
                 if e["ph"] == "M"}
        assert {"mid", "snk"} <= names
        # and the terminal table renders
        table = trace_mod.hop_table(spans)
        assert "frag" in table and "mid" in table
    finally:
        jt.close()
        jt.unlink()


# -- /metrics + /healthz scrape round trip ----------------------------------

def _check_exposition(body: str):
    """Minimal Prometheus text-format checker: every sample line parses,
    every metric family was HELP+TYPE-declared exactly once with a valid
    kind (text-format conformance: one declaration per family even when
    the family spans many tiles/links)."""
    declared, helped = {}, set()
    for line in body.strip().splitlines():
        if line.startswith("# HELP "):
            name = line.split()[2]
            assert name not in helped, f"duplicate HELP for {name}"
            helped.add(name)
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split()
            assert kind in ("counter", "gauge", "histogram"), line
            assert name not in declared, f"duplicate TYPE for {name}"
            declared[name] = kind
            continue
        assert not line.startswith("#"), line
        name = line.split("{", 1)[0].split(" ", 1)[0]
        float(line.rsplit(" ", 1)[1])  # value parses
        base = name
        for suf in ("_bucket", "_sum", "_count"):
            if name.endswith(suf) and name.removesuffix(suf) in declared:
                base = name.removesuffix(suf)
        assert base in declared, f"undeclared metric {name}"
        if base != name:
            assert declared[base] == "histogram", line
    return declared


def test_metrics_http_roundtrip():
    from firedancer_tpu.disco.run import MetricsHttpServer

    spec = (
        TopoBuilder(f"obsh{os.getpid()}", wksp_mb=8)
        .link("a_b", depth=64, mtu=256)
        .tile("src", "sink", outs=["a_b"])
        .tile("snk", "sink", ins=["a_b"])
        .build()
    )
    jt = topo_mod.create(spec)
    srv = MetricsHttpServer(jt, port=0)
    try:
        m = jt.metrics["snk"]
        m.add("in_frag_cnt", 7)
        m.set("in0_hop_p50_ns", 1234)
        samples = [150, 1_000, 50_000, 2_000_000, 20e9]  # last overflows
        for v in samples:
            m.hist_sample("in_hop_ns", v)

        base = f"http://127.0.0.1:{srv.port}"
        r = urllib.request.urlopen(f"{base}/metrics", timeout=10)
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("text/plain")
        body = r.read().decode()
        declared = _check_exposition(body)
        assert declared["fdtpu_in_frag_cnt"] == "counter"
        assert declared["fdtpu_in0_hop_p50_ns"] == "gauge"
        assert declared["fdtpu_in_hop_ns"] == "histogram"

        # le-bucket invariants for the snk tile's hop histogram
        buckets, total, hsum = [], None, None
        for line in body.splitlines():
            if line.startswith("fdtpu_in_hop_ns") and 'tile="snk"' in line:
                val = float(line.rsplit(" ", 1)[1])
                if "_bucket{" in line:
                    le = line.split(',le="', 1)[1].split('"', 1)[0]
                    buckets.append((le, val))
                elif line.startswith("fdtpu_in_hop_ns_count"):
                    total = val
                elif line.startswith("fdtpu_in_hop_ns_sum"):
                    hsum = val
        assert buckets and buckets[-1][0] == "+Inf"
        cum = [v for _, v in buckets]
        assert cum == sorted(cum), "cumulative buckets must be monotonic"
        assert cum[-1] == total == len(samples)
        # the overflow sample sits only in +Inf
        assert cum[-2] == len(samples) - 1
        assert hsum == sum(int(v) for v in samples)

        # healthz: BOOT tiles -> 503 with the offenders listed
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/healthz", timeout=10)
        assert ei.value.code == 503
        assert "src" in ei.value.read().decode()
        # all RUN with fresh heartbeats -> 200
        for cnc in jt.cnc.values():
            cnc.signal(Cnc.SIGNAL_RUN)
            cnc.heartbeat(time.monotonic_ns())
        r = urllib.request.urlopen(f"{base}/healthz", timeout=10)
        assert r.status == 200
        # unknown path -> 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/nope", timeout=10)
        assert ei.value.code == 404
    finally:
        srv.close()
        jt.close()
        jt.unlink()


def test_metrics_schema_lints():
    metrics_mod.lint_schema()


# -- compile events + occupancy on forced bucket recompile ------------------

def _make_payloads(n, extra_accounts, seed):
    from firedancer_tpu.ballet import txn as txn_lib
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        msg = txn_lib.build_unsigned(
            [rng.bytes(32)], rng.bytes(32), [(1, bytes([0]), bytes(8))],
            extra_accounts=[rng.bytes(32) for _ in range(extra_accounts)])
        out.append(txn_lib.assemble([rng.bytes(64)], msg))
    return out


def test_compile_events_and_occupancy():
    from firedancer_tpu.ballet import txn as txn_lib
    from firedancer_tpu.disco.pipeline import VerifyPipeline

    small = _make_payloads(4, 1, seed=7)
    big = _make_payloads(4, 12, seed=8)
    len_s = len(txn_lib.parse(small[0]).message(small[0]))
    len_b = len(txn_lib.parse(big[0]).message(big[0]))
    assert len_s < len_b

    ring_buf = bytearray(trace_mod.footprint(depth=256))
    ring = trace_mod.TraceRing(memoryview(ring_buf), 0, create=True,
                               depth=256)

    def fake_verify(msgs, lens, sigs, pubs):
        return np.ones(msgs.shape[0], dtype=bool)

    pipe = VerifyPipeline(fake_verify,
                          buckets=[(4, len_s), (4, len_b)],
                          tracer=ring)
    for p in small + big:
        pipe.submit(p)
    pipe.flush()

    s = pipe.metrics
    # one compile event per (batch, maxlen) shape's first dispatch
    assert s.compile_cnt == 2
    assert s.compile_ns > 0
    # both buckets filled completely: no padding lanes
    assert s.lanes_filled == 8
    assert s.lanes_dispatched == 8
    assert s.last_fill_pct == 100
    # the process-wide registry saw the same two shapes
    evs = trace_mod.compile_events()
    assert evs[("verify", 4, len_s)]["cnt"] >= 1
    assert evs[("verify", 4, len_b)]["cnt"] >= 1

    _, recs = ring.snapshot()
    kinds = recs["kind"]
    assert int(np.sum(kinds == trace_mod.KIND_COMPILE)) == 2
    assert int(np.sum(kinds == trace_mod.KIND_COALESCE)) == 2
    assert int(np.sum(kinds == trace_mod.KIND_DEVICE)) == 2
    dev = recs[kinds == trace_mod.KIND_DEVICE]
    assert np.all(dev["cnt"] == 4)

    # a re-dispatch of an already-seen shape is NOT a compile event
    more = _make_payloads(4, 1, seed=9)
    for p in more:
        pipe.submit(p)
    pipe.flush()
    assert pipe.metrics.compile_cnt == 2


# -- trace ring + Histf unit invariants -------------------------------------

def test_trace_ring_lap_and_order():
    depth = 64
    buf = bytearray(trace_mod.footprint(depth=depth))
    ring = trace_mod.TraceRing(memoryview(buf), 0, create=True, depth=depth)
    for i in range(200):
        ring.record(trace_mod.KIND_FRAG, ts=1000 + i, dur=5, seq=i)
    cur, recs = ring.snapshot()
    assert cur == 200
    assert len(recs) == depth  # lapped: only the newest depth survive
    assert recs[0]["seq"] == 200 - depth and recs[-1]["seq"] == 199
    assert np.all(np.diff(recs["ts"].astype(np.int64)) > 0)
    # incremental drain: nothing new -> empty
    cur2, recs2 = ring.snapshot(since=cur)
    assert cur2 == cur and len(recs2) == 0
    # a joiner over the same memory sees the same records
    ring2 = trace_mod.TraceRing(memoryview(buf), 0)
    _, recs3 = ring2.snapshot()
    assert np.array_equal(recs3, recs)


def test_histf_percentile_and_overflow():
    h = Histf(100, 1e9)
    rng = np.random.default_rng(3)
    vals = rng.integers(100, 1_000_000, size=500)
    for v in vals:
        h.sample(int(v))
    for q in (0.25, 0.5, 0.9, 0.99, 1.0):
        # reference semantics: first edge whose cumulative count reaches
        # ceil(q * total)
        target = int(np.ceil(q * h.count()))
        acc = 0
        want = float(h.edges[-1])
        for i, c in enumerate(h.counts):
            acc += int(c)
            if acc >= target:
                want = float(h.edges[min(i, len(h.edges) - 1)])
                break
        assert h.percentile(q) == want
    assert h.overflow_cnt() == 0
    h.sample(5e9)  # beyond max -> clamped into the overflow bucket
    assert h.overflow_cnt() == 1
    assert h.percentile(1.0) == float(h.edges[-1])
    assert Histf(100, 1e9).percentile(0.99) == 0.0
