"""Adversarial ed25519 conformance corpus, Wycheproof/CCTV-class.

Generates the vector classes of the reference's conformance suites —
Wycheproof EdDSA (src/ballet/ed25519/test_ed25519_wycheproof.c), the
"Taming the many EdDSAs" CCTV corpus (test_ed25519_cctv.c) and the
malleability suite (test_ed25519_signature_malleability.c) — as
(msg, sig, pub, expected, label) tuples with expectations matching the
reference's strict rule set (fd_ed25519_user.c:135-229):

  * S >= L rejected (malleability), non-canonical A/R y-encodings accepted,
    small-order A or R rejected, cofactorless group equation.

The corpus generator deliberately uses ONLY the golden model for point
arithmetic; tests cross-check the golden model itself against the
OpenSSL-backed `cryptography` package (an implementation with no shared
authorship) on the semantics-universal classes, so a shared-misunderstanding
bug between golden model and device code cannot pass silently.
"""

from . import ed25519_golden as g

L = g.L
P = g.P

# The 8 canonical encodings of small-order points (order | 8): identity,
# the order-2 point, two order-4, four order-8 — derived here from the
# golden model rather than pasted, then sanity-asserted.


def _small_order_encodings():
    # [k](order-8 generator) for k in 0..7 where the order-8 generator is a
    # point with y = _ORDER8_Y0 (golden model's table)
    p8 = g.pt_decompress(g._ORDER8_Y0.to_bytes(32, "little"))
    assert p8 is not None
    encs = []
    acc = g.IDENT
    for k in range(8):
        encs.append(g.pt_compress(acc))
        acc = g.pt_add(acc, p8)
    assert g.pt_eq(acc, g.IDENT)  # order divides 8
    # plus the sign-bit variants that also decompress to small order
    extra = []
    for e in encs:
        flipped = bytes(e[:31]) + bytes([e[31] ^ 0x80])
        d = g.pt_decompress(flipped)
        if d is not None and g.is_small_order_affine(d):
            extra.append(flipped)
    return encs + extra


def build_corpus():
    """Returns list of (label, msg, sig, pub, expected_bool)."""
    out = []

    def add(label, msg, sig, pub, expected):
        assert len(sig) == 64 and len(pub) == 32
        out.append((label, msg, sig, pub, expected))

    secret = bytes(range(32))
    pub = g.public_key(secret)

    # ---- valid signatures across message sizes (incl. empty) ----
    for n in (0, 1, 32, 64, 100, 255, 1000):
        msg = bytes((7 * i + n) & 0xFF for i in range(n))
        add(f"valid_len{n}", msg, g.sign(secret, msg), pub, True)

    msg = b"wycheproof-class vectors"
    sig = g.sign(secret, msg)

    # ---- bit flips over every sig byte region + pub ----
    for pos in (0, 15, 31, 32, 47, 63):
        bad = bytearray(sig)
        bad[pos] ^= 0x01
        # flipping inside S may produce S >= L or a wrong-but-canonical S;
        # either way verification must fail
        add(f"sigflip_{pos}", msg, bytes(bad), pub, False)
    badpub = bytearray(pub)
    badpub[3] ^= 0x40
    d = g.pt_decompress(bytes(badpub))
    if d is not None:  # decompressible corrupted key: must still reject
        add("pubflip", msg, sig, bytes(badpub), False)
    add("wrong_msg", msg + b"x", sig, pub, False)

    # ---- scalar range: the malleability suite ----
    R, S = sig[:32], int.from_bytes(sig[32:], "little")
    add("s_eq_L", msg, R + L.to_bytes(32, "little"), pub, False)
    add("s_plus_L", msg, R + (S + L).to_bytes(32, "little"), pub, False)
    add("s_maxu256", msg, R + (2**256 - 1).to_bytes(32, "little"), pub, False)
    add("s_high_bit", msg, R + ((S | (1 << 255)) .to_bytes(32, "little")),
        pub, False)
    add("s_zero_wrong", msg, R + bytes(32), pub, False)

    # ---- non-canonical y encodings ----
    # Only y < 19 has a second encoding y' = y + p < 2^255, and every curve
    # point with y < 19 is small order — so the observable contract is:
    # non-canonical encodings DECOMPRESS (not rejected as malformed, the
    # dalek-2.x/fd_f25519_frombytes semantics) and are then rejected by the
    # small-order rule.  A strict-canonical decoder would reject them one
    # step earlier; either way the bit is False, but the decompress-accept
    # behavior is pinned by the golden/device decompress tests below.
    a, prefix = g.secret_expand(secret)
    for y in range(19):
        enc = (y + P).to_bytes(32, "little")
        d = g.pt_decompress(enc)
        if d is None:
            continue
        # y ∈ {0, 1} decompress to small-order points; other small y can be
        # ordinary curve points — either way no signature under them exists
        # here, so the verify bit is False; the decompress-accept semantic
        # is pinned separately by test_noncanonical_encodings_decompress.
        add(f"noncanon_A_y{y}", msg, sig, enc, False)
        add(f"noncanon_R_y{y}", msg, enc + sig[32:], pub, False)

    # ---- small-order A and R: strict mode rejects ----
    so = _small_order_encodings()
    for i, enc in enumerate(so):
        add(f"smallorder_A_{i}", msg, sig, enc, False)
        add(f"smallorder_R_{i}", msg, enc + sig[32:], pub, False)

    # ---- small-order with the group equation HOLDING: rejection must be
    # attributable to the small-order rule itself, not a failed equation
    # (the CCTV construction, test_ed25519_cctv.c) ----
    # (a) A small order: find msg with k ≡ 0 (mod 8); then [k]A = identity
    #     and (R=[s0]B, S=s0) satisfies the cofactorless equation.
    so8 = [e for e in so if not g.pt_eq(g.pt_decompress(e) or g.IDENT,
                                        g.IDENT)]
    if so8:
        A_enc = so8[-1]
        s0 = 12345
        R0 = g.pt_compress(g.pt_mul(s0, g.BASE))
        for tweak in range(256):
            m3 = b"cctv-small-A" + bytes([tweak])
            k = int.from_bytes(g.sha512(R0 + A_enc + m3), "little") % L
            if k % 8 == 0:
                add("smallorder_A_eq_holds", m3,
                    R0 + s0.to_bytes(32, "little"), A_enc, False)
                break
    # (b) R = identity: S = k*a satisfies [S]B = identity + [k]A exactly.
    ident_enc = g.pt_compress(g.IDENT)
    m4 = b"cctv-identity-R"
    k = int.from_bytes(g.sha512(ident_enc + pub + m4), "little") % L
    s_id = k * a % L
    add("smallorder_R_eq_holds", m4, ident_enc + s_id.to_bytes(32, "little"),
        pub, False)

    # ---- x=0-with-sign-bit encodings (decompress ok, small order) ----
    for y in (0, 1):
        enc = (y | (1 << 255)).to_bytes(32, "little")
        if g.pt_decompress(enc) is not None:
            add(f"x0_signbit_y{y}", msg, sig, enc, False)

    # ---- non-square y (undecompressible A / R) ----
    for cand in range(2, 300):
        enc = cand.to_bytes(32, "little")
        if g.pt_decompress(enc) is None:
            add("undecompressible_A", msg, sig, enc, False)
            add("undecompressible_R", msg, enc + sig[32:], pub, False)
            break

    # ---- second keypair sanity + cross-key confusion ----
    secret2 = bytes(31) + b"\x01"
    pub2 = g.public_key(secret2)
    add("valid_key2", msg, g.sign(secret2, msg), pub2, True)
    add("cross_key", msg, g.sign(secret2, msg), pub, False)

    return out
