"""Pure-python ed25519 golden model (python ints + hashlib).

Plays the role the cocotb golden model `py/ref_ed25519.py` plays for the
reference's FPGA backend (reference: src/wiredancer/sim/*/test.py): every
device kernel is differential-tested against this model.

Semantics follow RFC 8032 with the exact deviations the reference applies
(reference: src/ballet/ed25519/fd_ed25519_user.c:135-229):

  * scalar S must satisfy 0 <= S < L, else invalid
  * A and R are decompressed per RFC; non-canonical y encodings (y >= p) are
    ACCEPTED (dalek 2.x behavior; fd_ed25519_user.c:180-199 comment)
  * the x=0-with-sign-bit-set encoding is ACCEPTED at decompress (matches
    fd_ed25519_point_frombytes, src/ballet/ed25519/fd_curve25519.c:26-63,
    which applies no such check) — such points are then rejected as small
    order anyway
  * small-order A or R (order <= 8) are REJECTED (verify_strict rule,
    fd_ed25519_user.c:200-206)
  * group equation checked as [S]B + [k](-A) == R without cofactor-8
    multiplication (fd_ed25519_user.c:216-224)
"""

import hashlib

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)

# order-8 subgroup y coordinates (fd_curve25519.h:82-113 table)
_ORDER8_Y0 = int.from_bytes(
    bytes.fromhex("26e8958fc2b227b045c3f489f2ef98f0d5dfac05d3c63339b13802886d53fc05"),
    "little",
) & ((1 << 255) - 1)
_ORDER8_Y1 = int.from_bytes(
    bytes.fromhex("c7176a703d4dd84fba3c0b760d10670f2a2053fa2c39ccc64ec7fd7792ac037a"),
    "little",
) & ((1 << 255) - 1)


def sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


# ---------------------------------------------------------------- field


def finv(x: int) -> int:
    return pow(x, P - 2, P)


def sqrt_ratio(u: int, v: int):
    """Returns (ok, x) with x = sqrt(u/v) when it exists, following the
    candidate-root recipe of RFC 8032 5.1.3."""
    x = (u * pow(v, 3, P) % P) * pow(u * pow(v, 7, P) % P, (P - 5) // 8, P) % P
    vxx = v * x * x % P
    if vxx == u % P:
        return True, x
    if vxx == (-u) % P:
        return True, x * SQRT_M1 % P
    return False, 0


# ---------------------------------------------------------------- points
# Extended homogeneous coordinates (X:Y:Z:T), x=X/Z, y=Y/Z, T=XY/Z.

IDENT = (0, 1, 1, 0)


def pt_add(p, q):
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = (Y1 - X1) * (Y2 - X2) % P
    B = (Y1 + X1) * (Y2 + X2) % P
    C = 2 * T1 * T2 * D % P
    Dd = 2 * Z1 * Z2 % P
    E, F, G, H = (B - A) % P, (Dd - C) % P, (Dd + C) % P, (B + A) % P
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def pt_double(p):
    return pt_add(p, p)


def pt_mul(s: int, p):
    q = IDENT
    while s > 0:
        if s & 1:
            q = pt_add(q, p)
        p = pt_double(p)
        s >>= 1
    return q


def pt_neg(p):
    X, Y, Z, T = p
    return ((-X) % P, Y, Z, (-T) % P)


def pt_eq(p, q) -> bool:
    X1, Y1, Z1, _ = p
    X2, Y2, Z2, _ = q
    return (X1 * Z2 - X2 * Z1) % P == 0 and (Y1 * Z2 - Y2 * Z1) % P == 0


def pt_compress(p) -> bytes:
    X, Y, Z, _ = p
    zi = finv(Z)
    x, y = X * zi % P, Y * zi % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def pt_decompress(b: bytes):
    """Returns the point or None.  Accepts non-canonical y (reduced mod p)."""
    n = int.from_bytes(b, "little")
    sign = n >> 255
    y = (n & ((1 << 255) - 1)) % P
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    ok, x = sqrt_ratio(u, v)
    if not ok:
        return None
    if (x & 1) != sign:
        x = (-x) % P
    return (x, y, 1, x * y % P)


BASE_Y = 4 * finv(5) % P
_ok, BASE_X = sqrt_ratio((BASE_Y * BASE_Y - 1) % P, (D * BASE_Y * BASE_Y + 1) % P)
if BASE_X & 1:
    BASE_X = (-BASE_X) % P
BASE = (BASE_X, BASE_Y, 1, BASE_X * BASE_Y % P)


def is_small_order_affine(p) -> bool:
    """fd_ed25519_affine_is_small_order (fd_curve25519.h:82-113): affine
    point (Z==1) has order <= 8 iff X==0 or Y==0 or Y is an order-8 y."""
    X, Y, Z, _ = p
    assert Z == 1
    return X % P == 0 or Y % P == 0 or Y % P == _ORDER8_Y0 or Y % P == _ORDER8_Y1


# ---------------------------------------------------------------- eddsa


def secret_expand(secret: bytes):
    h = sha512(secret)
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def public_key(secret: bytes) -> bytes:
    a, _ = secret_expand(secret)
    return pt_compress(pt_mul(a, BASE))


def sign(secret: bytes, msg: bytes) -> bytes:
    a, prefix = secret_expand(secret)
    A = pt_compress(pt_mul(a, BASE))
    r = int.from_bytes(sha512(prefix + msg), "little") % L
    Rs = pt_compress(pt_mul(r, BASE))
    k = int.from_bytes(sha512(Rs + A + msg), "little") % L
    s = (r + k * a) % L
    return Rs + s.to_bytes(32, "little")


def verify(msg: bytes, sig: bytes, pubkey: bytes) -> bool:
    """Strict verify with the reference's exact rule set (module docstring)."""
    if len(sig) != 64 or len(pubkey) != 32:
        return False
    S = int.from_bytes(sig[32:], "little")
    if S >= L:
        return False
    A = pt_decompress(pubkey)
    if A is None:
        return False
    R = pt_decompress(sig[:32])
    if R is None:
        return False
    if is_small_order_affine(A) or is_small_order_affine(R):
        return False
    k = int.from_bytes(sha512(sig[:32] + pubkey + msg), "little") % L
    chk = pt_add(pt_mul(S, BASE), pt_mul(k, pt_neg(A)))
    return pt_eq(chk, R)
