"""Transaction wire-format parser tests (rule set of fd_txn_parse,
src/ballet/txn/fd_txn_parse.c; the reference's test_txn_parse drives the
same cases from fuzz corpora)."""

import secrets

import pytest

from firedancer_tpu.ballet import compact_u16 as cu16
from firedancer_tpu.ballet import txn as txn_lib


def test_compact_u16_roundtrip():
    for v in [0, 1, 0x7F, 0x80, 0x3FFF, 0x4000, 0xFFFF]:
        enc = cu16.encode(v)
        dec, used = cu16.decode(enc)
        assert (dec, used) == (v, len(enc))


def test_compact_u16_non_minimal_rejected():
    # 0x80 0x00 encodes 0 in two bytes: illegal
    with pytest.raises(ValueError):
        cu16.decode(bytes([0x80, 0x00]))
    # 3-byte with zero third byte: illegal
    with pytest.raises(ValueError):
        cu16.decode(bytes([0x80, 0x80, 0x00]))
    # third byte > 3 overflows u16
    with pytest.raises(ValueError):
        cu16.decode(bytes([0x80, 0x80, 0x04]))
    with pytest.raises(ValueError):
        cu16.decode(bytes([0x80]))  # truncated


def _mk_txn(nsig=1, version=txn_lib.VLEGACY, ninstr=1, extra=1, data=b"\x01\x02"):
    signers = [secrets.token_bytes(32) for _ in range(nsig)]
    extras = [secrets.token_bytes(32) for _ in range(extra)]
    instrs = [(nsig, bytes([0]), data)] * ninstr  # program = first extra acct
    msg = txn_lib.build_unsigned(
        signers, secrets.token_bytes(32), instrs, extras, version=version
    )
    sigs = [secrets.token_bytes(64) for _ in range(nsig)]
    return txn_lib.assemble(sigs, msg), signers, sigs, msg


def test_parse_legacy_roundtrip():
    payload, signers, sigs, msg = _mk_txn(nsig=2, extra=2, ninstr=3)
    t = txn_lib.parse(payload)
    assert t.transaction_version == txn_lib.VLEGACY
    assert t.signature_cnt == 2
    assert t.acct_addr_cnt == 4
    assert len(t.instrs) == 3
    assert t.signatures(payload) == sigs
    assert t.signer_pubkeys(payload) == signers
    assert t.message(payload) == msg
    assert t.instrs[0].program_id == 2
    assert payload[t.instrs[0].data_off : t.instrs[0].data_off + t.instrs[0].data_sz] == b"\x01\x02"


def test_parse_v0_roundtrip():
    payload, signers, sigs, msg = _mk_txn(nsig=1, version=txn_lib.V0)
    t = txn_lib.parse(payload)
    assert t.transaction_version == txn_lib.V0
    assert t.addr_table_lookup_cnt == 0
    assert t.message(payload) == msg


def test_parse_rejects_trailing_bytes():
    payload, *_ = _mk_txn()
    with pytest.raises(txn_lib.TxnParseError):
        txn_lib.parse(payload + b"\x00")


def test_parse_rejects_truncation():
    payload, *_ = _mk_txn()
    for cut in (1, 32, 64, len(payload) - 1):
        with pytest.raises(txn_lib.TxnParseError):
            txn_lib.parse(payload[:cut])


def test_parse_rejects_zero_sigs():
    payload, *_ = _mk_txn()
    bad = bytes([0]) + payload[1:]
    with pytest.raises(txn_lib.TxnParseError):
        txn_lib.parse(bad)


def test_parse_rejects_mtu_overflow():
    with pytest.raises(txn_lib.TxnParseError):
        txn_lib.parse(b"\x01" * (txn_lib.MTU + 1))


def test_parse_rejects_header_mismatch():
    payload, *_ = _mk_txn(nsig=1)
    # legacy: message byte 0 must equal signature_cnt
    msg_off = 1 + 64
    bad = payload[:msg_off] + bytes([2]) + payload[msg_off + 1 :]
    with pytest.raises(txn_lib.TxnParseError):
        txn_lib.parse(bad)


def test_parse_rejects_bad_version():
    payload, *_ = _mk_txn(nsig=1)
    msg_off = 1 + 64
    bad = payload[:msg_off] + bytes([0x81]) + payload[msg_off + 1 :]  # version 1
    with pytest.raises(txn_lib.TxnParseError):
        txn_lib.parse(bad)


def test_parse_rejects_program_is_fee_payer():
    signers = [secrets.token_bytes(32)]
    msg = txn_lib.build_unsigned(
        signers, secrets.token_bytes(32), [(0, b"", b"")], [secrets.token_bytes(32)]
    )
    payload = txn_lib.assemble([secrets.token_bytes(64)], msg)
    with pytest.raises(txn_lib.TxnParseError):
        txn_lib.parse(payload)


def test_parse_rejects_account_index_out_of_range():
    signers = [secrets.token_bytes(32)]
    msg = txn_lib.build_unsigned(
        signers, secrets.token_bytes(32), [(1, bytes([7]), b"")], [secrets.token_bytes(32)]
    )
    payload = txn_lib.assemble([secrets.token_bytes(64)], msg)
    with pytest.raises(txn_lib.TxnParseError):
        txn_lib.parse(payload)


def test_parse_random_mutations_never_crash():
    payload, *_ = _mk_txn(nsig=2, extra=2, ninstr=2)
    import random

    rng = random.Random(7)
    for _ in range(500):
        b = bytearray(payload)
        for _ in range(rng.randint(1, 4)):
            b[rng.randrange(len(b))] = rng.randrange(256)
        try:
            txn_lib.parse(bytes(b))
        except txn_lib.TxnParseError:
            pass  # rejection is fine; crashing is not


def test_writability_partition():
    # 3 signers (1 ro), 3 unsigned (2 ro)
    signers = [secrets.token_bytes(32) for _ in range(3)]
    extras = [secrets.token_bytes(32) for _ in range(3)]
    msg = txn_lib.build_unsigned(
        signers,
        secrets.token_bytes(32),
        [(3, bytes([0]), b"")],
        extras,
        readonly_signed_cnt=1,
        readonly_unsigned_cnt=2,
    )
    payload = txn_lib.assemble([secrets.token_bytes(64)] * 3, msg)
    t = txn_lib.parse(payload)
    assert [t.is_writable(i) for i in range(6)] == [True, True, False, True, False, False]
