"""Transaction wire-format parser tests (rule set of fd_txn_parse,
src/ballet/txn/fd_txn_parse.c; the reference's test_txn_parse drives the
same cases from fuzz corpora)."""

import secrets

import pytest

from firedancer_tpu.ballet import compact_u16 as cu16
from firedancer_tpu.ballet import txn as txn_lib


def test_compact_u16_roundtrip():
    for v in [0, 1, 0x7F, 0x80, 0x3FFF, 0x4000, 0xFFFF]:
        enc = cu16.encode(v)
        dec, used = cu16.decode(enc)
        assert (dec, used) == (v, len(enc))


def test_compact_u16_non_minimal_rejected():
    # 0x80 0x00 encodes 0 in two bytes: illegal
    with pytest.raises(ValueError):
        cu16.decode(bytes([0x80, 0x00]))
    # 3-byte with zero third byte: illegal
    with pytest.raises(ValueError):
        cu16.decode(bytes([0x80, 0x80, 0x00]))
    # third byte > 3 overflows u16
    with pytest.raises(ValueError):
        cu16.decode(bytes([0x80, 0x80, 0x04]))
    with pytest.raises(ValueError):
        cu16.decode(bytes([0x80]))  # truncated


def _mk_txn(nsig=1, version=txn_lib.VLEGACY, ninstr=1, extra=1, data=b"\x01\x02"):
    signers = [secrets.token_bytes(32) for _ in range(nsig)]
    extras = [secrets.token_bytes(32) for _ in range(extra)]
    instrs = [(nsig, bytes([0]), data)] * ninstr  # program = first extra acct
    msg = txn_lib.build_unsigned(
        signers, secrets.token_bytes(32), instrs, extras, version=version
    )
    sigs = [secrets.token_bytes(64) for _ in range(nsig)]
    return txn_lib.assemble(sigs, msg), signers, sigs, msg


def test_parse_legacy_roundtrip():
    payload, signers, sigs, msg = _mk_txn(nsig=2, extra=2, ninstr=3)
    t = txn_lib.parse(payload)
    assert t.transaction_version == txn_lib.VLEGACY
    assert t.signature_cnt == 2
    assert t.acct_addr_cnt == 4
    assert len(t.instrs) == 3
    assert t.signatures(payload) == sigs
    assert t.signer_pubkeys(payload) == signers
    assert t.message(payload) == msg
    assert t.instrs[0].program_id == 2
    assert payload[t.instrs[0].data_off : t.instrs[0].data_off + t.instrs[0].data_sz] == b"\x01\x02"


def test_parse_v0_roundtrip():
    payload, signers, sigs, msg = _mk_txn(nsig=1, version=txn_lib.V0)
    t = txn_lib.parse(payload)
    assert t.transaction_version == txn_lib.V0
    assert t.addr_table_lookup_cnt == 0
    assert t.message(payload) == msg


def test_parse_rejects_trailing_bytes():
    payload, *_ = _mk_txn()
    with pytest.raises(txn_lib.TxnParseError):
        txn_lib.parse(payload + b"\x00")


def test_parse_rejects_truncation():
    payload, *_ = _mk_txn()
    for cut in (1, 32, 64, len(payload) - 1):
        with pytest.raises(txn_lib.TxnParseError):
            txn_lib.parse(payload[:cut])


def test_parse_rejects_zero_sigs():
    payload, *_ = _mk_txn()
    bad = bytes([0]) + payload[1:]
    with pytest.raises(txn_lib.TxnParseError):
        txn_lib.parse(bad)


def test_parse_rejects_mtu_overflow():
    with pytest.raises(txn_lib.TxnParseError):
        txn_lib.parse(b"\x01" * (txn_lib.MTU + 1))


def test_parse_rejects_header_mismatch():
    payload, *_ = _mk_txn(nsig=1)
    # legacy: message byte 0 must equal signature_cnt
    msg_off = 1 + 64
    bad = payload[:msg_off] + bytes([2]) + payload[msg_off + 1 :]
    with pytest.raises(txn_lib.TxnParseError):
        txn_lib.parse(bad)


def test_parse_rejects_bad_version():
    payload, *_ = _mk_txn(nsig=1)
    msg_off = 1 + 64
    bad = payload[:msg_off] + bytes([0x81]) + payload[msg_off + 1 :]  # version 1
    with pytest.raises(txn_lib.TxnParseError):
        txn_lib.parse(bad)


def test_parse_rejects_program_is_fee_payer():
    signers = [secrets.token_bytes(32)]
    msg = txn_lib.build_unsigned(
        signers, secrets.token_bytes(32), [(0, b"", b"")], [secrets.token_bytes(32)]
    )
    payload = txn_lib.assemble([secrets.token_bytes(64)], msg)
    with pytest.raises(txn_lib.TxnParseError):
        txn_lib.parse(payload)


def test_parse_rejects_account_index_out_of_range():
    signers = [secrets.token_bytes(32)]
    msg = txn_lib.build_unsigned(
        signers, secrets.token_bytes(32), [(1, bytes([7]), b"")], [secrets.token_bytes(32)]
    )
    payload = txn_lib.assemble([secrets.token_bytes(64)], msg)
    with pytest.raises(txn_lib.TxnParseError):
        txn_lib.parse(payload)


def test_parse_random_mutations_never_crash():
    payload, *_ = _mk_txn(nsig=2, extra=2, ninstr=2)
    import random

    rng = random.Random(7)
    for _ in range(500):
        b = bytearray(payload)
        for _ in range(rng.randint(1, 4)):
            b[rng.randrange(len(b))] = rng.randrange(256)
        try:
            txn_lib.parse(bytes(b))
        except txn_lib.TxnParseError:
            pass  # rejection is fine; crashing is not


def test_writability_partition():
    # 3 signers (1 ro), 3 unsigned (2 ro)
    signers = [secrets.token_bytes(32) for _ in range(3)]
    extras = [secrets.token_bytes(32) for _ in range(3)]
    msg = txn_lib.build_unsigned(
        signers,
        secrets.token_bytes(32),
        [(3, bytes([0]), b"")],
        extras,
        readonly_signed_cnt=1,
        readonly_unsigned_cnt=2,
    )
    payload = txn_lib.assemble([secrets.token_bytes(64)] * 3, msg)
    t = txn_lib.parse(payload)
    assert [t.is_writable(i) for i in range(6)] == [True, True, False, True, False, False]


# ------------------------------------------------------- native batch parser


def _burst_parse_one(payload, maxlen=1232, cap=16):
    """Run the native burst parser on a single payload with fresh arrays."""
    import numpy as np

    from firedancer_tpu.ballet import txn_native as tn

    msgs = np.zeros((cap, maxlen), np.uint8)
    lens = np.zeros((cap,), np.int32)
    sigs = np.zeros((cap, 64), np.uint8)
    pubs = np.zeros((cap, 32), np.uint8)
    r = tn.parse_burst([payload], msgs, lens, sigs, pubs, 0, None)
    return r, msgs, lens, sigs, pubs


def test_native_parser_matches_python_accept_bits():
    """Rule parity: the C++ parser and ballet/txn.py accept/reject the
    same payloads over structured cases + random mutations."""
    import numpy as np

    from firedancer_tpu.ballet import txn_native as tn

    cases = []
    for nsig in (1, 2, 12):
        for version in (txn_lib.VLEGACY, txn_lib.V0):
            for ninstr in (0, 1, 3):
                p, *_ = _mk_txn(nsig=nsig, version=version, ninstr=ninstr,
                                extra=2)
                cases.append(p)
    # v0 with lookups
    signers = [secrets.token_bytes(32)]
    msg = txn_lib.build_unsigned(
        signers, secrets.token_bytes(32), [(1, bytes([0]), b"\x07")],
        [secrets.token_bytes(32)], version=txn_lib.V0,
        lookups=[(secrets.token_bytes(32), bytes([0, 1]), bytes([2]))])
    cases.append(txn_lib.assemble([secrets.token_bytes(64)], msg))
    # mutations of a base txn
    base, *_ = _mk_txn(nsig=2, extra=2, ninstr=2)
    rng = __import__("random").Random(99)
    for _ in range(400):
        b = bytearray(base)
        for _ in range(rng.randint(1, 3)):
            b[rng.randrange(len(b))] = rng.randrange(256)
        if rng.random() < 0.3:
            b = b[: rng.randrange(1, len(b))]
        cases.append(bytes(b))

    for p in cases:
        try:
            t = txn_lib.parse(p)
            py_ok = True
        except txn_lib.TxnParseError:
            py_ok = False
        r, msgs, lens, sigs, pubs = _burst_parse_one(p)
        assert r.consumed == 1
        c_ok = bool(r.err[0] == tn.OK)
        assert c_ok == py_ok, (p.hex(), r.err[0])
        if py_ok:
            # extraction parity: lanes carry the same msg/sig/pub bytes
            assert int(r.nsig[0]) == t.signature_cnt
            m = t.message(p)
            want_sigs = t.signatures(p)
            want_pubs = t.signer_pubkeys(p)
            for lane in range(t.signature_cnt):
                assert int(lens[lane]) == len(m)
                assert bytes(msgs[lane, : len(m)]) == m
                assert not msgs[lane, len(m):].any()
                assert bytes(sigs[lane]) == want_sigs[lane]
                assert bytes(pubs[lane]) == want_pubs[lane]
            assert int(r.tag[0]) == int.from_bytes(
                want_sigs[0][:8], "little")


def test_native_parser_burst_fill_and_dedup():
    """Bucket fill across flush boundaries + inline tcache dedup."""
    import numpy as np

    from firedancer_tpu.ballet import txn_native as tn
    from firedancer_tpu.tango.tcache import NativeTCache

    payloads = [_mk_txn()[0] for _ in range(10)]
    cap = 4
    msgs = np.zeros((cap, 256), np.uint8)
    lens = np.zeros((cap,), np.int32)
    sigs = np.zeros((cap, 64), np.uint8)
    pubs = np.zeros((cap, 32), np.uint8)
    tc = NativeTCache(64)

    r = tn.parse_burst(payloads, msgs, lens, sigs, pubs, 0, tc.handle)
    assert r.consumed == 4 and r.lanes_used == 4          # stopped at cap
    assert list(r.lane0) == [0, 1, 2, 3]

    # duplicate of an already-inserted tag is dropped inline
    tc.insert(int(r.tag[0]))
    r2 = tn.parse_burst(payloads[:1], msgs, lens, sigs, pubs, 0, tc.handle)
    assert r2.err[0] == tn.ERR_DUP


def test_pipeline_submit_burst_matches_scalar():
    """submit_burst end-to-end vs scalar submit on the same traffic, with
    a deterministic fake verifier (every even lane passes)."""
    import numpy as np

    from firedancer_tpu.disco.pipeline import VerifyPipeline

    payloads = [_mk_txn()[0] for _ in range(33)]
    payloads.append(payloads[0])          # exact duplicate -> dedup drop
    payloads.append(b"\x01garbage")       # parse failure

    def fake(m, l, s, p):
        return np.arange(np.asarray(m).shape[0]) % 2 == 0

    out_scalar, out_burst = [], []
    for mode in ("scalar", "burst"):
        pipe = VerifyPipeline(fake, batch=8, msg_maxlen=256)
        if mode == "scalar":
            for p in payloads:
                out_scalar += [pl for pl, _ in pipe.submit(p)]
            out_scalar += [pl for pl, _ in pipe.flush()]
            snap_s = pipe.metrics.snapshot()
        else:
            out_burst += [pl for pl, _ in pipe.submit_burst(payloads)]
            out_burst += [pl for pl, _ in pipe.flush()]
            snap_b = pipe.metrics.snapshot()

    assert out_scalar == out_burst
    for k in ("txns_in", "parse_fail", "dedup_drop", "verify_pass",
              "verify_fail"):
        assert snap_s[k] == snap_b[k], k
