"""Corpus replay + bounded coverage-guided fuzzing of every untrusted
parser (ref: src/util/sanitize/fd_fuzz_stub.c stub-replay + the per-parser
fuzz_*.c targets with checked-in corpus/ seeds).

CI semantics: replay every seed (fast, deterministic), then a short
mutation sweep with line-coverage feedback per target.  Any exception a
harness does not declare is a failure.  Longer runs: tools/fuzz_run.py."""

import os
import pathlib

import pytest

from firedancer_tpu.utils import fuzz
from firedancer_tpu.utils.fuzz_targets import TARGETS

CORPUS = pathlib.Path(__file__).parent / "corpus"


@pytest.mark.parametrize("name", sorted(TARGETS))
def test_corpus_replay(name):
    d = CORPUS / name
    assert d.is_dir() and any(d.iterdir()), \
        f"missing seed corpus for {name} (run tools/fuzz_corpus.py)"
    n = fuzz.replay(d, TARGETS[name])
    assert n >= 1


@pytest.mark.parametrize("name", sorted(TARGETS))
def test_fuzz_sweep(name):
    seeds = [p.read_bytes() for p in sorted((CORPUS / name).iterdir())]
    iters = int(os.environ.get("FDTPU_FUZZ_ITERS", 1500))
    grown, findings = fuzz.fuzz(TARGETS[name], seeds, iters=iters,
                                seed=0xF0 + len(name))
    assert not findings, [(f"{type(e).__name__}: {e}", d[:64].hex())
                          for d, e in findings[:5]]


def test_coverage_feedback_grows_corpus():
    """The engine itself: coverage feedback must discover inputs that
    reach new lines (a compact_u16 seed of one form should grow into the
    other encoding forms)."""
    seeds = [b"\x01\xff\xff"]
    grown, findings = fuzz.fuzz(TARGETS["compact_u16"], seeds, iters=3000,
                                seed=1)
    assert not findings
    assert grown, "no coverage-driven corpus growth"
