"""Zero-copy wire->device host path (round 8 tentpole).

Five falsifiable contracts, all CPU:

  1. NO MATERIALIZATION — ring tx of a large frag never builds an
     intermediate bytes copy (the old ctypes.c_char_p(bytes(buf))), and
     dcache views share memory with the shm mapping.
  2. ZERO REPACK — the blob handed to dispatch_blob by
     submit_packed_rows IS the dcache shm region (np.shares_memory), not
     a copy.
  3. NO TORN BUFFER — an overrun between rx and the post-dispatch seq
     re-check drops the batch whole (torn_drop) and still releases the
     held credit.
  4. BIT IDENTITY — verdicts through the zero-repack submit_rows path
     equal the legacy _pack_into path on a mixed valid/tampered batch,
     fixed seed.
  5. WIRE RECONSTRUCTION — passing rows rebuild the exact single-sig
     wire form (0x01 | sig | msg) from the pinned view, with tags
     inserted into the tcache only after verify passes.
"""

import secrets
import tracemalloc

import numpy as np
import pytest

from firedancer_tpu.ballet import txn as txn_lib
from firedancer_tpu.disco.pipeline import VerifyPipeline
from firedancer_tpu.disco.topo import LinkSpec, TileSpec, TopoSpec, \
    assign_affinity
from firedancer_tpu.ops import ed25519 as ed
from firedancer_tpu.tango.ring import (
    Dcache,
    MCache,
    PACKED_ROW_EXTRA,
    Workspace,
    packed_row_ml,
    tx_burst,
)

ML = packed_row_ml(256)          # 284: stride 384 == 6 chunks exactly
STRIDE = ML + PACKED_ROW_EXTRA


def test_packed_row_ml_chunk_aligned():
    for maxlen in (1, 64, 96, 256, 1232):
        ml = packed_row_ml(maxlen)
        assert ml >= maxlen
        assert (ml + PACKED_ROW_EXTRA) % 64 == 0
    assert packed_row_ml(256) == 284
    with pytest.raises(ValueError):
        packed_row_ml(0)


@pytest.fixture
def ring():
    ws = Workspace("fdtpu_test_hostpath", 32 << 20, create=True)
    try:
        mc = MCache.new(ws, 4)
        dc = Dcache.new(ws, 4 << 20, 2, 1)
        yield ws, mc, dc
    finally:
        # test-held views export pointers into the mapping; the mapping
        # dies with the process if one survives gc (same stance as
        # JoinedTopology.close)
        mc = dc = None
        import gc
        gc.collect()
        try:
            ws.close()
        except BufferError:
            pass
        ws.unlink()


def test_dcache_views_share_shm(ring):
    ws, mc, dc = ring
    w = dc.write_view(dc.chunk0, 3 * STRIDE)
    assert np.shares_memory(w, dc._arr)
    w[:] = 7
    rows = dc.rows(dc.chunk0, 3, STRIDE)
    assert rows.shape == (3, STRIDE)
    assert np.shares_memory(rows, dc._arr)
    assert (rows == 7).all()
    # advance lands on the next chunk boundary, never splitting a frag
    nxt = dc.advance(dc.chunk0, 3 * STRIDE)
    assert nxt == dc.chunk0 + 3 * STRIDE // dc.chunk_sz
    with pytest.raises(ValueError):
        dc.view(dc.chunk0, dc.data_sz + 64)


def test_tx_burst_no_bytes_materialization(ring):
    """Satellite 1: a 4 MB frag through tx_burst must not materialize an
    intermediate bytes copy of the payload (numpy routes allocations
    through tracemalloc, so a bytes(buf) or asarray copy would show as a
    ~4 MB peak; the zero-copy path allocates only scratch)."""
    ws, mc, dc = ring
    frag = np.arange(4 << 20, dtype=np.uint8)  # wraps mod 256; fine
    starts = np.zeros(1, np.int64)
    lens = np.array([frag.nbytes], np.int32)
    sigs = np.array([1], np.uint64)
    tx_burst(mc, dc, dc.chunk0, frag, starts, lens, sigs)  # warm scratch
    tracemalloc.start()
    tx_burst(mc, dc, dc.chunk0, frag, starts, lens, sigs)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert peak < frag.nbytes // 4, \
        f"tx materialized ~{peak} bytes for a {frag.nbytes} B frag"
    # and the memoryview/bytes entry points wrap zero-copy too
    mv = memoryview(bytes(frag))
    tracemalloc.start()
    tx_burst(mc, dc, dc.chunk0, mv, starts, lens, sigs)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert peak < frag.nbytes // 4


class _FakeBlobFn:
    """Captures the exact array object handed to dispatch; all-pass."""

    def __init__(self):
        self.blobs = []

    def __call__(self, m, ln, s, p):
        return np.ones(m.shape[0], bool)

    def dispatch_blob(self, blob, maxlen=None):
        self.blobs.append(blob)
        return np.ones(blob.shape[0], bool)


def _stamp_rows(view, wires, pubs, ml=ML):
    """Producer-side packed-row stamp: wire i = 0x01 | sig | msg."""
    for i, (w, pub) in enumerate(zip(wires, pubs)):
        msg = w[65:]
        view[i, :len(msg)] = np.frombuffer(msg, np.uint8)
        view[i, ml:ml + 64] = np.frombuffer(w[1:65], np.uint8)
        view[i, ml + 64:ml + 96] = np.frombuffer(pub, np.uint8)
        view[i, ml + 96:ml + 100] = np.frombuffer(
            len(msg).to_bytes(4, "little"), np.uint8)


def _signed_txn(seed: bytes, nonce: int) -> tuple[bytes, bytes]:
    pub = ed.keypair_from_seed(seed)[0]
    msg = txn_lib.build_unsigned(
        [pub], secrets.token_bytes(32),
        [(1, b"\x00", nonce.to_bytes(8, "little"))],
        [secrets.token_bytes(32)])
    return txn_lib.assemble([ed.sign(seed, msg)], msg), pub


def test_dispatch_receives_shm_view_not_copy(ring):
    """Satellite/acceptance: ZERO payload copies between ring rx and
    device dispatch — the blob at dispatch_blob IS dcache memory."""
    ws, mc, dc = ring
    fn = _FakeBlobFn()
    pipe = VerifyPipeline(fn, buckets=[(4, ML)], tcache_depth=64,
                          max_inflight=0)
    rows = dc.rows(dc.chunk0, 4, STRIDE)
    wires_pubs = [_signed_txn(bytes([i + 1]) * 32, i) for i in range(4)]
    _stamp_rows(rows, [w for w, _ in wires_pubs],
                [p for _, p in wires_pubs])
    mc.publish(sig=1, chunk=dc.chunk0, sz=4)
    passed = pipe.submit_packed_rows(rows, n=4, guard=(mc, 0))
    assert len(fn.blobs) == 1
    assert np.shares_memory(fn.blobs[0], dc._arr), \
        "dispatch got a copy, not the dcache view"
    assert [p for p, _ in passed] == [w for w, _ in wires_pubs]
    assert pipe.metrics.torn_drop == 0


def test_torn_upload_detected_and_dropped(ring):
    """Satellite 3: producer laps the mcache between rx and the
    post-dispatch re-check -> batch dropped whole, credit released."""
    ws, mc, dc = ring
    fn = _FakeBlobFn()
    pipe = VerifyPipeline(fn, buckets=[(4, ML)], tcache_depth=64,
                          max_inflight=0)
    rows = dc.rows(dc.chunk0, 4, STRIDE)
    wires_pubs = [_signed_txn(bytes([i + 9]) * 32, 100 + i)
                  for i in range(4)]
    _stamp_rows(rows, [w for w, _ in wires_pubs],
                [p for _, p in wires_pubs])
    # depth-4 mcache: seq 0 published, then lapped by 4 more publishes
    for s in range(5):
        mc.publish(sig=s + 1, chunk=dc.chunk0, sz=4)
    released = []
    passed = pipe.submit_packed_rows(rows, n=4, guard=(mc, 0),
                                     release_cb=lambda: released.append(1))
    assert passed == []
    assert pipe.metrics.torn_drop == 1
    assert released == [1], "credit must release exactly once on torn drop"
    assert pipe.metrics.verify_pass == 0


def test_release_fires_once_on_clean_path(ring):
    ws, mc, dc = ring
    fn = _FakeBlobFn()
    pipe = VerifyPipeline(fn, buckets=[(4, ML)], tcache_depth=64,
                          max_inflight=0)
    rows = dc.rows(dc.chunk0, 4, STRIDE)
    wires_pubs = [_signed_txn(bytes([i + 20]) * 32, 200 + i)
                  for i in range(4)]
    _stamp_rows(rows, [w for w, _ in wires_pubs],
                [p for _, p in wires_pubs])
    mc.publish(sig=1, chunk=dc.chunk0, sz=4)
    released = []
    pipe.submit_packed_rows(rows, n=4, guard=(mc, 0),
                            release_cb=lambda: released.append(1))
    assert released == [1]


def test_wire_reconstruction_and_harvest_dedup():
    """Contract 5 with a REAL verifier: mixed valid/tampered rows, n <
    batch (zero padding), tags inserted only after verify passes."""
    import jax
    from firedancer_tpu.disco.tiles import _jit_blob_fn

    fn = _jit_blob_fn(jax.jit(ed.verify_batch))
    pipe = VerifyPipeline(fn, buckets=[(8, ML)], tcache_depth=64,
                          max_inflight=0)
    rows = np.zeros((8, STRIDE), np.uint8)
    wires_pubs = [_signed_txn(bytes([i + 40]) * 32, 300 + i)
                  for i in range(5)]
    _stamp_rows(rows, [w for w, _ in wires_pubs],
                [p for _, p in wires_pubs])
    rows[1, ML + 5] ^= 1          # tamper row 1's signature
    passed = pipe.submit_packed_rows(rows, n=5)
    assert sorted(p for p, _ in passed) == sorted(
        w for i, (w, _) in enumerate(wires_pubs) if i != 1)
    assert pipe.metrics.verify_pass == 4
    assert pipe.metrics.verify_fail == 1
    # resubmit: tags are in the tcache now -> all pre-dedup'd out
    rows[1, ML + 5] ^= 1          # untamper
    before = pipe.metrics.dedup_drop
    passed2 = pipe.submit_packed_rows(rows, n=5)
    assert [p for p, _ in passed2] == [wires_pubs[1][0]]  # only the fixed row
    assert pipe.metrics.dedup_drop == before + 4


def test_bit_identity_rows_vs_legacy_pack():
    """Satellite 4: zero-repack submit_rows verdicts == legacy _pack_into
    verdicts, mixed valid/tampered batch, fixed seed, CPU."""
    from firedancer_tpu.models.verifier import (
        SigVerifier,
        VerifierConfig,
        make_example_batch,
        use_legacy_pack,
    )

    B, ml = 64, 96
    sv = SigVerifier(VerifierConfig(batch=B, msg_maxlen=ml))
    msgs, lens, sigs, pubs = (np.asarray(a) for a in make_example_batch(
        B, ml, valid=True, sign_pool=8, seed=7))
    sigs = sigs.copy()
    sigs[3, 0] ^= 0xFF            # tampered lanes
    sigs[11, 63] ^= 0x01

    eng = sv.make_ingest(ml=ml, nbuf=2, depth=1)
    eng.submit(msgs, lens, sigs, pubs)
    (ref,) = eng.drain()
    assert ref.any() and not ref.all()

    rows = np.zeros((B, ml + PACKED_ROW_EXTRA), np.uint8)
    rows[:, :ml] = msgs
    rows[:, ml:ml + 64] = sigs
    rows[:, ml + 64:ml + 96] = pubs
    rows[:, ml + 96:ml + 100] = (
        lens.astype(np.int32).view(np.uint8).reshape(B, 4))
    eng2 = sv.make_ingest(ml=ml, nbuf=2, depth=1)
    eng2.submit_rows(rows)
    (got,) = eng2.drain()
    np.testing.assert_array_equal(got, ref)

    # the knob that routes ingest through the legacy path
    import os
    old = os.environ.pop("FDTPU_INGEST_LEGACY_PACK", None)
    try:
        assert not use_legacy_pack()
        os.environ["FDTPU_INGEST_LEGACY_PACK"] = "1"
        assert use_legacy_pack()
    finally:
        if old is None:
            os.environ.pop("FDTPU_INGEST_LEGACY_PACK", None)
        else:
            os.environ["FDTPU_INGEST_LEGACY_PACK"] = old


def test_assign_affinity():
    spec = TopoSpec("afftest", (LinkSpec("l", 4, 64),), (
        TileSpec("a", "source", (), ("l",)),
        TileSpec("b", "sink", (), (), {"cpu_idx": 9}),
        TileSpec("c", "sink", (), ()),
    ))
    # explicit list wraps in topology order; explicit cfg pins win
    out = assign_affinity(spec, "3,5")
    assert [t.cfg.get("cpu_idx") for t in out.tiles] == [3, 9, 3]
    # "" / None = untouched (same spec object)
    assert assign_affinity(spec, "") is spec
    assert assign_affinity(spec, None) is spec
    auto = assign_affinity(spec, "auto")
    assert all(t.cfg.get("cpu_idx") is not None for t in auto.tiles)


@pytest.mark.slow
def test_packed_topology_smoke():
    """2-verify-tile packed-wire topology boots, moves packed frags
    end-to-end with zero torn drops, and both tiles take work (the
    round-robin burst splitter deals across them)."""
    from firedancer_tpu.app import config as app_config
    from firedancer_tpu.disco.run import TopoRun
    from firedancer_tpu.utils import aot

    # AOT-first boot: spawn-context children must never cold-compile
    # (minutes on a contended core vs ~1 s deserialize)
    aot_dir = "/tmp/fdtpu_aot_test"
    if aot.ensure_verify_packed(aot_dir, 64, ML) is None:
        pytest.skip("AOT unusable on this backend")

    cfg = app_config.load()
    cfg["topology"] = "verify-bench"
    cfg["layout"]["verify_tile_count"] = 2
    cfg["development"]["packed_wire"] = 1
    cfg["development"]["source_count"] = 2048
    cfg["tiles"]["verify"]["batch"] = 64
    cfg["tiles"]["verify"]["aot_dir"] = aot_dir
    cfg["tiles"]["verify"]["aot_require"] = 1
    spec = app_config.build_topology(cfg)
    with TopoRun(spec) as run:
        run.wait_ready(timeout=300)
        import time
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            got = sum(run.metrics(f"verify:{v}")["txn_in_cnt"]
                      for v in range(2))
            if got >= 2048:
                break
            time.sleep(0.2)
        m0 = run.metrics("verify:0")
        m1 = run.metrics("verify:1")
        assert m0["txn_in_cnt"] + m1["txn_in_cnt"] >= 2048
        assert m0["txn_in_cnt"] > 0 and m1["txn_in_cnt"] > 0
        assert m0["torn_drop_cnt"] == 0 and m1["torn_drop_cnt"] == 0


def test_torn_rows_excluded_from_txn_accounting(ring):
    """Round-11 satellite: torn rows land in their OWN counter
    (torn_txns), never in txns_in/dedup_drop — pass/fail rates derived
    from txns_in stay honest — and a clean frag afterwards counts
    normally."""
    ws, mc, dc = ring
    fn = _FakeBlobFn()
    pipe = VerifyPipeline(fn, buckets=[(4, ML)], tcache_depth=64,
                          max_inflight=0)
    rows = dc.rows(dc.chunk0, 4, STRIDE)
    wires_pubs = [_signed_txn(bytes([i + 60]) * 32, 400 + i)
                  for i in range(4)]
    _stamp_rows(rows, [w for w, _ in wires_pubs],
                [p for _, p in wires_pubs])
    for s in range(5):                   # depth-4 mcache: seq 0 lapped
        mc.publish(sig=s + 1, chunk=dc.chunk0, sz=4)
    pipe.submit_packed_rows(rows, n=4, guard=(mc, 0))
    assert pipe.metrics.torn_drop == 1
    assert pipe.metrics.torn_txns == 4
    assert pipe.metrics.txns_in == 0, \
        "torn rows must not count as ingested"
    assert pipe.metrics.dedup_drop == 0
    # the snapshot carries the new field for _sync_metrics
    assert dict(pipe.metrics.snapshot())["torn_txns"] == 4
    # clean frag: normal accounting, torn counters untouched
    seq = mc.seq_query()
    mc.publish(sig=9, chunk=dc.chunk0, sz=4)
    passed = pipe.submit_packed_rows(rows, n=4, guard=(mc, seq))
    assert len(passed) == 4
    assert pipe.metrics.txns_in == 4
    assert pipe.metrics.torn_txns == 4


class _DedupCtx:
    """Minimal tile ctx for DedupTile.on_burst_view: metrics counters,
    the in-link mcache, and a publish_burst recorder."""

    def __init__(self, mc, cfg):
        self.cfg = cfg
        self._mc = mc
        self.metrics = self
        self.counts = {}
        self.published = []

    def add(self, name, n=1):
        self.counts[name] = self.counts.get(name, 0) + n

    def in_mcache(self, iidx):
        return self._mc

    def publish_burst(self, buf, starts, lens, sigs):
        b = np.asarray(buf)
        self.published += [
            (bytes(b[int(s):int(s) + int(ln)]), int(sig))
            for s, ln, sig in zip(starts, lens, sigs)]


def _packed_verdict_frag(dc, chunk, wires):
    """Stamp one round-11 arena frag (u32 offs[k+1] | wires) into the
    dcache the way VerifyTile._publish_packed_verdicts does."""
    k = len(wires)
    offs = np.zeros(k + 1, np.uint32)
    np.cumsum([len(w) for w in wires], out=offs[1:])
    hdr = 4 * (k + 1)
    nb = hdr + int(offs[k])
    blk = dc.write_view(chunk, nb)
    blk[:hdr].view(np.uint32)[:] = offs
    blk[hdr:nb] = np.frombuffer(b"".join(wires), np.uint8)
    return nb


def test_packed_egress_dedup_consumer(ring):
    """Round-11 egress, consumer half: the DedupTile unpacks one arena
    frag into exactly the per-txn wires (ragged lengths), keys dedup on
    wire[1:9], drops a resubmitted frag whole as dups, and drops a torn
    frag before anything derived from it is published."""
    from firedancer_tpu.disco.tiles import DedupTile

    ws, mc, dc = ring
    rng = np.random.default_rng(5)
    wires = [b"\x01" + bytes(rng.integers(0, 256, 64, dtype=np.uint8))
             + bytes(rng.integers(0, 256, int(L), dtype=np.uint8))
             for L in (100, 7, 256, 0, 31)]
    _packed_verdict_frag(dc, dc.chunk0, wires)
    mc.publish(sig=1, chunk=dc.chunk0, sz=len(wires))
    ctx = _DedupCtx(mc, {"packed_egress": 1, "tcache_depth": 4096})
    dt = DedupTile()
    dt.init(ctx)
    assert dt.on_burst is None, \
        "packed egress must hide on_burst (rx-scratch sizing)"
    metas, _ = mc.consume_burst(0, 8)
    dt.on_burst_view(ctx, 0, metas, dc)
    want = [(w, int.from_bytes(w[1:9], "little")) for w in wires]
    assert ctx.published == want
    assert ctx.counts.get("uniq_cnt") == len(wires)
    assert ctx.counts.get("dup_drop_cnt") is None
    # same frag again: every tag already inserted -> all dup, no publish
    seq = mc.seq_query()
    mc.publish(sig=1, chunk=dc.chunk0, sz=len(wires))
    metas, _ = mc.consume_burst(seq, 8)
    dt.on_burst_view(ctx, 0, metas, dc)
    assert ctx.published == want
    assert ctx.counts.get("dup_drop_cnt") == len(wires)
    # torn: consume the meta, then lap the depth-4 mcache before the
    # consumer reads the payload -> dropped whole, nothing published
    seq = mc.seq_query()
    mc.publish(sig=1, chunk=dc.chunk0, sz=len(wires))
    metas, _ = mc.consume_burst(seq, 8)
    for s in range(4):
        mc.publish(sig=2 + s, chunk=dc.chunk0, sz=len(wires))
    before = len(ctx.published)
    dt.on_burst_view(ctx, 0, metas, dc)
    assert len(ctx.published) == before
    assert ctx.counts.get("torn_drop_cnt") == 1
