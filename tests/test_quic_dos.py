"""DoS front-door hardening (waltz/quic.py abuse bounds, the net tile's
pps bucket, and the quic tiles' packed-row publish mode).

Attack traffic is forged with disco.faultinject.WireFaultGen — AEAD-valid
Initials that pass the admission probe, malformed mutations that must die
in the parser, and never-FIN partial stream frames — against raw
endpoints (no sockets, no processes)."""

import os

import numpy as np
import pytest

from firedancer_tpu.disco.faultinject import WireFaultGen
from firedancer_tpu.waltz.aio import Aio, Pkt
from firedancer_tpu.waltz.quic import (CID_SZ, TXN_MTU, QuicConfig,
                                       QuicEndpoint)


def _server(**kw):
    sent = []
    sv = QuicEndpoint(
        QuicConfig(identity_seed=os.urandom(32), is_server=True, **kw),
        Aio(lambda p: sent.extend(p) or len(p)))
    return sv, sent


def _mem_pair(**server_kw):
    c2s, s2c = [], []
    cl = QuicEndpoint(QuicConfig(identity_seed=os.urandom(32)),
                      Aio(lambda p: c2s.extend(p) or len(p)))
    sv = QuicEndpoint(
        QuicConfig(identity_seed=os.urandom(32), is_server=True,
                   **server_kw),
        Aio(lambda p: s2c.extend(p) or len(p)))
    return cl, sv, c2s, s2c


def _handshake(cl, sv, c2s, s2c, now=0.0, iters=40):
    conn = cl.connect(("10.0.0.9", 9001))
    for _ in range(iters):
        now += 0.01
        if c2s:
            pkts, c2s[:] = list(c2s), []
            sv.rx(pkts, now)
        if s2c:
            pkts, s2c[:] = list(s2c), []
            cl.rx(pkts, now)
        if conn.handshake_done:
            break
    assert conn.handshake_done
    return conn, now


def _pump(cl, sv, c2s, s2c, now, steps=20):
    for _ in range(steps):
        now += 0.01
        if c2s:
            pkts, c2s[:] = list(c2s), []
            sv.rx(pkts, now)
        if s2c:
            pkts, s2c[:] = list(s2c), []
            cl.rx(pkts, now)
        cl.service(now)
        sv.service(now)
    return now


# ------------------------------------------------------- admission bounds


def test_per_peer_conn_cap_rejects_flood():
    sv, _ = _server(max_conns=64, max_conns_per_peer=4)
    g = WireFaultGen(3)
    addr = ("9.9.9.9", 1111)
    for d in g.conn_flood(12):
        sv.rx([Pkt(d, addr)], now=1.0)
    assert len(sv.conns) == 4
    assert sv.metrics["conn_reject"] == 8
    assert sv._peer_conns[("9.9.9.9", 1111)[0]] == 4
    # a different peer is still welcome
    sv.rx([Pkt(g.forged_initial()[0], ("8.8.8.8", 2))], now=1.0)
    assert len(sv.conns) == 5


def test_half_open_accounting_decrements_on_drop():
    sv, _ = _server(max_conns=64, idle_timeout=1.0)
    g = WireFaultGen(4)
    for d in g.conn_flood(5):
        sv.rx([Pkt(d, ("7.7.7.7", 1))], now=1.0)
    assert sv.half_open == 5
    sv.service(3.0)                          # idle reaper drops them all
    assert sv.half_open == 0
    assert len(sv.conns) == 0
    assert sv._peer_conns == {}              # peer table can't leak


def test_global_cap_lru_evicts_idle_conn():
    sv, _ = _server(max_conns=3, lru_evict_idle=1.0)
    g = WireFaultGen(5)
    for i, d in enumerate(g.conn_flood(3)):
        sv.rx([Pkt(d, (f"1.1.1.{i}", 1))], now=1.0 + i * 0.1)
    assert len(sv.conns) == 3
    # table full and everyone FRESH (< lru_evict_idle): admission refused —
    # a flood can't churn conns that are actively handshaking
    sv.rx([Pkt(g.forged_initial()[0], ("3.3.3.3", 1))], now=1.5)
    assert len(sv.conns) == 3
    assert sv.metrics["conn_reject"] == 1
    assert sv.metrics["conn_evict"] == 0
    # later, with everyone idle >= lru_evict_idle: the oldest is evicted
    sv.rx([Pkt(g.forged_initial()[0], ("2.2.2.2", 1))], now=5.0)
    assert len(sv.conns) == 3
    assert sv.metrics["conn_evict"] == 1
    assert sv.metrics["conn_reject"] == 1
    assert "2.2.2.2" in sv._peer_conns


def test_retry_threshold_and_token_redeem():
    sv, sent = _server(max_conns=64, retry_half_open_threshold=2)
    g = WireFaultGen(6)
    addr = ("6.6.6.6", 42)
    for d in g.conn_flood(5):
        sv.rx([Pkt(d, addr)], now=1.0)
    # 2 half-opens admitted, then stateless Retries only
    assert len(sv.conns) == 2
    assert sv.metrics["retry_tx"] == 3
    retries = [p.payload for p in sent if (p.payload[0] & 0xF0) == 0xF0]
    assert len(retries) == 3
    r0_scid, r0_tok = WireFaultGen.redeem_retry(retries[0])
    r1_scid, r1_tok = WireFaultGen.redeem_retry(retries[1])
    assert len(r0_scid) == CID_SZ and r0_tok
    # a token presented from a DIFFERENT address is refused silently
    # (the address is AAD in the token AEAD: it fails to open)
    sv.rx([Pkt(g.forged_initial(dcid=r1_scid, token=r1_tok)[0],
               ("66.66.66.66", 42))], now=1.4)
    assert sv.metrics["retry_token_reject"] == 1
    assert len(sv.conns) == 2
    # redeemed from the SAME address: admitted, path validated
    sv.rx([Pkt(g.forged_initial(dcid=r0_scid, token=r0_tok)[0], addr)],
          now=1.5)
    assert sv.metrics["retry_token_accept"] == 1
    assert len(sv.conns) == 3
    conn = sv._initial_conns[r0_scid]
    assert conn.addr_validated


def test_malformed_storm_no_conn_state_no_crash():
    sv, _ = _server(max_conns=64)
    g = WireFaultGen(7)
    for d in g.malformed(160):
        sv.rx([Pkt(d, ("5.5.5.5", 5))], now=1.0)
    assert sv.conns == {}
    m = sv.metrics
    assert m["pkt_malformed"] + m["pkt_undecryptable"] > 0
    assert m["conn_created"] == 0


def test_initial_key_cache_lru_bounds_random_dcid_flood():
    """Every distinct client dcid derives an Initial key schedule at the
    admission probe; the per-endpoint LRU must bound that memory under a
    random-dcid flood and count the evictions."""
    sv, _ = _server(max_conns=128, initial_key_cache=16)
    g = WireFaultGen(11)
    for i in range(64):  # 64 distinct dcids from distinct source IPs
        d = g.forged_initial()[0]
        sv.rx([Pkt(d, (f"9.9.{i}.1", 9))], now=1.0)
    assert len(sv._initial_keys) <= 16
    assert sv.metrics["initial_keys_evict"] >= 64 - 16
    # cache hit path: the SAME dcid probes and admits on one derivation
    sv2, _ = _server(initial_key_cache=16)
    d, dcid, _ = g.forged_initial()
    sv2.rx([Pkt(d, ("8.8.8.8", 8))], now=1.0)
    assert sv2.metrics["conn_created"] == 1
    assert dcid in sv2._initial_keys
    conn = sv2._initial_conns[dcid]
    # the admitted conn holds the CACHED schedule object, not a re-derive
    assert conn.rx_keys[0] is sv2._initial_keys[dcid][0]


def test_initial_key_cache_disabled_derives_direct():
    sv, _ = _server(initial_key_cache=0)
    g = WireFaultGen(12)
    sv.rx([Pkt(g.forged_initial()[0], ("7.7.7.8", 7))], now=1.0)
    assert sv.metrics["conn_created"] == 1
    assert len(sv._initial_keys) == 0


# --------------------------------------------------- stream-level budgets


def test_conn_reasm_budget_evicts_oldest_partials():
    cl, sv, c2s, s2c = _mem_pair(conn_reasm_budget=1000)
    conn, now = _handshake(cl, sv, c2s, s2c)
    g = WireFaultGen(8)
    # 4 x 400 B never-FIN partials on distinct streams > 1000 B budget
    for i in range(4):
        cl.ep_frame = WireFaultGen.partial_stream_frame(
            4_002 + 4 * i, 0, g.oversize_stream_payload(400))
        cl._emit(conn, 2, cl.ep_frame, True, None)
    cl._flush(conn)
    cl._send_pending()
    now = _pump(cl, sv, c2s, s2c, now)
    sconn = next(iter(sv.conns.values()))
    assert sv.metrics["reasm_evict"] >= 1
    assert sconn.reasm_bytes <= 1000
    # whole txns still deliver on the same conn after the shed
    got = []
    sv.on_stream = lambda c, sid, data: got.append(data)
    assert conn.send_txn(b"post-shed" + bytes(64)) is not None
    cl.service(now)
    now = _pump(cl, sv, c2s, s2c, now)
    assert got and got[0][:9] == b"post-shed"


def test_conn_txn_rate_bucket_sheds_and_refills():
    cl, sv, c2s, s2c = _mem_pair(conn_txn_rate=10.0, conn_txn_burst=4)
    conn, now = _handshake(cl, sv, c2s, s2c)
    got = []
    sv.on_stream = lambda c, sid, data: got.append(data)
    for t in range(12):
        assert conn.send_txn(b"txn-%02d" % t) is not None
    cl.service(now)
    now = _pump(cl, sv, c2s, s2c, now, steps=4)  # ~0.04 s: no real refill
    assert len(got) <= 5                     # burst 4 (+<=1 refill token)
    assert sv.metrics["rate_drop"] >= 7
    # a second of refill at 10/s admits more
    n0 = len(got)
    now = _pump(cl, sv, c2s, s2c, now + 1.0, steps=2)
    for t in range(4):
        assert conn.send_txn(b"more-%02d" % t) is not None
    cl.service(now)
    now = _pump(cl, sv, c2s, s2c, now, steps=4)
    assert len(got) > n0


def test_oversize_stream_capped_by_stream_window():
    cl, sv, c2s, s2c = _mem_pair()
    conn, now = _handshake(cl, sv, c2s, s2c)
    sconn = next(iter(sv.conns.values()))
    big = WireFaultGen(9).oversize_stream_payload(2 * TXN_MTU)
    frame = WireFaultGen.partial_stream_frame(4002, sv.rx_max_stream_data,
                                              big[:100])
    cl._emit(conn, 2, frame, True, None)
    cl._flush(conn)
    cl._send_pending()
    now = _pump(cl, sv, c2s, s2c, now, steps=5)
    # data past the advertised stream window is discarded, not buffered
    assert 4002 not in sconn.recv_streams
    assert sconn.reasm_bytes == 0


# ---------------------------------------------------- service deadlines


def test_next_timeout_deadline_driven_service():
    sv, _ = _server(idle_timeout=10.0)
    assert sv.next_timeout() == 0.0          # first service runs at once
    sv.service(100.0)
    assert sv.next_timeout() == pytest.approx(110.0)  # empty: idle horizon
    g = WireFaultGen(10)
    sv.rx([Pkt(g.conn_flood(1)[0], ("4.4.4.4", 4))], now=101.0)
    sv.service(102.0)
    # conn idle deadline (last_rx 101 + 10) bounds the recomputed horizon
    assert sv.next_timeout() <= 111.0 + 1e-9
    # an in-flight ack-eliciting send pulls a CLIENT's deadline to ~now+pto
    c2s = []
    cl = QuicEndpoint(QuicConfig(identity_seed=os.urandom(32)),
                      Aio(lambda p: c2s.extend(p) or len(p)))
    cl.service(50.0)
    assert cl.next_timeout() == pytest.approx(50.0 + cl.idle_timeout)
    cl.connect(("10.0.0.9", 9001), now=50.0)
    assert c2s                               # Initial flight is in flight
    assert cl.next_timeout() <= 50.0 + cl.cfg.pto + 1e-9


def test_service_at_deadline_reaps_idle():
    cl, sv, c2s, s2c = _mem_pair(idle_timeout=1.0)
    conn, now = _handshake(cl, sv, c2s, s2c)
    assert len(sv.conns) == 1
    sv.service(now)
    # drive service() PURELY off next_timeout() (the tile's after_credit
    # loop): the deadlines must converge on the idle reap in bounded time
    t = now
    for _ in range(64):
        t = max(sv.next_timeout(), t) + 1e-3
        sv.service(t)
        if not sv.conns:
            break
    assert len(sv.conns) == 0
    assert t <= now + 5.0
    assert sv.metrics["conn_closed"] == 1


# ------------------------------------------------- packed publish parity


def test_wire_row_matches_txn_parse():
    from firedancer_tpu.ballet import txn as txn_lib
    from firedancer_tpu.disco.tiles import _wire_row
    from firedancer_tpu.ops import ed25519 as ed

    rng = np.random.default_rng(11)
    seed = rng.bytes(32)
    pub, _, _ = ed.keypair_from_seed(seed)
    msg = txn_lib.build_unsigned([pub], rng.bytes(32),
                                 [(1, bytes([0]), b"payload8")],
                                 extra_accounts=[rng.bytes(32)])
    wire = txn_lib.assemble([ed.sign(seed, msg)], msg)
    t = txn_lib.parse(wire)
    row = _wire_row(wire, 256)
    assert row is not None
    m, sig, p = row
    assert m == t.message(wire)
    assert sig == t.signatures(wire)[0]
    assert p == t.signer_pubkeys(wire)[0] == pub
    # the drop set == the legacy parse-fail set
    assert _wire_row(wire[:10], 256) is None          # truncated: parse fail
    assert _wire_row(wire, len(m) - 1) is None        # too long for bucket
    assert _wire_row(b"", 256) is None


class _FakeCtx:
    """Just enough TileCtx for _PackedWirePublisher: one reservation."""

    def __init__(self, rows, stride):
        self.buf = np.zeros(rows * stride, np.uint8)
        self.commits = []

    def out_reserve(self, nbytes):
        assert nbytes == len(self.buf)
        return 7, self.buf

    def out_commit(self, chunk, nbytes, sig=0, sz=None):
        self.commits.append((chunk, nbytes, sig, sz, self.buf.copy()))


def test_packed_wire_publisher_row_layout():
    from firedancer_tpu.ballet import txn as txn_lib
    from firedancer_tpu.disco.tiles import _PackedWirePublisher
    from firedancer_tpu.ops import ed25519 as ed
    from firedancer_tpu.tango.ring import PACKED_ROW_EXTRA, packed_row_ml

    rows, ml = 4, packed_row_ml(256)
    stride = ml + PACKED_ROW_EXTRA
    ctx = _FakeCtx(rows, stride)
    pub_ = _PackedWirePublisher(ctx, rows=rows, ml=ml)

    rng = np.random.default_rng(12)
    wires = []
    for i in range(rows):
        seed = rng.bytes(32)
        pk, _, _ = ed.keypair_from_seed(seed)
        msg = txn_lib.build_unsigned(
            [pk], rng.bytes(32), [(1, bytes([0]), i.to_bytes(8, "little"))],
            extra_accounts=[rng.bytes(32)])
        wires.append(txn_lib.assemble([ed.sign(seed, msg)], msg))
    for w in wires:
        assert pub_.add(w)
    # auto-flushed at rows
    assert len(ctx.commits) == 1
    chunk, nbytes, sig, sz, blk = ctx.commits[0]
    assert (chunk, nbytes, sz) == (7, rows * stride, rows)
    blk = blk.reshape(rows, stride)
    for i, w in enumerate(wires):
        t = txn_lib.parse(w)
        m = t.message(w)
        assert bytes(blk[i, :len(m)]) == m
        assert bytes(blk[i, ml:ml + 64]) == t.signatures(w)[0]
        assert bytes(blk[i, ml + 64:ml + 96]) == t.signer_pubkeys(w)[0]
        assert int.from_bytes(bytes(blk[i, ml + 96:ml + 100]),
                              "little") == len(m)
    # sig tag = first row's sig64 with the latency bit masked off
    from firedancer_tpu.disco.tiles import LAT_PRIO_BIT
    w0 = wires[0]
    want = (int.from_bytes(txn_lib.parse(w0).signatures(w0)[0][:8],
                           "little") & (LAT_PRIO_BIT - 1))
    assert sig == want
    # garbage is refused without opening a reservation
    assert not pub_.add(b"\x00")
    assert len(ctx.commits) == 1


# ------------------------------------------------------- net tile knobs


class _NetMetrics:
    def __init__(self):
        self.vals = {}

    def add(self, k, v=1):
        self.vals[k] = self.vals.get(k, 0) + v

    def set(self, k, v):
        self.vals[k] = v


class _NetCtx:
    def __init__(self):
        self.metrics = _NetMetrics()


def test_net_tile_pps_bucket_and_lru_map():
    from firedancer_tpu.disco.tiles import NetTile

    nt = NetTile.__new__(NetTile)
    nt._pps, nt._pps_burst = 10.0, 2.0
    from collections import OrderedDict
    nt._src_buckets = OrderedDict()
    nt._last_shed = -1e9
    ctx = _NetCtx()
    # burst of 2 admitted, then shed until refill
    assert nt._admit(ctx, "1.2.3.4", 0.0)
    assert nt._admit(ctx, "1.2.3.4", 0.0)
    assert not nt._admit(ctx, "1.2.3.4", 0.0)
    assert ctx.metrics.vals["rate_drop_cnt"] == 1
    assert nt._admit(ctx, "1.2.3.4", 0.2)    # +2 tokens after 0.2 s
    # other sources are independent
    assert nt._admit(ctx, "4.3.2.1", 0.2)
    # the source map is LRU-bounded
    nt._SRC_MAP_CAP = 4
    for i in range(8):
        nt._admit(ctx, f"10.0.0.{i}", 0.3)
    assert len(nt._src_buckets) <= 4


def test_net_tile_fini_idempotent_and_ordered():
    from firedancer_tpu.disco.tiles import NetTile

    closed = []

    class _S:
        def __init__(self, n):
            self.n = n

        def close(self):
            closed.append(self.n)

    nt = NetTile.__new__(NetTile)
    nt._xdp_fds = ()
    nt.socks = [(_S("a"), 0), (_S("b"), 1)]
    nt.fini(None)
    assert closed == ["a", "b"]
    assert nt.socks == [] and nt._xdp_fds == ()
    nt.fini(None)                            # re-entrant: a no-op
    nt.fini(None)
    assert closed == ["a", "b"]


# --------------------------------------------------------- forged packets


def test_forged_initial_is_aead_valid_and_deterministic():
    g1, g2 = WireFaultGen(77), WireFaultGen(77)
    d1 = [g1.forged_initial()[0] for _ in range(3)]
    d2 = [g2.forged_initial()[0] for _ in range(3)]
    assert d1 == d2                          # seeded: replays identically
    sv, _ = _server(max_conns=64)
    sv.rx([Pkt(d1[0], ("1.2.3.4", 9))], now=1.0)
    assert sv.metrics["conn_created"] == 1
    assert sv.metrics["pkt_undecryptable"] == 0
