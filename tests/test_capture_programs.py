"""Capture (solcap analogue) diffing, ed25519 precompile, config program
(ref behaviors: src/flamenco/capture/, fd_precompiles.c,
fd_config_program.c)."""

import pytest

from firedancer_tpu.ballet import txn as txn_lib
from firedancer_tpu.flamenco import capture, config_program, precompiles
from firedancer_tpu.flamenco import genesis as gen_mod
from firedancer_tpu.flamenco import system_program as sysprog
from firedancer_tpu.flamenco.runtime import Runtime
from firedancer_tpu.flamenco.types import (Account, CONFIG_PROGRAM_ID,
                                           ED25519_PRECOMPILE_ID,
                                           SECP256K1_PRECOMPILE_ID,
                                           SYSTEM_PROGRAM_ID)
from firedancer_tpu.ops import ed25519 as ed


def _keypair(i):
    seed = i.to_bytes(32, "little")
    return seed, ed.keypair_from_seed(seed)[0]


def _chain(extra_accounts=()):
    faucet_seed, faucet_pk = _keypair(1)
    g = gen_mod.create(faucet_pk, creation_time=1_700_000_000,
                       slots_per_epoch=32)
    for pk, acct in extra_accounts:
        g.accounts[pk] = acct
    return Runtime(g), (faucet_seed, faucet_pk)


def _exec(rt, bank, signers, ix, accounts, ro_cnt=1):
    msg = txn_lib.build_unsigned(
        [p for _, p in signers], rt.root_hash, ix,
        extra_accounts=accounts, readonly_unsigned_cnt=ro_cnt)
    payload = txn_lib.assemble([ed.sign(s, msg) for s, _ in signers], msg)
    return bank.execute_txn(payload)


def test_capture_roundtrip_and_diff(tmp_path):
    rt, faucet = _chain()
    _, dest = _keypair(5)
    pa, pb = str(tmp_path / "a.jsonl.gz"), str(tmp_path / "b.jsonl.gz")

    def run_chain(path, amount):
        r, f = _chain()
        b = r.new_bank(1)
        res = _exec(r, b, [f], [(2, bytes([0, 1]),
                                 sysprog.ix_transfer(amount))],
                    [dest, SYSTEM_PROGRAM_ID])
        assert res.ok
        b.freeze(b"\x10" * 32)
        with capture.CaptureWriter(path) as w:
            w.write_slot(capture.record_bank(
                b, [capture.TxnRecord("aa", res.ok, res.err, res.fee)]))

    run_chain(pa, 1000)
    run_chain(pb, 1000)
    assert capture.diff(pa, pb) is None  # identical replays

    run_chain(pb, 2000)  # overwrite with a divergent chain
    d = capture.diff(pa, pb)
    assert d is not None and d["slot"] == 1 and d["field"] == "delta_hash"


def test_ed25519_precompile():
    rt, faucet = _chain()
    b = rt.new_bank(1)
    sseed, spub = _keypair(7)
    m = b"attestation payload"
    sig = ed.sign(sseed, m)
    data = precompiles.build_ed25519_ix_data([(sig, spub, m)])
    res = _exec(rt, b, [faucet], [(1, b"", data)], [ED25519_PRECOMPILE_ID],
                ro_cnt=1)
    assert res.ok, res.err

    bad = precompiles.build_ed25519_ix_data(
        [(sig[:-1] + b"\x00", spub, m)])
    res = _exec(rt, b, [faucet], [(1, b"", bad)], [ED25519_PRECOMPILE_ID])
    assert not res.ok and "invalid" in res.err


def test_secp256k1_precompile():
    """The in-tree secp256k1 backend (ballet/secp256k1, added after the
    original gate) verifies eth-style recoverable sigs in the precompile."""
    from firedancer_tpu.ballet import secp256k1 as secp
    from firedancer_tpu.ballet.keccak256 import keccak256

    rt, faucet = _chain()
    b = rt.new_bank(1)
    sec = int.from_bytes(b"\x11" * 32, "big") % secp.N or 1
    pub = secp._mul(sec, (secp._GX, secp._GY))
    msg = b"eth attestation"
    r, s, recid = secp.sign(keccak256(msg), sec)
    sig = r.to_bytes(32, "big") + s.to_bytes(32, "big")
    addr = secp.eth_address(pub)
    data = precompiles.build_secp256k1_ix_data([(sig, recid, addr, msg)])
    res = _exec(rt, b, [faucet], [(1, b"", data)],
                [SECP256K1_PRECOMPILE_ID], ro_cnt=1)
    assert res.ok, res.err

    bad_addr = bytes(20)
    data = precompiles.build_secp256k1_ix_data([(sig, recid, bad_addr, msg)])
    res = _exec(rt, b, [faucet], [(1, b"", data)],
                [SECP256K1_PRECOMPILE_ID])
    assert not res.ok and "invalid" in res.err


def test_config_program():
    auth_seed, auth_pk = _keypair(8)
    cfg_seed, cfg_pk = _keypair(9)
    rt, faucet = _chain([(cfg_pk, Account(lamports=1_000_000,
                                          owner=CONFIG_PROGRAM_ID)),
                         (auth_pk, Account(lamports=1_000_000))])
    b = rt.new_bank(1)
    payload = b"validator-info: fdtpu"
    ix = config_program.ix_store([(auth_pk, True)], payload)
    # initial store: config account signs
    res = _exec(rt, b, [faucet, (cfg_seed, cfg_pk), (auth_seed, auth_pk)],
                [(3, bytes([1]), ix)], [CONFIG_PROGRAM_ID], ro_cnt=1)
    assert res.ok, res.err
    keys, got = config_program.parse_state(rt.accdb.load(b.xid, cfg_pk).data)
    assert got == payload and keys == [(auth_pk, True)]

    # update WITHOUT the required signer fails
    ix2 = config_program.ix_store([(auth_pk, True)], b"evil")
    res = _exec(rt, b, [faucet, (cfg_seed, cfg_pk)], [(2, bytes([1]), ix2)],
                [CONFIG_PROGRAM_ID], ro_cnt=1)
    assert not res.ok and "signer" in res.err

    # update with the signer succeeds
    res = _exec(rt, b, [faucet, (auth_seed, auth_pk)],
                [(3, bytes([2]), ix2)], [cfg_pk, CONFIG_PROGRAM_ID],
                ro_cnt=1)
    assert res.ok, res.err
