"""Curve ops vs the python golden model (extended-coordinate big-int math)."""

import secrets

import jax.numpy as jnp
import numpy as np

import tests.golden.ed25519_golden as g
from firedancer_tpu.ops import curve25519 as cv
from firedancer_tpu.ops import f25519 as fe

P = fe.P
BATCH = 8


def rand_points(n):
    """n random curve points (as golden-model tuples) via [r]B."""
    return [g.pt_mul(secrets.randbits(256), g.BASE) for _ in range(n)]


def pack_points(pts):
    """golden tuples -> batched Point"""
    arrs = {f: [] for f in "XYZT"}
    for X, Y, Z, T in pts:
        arrs["X"].append(fe._to_limbs_py(X))
        arrs["Y"].append(fe._to_limbs_py(Y))
        arrs["Z"].append(fe._to_limbs_py(Z))
        arrs["T"].append(fe._to_limbs_py(T))
    return cv.Point(*(jnp.asarray(np.stack(arrs[f], axis=1)) for f in "XYZT"))


def unpack_points(p: cv.Point):
    n = p.X.shape[1]
    out = []
    for i in range(n):
        out.append(tuple(fe.to_int(np.asarray(getattr(p, f)[:, i])) for f in "XYZT"))
    return out


def assert_points_equal(dev_pts, gold_pts):
    for i, (d, q) in enumerate(zip(dev_pts, gold_pts)):
        assert g.pt_eq(d, q), f"point {i} mismatch"


def test_base_point_matches_golden():
    assert (cv.BASE_X, cv.BASE_Y) == (g.BASE[0], g.BASE[1])


def test_add():
    ps, qs = rand_points(BATCH), rand_points(BATCH)
    got = unpack_points(cv.add(pack_points(ps), pack_points(qs)))
    assert_points_equal(got, [g.pt_add(p, q) for p, q in zip(ps, qs)])


def test_add_identity():
    ps = rand_points(BATCH)
    got = unpack_points(cv.add(pack_points(ps), cv.identity((BATCH,))))
    assert_points_equal(got, ps)


def test_double():
    ps = rand_points(BATCH)
    got = unpack_points(cv.double(pack_points(ps)))
    assert_points_equal(got, [g.pt_double(p) for p in ps])


def test_double_identity():
    got = unpack_points(cv.double(cv.identity((2,))))
    assert_points_equal(got, [g.IDENT, g.IDENT])


def test_neg():
    ps = rand_points(BATCH)
    got = unpack_points(cv.neg(pack_points(ps)))
    assert_points_equal(got, [g.pt_neg(p) for p in ps])


def test_eq_and_eq_z1():
    ps = rand_points(4)
    qs = [ps[0], g.pt_double(ps[1]), ps[2], ps[3]]
    m = cv.eq(pack_points(ps), pack_points(qs))
    assert list(np.asarray(m)) == [True, False, True, True]
    # eq_z1 with affine rhs (all golden points from pt_mul have Z=1? no — use
    # compressed/decompressed to force Z=1)
    affine = [g.pt_decompress(g.pt_compress(p)) for p in ps]
    m2 = cv.eq_z1(pack_points(qs), pack_points(affine))
    assert list(np.asarray(m2)) == [True, False, True, True]


def test_decompress():
    ps = rand_points(BATCH)
    raw = [g.pt_compress(p) for p in ps]
    arr = jnp.asarray(np.frombuffer(b"".join(raw), dtype=np.uint8).reshape(BATCH, 32))
    ok, pts = cv.decompress(arr)
    assert all(np.asarray(ok))
    assert_points_equal(unpack_points(pts), ps)


def test_decompress_invalid():
    # y with no valid x: find one by brute force over small ints
    bad = None
    for y in range(2, 200):
        u, v = (y * y - 1) % P, (g.D * y * y + 1) % P
        if not g.sqrt_ratio(u, v)[0]:
            bad = y
            break
    assert bad is not None
    raw = bad.to_bytes(32, "little")
    arr = jnp.asarray(np.frombuffer(raw, dtype=np.uint8).reshape(1, 32))
    ok, _ = cv.decompress(arr)
    assert not bool(np.asarray(ok)[0])


def test_decompress_noncanonical_accepted():
    # y = p+1 encodes 1 non-canonically; must decompress like y=1 (dalek 2.x)
    raw = (P + 1).to_bytes(32, "little")
    arr = jnp.asarray(np.frombuffer(raw, dtype=np.uint8).reshape(1, 32))
    ok, pts = cv.decompress(arr)
    assert bool(np.asarray(ok)[0])
    assert fe.to_int(np.asarray(pts.Y[:, 0])) == 1


def test_compress_roundtrip():
    ps = rand_points(BATCH)
    dev = pack_points(ps)
    raw = np.asarray(cv.compress(dev))
    for i, p in enumerate(ps):
        assert raw[i].tobytes() == g.pt_compress(p)


def test_small_order_detection():
    # all 8 low-order encodings from the reference table (fd_curve25519.h:84-92)
    enc = [
        "0100000000000000000000000000000000000000000000000000000000000000",
        "ecffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f",
        "0000000000000000000000000000000000000000000000000000000000000000",
        "0000000000000000000000000000000000000000000000000000000000000080",
        "26e8958fc2b227b045c3f489f2ef98f0d5dfac05d3c63339b13802886d53fc05",
        "26e8958fc2b227b045c3f489f2ef98f0d5dfac05d3c63339b13802886d53fc85",
        "c7176a703d4dd84fba3c0b760d10670f2a2053fa2c39ccc64ec7fd7792ac037a",
        "c7176a703d4dd84fba3c0b760d10670f2a2053fa2c39ccc64ec7fd7792ac03fa",
    ]
    raw = b"".join(bytes.fromhex(e) for e in enc)
    arr = jnp.asarray(np.frombuffer(raw, dtype=np.uint8).reshape(8, 32))
    ok, pts = cv.decompress(arr)
    assert all(np.asarray(ok))
    assert list(np.asarray(cv.is_small_order_affine(pts))) == [True] * 8
    # and regular points are NOT small order
    ps = rand_points(4)
    assert list(np.asarray(cv.is_small_order_affine(pack_points(ps)))) == [False] * 4


def windows_of(s: int):
    b = jnp.asarray(
        np.frombuffer(s.to_bytes(32, "little"), dtype=np.uint8).reshape(1, 32)
    )
    return cv.scalar_windows(b)


def test_scalar_mul_base():
    for s in [0, 1, 2, g.L - 1, secrets.randbits(252)]:
        w = windows_of(s)
        got = unpack_points(cv.scalar_mul_base(w))[0]
        assert g.pt_eq(got, g.pt_mul(s, g.BASE)), s


def test_scalar_mul_var():
    p = rand_points(1)[0]
    for s in [0, 1, 7, secrets.randbits(252)]:
        w = windows_of(s)
        got = unpack_points(cv.scalar_mul(w, pack_points([p])))[0]
        assert g.pt_eq(got, g.pt_mul(s, p)), s


def test_double_scalar_mul_base():
    batch = 4
    ss = [secrets.randbits(252) for _ in range(batch)]
    ks = [secrets.randbits(252) for _ in range(batch)]
    pts = rand_points(batch)
    sb = jnp.asarray(
        np.frombuffer(
            b"".join(s.to_bytes(32, "little") for s in ss), dtype=np.uint8
        ).reshape(batch, 32)
    )
    kb = jnp.asarray(
        np.frombuffer(
            b"".join(k.to_bytes(32, "little") for k in ks), dtype=np.uint8
        ).reshape(batch, 32)
    )
    got = unpack_points(
        cv.double_scalar_mul_base(cv.scalar_windows(sb), cv.scalar_windows(kb), pack_points(pts))
    )
    want = [
        g.pt_add(g.pt_mul(s, g.BASE), g.pt_mul(k, p)) for s, k, p in zip(ss, ks, pts)
    ]
    assert_points_equal(got, want)
