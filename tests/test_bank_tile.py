"""Mini-validator test: the full leader pipeline with a REAL executing bank
— source (funded transfers) -> verify -> dedup -> pack -> bank, where the
bank tile runs the flamenco Runtime over funk forks and freezes slots
(the fddev single-node-cluster analogue, SURVEY.md §3.3)."""

import os
import time

from firedancer_tpu.disco.run import TopoRun
from firedancer_tpu.disco.topo import TopoBuilder
from firedancer_tpu.flamenco import genesis as gen_mod
from firedancer_tpu.ops import ed25519 as ed


def _wait(pred, timeout_s, what=""):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise TimeoutError(f"timed out waiting for {what}")


def test_executing_bank_topology(tmp_path):
    n = 32
    seeds = [i.to_bytes(32, "little") for i in range(101, 105)]
    pubs = [ed.keypair_from_seed(s)[0] for s in seeds]
    faucet_pk = ed.keypair_from_seed((99).to_bytes(32, "little"))[0]
    g = gen_mod.create(faucet_pk, creation_time=1_700_000_000,
                       slots_per_epoch=32)
    from firedancer_tpu.flamenco.types import Account
    for pk in pubs:
        g.accounts[pk] = Account(lamports=1_000_000_000)
    gpath = str(tmp_path / "genesis.bin")
    g.write(gpath)
    bh = g.genesis_hash()

    spec = (
        TopoBuilder(f"bank{os.getpid()}", wksp_mb=16)
        .link("src_verify", depth=128, mtu=1280)
        .link("verify_dedup", depth=128, mtu=1280)
        .link("dedup_pack", depth=128, mtu=1280)
        .link("pack_bank", depth=128, mtu=1280)
        .tile("source", "source", outs=["src_verify"], count=n,
              executable=True, seeds=[s.hex() for s in seeds],
              blockhash=bh.hex())
        .tile("verify", "verify", ins=["src_verify"], outs=["verify_dedup"],
              batch=16, msg_maxlen=256, flush_age_ns=50_000_000)
        .tile("dedup", "dedup", ins=["verify_dedup"], outs=["dedup_pack"])
        .tile("pack", "pack", ins=["dedup_pack"], outs=["pack_bank"])
        .tile("bank", "bank", ins=["pack_bank"], genesis_path=gpath,
              slot_txn_max=8)
        .build()
    )
    with TopoRun(spec) as run:
        run.wait_ready(timeout=420)
        _wait(lambda: run.metrics("bank")["txn_exec_cnt"]
              + run.metrics("bank")["txn_fail_cnt"] == n, 180,
              f"{n} txns executed")
        m = run.metrics("bank")
        assert m["txn_exec_cnt"] == n, m
        assert m["txn_fail_cnt"] == 0
        assert m["slot_cnt"] >= n // 8 - 1  # slots rolled at slot_txn_max=8
        assert run.poll() is None


def test_blockhash_feedback_survives_eviction(tmp_path):
    """VERDICT r2 weak #5: no genesis pin — the bank->source blockhash
    feedback link carries real recency.  Deterministic design: 42 txns
    across 6 txn-driven rolls (slot_txn_max=7, max_age=6, time-rolls
    disabled) exactly fill the validity window, evicting genesis on the
    final roll; post-drain RPC probes then prove live semantics — a
    genesis-signed txn is REJECTED (blockhash not found) while a txn
    signed against the current RPC blockhash executes."""
    from firedancer_tpu.ballet import txn as txn_lib
    from firedancer_tpu.flamenco.rpc import RpcClient
    from firedancer_tpu.flamenco.system_program import ix_transfer
    from firedancer_tpu.flamenco.types import SYSTEM_PROGRAM_ID, Account

    n = 42
    seeds = [i.to_bytes(32, "little") for i in range(111, 115)]
    pubs = [ed.keypair_from_seed(s)[0] for s in seeds]
    faucet_pk = ed.keypair_from_seed((99).to_bytes(32, "little"))[0]
    payer_seed = (7).to_bytes(32, "little")
    payer_pk = ed.keypair_from_seed(payer_seed)[0]
    g = gen_mod.create(faucet_pk, creation_time=1_700_000_000,
                       slots_per_epoch=32)
    for pk in pubs:
        g.accounts[pk] = Account(lamports=1_000_000_000)
    g.accounts[payer_pk] = Account(lamports=1_000_000_000)
    gpath = str(tmp_path / "genesis.bin")
    g.write(gpath)
    bh_genesis = g.genesis_hash()

    spec = (
        TopoBuilder(f"bankfb{os.getpid()}", wksp_mb=16)
        .link("src_verify", depth=128, mtu=1280)
        .link("verify_dedup", depth=128, mtu=1280)
        .link("dedup_pack", depth=128, mtu=1280)
        .link("pack_bank", depth=128, mtu=1280)
        .link("bank_blockhash", depth=16, mtu=64)
        .tile("source", "source", ins=["bank_blockhash"],
              outs=["src_verify"], count=n,
              executable=True, seeds=[s.hex() for s in seeds],
              blockhash=bh_genesis.hex())
        .tile("verify", "verify", ins=["src_verify"], outs=["verify_dedup"],
              batch=4, msg_maxlen=256, flush_age_ns=50_000_000)
        .tile("dedup", "dedup", ins=["verify_dedup"], outs=["dedup_pack"])
        .tile("pack", "pack", ins=["dedup_pack"], outs=["pack_bank"])
        .tile("bank", "bank", ins=["pack_bank"], outs=["bank_blockhash"],
              genesis_path=gpath, slot_txn_max=7, rpc_port=0,
              slot_ns=10**15,            # rolls are txn-driven only
              pin_genesis_blockhash=False, blockhash_max_age=6)
        .build()
    )
    with TopoRun(spec) as run:
        run.wait_ready(timeout=420)
        _wait(lambda: run.metrics("bank")["txn_exec_cnt"]
              + run.metrics("bank")["txn_fail_cnt"] >= n, 300,
              f"{n} txns executed")
        _wait(lambda: run.metrics("bank")["slot_cnt"] >= 6, 30,
              "6th roll (the 42nd txn's roll)")
        m = run.metrics("bank")
        s = run.metrics("source")
        assert m["txn_exec_cnt"] == n, m
        assert m["txn_fail_cnt"] == 0, m
        assert m["slot_cnt"] >= 6, m       # genesis evicted at roll 6
        assert s["blockhash_refresh_cnt"] >= 1, s

        port = run.metrics("bank")["rpc_port"]
        assert port
        cl = RpcClient(f"http://127.0.0.1:{port}")

        def transfer(bh, amount):
            msg = txn_lib.build_unsigned(
                [payer_pk], bh,
                [(2, bytes([0, 1]), ix_transfer(amount))],
                extra_accounts=[b"\xd9" + bytes(31), SYSTEM_PROGRAM_ID],
                readonly_unsigned_cnt=1)
            return txn_lib.assemble([ed.sign(payer_seed, msg)], msg)

        # stale: the GENESIS hash has aged out -> rejected
        fails0 = m["txn_fail_cnt"]
        cl.send_transaction(transfer(bh_genesis, 111))
        _wait(lambda: run.metrics("bank")["txn_fail_cnt"] > fails0, 60,
              "stale txn rejected")

        # fresh: the CURRENT blockhash from RPC -> executes
        execs0 = run.metrics("bank")["txn_exec_cnt"]
        cl.send_transaction(transfer(cl.get_latest_blockhash(), 222))
        _wait(lambda: run.metrics("bank")["txn_exec_cnt"] > execs0, 60,
              "fresh txn executed")
        assert run.poll() is None
