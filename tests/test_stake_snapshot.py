"""Stake program lifecycle, sysvar refresh, snapshot save/restore, feature
gates (ref behaviors: src/flamenco/runtime/program/fd_stake_program.c,
runtime/sysvar/, snapshot/, features/)."""

import numpy as np
import pytest

from firedancer_tpu.ballet import txn as txn_lib
from firedancer_tpu.flamenco import genesis as gen_mod
from firedancer_tpu.flamenco import stake_program as stake
from firedancer_tpu.flamenco import sysvar
from firedancer_tpu.flamenco.features import Features
from firedancer_tpu.flamenco.runtime import Runtime
from firedancer_tpu.flamenco.types import (Account, STAKE_PROGRAM_ID,
                                           SYSVAR_CLOCK_ID, VOTE_PROGRAM_ID)
from firedancer_tpu.ops import ed25519 as ed


def _keypair(i):
    seed = i.to_bytes(32, "little")
    return seed, ed.keypair_from_seed(seed)[0]


def _signed(signers, msg):
    return txn_lib.assemble([ed.sign(s, msg) for s, _ in signers], msg)


@pytest.fixture()
def chain():
    faucet_seed, faucet_pk = _keypair(1)
    node_seed, node_pk = _keypair(2)
    vote_seed, vote_pk = _keypair(3)
    g = gen_mod.create(
        faucet_pk, faucet_lamports=10_000_000_000,
        bootstrap_validators=[(node_pk, vote_pk, 1_000_000)],
        slots_per_epoch=8, creation_time=1_700_000_000)
    staker_seed, staker_pk = _keypair(4)
    g.accounts[staker_pk] = Account(lamports=2_000_000_000)
    stake_seed, stake_pk = _keypair(5)
    g.accounts[stake_pk] = Account(lamports=1_000_000_000,
                                   owner=STAKE_PROGRAM_ID, data=b"\x00")
    rt = Runtime(g)
    return rt, (faucet_seed, faucet_pk), (staker_seed, staker_pk), \
        (stake_seed, stake_pk), vote_pk


def _run(rt, bank, signers, ix_data, accounts, ro_cnt=1):
    msg = txn_lib.build_unsigned(
        [p for _, p in signers], rt.root_hash, ix_data,
        extra_accounts=accounts, readonly_unsigned_cnt=ro_cnt)
    return bank.execute_txn(_signed(signers, msg))


def test_stake_lifecycle(chain):
    rt, faucet, staker, stake_acct, vote_pk = chain
    b = rt.new_bank(1)
    sseed, spk = staker
    kseed, kpk = stake_acct

    # initialize: stake account index 1, program last
    res = _run(rt, b, [staker], [(2, bytes([1]), stake.ix_initialize(spk, spk))],
               [kpk, STAKE_PROGRAM_ID])
    assert res.ok, res.err
    st = stake.StakeState.deserialize(
        rt.accdb.load(b.xid, kpk).data)
    assert st.kind == stake.StakeState.INITIALIZED and st.staker == spk

    # delegate to the vote account (staker signs)
    res = _run(rt, b, [staker], [(3, bytes([1, 2]), stake.ix_delegate())],
               [kpk, vote_pk, STAKE_PROGRAM_ID], ro_cnt=2)
    assert res.ok, res.err
    st = stake.StakeState.deserialize(rt.accdb.load(b.xid, kpk).data)
    assert st.kind == stake.StakeState.DELEGATED and st.voter == vote_pk
    assert st.activation_epoch == 1  # slot 1, epoch 0 -> active next epoch
    assert st.effective_stake(0) == 0
    assert st.effective_stake(1) == 1_000_000_000

    # withdraw while active must fail
    res = _run(rt, b, [staker],
               [(2, bytes([1, 0]), stake.ix_withdraw(1000))],
               [kpk, STAKE_PROGRAM_ID])
    assert not res.ok and "not deactivated" in res.err

    # deactivate, then withdraw succeeds once past deactivation epoch
    res = _run(rt, b, [staker], [(2, bytes([1]), stake.ix_deactivate())],
               [kpk, STAKE_PROGRAM_ID])
    assert res.ok, res.err
    st = stake.StakeState.deserialize(rt.accdb.load(b.xid, kpk).data)
    assert st.deactivation_epoch == 1
    # roll to a slot in epoch >= 1: freeze + publish, open slot 9 (epoch 1)
    b.freeze(b"\x11" * 32)
    rt.publish(1)
    b2 = rt.new_bank(9)
    res = _run(rt, b2, [staker],
               [(2, bytes([1, 0]), stake.ix_withdraw(1000))],
               [kpk, STAKE_PROGRAM_ID])
    assert res.ok, res.err
    assert rt.accdb.load(b2.xid, kpk).lamports == 1_000_000_000 - 1000


def test_unauthorized_staker_rejected(chain):
    rt, faucet, staker, stake_acct, vote_pk = chain
    b = rt.new_bank(1)
    sseed, spk = staker
    kseed, kpk = stake_acct
    res = _run(rt, b, [staker],
               [(2, bytes([1]), stake.ix_initialize(spk, spk))],
               [kpk, STAKE_PROGRAM_ID])
    assert res.ok
    # faucet (not the staker authority) tries to delegate
    res = _run(rt, b, [faucet], [(3, bytes([1, 2]), stake.ix_delegate())],
               [kpk, vote_pk, STAKE_PROGRAM_ID], ro_cnt=2)
    assert not res.ok and "staker must sign" in res.err


def test_uninitialized_stake_withdraw_needs_own_signature(chain):
    """An UNINITIALIZED stake account's withdraw authority is the account
    itself (Agave rule) — a third party must not be able to drain it."""
    rt, faucet, staker, stake_acct, vote_pk = chain
    b = rt.new_bank(1)
    kseed, kpk = stake_acct

    # attacker (staker keypair, NOT the stake account) tries to drain the
    # still-uninitialized stake account into their own account
    res = _run(rt, b, [staker],
               [(2, bytes([1, 0]), stake.ix_withdraw(500_000_000))],
               [kpk, STAKE_PROGRAM_ID])
    assert not res.ok and "own signature" in res.err
    assert rt.accdb.load(b.xid, kpk).lamports == 1_000_000_000

    # the stake account itself signing: withdraw succeeds (staker is the
    # fee payer so the stake balance moves only by the withdrawn amount)
    sseed, spk = staker
    msg = txn_lib.build_unsigned(
        [spk, kpk], rt.root_hash,
        [(2, bytes([1, 0]), stake.ix_withdraw(500_000_000))],
        extra_accounts=[STAKE_PROGRAM_ID], readonly_unsigned_cnt=1)
    res = b.execute_txn(_signed([staker, stake_acct], msg))
    assert res.ok, res.err
    assert rt.accdb.load(b.xid, kpk).lamports == 500_000_000


def test_sysvar_clock_refreshed(chain):
    rt = chain[0]
    b = rt.new_bank(3)
    clock = rt.accdb.load(b.xid, SYSVAR_CLOCK_ID)
    slot, ts, epoch = sysvar.clock_parse(clock.data)
    assert slot == 3 and epoch == 0
    assert ts == rt.genesis.creation_time + (3 * 2) // 5


def test_snapshot_roundtrip(chain, tmp_path):
    rt, faucet, staker, stake_acct, vote_pk = chain
    from firedancer_tpu.flamenco import system_program as sysprog
    from firedancer_tpu.flamenco.types import SYSTEM_PROGRAM_ID
    b = rt.new_bank(1)
    _, dest = _keypair(77)
    res = _run(rt, b, [faucet],
               [(2, bytes([0, 1]), sysprog.ix_transfer(123_456))],
               [dest, SYSTEM_PROGRAM_ID])
    assert res.ok, res.err
    b.freeze(b"\x22" * 32)
    rt.publish(1)

    p = str(tmp_path / "snap.tar.gz")
    rt.snapshot(p)
    rt2 = Runtime.from_snapshot(rt.genesis, p)
    assert rt2.root_slot == 1 and rt2.root_hash == rt.root_hash
    assert rt2.balance(dest) == 123_456
    # restored chain keeps executing: recent blockhashes survived
    b2 = rt2.new_bank(2)
    res = _run(rt2, b2, [faucet],
               [(2, bytes([0, 1]), sysprog.ix_transfer(1))],
               [dest, SYSTEM_PROGRAM_ID])
    assert res.ok, res.err


def test_feature_gates():
    f = Features()
    assert f.active("strict_blockhash_age", 0)
    f.schedule("batch_sigverify_rlc", 100)
    assert not f.active("batch_sigverify_rlc", 99)
    assert f.active("batch_sigverify_rlc", 100)
    f.schedule("batch_sigverify_rlc", None)
    assert not f.active("batch_sigverify_rlc", 10**9)
    with pytest.raises(KeyError):
        f.active("nope", 0)
