"""Batched SHA-512 vs hashlib (NIST CAVP-style length sweep; the reference
tests hashes against CAVP vectors, src/ballet/README_cavp.md)."""

import hashlib
import secrets

import jax.numpy as jnp
import numpy as np

from firedancer_tpu.ops import sha512 as sh


def run_batch(msgs, maxlen=None):
    maxlen = maxlen or max((len(m) for m in msgs), default=1)
    buf = np.zeros((len(msgs), maxlen), dtype=np.uint8)
    lens = np.zeros((len(msgs),), dtype=np.int32)
    for i, m in enumerate(msgs):
        buf[i, : len(m)] = np.frombuffer(m, dtype=np.uint8)
        lens[i] = len(m)
    out = np.asarray(sh.sha512(jnp.asarray(buf), jnp.asarray(lens)))
    return [out[i].tobytes() for i in range(len(msgs))]


def test_boundary_lengths():
    # padding boundaries: 111/112 straddle the one-vs-two block edge
    lens = [0, 1, 2, 55, 56, 63, 64, 65, 111, 112, 113, 127, 128, 129, 200, 255, 256]
    msgs = [secrets.token_bytes(n) for n in lens]
    got = run_batch(msgs, maxlen=256)
    for m, d in zip(msgs, got):
        assert d == hashlib.sha512(m).digest(), len(m)


def test_mixed_batch_content():
    msgs = [b"", b"abc", b"a" * 1000, secrets.token_bytes(1232)]
    got = run_batch(msgs, maxlen=1232)
    for m, d in zip(msgs, got):
        assert d == hashlib.sha512(m).digest(), len(m)


def test_verify_preimage_shape():
    # the shape used by ed25519 verify: 32B R || 32B A || msg
    msg = secrets.token_bytes(64)
    pre = secrets.token_bytes(32) + secrets.token_bytes(32) + msg
    (d,) = run_batch([pre], maxlen=160)
    assert d == hashlib.sha512(pre).digest()
