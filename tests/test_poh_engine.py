"""Device PoH engine tests (round 14): batched span hashing vs the host
chain golden (ballet.entry.next_hash), the fixed-length sha256 fast paths,
device-batched mixin trees vs txn_mixin, the verify_entries bucketed-shape
ladder, and compile-count flatness across steady-state dispatches.

Shapes are kept tiny (lanes <= 3, hashes <= 8) so the whole module stays
in the fast tier on a cold cache."""

import hashlib

import numpy as np
import pytest

from firedancer_tpu.ballet import entry as entry_lib
from firedancer_tpu.ballet import poh as poh_lib
from firedancer_tpu.ballet import poh_engine as pe


def _h(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


# ---------------------------------------------------------------- sha paths

def test_sha256_fixed_paths_bit_exact():
    from firedancer_tpu.ops.sha256 import sha256_fixed32, sha256_fixed64
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    m32 = rng.integers(0, 256, (4, 32), dtype=np.uint8)
    m64 = rng.integers(0, 256, (4, 64), dtype=np.uint8)
    got32 = np.asarray(sha256_fixed32(jnp.asarray(m32)))
    got64 = np.asarray(sha256_fixed64(jnp.asarray(m64)))
    for i in range(4):
        assert bytes(got32[i]) == _h(bytes(m32[i]))
        assert bytes(got64[i]) == _h(bytes(m64[i]))


# ----------------------------------------------------------- verify ladder

def test_fit_max_hashes_ladder():
    fit = poh_lib.fit_max_hashes
    assert fit(1, 1024) == 1
    assert fit(3, 1024) == 4
    assert fit(4, 1024) == 4
    assert fit(5, 1024) == 8
    assert fit(0, 1024) == 1          # clamps up
    assert fit(9999, 64) == 64        # clamps to max
    assert fit(33, 64, ladder=(16, 48)) == 48


def test_verify_entries_fit_matches_host():
    start = b"\x22" * 32
    h = start
    entries = []
    for i in range(4):
        mix = bytes([i]) * 32 if i % 2 else None
        n = i + 1
        h = entry_lib.next_hash(h, n, mix)
        entries.append((n, mix, h))
    starts = np.zeros((4, 32), np.uint8)
    nums = np.array([e[0] for e in entries], np.int32)
    mixins = np.zeros((4, 32), np.uint8)
    has = np.zeros((4,), np.bool_)
    prev = start
    for i, (n, mix, hh) in enumerate(entries):
        starts[i] = np.frombuffer(prev, np.uint8)
        if mix is not None:
            mixins[i] = np.frombuffer(mix, np.uint8)
            has[i] = True
        prev = hh
    got = np.asarray(
        poh_lib.verify_entries_fit(starts, nums, mixins, has, max_hashes=8))
    for i, (_, _, hh) in enumerate(entries):
        assert bytes(got[i]) == hh


def test_warm_verify_ladder_counts_rungs():
    n = poh_lib.warm_verify_ladder(batch=2, max_hashes=8)
    assert n == 4  # 1, 2, 4, 8


# ------------------------------------------------------------ device mixin

def test_txn_mixins_device_matches_host():
    rng = np.random.default_rng(11)

    def mk(i):
        return bytes([1]) + rng.bytes(64) + bytes([i])

    batches = [[mk(i) for i in range(w)] for w in (1, 2, 3, 5, 8)]
    got = entry_lib.txn_mixins_device(batches, pad_batch=6, pad_width=8)
    for i, ts in enumerate(batches):
        assert bytes(got[i]) == entry_lib.txn_mixin(ts)


def test_txn_mixins_device_rejects_empty_microblock():
    with pytest.raises(ValueError):
        entry_lib.txn_mixins_device([[]])


# ------------------------------------------------------------- poh engine

def _specs_tick_with_mixins(start: bytes, mixes: list[bytes], hpt: int):
    steps = [(1, m) for m in mixes] + [(hpt - len(mixes), None)]
    return [(start, steps)]


def test_host_spans_chain_rule():
    # the host golden chains steps WITHIN a lane: a tick with 2 microblocks
    # is [(1, m1), (1, m2), (hpt - 2, None)] composed left to right
    start = b"\x01" * 32
    m1, m2 = b"\xaa" * 32, b"\xbb" * 32
    golden = pe.host_spans([(start, [(1, m1), (1, m2), (6, None)])], steps=3)
    h = entry_lib.next_hash(start, 1, m1)
    assert bytes(golden[0, 0]) == h
    h = entry_lib.next_hash(h, 1, m2)
    assert bytes(golden[0, 1]) == h
    assert bytes(golden[0, 2]) == entry_lib.next_hash(h, 6, None)


def test_engine_bit_exact_vs_host():
    eng = pe.PohEngine(lanes=2, steps=2, max_hashes=8, unroll=4)
    specs = [
        (b"\x03" * 32, [(1, b"\xcc" * 32), (7, None)]),
        (b"\x04" * 32, [(8, None), (0, None)]),   # n=0 tail = passthrough
    ]
    golden = pe.host_spans(specs, steps=2)
    outs = []
    for v in eng.submit_lanes(specs):
        outs.append(eng.split_verdict(v))
    for v in eng.drain():
        outs.append(eng.split_verdict(v))
    assert len(outs) == 1
    planes = outs[0]
    for lane in range(2):
        for s in range(2):
            assert bytes(planes[lane, s]) == bytes(golden[lane, s])


def test_engine_idle_lane_passthrough():
    eng = pe.PohEngine(lanes=3, steps=1, max_hashes=4, unroll=2)
    specs = [(b"\x05" * 32, [(4, None)])]     # lanes 1,2 idle
    outs = []
    for v in eng.submit_lanes(specs):
        outs.append(eng.split_verdict(v))
    for v in eng.drain():
        outs.append(eng.split_verdict(v))
    planes = outs[0]
    assert bytes(planes[0, 0]) == entry_lib.next_hash(b"\x05" * 32, 4, None)
    assert bytes(planes[1, 0]) == b"\x00" * 32   # idle lane untouched


def test_engine_rejects_mixin_without_hash():
    # consensus guard: a mixin step with n == 0 would PASS THROUGH on the
    # kernel (masked scan skips it) while the host golden absorbs the
    # mixin — the engine must refuse rather than silently diverge
    eng = pe.PohEngine(lanes=1, steps=1, max_hashes=4, unroll=2)
    with pytest.raises(ValueError):
        eng.submit_lanes([(b"\x06" * 32, [(0, b"\xdd" * 32)])])
    with pytest.raises(ValueError):
        pe.host_spans([(b"\x06" * 32, [(0, b"\xdd" * 32)])], steps=1)
    # the engine survives a rejected submit: the buffer went back on the
    # free ring and a valid span still dispatches
    outs = []
    for v in eng.submit_lanes([(b"\x07" * 32, [(2, None)])]):
        outs.append(eng.split_verdict(v))
    for v in eng.drain():
        outs.append(eng.split_verdict(v))
    assert bytes(outs[0][0, 0]) == entry_lib.next_hash(b"\x07" * 32, 2, None)


def test_engine_zero_steadystate_compiles():
    from firedancer_tpu.disco import trace

    trace.install_jax_compile_listener()
    eng = pe.PohEngine(lanes=2, steps=2, max_hashes=4, unroll=2)
    eng.warm()
    mix = b"\xee" * 32
    specs = [(b"\x08" * 32, [(1, mix), (3, None)]),
             (b"\x09" * 32, [(4, None), (0, None)])]
    for v in eng.submit_lanes(specs):
        pass
    eng.drain()
    cnt0, _ = trace.compile_totals()
    for i in range(3):                      # fresh data, same shape
        s2 = [(bytes([i + 1]) * 32, [(1, mix), (3, None)]),
              (bytes([i + 2]) * 32, [(2, None), (2, None)])]
        for v in eng.submit_lanes(s2):
            pass
        eng.drain()
    cnt1, _ = trace.compile_totals()
    assert cnt1 == cnt0, f"steady-state dispatch compiled {cnt1 - cnt0}x"


def test_engine_stats_surface():
    eng = pe.PohEngine(lanes=1, steps=1, max_hashes=2, unroll=2)
    for v in eng.submit_lanes([(b"\x0a" * 32, [(2, None)])]):
        pass
    eng.drain()
    st = eng.stats()
    assert st["dispatches"] >= 1
    assert st["inflight_depth"] == 0
