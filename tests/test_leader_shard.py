"""Round-15 leader-lane tests: fee-payer shard steering determinism,
global budget enforcement at the merge point, native-vs-Python pack
schedule bit-identity, and K-tick PoH speculation splices against the
host chain rule."""

import collections

import pytest

from firedancer_tpu.ballet import pack, txn as txn_lib


def _mk_txn(
    signer: bytes,
    writable_extra: list[bytes] = (),
    readonly_extra: list[bytes] = (),
    program: bytes = b"\x07" * 32,
    data: bytes = b"\x00" * 8,
    cu_price: int | None = None,
):
    extra = list(writable_extra) + list(readonly_extra) + [program]
    n_accts = 1 + len(extra)
    prog_idx = n_accts - 1
    instrs = [(prog_idx, bytes([0]), data)]
    if cu_price is not None:
        cb = pack.COMPUTE_BUDGET_PROG_ID
        extra = list(writable_extra) + list(readonly_extra) + [program, cb]
        n_accts = 1 + len(extra)
        prog_idx = n_accts - 2
        instrs = [
            (prog_idx, bytes([0]), data),
            (n_accts - 1, b"", bytes([3]) + cu_price.to_bytes(8, "little")),
        ]
    msg = txn_lib.build_unsigned(
        [signer],
        b"\x11" * 32,
        instrs,
        extra_accounts=extra,
        readonly_unsigned_cnt=len(readonly_extra)
        + (2 if cu_price is not None else 1),
    )
    payload = txn_lib.assemble([b"\x5a" * 64], msg)
    return payload, txn_lib.parse(payload)


def _acct(i: int) -> bytes:
    return i.to_bytes(2, "little") + bytes(30)


class _Metrics:
    def __init__(self):
        self.d = collections.Counter()

    def add(self, k, v=1):
        self.d[k] += v

    def set(self, k, v):
        self.d[k] = v


class _Ctx:
    def __init__(self, cfg):
        self.cfg = cfg
        self.metrics = _Metrics()
        self.out = []

    def publish(self, payload, sig=0):
        self.out.append((bytes(payload), sig))


# --------------------------------------------------------- fee-payer steering

def test_fee_payer_matches_full_parse():
    for i in range(1, 40):
        payload, parsed = _mk_txn(_acct(i), cu_price=i * 7 or None)
        o = parsed.acct_addr_off
        assert txn_lib.fee_payer(payload) == payload[o:o + 32]
    assert txn_lib.fee_payer(b"\x01") is None
    assert txn_lib.fee_payer(bytes(4)) is None


def test_shard_steering_deterministic_across_respawn():
    """The fee-payer hash partition is stateless: a respawned shard tile
    (fresh init, zero heap state) must own EXACTLY the same txns, and
    every txn must be owned by exactly one shard."""
    from firedancer_tpu.disco.tiles import LeaderPackTile

    payloads = [_mk_txn(_acct(i))[0] for i in range(1, 120)]

    def owned(shard_idx):
        ctx = _Ctx(dict(shard_cnt=2, shard_idx=shard_idx, max_txn=4,
                        max_pending=0, block_us=10**9))
        tile = LeaderPackTile()
        tile.init(ctx)
        got = set()
        for p in payloads:
            before = tile.pack.pending
            tile._insert(ctx, p)
            if tile.pack.pending > before:
                got.add(p)
        return got, ctx.metrics.d["shard_steer_cnt"]

    o0a, steer0a = owned(0)
    o1a, steer1a = owned(1)
    o0b, steer0b = owned(0)          # the "respawn": a fresh incarnation
    assert o0a == o0b and steer0a == steer0b
    assert o0a | o1a == set(payloads)
    assert not (o0a & o1a)
    assert o0a and o1a               # both shards own a nonempty partition
    assert steer0a == len(o0a) and steer1a == len(o1a)


# ------------------------------------------------------- merge global budgets

def test_merge_enforces_global_acct_write_budget():
    """Two shards schedule the same hot writable account: each shard's
    LOCAL budget admits its microblock, but the merge point must defer
    the second one once the GLOBAL per-account write budget is hit."""
    from firedancer_tpu.disco.tiles import LeaderMergeTile, LeaderPackTile

    hot = pack.acct_key(_acct(99))
    near_cap = pack.MAX_WRITE_COST_PER_ACCT - 10
    mk = LeaderPackTile.MERGE_HDR.pack
    item = LeaderPackTile.MERGE_ITEM.pack
    frag_a = mk(1, 1000, 0, 64) + item(hot, near_cap) + b"innerA"
    frag_b = mk(1, 1000, 0, 64) + item(hot, near_cap) + b"innerB"

    ctx = _Ctx(dict(block_us=10**9))
    tile = LeaderMergeTile()
    tile.init(ctx)
    tile.on_frag(ctx, 0, None, frag_a)       # shard 0: admits
    tile.on_frag(ctx, 1, None, frag_b)       # shard 1: same hot account
    assert ctx.metrics.d["mb_merge_cnt"] == 1
    assert ctx.metrics.d["merge_budget_defer_cnt"] >= 1
    assert ctx.metrics.d["merge_stall_cnt"] >= 1
    assert [p for p, _ in ctx.out] == [b"innerA"]
    # block rolls: the deferred head admits against a fresh budget
    tile.budget.end_block()
    tile._admit(ctx)
    assert [p for p, _ in ctx.out] == [b"innerA", b"innerB"]
    assert ctx.metrics.d["mb_merge_cnt"] == 2
    # merged seqs are this tile's own monotonic microblock sequence
    assert [s for _, s in ctx.out] == [0, 1]


def test_merge_budget_all_or_nothing():
    b = pack.MergeBudget()
    hot = 0x1234
    assert b.try_admit(10, 0, 10, [(hot, pack.MAX_WRITE_COST_PER_ACCT)])
    # second admission overflows the account budget: NOTHING commits
    cost0, data0 = b.block_cost, b.block_data
    assert not b.try_admit(10, 0, 10, [(0x9999, 5), (hot, 1)])
    assert b.block_cost == cost0 and b.block_data == data0
    assert 0x9999 not in b.acct_write_cost
    b.end_block()
    assert b.try_admit(10, 0, 10, [(hot, 1)])


def test_merge_round_robin_interleave():
    """Per pass each shard contributes at most one head: 3 queued on one
    shard and 1 on the other must interleave, not burst."""
    from firedancer_tpu.disco.tiles import LeaderMergeTile, LeaderPackTile

    mk = LeaderPackTile.MERGE_HDR.pack
    ctx = _Ctx(dict(block_us=10**9))
    tile = LeaderMergeTile()
    tile.init(ctx)
    # queue manually so no admission happens between frags
    for tag in (b"a0", b"a1", b"a2"):
        tile._qs.setdefault(0, tile._deque()).append((1, 0, 1, [], tag))
    tile._qs.setdefault(1, tile._deque()).append((1, 0, 1, [], b"b0"))
    tile._admit(ctx)
    got = [p for p, _ in ctx.out]
    assert set(got[:2]) == {b"a0", b"b0"}    # first pass: one per shard
    assert got[2:] == [b"a1", b"a2"]
    assert mk(0, 0, 0, 0)                    # (struct sanity)


# ------------------------------------------- native vs python schedule sweep

def _sweep_stream(native, payloads, banks=2, max_pending=48):
    p = pack.Pack(bank_tile_cnt=banks, max_txn_per_microblock=5,
                  max_pending=max_pending, native=native)
    stream = []
    for pay, parsed in payloads:
        p.insert(pay, parsed)
    stalls = 0
    busy = [False] * banks
    bank = 0
    while stalls < 2 * banks + 2:
        if busy[bank]:
            p.done(bank)
            busy[bank] = False
        mb = p.schedule(bank)
        if mb is None:
            if p.pending and all(not b for b in busy):
                p.end_block()
                stream.append(("END",))
                stalls += 1
            else:
                stalls += 1
        else:
            stalls = 0
            busy[bank] = True
            stream.append((bank, tuple(mb.payloads)))
        bank = (bank + 1) % banks
    for b in range(banks):
        if busy[b]:
            p.done(b)
    return stream, dict(p.metrics), p.pending


def test_native_python_schedule_bit_identity_sweep():
    try:
        probe = pack.Pack(bank_tile_cnt=1, native=True)
    except Exception:
        pytest.skip("native pack unavailable on this host")
    assert probe.native

    import random
    rng = random.Random(1234)
    payloads = []
    for i in range(300):
        kind = rng.randrange(10)
        signer = _acct(1 + rng.randrange(40))
        if kind < 2:                       # simple votes (bypass lane)
            payloads.append(_mk_txn(signer, program=pack.VOTE_PROG_ID,
                                    data=bytes(4)))
        elif kind < 5:                     # hot-account conflicts
            payloads.append(_mk_txn(
                signer, writable_extra=[_acct(200 + rng.randrange(3))],
                cu_price=rng.choice([0, 1, 1, 5_000, 5_000, 10**6])))
        else:                              # priority ties on purpose
            payloads.append(_mk_txn(
                signer, readonly_extra=[_acct(300 + rng.randrange(5))],
                data=bytes(4 * rng.randrange(1, 9)),
                cu_price=rng.choice([None, 0, 777, 777, 10**9])))
    s_native, m_native, pend_native = _sweep_stream(True, payloads)
    s_py, m_py, pend_py = _sweep_stream(False, payloads)
    assert s_native == s_py
    assert pend_native == pend_py
    assert m_native == m_py


def test_native_python_vote_bypass_and_cap_boundary():
    try:
        pack.Pack(bank_tile_cnt=1, native=True)
    except Exception:
        pytest.skip("native pack unavailable on this host")
    # heap capped at 4: non-votes shed past the cap, votes bypass
    payloads = [_mk_txn(_acct(i)) for i in range(1, 8)]
    votes = [_mk_txn(_acct(50 + i), program=pack.VOTE_PROG_ID,
                     data=bytes(4)) for i in range(3)]
    for native in (True, False):
        p = pack.Pack(bank_tile_cnt=1, max_txn_per_microblock=31,
                      max_pending=4, native=native)
        ins = [p.insert(pay, t) for pay, t in payloads]
        assert ins == [True] * 4 + [False] * 3, (native, ins)
        assert all(p.insert(pay, t) for pay, t in votes)
        assert p.pending == 7
        assert p.metrics["dropped_heap_full"] == 3
        assert p.metrics["vote_inserted"] == 3


# ------------------------------------------------- K-tick PoH splice vs host

def _drive_pohdev(mb_plan, hpt=8, tps=4, mb_cap=3, k=2):
    """Run PohDevTile over a per-tick microblock plan, return (entries,
    metrics)."""
    from firedancer_tpu.ballet import entry as entry_lib
    from firedancer_tpu.disco.tiles import PohDevTile

    ctx = _Ctx(dict(hashes_per_tick=hpt, ticks_per_slot=tps,
                    mb_per_tick=mb_cap, spec_ticks=k, spec_spans=3,
                    mixin_txn_max=8, unroll=4))
    tile = PohDevTile()
    tile.init(ctx)
    for mbs in mb_plan:
        for mb in mbs:
            tile._mb_q.append(mb)
        tile.house(ctx)
        tile.after_credit(ctx)
    tile.fini(ctx)
    entries = []
    for payload, sig in ctx.out:
        e, _ = entry_lib.Entry.deserialize(payload)
        entries.append(e)
    return entries, ctx.metrics.d


@pytest.mark.parametrize("j", [0, 1, 2, 3])
def test_ktick_splice_bit_identical_at_every_offset(j):
    """Mixins at every offset of the mixin region (j = 0..mb_cap) must
    emit a chain bit-identical to the host rule (verify_chain recomputes
    every next_hash + mixin), with the splice geometry P+1 / 1.. / tail."""
    from firedancer_tpu.ballet import entry as entry_lib

    hpt, mb_cap = 8, 3
    mbs = [[bytes([10 * j + i]) * 65] for i in range(j)]
    plan = [list(mbs), [], []]           # mixins land in tick 1 only
    entries, m = _drive_pohdev(plan, hpt=hpt, mb_cap=mb_cap, k=2)
    assert entry_lib.verify_chain(bytes(32), entries)
    assert sum(len(e.txns) for e in entries) == j
    if j == 0:
        assert m["spec_miss_cnt"] == 0
        assert all(e.num_hashes == hpt for e in entries)
    else:
        p = hpt - mb_cap - 1
        shapes = [e.num_hashes for e in entries[:j + 1]]
        assert shapes == [p + 1] + [1] * (j - 1) + [mb_cap + 1 - j]
        assert m["rehash_cnt"] == mb_cap + 1 - j
        assert m["splice_dispatch_cnt"] == 1
    assert m["recheck_fail_cnt"] == 0


def test_ktick_window_spec_hits_and_invalidation():
    """A full window of empty ticks consumes K speculated ticks from ONE
    dispatch; a mixin mid-window invalidates the remainder."""
    from firedancer_tpu.ballet import entry as entry_lib

    # 6 empty ticks, K=3: exactly 2 window dispatches, 6 spec hits
    entries, m = _drive_pohdev([[] for _ in range(6)], tps=8, k=3)
    assert entry_lib.verify_chain(bytes(32), entries)
    assert m["spec_hit_cnt"] == 6        # incl. the fini slot close
    assert m["dispatch_cnt"] == 2        # 6 ticks from 2 window dispatches
    assert m["splice_dispatch_cnt"] == 0

    # mixin lands on the middle tick of a K=3 window
    plan = [[], [[b"\x42" * 65]], [], []]
    entries, m = _drive_pohdev(plan, tps=8, k=3)
    assert entry_lib.verify_chain(bytes(32), entries)
    assert m["spec_miss_cnt"] == 1
    assert m["splice_dispatch_cnt"] == 1
    assert m["recheck_fail_cnt"] == 0
