"""mod-L scalar ops vs python big-int ground truth."""

import secrets

import jax.numpy as jnp
import numpy as np

from firedancer_tpu.ops import scalar25519 as sc

L = sc.L


def pack_bytes(bs):
    return jnp.asarray(np.frombuffer(b"".join(bs), dtype=np.uint8).reshape(len(bs), -1))


def test_reduce_512():
    vals = [0, 1, L - 1, L, L + 1, 2 * L, 2**512 - 1, 2**252, 2**255 - 19]
    vals += [secrets.randbits(512) for _ in range(64)]
    raw = [v.to_bytes(64, "little") for v in vals]
    out = sc.reduce_512(pack_bytes(raw))
    got = [sc.to_int(np.asarray(out[:, i])) for i in range(len(vals))]
    assert got == [v % L for v in vals]


def test_reduce_512_canonical_limbs():
    vals = [secrets.randbits(512) for _ in range(16)]
    out = np.asarray(sc.reduce_512(pack_bytes([v.to_bytes(64, "little") for v in vals])))
    assert out.min() >= 0 and out.max() <= sc.MASK


def test_is_canonical():
    vals = [0, 1, L - 1, L, L + 1, 2**256 - 1, 2**252, secrets.randbits(250)]
    raw = [v.to_bytes(32, "little") for v in vals]
    got = list(np.asarray(sc.is_canonical(pack_bytes(raw))))
    assert got == [v < L for v in vals]


def test_windows():
    v = secrets.randbits(252)
    limbs = jnp.asarray(
        np.array([(v >> (12 * i)) & 0xFFF for i in range(22)], dtype=np.int32)[:, None]
    )
    w = np.asarray(sc.limbs_to_windows(limbs))[:, 0]
    assert all(int(w[j]) == ((v >> (4 * j)) & 0xF) for j in range(64))
