"""sBPF VM + ELF loader tests (ref test model: src/flamenco/vm/test_vm_interp.c
instruction-level cases; src/ballet/sbpf/test_sbpf_loader.c)."""

import hashlib
import struct

import pytest

from firedancer_tpu.ballet.sbpf import mini_elf, asm, ins, load, SbpfLoaderError
from firedancer_tpu.flamenco.vm import (MM_HEAP, MM_INPUT, MM_STACK, Vm,
                                        VmComputeExceeded, VmFault,
                                        syscall_id)


def run(text, *args, **kw):
    return Vm(text, **kw).run(*args)


def test_alu64_basics():
    assert run(asm("""
        mov r0, 10
        add r0, 5
        mul r0, 3
        sub r0, 1
        exit""")) == 44
    assert run(asm("""
        mov r1, 7
        mov r0, 100
        div r0, r1
        exit""")) == 14
    assert run(asm("""
        mov r0, 0xff
        and r0, 0x0f
        or  r0, 0x100
        xor r0, 0x01
        exit""")) == 0x10E
    assert run(asm("""
        mov r0, 1
        lsh r0, 40
        rsh r0, 8
        exit""")) == 1 << 32


def test_alu_negative_and_arsh():
    assert run(asm("""
        mov r0, 5
        neg r0
        exit""")) == (-5) & ((1 << 64) - 1)
    assert run(asm("""
        mov r0, -16
        arsh r0, 2
        exit""")) == (-4) & ((1 << 64) - 1)
    # 32-bit ops truncate
    assert run(asm("""
        mov32 r0, -1
        add32 r0, 1
        exit""")) == 0


def test_div_by_zero_faults():
    with pytest.raises(VmFault):
        run(asm("""
            mov r0, 1
            mov r1, 0
            div r0, r1
            exit"""))


def test_lddw_and_endian():
    assert run(asm("""
        lddw r0, 0x1122334455667788
        exit""")) == 0x1122334455667788
    assert run(asm("""
        lddw r0, 0x1122334455667788
        be r0, 64
        exit""")) == 0x8877665544332211


def test_jumps_and_loop():
    # sum 1..10 with a loop
    assert run(asm("""
        mov r0, 0
        mov r1, 10
    loop:
        add r0, r1
        sub r1, 1
        jne r1, 0, =loop
        exit""")) == 55
    assert run(asm("""
        mov r0, 1
        mov r1, 5
        jsgt r1, 10, =big
        mov r0, 2
    big:
        exit""")) == 2


def test_stack_memory():
    assert run(asm("""
        stdw [r10+-8], 0x1234
        ldxdw r0, [r10+-8]
        exit""")) == 0x1234
    assert run(asm("""
        mov r1, 0xabcd
        stxh [r10+-16], r1
        ldxb r0, [r10+-16]
        exit""")) == 0xCD


def test_input_region_and_fault():
    inp = bytearray(b"\x2a" + bytes(7))
    text = asm(f"""
        lddw r1, {MM_INPUT}
        ldxdw r0, [r1+0]
        exit""")
    assert Vm(text, input_mem=inp).run() == 0x2A
    # out-of-bounds read faults
    with pytest.raises(VmFault):
        Vm(text, input_mem=bytearray(4)).run()
    # write to program region faults
    with pytest.raises(VmFault):
        run(asm("""
            lddw r1, 0x100000000
            stdw [r1+0], 1
            exit"""))


def test_bpf_to_bpf_call():
    # f(x) = x*2 called twice (21 -> 42 -> 84); frames preserve r6-r9
    assert run(asm("""
        mov r6, 77
        mov r1, 21
        call =dbl
        mov r1, r0
        call =dbl
        jne r6, 77, =bad
        exit
    bad:
        mov r0, 0
        exit
    dbl:
        mov r0, r1
        add r0, r0
        exit""")) == 84


def test_callx():
    assert run(asm("""
        lddw r2, 0x100000020
        callx r2
        exit
        mov r0, 99
        exit""")) == 99  # 0x20/8 = pc 4 (after lddw=2, callx, exit)


def test_call_depth_limit():
    with pytest.raises(VmFault, match="call depth"):
        run(asm("""
        rec:
            call =rec
            exit"""))


def test_compute_metering():
    with pytest.raises(VmComputeExceeded):
        run(asm("""
        loop:
            ja =loop
            exit"""), compute_units=1000)
    # exact budget: 3 instructions cost 3
    assert Vm(asm("""
        mov r0, 1
        add r0, 1
        exit"""), compute_units=3).run() == 2


def test_syscall_log_and_sha256():
    inp = bytearray(b"hello world" + bytes(64))
    # log the 11 input bytes, then sha256 them via the slices ABI
    text = asm(f"""
        lddw r1, {MM_INPUT}
        mov r2, 11
        syscall sol_log_
        lddw r6, {MM_HEAP}
        lddw r1, {MM_INPUT}
        stxdw [r6+0], r1
        stdw [r6+8], 11
        mov r1, r6
        mov r2, 1
        lddw r3, {MM_HEAP + 64}
        syscall sol_sha256
        lddw r6, {MM_HEAP + 64}
        ldxdw r0, [r6+0]
        exit""")
    vm = Vm(text, input_mem=inp)
    r0 = vm.run()
    assert vm.log == [b"hello world"]
    want = hashlib.sha256(b"hello world").digest()
    assert r0 == int.from_bytes(want[:8], "little")


def test_syscall_memops():
    text = asm(f"""
        lddw r1, {MM_HEAP}
        lddw r2, {MM_INPUT}
        mov r3, 8
        syscall sol_memcpy_
        lddw r1, {MM_HEAP}
        ldxdw r0, [r1+0]
        exit""")
    vm = Vm(text, input_mem=bytearray(struct.pack("<Q", 0xDEAD)))
    assert vm.run() == 0xDEAD


def test_abort_and_unknown_call():
    with pytest.raises(VmFault, match="abort"):
        run(asm("syscall abort\nexit"))
    with pytest.raises(VmFault):
        run(ins(0x85, imm=0x7FFFFFFF) + ins(0x95))  # bogus call target


# -- ELF loader -------------------------------------------------------------

_mini_elf = mini_elf


def test_elf_load_and_run():
    text = asm("""
        mov r0, 1234
        exit""")
    prog = load(_mini_elf(text))
    assert prog.entry_pc == 0
    vm = Vm(prog.text, entry_pc=prog.entry_pc, rodata=prog.rodata)
    assert vm.run() == 1234


def test_elf_rejects_garbage():
    with pytest.raises(SbpfLoaderError):
        load(b"not an elf at all")
    with pytest.raises(SbpfLoaderError):
        load(b"\x7fELF\x01\x01" + bytes(58))  # 32-bit


def test_syscall_keccak_blake3_logdata():
    """sol_keccak256 / sol_blake3 / sol_log_data over the shared slices ABI
    (fd_vm_syscall hash family)."""
    from firedancer_tpu.ballet.keccak256 import keccak256
    from firedancer_tpu.ops.blake3 import blake3

    inp = bytearray(b"syscall hash input!" + bytes(32))

    def run_hash(name):
        text = asm(f"""
            lddw r6, {MM_HEAP}
            lddw r1, {MM_INPUT}
            stxdw [r6+0], r1
            stdw [r6+8], 19
            mov r1, r6
            mov r2, 1
            lddw r3, {MM_HEAP + 64}
            syscall {name}
            lddw r6, {MM_HEAP + 64}
            ldxdw r0, [r6+0]
            exit""")
        return Vm(text, input_mem=bytearray(inp)).run()

    msg = bytes(inp[:19])
    assert run_hash("sol_keccak256") == int.from_bytes(
        keccak256(msg)[:8], "little")
    assert run_hash("sol_blake3") == int.from_bytes(
        blake3(msg)[:8], "little")

    text = asm(f"""
        lddw r6, {MM_HEAP}
        lddw r1, {MM_INPUT}
        stxdw [r6+0], r1
        stdw [r6+8], 19
        mov r1, r6
        mov r2, 1
        syscall sol_log_data
        mov r0, 0
        exit""")
    vm = Vm(text, input_mem=bytearray(inp))
    assert vm.run() == 0
    assert vm.log == [msg]


def test_disasm_roundtrips_through_asm():
    """disasm(asm(src)) reassembles to identical bytes (modulo labels
    resolving to numeric offsets)."""
    from firedancer_tpu.ballet.sbpf import asm, disasm

    src = """
    mov r1, 7
    mov32 r2, -1
    add r1, r2
    lsh r1, 2
    lddw r3, 0x123456789abcdef0
    ldxdw r4, [r3+8]
    stxw [r10+-8], r1
    stb [r10+-16], 255
    jeq r1, 0, 2
    neg r1
    ja 1
    be r1 64
    exit
    """
    code = asm(src)
    text = disasm(code)
    # reassemble the disassembly (skip lddw continuation comments)
    re_src = "\n".join(t for t in text if not t.startswith(";"))
    assert asm(re_src) == code


def test_vm_tracer_records_execution():
    from firedancer_tpu.ballet.sbpf import asm
    from firedancer_tpu.flamenco.vm import Vm

    code = asm("""
    mov r0, 0
    add r0, 5
    add r0, 7
    exit
    """)
    vm = Vm(code)
    trace = []
    vm.tracer = lambda pc, op, regs: trace.append((pc, op, regs[0]))
    assert vm.run() == 12
    assert [t[0] for t in trace] == [0, 1, 2, 3]
    assert trace[-1][2] == 12  # r0 before exit
    # tracer off by default: no overhead path
    vm2 = Vm(code)
    assert vm2.run() == 12
