"""Antipa halved-scalar verify — device-resident divstep form (round 9).

verify_batch_antipa must reproduce verify_batch's bits on honest and
corrupted signatures.  The ONE documented divergence is cofactored
laxity: antipa checks [v]([S]B - [k]A - R) == 0, so a signature whose
defect D = [S]B - [k]A - R is a small-torsion point is accepted iff
ord(D) divides v.  test_torsion_laxity_enumerated constructs exactly
those forgeries (defect forced to an order-2 / order-4 point) and pins
the divergence to the ord(T) | v predicate — nothing else may differ.
"""

import hashlib

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from firedancer_tpu.models.verifier import make_example_batch
from firedancer_tpu.ops import curve25519 as cv
from firedancer_tpu.ops import ed25519 as ed
from firedancer_tpu.ops import scalar25519 as sc

BATCH = 16
P = ed.P
L = sc.L

# identity encoding: y = 1, x-sign 0 — decompresses to the neutral
# element, which is small-order (rejected by both verify modes)
_ID_ENC = bytes([1] + [0] * 31)


def test_halve_scalar_invariant():
    rng = np.random.default_rng(41)
    ks = [int.from_bytes(rng.bytes(32), "little") % sc.L
          for _ in range(64)]
    ks[:3] = [0, 1, sc.L - 1]
    for k in ks:
        u, v = ed._halve_scalar_host(k)
        assert 0 <= u < (1 << 127)
        assert v != 0 and abs(v) < (1 << 127)
        assert u % sc.L == (k * v) % sc.L, hex(k)


def test_antipa_matches_verify_batch():
    msgs, lens, sigs, pubs = make_example_batch(
        BATCH, 96, valid=True, sign_pool=8, seed=51)
    msgs = np.asarray(msgs).copy()
    sigs = np.asarray(sigs).copy()
    pubs = np.asarray(pubs).copy()
    sigs[1, 5] ^= 0xFF                        # tampered R
    sigs[2, 32] ^= 0x01                       # tampered S
    sigs[3, 63] |= 0x80                       # non-canonical S
    pubs[4] = np.frombuffer(bytes([0x07] * 32), np.uint8)   # bad A
    pubs[5] = np.frombuffer(_ID_ENC, np.uint8)              # small-order A
    sigs[6, :32] = np.frombuffer(_ID_ENC, np.uint8)         # small-order R
    msgs[7, 0] ^= 0xA5                        # tampered message
    msgs, sigs, pubs = jnp.asarray(msgs), jnp.asarray(sigs), jnp.asarray(pubs)

    want = np.asarray(ed.verify_batch(msgs, lens, sigs, pubs))
    got = np.asarray(ed.verify_batch_antipa(msgs, lens, sigs, pubs))
    assert want[0] and not want[1:8].any()    # the corpus is mixed
    assert got.tolist() == want.tolist()


@pytest.mark.slow
def test_antipa_is_jittable():
    """The whole antipa chain — divstep halving included — must trace:
    a host half_gcd (the round-6 kill) would raise under jit.  Verdicts
    must not change between eager and compiled execution."""
    msgs, lens, sigs, pubs = make_example_batch(
        4, 64, valid=True, sign_pool=2, seed=77)
    sigs = np.asarray(sigs).copy()
    sigs[3, 40] ^= 0x10
    sigs = jnp.asarray(sigs)
    eager = np.asarray(ed.verify_batch_antipa(msgs, lens, sigs, pubs))
    jitted = np.asarray(jax.jit(ed.verify_batch_antipa)(
        msgs, lens, sigs, pubs))
    assert eager.tolist() == [True, True, True, False]
    assert jitted.tolist() == eager.tolist()


def _forge_with_torsion(seed: bytes, msg: bytes, t_pt):
    """Build (sig, pub) whose verification defect [S]B - [k]A - R is
    exactly -T:  R = [r]B + T with honest S = r + k*a.  Strict verify
    must reject (T != identity); antipa accepts iff ord(T) | v."""
    pub, a, _ = ed.keypair_from_seed(seed)
    r = int.from_bytes(hashlib.sha512(b"forge" + seed + msg).digest(),
                       "little") % L
    r_pt = ed._pt_add_host(ed._scalar_mul_base_host(r), t_pt)
    rb = ed._compress_host(r_pt)
    k = int.from_bytes(hashlib.sha512(rb + pub + msg).digest(),
                       "little") % L
    s = (r + k * a) % L
    return rb + s.to_bytes(32, "little"), pub, k


def test_torsion_laxity_enumerated():
    """The exhaustive enumeration of where antipa may legally diverge
    from strict: defects in E[2] and E[4].  Everything else in this
    suite asserts bit-parity; these rows assert that the divergence is
    exactly the ord(T) | v predicate, decided by the same device v the
    verifier uses."""
    # order-2 and order-4 torsion in extended coords (X, Y, Z, T)
    t2 = (0, P - 1, 1, 0)
    x4 = pow(2, (P - 1) // 4, P)     # sqrt(-1); y = 0 on the curve
    t4 = (x4, 0, 1, 0)
    # sanity: claimed orders
    assert ed._pt_add_host(t2, t2)[0] % P == 0
    d4 = ed._pt_add_host(t4, t4)
    assert (d4[1] + d4[2]) % P == 0 and d4[0] % P == 0    # [2]T4 = T2-ish
    orders = [2, 4, 1]

    maxlen = 64
    msgs = np.zeros((3, maxlen), np.uint8)
    lens = np.full((3,), 32, np.int32)
    sigs = np.zeros((3, 64), np.uint8)
    pubs = np.zeros((3, 32), np.uint8)
    ks = []
    for i, t_pt in enumerate([t2, t4, (0, 1, 1, 0)]):   # last = honest row
        msg = bytes(range(32))
        sig, pub, k = _forge_with_torsion(bytes([i + 9] * 32), msg, t_pt)
        msgs[i, :32] = np.frombuffer(msg, np.uint8)
        sigs[i] = np.frombuffer(sig, np.uint8)
        pubs[i] = np.frombuffer(pub, np.uint8)
        ks.append(k)

    kb = np.zeros((3, 32), np.uint8)
    for i, k in enumerate(ks):
        kb[i] = np.frombuffer(k.to_bytes(32, "little"), np.uint8)
    _, av_l, _ = sc.halve_scalar(sc.bytes_to_limbs(jnp.asarray(kb), 22))
    av_l = np.asarray(av_l)
    avs = [sum(int(av_l[j, i]) << (12 * j) for j in range(22))
           for i in range(3)]

    msgs, sigs, pubs = jnp.asarray(msgs), jnp.asarray(sigs), jnp.asarray(pubs)
    strict = np.asarray(ed.verify_batch(msgs, lens, sigs, pubs))
    antipa = np.asarray(ed.verify_batch_antipa(msgs, lens, sigs, pubs))

    assert strict.tolist() == [False, False, True]
    expect = [avs[i] % orders[i] == 0 for i in range(3)]
    assert antipa.tolist() == expect
    # host cross-check that the torsion rows really are the documented
    # laxity (host strict verify agrees with device strict verify), and
    # that the antipa host twin reproduces the device antipa bits —
    # the GuardedVerifier degraded-mode contract for antipa mode
    for i in range(3):
        sig_b = bytes(np.asarray(sigs[i]))
        pub_b = bytes(np.asarray(pubs[i]))
        assert ed.verify_one_host(sig_b, bytes(range(32)),
                                  pub_b) == bool(strict[i])
        assert ed.verify_one_host_antipa(sig_b, bytes(range(32)),
                                         pub_b) == bool(antipa[i])


def test_guarded_fallback_routes_by_mode():
    """A degraded antipa-mode verifier must fall back to the antipa
    HOST twin, not the strict one: on a torsion forgery the two host
    backends can disagree (that is the whole laxity), so mode routing
    is observable.  Host-only — no device graphs compile here."""
    from firedancer_tpu.disco.pipeline import GuardedVerifier

    # order-2 torsion forgery with an even-v k: antipa accepts, strict
    # rejects.  Search a few nonce seeds for the even-v case (v odd
    # rejects in both modes and would not discriminate the routing).
    t2 = (0, P - 1, 1, 0)
    msg = bytes(range(32))
    for tag in range(64):
        sig, pub, k = _forge_with_torsion(bytes([tag]) + bytes(31), msg, t2)
        _, v = ed._divstep_halve_host(k)
        if v % 2 == 0:
            break
    else:  # pragma: no cover - 2^-64 miss odds
        raise AssertionError("no even-v nonce found")
    assert not ed.verify_one_host(sig, msg, pub)
    assert ed.verify_one_host_antipa(sig, msg, pub)

    msgs = np.zeros((1, 64), np.uint8)
    msgs[0, :32] = np.frombuffer(msg, np.uint8)
    lens = np.full((1,), 32, np.int32)
    sigs = np.frombuffer(sig, np.uint8).reshape(1, 64)
    pubs = np.frombuffer(pub, np.uint8).reshape(1, 32)

    class _Dead:
        def __init__(self, mode):
            self.mode = mode

        def __call__(self, *a):
            raise RuntimeError("device gone")

    verdicts = {}
    for mode in ("strict", "antipa"):
        g = GuardedVerifier(_Dead(mode), fail_threshold=1, retries=0)
        verdicts[mode] = bool(g(msgs, lens, sigs, pubs)[0])
        assert g.degraded
    assert verdicts == {"strict": False, "antipa": True}
