"""Antipa halved-scalar strict verify (round-6 go/no-go lever).

verify_batch_antipa must reproduce verify_batch's bits on honest and
corrupted signatures (the torsion-adversarial caveat is documented on
the function; these are the cases the lever would ever serve).
"""

import numpy as np
import jax.numpy as jnp

from firedancer_tpu.models.verifier import make_example_batch
from firedancer_tpu.ops import ed25519 as ed
from firedancer_tpu.ops import scalar25519 as sc

BATCH = 16


def test_halve_scalar_invariant():
    rng = np.random.default_rng(41)
    ks = [int.from_bytes(rng.bytes(32), "little") % sc.L
          for _ in range(64)]
    ks[:3] = [0, 1, sc.L - 1]
    for k in ks:
        u, v = ed._halve_scalar_host(k)
        assert 0 <= u < (1 << 127)
        assert v != 0 and abs(v) < (1 << 127)
        assert u % sc.L == (k * v) % sc.L, hex(k)


def test_antipa_matches_verify_batch():
    msgs, lens, sigs, pubs = make_example_batch(
        BATCH, 96, valid=True, sign_pool=8, seed=51)
    sigs = np.asarray(sigs).copy()
    pubs = np.asarray(pubs).copy()
    sigs[1, 5] ^= 0xFF                        # tampered R
    sigs[2, 32] ^= 0x01                       # tampered S
    sigs[3, 63] |= 0x80                       # non-canonical S
    pubs[4] = np.frombuffer(bytes([0x07] * 32), np.uint8)   # bad A
    sigs, pubs = jnp.asarray(sigs), jnp.asarray(pubs)

    want = np.asarray(ed.verify_batch(msgs, lens, sigs, pubs))
    got = np.asarray(ed.verify_batch_antipa(msgs, lens, sigs, pubs))
    assert want[0] and not want[1:5].any()    # the corpus is mixed
    assert got.tolist() == want.tolist()
