"""Fleet fault-tolerance unit tests (fast tier, round 17): consistent-
hash steering determinism across host respawn, sig-digest gossip + the
RecentSigCache replay reject on a non-owner host, sharded-tcache foreign
dedup, dedup preload file parsing, drain-manifest corruption fallback,
the stale-pidfile drain guard, fleet fault-grammar parsing, and per-host
config isolation.

Everything multi-process (real host SIGKILL -> failover -> exactly-once
fleet ledger) lives in tools/chaos_smoke.py --fleet (the `fleet` ci.sh
tier)."""

import json
import os
import time

import pytest

from firedancer_tpu.disco import faultinject
from firedancer_tpu.disco import fleet as fleet_mod
from firedancer_tpu.flamenco import gossip as gossip_mod
from firedancer_tpu.tango.tcache import ShardedTCache
from firedancer_tpu.waltz.pkteng import PeerSteer, SteerRing

# -- consistent-hash steering ------------------------------------------------


def test_steer_ring_determinism_across_respawn():
    """Ring points derive only from host identity: a host that leaves
    and re-joins owns exactly its old arcs, and every other arc is
    untouched — a rebooted host resumes its old shard set."""
    hosts = [f"h{i}" for i in range(4)]
    ring = SteerRing(hosts, vnodes=64)
    peers = [("10.0.%d.%d" % (i >> 8, i & 255), 8000 + i)
             for i in range(512)]
    before = {p: ring.owner_of_peer(*p) for p in peers}
    shards_before = {s: ring.shard_owner(s, 4) for s in range(16)}
    ring.remove_host("h2")
    assert all(ring.owner_of_peer(*p) != "h2" for p in peers)
    ring.add_host("h2")
    after = {p: ring.owner_of_peer(*p) for p in peers}
    assert before == after
    assert shards_before == {s: ring.shard_owner(s, 4) for s in range(16)}


def test_steer_ring_removal_matches_survivor_ring():
    """Removing a host must leave the exact ring a fresh boot of the
    survivors would build — steering re-convergence is deterministic,
    not path-dependent."""
    ring = SteerRing(["h0", "h1", "h2"], vnodes=64)
    ring.remove_host("h1")
    fresh = SteerRing(["h0", "h2"], vnodes=64)
    for i in range(256):
        tag = (i * 0x9E3779B97F4A7C15) & ((1 << 64) - 1)
        assert ring.owner_of_sig(tag) == fresh.owner_of_sig(tag)
    for s in range(16):
        assert ring.shard_owner(s, 4) == fresh.shard_owner(s, 4)


def test_steer_ring_shards_partition():
    """Shard ownership is a partition: every shard owned by exactly one
    host, union covers the keyspace."""
    hosts = ["h0", "h1", "h2"]
    ring = SteerRing(hosts, vnodes=64)
    seen = {}
    for h in hosts:
        for s in ring.owned_shards(h, 4):
            assert s not in seen, f"shard {s} owned twice"
            seen[s] = h
    assert sorted(seen) == list(range(16))
    for s in range(16):
        assert ring.shard_owner(s, 4) == seen[s]


def test_peer_steer_bounces_missteered_and_fails_open():
    ring = SteerRing(["h0", "h1"], vnodes=64)
    bounced = []
    steer = PeerSteer(
        ring, "h0",
        bounce_fn=lambda ip, port, owner: bounced.append((ip, owner))
        or b"retry")
    admitted = misrouted = 0
    for i in range(256):
        ok, tok = steer.admit(f"10.0.0.{i % 250}", 1000 + i)
        if ok:
            admitted += 1
            assert tok is None
        else:
            misrouted += 1
            assert tok == b"retry"
    assert admitted and misrouted
    assert steer.admit_cnt == admitted and steer.bounce_cnt == misrouted
    assert len(bounced) == misrouted
    # empty ring (every host lost): fail open, never drop ingest
    empty = PeerSteer(SteerRing([], vnodes=64), "h0",
                      bounce_fn=lambda ip, port, owner: b"retry")
    ok, tok = empty.admit("10.0.0.1", 5)
    assert ok and tok is None and empty.orphan_cnt == 1


# -- sig-digest gossip + replay reject ---------------------------------------


def _mk_digest_value(origin: bytes, shard: int, seq: int, tags):
    body = gossip_mod.sig_digest_body(shard, seq, tags, bloom_seed=7)
    return gossip_mod.CrdsValue(
        kind=gossip_mod.KIND_SIG_DIGEST, origin=origin, body=body,
        wallclock_ms=0, signature=b"\0" * 64)


def test_sig_digest_roundtrip_and_torn():
    tags = [0xDEAD0000_0000_0000 + i for i in range(100)]
    body = gossip_mod.sig_digest_body(3, 9, tags, bloom_seed=1)
    shard, seq, got, bloom = gossip_mod.sig_digest_parse(body)
    assert (shard, seq) == (3, 9) and got == tags
    assert all(t.to_bytes(8, "little") in bloom for t in tags)
    with pytest.raises(ValueError):
        gossip_mod.sig_digest_parse(body[:-3])     # torn tail
    with pytest.raises(ValueError):
        gossip_mod.sig_digest_parse(b"\x01")       # torn header


def test_recent_sig_cache_rejects_replay_on_non_owner_host():
    """The failover contract: host B (not the owner, never saw the txn)
    folds host A's gossiped digest and can reject a replayed sig with
    EXACT confidence — 'maybe' (bloom-only) is advisory, never a drop
    verdict, so a false positive can't lose a verdict."""
    cache = gossip_mod.RecentSigCache()
    verdicted = [0xA000_0000_0000_0000 + i for i in range(300)]
    v = _mk_digest_value(b"A" * 32, shard=0, seq=0, tags=verdicted)
    assert cache.fold(v) == len(verdicted)
    assert cache.fold(v) == 0                       # per-chunk idempotent
    for t in verdicted:
        assert cache.seen(t) == "exact"
    # a tag host A never verdicted: must NOT come back "exact"
    assert cache.seen(0xB000_0000_0000_0000) != "exact"
    assert set(cache.exact_tags()) == set(verdicted)
    # torn digest body: counted, never folded, never raises
    torn = gossip_mod.CrdsValue(
        kind=gossip_mod.KIND_SIG_DIGEST, origin=b"A" * 32,
        body=b"\x02\x00", wallclock_ms=0, signature=b"\0" * 64)
    before = cache.torn_cnt
    assert cache.fold(torn) == 0
    assert cache.torn_cnt == before + 1


def test_sharded_tcache_foreign_still_dedups():
    """Mis-steered (foreign-shard) tags still dedup — fail-safe — but
    are counted so fleet top can surface steering skew."""
    tc = ShardedTCache(1 << 10, shard_bits=2, owned={0, 1}, native=False)
    own_tag = 0x0000_0000_0000_0001        # shard 0
    foreign = 0xC000_0000_0000_0001        # shard 3
    assert tc.insert(own_tag) is False and tc.insert(own_tag) is True
    assert tc.foreign_cnt == 0
    assert tc.insert(foreign) is False and tc.insert(foreign) is True
    assert tc.foreign_cnt == 2


def test_dedup_preload_file_parsing(tmp_path):
    """The failover preload surface: one u64 hex tag per line; torn
    lines (writer died mid-append) and garbage skipped; missing file
    swallowed — preload must never wedge a restart."""
    from firedancer_tpu.disco.tiles import DedupTile

    class _Metrics:
        def __init__(self):
            self.vals = {}

        def add(self, k, n=1):
            self.vals[k] = self.vals.get(k, 0) + n

        def set(self, k, v):
            self.vals[k] = v

    class _Ctx:
        def __init__(self, cfg):
            self.cfg = cfg
            self.metrics = _Metrics()

    p = tmp_path / "preload.tags"
    tags = [0x1111_0000_0000_0000 + i for i in range(10)]
    p.write_text("".join("%016x\n" % t for t in tags)
                 + "not-hex\n" + "%08x" % 0xAB)     # garbage + torn tail
    tile = DedupTile()
    ctx = _Ctx({"preload_tags_path": str(p), "tcache_depth": 1 << 10})
    tile.init(ctx)
    assert ctx.metrics.vals["preload_cnt"] >= len(tags)
    for t in tags:
        assert tile.tcache.insert(t) is True        # preloaded -> dup
    # missing file: clean boot, zero preloaded
    tile2 = DedupTile()
    ctx2 = _Ctx({"preload_tags_path": str(tmp_path / "nope.tags"),
                 "tcache_depth": 1 << 10})
    tile2.init(ctx2)
    assert "preload_cnt" not in ctx2.metrics.vals


# -- drain-manifest corruption fallback --------------------------------------


def _stub_run(manifest_dir: str):
    from firedancer_tpu.disco.run import SupervisionPolicy, TopoRun
    run = TopoRun.__new__(TopoRun)          # validation needs only policy
    run.policy = SupervisionPolicy(drain_manifest_dir=manifest_dir)
    return run


def test_load_drain_manifest_validation(tmp_path):
    run = _stub_run(str(tmp_path))
    path = tmp_path / "v_0.manifest.json"
    good = {"tile": "v:0", "kind": "verify", "restart_cnt": 0,
            "knob_gen": 0, "cursors": {"a_b": 6}, "outs": {"b_c": 3}}
    path.write_text(json.dumps(good))
    assert run._load_drain_manifest("v:0")["cursors"] == {"a_b": 6}
    # torn JSON (truncated mid-write)
    path.write_text(json.dumps(good)[:25])
    with pytest.raises(ValueError, match="torn"):
        run._load_drain_manifest("v:0")
    # wrong tile's manifest under our name
    path.write_text(json.dumps(dict(good, tile="other")))
    with pytest.raises(ValueError, match="mismatch"):
        run._load_drain_manifest("v:0")
    # non-integer cursors
    path.write_text(json.dumps(dict(good, cursors={"a_b": "six"})))
    with pytest.raises(ValueError, match="cursors"):
        run._load_drain_manifest("v:0")
    path.write_text(json.dumps(dict(good, outs={"b_c": -1})))
    with pytest.raises(ValueError, match="outs"):
        run._load_drain_manifest("v:0")
    # absent file / unconfigured dir: None, not an error
    os.unlink(path)
    assert run._load_drain_manifest("v:0") is None
    assert _stub_run("")._load_drain_manifest("v:0") is None


def test_rolling_restart_corrupt_manifest_falls_back(tmp_path,
                                                     monkeypatch):
    """A drain that 'succeeds' but leaves a torn manifest must NOT be
    trusted: rolling_restart counts manifest_corrupt_cnt and degrades to
    the crash-eviction respawn — the topology recovers either way."""
    from firedancer_tpu.disco.run import TopoRun
    from firedancer_tpu.disco.topo import TopoBuilder
    spec = (
        TopoBuilder(f"fmc{os.getpid()}", wksp_mb=8)
        .link("s_k", depth=64, mtu=256)
        .tile("source", "source", outs=["s_k"], count=4)
        .tile("sink", "sink", ins=["s_k"])
        .build()
    )
    man = tmp_path / "sink.manifest.json"
    man.write_text('{"tile": "sink", "cursors": {"s_k"')   # torn
    with TopoRun(spec) as run:
        run.wait_ready(timeout=60)
        run.policy.drain_manifest_dir = str(tmp_path)
        # isolate the unit under test: receipt validation + fallback
        # (the drain protocol itself is chaos/test_supervision ground)
        monkeypatch.setattr(TopoRun, "drain_tile",
                            lambda self, name, t: True)
        old_pid = run.procs["sink"].pid
        # corrupt receipt -> NOT a graceful rolling restart (False), but
        # the tile is respawned via the crash-eviction fallback
        assert run.rolling_restart("sink") is False
        assert run.manifest_corrupt_cnt == 1
        assert run.procs["sink"].pid != old_pid
        fams = {f[0] for f in run._extra_families()}
        assert "fdtpu_manifest_corrupt_cnt" in fams
        deadline = time.monotonic() + 30
        while run.poll() is not None and time.monotonic() < deadline:
            time.sleep(0.05)
        assert run.poll() is None


# -- stale-pidfile drain guard -----------------------------------------------


def test_stale_pidfile_never_signals_recycled_pid(tmp_path):
    """`fdtpuctl drain` preconditions: only a pid that is alive AND
    demonstrably the writer of the pidfile may be SIGTERMed.  A live but
    RECYCLED pid (process started after the pidfile was written) must
    read as stale -> the caller falls through to cnc-direct."""
    from firedancer_tpu.app.fdtpuctl import (_live_supervisor_pid,
                                             _proc_start_time)
    pf = str(tmp_path / "fdtpu_x.pid")
    # our own pid, fresh file: accepted
    with open(pf, "w") as f:
        f.write(str(os.getpid()))
    assert _live_supervisor_pid(pf) == os.getpid()
    # recycled: file written long before this process started
    old = time.time() - 3600.0
    os.utime(pf, (old, old))
    assert _live_supervisor_pid(pf) == 0
    # dead pid
    with open(pf, "w") as f:
        f.write("999999")
    assert _live_supervisor_pid(pf) == 0
    # garbage / missing
    with open(pf, "w") as f:
        f.write("not-a-pid")
    assert _live_supervisor_pid(pf) == 0
    os.unlink(pf)
    assert _live_supervisor_pid(pf) == 0
    st = _proc_start_time(os.getpid())
    if st is not None:                       # /proc present (linux CI)
        assert abs(time.time() - st) < 7 * 24 * 3600


# -- fleet fault grammar -----------------------------------------------------


def test_fleet_faults_parse_and_gating():
    cfg = {"development": {"bench_seed": 42}}
    env = {"FDTPU_FAULTS": "fleet=host_kill:1,after_capture:50,boot:0"}
    f = faultinject.fleet_faults(env, cfg, 0)
    assert f is not None and f.host_kill == 1
    assert not f.should_kill(0, 10_000)          # wrong host
    assert not f.should_kill(1, 10)              # below threshold
    assert faultinject.fleet_faults(env, cfg, 1) is None   # gen-gated
    assert faultinject.fleet_faults({}, cfg, 0) is None
    p = faultinject.fleet_faults(
        {"FDTPU_FAULTS": "fleet=partition:0-2+1-2"}, cfg, 0)
    assert p.partitioned(0, 2) and p.partitioned(2, 0)
    assert p.partitioned(1, 2) and not p.partitioned(0, 1)
    assert p.partition_peers(2) == {0, 1}


# -- per-host config + ledger ------------------------------------------------


def test_host_cfg_isolation(tmp_path):
    from firedancer_tpu.app import config as config_mod
    base = config_mod.load(None)
    base["fleet"] = dict(base.get("fleet") or {}, hosts=3)
    cfgs = [fleet_mod.host_cfg(base, i, str(tmp_path)) for i in range(3)]
    names = {c["name"] for c in cfgs}
    seeds = {c["development"]["bench_seed"] for c in cfgs}
    caps = {c["tiles"]["sink"]["capture_path"] for c in cfgs}
    mans = {c["supervision"]["drain_manifest_dir"] for c in cfgs}
    assert len(names) == len(seeds) == len(caps) == len(mans) == 3
    # graceful-drain budget always armed for fleet hosts
    assert all(c["supervision"]["drain_timeout_s"] > 0 for c in cfgs)
    # dedup shard ownership partitions the shard space across hosts
    shards = [set(c["tiles"]["dedup"]["shard_own"]) for c in cfgs]
    assert set().union(*shards) == set(range(16))
    assert sum(len(s) for s in shards) == 16
    # hosts=1 keeps the fleet layer inert
    base1 = config_mod.load(None)
    with pytest.raises(ValueError):
        fleet_mod.FleetRun(base1, str(tmp_path), start=False)


def test_capture_tags_tolerates_torn_tail(tmp_path):
    p = str(tmp_path / "h0.cap")
    recs = [(0x10 + i, b"x" * (20 + i)) for i in range(5)]
    with open(p, "wb") as f:
        for tag, payload in recs:
            f.write(tag.to_bytes(8, "little")
                    + len(payload).to_bytes(4, "little") + payload)
        # SIGKILL mid-append: header promises more bytes than exist
        f.write((0x99).to_bytes(8, "little")
                + (1000).to_bytes(4, "little") + b"partial")
    assert fleet_mod.capture_tags(p) == [t for t, _ in recs]
    assert fleet_mod.capture_tags(str(tmp_path / "absent.cap")) == []


def test_stream_universe_matches_source_streams(tmp_path):
    from firedancer_tpu.app import config as config_mod
    from firedancer_tpu.disco.tiles import source_txn_stream
    base = config_mod.load(None)
    base["development"]["source_count"] = 20
    base["development"]["bench_seed"] = 7
    specs = [fleet_mod.host_stream_spec(base, i) for i in range(2)]
    assert specs[0]["seed"] != specs[1]["seed"]
    uni = fleet_mod.stream_universe(specs)
    assert len(uni) == 40
    direct = {t for t, _ in source_txn_stream(specs[1]["seed"], 4, 20)}
    assert {t for t, h in uni.items() if h == 1} == direct
