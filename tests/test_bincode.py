"""Bincode engine + consensus-type layouts (flamenco/bincode.py; role of
the reference's generated fd_types round-trip tests)."""

import pytest

from firedancer_tpu.flamenco import bincode as bc


def test_scalars_roundtrip():
    for kind, v in (("u8", 255), ("u16", 65535), ("u32", 1 << 31),
                    ("u64", (1 << 63) + 5), ("i64", -42), ("bool", True),
                    ("f64", 0.25)):
        assert bc.loads(kind, bc.encode(kind, v)) == v


def test_compound_roundtrip():
    schema = ("struct", (
        ("a", ("option", "u64")),
        ("b", ("vec", ("bytes", 4))),
        ("c", ("string",)),
        ("d", ("enum", (("x", None), ("y", "u32")))),
    ))
    for val in (
        {"a": None, "b": [], "c": "", "d": ("x", None)},
        {"a": 7, "b": [b"abcd", b"wxyz"], "c": "héllo", "d": ("y", 9)},
    ):
        assert bc.loads(schema, bc.encode(schema, val)) == val


def test_known_encodings():
    """Pin the exact upstream bincode byte layout."""
    assert bc.encode("u64", 1) == bytes([1, 0, 0, 0, 0, 0, 0, 0])
    assert bc.encode(("option", "u8"), None) == b"\x00"
    assert bc.encode(("option", "u8"), 3) == b"\x01\x03"
    assert bc.encode(("vec", "u16"), [5]) \
        == bytes([1, 0, 0, 0, 0, 0, 0, 0, 5, 0])
    assert bc.encode(("string",), "ab") \
        == bytes([2, 0, 0, 0, 0, 0, 0, 0]) + b"ab"
    assert bc.encode(("enum", (("a", None), ("b", "u8"))), ("b", 9)) \
        == bytes([1, 0, 0, 0, 9])


def test_malformed_rejection():
    with pytest.raises(bc.BincodeError):
        bc.loads("u64", b"\x01\x02")                      # truncated
    with pytest.raises(bc.BincodeError):
        bc.loads(("option", "u8"), b"\x02\x00")           # bad tag
    with pytest.raises(bc.BincodeError):
        bc.loads("bool", b"\x07")
    with pytest.raises(bc.BincodeError):
        bc.loads(("vec", "u8"), bytes([255] * 8))         # absurd length
    with pytest.raises(bc.BincodeError):
        bc.loads("u8", b"\x01\x00")                       # trailing bytes


def _mk_vote_state_current():
    pk = bytes(range(32))
    return ("current", {
        "node_pubkey": pk,
        "authorized_withdrawer": pk[::-1],
        "commission": 5,
        "votes": [
            {"latency": 1,
             "lockout": {"slot": 100 + i, "confirmation_count": 31 - i}}
            for i in range(31)
        ],
        "root_slot": 99,
        "authorized_voters": [{"epoch": 0, "pubkey": pk}],
        "prior_voters": {
            "buf": [{"pubkey": bytes(32), "epoch_start": 0, "epoch_end": 0}
                    for _ in range(32)],
            "idx": 31,
            "is_empty": True,
        },
        "epoch_credits": [
            {"epoch": 3, "credits": 1000, "prev_credits": 900}],
        "last_timestamp": {"slot": 130, "timestamp": 1700000000},
    })


def test_vote_state_versioned_roundtrip():
    v = _mk_vote_state_current()
    raw = bc.encode(bc.VOTE_STATE_VERSIONED, v)
    assert bc.loads(bc.VOTE_STATE_VERSIONED, raw) == v
    # discriminant 2 == "current" (fd_vote_state_versioned ordering)
    assert raw[:4] == bytes([2, 0, 0, 0])


def test_stake_state_v2_roundtrip():
    pk = bytes(range(32))
    v = ("stake", {
        "meta": {
            "rent_exempt_reserve": 2282880,
            "authorized": {"staker": pk, "withdrawer": pk},
            "lockup": {"unix_timestamp": 0, "epoch": 0,
                       "custodian": bytes(32)},
        },
        "stake": {
            "delegation": {
                "voter_pubkey": pk[::-1],
                "stake": 5_000_000_000,
                "activation_epoch": 7,
                "deactivation_epoch": 2**64 - 1,
                "warmup_cooldown_rate": 0.25,
            },
            "credits_observed": 12345,
        },
        "stake_flags": 0,
    })
    raw = bc.encode(bc.STAKE_STATE_V2, v)
    assert bc.loads(bc.STAKE_STATE_V2, raw) == v
    assert raw[:4] == bytes([2, 0, 0, 0])
    # upstream StakeStateV2::Stake account size is 200 bytes total when
    # padded; the bincode payload itself is 4 + 120 + 72 + 1
    assert len(raw) == 197


def test_sysvar_layouts():
    clock = {"slot": 5, "epoch_start_timestamp": 100, "epoch": 0,
             "leader_schedule_epoch": 1, "unix_timestamp": 105}
    raw = bc.encode(bc.SYSVAR_CLOCK, clock)
    assert len(raw) == 40
    assert bc.loads(bc.SYSVAR_CLOCK, raw) == clock

    sched = {"slots_per_epoch": 432000, "leader_schedule_slot_offset":
             432000, "warmup": False, "first_normal_epoch": 0,
             "first_normal_slot": 0}
    raw = bc.encode(bc.SYSVAR_EPOCH_SCHEDULE, sched)
    assert len(raw) == 33
    assert bc.loads(bc.SYSVAR_EPOCH_SCHEDULE, raw) == sched

    sh = [{"slot": 9, "hash": bytes(32)}] * 3
    raw = bc.encode(bc.SYSVAR_SLOT_HASHES, sh)
    assert len(raw) == 8 + 3 * 40
    assert bc.loads(bc.SYSVAR_SLOT_HASHES, raw) == sh


def test_varint_roundtrip():
    for v in (0, 1, 127, 128, 300, 1 << 20, (1 << 63) - 1, (1 << 64) - 1):
        raw = bc.encode("varint", v)
        got, off = bc.decode("varint", raw)
        assert (got, off) == (v, len(raw)), v


def test_varint_rejects_overflow():
    """serde_varint strictness (Agave varint.rs): accumulated value must
    fit u64."""
    import pytest
    # 2^64 exactly: 10 bytes, final payload 2 at shift 63
    with pytest.raises(bc.BincodeError):
        bc.decode("varint", bytes([0x80] * 9 + [0x02]))
    # an 11th byte (shift 70) regardless of payload
    with pytest.raises(bc.BincodeError):
        bc.decode("varint", bytes([0x80] * 10 + [0x01]))
    # max u64 still decodes: 9 x 0xFF + 0x01
    got, _ = bc.decode("varint", bytes([0xFF] * 9 + [0x01]))
    assert got == (1 << 64) - 1


def test_varint_rejects_non_minimal():
    """A zero FINAL byte after a continuation re-encodes shorter; Agave
    errors instead of accepting the alias.  Middle zero-payload bytes
    stay legal (128 is 80 01; 2^14 is 80 80 01)."""
    import pytest
    with pytest.raises(bc.BincodeError):
        bc.decode("varint", bytes([0x81, 0x00]))          # 1, padded
    with pytest.raises(bc.BincodeError):
        bc.decode("varint", bytes([0xFF, 0x80, 0x00]))    # trailing group
    assert bc.decode("varint", bytes([0x80, 0x01]))[0] == 128
    assert bc.decode("varint", bytes([0x80, 0x80, 0x01]))[0] == 1 << 14
    assert bc.decode("varint", bytes([0x00]))[0] == 0     # bare zero ok


def test_varint_truncated():
    import pytest
    with pytest.raises(bc.BincodeError):
        bc.decode("varint", bytes([0x80]))
