"""Async verify data plane (VERDICT r2 #3 / wiredancer's contract,
src/wiredancer/c/wd_f1.h:85-113): a filled batch is dispatched without
blocking the submitter, up to N batches ride the device queue, verdicts
are harvested on completion.

The device is simulated with a fixed-latency future so the test measures
the ARCHITECTURE (overlap, ordering, bounded queue) deterministically on
CPU: with batch latency L and B batches, the sync path costs ~B*L while
the async path costs ~L + submit overhead."""

import time

import numpy as np

from firedancer_tpu.disco.pipeline import VerifyPipeline
from tests.test_pipeline import make_signed_txn

BATCH = 4
LAT_S = 0.03


class _FakeResult:
    """Device-future stand-in: ready after a fixed latency; np.asarray
    blocks until ready (the jax.Array contract the pipeline relies on)."""

    def __init__(self, arr, ready_at):
        self._arr = arr
        self._ready_at = ready_at

    def is_ready(self):
        return time.monotonic() >= self._ready_at

    def __array__(self, dtype=None, copy=None):
        while not self.is_ready():
            time.sleep(0.001)
        return self._arr if dtype is None else self._arr.astype(dtype)


def _fake_verify(msgs, lens, sigs, pubs):
    n = np.asarray(msgs).shape[0]
    return _FakeResult(np.ones((n,), dtype=bool), time.monotonic() + LAT_S)


def _drive(max_inflight, n_txns):
    p = VerifyPipeline(_fake_verify, batch=BATCH, msg_maxlen=256,
                       tcache_depth=256, max_inflight=max_inflight)
    txns = [make_signed_txn(5000 + i) for i in range(n_txns)]
    t0 = time.monotonic()
    passed = []
    max_submit = 0.0
    for t in txns:
        s0 = time.monotonic()
        passed += p.submit(t)
        max_submit = max(max_submit, time.monotonic() - s0)
    passed += p.flush()
    wall = time.monotonic() - t0
    return p, passed, wall, max_submit, txns


def test_async_overlaps_device_latency():
    n = BATCH * 10
    _, passed_sync, wall_sync, _, txns_s = _drive(0, n)
    # queue depth > batch count: no submit ever hits the bound
    p, passed_async, wall_async, max_submit, txns_a = _drive(16, n)

    # every txn verdict arrives exactly once, in dispatch order
    assert [pl for pl, _ in passed_sync] == txns_s
    assert [pl for pl, _ in passed_async] == txns_a
    assert p.metrics.verify_pass == n and p.metrics.verify_fail == 0
    assert not p.inflight

    # the architecture claim: 10 batches of 30 ms latency cost ~300 ms
    # synchronously but overlap down to ~1 latency + submit overhead
    assert wall_sync > 9 * LAT_S, wall_sync
    assert wall_async < wall_sync / 3, (wall_async, wall_sync)
    # no single submit ever blocked on the device
    assert max_submit < LAT_S / 2, max_submit


def test_async_bounded_queue_blocks_at_depth():
    """With max_inflight=1 the queue retires the oldest batch before
    accepting a third: wall time degrades toward sync, proving the bound
    is enforced (the tile can never run unboundedly ahead of the device)."""
    n = BATCH * 6
    p, passed, wall, _, _ = _drive(1, n)
    assert len(passed) == n
    # 6 batches, queue depth 1: >= ~4 latencies must have been absorbed
    assert wall > 3 * LAT_S, wall


def test_async_age_dispatch_open():
    """dispatch_open() sends a partial bucket without blocking and the
    verdicts surface on a later harvest."""
    p = VerifyPipeline(_fake_verify, batch=BATCH, msg_maxlen=256,
                       tcache_depth=64, max_inflight=4)
    t = make_signed_txn(9000)
    assert p.submit(t) == []
    s0 = time.monotonic()
    assert p.dispatch_open() == []          # dispatched, not waited
    assert time.monotonic() - s0 < LAT_S / 2
    assert p.harvest() == []                # not ready yet
    time.sleep(LAT_S * 1.5)
    out = p.harvest()
    assert [pl for pl, _ in out] == [t]
    assert not p.has_pending


def test_packed_dispatch_matches_call():
    """The single-blob packed dispatch must produce identical per-lane
    verdicts to the 4-array path, including trimmed message columns."""
    import numpy as np

    from firedancer_tpu.models.verifier import SigVerifier, VerifierConfig

    v = SigVerifier(VerifierConfig(batch=16, msg_maxlen=256))
    msgs, lens, sigs, pubs = v.example_args()
    sigs = np.asarray(sigs).copy()
    sigs[5, 2] ^= 1  # one bad lane
    want = np.asarray(v(msgs, lens, sigs, pubs))
    got = np.asarray(v.packed_dispatch(msgs, lens, sigs, pubs))
    assert got.tolist() == want.tolist()
    got_trim = np.asarray(v.packed_dispatch(
        msgs, lens, sigs, pubs, ml=int(np.asarray(lens).max())))
    assert got_trim.tolist() == want.tolist()
    assert not want[5] and want[4]


def test_packed_layout_constants_agree():
    """pipeline._Bucket mirrors ops.ed25519.PACKED_EXTRA without the jax
    import; the two must never diverge (single-layout contract)."""
    from firedancer_tpu.disco.pipeline import _Bucket
    from firedancer_tpu.ops import ed25519 as ed

    assert _Bucket.PACKED_EXTRA == ed.PACKED_EXTRA
