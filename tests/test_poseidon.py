"""Poseidon-BN254 conformance (ballet/poseidon.py).

Golden vectors are the reference's own (src/ballet/bn254/test_poseidon.c,
which pins light-poseidon 0.1.2 behavior); the ARK tables are
Grain-LFSR-generated here and checked byte-identical against the
reference's baked table for width 2."""

import pytest

from firedancer_tpu.ballet import poseidon


GOLD_1X32_LE = bytes([
    230, 117, 27, 127, 210, 224, 145, 185, 157, 99, 172, 7, 132, 30, 241,
    130, 136, 166, 99, 99, 197, 198, 25, 204, 119, 97, 238, 129, 229, 172,
    191, 5])
GOLD_2X32_BE = bytes([
    13, 84, 225, 147, 143, 138, 140, 28, 125, 235, 94, 3, 85, 242, 99, 25,
    32, 123, 132, 254, 156, 162, 206, 27, 38, 231, 53, 200, 41, 130, 25,
    144])
GOLD_ONES_BE = bytes([
    0, 122, 243, 70, 226, 211, 4, 39, 158, 121, 224, 169, 243, 2, 63, 119,
    18, 148, 167, 138, 203, 112, 231, 63, 144, 175, 226, 124, 173, 64, 30,
    129])


def test_reference_golden_vectors():
    assert poseidon.hash(bytes([1]) * 32, False) == GOLD_1X32_LE
    assert poseidon.hash(bytes([1]) * 32, True) == GOLD_1X32_LE[::-1]
    assert poseidon.hash(bytes([1]) * 32 + bytes([2]) * 32, True) \
        == GOLD_2X32_BE
    inp = bytes(31) + bytes([1]) + bytes(31) + bytes([1])
    assert poseidon.hash(inp, True) == GOLD_ONES_BE


def test_grain_ark_matches_baked_table():
    """First grain-generated ARK constant == light-poseidon's baked table
    entry (the reference's ark_2[0])."""
    ark, mds, r_p = poseidon._params(2)
    want = int.from_bytes(bytes([
        167, 215, 171, 208, 219, 192, 125, 108, 27, 221, 76, 83, 119, 161,
        26, 167, 56, 186, 76, 41, 186, 170, 31, 254, 212, 155, 142, 198,
        158, 110, 196, 9]), "little")
    assert ark[0] == want
    assert r_p == 56 and len(ark) == 2 * (8 + 56)


def test_all_widths():
    """Every supported width hashes and stays in-field."""
    for n in range(1, 13):
        out = poseidon.hash(bytes(range(32)) * n, False)
        assert int.from_bytes(out, "little") < poseidon.P


def test_input_limits():
    with pytest.raises(poseidon.PoseidonError):
        poseidon.hash(b"", False)
    with pytest.raises(poseidon.PoseidonError):
        poseidon.hash(bytes(32 * 13), False)


class _StubVm:
    def __init__(self):
        self.mem = {}
        self.cu = 1 << 30

    def _consume(self, n):
        self.cu -= n

    def mem_read(self, va, n):
        return int.from_bytes(self.mem.get(va, bytes(n))[:n], "little")

    def mem_read_bytes(self, va, n):
        return bytes(self.mem.get(va, b"")[:n]).ljust(n, b"\0")

    def mem_write_bytes(self, va, data):
        self.mem[va] = bytes(data)


def test_sol_poseidon_syscall():
    from firedancer_tpu.flamenco import vm as vmmod

    vm = _StubVm()
    # two 32-byte big-endian inputs (1 and 1) -> reference's 4th vector
    vm.mem[0x500] = (bytes(31) + bytes([1]))
    vm.mem[0x540] = (bytes(31) + bytes([1]))
    for i, p in enumerate((0x500, 0x540)):   # (ptr, len) descriptors
        vm.mem[0x400 + 16 * i] = p.to_bytes(8, "little")
        vm.mem[0x400 + 16 * i + 8] = (32).to_bytes(8, "little")
    assert vmmod._sc_poseidon(vm, 0, 0, 0x400, 2, 0x600) == 0
    assert vm.mem[0x600] == GOLD_ONES_BE

    # little-endian single input
    vm.mem[0x500] = bytes([1]) * 32
    assert vmmod._sc_poseidon(vm, 0, 1, 0x400, 1, 0x610) == 0
    assert vm.mem[0x610] == GOLD_1X32_LE

    # errors: bad param set, zero inputs, oversized slice
    assert vmmod._sc_poseidon(vm, 1, 0, 0x400, 1, 0x620) == 1
    assert vmmod._sc_poseidon(vm, 0, 0, 0x400, 0, 0x620) == 1
    vm.mem[0x408] = (33).to_bytes(8, "little")
    assert vmmod._sc_poseidon(vm, 0, 0, 0x400, 1, 0x620) == 1
    assert 0x620 not in vm.mem
