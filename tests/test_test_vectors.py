"""Cross-client conformance corpus replay (VERDICT r4 #4): >= 1,000
fixtures in the test-vectors `.fix` proto3 interchange format, replayed
through flamenco/test_vectors.py.  The corpus is anchored by the 104
reference-cited hand fixtures; mutations/parametrics/ELF cases pin the
full behavior surface (tools/gen_test_vectors.py documents the split)."""

import os
import tarfile

from firedancer_tpu.flamenco import test_vectors as tv

TAR = os.path.join(os.path.dirname(__file__), "fixtures", "test_vectors.tar")


def test_corpus_replays_clean():
    results = tv.run_path(TAR)
    failed = [r for r in results if not r.passed]
    assert not failed, (
        f"{len(failed)}/{len(results)} failed; first: "
        f"{failed[0].name}: {failed[0].detail}")
    assert len(results) >= 1000


def test_codec_roundtrip_all():
    with tarfile.open(TAR) as tf:
        members = [m for m in tf.getmembers() if m.name.endswith(".fix")]
        assert len(members) >= 1000
        for m in members[::37]:  # sampled
            blob = tf.extractfile(m).read()
            schema = ("ELFLoaderFixture" if "elf_loader" in m.name
                      else "InstrFixture")
            msg = tv.decode(schema, blob)
            again = tv.decode(schema, tv.encode(schema, msg))
            assert again == msg


def test_negative_detection():
    """A fixture with falsified effects must FAIL replay (the runner
    actually compares, it doesn't rubber-stamp)."""
    with tarfile.open(TAR) as tf:
        for m in tf.getmembers():
            if m.name.startswith("instr/") and m.name.endswith(".fix"):
                fx = tv.decode("InstrFixture", tf.extractfile(m).read())
                out = fx.setdefault("output", {})
                if out.get("result", 0) == 0 and out.get(
                        "modified_accounts"):
                    out["modified_accounts"][0]["lamports"] = (
                        out["modified_accounts"][0].get("lamports", 0) + 1)
                    r = tv.run_instr_fixture(fx, m.name)
                    assert not r.passed
                    return
    raise AssertionError("no suitable fixture found")


def test_varint_negative_result_roundtrip():
    blob = tv.encode("InstrEffects", {"result": -5})
    assert tv.decode("InstrEffects", blob)["result"] == -5


def test_calldests_rep_varint_wire_format():
    """proto3 `repeated uint64` is PACKED VARINT on the wire (ADVICE r5:
    rep_fixed64 here made foreign .fix corpora misparse).  Pin the exact
    bytes: field 7, wire type 2, varint elements."""
    vals = [0, 1, 127, 128, 300, (1 << 64) - 1]
    blob = tv.encode("ELFLoaderEffects", {"calldests": vals})
    # tag = (7 << 3) | 2 = 0x3A; payload = concatenated varints
    payload = b"".join(tv._enc_varint(v) for v in vals)
    assert blob == bytes([0x3A, len(payload)]) + payload
    assert tv.decode("ELFLoaderEffects", blob)["calldests"] == vals


def test_calldests_accepts_unpacked_varint():
    """Decoders must accept the unpacked form too (one VARINT field per
    element) — proto3 rule for packable repeated fields."""
    blob = b"".join(tv._tag(7, 0) + tv._enc_varint(v)
                    for v in (9, 1 << 40))
    assert tv.decode("ELFLoaderEffects", blob)["calldests"] == [9, 1 << 40]


def test_features_stays_rep_fixed64():
    """FeatureSet.features is `repeated fixed64` in the vendored proto —
    the wire stays 8-byte LE chunks."""
    vals = [5, (1 << 64) - 2]
    blob = tv.encode("FeatureSet", {"features": vals})
    payload = b"".join(v.to_bytes(8, "little") for v in vals)
    assert blob == bytes([0x0A, len(payload)]) + payload
    assert tv.decode("FeatureSet", blob)["features"] == vals
