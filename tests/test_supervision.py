"""Self-healing topology unit tests (fast tier, no device graphs):
SupervisionPolicy parsing/backoff, TopoRun poll + three-state /healthz,
tango dead-consumer eviction, deterministic fault injection, the
GuardedVerifier degradation state machine (fake verifier + fake clock),
pipeline heartbeats through device waits, and mux fseq-cursor resume.

Everything multi-process (real kill -> respawn -> unstall) lives in
tools/chaos_smoke.py (the `chaos` ci.sh tier)."""

import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from firedancer_tpu.disco import faultinject
from firedancer_tpu.disco import topo as topo_mod
from firedancer_tpu.disco.mux import Mux
from firedancer_tpu.disco.run import SupervisionPolicy, TopoRun
from firedancer_tpu.disco.topo import TopoBuilder
from firedancer_tpu.tango.fctl import Fctl
from firedancer_tpu.tango.ring import Cnc

# -- SupervisionPolicy -------------------------------------------------------


def test_policy_from_cfg_defaults():
    from firedancer_tpu.app import config as config_mod
    cfg = config_mod.load(None)
    p = SupervisionPolicy.from_cfg(cfg)
    assert p.restart_policy == "fail_fast"
    assert p.max_restarts == 5
    # per-kind staleness: verify overridden in [supervision.heartbeat_stale]
    assert p.stale_ns("verify") == int(120.0 * 1e9)
    assert p.stale_ns("net") == int(60.0 * 1e9)
    assert p.stale_ns(None) == int(60.0 * 1e9)


def test_policy_from_cfg_env_overlay_strings():
    # FDTPU_* env overlays arrive as strings; from_cfg must coerce
    p = SupervisionPolicy.from_cfg({"supervision": {
        "restart_policy": "respawn", "max_restarts": "2",
        "backoff_initial_s": "0.01", "heartbeat_stale_s": "1.5",
        "heartbeat_stale": {"verify": "3"}}})
    assert p.restart_policy == "respawn" and p.max_restarts == 2
    assert p.backoff_initial_s == 0.01
    assert p.stale_ns("verify") == int(3e9)
    assert p.stale_ns("dedup") == int(1.5e9)


def test_backoff_deterministic_and_bounded():
    p = SupervisionPolicy(backoff_initial_s=0.25, backoff_max_s=8.0,
                          backoff_jitter=0.2)
    for attempt in range(1, 12):
        d1 = p.backoff_s(attempt, "verify:0")
        d2 = p.backoff_s(attempt, "verify:0")
        assert d1 == d2, "jitter must be deterministic per (tile, attempt)"
        base = min(0.25 * 2 ** (attempt - 1), 8.0)
        assert base * 0.8 <= d1 <= base * 1.2
    # different tiles de-synchronize
    assert p.backoff_s(3, "verify:0") != p.backoff_s(3, "verify:1")
    # jitter off -> exact exponential
    p0 = SupervisionPolicy(backoff_initial_s=0.5, backoff_max_s=4.0,
                           backoff_jitter=0.0)
    assert [p0.backoff_s(a) for a in (1, 2, 3, 4, 5)] == \
        [0.5, 1.0, 2.0, 4.0, 4.0]


# -- TopoRun: wait_ready regression + poll + /healthz ------------------------


def _mini_spec(tag: str):
    return (
        TopoBuilder(f"sup{tag}{os.getpid()}", wksp_mb=8)
        .link("a_b", depth=64, mtu=256)
        .tile("src", "sink", outs=["a_b"])
        .tile("v:0", "verify", ins=["a_b"])
        .build()
    )


class _FakeProc:
    def __init__(self, alive=True):
        self._alive = alive

    def is_alive(self):
        return self._alive

    def join(self, *a):
        pass

    def terminate(self):
        self._alive = False

    def kill(self):
        self._alive = False


def test_wait_ready_unstarted_raises():
    # regression: start=False + wait_ready used to die with a bare
    # KeyError off the empty procs dict
    run = TopoRun(_mini_spec("wr"), start=False)
    try:
        with pytest.raises(RuntimeError, match="not started"):
            run.wait_ready(timeout=0.1)
    finally:
        run.close()


def test_poll_states_and_healthz_three_way():
    policy = SupervisionPolicy(heartbeat_stale_s=0.05,
                               heartbeat_stale_by_kind={"verify": 30.0})
    run = TopoRun(_mini_spec("hz"), start=False, metrics_port=0,
                  policy=policy)
    try:
        run.procs = {"src": _FakeProc(), "v:0": _FakeProc()}
        base = f"http://127.0.0.1:{run.metrics_port}"

        # tiles still in BOOT within grace -> poll() holds fire, /healthz 503
        assert run.poll() is None
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/healthz", timeout=10)
        assert ei.value.code == 503
        assert "unhealthy" in ei.value.read().decode()

        # everything RUN + fresh heartbeats -> healthy
        for cnc in run.jt.cnc.values():
            cnc.signal(Cnc.SIGNAL_RUN)
            cnc.heartbeat(time.monotonic_ns())
        assert run.poll() is None
        r = urllib.request.urlopen(f"{base}/healthz", timeout=10)
        body = r.read().decode()
        assert r.status == 200 and body.startswith("ok\n")
        assert "slo " in body  # healthz carries the SLO one-liner now

        # degraded verify tile: still 200, but flagged (load balancers keep
        # routing; operators get a distinct state)
        run.jt.metrics["v:0"].set("degraded_mode", 1)
        r = urllib.request.urlopen(f"{base}/healthz", timeout=10)
        assert r.status == 200
        body = r.read().decode()
        assert body.startswith("degraded\n") and "v:0" in body
        run.jt.metrics["v:0"].set("degraded_mode", 0)

        # per-KIND staleness: age both heartbeats past the 50ms default;
        # the verify tile's 30s override keeps it healthy, src flags
        old = time.monotonic_ns() - int(0.2 * 1e9)
        for cnc in run.jt.cnc.values():
            cnc.heartbeat(old)
        assert run.poll() == "src"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/healthz", timeout=10)
        body = ei.value.read().decode()
        assert "src" in body and "v:0" not in body

        # dead process beats everything
        run.jt.cnc["src"].heartbeat(time.monotonic_ns())
        run.procs["src"]._alive = False
        assert run.poll() == "src"

        # a tile wedged in BOOT past its grace window is a failure too
        run.procs["src"]._alive = True
        run.jt.cnc["src"].signal(Cnc.SIGNAL_BOOT)
        run._boot_deadline["src"] = time.monotonic() - 1.0
        assert run.poll() == "src"
    finally:
        run.procs = {}
        run.close()


# -- tango dead-consumer eviction --------------------------------------------


class _FakeFSeq:
    def __init__(self, seq=0):
        self.seq = seq

    def update(self, seq):
        self.seq = seq

    def query(self):
        return self.seq

    def diag_add(self, idx, delta=1):
        pass


class _FakeMcache:
    def __init__(self, seq):
        self._seq = seq

    def seq_query(self):
        return self._seq


def test_fctl_rx_evict_unblocks_producer():
    fs_dead, fs_live = _FakeFSeq(0), _FakeFSeq(90)
    f = Fctl(cr_max=64).rx_add(fs_dead).rx_add(fs_live)
    assert f.cr_query(100) == 0          # dead consumer pins credits
    assert f.rx_evict(fs_dead) is True
    assert f.rx_cnt == 1
    assert f.cr_query(100) == 64 - 10    # only the live consumer counts
    assert f.rx_evict(fs_dead) is False  # already gone


def test_evict_dead_consumer_fast_forwards():
    fs = _FakeFSeq(3)
    cur = Fctl.evict_dead_consumer(fs, _FakeMcache(777))
    assert cur == 777 and fs.query() == 777
    # and again in real shm: FSeq.reset is the supervisor-side store
    spec = _mini_spec("ev")
    jt = topo_mod.create(spec)
    try:
        fseq = jt.fseq[("v:0", "a_b")]
        mc = jt.links["a_b"].mcache
        fseq.update(1)
        # produce a few frags so the producer cursor moves ahead
        for i in range(5):
            mc.publish(i)
        assert Fctl.evict_dead_consumer(fseq, mc) == mc.seq_query()
        assert fseq.query() == mc.seq_query()
    finally:
        jt.close()
        jt.unlink()


# -- fault injection ---------------------------------------------------------


def test_faultinject_parse_and_overlay():
    plans = faultinject.parse_plan(
        "verify=delay_frag_us:50,seed:9; verify:1=kill_after_frags:10,boot:0"
        ";source=drop_frag_p:0.25")
    assert plans["verify"] == {"delay_frag_us": 50, "seed": 9}
    assert plans["source"] == {"drop_frag_p": 0.25}
    # kind entry applies to every instance; exact entry overlays knob-wise
    assert faultinject.plan_for("verify:0", plans) == \
        {"delay_frag_us": 50, "seed": 9}
    assert faultinject.plan_for("verify:1", plans) == \
        {"delay_frag_us": 50, "seed": 9, "kill_after_frags": 10, "boot": 0}
    assert faultinject.plan_for("dedup", plans) is None


def test_faultinject_for_tile_gating():
    env = {"FDTPU_FAULTS": "verify:0=kill_after_frags:5,boot:0"}
    # no plan names the tile -> None (the zero-overhead contract)
    assert faultinject.for_tile("dedup", environ=env) is None
    assert faultinject.for_tile("verify:0", environ={}) is None
    f = faultinject.for_tile("verify:0", environ=env)
    assert f is not None and f._kill_after == 5
    # boot-generation gate: the respawned incarnation runs fault-free
    assert faultinject.for_tile("verify:0", restart_cnt=1, environ=env) is None
    # cfg string plan merges over env; cfg dict applies directly
    f = faultinject.for_tile(
        "verify:0", cfg={"faults": "verify:0=delay_frag_us:7"}, environ=env)
    assert f._kill_after == 5 and f._delay_s == pytest.approx(7e-6)
    f = faultinject.for_tile("x", cfg={"faults": {"drop_frag_p": 0.5}},
                             environ={})
    assert f._drop_p == 0.5


def test_faultinject_deterministic_streams():
    mk = lambda: faultinject.FaultInjector(  # noqa: E731
        "verify:0", {"drop_frag_p": 0.3, "corrupt_payload_p": 0.3, "seed": 4})
    a, b = mk(), mk()
    pay = bytes(range(64))
    seq_a = [a.frag(pay) for _ in range(64)]
    seq_b = [b.frag(pay) for _ in range(64)]
    assert seq_a == seq_b
    drops = sum(1 for _, d in seq_a if d)
    flips = sum(1 for p, d in seq_a if not d and p != pay)
    assert drops and flips  # both knobs actually fired
    # corrupted payloads differ by exactly one bit
    for p, d in seq_a:
        if not d and p != pay:
            diff = np.bitwise_xor(np.frombuffer(p, np.uint8),
                                  np.frombuffer(pay, np.uint8))
            assert int(np.unpackbits(diff).sum()) == 1
    # a different instance name diverges under the same plan seed
    c = faultinject.FaultInjector(
        "verify:1", {"drop_frag_p": 0.3, "corrupt_payload_p": 0.3, "seed": 4})
    assert [c.frag(pay) for _ in range(64)] != seq_a


def test_faultinject_kill_fires_before_nth_frag(monkeypatch):
    exits = []
    monkeypatch.setattr(faultinject.os, "_exit",
                        lambda code: exits.append(code))
    f = faultinject.FaultInjector("v", {"kill_after_frags": 3})
    f.frag(b"x")
    f.frag(b"x")
    assert not exits
    f.frag(b"x")  # the 3rd frag is never processed
    assert exits == [faultinject.KILL_EXIT_CODE]


def test_faultinject_batch_kill_defers_to_frag_boundary(monkeypatch):
    # vectorized rx paths: a kill threshold inside the batch trims it to
    # the allowed prefix (processed + span-recorded by the mux) and the
    # kill fires at the NEXT fault-point entry — the dead tile's flight
    # bundle keeps its final spans instead of losing the whole burst
    exits = []
    monkeypatch.setattr(faultinject.os, "_exit",
                        lambda code: exits.append(code))
    f = faultinject.FaultInjector("v", {"kill_after_frags": 150})
    assert f.burst(100, None, None) == 100   # wholly under threshold
    assert not exits
    assert f.burst(100, None, None) == 49    # trimmed to frags 101..149
    assert not exits                         # deferred past the batch
    f.house()                                # next entry: corpse drops
    assert exits == [faultinject.KILL_EXIT_CODE]


def test_faultinject_dispatch_fail_n_then_heals():
    f = faultinject.FaultInjector("v", {"fail_dispatch_n": 2})
    for _ in range(2):
        with pytest.raises(faultinject.InjectedDispatchError):
            f.dispatch()
    f.dispatch()  # healed
    assert f.dispatch_cnt == 3


# -- GuardedVerifier state machine -------------------------------------------


def _host_odd(msgs, lens, sigs, pubs):
    # deterministic fake host backend: odd lanes pass
    return np.arange(len(msgs)) % 2 == 1


class _FlakyFn:
    """Fake device verifier: scripted per-call behavior."""

    def __init__(self, script):
        self.script = list(script)  # "ok" | "raise" | "hang"
        self.calls = 0

    def __call__(self, msgs, lens, sigs, pubs):
        mode = self.script[min(self.calls, len(self.script) - 1)]
        self.calls += 1
        if mode == "raise":
            raise RuntimeError("injected device loss")
        if mode == "hang":
            return _Hung()
        return np.ones(len(msgs), dtype=bool)


class _Hung:
    def is_ready(self):
        return False

    def __array__(self, dtype=None, copy=None):
        raise RuntimeError("device gone")


def _gv(fn, **kw):
    from firedancer_tpu.disco.pipeline import GuardedVerifier
    t = [0.0]
    kw.setdefault("clock", lambda: t[0])
    kw.setdefault("host_arrays", _host_odd)
    g = GuardedVerifier(fn, **kw)
    return g, t


def _args(n=8):
    z = np.zeros((n, 4), np.uint8)
    return z, np.zeros(n, np.int32), z, z


def test_guarded_retry_masks_transient_failure():
    g, _ = _gv(_FlakyFn(["raise", "ok"]), retries=1, fail_threshold=3)
    ok = np.asarray(g(*_args()))
    assert ok.all() and not g.degraded
    assert g.device_fail_cnt == 0 and g.fallback_lanes == 0


def test_guarded_batch_fallback_then_degraded_then_recovery():
    g, t = _gv(_FlakyFn(["raise"] * 9 + ["ok"]), retries=0,
               fail_threshold=3, reprobe_s=5.0)
    expect = _host_odd(*_args())
    # failures 1..2: per-batch host fallback, still healthy
    for i in range(2):
        ok = np.asarray(g(*_args()))
        assert np.array_equal(ok, expect)
        assert not g.degraded and g.device_fail_cnt == i + 1
    # failure 3 crosses the consecutive threshold
    np.asarray(g(*_args()))
    assert g.degraded and g.device_fail_cnt == 3
    # degraded: dispatches short-circuit to host (device fn NOT called)
    calls0 = g.fn.calls
    np.asarray(g(*_args()))
    assert g.fn.calls == calls0
    assert g.fallback_vps() == 0  # clock frozen; just must not divide by 0
    # advance past the reprobe window: probe fails, re-arms the timer
    t[0] += 6.0
    np.asarray(g(*_args()))
    assert g.fn.calls == calls0 + 1 and g.degraded
    assert g.reprobe_cnt == 1
    # next window: the script heals, the probe materializes -> recovered
    g.fn.script = ["ok"]
    g.fn.calls = 0
    t[0] += 6.0
    ok = np.asarray(g(*_args()))
    assert ok.all()
    assert not g.degraded and g._consec == 0
    # healthy again: device path serves
    assert np.asarray(g(*_args())).all()


def test_guarded_harvest_deadline_counts_as_failure():
    # device accepts every dispatch but never completes: the dispatch-side
    # never raises, so only the harvest deadline can cross the threshold
    g, t = _gv(_FlakyFn(["hang"]), retries=0, fail_threshold=2,
               deadline_s=1.0)
    expect = _host_odd(*_args())
    v = g(*_args())
    assert not v.is_ready()
    t[0] += 2.0            # past deadline: harvest must not block forever
    assert v.is_ready()
    ok = np.asarray(v)
    assert np.array_equal(ok, expect)
    assert g.device_fail_cnt == 1 and not g.degraded
    v2 = g(*_args())
    t[0] += 2.0
    np.asarray(v2)
    assert g.degraded


def test_guarded_deadline_zero_disables_hang_watchdog():
    # deadline_s <= 0: a slow dispatch is never declared hung no matter
    # how much time passes (bench topologies on a contended CPU host
    # disable the watchdog this way); a verdict that eventually
    # materializes still counts as a clean device success
    g, t = _gv(_FlakyFn(["hang"]), retries=0, fail_threshold=2,
               deadline_s=0.0)
    v = g(*_args())
    t[0] += 1e6
    assert not v.is_ready()                 # poll-only, never force-ready
    # the "hung" device finally completes: swap in a real verdict
    v._dev = np.ones(8, dtype=bool)
    assert v.is_ready()
    assert np.asarray(v).all()
    assert g.device_fail_cnt == 0 and not g.degraded


def test_guarded_consec_clears_only_on_materialized_verdict():
    g, t = _gv(_FlakyFn(["raise", "ok", "raise", "raise"]), retries=0,
               fail_threshold=3)
    np.asarray(g(*_args()))        # fail #1
    assert g._consec == 1
    np.asarray(g(*_args()))        # a verdict MATERIALIZES -> consec clears
    assert g._consec == 0
    np.asarray(g(*_args()))
    np.asarray(g(*_args()))
    assert g._consec == 2 and not g.degraded


def test_guarded_fault_injection_drives_dispatch():
    fault = faultinject.FaultInjector("v", {"fail_dispatch_n": 2})
    g, t = _gv(_FlakyFn(["ok"]), retries=0, fail_threshold=2,
               reprobe_s=1.0, fault=fault)
    expect = _host_odd(*_args())
    assert np.array_equal(np.asarray(g(*_args())), expect)
    np.asarray(g(*_args()))
    assert g.degraded              # 2 consecutive injected failures
    t[0] += 2.0                    # fault healed (fail_dispatch_n spent)
    assert np.asarray(g(*_args())).all()
    assert not g.degraded


def test_guarded_surface_mirrors_wrapped_fn():
    # a plain 4-array fn must NOT grow dispatch_blob (pipeline packed
    # autodetect is hasattr-based)
    g, _ = _gv(_FlakyFn(["ok"]))
    assert not hasattr(g, "dispatch_blob")

    class _Packed:
        mode = "strict"

        def __call__(self, *a):
            return np.ones(4, bool)

        def dispatch_blob(self, blob, maxlen=None):
            return np.ones(len(blob), dtype=bool)

    from firedancer_tpu.disco.pipeline import GuardedVerifier
    g2 = GuardedVerifier(_Packed(), host_blob=lambda b, maxlen: np.ones(
        len(b), bool), host_arrays=_host_odd)
    assert hasattr(g2, "dispatch_blob")
    assert g2.mode == "strict"     # __getattr__ passthrough
    ok = np.asarray(g2.dispatch_blob(np.zeros((4, 8), np.uint8)))
    assert ok.shape == (4,)


# -- pipeline heartbeats through device waits --------------------------------


def test_pipeline_heartbeats_during_device_wait():
    from firedancer_tpu.ballet import txn as txn_lib
    from firedancer_tpu.disco.pipeline import VerifyPipeline

    class _SlowVerdict:
        def __init__(self, n, polls):
            self.n = n
            self.polls = polls

        def is_ready(self):
            self.polls -= 1
            return self.polls <= 0

        def __array__(self, dtype=None, copy=None):
            return np.ones(self.n, dtype=bool)

    def slow_fn(msgs, lens, sigs, pubs):
        return _SlowVerdict(len(msgs), polls=5)

    beats = []
    rng = np.random.default_rng(11)
    payloads = []
    for _ in range(4):
        msg = txn_lib.build_unsigned([rng.bytes(32)], rng.bytes(32),
                                     [(1, bytes([0]), bytes(8))],
                                     extra_accounts=[rng.bytes(32)])
        payloads.append(txn_lib.assemble([rng.bytes(64)], msg))
    mlen = len(txn_lib.parse(payloads[0]).message(payloads[0]))
    pipe = VerifyPipeline(slow_fn, buckets=[(4, mlen)], max_inflight=0,
                          heartbeat_cb=lambda: beats.append(1))
    out = []
    for p in payloads:
        out += pipe.submit(p)
    out += pipe.flush()
    assert len(out) == 4
    # ~4 not-ready polls each heartbeat once before the verdict lands
    assert len(beats) >= 3


# -- mode-routed degradation under respawn (PR 5 x PR 9 interaction) ---------


class _AntipaDeadDevice:
    """Device graph that is permanently down, advertising antipa mode —
    the GuardedVerifier must route fallback to the antipa host twin."""

    mode = "antipa"

    def __call__(self, msgs, lens, sigs, pubs):
        raise RuntimeError("injected device loss")


def _sign_batch(n: int, seed: int = 33):
    """n real (msg, sig, pub) triples; odd lanes corrupted -> mixed
    verdicts, so a fallback that fails open (or closed) is caught."""
    from firedancer_tpu.ops import ed25519 as ed
    rng = np.random.default_rng(seed)
    msgs, sigs, pubs = [], [], []
    for i in range(n):
        seed_b = rng.bytes(32)
        pub, _, _ = ed.keypair_from_seed(seed_b)
        msg = rng.bytes(32)
        sig = bytearray(ed.sign(seed_b, msg))
        if i % 2:
            sig[10] ^= 0x40
        msgs.append(msg)
        sigs.append(bytes(sig))
        pubs.append(pub)
    return msgs, sigs, pubs


def test_guarded_fallback_serves_antipa_host_twin():
    from firedancer_tpu.disco.pipeline import GuardedVerifier
    from firedancer_tpu.models.verifier import host_verify_arrays

    n = 4
    msgs, sigs, pubs = _sign_batch(n)
    m = np.frombuffer(b"".join(msgs), np.uint8).reshape(n, 32)
    ln = np.full(n, 32, np.int32)
    s = np.frombuffer(b"".join(sigs), np.uint8).reshape(n, 64)
    p = np.frombuffer(b"".join(pubs), np.uint8).reshape(n, 32)
    expect = host_verify_arrays(m, ln, s, p, mode="antipa")
    assert list(expect) == [True, False, True, False]

    g = GuardedVerifier(_AntipaDeadDevice(), retries=0, fail_threshold=1,
                        reprobe_s=1e9, clock=lambda: 0.0)
    ok = np.asarray(g(m, ln, s, p))
    assert np.array_equal(ok, expect)
    assert g.degraded and g.fallback_lanes == n
    # the lazily-bound default backend is the ANTIPA host twin, not the
    # strict one (the wrapped fn's .mode routed it)
    assert g._host_arrays.keywords["mode"] == "antipa"
    # and the strict twin would have produced the same verdicts here only
    # by accident of these inputs; assert the mode plumbing, not luck
    g2 = GuardedVerifier(_AntipaDeadDevice(), retries=0, fail_threshold=1,
                         reprobe_s=1e9, clock=lambda: 0.0)
    g2.fn = type("S", (), {"mode": "strict",
                           "__call__": lambda self, *a: (_ for _ in ())
                           .throw(RuntimeError("down"))})()
    np.asarray(g2(m, ln, s, p))
    assert g2._host_arrays.keywords["mode"] == "strict"


class _AntipaVerifyVt:
    """Fast-tier stand-in for the verify tile's mode routing: init reads
    [verify] mode from the tile cfg exactly like tiles.VerifyTile does,
    verdicts come from a GuardedVerifier whose device graph is dead (so
    every verdict is served by the mode-routed host twin), and the tile
    'dies' (halts mid-stream) after `die_after` frags."""

    def __init__(self, die_after=None):
        self.die_after = die_after
        self.mode_seen = None
        self.seqs = []
        self.g = None

    def init(self, ctx):
        from firedancer_tpu.disco.pipeline import GuardedVerifier
        self.mode_seen = str(ctx.cfg.get("mode", "strict"))
        dev = _AntipaDeadDevice()
        dev.mode = self.mode_seen
        self.g = GuardedVerifier(dev, retries=0, fail_threshold=1,
                                 reprobe_s=1e9, clock=lambda: 0.0)

    def on_frag(self, ctx, iidx, meta, payload):
        pub, sig, msg = payload[:32], payload[32:96], payload[96:]
        ok = np.asarray(self.g(
            np.frombuffer(msg, np.uint8)[None, :],
            np.array([len(msg)], np.int32),
            np.frombuffer(sig, np.uint8)[None, :],
            np.frombuffer(pub, np.uint8)[None, :]))
        self.seqs.append(int(meta["seq"]))
        ctx.publish(b"", sig=int(bool(ok[0])), out=0)
        if self.die_after is not None and len(self.seqs) >= self.die_after:
            ctx.halt()


def test_antipa_mode_resumes_across_respawn_no_dup_verdicts():
    """Kill -> respawn while [verify] mode = antipa: the respawned
    incarnation resumes with the SAME mode (cfg-routed, tiles.py:300),
    picks up from the dead tile's fseq cursor so ZERO verdicts are
    duplicated, and its GuardedVerifier fallback still serves the antipa
    host twin."""
    n = 12
    spec = (
        TopoBuilder(f"antipa{os.getpid()}", wksp_mb=8)
        .link("src_verify", depth=64, mtu=256)
        .link("verify_dedup", depth=64, mtu=64)
        .tile("source", "sink", outs=["src_verify"])
        .tile("verify:0", "verify", ins=["src_verify"],
              outs=["verify_dedup"], mode="antipa")
        .tile("dedup", "sink", ins=["verify_dedup"])
        .build()
    )
    jt = topo_mod.create(spec)
    try:
        msgs, sigs, pubs = _sign_batch(n)
        lnk = jt.links["src_verify"]
        chunk = 0
        for i in range(n):
            payload = pubs[i] + sigs[i] + msgs[i]
            nxt = lnk.dcache.write(chunk, payload)
            lnk.mcache.publish(0, chunk, len(payload))
            chunk = nxt
        # keep the dedup consumer from pinning verdict-link credits
        jt.fseq[("dedup", "verify_dedup")].update(
            jt.links["verify_dedup"].mcache.seq0() + n)

        # incarnation 0 dies after 5 verdicts (mid-stream halt)
        vt0 = _AntipaVerifyVt(die_after=5)
        m0 = Mux(jt, "verify:0", vt0)
        m0.run()
        assert vt0.mode_seen == "antipa"
        assert len(vt0.seqs) == 5
        cursor = jt.fseq[("verify:0", "src_verify")].query()
        assert cursor == vt0.seqs[-1] + 1, "cursor must persist the ack"

        # respawn: restart_cnt=1 resumes from the cursor, same spec cfg
        vt1 = _AntipaVerifyVt(die_after=n - 5)
        m1 = Mux(jt, "verify:0", vt1, restart_cnt=1)
        m1.run()
        assert vt1.mode_seen == "antipa", "respawn lost the verify mode"
        assert vt1.g._host_arrays.keywords["mode"] == "antipa", \
            "respawned fallback is not the antipa host twin"

        # zero duplicate verdicts: the two incarnations' frag seqs are
        # disjoint and together cover the full stream
        assert not (set(vt0.seqs) & set(vt1.seqs)), "duplicate verdicts"
        assert sorted(vt0.seqs + vt1.seqs) == sorted(
            set(vt0.seqs) | set(vt1.seqs))
        assert len(vt0.seqs) + len(vt1.seqs) == n

        # and the verdict stream downstream carries the mixed host-twin
        # verdicts (odd lanes corrupted at signing time)
        mc = jt.links["verify_dedup"].mcache
        verdicts = []
        seq = mc.seq0()
        for _ in range(n):
            rc, meta = mc.query(seq)
            assert rc == 0
            verdicts.append(int(meta["sig"]))
            seq += 1
        assert verdicts == [1, 0] * (n // 2)
        # drop every shm view (mux dcaches, link handles, the last meta
        # record) before the workspace unmaps
        m0 = m1 = meta = lnk = mc = None  # noqa: F841
        import gc
        gc.collect()
    finally:
        jt.close()
        jt.unlink()


# -- drain protocol: DRAIN/DRAINED state machine -----------------------------


def test_policy_from_cfg_drain_knobs():
    from firedancer_tpu.app import config as config_mod
    cfg = config_mod.load(None)
    p = SupervisionPolicy.from_cfg(cfg)
    # unconfigured: drain off, behavior identical to pre-drain trees
    assert p.drain_timeout_s == 0.0 and p.drain_manifest_dir == ""
    p = SupervisionPolicy.from_cfg({"supervision": {
        "drain_timeout_s": "2.5", "drain_manifest_dir": "/tmp/dm"}})
    assert p.drain_timeout_s == 2.5 and p.drain_manifest_dir == "/tmp/dm"


def test_dependency_order_producers_first():
    from firedancer_tpu.disco.run import dependency_order
    spec = (
        TopoBuilder(f"dep{os.getpid()}", wksp_mb=8)
        .link("s_v", depth=64, mtu=256)
        .link("v_d", depth=64, mtu=64)
        .tile("dedup", "sink", ins=["v_d"])          # declared consumer-first
        .tile("verify:0", "verify", ins=["s_v"], outs=["v_d"])
        .tile("source", "sink", outs=["s_v"])
        .build()
    )
    order = dependency_order(spec)
    assert sorted(order) == sorted(t.name for t in spec.tiles)
    assert order.index("source") < order.index("verify:0")
    assert order.index("verify:0") < order.index("dedup")


def test_fctl_evict_then_rejoin_no_double_credit_no_redelivery():
    """Eviction -> re-join race: after the supervisor fast-forwards a dead
    consumer's fseq, the respawned incarnation must resume FROM the
    evicted cursor (mux restart_cnt>0 resume), so its first fseq publish
    can never rewind the line (double-crediting the producer with lag it
    already acked) and no frag below the cursor is ever re-delivered."""
    spec = _mini_spec("rj")
    jt = topo_mod.create(spec)
    try:
        mc = jt.links["a_b"].mcache
        for i in range(10):
            mc.publish(i)
        fseq = jt.fseq[("v:0", "a_b")]
        fseq.update(mc.seq0() + 3)   # consumer died 7 frags behind

        # producer side: the dead line pins credits until evicted
        f = Fctl(cr_max=8).rx_add(fseq)
        assert f.cr_query(mc.seq_query()) == 1  # 8 - 7 lag
        cursor = Fctl.evict_dead_consumer(fseq, mc)
        assert cursor == mc.seq_query()
        assert f.cr_query(mc.seq_query()) == 8  # fully refilled

        # re-join: the respawned mux resumes from the evicted cursor,
        # not its corpse's last position
        class _Vt:
            pass

        m1 = Mux(jt, "v:0", _Vt(), restart_cnt=1)
        assert m1.ins[0].seq == cursor, "respawn would re-deliver frags"
        # its first housekeeping-style ack writes the same cursor: the
        # producer's credit view never rewinds
        m1.ins[0].fseq.update(m1.ins[0].seq)
        assert f.cr_query(mc.seq_query()) == 8
        m1 = None  # noqa: F841
        import gc
        gc.collect()
    finally:
        jt.close()
        jt.unlink()


class _DrainVt:
    """Records delivered frag seqs; optional drain hook that reports dry
    only after `wet` polls (an in-flight device batch flushing)."""

    def __init__(self, die_after=None, wet=0):
        self.seqs = []
        self.die_after = die_after
        self.wet = wet
        self.drain_polls = 0

    def on_frag(self, ctx, iidx, meta, payload):
        self.seqs.append(int(meta["seq"]))
        if self.die_after is not None and len(self.seqs) >= self.die_after:
            ctx.halt()

    def drain(self, ctx) -> bool:
        self.drain_polls += 1
        return self.drain_polls > self.wet


def _run_mux_thread(m):
    import threading
    t = threading.Thread(target=m.run, daemon=True)
    t.start()
    return t


def _wait(pred, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() > deadline:
            raise TimeoutError(f"timed out waiting for {what}")
        time.sleep(0.002)


def test_mux_drain_flushes_parks_and_manifests(tmp_path):
    spec = (
        TopoBuilder(f"dr{os.getpid()}", wksp_mb=8)
        .link("a_b", depth=64, mtu=256)
        .tile("src", "sink", outs=["a_b"])
        .tile("v:0", "verify", ins=["a_b"],
              supervision={"drain_manifest_dir": str(tmp_path)})
        .build()
    )
    jt = topo_mod.create(spec)
    try:
        mc = jt.links["a_b"].mcache
        for i in range(6):
            mc.publish(i)
        vt = _DrainVt(wet=3)
        m = Mux(jt, "v:0", vt)
        m.HOUSE_NS = 1_000_000  # 1ms housekeeping: fast DRAIN pickup
        cnc = jt.cnc["v:0"]
        th = _run_mux_thread(m)
        try:
            _wait(lambda: cnc.signal_query() == Cnc.SIGNAL_RUN, what="RUN")
            _wait(lambda: len(vt.seqs) == 6, what="frag consumption")
            cnc.signal(Cnc.SIGNAL_DRAIN)
            _wait(lambda: cnc.signal_query() == Cnc.SIGNAL_DRAINED,
                  what="DRAINED ack")
            # the drain hook was polled until it reported dry
            assert vt.drain_polls >= 4
            # frozen cursor covers everything consumed
            assert jt.fseq[("v:0", "a_b")].query() == mc.seq0() + 6
            snap = jt.metrics["v:0"].snapshot()
            assert snap["drain_cnt"] == 1
            assert snap["drain_flush_ns"] >= 0
            # cursor manifest persisted for the successor / audit
            import json
            man_path = tmp_path / "v_0.manifest.json"
            assert man_path.exists()
            man = json.loads(man_path.read_text())
            assert man["tile"] == "v:0" and man["kind"] == "verify"
            assert man["cursors"]["a_b"] == mc.seq0() + 6
            assert man["restart_cnt"] == 0 and man["knob_gen"] == 0
            # park holds DRAINED (the finally's BOOT must not clobber it)
            time.sleep(0.05)
            assert cnc.signal_query() == Cnc.SIGNAL_DRAINED
            hb0 = cnc.heartbeat_query()
            _wait(lambda: cnc.heartbeat_query() > hb0, what="park heartbeat")
        finally:
            cnc.signal(Cnc.SIGNAL_HALT)
            th.join(10.0)
        assert not th.is_alive()
        m = None  # noqa: F841
        import gc
        gc.collect()
    finally:
        jt.close()
        jt.unlink()


def test_mux_drain_restart_zero_loss_zero_dup():
    """Rolling-restart data-plane contract, in process: incarnation 0 is
    DRAINed mid-stream (not killed), incarnation 1 resumes from the
    drained cursor — the two seq sets are disjoint and cover the whole
    stream (zero loss, zero duplicate verdicts)."""
    spec = _mini_spec("dz")
    jt = topo_mod.create(spec)
    try:
        mc = jt.links["a_b"].mcache
        for i in range(12):
            mc.publish(i)
        vt0 = _DrainVt()
        m0 = Mux(jt, "v:0", vt0)
        m0.HOUSE_NS = 1_000_000
        cnc = jt.cnc["v:0"]
        th = _run_mux_thread(m0)
        try:
            _wait(lambda: len(vt0.seqs) == 12, what="pre-drain consumption")
            cnc.signal(Cnc.SIGNAL_DRAIN)
            _wait(lambda: cnc.signal_query() == Cnc.SIGNAL_DRAINED,
                  what="DRAINED ack")
        finally:
            cnc.signal(Cnc.SIGNAL_HALT)
            th.join(10.0)
        assert not th.is_alive()

        # frags published after the drain belong to the successor
        for i in range(6):
            mc.publish(100 + i)
        vt1 = _DrainVt(die_after=6)
        m1 = Mux(jt, "v:0", vt1, restart_cnt=1)
        assert m1.ins[0].seq == mc.seq0() + 12, "successor must resume " \
            "from the drained cursor"
        m1.run()
        assert not (set(vt0.seqs) & set(vt1.seqs)), "duplicate delivery"
        assert len(vt0.seqs) + len(vt1.seqs) == 18, "lost frags"
        m0 = m1 = None  # noqa: F841
        import gc
        gc.collect()
    finally:
        jt.close()
        jt.unlink()


def test_drain_tile_acks_and_times_out():
    import threading
    run = TopoRun(_mini_spec("dt"), start=False, metrics_port=0,
                  policy=SupervisionPolicy(drain_timeout_s=5.0))
    try:
        run.procs = {"src": _FakeProc(), "v:0": _FakeProc()}
        cnc = run.jt.cnc["v:0"]
        cnc.signal(Cnc.SIGNAL_RUN)

        def _ack():
            while cnc.signal_query() != Cnc.SIGNAL_DRAIN:
                time.sleep(0.002)
            cnc.heartbeat(time.monotonic_ns())
            cnc.signal(Cnc.SIGNAL_DRAINED)

        t = threading.Thread(target=_ack, daemon=True)
        t.start()
        assert run.drain_tile("v:0", 5.0) is True
        t.join(5.0)
        # nobody acks src: bounded False, never a hang
        t0 = time.monotonic()
        assert run.drain_tile("src", 0.2) is False
        assert time.monotonic() - t0 < 2.0
        # death mid-drain is a False too (crash-respawn fallback)
        run.procs["v:0"]._alive = False
        cnc.signal(Cnc.SIGNAL_RUN)
        assert run.drain_tile("v:0", 5.0) is False
    finally:
        run.procs = {}
        run.close()


def test_drain_tile_reasserts_over_boot_stamp():
    # a tile respawned an instant before drain_tile stamps RUN on loop
    # entry, overwriting a DRAIN raised during its boot — the supervisor
    # must re-assert the lost request instead of timing out
    import threading
    run = TopoRun(_mini_spec("db"), start=False, metrics_port=0,
                  policy=SupervisionPolicy(drain_timeout_s=5.0))
    try:
        run.procs = {"src": _FakeProc(), "v:0": _FakeProc()}
        cnc = run.jt.cnc["v:0"]
        cnc.signal(Cnc.SIGNAL_BOOT)

        def _booting_tile():
            while cnc.signal_query() != Cnc.SIGNAL_DRAIN:
                time.sleep(0.002)          # supervisor raises DRAIN...
            cnc.signal(Cnc.SIGNAL_RUN)     # ...boot stamp loses it
            while cnc.signal_query() != Cnc.SIGNAL_DRAIN:
                time.sleep(0.002)          # re-asserted by drain_tile
            cnc.heartbeat(time.monotonic_ns())
            cnc.signal(Cnc.SIGNAL_DRAINED)

        t = threading.Thread(target=_booting_tile, daemon=True)
        t.start()
        assert run.drain_tile("v:0", 5.0) is True
        t.join(5.0)
    finally:
        run.procs = {}
        run.close()


def test_retile_swaps_restart_required_cfg():
    run = TopoRun(_mini_spec("rt"), start=False)
    try:
        src_cfg = dict(run.jt.tile_spec("src").cfg)
        run._retile("v:0", {"n_buffers": 5, "max_inflight": 2})
        # supervisor-side lookups (jt.tile_spec) follow the new spec
        assert run.jt.spec is run.spec
        ts = run.jt.tile_spec("v:0")
        assert ts.cfg["n_buffers"] == 5 and ts.cfg["max_inflight"] == 2
        # only the named tile's cfg changed; topology shape is intact
        assert ts.kind == "verify"
        assert [il.link for il in ts.in_links] == ["a_b"]
        assert dict(run.jt.tile_spec("src").cfg) == src_cfg
    finally:
        run.close()


def test_poll_and_healthz_report_draining():
    policy = SupervisionPolicy(heartbeat_stale_s=30.0)
    run = TopoRun(_mini_spec("dh"), start=False, metrics_port=0,
                  policy=policy)
    try:
        run.procs = {"src": _FakeProc(), "v:0": _FakeProc()}
        base = f"http://127.0.0.1:{run.metrics_port}"
        for cnc in run.jt.cnc.values():
            cnc.signal(Cnc.SIGNAL_RUN)
            cnc.heartbeat(time.monotonic_ns())

        # a DRAINing tile with a live heartbeat is an operational event,
        # not a failure: poll holds fire, healthz serves 200 "draining"
        run.jt.cnc["v:0"].signal(Cnc.SIGNAL_DRAIN)
        assert run.poll() is None
        r = urllib.request.urlopen(f"{base}/healthz", timeout=10)
        body = r.read().decode()
        assert r.status == 200
        assert body.startswith("draining\n") and "v:0" in body

        run.jt.cnc["v:0"].signal(Cnc.SIGNAL_DRAINED)
        assert run.poll() is None
        r = urllib.request.urlopen(f"{base}/healthz", timeout=10)
        assert r.read().decode().startswith("draining\n")

        # but a WEDGED drain (stale heartbeat) is still a failure
        run.jt.cnc["v:0"].signal(Cnc.SIGNAL_DRAIN)
        run.jt.cnc["v:0"].heartbeat(
            time.monotonic_ns() - int(120.0 * 1e9))
        assert run.poll() == "v:0"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/healthz", timeout=10)
        assert ei.value.code == 503 and "v:0" in ei.value.read().decode()

        # a tile mid rolling-restart is exempt from poll entirely (the
        # drain path owns its lifecycle, even through the reaped window)
        run._draining.add("v:0")
        run.procs["v:0"]._alive = False
        assert run.poll() is None
        run._draining.discard("v:0")
        assert run.poll() == "v:0"
    finally:
        run.procs = {}
        run.close()


# -- mux: fseq-cursor resume + zero-overhead fault default -------------------


def test_mux_respawn_resumes_from_fseq_cursor():
    spec = _mini_spec("rs")
    jt = topo_mod.create(spec)
    try:
        mc = jt.links["a_b"].mcache
        for i in range(10):
            mc.publish(i)
        fseq = jt.fseq[("v:0", "a_b")]
        cursor = mc.seq_query() - 3
        fseq.update(cursor)

        class _Vt:
            pass

        m0 = Mux(jt, "v:0", _Vt())              # first boot: from seq0
        assert m0.ins[0].seq == mc.seq0()
        assert m0.fault is None                 # no plan -> zero overhead
        m1 = Mux(jt, "v:0", _Vt(), restart_cnt=1)
        assert m1.ins[0].seq == cursor          # respawn: from the cursor
        assert m1.restart_cnt == 1
        # heartbeat_poke stamps the cnc and honors HALT
        hb0 = jt.cnc["v:0"].heartbeat_query()
        m1.heartbeat_poke()
        assert jt.cnc["v:0"].heartbeat_query() >= hb0
        jt.cnc["v:0"].signal(Cnc.SIGNAL_HALT)
        m1._next_poke = 0
        m1.heartbeat_poke()
        assert m1.ctx.halted
        # drop the muxes' dcache views before the workspace unmaps
        m0 = m1 = None  # noqa: F841
        import gc
        gc.collect()
    finally:
        jt.close()
        jt.unlink()
