"""Adversarial ed25519 conformance: Wycheproof/CCTV-class corpus through
every verify implementation, plus an independent cross-check of the golden
model against OpenSSL (the `cryptography` package).

Role of the reference's test_ed25519_wycheproof.c, test_ed25519_cctv.c and
test_ed25519_signature_malleability.c.  The corpus is generated in
tests/golden/ed25519_vectors.py; the OpenSSL cross-check breaks the
shared-authorship loop between the golden model and the device code.
"""

import numpy as np
import pytest

from firedancer_tpu.ops import ed25519 as ed

from golden import ed25519_golden as g
from golden.ed25519_vectors import P, build_corpus


@pytest.fixture(scope="module")
def corpus():
    c = build_corpus()
    # sanity: the generator must produce every adversarial class
    labels = {lbl.split("_")[0] for lbl, *_ in c}
    assert {"valid", "sigflip", "s", "noncanon", "smallorder",
            "undecompressible", "cross"} <= labels
    assert any(lbl == "smallorder_A_eq_holds" for lbl, *_ in c)
    assert any(lbl == "smallorder_R_eq_holds" for lbl, *_ in c)
    assert len(c) >= 40
    return c


@pytest.mark.slow
def test_noncanonical_encodings_decompress():
    """Pin the decompress-accepts-noncanonical semantic itself (golden +
    device), independent of the verify bit."""
    import jax
    import numpy as np

    from firedancer_tpu.ops import curve25519 as cv

    encs = []
    for y in range(19):
        enc = (y + P).to_bytes(32, "little")
        if g.pt_decompress(enc) is not None:
            encs.append((enc, y))
    assert encs  # at least y=1 (identity) must decompress

    arr = np.stack([np.frombuffer(e, dtype=np.uint8) for e, _ in encs])
    ok, pt = jax.jit(cv.decompress)(arr)
    ok = np.asarray(ok)
    for i, (enc, y) in enumerate(encs):
        assert bool(ok[i]), f"device rejected noncanonical y={y}"


def test_corpus_against_golden(corpus):
    for label, msg, sig, pub, expected in corpus:
        assert g.verify(msg, sig, pub) is expected, label


def test_corpus_against_host_verify(corpus):
    for label, msg, sig, pub, expected in corpus:
        assert ed.verify_one_host(sig, msg, pub) is expected, label


@pytest.mark.slow
def test_corpus_against_device_batch(corpus):
    import jax

    maxlen = 256
    usable = [v for v in corpus if len(v[1]) <= maxlen]
    assert len(usable) >= len(corpus) - 2  # only the long-msg vectors drop
    batch = 128
    assert len(usable) <= batch
    msgs = np.zeros((batch, maxlen), dtype=np.uint8)
    lens = np.zeros((batch,), dtype=np.int32)
    sigs = np.zeros((batch, 64), dtype=np.uint8)
    pubs = np.zeros((batch, 32), dtype=np.uint8)
    # pad spare lanes with the first (valid) vector so expectations are known
    pad = usable[0]
    rows = usable + [pad] * (batch - len(usable))
    expect = []
    for i, (label, msg, sig, pub, expected) in enumerate(rows):
        msgs[i, : len(msg)] = np.frombuffer(msg, dtype=np.uint8)
        lens[i] = len(msg)
        sigs[i] = np.frombuffer(sig, dtype=np.uint8)
        pubs[i] = np.frombuffer(pub, dtype=np.uint8)
        expect.append(expected)
    fn = jax.jit(ed.verify_batch)
    ok = np.asarray(fn(msgs, lens, sigs, pubs))
    for i, (label, *_rest) in enumerate(rows):
        assert bool(ok[i]) is expect[i], (i, label)


def test_golden_sign_matches_openssl():
    """Deterministic RFC 8032 signing: golden model and OpenSSL must emit
    byte-identical signatures (independent-implementation cross-check)."""
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )

    for i in range(8):
        secret = bytes([i]) * 32
        msg = b"cross-check" * (i + 1)
        sk = Ed25519PrivateKey.from_private_bytes(secret)
        ossl_pub = sk.public_key().public_bytes_raw()
        ossl_sig = sk.sign(msg)
        assert g.public_key(secret) == ossl_pub
        assert g.sign(secret, msg) == ossl_sig


def test_golden_verify_matches_openssl_on_universal_classes():
    """On semantics-universal vectors (valid sigs, corrupted sigs/keys/msgs,
    out-of-range S) golden verify and OpenSSL verify must agree.  Classes
    where strict-mode semantics legitimately diverge (small-order points,
    non-canonical encodings) are excluded — those are pinned to the
    reference's documented rules by the corpus tests above."""
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PublicKey,
    )

    universal = ("valid", "sigflip", "pubflip", "wrong", "s_", "cross")
    for label, msg, sig, pub, expected in build_corpus():
        if not label.startswith(universal):
            continue
        try:
            Ed25519PublicKey.from_public_bytes(pub).verify(sig, msg)
            ossl = True
        except (InvalidSignature, ValueError):
            ossl = False
        assert ossl is expected, label
        assert g.verify(msg, sig, pub) is ossl, label
