"""Fibre scheduler, sandbox hardening, and the IP route mirror (ref
behaviors: src/util/fibre/fd_fibre.c, src/util/sandbox/fd_sandbox.c,
src/waltz/ip/fd_ip.c)."""

import os

from firedancer_tpu.utils import sandbox
from firedancer_tpu.utils.fibre import FibreSched
from firedancer_tpu.waltz.ip import IpTable, NextHop

# ---------------------------------------------------------------------- fibre


def test_fibre_deterministic_interleave():
    log = []

    def worker(name, delay, n):
        for i in range(n):
            log.append((name, i))
            yield delay

    s = FibreSched()
    s.start(worker, "a", 10, 3)
    s.start(worker, "b", 25, 2)
    end = s.run()
    # a fires at t=0,10,20; b at t=0,25 -> deterministic order
    assert log == [("a", 0), ("b", 0), ("a", 1), ("a", 2), ("b", 1)]
    # each fibre is resumed once more after its last yield to observe
    # completion: b's final wakeup lands at 25+25
    assert end == 50


def test_fibre_run_until():
    log = []

    def tick():
        while True:
            log.append(len(log))
            yield 100

    s = FibreSched()
    s.start(tick)
    s.run(until=450)
    assert len(log) == 5  # t = 0, 100, 200, 300, 400
    s.run(until=460)
    assert len(log) == 5  # next wakeup at 500 is past the horizon


# --------------------------------------------------------------------- sandbox


def test_sandbox_best_effort_in_subprocess():
    """enter() must apply no-new-privs/undumpable and forbid forking;
    run in a child so the test process keeps its own limits."""
    import multiprocessing as mp

    def child(q):
        rep = sandbox.enter(allow_fork=False)
        can_fork = True
        try:
            pid = os.fork()
            if pid == 0:
                os._exit(0)
            os.waitpid(pid, 0)
        except OSError:
            can_fork = False
        q.put((rep, can_fork, os.geteuid()))

    ctx = mp.get_context("fork")
    q = ctx.Queue()
    p = ctx.Process(target=child, args=(q,))
    p.start()
    rep, can_fork, euid = q.get(timeout=30)
    p.join(timeout=30)
    assert rep["no_new_privs"] is True
    assert rep["undumpable"] is True
    if rep.get("nproc_zero") and euid != 0:
        assert not can_fork  # RLIMIT_NPROC=0 blocks fork (root bypasses it)


# ------------------------------------------------------------------------- ip


def test_ip_route_longest_prefix_match(tmp_path):
    # procfs encodings: little-endian hex (ntohl'd by the parser)
    route = tmp_path / "route"
    route.write_text(
        "Iface\tDestination\tGateway\tFlags\tRefCnt\tUse\tMetric\tMask\t"
        "MTU\tWindow\tIRTT\n"
        # default via 10.0.0.1 dev eth0 metric 100
        "eth0\t00000000\t0100000A\t0003\t0\t0\t100\t00000000\t0\t0\t0\n"
        # 10.0.0.0/28 on-link dev eth0
        "eth0\t0000000A\t00000000\t0001\t0\t0\t0\tF0FFFFFF\t0\t0\t0\n"
        # 192.168.7.0/24 on-link dev wg0
        "wg0\t0007A8C0\t00000000\t0001\t0\t0\t0\t00FFFFFF\t0\t0\t0\n"
    )
    arp = tmp_path / "arp"
    arp.write_text(
        "IP address       HW type     Flags       HW address"
        "            Mask     Device\n"
        "10.0.0.1         0x1         0x2         aa:bb:cc:dd:ee:01"
        "     *        eth0\n"
        "10.0.0.9         0x1         0x2         aa:bb:cc:dd:ee:09"
        "     *        eth0\n"
    )
    t = IpTable(route_path=str(route), arp_path=str(arp))
    # on-link /28 (F0FFFFFF LE = /28) match beats default
    nh = t.route("10.0.0.9")
    assert nh.iface == "eth0" and nh.gateway is None
    assert nh.mac == "aa:bb:cc:dd:ee:09"
    # off-subnet goes via the default gateway
    nh = t.route("8.8.8.8")
    assert nh.iface == "eth0" and nh.gateway == "10.0.0.1"
    assert nh.mac == "aa:bb:cc:dd:ee:01"
    # wg0 subnet
    nh = t.route("192.168.7.44")
    assert nh.iface == "wg0" and nh.gateway is None and nh.mac is None


def test_ip_table_missing_procfs_is_empty():
    t = IpTable(route_path="/nonexistent/r", arp_path="/nonexistent/a")
    assert t.route("1.2.3.4") is None


def test_seccomp_deny_blocks_socket_allows_benign():
    """Real kernel seccomp-BPF: the denylist policy must EPERM socket()
    while file IO and timers keep working (ref fd_sandbox.c seccomp
    allowlists; denylist is the CPython-compatible policy)."""
    import subprocess
    import sys
    import textwrap

    code = textwrap.dedent("""
        import socket
        from firedancer_tpu.utils import sandbox
        assert sandbox.install_seccomp_deny(), 'install failed'
        try:
            socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            raise SystemExit('socket allowed')
        except OSError:
            pass
        open('/dev/null').close()
        import time; time.sleep(0)
        print('ok')
    """)
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=120)
    assert r.returncode == 0 and r.stdout.strip() == "ok", r.stderr[-300:]


def test_seccomp_allowlist_blocks_everything_else():
    import subprocess
    import sys
    import textwrap

    code = textwrap.dedent("""
        from firedancer_tpu.utils import sandbox
        allowed = ['read','write','close','fstat','lseek','mmap','munmap',
                   'brk','futex','rt_sigaction','rt_sigprocmask','ioctl',
                   'getpid','clock_gettime','getrandom','madvise','mprotect']
        assert sandbox.install_seccomp_allow(allowed, default_errno=1)
        try:
            open('/dev/null')
            raise SystemExit('open allowed')
        except OSError:
            print('ok')
    """)
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=120)
    assert r.returncode == 0 and r.stdout.strip() == "ok", r.stderr[-300:]


def test_netlink_route_mirror_matches_procfs():
    """The rtnetlink dump (RTM_GETROUTE/RTM_GETNEIGH over a real
    AF_NETLINK socket) must agree with the procfs mirror on the same
    kernel state: identical (dest, mask, gateway, iface) route sets and
    identical next-hop answers."""
    import pytest as _pytest

    from firedancer_tpu.waltz.ip import IpTable, NetlinkIpTable, \
        netlink_routes

    try:
        nl = netlink_routes()
    except OSError as e:
        _pytest.skip(f"netlink unavailable: {e}")
    pf = IpTable()
    nl_set = {(r.dest, r.mask, r.gateway, r.iface) for r in nl}
    pf_set = {(r.dest, r.mask, r.gateway, r.iface) for r in pf.routes}
    assert pf_set <= nl_set  # procfs main table is a subset of the dump

    nt = NetlinkIpTable()
    for dst in ("127.0.0.1", "8.8.8.8"):
        a, b = nt.route(dst), pf.route(dst)
        if a is None or b is None:
            assert a == b
        else:
            assert (a.iface, a.gateway) == (b.iface, b.gateway)
