"""Golden-vector conformance against CAPTURED wire shreds (round 4,
VERDICT weak #7): tests/golden/demo-shreds.pcap carries 480 real
Agave-wire-format merkle shreds (240 data + 240 parity, the reference's
shred fixture capture, src/disco/shred/fixtures/) with the signing key
alongside.  Our parser, merkle tree, signature check, FEC recovery, and
deshredder must all agree with the capture — and deshredding must
reproduce the original entry-batch payload byte-for-byte
(demo-shreds-payload.bin)."""

import os
import struct

import pytest

from firedancer_tpu.ballet import shred as shred_lib
from firedancer_tpu.ops import ed25519 as ed

_GOLDEN = os.path.join(os.path.dirname(__file__), "golden")


def _read_pcap_udp_payloads(path):
    raw = open(path, "rb").read()
    magic = struct.unpack_from("<I", raw)[0]
    assert magic == 0xA1B2C3D4
    off = 24
    out = []
    while off + 16 <= len(raw):
        _ts, _tus, incl, _orig = struct.unpack_from("<IIII", raw, off)
        off += 16
        pkt = raw[off : off + incl]
        off += incl
        if pkt[12:14] == b"\x08\x00" and pkt[23] == 17:
            ihl = (pkt[14] & 0xF) * 4
            out.append(bytes(pkt[14 + ihl + 8 :]))
    return out


@pytest.fixture(scope="module")
def capture():
    shreds = _read_pcap_udp_payloads(
        os.path.join(_GOLDEN, "demo-shreds.pcap"))
    key = open(os.path.join(_GOLDEN, "demo-shreds.key"), "rb").read()
    payload = open(os.path.join(_GOLDEN, "demo-shreds-payload.bin"),
                   "rb").read()
    return shreds, key[32:], payload        # (wire shreds, pubkey, batch)


def test_capture_shape(capture):
    shreds, _, _ = capture
    assert len(shreds) == 480
    assert sorted({len(s) for s in shreds}) == [1203, 1228]


def test_parse_every_wire_shred(capture):
    shreds, _, _ = capture
    n_data = n_code = 0
    slots = set()
    for raw in shreds:
        sh = shred_lib.parse(raw)
        slots.add(sh.slot)
        if sh.is_data:
            n_data += 1
        else:
            n_code += 1
        assert sh.merkle_proof_len >= 0
    assert n_data == 240 and n_code == 240
    assert len(slots) <= 2, f"capture spans slots {slots}"


def test_leader_signature_verifies_on_every_shred(capture):
    """The shred signature covers the FEC set's merkle root; all 480 must
    verify against the capture's signing key (consensus acceptance)."""
    shreds, pubkey, _ = capture
    roots = {}
    for raw in shreds:
        sh = shred_lib.parse(raw)
        root = sh.merkle_root()
        assert root is not None, "merkle walk failed on a real shred"
        roots.setdefault((sh.slot, sh.fec_set_idx), set()).add(
            (root, sh.signature))
    for key, rs in roots.items():
        assert len(rs) == 1, f"fec set {key} disagrees on its root"
        root, sig = next(iter(rs))
        assert ed.verify_one_host(sig, root, pubkey), key


def test_deshred_reproduces_reference_payload(capture):
    """Data shreds reassemble to the exact original entry batch."""
    shreds, _, payload = capture
    data = [shred_lib.parse(raw) for raw in shreds]
    data = sorted((s for s in data if s.is_data), key=lambda s: s.idx)
    assert data[0].idx == 0
    assert data[-1].idx == len(data) - 1
    out = b"".join(s.payload() for s in data)
    assert out[: len(payload)] == payload
    assert not any(out[len(payload):]), "non-zero padding after batch"


def test_fec_recovery_on_real_sets(capture):
    """Drop half of each real FEC set's data shreds; reedsol recovery
    must reproduce the dropped shreds bit-exactly."""
    shreds, _, _ = capture
    parsed = [shred_lib.parse(raw) for raw in shreds]
    by_set = {}
    for sh, raw in zip(parsed, shreds):
        by_set.setdefault((sh.slot, sh.fec_set_idx), []).append((sh, raw))
    checked = 0
    for (slot, fsi), members in sorted(by_set.items())[:3]:
        datas = sorted(((s, r) for s, r in members if s.is_data),
                       key=lambda t: t[0].idx)
        codes = sorted(((s, r) for s, r in members if not s.is_data),
                       key=lambda t: t[0].idx)
        rx = shred_lib.FecResolver()
        # feed the SURVIVORS: every second data shred + all parity
        survivors = [s for i, (s, r) in enumerate(datas) if i % 2 == 0]
        survivors += [s for s, r in codes]
        for s in survivors:
            assert rx.add(s), f"real shred rejected in set {slot}/{fsi}"
        rec = rx.recover()   # per-data-shred reedsol-protected regions
        assert len(rec) == len(datas)
        for i, (s, raw) in enumerate(datas):
            want = raw[64 : 64 + len(rec[i])]
            assert rec[i] == want, \
                f"set {slot}/{fsi}: data {i} region not bit-exact " \
                f"({'recovered' if i % 2 else 'direct'})"
        checked += 1
    assert checked >= 1
