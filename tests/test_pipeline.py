"""End-to-end verify slice: signed txn bytes -> parse -> dedup -> device
verify -> verdicts (the reference's test_verify tile test + fddev bench
shape, SURVEY.md §4.5)."""

import secrets

import jax
import numpy as np
import pytest

from firedancer_tpu.ballet import txn as txn_lib
from firedancer_tpu.disco.pipeline import VerifyPipeline
from firedancer_tpu.ops import ed25519 as ed

BATCH = 16
MAXLEN = 256


def make_signed_txn(nonce: int, nsig: int = 1) -> bytes:
    """A well-formed, correctly signed transfer-like txn."""
    seeds = [bytes([i + 1]) * 32 for i in range(nsig)]
    pubs = [ed.keypair_from_seed(s)[0] for s in seeds]
    program = secrets.token_bytes(32)
    msg = txn_lib.build_unsigned(
        pubs,
        secrets.token_bytes(32),
        [(nsig, bytes(range(nsig)), nonce.to_bytes(8, "little"))],
        [program],
    )
    sigs = [ed.sign(s, msg) for s in seeds]
    return txn_lib.assemble(sigs, msg)


@pytest.fixture(scope="module")
def pipeline():
    fn = jax.jit(ed.verify_batch)
    return VerifyPipeline(fn, batch=BATCH, msg_maxlen=MAXLEN, tcache_depth=64)


def test_end_to_end(pipeline):
    pipeline.tcache.reset()
    good = [make_signed_txn(i) for i in range(5)]
    bad_sig = bytearray(make_signed_txn(100))
    bad_sig[5] ^= 1  # corrupt signature byte
    garbage = secrets.token_bytes(200)
    dup = good[0]

    for t in good:
        pipeline.submit(t)
    pipeline.submit(bytes(bad_sig))
    pipeline.submit(garbage)
    pipeline.submit(dup)
    passed = pipeline.flush()

    m = pipeline.metrics
    assert m.txns_in == 8
    assert m.parse_fail == 1
    assert m.dedup_drop == 1
    assert m.verify_pass == 5
    assert m.verify_fail == 1
    assert sorted(p for p, _ in passed) == sorted(good)


def test_multisig_all_lanes_must_pass(pipeline):
    pipeline.tcache.reset()
    t3 = make_signed_txn(200, nsig=3)
    pipeline.submit(t3)
    assert [p for p, _ in pipeline.flush()] == [t3]

    # corrupt only the SECOND signature: txn must fail as a whole
    bad = bytearray(make_signed_txn(201, nsig=3))
    bad[1 + 64 + 5] ^= 1
    pipeline.submit(bytes(bad))
    assert pipeline.flush() == []


def test_batch_overflow_flushes(pipeline):
    pipeline.tcache.reset()
    txns = [make_signed_txn(1000 + i) for i in range(BATCH + 3)]
    flushed = []
    for t in txns:
        flushed += pipeline.submit(t)
    assert len(flushed) == BATCH  # auto-flushed when full
    flushed += pipeline.flush()
    assert len(flushed) == BATCH + 3
    p99 = pipeline.metrics.snapshot()["batch_ns_p99"]
    assert p99 > 0


def test_too_long_dropped(pipeline):
    pipeline.tcache.reset()
    seeds = [b"\x01" * 32]
    pubs = [ed.keypair_from_seed(s)[0] for s in seeds]
    msg = txn_lib.build_unsigned(
        pubs,
        secrets.token_bytes(32),
        [(1, b"\x00", secrets.token_bytes(400))],
        [secrets.token_bytes(32)],
    )
    payload = txn_lib.assemble([ed.sign(seeds[0], msg)], msg)
    before = pipeline.metrics.too_long_drop
    assert pipeline.submit(payload) == []
    assert pipeline.metrics.too_long_drop == before + 1


def test_full_mtu_txn_verifies_in_bucket_ladder():
    """A wire-MTU-sized txn (1232 B, ref src/ballet/txn/fd_txn.h:92-103)
    must route to the full-width bucket and verify end-to-end, while small
    txns fill the narrow bucket — no silent too_long_drop."""
    fn = jax.jit(ed.verify_batch)
    p = VerifyPipeline(fn, buckets=[(4, 256), (2, 1232)], tcache_depth=64)

    seed = b"\x07" * 32
    pub = ed.keypair_from_seed(seed)[0]
    # pad instruction data until the whole payload hits the 1232 B MTU
    small = make_signed_txn(1)
    probe = txn_lib.build_unsigned(
        [pub], secrets.token_bytes(32), [(1, b"\x00", b"")],
        [secrets.token_bytes(32)])
    pad = 1232 - (1 + 64 + len(probe))
    big_msg = txn_lib.build_unsigned(
        [pub], secrets.token_bytes(32),
        [(1, b"\x00", secrets.token_bytes(pad - 2))],  # -2: varint len grows
        [secrets.token_bytes(32)])
    big = txn_lib.assemble([ed.sign(seed, big_msg)], big_msg)
    assert len(big) > 1200, len(big)

    p.submit(small)
    p.submit(big)
    passed = p.flush()
    assert p.metrics.too_long_drop == 0
    assert sorted(pl for pl, _ in passed) == sorted([small, big])
    # the two txns landed in different buckets => two device batches
    assert p.metrics.batches == 2


def test_sig_overflow_dropped_not_crashed():
    fn = jax.jit(ed.verify_batch)
    p = VerifyPipeline(fn, batch=2, msg_maxlen=MAXLEN)
    assert p.submit(make_signed_txn(999, nsig=3)) == []
    assert p.metrics.sig_overflow_drop == 1
    assert p.flush() == []
