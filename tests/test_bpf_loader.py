"""On-chain sBPF program lifecycle: deploy via the loader, invoke through
the executor, mutate account state from inside the VM (ref behaviors:
src/flamenco/runtime/program/fd_bpf_loader_v3_program.c + the runtime
test-vectors harness)."""

import struct

from firedancer_tpu.ballet import txn as txn_lib
from firedancer_tpu.ballet.sbpf import asm
from firedancer_tpu.flamenco import genesis as gen_mod
from firedancer_tpu.flamenco import system_program as sysprog
from firedancer_tpu.flamenco.bpf_loader import ix_deploy
from firedancer_tpu.flamenco.runtime import Runtime
from firedancer_tpu.flamenco.types import (Account, BPF_LOADER_ID,
                                           SYSTEM_PROGRAM_ID)
from firedancer_tpu.ops import ed25519 as ed
from tests.test_sbpf_vm import _mini_elf


def _keypair(i):
    seed = i.to_bytes(32, "little")
    return seed, ed.keypair_from_seed(seed)[0]


def _signed(signers, msg):
    return txn_lib.assemble([ed.sign(s, msg) for s, _ in signers], msg)


# a program that stores the first 8 bytes of instr data into account 0's
# data: input layout (bpf_loader.py ABI) for 1 account with data_len=8:
#   [0]=n_accounts, [8]=signer/writable, [10]=pubkey, [42]=owner,
#   [74]=lamports, [82]=data_len, [90]=data(8), pad to 104,
#   [104]=instr_len, [112]=instr
PROG = asm("""
    mov r6, r1
    ldxdw r2, [r6+112]
    stxdw [r6+90], r2
    mov r0, 0
    exit""")


def test_deploy_and_invoke():
    faucet_seed, faucet_pk = _keypair(1)
    prog_seed, prog_pk = _keypair(2)
    data_seed, data_pk = _keypair(3)
    g = gen_mod.create(faucet_pk, creation_time=1)
    # pre-fund the program + data accounts (system-create path is covered
    # by runtime tests; here the loader path is under test)
    g.accounts[prog_pk] = Account(lamports=1_000_000)
    # the data account must be OWNED by the program for it to write data
    g.accounts[data_pk] = Account(lamports=1_000_000, data=bytes(8),
                                  owner=prog_pk)
    rt = Runtime(g)
    b = rt.new_bank(1)

    elf = _mini_elf(PROG)
    msg = txn_lib.build_unsigned(
        [faucet_pk, prog_pk], rt.root_hash,
        [(2, bytes([1]), ix_deploy(elf))],
        extra_accounts=[BPF_LOADER_ID], readonly_unsigned_cnt=1)
    res = b.execute_txn(_signed([(faucet_seed, faucet_pk),
                                 (prog_seed, prog_pk)], msg))
    assert res.ok, res.err
    pa = rt.accdb.load(b.xid, prog_pk)
    assert pa.executable and pa.owner == BPF_LOADER_ID

    # invoke: program writes instr data into the data account
    magic = struct.pack("<Q", 0xFEEDFACECAFE)
    msg2 = txn_lib.build_unsigned(
        [faucet_pk], rt.root_hash,
        [(2, bytes([1]), magic)],
        extra_accounts=[data_pk, prog_pk], readonly_unsigned_cnt=1)
    res2 = b.execute_txn(_signed([(faucet_seed, faucet_pk)], msg2))
    assert res2.ok, res2.err
    da = rt.accdb.load(b.xid, data_pk)
    assert da.data == magic
    assert res2.compute_units > 0


def test_program_error_aborts_txn():
    faucet_seed, faucet_pk = _keypair(1)
    prog_pk = _keypair(4)[1]
    data_pk = _keypair(5)[1]
    bad_prog = asm("""
        mov r0, 42
        exit""")
    g = gen_mod.create(faucet_pk, creation_time=1)
    g.accounts[prog_pk] = Account(lamports=1, data=_mini_elf(bad_prog),
                                  owner=BPF_LOADER_ID, executable=True)
    g.accounts[data_pk] = Account(lamports=500, owner=BPF_LOADER_ID)
    rt = Runtime(g)
    b = rt.new_bank(1)
    msg = txn_lib.build_unsigned(
        [faucet_pk], rt.root_hash,
        [(2, bytes([1]), b"")],
        extra_accounts=[data_pk, prog_pk], readonly_unsigned_cnt=1)
    res = b.execute_txn(_signed([(faucet_seed, faucet_pk)], msg))
    assert not res.ok and "program error 0x2a" in res.err


def test_program_cannot_write_unowned_account():
    """Solana's owner rule: a program may only modify data of accounts it
    owns — a loader-owned (or vote-owned, etc.) account is off limits."""
    faucet_seed, faucet_pk = _keypair(1)
    prog_pk = _keypair(6)[1]
    victim_pk = _keypair(7)[1]
    g = gen_mod.create(faucet_pk, creation_time=1)
    g.accounts[prog_pk] = Account(lamports=1, data=_mini_elf(PROG),
                                  owner=BPF_LOADER_ID, executable=True)
    g.accounts[victim_pk] = Account(lamports=500, data=bytes(8),
                                    owner=BPF_LOADER_ID)
    rt = Runtime(g)
    b = rt.new_bank(1)
    msg = txn_lib.build_unsigned(
        [faucet_pk], rt.root_hash,
        [(2, bytes([1]), struct.pack("<Q", 1))],
        extra_accounts=[victim_pk, prog_pk], readonly_unsigned_cnt=1)
    res = b.execute_txn(_signed([(faucet_seed, faucet_pk)], msg))
    assert not res.ok and "does not own" in res.err
    assert rt.accdb.load(b.xid, victim_pk).data == bytes(8)
