"""Batched turbine shred lane (round 13): batched leader-sig admission
discipline (forge-then-censor resistance under deferred forwarding),
device-vs-host merkle root parity, and the ShredRecoverIngest packed
recover workload over the shared dispatch engine."""

import numpy as np
import pytest

from firedancer_tpu.ballet import shred as shred_lib
from firedancer_tpu.ops import ed25519 as ed

SEED = bytes(range(32))


def _leader():
    return ed.keypair_from_seed(SEED)[0]


def _mk_set(entry: bytes, slot: int = 5, data_cnt: int = 8,
            code_cnt: int = 8):
    return shred_lib.make_fec_set(
        entry, slot=slot, parent_off=1, version=1, fec_set_idx=0,
        sign_fn=lambda root: ed.sign(SEED, root),
        data_cnt=data_cnt, code_cnt=code_cnt)


# ---------------------------------------------------------------------------
# _ShredSigBatcher: admission discipline (host backend — the discipline
# is backend-independent; device parity rides the slow tier)


def _drive(stream, batch=8, backend="host"):
    """Run the ShredTile admission protocol over (raw, leader) pairs:
    ingress dedup query -> queue -> flush at `batch` -> verdict-time
    re-query -> insert + forward.  Returns the accounting."""
    from firedancer_tpu.disco.tiles import _ShredSigBatcher

    b = _ShredSigBatcher(batch=batch, backend=backend)
    dedup, forwards = set(), []
    censored = dup_ingress = dup_verdict = 0

    def admit(verdicts):
        nonlocal censored, dup_verdict
        for s, raw, tag, ok in verdicts:
            if not ok:
                censored += 1
                continue
            if tag in dedup:
                dup_verdict += 1
                continue
            dedup.add(tag)
            forwards.append(raw)

    for raw, leader in stream:
        s = shred_lib.parse(raw)
        tag = (s.slot << 17) | (s.idx << 1) | int(s.is_data)
        if tag in dedup:
            dup_ingress += 1
            continue
        b.add(s, raw, tag, leader)
        if b.full:
            admit(b.flush())
    admit(b.flush())
    return forwards, censored, dup_ingress, dup_verdict


def test_batcher_forwards_valid_burst():
    fs = _mk_set(b"x" * 1000)
    raws = fs.data_shreds + fs.code_shreds
    fwd, censored, di, dv = _drive([(r, _leader()) for r in raws])
    assert sorted(fwd) == sorted(raws)
    assert censored == 0 and di == 0 and dv == 0


def test_batcher_forge_then_censor_ordering():
    # a forged-signature copy arriving FIRST is censored without
    # inserting its tag; the genuine shred arriving later (even in a
    # LATER batch) must still forward — the insert-only-after-signed
    # discipline survives deferred batch forwarding
    fs = _mk_set(b"y" * 800)
    raws = fs.data_shreds + fs.code_shreds
    forged = bytearray(raws[0])
    forged[3] ^= 0xFF                        # signature byte only
    stream = [(bytes(forged), _leader())]
    stream += [(r, _leader()) for r in raws]
    fwd, censored, di, dv = _drive(stream, batch=4)
    assert censored == 1
    assert raws[0] in fwd, "forged copy censored the genuine shred"
    assert sorted(fwd) == sorted(raws)


def test_batcher_same_batch_duplicate_single_forward():
    # both copies of a shred queue before either verdict lands: the
    # verdict-time re-query must drop the second copy
    fs = _mk_set(b"z" * 600)
    raws = fs.data_shreds[:4]
    stream = []
    for r in raws:
        stream.append((r, _leader()))
        stream.append((r, _leader()))
    fwd, censored, di, dv = _drive(stream, batch=8)
    assert sorted(fwd) == sorted(raws)
    assert dv == len(raws) and di == 0 and censored == 0


def test_batcher_unknown_leader_censored():
    fs = _mk_set(b"w" * 500)
    stream = [(fs.data_shreds[0], None), (fs.data_shreds[1], _leader())]
    fwd, censored, di, dv = _drive(stream)
    assert fwd == [fs.data_shreds[1]]
    assert censored == 1


def test_batcher_age_deadline():
    from firedancer_tpu.disco.tiles import _ShredSigBatcher

    fs = _mk_set(b"q" * 300)
    b = _ShredSigBatcher(batch=32, backend="host", flush_age_us=0)
    assert not b.due()                       # empty queue never fires
    s = shred_lib.parse(fs.data_shreds[0])
    b.add(s, fs.data_shreds[0], 1, _leader())
    assert b.due()                           # zero age: due immediately
    out = b.flush()
    assert len(out) == 1 and out[0][3] is True
    assert not b.due()                       # drained queue re-arms


@pytest.mark.slow
def test_batcher_device_matches_host():
    # the batched bmtree+SigVerifier path returns the same verdicts as
    # per-shred host verification on a mixed burst
    from firedancer_tpu.disco.tiles import _ShredSigBatcher

    fs = _mk_set(b"d" * 700)
    raws = fs.data_shreds + fs.code_shreds
    forged = bytearray(raws[5])
    forged[8] ^= 0x01
    burst = [(r, _leader()) for r in raws[:6]]
    burst.append((bytes(forged), _leader()))
    burst.append((raws[7], None))

    verdicts = {}
    for backend in ("host", "device"):
        b = _ShredSigBatcher(batch=8, backend=backend)
        if backend == "device":
            b.warm()
        for i, (raw, leader) in enumerate(burst):
            b.add(shred_lib.parse(raw), raw, i, leader)
        verdicts[backend] = [(tag, ok) for _, _, tag, ok in b.flush()]
    assert verdicts["device"] == verdicts["host"]


# ---------------------------------------------------------------------------
# bmtree: batched device roots vs the np twin and the per-shred walk


def test_bmtree_batch_roots_device_vs_np():
    from firedancer_tpu.ballet import bmtree

    fs = _mk_set(b"m" * 1200)
    shreds = [shred_lib.parse(r) for r in fs.data_shreds + fs.code_shreds]
    B = len(shreds)
    maxlen = max(len(s.merkle_leaf_data()) for s in shreds)
    depth = shreds[0].merkle_proof_len
    leaf = np.zeros((B, maxlen), np.uint8)
    lens = np.zeros((B,), np.int32)
    idxs = np.zeros((B,), np.int32)
    proofs = np.zeros((B, depth, bmtree.MERKLE_NODE_SZ), np.uint8)
    depths = np.full((B,), depth, np.int32)
    for j, s in enumerate(shreds):
        ld = s.merkle_leaf_data()
        leaf[j, :len(ld)] = np.frombuffer(ld, np.uint8)
        lens[j] = len(ld)
        idxs[j] = s.tree_index()
        for d, node in enumerate(s.proof_nodes()):
            proofs[j, d] = np.frombuffer(node, np.uint8)
    got = np.asarray(bmtree.batch_walk_roots_jit()(
        leaf, lens, idxs, proofs, depths))
    want = bmtree.np_batch_walk_roots(
        [s.merkle_leaf_data() for s in shreds],
        [s.tree_index() for s in shreds],
        [s.proof_nodes() for s in shreds])
    for j, s in enumerate(shreds):
        assert bytes(got[j]) == want[j], j
        assert bytes(got[j]) == s.merkle_root(), j
        assert bytes(got[j]) == fs.merkle_root, j


# ---------------------------------------------------------------------------
# FecResolver batching seams: recover_args / data_regions /
# assemble_payload must compose back to the pre-round-13 recover()


def _resolver_with(fs, drop=()):
    res = shred_lib.FecResolver()
    for i, raw in enumerate(fs.data_shreds + fs.code_shreds):
        if i in drop:
            continue
        assert res.add(shred_lib.parse(raw))
    return res


def test_resolver_seams_roundtrip():
    from firedancer_tpu.ballet import reedsol as rs

    entry = bytes(np.random.default_rng(3).integers(0, 256, 3000,
                                                    dtype=np.uint8))
    fs = _mk_set(entry)
    res = _resolver_with(fs, drop={1, 3, 10})     # data + code erasures
    assert res.ready()
    args = res.recover_args()
    assert args is not None
    shreds, k, sz = args
    assert k == 8 and sum(s is None for s in shreds) == 3
    regions = res.data_regions(rs.recover(shreds, k, sz, device=False))
    payload = shred_lib.FecResolver.assemble_payload(regions)
    assert payload == entry
    assert res.payloads() == entry                # the composed legacy path


def test_resolver_all_data_fast_path():
    entry = b"all-data" * 100
    fs = _mk_set(entry)
    res = _resolver_with(fs, drop=set(range(8, 16)))  # every code shred
    assert res.ready()
    assert res.recover_args() is None             # nothing to recover
    assert shred_lib.FecResolver.assemble_payload(
        res.data_regions()) == entry


def test_resolver_batch_matches_perset():
    from firedancer_tpu.ballet import reedsol as rs

    entries = [bytes([i]) * (400 + 37 * i) for i in range(4)]
    fss = [_mk_set(e, slot=20 + i) for i, e in enumerate(entries)]
    resolvers = [_resolver_with(fs, drop={2 * i, 9})
                 for i, fs in enumerate(fss)]
    triples = [r.recover_args() for r in resolvers]
    outs = rs.recover_batch(triples, device=False)
    for entry, res, out in zip(entries, resolvers, outs):
        assert not isinstance(out, ValueError)
        assert shred_lib.FecResolver.assemble_payload(
            res.data_regions(out)) == entry


# ---------------------------------------------------------------------------
# ShredRecoverIngest: the packed recover workload on the rotating engine


@pytest.fixture(scope="module")
def ingest():
    from firedancer_tpu.disco.tiles import ShredRecoverIngest

    # 8+8 geometry: protected span 1139 - 20*4
    ing = ShredRecoverIngest(k_max=8, n_max=16, sz=1059, batch=4, nbuf=2)
    ing.warm()
    return ing


def test_ingest_roundtrip_bit_exact(ingest):
    from firedancer_tpu.ballet import reedsol as rs

    entries = [bytes(np.random.default_rng(40 + i).integers(
        0, 256, 2500, dtype=np.uint8)) for i in range(3)]
    fss = [_mk_set(e, slot=30 + i) for i, e in enumerate(entries)]
    resolvers = [_resolver_with(fs, drop={1, 8 + i})
                 for i, fs in enumerate(fss)]
    triples = [r.recover_args() for r in resolvers]

    verdicts = list(ingest.submit_sets(triples))
    verdicts += ingest.drain()
    assert len(verdicts) == 1
    full, ok = ingest.split_verdict(verdicts[0])
    assert ok.all()                          # padding rows self-consistent
    for r, (res, triple, entry) in enumerate(
            zip(resolvers, triples, entries)):
        golden = rs.recover(*triple, device=False)
        got = [full[r, i, :] for i in range(len(triple[0]))]
        assert all(np.array_equal(a, b) for a, b in zip(golden, got)), r
        assert shred_lib.FecResolver.assemble_payload(
            res.data_regions(got)) == entry


def test_ingest_flags_corrupt_set(ingest):
    fs = _mk_set(b"c" * 900, slot=40)
    res = _resolver_with(fs, drop={0})
    shreds, k, sz = res.recover_args()
    bad = list(shreds)
    idx = max(i for i, s in enumerate(bad) if s is not None)
    tampered = np.array(bad[idx], copy=True)
    tampered[5] ^= 0x10                      # surviving but inconsistent
    bad[idx] = tampered
    verdicts = list(ingest.submit_sets([(bad, k, sz)]))
    verdicts += ingest.drain()
    _, ok = ingest.split_verdict(verdicts[0])
    assert not ok[0]


def test_ingest_rejects_bad_geometry(ingest):
    with pytest.raises(ValueError, match="geometry"):
        list(ingest.submit_sets(
            [([np.zeros(64, np.uint8)] * 4, 2, 64)]))
        ingest.drain()
    ingest.drain()                           # engine stays usable
    with pytest.raises(ValueError, match="> engine batch"):
        ingest.submit_sets([([np.zeros(1059, np.uint8)] * 2, 1, 1059)] * 5)
    with pytest.raises(ValueError, match="unrecoverable"):
        list(ingest.submit_sets([([None] * 16, 8, 1059)]))
        ingest.drain()
    ingest.drain()


def test_shred_recover_tile_registered():
    from firedancer_tpu.disco import metrics
    from firedancer_tpu.disco.tiles import TILES, ShredRecoverTile

    assert TILES["shred_recover"] is ShredRecoverTile
    slots = metrics.TILE_SLOTS["shred_recover"]
    names = [s[0] if isinstance(s, tuple) else s for s in slots]
    for want in ("fec_complete_cnt", "fec_recovered_cnt", "fec_fail_cnt",
                 "fec_host_fallback_cnt", "recover_pending"):
        assert want in names
