"""Shred format + shredder + FEC resolver tests: wire round trips, merkle
inclusion verification, erasure recovery, and tamper rejection."""

import numpy as np
import pytest

from firedancer_tpu.ballet import shred
from firedancer_tpu.ops import ed25519 as ed


SEED = b"\x01" * 32


def _sign_fn(root: bytes) -> bytes:
    return ed.sign(SEED, root)


def _mk_set(batch=None, data_cnt=8, code_cnt=8, slot=7, **kw):
    if batch is None:
        batch = bytes(np.random.default_rng(0).integers(0, 256, 5000, dtype=np.uint8))
    return batch, shred.make_fec_set(
        batch, slot=slot, parent_off=1, version=3, fec_set_idx=64,
        sign_fn=_sign_fn, data_cnt=data_cnt, code_cnt=code_cnt, **kw
    )


def test_fec_set_shapes_and_parse():
    batch, fs = _mk_set()
    assert len(fs.data_shreds) == 8 and len(fs.code_shreds) == 8
    for i, raw in enumerate(fs.data_shreds):
        s = shred.parse(raw)
        assert s.is_data and s.slot == 7 and s.idx == 64 + i
        assert s.fec_set_idx == 64 and s.version == 3
        assert s.merkle_proof_len == 4  # 16 leaves -> depth 4
        assert len(raw) <= shred.MAX_SZ
    for j, raw in enumerate(fs.code_shreds):
        s = shred.parse(raw)
        assert not s.is_data
        assert s.data_cnt == 8 and s.code_cnt == 8 and s.code_idx == j
    # last data shred carries DATA_COMPLETE
    last = shred.parse(fs.data_shreds[-1])
    assert last.flags & shred.FLAG_DATA_COMPLETE


def test_signature_covers_root():
    batch, fs = _mk_set()
    pub, _, _ = ed.keypair_from_seed(SEED)
    s = shred.parse(fs.data_shreds[0])
    import jax.numpy as jnp

    ok = ed.verify_batch_single_msg(
        jnp.asarray(np.frombuffer(fs.merkle_root, dtype=np.uint8)),
        jnp.asarray(np.frombuffer(s.signature, dtype=np.uint8)[None, :]),
        jnp.asarray(np.frombuffer(pub, dtype=np.uint8)[None, :]),
    )
    assert bool(np.asarray(ok)[0])


def test_resolver_accepts_and_reassembles_no_loss():
    batch, fs = _mk_set()
    r = shred.FecResolver()
    for raw in fs.code_shreds + fs.data_shreds:
        assert r.add(shred.parse(raw)), "valid shred rejected"
    assert r.ready()
    assert r.payloads() == batch


def test_resolver_recovers_erasures():
    batch, fs = _mk_set(data_cnt=8, code_cnt=8)
    # lose 5 data shreds and 3 code shreds (8 survivors >= k=8)
    r = shred.FecResolver()
    for raw in fs.data_shreds[:3] + fs.code_shreds[:5]:
        assert r.add(shred.parse(raw))
    assert r.ready()
    assert r.payloads() == batch


def test_resolver_needs_k_shreds():
    batch, fs = _mk_set(data_cnt=8, code_cnt=8)
    r = shred.FecResolver()
    for raw in fs.data_shreds[:4] + fs.code_shreds[:3]:  # 7 < 8
        r.add(shred.parse(raw))
    assert not r.ready()
    with pytest.raises(ValueError):
        r.recover()


def test_resolver_rejects_tampered_payload():
    batch, fs = _mk_set()
    raw = bytearray(fs.data_shreds[2])
    raw[shred.DATA_HEADER_SZ + 5] ^= 0xFF  # flip a payload byte

    # wire format stores only the PROOF (round 4): a lone tampered shred
    # walks to a different-but-self-consistent root, so rejection comes
    # from (a) the signature gate on the first member's computed root...
    r = shred.FecResolver(
        root_check=lambda root, sig: root == fs.merkle_root)
    assert not r.add(shred.parse(bytes(raw)))
    assert r.add(shred.parse(fs.data_shreds[0]))

    # ...or (b) root disagreement with an honest member already admitted
    r2 = shred.FecResolver()
    assert r2.add(shred.parse(fs.data_shreds[0]))
    assert not r2.add(shred.parse(bytes(raw)))


def test_resolver_rejects_foreign_shred():
    batch, fs = _mk_set()
    _, fs2 = _mk_set(batch=b"other batch contents" * 100)
    r = shred.FecResolver()
    assert r.add(shred.parse(fs.data_shreds[0]))
    # shred from a different FEC set (different root) rejected
    assert not r.add(shred.parse(fs2.data_shreds[1]))


def test_parse_rejects_garbage():
    with pytest.raises(shred.ShredParseError):
        shred.parse(b"\x00" * 20)  # too short
    batch, fs = _mk_set()
    raw = bytearray(fs.data_shreds[0])
    raw[0x40] = 0x30  # invalid type nibble
    with pytest.raises(shred.ShredParseError):
        shred.parse(bytes(raw))
    raw = bytearray(fs.data_shreds[0])
    raw[0x56:0x58] = (60000).to_bytes(2, "little")  # size > buffer
    with pytest.raises(shred.ShredParseError):
        shred.parse(bytes(raw))


def test_capacity_limit():
    with pytest.raises(ValueError, match="capacity"):
        shred.make_fec_set(
            b"x" * (shred.MAX_SZ * 9), slot=1, parent_off=1, version=1,
            fec_set_idx=0, sign_fn=_sign_fn, data_cnt=8, code_cnt=8,
        )


def test_resolver_spoofed_code_counts_do_not_poison_set():
    """A rejected first code shred with a forged data_cnt must not commit
    its counts — honest members must still assemble the set (one spoofed
    packet could otherwise DoS the whole FEC set)."""
    batch, fs = _mk_set()
    spoof = bytearray(fs.code_shreds[0])
    spoof[0x53] = 7  # forge data_cnt (low byte)
    r = shred.FecResolver(
        root_check=lambda root, sig: root == fs.merkle_root)
    assert not r.add(shred.parse(bytes(spoof)))
    assert r.data_cnt is None                 # nothing committed
    for raw in fs.code_shreds:
        assert r.add(shred.parse(raw))
    for raw in fs.data_shreds[: len(fs.data_shreds) // 2]:
        assert r.add(shred.parse(raw))
    assert r.ready()
    assert r.recover()
