"""Multi-chip collectives on the virtual 8-device CPU mesh (SURVEY.md §4.4
pattern: jax sharding semantics are identical between the CPU mesh and a
real pod slice): ring point fold over the mesh axis, and the data-parallel
RLC/MSM verify (BASELINE config #5)."""

import numpy as np
import pytest

import jax

from firedancer_tpu.models.verifier import make_example_batch
from firedancer_tpu.ops import curve25519 as cv
from firedancer_tpu.ops import ed25519 as ed
from firedancer_tpu.ops import f25519 as fe
from firedancer_tpu.parallel import collectives as co
from firedancer_tpu.parallel import mesh as pm

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"need {N_DEV} devices")
    return pm.make_mesh(N_DEV)


def _host_point(p, i=None):
    """Device Point -> python affine pair for comparison."""
    X = fe.to_int(np.asarray(p.X) if i is None else np.asarray(p.X)[:, i])
    Y = fe.to_int(np.asarray(p.Y) if i is None else np.asarray(p.Y)[:, i])
    Z = fe.to_int(np.asarray(p.Z) if i is None else np.asarray(p.Z)[:, i])
    zi = pow(Z, fe.P - 2, fe.P)
    return (X * zi % fe.P, Y * zi % fe.P)


def test_ring_point_fold(mesh):
    # 8 partial points: [i+1]B on device i; ring-fold must give [36]B
    from firedancer_tpu.ops.ed25519 import (
        _compress_host,
        _scalar_mul_base_host,
    )

    parts = []
    for i in range(N_DEV):
        x, y, z, t = _scalar_mul_base_host(i + 1)
        zi = pow(z, fe.P - 2, fe.P)
        parts.append((x * zi % fe.P, y * zi % fe.P))
    stack = {
        "X": np.stack([np.asarray(fe.const(x)).reshape(fe.NLIMB)
                       for x, _ in parts]),
        "Y": np.stack([np.asarray(fe.const(y)).reshape(fe.NLIMB)
                       for _, y in parts]),
    }
    ones = np.stack([np.asarray(fe.const(1)).reshape(fe.NLIMB)] * N_DEV)
    ts = np.stack([np.asarray(fe.const(x * y % fe.P)).reshape(fe.NLIMB)
                   for x, y in parts])
    fold = co.ring_point_fold(mesh)
    X, Y, Z, T = fold(stack["X"], stack["Y"], ones, ts)
    total = _scalar_mul_base_host(sum(range(1, N_DEV + 1)))  # [36]B
    zi = pow(total[2], fe.P - 2, fe.P)
    want = (total[0] * zi % fe.P, total[1] * zi % fe.P)
    for i in range(N_DEV):  # replicated on every device
        got = _host_point(
            cv.Point(X[i], Y[i], Z[i], T[i]))
        assert got == want


def test_shard_rlc_verify(mesh):
    batch = 4 * N_DEV  # 4 sigs per device, m=2
    msgs, lens, sigs, pubs = make_example_batch(
        batch, 64, valid=True, sign_pool=8)
    rng = np.random.default_rng(7)
    z = rng.integers(0, 256, size=(batch, 16), dtype=np.uint8)
    step = co.shard_rlc_verify(mesh, m=2)
    margs = pm.shard_batch(mesh, msgs, lens, sigs, pubs, z)
    all_ok, pre = step(*margs)
    assert bool(np.asarray(all_ok))
    assert np.asarray(pre).all()

    # one corrupted signature anywhere must fail the global check
    bad = np.asarray(sigs).copy()
    bad[batch // 2, 40] ^= 1
    margs2 = pm.shard_batch(
        mesh, msgs, lens, jax.numpy.asarray(bad), pubs, z)
    all_ok2, pre2 = step(*margs2)
    assert not bool(np.asarray(all_ok2))
    assert np.asarray(pre2).all()  # prechecks still pass (sig parse ok)
