"""Bounded exhaustive verification — the role of the reference's CBMC
proof harnesses (verification/proofs/: bounded model checks over parser
state spaces), in executable form: for domains small enough to
ENUMERATE COMPLETELY, check the property over EVERY input, not a
sample.  A pass is a proof over the stated bound, not a statistical
argument.

Domains proven here:
  * compact-u16: every value round-trips (all 65,536, exhaustive);
    decode totality/canonicity checked pointwise against a closed-form
    acceptance model at every structural boundary and a ~1,700-point
    lattice of the 3-byte space.
  * bincode bool/option framing: every single-byte prefix either decodes
    or raises — no third behavior, no crash.
  * ed25519 R-byte smallness: the y-membership test agrees with the
    ground-truth 8-torsion subgroup, enumerated exhaustively (all 8
    points x both y encodings x sign bits), plus every canonical y
    boundary (0, 1, p-1, +-y8, p, 2^255-1).
"""

import numpy as np
import pytest

from firedancer_tpu.ballet import compact_u16 as cu16
from firedancer_tpu.flamenco import bincode as bc


def test_compact_u16_roundtrip_complete():
    """ALL 65,536 values: encode is minimal, decode inverts it."""
    for v in range(0x10000):
        enc = cu16.encode(v)
        got, used = cu16.decode(enc)
        assert got == v and used == len(enc)
        # minimality: 1 byte < 0x80, 2 bytes < 0x4000, else 3
        want_len = 1 if v < 0x80 else 2 if v < 0x4000 else 3
        assert len(enc) == want_len, v


def test_compact_u16_decode_totality_model():
    """Parser totality against a closed-form acceptance model: the
    implementation is checked POINTWISE at every structural boundary ±2
    and a ~1,700-point deterministic lattice of the 3-byte space — each
    input either decodes to the model's value with a minimal-prefix
    re-encode, or raises ValueError.  (The truly exhaustive member of
    this suite is the 65,536-value round-trip above; this one bounds the
    decode side by boundaries + lattice, not full 2^24 enumeration.)"""

    def model(b0, b1, b2):
        """(accepts, value) per the fd_cu16 rules."""
        if b0 < 0x80:
            return True, b0
        if b1 < 0x80:
            return (b1 != 0), (b0 & 0x7F) | (b1 << 7)
        if 1 <= b2 <= 3:
            return True, (b0 & 0x7F) | ((b1 & 0x7F) << 7) | (b2 << 14)
        return False, 0

    idxs = set()
    for base in (0, 0x7F, 0x80, 0x3FFF, 0x4000, 0xFFFF):
        for d in range(-2, 3):
            idxs.add((base + d) % (1 << 24))
    idxs.update(range(0, 1 << 24, 9973))  # ~1680 lattice points
    for i in sorted(idxs):
        b0, b1, b2 = i & 0xFF, (i >> 8) & 0xFF, i >> 16
        raw = bytes([b0, b1, b2])
        ok, val = model(b0, b1, b2)
        try:
            got, used = cu16.decode(raw)
            assert ok, (raw.hex(), got)
            assert got == val
            assert cu16.encode(got) == raw[:used]
        except ValueError:
            assert not ok, raw.hex()

def test_bincode_bool_option_total():
    """Every 1-byte input: bool/option decode accepts {0,1} and raises on
    everything else — exhaustive, no crash, no silent coercion."""
    for byte in range(256):
        raw = bytes([byte])
        for schema in ("bool", ("option", "u8")):
            try:
                v, off = bc.decode(schema, raw)
                assert byte in (0, 1)
                if schema == "bool":
                    assert v is (byte == 1)
                else:
                    assert (v is None) == (byte == 0)
            except bc.BincodeError:
                assert byte > 1 or (schema != "bool" and byte == 1)


def test_r_smallness_matches_enumerated_torsion():
    """ed25519._parse_r_bytes' y-membership smallness bit vs the actual
    8-torsion subgroup, enumerated exhaustively from the order-8 point:
    every small-order point (both y encodings, both sign bits) must be
    flagged; canonical boundary ys that are NOT torsion must not be."""
    jnp = pytest.importorskip("jax.numpy")
    from firedancer_tpu.ops import curve25519 as cv
    from firedancer_tpu.ops import ed25519 as ed
    from firedancer_tpu.ops import f25519 as fe

    P = fe.P
    # enumerate the full torsion subgroup from a generator of order 8
    t8 = None
    for y in (cv._ORDER8_Y0 % P, cv._ORDER8_Y1 % P):
        cand = ed._decompress_host(y.to_bytes(32, "little"))
        if cand is not None and ed._is_small_order_host(cand):
            t8 = cand
            break
    assert t8 is not None
    pts, q = [], (0, 1, 1, 0)
    for _ in range(8):
        pts.append(q)
        q = ed._pt_add_host(q, t8)
    assert len({(x % P, y % P) for x, y, *_ in
                [(X * pow(Z, P - 2, P), Y * pow(Z, P - 2, P))
                 for X, Y, Z, _ in pts]}) == 8  # all 8 distinct: order 8

    cases = []
    want = []
    for X, Y, Z, _T in pts:
        zi = pow(Z, P - 2, P)
        y_aff = Y * zi % P
        for enc_y in (y_aff, y_aff + P):            # non-canonical too
            if enc_y >= 1 << 255:
                continue
            for sign in (0, 1):
                cases.append(enc_y | (sign << 255))
                want.append(True)
    for y in (2, 3, 5, P - 2, (1 << 255) - 1):  # non-torsion edges
        # (2^255-1 = non-canonical encoding of 18, sign bit clear)
        cases.append(y)
        want.append(False)
    r_bytes = jnp.asarray(np.stack([
        np.frombuffer(int(c).to_bytes(32, "little"), np.uint8)
        for c in cases]))
    _y, _sgn, small = ed._parse_r_bytes(r_bytes)
    got = np.asarray(small).tolist()
    assert got == want
