"""QUIC saturating-load firehose (fddev benchg/benchs analogue) — a
multi-process topology boot, so it rides the slow tier like its topo
siblings (conftest SLOW_MODULES)."""
def test_quic_firehose_saturating_load():
    """The benchg/benchs analogue (fddev bench over live QUIC loopback):
    hundreds of txn streams pushed as fast as quota allows.  Guards the
    packetization fix the harness found (a single frame-join built
    >64 KB datagrams -> EMSGSIZE once more than ~40 streams queued)."""
    import json as _json

    from firedancer_tpu.app.fdtpudev import _quic_firehose

    import contextlib
    import io as _io

    buf = _io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = _quic_firehose(300)
    out = _json.loads(buf.getvalue().strip().splitlines()[-1])
    assert rc == 0 and out["txns"] == 300
    assert out["tps"] > 0

