"""Multi-process topology tests — the analogue of the reference's
shell-orchestrated multi-process IPC tests (SURVEY.md §4.4:
src/tango/test_ipc_full, src/disco/mux/test_mux_ipc_*): real shared memory,
one OS process per tile, supervised boot/halt.
"""

import os
import time

import pytest

from firedancer_tpu.disco import topo as topo_mod
from firedancer_tpu.disco.run import TopoRun
from firedancer_tpu.disco.topo import TopoBuilder


def _wait(pred, timeout_s, what=""):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise TimeoutError(f"timed out waiting for {what}")


def test_layout_join_determinism():
    spec = (
        TopoBuilder("layouttest", wksp_mb=8)
        .link("a_b", depth=64, mtu=512)
        .tile("a", "sink", outs=["a_b"])
        .tile("b", "sink", ins=["a_b"])
        .build()
    )
    creator = topo_mod.create(spec)
    try:
        joiner = topo_mod.join(spec)
        try:
            # identical deterministic layout: joiner sees the creator's ring
            assert joiner.links["a_b"].mcache.off == creator.links["a_b"].mcache.off
            assert joiner.links["a_b"].mcache.depth == 64
            lnk = creator.links["a_b"]
            chunk = 0
            chunk_next = lnk.dcache.write(chunk, b"hello tango")
            seq = lnk.mcache.publish(sig=7, chunk=chunk, sz=11)
            rc, meta = joiner.links["a_b"].mcache.query(seq)
            assert rc == 0 and int(meta["sig"]) == 7
            assert joiner.links["a_b"].dcache.read(int(meta["chunk"]), 11) == b"hello tango"
            # fseq visible both sides
            creator.fseq[("b", "a_b")].update(seq + 1)
            assert joiner.fseq[("b", "a_b")].query() == seq + 1
        finally:
            joiner.close()
    finally:
        creator.close()
        creator.unlink()


def test_verify_topology_end_to_end():
    """source -> verify -> dedup -> pack -> 2 bank sinks, all real processes.

    48 distinct valid txns must all survive verify+dedup and reach the banks
    via conflict-free microblocks."""
    n = 48
    spec = (
        TopoBuilder(f"e2e{os.getpid()}", wksp_mb=16)
        .link("src_verify", depth=128, mtu=1280)
        .link("verify_dedup", depth=128, mtu=1280)
        .link("dedup_pack", depth=128, mtu=1280)
        .link("pack_bank0", depth=128, mtu=1280)
        .link("pack_bank1", depth=128, mtu=1280)
        .tile("source", "source", outs=["src_verify"], count=n, keys=4)
        .tile("verify", "verify", ins=["src_verify"], outs=["verify_dedup"],
              batch=16, msg_maxlen=256, flush_age_ns=50_000_000)
        .tile("dedup", "dedup", ins=["verify_dedup"], outs=["dedup_pack"])
        .tile("pack", "pack", ins=["dedup_pack"],
              outs=["pack_bank0", "pack_bank1"])
        .tile("bank0", "sink", ins=["pack_bank0"])
        .tile("bank1", "sink", ins=["pack_bank1"])
        .build()
    )
    with TopoRun(spec) as run:
        run.wait_ready(timeout=900)  # CPU-backend verify boots pay
        # trace+deserialize (~2-5 min/child on this 1-core host) and the
        # full-suite run adds contention; 420 s flaked at suite scale

        def all_arrived():
            got = (run.metrics("bank0")["frag_cnt"]
                   + run.metrics("bank1")["frag_cnt"])
            return got == n

        _wait(all_arrived, 180, f"{n} txns at the banks")
        assert run.poll() is None, "no tile should have failed"
        v = run.metrics("verify")
        assert v["verify_pass_cnt"] == n
        assert v["verify_fail_cnt"] == 0
        assert v["parse_fail_cnt"] == 0
        d = run.metrics("dedup")
        assert d["uniq_cnt"] == n
        assert d["dup_drop_cnt"] == 0
        p = run.metrics("pack")
        assert p["txn_insert_cnt"] == n
        assert p["microblock_cnt"] >= 1


def test_supervision_detects_tile_death():
    spec = (
        TopoBuilder(f"sup{os.getpid()}", wksp_mb=8)
        .link("s_k", depth=64, mtu=256)
        .tile("source", "source", outs=["s_k"], count=4)
        .tile("sink", "sink", ins=["s_k"])
        .build()
    )
    with TopoRun(spec) as run:
        run.wait_ready(timeout=60)
        assert run.poll() is None
        run.procs["sink"].terminate()
        _wait(lambda: run.poll() == "sink", 10, "death detection")


def test_burst_firehose_round_robin_verify():
    """Round-4 burst data plane, multi-process: a numpy-stamping burst
    source firehoses unique-tag txns at 4 round-robin verify tiles over
    tango rings (ring-level RR filter, native rx/parse/dedup per burst).
    The stamped txns carry invalid signatures by design, so the assertion
    is on intake + verdicts, not forwarding (burst_n mode's contract)."""
    n = 4096
    b = TopoBuilder(f"burst{os.getpid()}", wksp_mb=32)
    b.link("src_verify", depth=4096, mtu=1280)
    b.tile("source", "source", outs=["src_verify"], count=n, burst_n=512)
    for v in range(4):
        b.link(f"verify_dedup:{v}", depth=256, mtu=1280)
        b.tile(f"verify:{v}", "verify", ins=["src_verify"],
               outs=[f"verify_dedup:{v}"], batch=64, msg_maxlen=256,
               round_robin_cnt=4, round_robin_idx=v,
               flush_age_ns=50_000_000)
    b.link("dedup_sink", depth=256, mtu=1280)
    b.tile("dedup", "dedup",
           ins=[f"verify_dedup:{v}" for v in range(4)], outs=["dedup_sink"])
    b.tile("sink", "sink", ins=["dedup_sink"])
    with TopoRun(b.build()) as run:
        run.wait_ready(timeout=900)  # CPU-backend verify boots pay
        # trace+deserialize (~2-5 min/child on this 1-core host) and the
        # full-suite run adds contention; 420 s flaked at suite scale

        def consumed_all():
            return sum(run.metrics(f"verify:{v}")["txn_in_cnt"]
                       for v in range(4)) >= n

        _wait(consumed_all, 240, f"{n} txns through 4 verify tiles")
        assert run.poll() is None, "no tile should have failed"
        per_tile = [run.metrics(f"verify:{v}")["txn_in_cnt"]
                    for v in range(4)]
        assert sum(per_tile) == n
        # ring-level round robin: seq-sliced, so near-equal split
        assert all(p > 0 for p in per_tile), per_tile

        def verdicts():
            return sum(run.metrics(f"verify:{v}")[k]
                       for v in range(4)
                       for k in ("verify_fail_cnt", "verify_pass_cnt"))

        # verdicts trail intake: the async pipeline has open buckets and
        # in-flight device batches at the moment intake completes
        _wait(lambda: verdicts() == n, 240, "all verdicts harvested")
        fails = sum(run.metrics(f"verify:{v}")["verify_fail_cnt"]
                    for v in range(4))
        assert fails >= n - 1  # stamped sigs are invalid (see burst_n doc)
