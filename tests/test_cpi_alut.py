"""Cross-program invocation, address lookup tables, compute budget
(ref behaviors: src/flamenco/vm/fd_vm_cpi.h, fd_vm_syscall_pda,
src/flamenco/runtime/program/fd_address_lookup_table_program.c,
fd_compute_budget_program.c)."""

import struct

from firedancer_tpu.ballet import txn as txn_lib
from firedancer_tpu.ballet.sbpf import asm
from firedancer_tpu.flamenco import alut_program, genesis as gen_mod
from firedancer_tpu.flamenco import system_program as sysprog
from firedancer_tpu.flamenco.bpf_loader import ix_deploy
from firedancer_tpu.flamenco.runtime import Runtime
from firedancer_tpu.flamenco.types import (
    ADDRESS_LOOKUP_TABLE_PROGRAM_ID, Account, BPF_LOADER_ID,
    COMPUTE_BUDGET_PROGRAM_ID, SYSTEM_PROGRAM_ID)
from firedancer_tpu.flamenco.vm import (
    MM_INPUT, cpi_instruction_bytes, try_find_program_address)
from firedancer_tpu.ops import ed25519 as ed
from tests.test_sbpf_vm import _mini_elf


def _keypair(i):
    seed = i.to_bytes(32, "little")
    return seed, ed.keypair_from_seed(seed)[0]


def _signed(signers, msg):
    return txn_lib.assemble([ed.sign(s, msg) for s, _ in signers], msg)


def _deploy(rt, bank, faucet, prog):
    elf = _mini_elf(CPI_PROG)
    msg = txn_lib.build_unsigned(
        [faucet[1], prog[1]], rt.root_hash,
        [(2, bytes([1]), ix_deploy(elf))],
        extra_accounts=[BPF_LOADER_ID], readonly_unsigned_cnt=1)
    res = bank.execute_txn(_signed([faucet, prog], msg))
    assert res.ok, res.err


# Program: sol_invoke_signed_c(input+192, input+192+CPI_BUF_LEN, 1).
# The instruction data carries a prebuilt CPI buffer + signer-seed
# descriptors with absolute input-region vaddrs (the input region base is
# architectural, MM_INPUT, so the host can precompute them).  Input layout
# for 2 zero-data accounts: 8 + 2*88 = 184, instr_len u64, instr at 192.
CPI_BUF_LEN = 32 + 8 + 2 * 40 + 8 + 12  # prog id, 2 metas, transfer ix
CPI_PROG = asm(f"""
    mov r6, r1
    mov r1, r6
    add r1, 192
    mov r2, r6
    add r2, {192 + CPI_BUF_LEN}
    mov r3, 1
    syscall sol_invoke_signed_c
    mov r0, 0
    exit""")


def _cpi_instr_payload(prog_pk, pda, bump, recipient, lamports,
                       pda_is_signer=True):
    """CPI buffer + signer descriptors, vaddr-linked for input offset 192."""
    cpi_buf = cpi_instruction_bytes(
        SYSTEM_PROGRAM_ID,
        [(pda, pda_is_signer, True), (recipient, False, True)],
        sysprog.ix_transfer(lamports))
    assert len(cpi_buf) == CPI_BUF_LEN
    base = MM_INPUT + 192
    off_slices = len(cpi_buf) + 16
    off_seed0 = off_slices + 32
    payload = bytearray(cpi_buf)
    payload += struct.pack("<QQ", base + off_slices, 2)       # signer entry
    payload += struct.pack("<QQ", base + off_seed0, 5)        # b"vault"
    payload += struct.pack("<QQ", base + off_seed0 + 5, 1)    # bump byte
    payload += b"vault" + bytes([bump])
    return bytes(payload)


def test_cpi_pda_signed_transfer_roundtrip():
    """Program A CPIs system.transfer from its PDA vault: the PDA's signer
    privilege must materialize from the seeds, lamports must move, and the
    bank's lamport-conservation check must still pass."""
    faucet = _keypair(1)
    prog = _keypair(2)
    recip = _keypair(3)
    pda, bump = try_find_program_address([b"vault"], prog[1])

    g = gen_mod.create(faucet[1], creation_time=1)
    g.accounts[prog[1]] = Account(lamports=1_000_000)
    g.accounts[pda] = Account(lamports=10_000)
    rt = Runtime(g)
    b = rt.new_bank(1)
    _deploy(rt, b, faucet, prog)

    payload = _cpi_instr_payload(prog[1], pda, bump, recip[1], 700)
    msg = txn_lib.build_unsigned(
        [faucet[1]], rt.root_hash,
        [(3, bytes([1, 2]), payload)],
        extra_accounts=[pda, recip[1], prog[1]], readonly_unsigned_cnt=1)
    res = b.execute_txn(_signed([faucet], msg))
    assert res.ok, res.err
    assert rt.accdb.load(b.xid, pda).lamports == 10_000 - 700
    assert rt.accdb.load(b.xid, recip[1]).lamports == 700
    assert res.compute_units > 0


def test_cpi_signer_privilege_escalation_rejected():
    """Marking a non-signer, non-PDA account as a CPI signer must fail the
    transaction (fd_vm_cpi privilege checks)."""
    faucet = _keypair(1)
    prog = _keypair(2)
    victim = _keypair(4)  # funded account nobody signed for
    pda, bump = try_find_program_address([b"vault"], prog[1])

    g = gen_mod.create(faucet[1], creation_time=1)
    g.accounts[prog[1]] = Account(lamports=1_000_000)
    g.accounts[victim[1]] = Account(lamports=50_000)
    rt = Runtime(g)
    b = rt.new_bank(1)
    _deploy(rt, b, faucet, prog)

    # same CPI program, but the "vault" meta points at the victim account
    thief = _keypair(6)
    payload = _cpi_instr_payload(prog[1], victim[1], bump, thief[1], 700)
    msg = txn_lib.build_unsigned(
        [faucet[1]], rt.root_hash,
        [(3, bytes([1, 2]), payload)],
        extra_accounts=[victim[1], thief[1], prog[1]],
        readonly_unsigned_cnt=1)
    res = b.execute_txn(_signed([faucet], msg))
    assert not res.ok
    assert "privilege" in res.err or "CPI" in res.err, res.err
    assert rt.accdb.load(b.xid, victim[1]).lamports == 50_000


def _make_table(rt, bank, faucet, addresses):
    """Create + extend a table with `faucet` as the authority (accounts:
    0=faucet signer, 1=table writable, 2=ALUT program readonly)."""
    table = _keypair(77)
    # fund the table (zero-lamport accounts cease to exist), create, extend
    msg = txn_lib.build_unsigned(
        [faucet[1]], rt.root_hash,
        [(3, bytes([0, 1]), sysprog.ix_transfer(1_000)),
         (2, bytes([1, 0]), alut_program.ix_create(0)),
         (2, bytes([1, 0]), alut_program.ix_extend(addresses))],
        extra_accounts=[table[1], ADDRESS_LOOKUP_TABLE_PROGRAM_ID,
                        SYSTEM_PROGRAM_ID],
        readonly_unsigned_cnt=2)
    res = bank.execute_txn(_signed([faucet], msg))
    assert res.ok, res.err
    return table[1]


def test_alut_create_extend_and_v0_resolution():
    """Create + extend a lookup table, then execute a v0 txn whose transfer
    destination is only reachable through the table."""
    faucet = _keypair(1)
    dest = _keypair(9)
    g = gen_mod.create(faucet[1], creation_time=1)
    rt = Runtime(g)
    b = rt.new_bank(1)

    table_pk = _make_table(rt, b, faucet, [dest[1], faucet[1]])
    st = alut_program.LookupTable.deserialize(
        rt.accdb.load(b.xid, table_pk).data)
    assert st.addresses == [dest[1], faucet[1]]

    # v0 txn: static accounts [faucet, system]; dest arrives via lookup
    msg = txn_lib.build_unsigned(
        [faucet[1]], rt.root_hash,
        [(1, bytes([0, 2]), sysprog.ix_transfer(1234))],
        extra_accounts=[SYSTEM_PROGRAM_ID], readonly_unsigned_cnt=1,
        version=txn_lib.V0,
        lookups=[(table_pk, bytes([0]), b"")])
    res = b.execute_txn(_signed([faucet], msg))
    assert res.ok, res.err
    assert rt.accdb.load(b.xid, dest[1]).lamports == 1234


def test_alut_frozen_and_lifecycle():
    faucet = _keypair(1)
    g = gen_mod.create(faucet[1], creation_time=1)
    rt = Runtime(g)
    b = rt.new_bank(1)
    table_pk = _make_table(rt, b, faucet, [faucet[1]])

    def run_ix(data, accounts=(1, 0)):
        msg = txn_lib.build_unsigned(
            [faucet[1]], rt.root_hash, [(2, bytes(accounts), data)],
            extra_accounts=[table_pk, ADDRESS_LOOKUP_TABLE_PROGRAM_ID],
            readonly_unsigned_cnt=1)
        return b.execute_txn(_signed([faucet], msg))

    # extend by a stranger (no authority signature) must fail:
    stranger = _keypair(5)
    msg = txn_lib.build_unsigned(
        [faucet[1]], rt.root_hash,
        [(3, bytes([1, 2]), alut_program.ix_extend([faucet[1]]))],
        extra_accounts=[table_pk, stranger[1],
                        ADDRESS_LOOKUP_TABLE_PROGRAM_ID],
        readonly_unsigned_cnt=2)
    res = b.execute_txn(_signed([faucet], msg))
    assert not res.ok  # account 2 (stranger) did not sign

    # freeze, then extend must fail
    res = run_ix(alut_program.ix_freeze(), accounts=(1, 0))
    assert res.ok, res.err
    res = run_ix(alut_program.ix_extend([faucet[1]]), accounts=(1, 0))
    assert not res.ok and "frozen" in res.err


def test_alut_close_requires_cooldown():
    faucet = _keypair(1)
    g = gen_mod.create(faucet[1], creation_time=1)
    rt = Runtime(g)
    b = rt.new_bank(1)
    table_pk = _make_table(rt, b, faucet, [faucet[1]])

    def ix(bank, data, accounts):
        msg = txn_lib.build_unsigned(
            [faucet[1]], rt.root_hash, [(2, bytes(accounts), data)],
            extra_accounts=[table_pk, ADDRESS_LOOKUP_TABLE_PROGRAM_ID],
            readonly_unsigned_cnt=1)
        return bank.execute_txn(_signed([faucet], msg))

    res = ix(b, alut_program.ix_deactivate(), (1, 0))
    assert res.ok, res.err
    res = ix(b, alut_program.ix_close(), (1, 0, 0))
    assert not res.ok and "cooldown" in res.err
    # far-future bank: cooldown elapsed
    b.freeze(b"\x00" * 32)
    rt.publish(1)
    b2 = rt.new_bank(1 + alut_program.DEACTIVATION_COOLDOWN_SLOTS + 1, 1)
    res = ix(b2, alut_program.ix_close(), (1, 0, 0))
    assert res.ok, res.err
    # drained to zero lamports -> the account ceases to exist
    assert rt.accdb.load(b2.xid, table_pk) is None


def test_compute_budget_limit_enforced():
    """SetComputeUnitLimit must bound a deployed program's execution; the
    same program under the default budget completes."""
    faucet = _keypair(1)
    prog = _keypair(2)
    g = gen_mod.create(faucet[1], creation_time=1)
    g.accounts[prog[1]] = Account(lamports=1_000_000)
    rt = Runtime(g)
    b = rt.new_bank(1)

    # ~4000 executed instructions
    looper = asm("""
        mov r1, 1000
    loop:
        sub r1, 1
        mov r2, r1
        jne r1, 0, =loop
        mov r0, 0
        exit""")
    elf = _mini_elf(looper)
    msg = txn_lib.build_unsigned(
        [faucet[1], prog[1]], rt.root_hash,
        [(2, bytes([1]), ix_deploy(elf))],
        extra_accounts=[BPF_LOADER_ID], readonly_unsigned_cnt=1)
    assert b.execute_txn(_signed([faucet, prog], msg)).ok

    def invoke(with_limit):
        instrs = [(1, b"", b"")]
        extra = [prog[1]]
        if with_limit is not None:
            # compute-budget ix: program index 2, SetComputeUnitLimit
            instrs = [(2, b"", bytes([2]) + struct.pack("<I", with_limit)),
                      (1, b"", b"")]
            extra = [prog[1], COMPUTE_BUDGET_PROGRAM_ID]
        msg = txn_lib.build_unsigned(
            [faucet[1]], rt.root_hash, instrs,
            extra_accounts=extra, readonly_unsigned_cnt=len(extra))
        return b.execute_txn(_signed([faucet], msg))

    res = invoke(None)
    assert res.ok, res.err
    assert res.compute_units > 3000

    res = invoke(500)  # far below the ~4k instructions the loop needs
    assert not res.ok
    assert "compute" in res.err.lower(), res.err
