"""eBPF/XDP tier tests (round 4, VERDICT missing #4): the generated XDP
redirect program executed on the in-repo sBPF interpreter with kernel
helper shims, plus the ELF static linker against a crafted relocatable
object (the shape clang -target bpf emits)."""

import struct

import pytest

from firedancer_tpu.waltz import ebpf


def _udp_packet(dst_ip: bytes, dst_port: int, ihl: int = 5,
                proto: int = 17, ethertype: bytes = b"\x08\x00") -> bytes:
    eth = b"\xaa" * 6 + b"\xbb" * 6 + ethertype
    ip = bytes([0x40 | ihl, 0]) + struct.pack(">H", 20 + 8 + 4)
    ip += b"\x00" * 4 + bytes([64, proto]) + b"\x00\x00"
    ip += b"\x0a\x00\x00\x01" + dst_ip
    ip += b"\x00" * (ihl * 4 - 20)
    udp = struct.pack(">HHHH", 5000, dst_port, 8 + 4, 0)
    return eth + ip + udp + b"data"


def _flow_key(dst_ip: bytes, dst_port: int) -> int:
    # the program loads ip dst as a host-endian u32 and port as u16 from
    # the wire (LE loads of network-order bytes) and packs (ip<<16)|port
    ip_le = int.from_bytes(dst_ip, "little")
    port_le = int.from_bytes(struct.pack(">H", dst_port), "little")
    return (ip_le << 16) | port_le


DST_IP = b"\xc0\x00\x02\x07"        # 192.0.2.7
PORT = 9001


@pytest.fixture
def sim():
    prog = ebpf.build_xdp_redirect_prog(udp_dsts_fd=1, xsks_fd=2)
    return ebpf.XdpSim(prog, udp_dsts={_flow_key(DST_IP, PORT): 1},
                       xsks={0: 77, 3: 78})


def test_registered_flow_redirects(sim):
    act = sim.run(_udp_packet(DST_IP, PORT), rx_queue=0)
    assert act == ebpf.XDP_REDIRECT
    assert sim.redirects == [(2, 0)]


def test_queue_index_keys_the_xsk_map(sim):
    act = sim.run(_udp_packet(DST_IP, PORT), rx_queue=3)
    assert act == ebpf.XDP_REDIRECT
    assert sim.redirects == [(2, 3)]


def test_unregistered_port_passes(sim):
    assert sim.run(_udp_packet(DST_IP, PORT + 1)) == ebpf.XDP_PASS


def test_unregistered_ip_passes(sim):
    assert sim.run(_udp_packet(b"\xc0\x00\x02\x08", PORT)) == ebpf.XDP_PASS


def test_non_udp_passes(sim):
    assert sim.run(_udp_packet(DST_IP, PORT, proto=6)) == ebpf.XDP_PASS


def test_non_ipv4_passes(sim):
    assert sim.run(_udp_packet(DST_IP, PORT,
                               ethertype=b"\x86\xdd")) == ebpf.XDP_PASS


def test_options_bearing_ip_header(sim):
    """IHL > 5: the UDP header moves; the program must follow it."""
    assert sim.run(_udp_packet(DST_IP, PORT, ihl=8)) == ebpf.XDP_REDIRECT


def test_runt_packet_passes(sim):
    assert sim.run(b"\x00" * 30) == ebpf.XDP_PASS


def test_unknown_queue_returns_flags_fallback(sim):
    # queue 9 has no XSK: kernel semantics return the flags argument (0 =
    # XDP_ABORTED) — the packet is not silently redirected
    assert sim.run(_udp_packet(DST_IP, PORT),
                   rx_queue=9) == ebpf.XDP_ABORTED


# ------------------------------------------------------------ static linker


def _craft_rel_elf(section: str, text: bytes, relocs, symbols):
    """Minimal ET_REL ELF64 with .text-like prog section + SHT_REL +
    symtab/strtab — the layout fd_ebpf_static_link consumes."""
    names = ["", section, ".rel" + section, ".symtab", ".strtab",
             ".shstrtab"]
    shstr = bytearray(b"\0")
    name_off = {}
    for n in names[1:]:
        name_off[n] = len(shstr)
        shstr += n.encode() + b"\0"
    strtab = bytearray(b"\0")
    sym_off = {}
    for s in symbols:
        sym_off[s] = len(strtab)
        strtab += s.encode() + b"\0"
    # symtab: null + one entry per symbol
    symtab = bytearray(24)
    sym_idx = {}
    for i, s in enumerate(symbols):
        sym_idx[s] = i + 1
        symtab += struct.pack("<IBBHQQ", sym_off[s], 0, 0, 0, 0, 0)
    rel = bytearray()
    for off, sname in relocs:
        rel += struct.pack("<QQ", off, (sym_idx[sname] << 32) | 1)

    bodies = [b"", bytes(text), bytes(rel), bytes(symtab), bytes(strtab),
              bytes(shstr)]
    types = [0, 1, 9, 2, 3, 3]
    links = [0, 0, 3, 4, 0, 0]
    infos = [0, 0, 1, 1, 0, 0]
    entsizes = [0, 0, 16, 24, 0, 0]

    off = 64
    offs = []
    blob = bytearray()
    for b in bodies:
        offs.append(off + 0)
        blob += b
        off += len(b)
    sh_off = 64 + len(blob)
    # section offsets are absolute: recompute
    off = 64
    offs = []
    for b in bodies:
        offs.append(off)
        off += len(b)

    ehdr = bytearray(64)
    ehdr[:4] = b"\x7fELF"
    ehdr[4], ehdr[5] = 2, 1
    struct.pack_into("<H", ehdr, 16, 1)            # ET_REL
    struct.pack_into("<H", ehdr, 18, 0xF7)         # EM_BPF
    struct.pack_into("<Q", ehdr, 40, sh_off)
    struct.pack_into("<HHH", ehdr, 58, 64, len(bodies), 5)

    sh = bytearray()
    for i, b in enumerate(bodies):
        ent = bytearray(64)
        struct.pack_into("<II", ent, 0,
                         name_off.get(names[i], 0), types[i])
        struct.pack_into("<QQ", ent, 24, offs[i], len(b))
        struct.pack_into("<II", ent, 40, links[i], infos[i])
        struct.pack_into("<Q", ent, 56, entsizes[i])
        sh += ent
    return bytes(ehdr) + bytes(blob) + bytes(sh)


def test_static_link_patches_map_fds():
    # program with two unresolved map loads (imm=0) at insn 0 and 3
    text = (ebpf.lddw(1, 0) + ebpf.ins(0xB7, 0, 0, 0, 2)
            + ebpf.lddw(1, 0) + ebpf.ins(0x95))
    elf = _craft_rel_elf("xdp", text,
                         relocs=[(0, "fd_xdp_udp_dsts"),
                                 (24, "fd_xdp_xsks")],
                         symbols=["fd_xdp_udp_dsts", "fd_xdp_xsks"])
    linked = ebpf.static_link(elf, "xdp", {"fd_xdp_udp_dsts": 7,
                                           "fd_xdp_xsks": 9})
    assert linked.reloc_offs == [0, 24]
    # imm patched + src_reg = BPF_PSEUDO_MAP_FD
    op, regs, _, imm = struct.unpack_from("<BBhi", linked.text, 0)
    assert op == 0x18 and regs >> 4 == 1 and imm == 7
    op, regs, _, imm = struct.unpack_from("<BBhi", linked.text, 24)
    assert op == 0x18 and regs >> 4 == 1 and imm == 9


def test_static_link_rejects_undefined_symbol():
    text = ebpf.lddw(1, 0) + ebpf.ins(0x95)
    elf = _craft_rel_elf("xdp", text, relocs=[(0, "mystery")],
                         symbols=["mystery"])
    with pytest.raises(ValueError, match="undefined"):
        ebpf.static_link(elf, "xdp", {})


def test_static_link_rejects_non_elf():
    with pytest.raises(ValueError):
        ebpf.static_link(b"not an elf at all" * 8, "xdp", {})


def test_kernel_path_gates_cleanly():
    """Inside an unprivileged container the kernel path must raise
    EbpfUnavailable (callers fall back to AF_PACKET), never crash."""
    try:
        k = ebpf.KernelXdp()
        fd = k.map_create(ebpf.KernelXdp.BPF_MAP_TYPE_HASH, 8, 4, 16)
    except ebpf.EbpfUnavailable:
        return
    import os
    os.close(fd)  # privileged environment: creation worked; that's a pass
