"""Batched QUIC packet protection (waltz/quic_crypto.py + the
native/aescrypt.cpp burst engine).

Three tiers of evidence, strongest last:
  1. RFC 9001 Appendix A vectors — Initial key schedule, full client
     Initial protect/unprotect, Retry integrity tag — pinned on BOTH
     backends (the spec authors' bytes, not ours).
  2. Fuzzed burst bit-identity: random key/packet/burst shapes (long and
     short headers, coalesced packets, truncated samples, corrupt tags)
     must produce byte-identical buffers and verdict tables from the C
     engine and the NumPy fallback — including the no-mutation-on-reject
     guarantee.
  3. Endpoint-level: corrupt tags land in pkt_undecryptable on both
     backends, never raise; an endpoint pair on MIXED backends (native
     client, fallback server) interoperates — the wire format is the
     cross-check.
"""

import os
import random

import pytest

from firedancer_tpu.waltz import quic as q
from firedancer_tpu.waltz import quic_crypto as qc
from firedancer_tpu.waltz.aio import Aio, Pkt

_GOLDEN = os.path.join(os.path.dirname(__file__), "golden")
DCID = bytes.fromhex("8394c8f03e515708")

with open(os.path.join(_GOLDEN, "rfc9001-client-initial-payload.bin"),
          "rb") as f:
    PAYLOAD = f.read()
with open(os.path.join(_GOLDEN, "rfc9001-client-initial-encrypted.bin"),
          "rb") as f:
    ENCRYPTED = f.read()

# RFC 9001 A.2: the unprotected header (pn=2, pn_len=4, len=1182)
HEADER = bytes.fromhex("c300000001088394c8f03e5157080000449e00000002")
PN_OFF = len(HEADER) - 4

# RFC 9001 A.5: Retry packet for ODCID 0x8394c8f03e515708, token "token"
RETRY_SANS_TAG = bytes.fromhex(
    "ff000000010008f067a5502a4262b5746f6b656e")
RETRY_TAG = bytes.fromhex("04a265ba2eff4d829058fb3f0f2496ba")


def _native_available() -> bool:
    try:
        return qc._native_lib() is not None
    except Exception:
        return False


def _backend_params():
    params = [pytest.param(False, id="fallback")]
    if _native_available():
        params.append(pytest.param(True, id="native"))
    else:
        params.append(pytest.param(
            True, id="native",
            marks=pytest.mark.skip(reason="aescrypt.cpp did not build")))
    return params


@pytest.fixture(params=_backend_params())
def backend(request):
    return qc.CryptoBackend(native=request.param)


# --------------------------------------------------- RFC 9001 Appendix A


def test_retry_integrity_tag_rfc9001_a5():
    odcid = DCID
    assert q.retry_integrity_tag(odcid, RETRY_SANS_TAG) == RETRY_TAG


def test_decrypt_client_initial_vector(backend):
    """Their protected bytes -> our burst engine -> their payload."""
    server_rx, _ = q.initial_keys(DCID, is_server=True)
    buf = bytearray(ENCRYPTED)
    slot = server_rx.slot(backend)
    (ok, pn, pt_off, pt_len), = backend.decrypt_burst(
        [(buf, 0, PN_OFF, len(ENCRYPTED), slot, 0)])
    assert ok
    assert pn == 2
    assert bytes(buf[pt_off : pt_off + pt_len]) == PAYLOAD
    # HP removal restored the cleartext header in place
    assert bytes(buf[:PN_OFF + 4]) == HEADER


def test_encrypt_client_initial_vector(backend):
    """Our burst engine over the RFC payload -> their exact bytes."""
    _, client_tx = q.initial_keys(DCID, is_server=False)
    buf = bytearray(HEADER + PAYLOAD + b"\0" * 16)
    slot = client_tx.slot(backend)
    backend.encrypt_burst([(buf, PN_OFF, 2, len(PAYLOAD), slot)])
    assert bytes(buf) == ENCRYPTED


def test_corrupt_tag_rejected_and_untouched(backend):
    server_rx, _ = q.initial_keys(DCID, is_server=True)
    slot = server_rx.slot(backend)
    buf = bytearray(ENCRYPTED)
    buf[-1] ^= 0x40  # flip a tag bit
    before = bytes(buf)
    (ok, _, _, _), = backend.decrypt_burst(
        [(buf, 0, PN_OFF, len(ENCRYPTED), slot, 0)])
    assert not ok
    assert bytes(buf) == before  # reject leaves the buffer bit-identical


# ------------------------------------------------ fuzzed burst identity


def _mk_packet(rng, key_idx, pn):
    """One synthetic packet: (plaintext_buf, start, pn_off, pt_len,
    long_hdr).  Headers are arbitrary bytes with only the form bit
    pinned; the engines never parse them beyond first-byte masking."""
    long_hdr = rng.random() < 0.5
    hdr_len = rng.randint(5, 24)
    hdr = bytearray(rng.randbytes(hdr_len))
    hdr[0] = (0xC0 if long_hdr else 0x40) | (hdr[0] & 0x0F) | 0x03
    pt_len = rng.randint(4, 600)
    payload = rng.randbytes(pt_len)
    buf = bytearray(
        bytes(hdr) + (pn & 0xFFFFFFFF).to_bytes(4, "big")
        + payload + b"\0" * 16)
    return buf, hdr_len, pt_len


@pytest.mark.skipif(not _native_available(),
                    reason="aescrypt.cpp did not build")
def test_fuzz_burst_bit_identity():
    rng = random.Random(0xA5C3)
    nat = qc.CryptoBackend(native=True)
    py = qc.CryptoBackend(native=False)
    key_mat = [(rng.randbytes(16), rng.randbytes(12), rng.randbytes(16))
               for _ in range(5)]
    nslots = [nat.key_new(*k) for k in key_mat]
    pslots = [py.key_new(*k) for k in key_mat]

    for _ in range(8):  # bursts
        n = rng.randint(1, 48)
        plain, meta = [], []
        for i in range(n):
            ki = rng.randrange(len(key_mat))
            pn = rng.randint(0, 1 << 30)
            buf, pn_off, pt_len = _mk_packet(rng, ki, pn)
            plain.append(bytes(buf))
            meta.append((ki, pn, pn_off, pt_len))

        # encrypt the same plaintexts on both backends -> identical wire
        nbufs = [bytearray(p) for p in plain]
        pbufs = [bytearray(p) for p in plain]
        nat.encrypt_burst(
            [(b, m[2], m[1], m[3], nslots[m[0]])
             for b, m in zip(nbufs, meta)])
        py.encrypt_burst(
            [(b, m[2], m[1], m[3], pslots[m[0]])
             for b, m in zip(pbufs, meta)])
        assert nbufs == pbufs

        # mutate a subset: corrupt tags/ct bytes, truncate below the HP
        # sample, mismatch the key slot
        kinds = []
        for i, b in enumerate(nbufs):
            r = rng.random()
            if r < 0.2:
                pos = rng.randrange(meta[i][2], len(b))
                b[pos] ^= 1 << rng.randrange(8)
                pbufs[i][pos] = b[pos]
                kinds.append("corrupt")
            elif r < 0.3:
                cut = meta[i][2] + rng.randint(0, 19)
                del b[cut:]
                del pbufs[i][cut:]
                kinds.append("truncated")
            elif r < 0.4:
                kinds.append("wrong-key")
            else:
                kinds.append("ok")

        expected = [rng.randint(0, 1 << 30) if rng.random() < 0.5
                    else m[1] for m in meta]
        njobs, pjobs = [], []
        for i, m in enumerate(meta):
            ki = (m[0] + 1) % len(key_mat) if kinds[i] == "wrong-key" \
                else m[0]
            njobs.append((nbufs[i], 0, m[2], len(nbufs[i]),
                          nslots[ki], expected[i]))
            pjobs.append((pbufs[i], 0, m[2], len(pbufs[i]),
                          pslots[ki], expected[i]))
        nres = nat.decrypt_burst(njobs)
        pres = py.decrypt_burst(pjobs)
        assert nres == pres
        assert nbufs == pbufs  # successes decrypted AND failures
        #                        untouched, byte-identical either way
        for i, (ok, pn, pt_off, pt_len) in enumerate(nres):
            if kinds[i] in ("corrupt", "truncated", "wrong-key"):
                assert not ok, (i, kinds[i])
            elif kinds[i] == "ok":
                assert ok, (i, kinds[i])
                assert bytes(nbufs[i][pt_off : pt_off + pt_len]) == \
                    plain[i][meta[i][2] + 4 : meta[i][2] + 4 + meta[i][3]]


@pytest.mark.skipif(not _native_available(),
                    reason="aescrypt.cpp did not build")
def test_coalesced_packets_share_one_buffer():
    """Two packets coalesced in one datagram buffer: per-packet start/
    pn_off/end offsets address disjoint slices of the same bytearray."""
    rng = random.Random(7)
    nat = qc.CryptoBackend(native=True)
    py = qc.CryptoBackend(native=False)
    key = (rng.randbytes(16), rng.randbytes(12), rng.randbytes(16))
    ns, ps = nat.key_new(*key), py.key_new(*key)

    p1, off1, len1 = _mk_packet(rng, 0, 11)
    p2, off2, len2 = _mk_packet(rng, 0, 12)
    for be, slot in ((nat, ns), (py, ps)):
        a = bytearray(p1)
        b = bytearray(p2)
        be.encrypt_burst([(a, off1, 11, len1, slot),
                          (b, off2, 12, len2, slot)])
        if be is nat:
            wire = bytes(a) + bytes(b)
    dg_n = bytearray(wire)
    dg_p = bytearray(wire)
    jobs = lambda dg, slot: [
        (dg, 0, off1, len(p1), slot, 11),
        (dg, len(p1), len(p1) + off2, len(wire), slot, 12)]
    rn = nat.decrypt_burst(jobs(dg_n, ns))
    rp = py.decrypt_burst(jobs(dg_p, ps))
    assert rn == rp
    assert dg_n == dg_p
    assert all(ok for ok, *_ in rn)
    (_, pn1, o1, l1), (_, pn2, o2, l2) = rn
    assert (pn1, pn2) == (11, 12)
    assert bytes(dg_n[o1:o1 + l1]) == bytes(p1[off1 + 4:off1 + 4 + len1])
    assert bytes(dg_n[o2:o2 + l2]) == bytes(p2[off2 + 4:off2 + 4 + len2])


# ------------------------------------------------------- endpoint level


def _endpoint_pair(client_native, server_native):
    c2s, s2c = [], []
    cl = QuicEndpointFactory(client_native, False, c2s)
    sv = QuicEndpointFactory(server_native, True, s2c)
    return cl, sv, c2s, s2c


def QuicEndpointFactory(native, is_server, out):
    return q.QuicEndpoint(
        q.QuicConfig(identity_seed=os.urandom(32), is_server=is_server,
                     crypto_native=native),
        Aio(lambda p: out.extend(p) or len(p)))


def _pump(cl, sv, c2s, s2c, now=0.0, steps=30):
    conn = cl.connect(("10.0.0.9", 9001))
    for _ in range(steps):
        now += 0.01
        if c2s:
            pkts, c2s[:] = list(c2s), []
            sv.rx(pkts, now)
        if s2c:
            pkts, s2c[:] = list(s2c), []
            cl.rx(pkts, now)
        if conn.handshake_done:
            break
    return conn, now


@pytest.mark.parametrize("native", [False, True])
def test_corrupt_datagrams_never_raise(native):
    if native and not _native_available():
        pytest.skip("aescrypt.cpp did not build")
    from firedancer_tpu.disco.faultinject import WireFaultGen
    g = WireFaultGen(seed=3)
    sent = []
    sv = q.QuicEndpoint(
        q.QuicConfig(identity_seed=os.urandom(32), is_server=True,
                     crypto_native=native),
        Aio(lambda p: sent.extend(p) or len(p)))
    # valid Initials with every tag bit-flipped + raw malformed storms
    for i in range(32):
        d = bytearray(g.forged_initial()[0])
        d[-1 - (i % 16)] ^= 0xFF
        sv.rx([Pkt(d, ("6.6.6.6", 6))], now=1.0)
    for d in g.malformed(64):
        sv.rx([Pkt(d, ("6.6.6.7", 6))], now=1.0)
    assert sv.metrics["pkt_undecryptable"] >= 32
    assert len(sv.conns) == 0
    assert (sv.metrics["crypto_native" if native else "crypto_fallback"]
            > 0)
    assert sv.metrics["crypto_fallback" if native else "crypto_native"] \
        == 0


@pytest.mark.skipif(not _native_available(),
                    reason="aescrypt.cpp did not build")
def test_mixed_backend_interop():
    """Native client <-> fallback server (and the reverse): the wire
    bytes are the cross-check that both engines speak the same QUIC."""
    for cn, sn in ((True, False), (False, True)):
        cl, sv, c2s, s2c = _endpoint_pair(cn, sn)
        got = []
        sv.on_stream = lambda conn, sid, data: got.append(bytes(data))
        conn, now = _pump(cl, sv, c2s, s2c)
        assert conn.handshake_done, (cn, sn)
        conn.send_txn(b"interop" * 30)
        cl._flush(conn)
        cl._send_pending()
        pkts, c2s[:] = list(c2s), []
        sv.rx(pkts, now + 0.01)
        assert got == [b"interop" * 30], (cn, sn)
