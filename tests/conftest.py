"""Test harness bootstrap.

The reference tests "distributed" behavior with single-host multi-process
shared memory (SURVEY.md §4.4); our analogue is a virtual 8-device CPU mesh
(``--xla_force_host_platform_device_count=8``), since jax sharding semantics
are identical between the CPU backend and a real TPU pod slice.

This container bakes a sitecustomize that imports jax and registers the axon
TPU PJRT plugin in every python process, so env vars alone are too late.
jax.config.update('jax_platforms') still works as long as no backend has been
initialized, which is guaranteed at conftest import time.
"""

import os

import jax

# FDTPU_TEST_TPU=1 runs the suite against the real chip (Pallas kernels
# engage); default is the virtual CPU mesh.
_USE_TPU = bool(os.environ.get("FDTPU_TEST_TPU"))

if not _USE_TPU:
    # children spawned by disco.run inherit this env and come up CPU-only too
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    os.environ.setdefault("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
        os.environ["XLA_FLAGS"] = (
            os.environ["XLA_FLAGS"]
            + " --xla_force_host_platform_device_count=8"
        ).strip()
    jax.config.update("jax_platforms", "cpu")

from firedancer_tpu.utils import xla_cache  # noqa: E402

# Tests write the cache (first run of an unprimed shape populates it;
# re-running a cold suite without writes would recompile every time).
# tools/prime_test_cache.py pre-populates the heavy shapes; tile
# processes read-only (disco/run.py) for boot robustness.  Set
# FDTPU_XLA_CACHE_READONLY=1 to suppress writes entirely.
xla_cache.enable()

import pytest  # noqa: E402

# Modules whose tests compile large device graphs (crypto scalar-mul chains,
# multi-device collectives, multi-process pipelines).  On a cold .xla_cache
# these take minutes each on a CPU host; `pytest -m "not slow"` is the
# < 2-minute default tier (the reference's unit-vs-integration tiering,
# contrib/test/run_unit_tests.sh).  Run the full suite after priming with
# tools/prime_test_cache.py.
# Prime-or-skip (VERDICT r4 weak #4): these modules compile mid-size
# device graphs (batched verify shapes, verify_one (1,1280), interpret-
# mode kernels) that run in seconds against a PRIMED cache but cost
# minutes each cold.  tools/prime_test_cache.py drops a PRIMED-<srchash>
# sentinel; without a current sentinel they defer to the slow tier so
# `pytest -m "not slow"` stays fast from any state.
PRIMED_ONLY_MODULES = {
    "test_curve_pallas",
    "test_degraded_verify",
    "test_ed25519_conformance",
    "test_ed25519_real_corpora",
    "test_pipeline_async",
    "test_repair_tile",
    "test_shred",
    "test_verify_smoke",
}


def _cache_primed() -> bool:
    from firedancer_tpu.utils.aot import _src_hash
    from firedancer_tpu.utils.xla_cache import cache_dir
    return os.path.exists(
        os.path.join(cache_dir(), f"PRIMED-{_src_hash()}"))


SLOW_MODULES = {
    "test_ed25519",
    "test_ed25519_rlc",
    "test_curve25519",
    "test_x25519_ristretto",
    "test_collectives",
    "test_sharded_verify",  # 8-device graphs load in ~40 s each even warm
    "test_leader_pipeline",
    "test_topo_run",
    "test_turbine",        # boots three multi-process validator nodes
    "test_quic_firehose",  # multi-process QUIC topology at load
    "test_waltz_ingest",
    "test_pipeline",
    "test_sha512",
    "test_sha256",
    "test_blake3",
    "test_f25519",
    "test_reedsol",
    "test_fuzz_smoke",
    "test_rewards_secp_shredcap",
    "test_bank_tile",
}


def pytest_collection_modifyitems(config, items):
    slow = set(SLOW_MODULES)
    if not _USE_TPU and not _cache_primed():
        slow |= PRIMED_ONLY_MODULES
        print("\n[conftest] XLA cache not primed for current sources: "
              f"{len(PRIMED_ONLY_MODULES)} graph-compiling modules deferred "
              "to the slow tier (run tools/prime_test_cache.py)")
    for item in items:
        mod = item.nodeid.split("::", 1)[0].rsplit("/", 1)[-1].removesuffix(".py")
        if mod in slow:
            item.add_marker(pytest.mark.slow)
