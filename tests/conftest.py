"""Test harness bootstrap.

The reference tests "distributed" behavior with single-host multi-process
shared memory (SURVEY.md §4.4); our analogue is a virtual 8-device CPU mesh
(``--xla_force_host_platform_device_count=8``), since jax sharding semantics
are identical between the CPU backend and a real TPU pod slice.

This container bakes a sitecustomize that imports jax and registers the axon
TPU PJRT plugin in every python process, so env vars alone are too late.
jax.config.update('jax_platforms') still works as long as no backend has been
initialized, which is guaranteed at conftest import time.
"""

import os

import jax

# children spawned by disco.run inherit this env and come up CPU-only too
os.environ.setdefault("JAX_PLATFORMS", "cpu")

os.environ.setdefault("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] = (
        os.environ["XLA_FLAGS"] + " --xla_force_host_platform_device_count=8"
    ).strip()
jax.config.update("jax_platforms", "cpu")

from firedancer_tpu.utils import xla_cache  # noqa: E402

xla_cache.enable()
