"""TpuReasm under pressure (disco/tpu_reasm.py): the fixed-slot pool's
DoS bounds.  Depth exhaustion FIFO-evicts, per-conn byte budgets evict
that conn's oldest slots (never grow), and the loss accounting invariant
holds: dup_cnt + evict_cnt + oversz_cnt covers every prepare()d slot that
never reached publish()/cancel()."""

from firedancer_tpu.disco.tpu_reasm import TXN_MTU, TpuReasm


def _mk(depth=4, conn_budget=0, mtu=TXN_MTU):
    out = []
    r = TpuReasm(depth, out.append, mtu=mtu, conn_budget=conn_budget)
    return r, out


def test_depth_exhaustion_fifo_evicts_oldest():
    r, out = _mk(depth=4)
    for c in range(6):                       # 6 opens into 4 slots
        assert r.prepare((c, 0))
        assert r.append((c, 0), b"x" * 10)
    assert len(r._slots) == 4
    assert r.metrics["evict_cnt"] == 2
    # the two oldest died; appends to them are dropped frags
    assert not r.append((0, 0), b"y")
    assert not r.append((1, 0), b"y")
    # the survivors still publish
    for c in range(2, 6):
        assert r.publish((c, 0))
    assert out == [b"x" * 10] * 4
    assert r._slots == {} and r._conn_bytes == {}


def test_cancel_and_dup_prepare_account():
    r, out = _mk()
    assert r.prepare((1, 0))
    assert r.append((1, 0), b"abc")
    r.cancel((1, 0))
    assert r._conn_bytes == {}               # cancel releases the bytes
    assert r.prepare((2, 0))
    assert r.append((2, 0), b"d")
    assert r.prepare((2, 0))                 # dup prepare restarts stream
    assert r.metrics["dup_cnt"] == 1
    assert r.append((2, 0), b"ef")
    assert r.publish((2, 0))
    assert out == [b"ef"]                    # pre-dup bytes are gone


def test_interleaved_streams_many_conns():
    r, out = _mk(depth=64)
    n_conn, per = 16, 4
    for part in range(3):                    # byte-interleaved appends
        for c in range(n_conn):
            for s in range(per):
                key = (c, s)
                if part == 0:
                    assert r.prepare(key)
                assert r.append(key, bytes([c]) * (part + 1))
    for c in range(n_conn):
        for s in range(per):
            assert r.publish((c, s))
    assert len(out) == n_conn * per
    assert all(len(b) == 6 for b in out)
    assert r._conn_bytes == {}
    assert r.metrics["evict_cnt"] == 0       # depth 64 fits all 64 streams


def test_oversize_stream_dropped_and_counted():
    r, out = _mk(mtu=64)
    assert r.prepare((1, 0))
    assert r.append((1, 0), b"a" * 60)
    assert not r.append((1, 0), b"b" * 10)   # 70 > 64: slot killed
    assert r.metrics["oversz_cnt"] == 1
    assert not r.publish((1, 0))
    assert out == [] and r._conn_bytes == {}


def test_conn_budget_evicts_oldest_of_that_conn_only():
    r, out = _mk(depth=64, conn_budget=100)
    # victim conn 7 opens three streams; hostile growth on a fourth must
    # shed conn 7's OLDEST streams, never conn 8's
    assert r.prepare((8, 0)) and r.append((8, 0), b"z" * 90)
    for s in range(3):
        assert r.prepare((7, s)) and r.append((7, s), b"x" * 30)
    assert r.prepare((7, 3))
    # 90+40 > 100: evicting (7,0) alone (-30) gets back under budget —
    # evict-oldest stops as soon as the append fits, never over-sheds
    assert r.append((7, 3), b"y" * 40)
    assert r.metrics["evict_cnt"] == 1
    assert (7, 0) not in r._slots and (7, 1) in r._slots
    assert (8, 0) in r._slots                # the other conn is untouched
    assert r._conn_bytes[7] == 30 + 30 + 40 and r._conn_bytes[8] == 90
    assert r.publish((7, 1)) and r.publish((7, 2)) and r.publish((7, 3))
    assert r.publish((8, 0))


def test_conn_budget_stream_bigger_than_budget_never_grows():
    r, out = _mk(conn_budget=50)
    assert r.prepare((1, 0))
    assert r.append((1, 0), b"a" * 40)
    assert not r.append((1, 0), b"b" * 20)   # 60 > 50 and nothing to shed
    assert r.metrics["evict_cnt"] == 1       # the stream itself was shed
    assert r._slots == {} and r._conn_bytes == {}
    assert not r.publish((1, 0))


def test_loss_accounting_invariant():
    """Every prepared slot ends in exactly one bucket: published,
    cancelled, or a counted loss (dup/evict/oversz)."""
    r, out = _mk(depth=8, conn_budget=200, mtu=100)
    prepared = published = cancelled = 0
    for i in range(200):
        key = (i % 5, i % 13)
        if key not in r._slots:
            r.prepare(key)
            prepared += 1
        else:
            r.prepare(key)                   # dup: old slot becomes a loss
            prepared += 1
        ok = r.append(key, bytes((i % 37) + 1))
        if not ok:
            continue
        if i % 3 == 0:
            if r.publish(key):
                published += 1
        elif i % 7 == 0:
            if key in r._slots:
                r.cancel(key)
                cancelled += 1
    # drain the remainder
    for key in list(r._slots):
        r.cancel(key)
        cancelled += 1
    m = r.metrics
    losses = m["dup_cnt"] + m["evict_cnt"] + m["oversz_cnt"]
    assert prepared == published + cancelled + losses, (
        f"prepared={prepared} published={published} cancelled={cancelled} "
        f"losses={losses} metrics={m}")
    assert r._conn_bytes == {}               # no leaked accounting


def test_publish_datagram_legacy_path():
    r, out = _mk(mtu=32)
    assert r.publish_datagram(b"ok")
    assert not r.publish_datagram(b"")
    assert not r.publish_datagram(b"x" * 33)
    assert out == [b"ok"]
    assert r.metrics["empty_cnt"] == 1 and r.metrics["oversz_cnt"] == 1
