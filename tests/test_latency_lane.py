"""Dual-lane dispatch policy (round 9): a deadline-driven low-latency
lane beside the throughput lane — batch-close-on-deadline at any fill,
priority admission, spill-to-throughput under overload, and zero compiles
on the hot path once the ladder shapes are pre-warmed.

The device is a fake (fixed-latency future / content-dependent verdict)
so every test measures the DISPATCH POLICY deterministically on CPU, with
no jax graph compiles in the fast tier."""

import time

import numpy as np

from firedancer_tpu.disco.pipeline import (
    LAT_PRIO_BIT, VerifyPipeline, _Bucket)
from tests.test_pipeline import make_signed_txn

MAXLEN = 256
LAT_S = 0.02


class _FakeResult:
    def __init__(self, arr, ready_at):
        self._arr = arr
        self._ready_at = ready_at

    def is_ready(self):
        return time.monotonic() >= self._ready_at

    def __array__(self, dtype=None, copy=None):
        while not self.is_ready():
            time.sleep(0.001)
        return self._arr if dtype is None else self._arr.astype(dtype)


def _fake_verify(msgs, lens, sigs, pubs):
    n = np.asarray(msgs).shape[0]
    return _FakeResult(np.ones((n,), dtype=bool), time.monotonic() + LAT_S)


def _content_verify(msgs, lens, sigs, pubs):
    """Verdict from row CONTENT only (byte sums are invariant under the
    zero padding that differs between bucket widths): the cross-lane
    bit-identity oracle."""
    m = np.asarray(msgs).astype(np.int64)
    s = np.asarray(sigs).astype(np.int64)
    v = (m.sum(axis=1) + s.sum(axis=1) + np.asarray(lens)) % 2 == 0
    return v.astype(bool)


def _warm_shapes(p, shapes):
    p.mark_warm([(b, MAXLEN) for b in shapes])


def test_deadline_close_at_low_fill():
    """The open lat batch dispatches the moment its oldest txn ages past
    deadline_us — at 1/16 fill, in the closest-fit ladder shape."""
    p = VerifyPipeline(_fake_verify, batch=256, msg_maxlen=MAXLEN,
                       tcache_depth=256, max_inflight=4,
                       lat_shapes=(16, 64), deadline_us=1000)
    _warm_shapes(p, (16, 64, 256))
    t = make_signed_txn(1)
    assert p.submit(t, lat=True) == []
    assert p.metrics.lat_txns == 1
    assert not p.lat_due()
    assert p.dispatch_due() == []           # not due yet: nothing closes
    assert p.metrics.lat_deadline_closes == 0
    time.sleep(0.002)
    assert p.lat_due()
    assert p.dispatch_due() == []           # closed + dispatched, not done
    assert p.metrics.lat_deadline_closes == 1
    assert p.metrics.lanes_dispatched == 16  # closest-fit, not 64/256
    assert p.metrics.last_fill_pct == 100 * 1 // 16
    time.sleep(LAT_S * 1.5)
    out = p.harvest()
    assert [pl for pl, _ in out] == [t]
    assert p.metrics.lat_batches == 1
    assert p.metrics.compile_cnt == 0       # pre-warmed: no hot compile


def test_priority_admission_routes_lanes():
    """lat=True admits to the small lane, bulk fills the throughput
    bucket; both verify."""
    p = VerifyPipeline(_fake_verify, batch=8, msg_maxlen=MAXLEN,
                       tcache_depth=64, max_inflight=4,
                       lat_shapes=(4,), deadline_us=10_000_000)
    bulk = [make_signed_txn(100 + i) for i in range(3)]
    prio = [make_signed_txn(200 + i) for i in range(2)]
    for t in bulk:
        p.submit(t)
    for t in prio:
        p.submit(t, lat=True)
    assert p.metrics.lat_txns == 2
    assert len(p.buckets[0].pending) == 3
    assert len(p.lat_bucket.pending) == 2
    out = p.flush()
    assert sorted(pl for pl, _ in out) == sorted(bulk + prio)
    assert p.metrics.verify_pass == 5
    assert p.metrics.lat_spill == 0


def test_spill_to_throughput_under_overload():
    """With the lane's inflight budget exhausted, a latency admission
    SPILLS to the throughput lane — counted, still verified, never
    dropped."""
    p = VerifyPipeline(_fake_verify, batch=8, msg_maxlen=MAXLEN,
                       tcache_depth=64, max_inflight=4,
                       lat_shapes=(4,), deadline_us=10_000_000,
                       lat_max_inflight=1)
    txns = [make_signed_txn(300 + i) for i in range(5)]
    for t in txns[:4]:                      # fills + dispatches the lane
        p.submit(t, lat=True)
    assert len(p.lat_inflight) == 1
    assert p.submit(txns[4], lat=True) == []   # budget hit: spill
    assert p.metrics.lat_spill == 1
    assert p.metrics.lat_txns == 4
    assert len(p.buckets[0].pending) == 1   # spilled into the bulk bucket
    out = p.flush()
    assert sorted(pl for pl, _ in out) == sorted(txns)
    assert p.metrics.verify_pass == 5       # the spilled txn verified too


def test_bit_identical_verdicts_across_lanes():
    """The same txns produce the same verdicts whether they ride the
    throughput bucket or the small-shape lane (zero padding between
    bucket widths must not leak into verdicts)."""
    txns = [make_signed_txn(400 + i) for i in range(12)]

    a = VerifyPipeline(_content_verify, batch=16, msg_maxlen=MAXLEN,
                       tcache_depth=64, max_inflight=0)
    pass_a = []
    for t in txns:
        pass_a += [pl for pl, _ in a.submit(t)]
    pass_a += [pl for pl, _ in a.flush()]

    b = VerifyPipeline(_content_verify, batch=16, msg_maxlen=MAXLEN,
                       tcache_depth=64, max_inflight=0,
                       lat_shapes=(4, 8, 16), deadline_us=10_000_000)
    pass_b = []
    for t in txns:
        pass_b += [pl for pl, _ in b.submit(t, lat=True)]
    pass_b += [pl for pl, _ in b.flush()]

    assert a.metrics.verify_pass == b.metrics.verify_pass
    assert a.metrics.verify_fail == b.metrics.verify_fail
    assert a.metrics.verify_fail > 0        # the oracle is actually mixed
    assert sorted(pass_a) == sorted(pass_b)
    assert b.metrics.lat_txns == 12


def test_no_compile_on_hot_path_after_warm():
    """mark_warm pre-seeds the ladder: steady-state dispatches count zero
    compiles; a cold shape (no mark_warm) is counted — the signal works
    both ways."""
    p = VerifyPipeline(_fake_verify, batch=8, msg_maxlen=MAXLEN,
                       tcache_depth=64, max_inflight=4,
                       lat_shapes=(4,), deadline_us=10_000_000)
    _warm_shapes(p, (4, 8))
    for i in range(8):
        p.submit(make_signed_txn(500 + i))
    p.submit(make_signed_txn(520), lat=True)
    p.flush()
    assert p.metrics.compile_cnt == 0

    cold = VerifyPipeline(_fake_verify, batch=8, msg_maxlen=MAXLEN,
                          tcache_depth=64, max_inflight=4)
    for i in range(8):
        cold.submit(make_signed_txn(600 + i))
    cold.flush()
    assert cold.metrics.compile_cnt == 1


def test_bucket_bidx_matches_position():
    """_Bucket.bidx is assigned at creation (the O(n) buckets.index()
    this replaced ran once per dispatch); the lat accumulator sits one
    past the ladder."""
    p = VerifyPipeline(_fake_verify,
                       buckets=[(64, 1232), (2048, 256), (256, 768)],
                       tcache_depth=64, lat_shapes=(16,),
                       deadline_us=1000)
    assert [bk.maxlen for bk in p.buckets] == [256, 768, 1232]
    assert [bk.bidx for bk in p.buckets] == [0, 1, 2]
    assert all(bk.lane == 0 for bk in p.buckets)
    assert p.lat_bucket.bidx == 3 and p.lat_bucket.lane == 1


def test_adaptive_heartbeat_backoff():
    """_finish's device wait starts at ~50 us and decays toward the old
    500 us cap: a ~5 ms verdict heartbeats MANY times (the fixed 500 us
    poll managed ~10; the backoff front-loads sub-100 us polls for the
    lat lane's sub-ms verdicts)."""
    beats = []

    def fake(msgs, lens, sigs, pubs):
        n = np.asarray(msgs).shape[0]
        return _FakeResult(np.ones((n,), dtype=bool),
                           time.monotonic() + 0.005)

    p = VerifyPipeline(fake, batch=2, msg_maxlen=MAXLEN, tcache_depth=64,
                       max_inflight=0, heartbeat_cb=lambda: beats.append(1))
    txns = [make_signed_txn(700 + i) for i in range(2)]
    out = []
    for t in txns:
        out += p.submit(t)
    assert sorted(pl for pl, _ in out) == sorted(txns)
    assert len(beats) >= 5                   # 50+100+200+400+500... < 5 ms


class _PackedFake:
    """dispatch_blob verifier stand-in recording dispatched row counts."""

    def __init__(self):
        self.shapes = []

    def __call__(self, msgs, lens, sigs, pubs):
        return np.ones((np.asarray(msgs).shape[0],), bool)

    def dispatch_blob(self, blob, maxlen=None):
        self.shapes.append(int(blob.shape[0]))
        return np.ones((blob.shape[0],), bool)


def _packed_rows(lens, ml, seed=3):
    """Device-blob rows (msg | sig64 | pub32 | len-le32) with nonzero
    tags, one single-sig wire txn per row."""
    rng = np.random.default_rng(seed)
    stride = ml + _Bucket.PACKED_EXTRA
    rows = np.zeros((len(lens), stride), np.uint8)
    for i, L in enumerate(lens):
        rows[i, :L] = rng.integers(1, 256, L, dtype=np.uint8)
        rows[i, ml:ml + 64] = rng.integers(1, 256, 64, dtype=np.uint8)
        rows[i, ml + 96:ml + 100] = np.frombuffer(
            np.int32(L).tobytes(), np.uint8)
    return rows


def test_ragged_wire_reconstruction_vectorized():
    """The unequal-length _finish_rows fallback (vectorized round 9)
    must reconstruct byte-exact wires: 0x01 | sig | msg[:len] per row."""
    ml = 128
    lens = [5, 40, 40, 17, 128, 1, 33]
    rows = _packed_rows(lens, ml)
    p = VerifyPipeline(_PackedFake(), buckets=[(len(lens), ml)],
                       tcache_depth=64, max_inflight=0)
    out = p.submit_packed_rows(rows)
    assert len(out) == len(lens)
    for i, (wire, _) in enumerate(out):
        expect = (b"\x01" + rows[i, ml:ml + 64].tobytes()
                  + rows[i, :lens[i]].tobytes())
        assert wire == expect, f"row {i} wire mismatch"
    # all-dup resubmission exercises the empty-keep early return
    assert p.submit_packed_rows(rows) == []


def test_packed_rows_lat_closest_fit():
    """A latency-class packed frag dispatches the closest-fit ladder
    slice (still zero-copy), not the full accumulator width."""
    ml = 128
    fake = _PackedFake()
    p = VerifyPipeline(fake, buckets=[(16, ml)], tcache_depth=64,
                       max_inflight=4, lat_shapes=(4, 8, 16),
                       deadline_us=10_000_000)
    rows = np.zeros((16, ml + _Bucket.PACKED_EXTRA), np.uint8)
    rows[:3] = _packed_rows([20, 30, 40], ml, seed=5)
    # the fake's verdict is ready instantly, so the dispatch's trailing
    # harvest returns the wires in the same call
    out = p.submit_packed_rows(rows, n=3, lat=True)
    out += p.harvest(block=True)
    assert fake.shapes[-1] == 4             # 3 live rows -> 4-row slice
    assert p.metrics.lat_txns == 3
    assert len(out) == 3
    assert p.metrics.lat_batches == 1
    assert not p.lat_inflight


def test_trace_lane_split_and_prio_bit():
    """Span iidx carries the lane tag in a high bit; the sig priority
    bit sits above the source-tag range so wire sig bytes can be masked
    clean."""
    from firedancer_tpu.disco import trace

    assert LAT_PRIO_BIT == 1 << 63
    idx, is_lat = trace._lane_split(3 | trace.LANE_LAT)
    assert idx == 3 and is_lat
    idx, is_lat = trace._lane_split(5)
    assert idx == 5 and not is_lat
