"""RepairTile request side (VERDICT r2 weak #7): the planner runs IN the
tile — two tiles over real UDP sockets, the gappy one closes its slot
against the complete one, and repaired shreds are published downstream."""

import time
from dataclasses import dataclass, field

from firedancer_tpu.ballet import entry as entry_lib
from firedancer_tpu.ballet import shred as shred_lib
from firedancer_tpu.disco import keyguard
from firedancer_tpu.disco.tiles import RepairTile
from firedancer_tpu.flamenco import repair as repair_mod
from firedancer_tpu.ops import ed25519 as ed


@dataclass
class _FakeMetrics:
    vals: dict = field(default_factory=dict)

    def set(self, k, v):
        self.vals[k] = v

    def add(self, k, d=1):
        self.vals[k] = self.vals.get(k, 0) + d

    def get(self, k, default=0):
        return self.vals.get(k, default)


@dataclass
class _FakeTile:
    out_links: tuple = ()


class _FakeCtx:
    def __init__(self, cfg, out_links=("repair_store",)):
        self.cfg = cfg
        self.metrics = _FakeMetrics()
        self.tile = _FakeTile(tuple(out_links))
        self.published = []

    def publish(self, payload, sig=0, out=0):
        self.published.append((bytes(payload), sig, out))


def _mk_tile(tmp_path, name, seed_i, peers=()):
    seed = seed_i.to_bytes(32, "little")
    pub = ed.keypair_from_seed(seed)[0]
    kpath = str(tmp_path / f"{name}.json")
    keyguard.keypair_write(kpath, seed, pub)
    ctx = _FakeCtx(dict(key_path=kpath, repair_port=0, peers=list(peers),
                        plan_interval_s=0.0))
    t = RepairTile()
    t.init(ctx)
    return t, ctx, pub


def test_repair_tile_closes_gaps_over_udp(tmp_path):
    lead_seed = (61).to_bytes(32, "little")
    entries = [entry_lib.Entry(1, bytes([i]) * 32, []) for i in range(3)]
    fs = shred_lib.make_fec_set(
        entry_lib.serialize_batch(entries), slot=5, parent_off=1, version=1,
        fec_set_idx=0, sign_fn=lambda r: ed.sign(lead_seed, r),
        data_cnt=32, code_cnt=32, slot_complete=True)

    srv, srv_ctx, srv_pub = _mk_tile(tmp_path, "srv", 62)
    # feed the server tile the full slot through its in-link path
    for raw in fs.data_shreds + fs.code_shreds:
        srv.on_frag(srv_ctx, 0, {}, raw)
    assert srv.store.slot_complete(5)

    cli, cli_ctx, _ = _mk_tile(
        tmp_path, "cli", 63,
        peers=[[srv_pub.hex(), "127.0.0.1", srv.sock.port, 100]])
    # gappy ingest: first 21 data shreds with two interior holes
    for i, raw in enumerate(fs.data_shreds[:21]):
        if i not in (4, 9):
            cli.on_frag(cli_ctx, 0, {}, raw)
    assert not cli.store.slot_complete(5)

    # warm the (1,1280) verifier BEFORE the pacing deadline: the server
    # verifies request signatures through it and a cold compile would eat
    # the whole window
    ed.verify_one(bytes(64), b"warm", bytes(32))

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and not cli.store.slot_complete(5):
        cli._last_plan = 0.0          # defeat pacing for test speed
        cli.house(cli_ctx)
        time.sleep(0.02)
        srv.after_credit(srv_ctx)
        time.sleep(0.02)
        cli.after_credit(cli_ctx)

    assert cli.store.slot_complete(5)
    assert cli_ctx.metrics.get("repaired_cnt") > 0
    assert srv_ctx.metrics.get("served_cnt") > 0
    # repaired shreds were published downstream (to the store fan-in)
    assert cli_ctx.published
    cli.fini(cli_ctx)
    srv.fini(srv_ctx)


def test_repair_role_disjoint_from_gossip():
    """ROLE_REPAIR accepts exactly the 49-byte request pre-image and
    ROLE_GOSSIP refuses it (mutual exclusion keeps a compromised gossip
    tile from minting repair requests and vice versa)."""
    req = repair_mod.make_request(
        lambda m: b"\0" * 64, b"\x11" * 32, repair_mod.REQ_WINDOW_INDEX,
        7, 5, 3)
    pre = req.signable()
    dl = len(repair_mod.SIGN_DOMAIN)
    assert pre.startswith(repair_mod.SIGN_DOMAIN) and len(pre) == dl + 49
    assert keyguard.role_payload_ok(keyguard.ROLE_REPAIR, pre)
    assert not keyguard.role_payload_ok(keyguard.ROLE_GOSSIP, pre)
    assert not keyguard.role_payload_ok(keyguard.ROLE_REPAIR, pre + b"x")
    assert not keyguard.role_payload_ok(
        keyguard.ROLE_REPAIR, pre[: dl + 32] + b"\x09" + pre[dl + 33 :])
    # un-domained blob of the same length is not a repair preimage
    assert not keyguard.role_payload_ok(keyguard.ROLE_REPAIR,
                                        b"\x01" * len(pre))
    # gossip blobs that are NOT domain-prefixed still sign fine —
    # including 49-byte CRDS signables (lowest-slot etc.), which a
    # length-shape heuristic would have wrongly refused
    assert keyguard.role_payload_ok(keyguard.ROLE_GOSSIP, b"\x01" * 48)
    crds_like = b"\x22" * 32 + b"\x02" + b"\x00" * 16  # origin|kind|wc|body
    assert len(crds_like) == 49
    assert keyguard.role_payload_ok(keyguard.ROLE_GOSSIP, crds_like)
