"""X25519 (RFC 7748) and ristretto255 (RFC 9496) test vectors."""

import pytest

from firedancer_tpu.ops import ristretto255 as rst
from firedancer_tpu.ops import x25519


# ------------------------------------------------------------------ x25519

def test_x25519_rfc7748_vector1():
    k = bytes.fromhex(
        "a546e36bf0527c9d3b16154b82465edd62144c0ac1fc5a18506a2244ba449ac4"
    )
    u = bytes.fromhex(
        "e6db6867583030db3594c1a424b15f7c726624ec26b3353b10a903a6d0ab1c4c"
    )
    want = "c3da55379de9c6908e94ea4df28d084f32eccf03491c71f754b4075577a28552"
    assert x25519.x25519(k, u).hex() == want


def test_x25519_rfc7748_vector2():
    k = bytes.fromhex(
        "4b66e9d4d1b4673c5ad22691957d6af5c11b6421e0ea01d42ca4169e7918ba0d"
    )
    u = bytes.fromhex(
        "e5210f12786811d3f4b7959d0538ae2c31dbe7106fc03c3efc4cd549c715a493"
    )
    want = "95cbde9476e8907d7aade45cb4b873f88b595a68799fa152e6f8f7647aac7957"
    assert x25519.x25519(k, u).hex() == want


def test_x25519_rfc7748_iterated():
    # RFC 7748 §5.2: after 1 iteration of k,u <- X25519(k,u),k
    k = u = (9).to_bytes(32, "little")
    r = x25519.x25519(k, u)
    assert r.hex() == (
        "422c8e7a6227d7bca1350b3e2bb7279f7897b87bb6854b783c60e80311ae3079"
    )
    # 1000 iterations
    k, u = r, k
    for _ in range(999):
        k, u = x25519.x25519(k, u), k
    assert k.hex() == (
        "684cf59ba83309552800ef566f2f4d3c1c3887c49360e3875f2eb94d99532c51"
    )


def test_x25519_dh():
    a_priv = bytes.fromhex(
        "77076d0a7318a57d3c16c17251b26645df4c2f87ebc0992ab177fba51db92c2a"
    )
    b_priv = bytes.fromhex(
        "5dab087e624a8a4b79e17f8b83800ee66f3bb1292618b6fd1c2f8b27ff88e0eb"
    )
    a_pub = x25519.public_key(a_priv)
    b_pub = x25519.public_key(b_priv)
    assert a_pub.hex() == (
        "8520f0098930a754748b7ddcb43ef75a0dbf3a0d26381af4eba4a98eaa9b4e6a"
    )
    assert b_pub.hex() == (
        "de9edb7d7b7dc1b4d35b61c2ece435373f8343c85b78674dadfc7e146f882b4f"
    )
    shared = "4a5d9d5ba4ce2de1728e3bf480350f25e07e21c947d19e3376f09b3c1e161742"
    assert x25519.shared_secret(a_priv, b_pub).hex() == shared
    assert x25519.shared_secret(b_priv, a_pub).hex() == shared


def test_x25519_rejects_low_order():
    with pytest.raises(ValueError):
        x25519.shared_secret(b"\x42" * 32, b"\x00" * 32)  # order-1 point


# --------------------------------------------------------------- ristretto

# RFC 9496 §A.1: encodings of B, 2B, ..., 15B  (first 6 checked)
_MULTIPLES = [
    "0000000000000000000000000000000000000000000000000000000000000000",
    "e2f2ae0a6abc4e71a884a961c500515f58e30b6aa582dd8db6a65945e08d2d76",
    "6a493210f7499cd17fecb510ae0cea23a110e8d5b901f8acadd3095c73a3b919",
    "94741f5d5d52755ece4f23f044ee27d5d1ea1e2bd196b462166b16152a9d0259",
    "da80862773358b466ffadfe0b3293ab3d9fd53c5ea6c955358f568322daf6a57",
    "e882b131016b52c1d3337080187cf768423efccbb517bb495ab812c4160ff44e",
]


def test_ristretto_generator_multiples():
    p = rst.Point.identity()
    for i, want in enumerate(_MULTIPLES):
        assert p.encode().hex() == want, i
        # decode round-trips to an equal group element
        assert rst.decode(bytes.fromhex(want)) == p
        p = p + rst.BASE


def test_ristretto_scalar_mul_matches_adds():
    assert rst.BASE.mul(5).encode() == bytes.fromhex(_MULTIPLES[5])
    assert (rst.BASE.mul(3) + rst.BASE.mul(2)) == rst.BASE.mul(5)
    assert (rst.BASE.mul(7) - rst.BASE.mul(2)).encode() == rst.BASE.mul(5).encode()


# RFC 9496 §A.3: invalid encodings
_INVALID = [
    # non-canonical field encodings
    "00ffffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff",
    "ffffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f",
    "f3ffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f",
    "edffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f",
    # negative field elements
    "0100000000000000000000000000000000000000000000000000000000000000",
    "01ffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f",
    # non-square x^2
    "26948d35ca62e643e26a83177332e6b6afeb9d08e4268b650f1f5bbd8d81d371",
]


def test_ristretto_invalid_encodings():
    for h in _INVALID:
        assert rst.decode(bytes.fromhex(h)) is None, h


def test_ristretto_from_uniform():
    # determinism + group membership (encodes/decodes cleanly)
    p = rst.from_uniform_bytes(bytes(range(64)))
    q = rst.from_uniform_bytes(bytes(range(64)))
    assert p == q
    enc = p.encode()
    assert rst.decode(enc) == p
    # different input -> different element
    r = rst.from_uniform_bytes(bytes(range(1, 65)))
    assert r != p
