"""Systematic race-detection stress suite.

Role of the reference's concurrency tests (test_funk_concur.cxx, the
tango mcache/fseq multi-producer tests, SURVEY.md §5 "sanitizers/race
detection"): hammer the lock-free structures from multiple REAL
processes and assert the invariants that a torn read/write would break.

Every payload carries a self-checksum so any torn frag, stale-chunk read,
or seqlock violation turns into a hard assertion, not a flake.  Processes
are spawned (not forked) so each side re-joins the shared memory cold,
like independent tiles.
"""

import hashlib
import multiprocessing as mp
import os
import time

import numpy as np
import pytest

from firedancer_tpu.tango.ring import Dcache, FSeq, MCache, Workspace, ctl

DEPTH = 64
MTU = 512
N_FRAGS = 4000


def _payload(seq: int) -> bytes:
    """Deterministic self-checking payload: body derived from seq."""
    body = hashlib.sha256(seq.to_bytes(8, "little")).digest() * 8
    return body[: 16 + (seq % (MTU - 48))]


def _producer(name: str, mc_off: int, dc_off: int, fseq_off: int,
              n: int, err_q):
    try:
        ws = Workspace(name, 1 << 22)
        mc = MCache.join(ws, mc_off)
        dc = Dcache.join(ws, dc_off)
        fs = FSeq.join(ws, fseq_off)
        cur = dc.chunk0
        for seq in range(n):
            # reliable flow control: don't lap the consumer
            while seq - fs.query() >= DEPTH - 2:
                time.sleep(0)
            data = _payload(seq)
            nxt = dc.write(cur, data)
            mc.publish(sig=seq, chunk=cur, sz=len(data), ctl_=ctl())
            cur = nxt
    except Exception as e:  # pragma: no cover
        err_q.put(f"producer: {e!r}")


def _consumer(name: str, mc_off: int, dc_off: int, fseq_off: int,
              n: int, err_q):
    try:
        ws = Workspace(name, 1 << 22)
        mc = MCache.join(ws, mc_off)
        dc = Dcache.join(ws, dc_off)
        fs = FSeq.join(ws, fseq_off)
        seq = mc.seq0()
        base = seq
        while seq < base + n:
            rc, m = mc.query(seq)
            if rc == -1:
                time.sleep(0)
                continue
            if rc == 1:
                err_q.put(f"consumer: overrun at {seq}")
                return
            data = dc.read(int(m["chunk"]), int(m["sz"]))
            want = _payload(int(m["sig"]))
            if bytes(data) != want:
                err_q.put(
                    f"consumer: TORN frag at seq {seq}: sig={m['sig']}")
                return
            if int(m["sig"]) != seq - base:
                err_q.put(f"consumer: sig mismatch {m['sig']} != {seq}")
                return
            seq += 1
            fs.update(seq - base)
    except Exception as e:  # pragma: no cover
        err_q.put(f"consumer: {e!r}")


@pytest.mark.slow
def test_ring_no_torn_frags_under_load():
    """One producer + one consumer, 4000 checksummed frags through a
    64-deep ring with reliable backpressure: any seqlock tear fails."""
    name = f"fdtpu_race_{os.getpid()}"
    ws = Workspace(name, 1 << 22, create=True)
    mc = MCache.new(ws, DEPTH)
    dc = Dcache.new(ws, MTU, DEPTH, burst=4)
    fs = FSeq.new(ws)
    ctxmp = mp.get_context("spawn")
    err_q = ctxmp.Queue()
    args = (name, mc.off, dc.off, fs.off, N_FRAGS, err_q)
    cons = ctxmp.Process(target=_consumer, args=args)
    prod = ctxmp.Process(target=_producer, args=args)
    cons.start()
    prod.start()
    prod.join(120)
    cons.join(120)
    errs = []
    while not err_q.empty():
        errs.append(err_q.get())
    try:
        assert not errs, errs
        assert prod.exitcode == 0 and cons.exitcode == 0
    finally:
        for p in (prod, cons):
            if p.is_alive():
                p.terminate()
        ws.unlink()


def _unreliable_reader(name: str, mc_off: int, dc_off: int, n: int, err_q,
                       done_q):
    """Overrun-tolerant consumer (the tango unreliable pattern): must
    DETECT every overrun, never read a torn frag undetected."""
    try:
        ws = Workspace(name, 1 << 22)
        mc = MCache.join(ws, mc_off)
        dc = Dcache.join(ws, dc_off)
        seq = mc.seq0()
        end = seq + n
        seen = 0
        overruns = 0
        while seq < end:
            rc, m = mc.query(seq)
            if rc == -1:
                if mc.seq_query() >= end:
                    break
                time.sleep(0)
                continue
            if rc == 1:
                overruns += 1
                seq = max(seq + 1, mc.seq_query() - DEPTH // 2)
                continue
            data = bytes(dc.read(int(m["chunk"]), int(m["sz"])))
            # frag was valid at read time iff a re-query still matches
            rc2, m2 = mc.query(seq)
            still_valid = rc2 == 0 and int(m2["sig"]) == int(m["sig"])
            if still_valid and data != _payload(int(m["sig"])):
                err_q.put(f"reader: undetected tear at {seq}")
                return
            seen += 1
            seq += 1
        done_q.put((seen, overruns))
    except Exception as e:  # pragma: no cover
        err_q.put(f"reader: {e!r}")


@pytest.mark.slow
def test_ring_overrun_detection_unreliable_reader():
    """Fast producer, slow unreliable reader: overruns must be flagged by
    the seqlock, and every frag that validates must checksum clean."""
    name = f"fdtpu_race2_{os.getpid()}"
    ws = Workspace(name, 1 << 22, create=True)
    mc = MCache.new(ws, DEPTH)
    dc = Dcache.new(ws, MTU, DEPTH, burst=4)
    ctxmp = mp.get_context("spawn")
    err_q = ctxmp.Queue()
    done_q = ctxmp.Queue()
    n = 3000
    reader = ctxmp.Process(
        target=_unreliable_reader,
        args=(name, mc.off, dc.off, n, err_q, done_q))
    reader.start()

    cur = dc.chunk0
    for seq in range(n):  # unthrottled: laps the reader constantly
        data = _payload(seq)
        nxt = dc.write(cur, data)
        mc.publish(sig=seq, chunk=cur, sz=len(data), ctl_=ctl())
        cur = nxt
    reader.join(120)
    errs = []
    while not err_q.empty():
        errs.append(err_q.get())
    try:
        assert not errs, errs
        assert reader.exitcode == 0
        seen, overruns = done_q.get(timeout=5)
        assert seen > 0
    finally:
        if reader.is_alive():
            reader.terminate()
        ws.unlink()


@pytest.mark.slow
def test_funk_concurrent_readers_during_writes():
    """funk partitions + reader locking (ref test_funk_concur.cxx role):
    thread readers traverse while the writer publishes forks; every read
    must return either the old or the new committed value, never a mix."""
    import threading

    from firedancer_tpu.funk.funk import Funk

    funk = Funk()
    root = None
    keys = [f"acct{i}".encode() for i in range(32)]
    # generation-stamped values: value = gen for every key in that gen
    funk.txn_prepare(b"g0", root)
    for k in keys:
        funk.write(b"g0", k, (0).to_bytes(8, "little") * 4)
    funk.txn_publish(b"g0")

    stop = threading.Event()
    errs = []

    def reader():
        while not stop.is_set():
            gens = set()
            for k in keys:
                v = funk.read(None, k)
                if v is None:
                    errs.append(f"missing {k}")
                    return
                vals = {v[i : i + 8] for i in range(0, len(v), 8)}
                if len(vals) != 1:
                    errs.append(f"torn value for {k}: {vals}")
                    return
                gens.add(int.from_bytes(v[:8], "little"))
            # a full sweep may straddle one publish, never more than 2 gens
            if len(gens) > 2:
                errs.append(f"sweep saw {len(gens)} generations: {gens}")
                return

    threads = [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    for gen in range(1, 40):
        xid = f"g{gen}".encode()
        funk.txn_prepare(xid, None)
        for k in keys:
            funk.write(xid, k, gen.to_bytes(8, "little") * 4)
        funk.txn_publish(xid)
    stop.set()
    for t in threads:
        t.join(30)
    assert not errs, errs[:3]
