"""Upgradeable BPF loader (v3) lifecycle: buffer -> deploy -> invoke ->
upgrade -> authority/close (ref fd_bpf_loader_v3_program.c behaviors)."""

import struct

import pytest

from firedancer_tpu.ballet import txn as txn_lib
from firedancer_tpu.ballet.sbpf import asm
from firedancer_tpu.flamenco import bpf_loader_upgradeable as up
from firedancer_tpu.flamenco import genesis as gen_mod
from firedancer_tpu.flamenco.runtime import Runtime
from firedancer_tpu.flamenco.types import Account
from firedancer_tpu.ops import ed25519 as ed
from tests.test_sbpf_vm import _mini_elf


def _keypair(i):
    seed = i.to_bytes(32, "little")
    return seed, ed.keypair_from_seed(seed)[0]


def _signed(signers, msg):
    return txn_lib.assemble([ed.sign(s, msg) for s, _ in signers], msg)


PROG_V1 = asm("""
    mov r6, r1
    ldxdw r2, [r6+112]
    stxdw [r6+90], r2
    mov r0, 0
    exit""")

# v2 stores instr data + 1 (observable difference after upgrade)
PROG_V2 = asm("""
    mov r6, r1
    ldxdw r2, [r6+112]
    add r2, 1
    stxdw [r6+90], r2
    mov r0, 0
    exit""")


@pytest.fixture
def world():
    faucet_seed, faucet_pk = _keypair(1)
    auth_seed, auth_pk = _keypair(2)
    buf_seed, buf_pk = _keypair(3)
    buf2_seed, buf2_pk = _keypair(7)
    pdata_pk = _keypair(4)[1]
    prog_pk = _keypair(5)[1]
    data_pk = _keypair(6)[1]
    g = gen_mod.create(faucet_pk, creation_time=1)
    elf_cap = len(_mini_elf(PROG_V1)) + 128
    g.accounts[buf_pk] = Account(
        lamports=1_000_000, data=bytes(up.BUFFER_META_SZ + elf_cap))
    g.accounts[buf2_pk] = Account(
        lamports=1_000_000, data=bytes(up.BUFFER_META_SZ + elf_cap))
    g.accounts[pdata_pk] = Account(lamports=1_000_000)
    g.accounts[prog_pk] = Account(lamports=1_000_000, data=bytes(36))
    g.accounts[data_pk] = Account(lamports=1_000_000, data=bytes(8),
                                  owner=prog_pk)
    rt = Runtime(g)
    b = rt.new_bank(1)
    return dict(rt=rt, b=b, faucet=(faucet_seed, faucet_pk),
                auth=(auth_seed, auth_pk), buf=buf_pk, buf2=buf2_pk,
                buf_kp=(buf_seed, buf_pk), buf2_kp=(buf2_seed, buf2_pk),
                pdata=pdata_pk, prog=prog_pk, data=data_pk)


def _run(w, signers, extra, prog_index, ix_accounts, data, n_ro=1):
    """One instruction; account list = [faucet] + signers + extra;
    prog_index / ix_accounts are explicit indices into that list."""
    rt, b = w["rt"], w["b"]
    fs, fpk = w["faucet"]
    msg = txn_lib.build_unsigned(
        [fpk] + [pk for _, pk in signers], rt.root_hash,
        [(prog_index, bytes(ix_accounts), data)],
        extra_accounts=extra, readonly_unsigned_cnt=n_ro)
    return b.execute_txn(_signed([(fs, fpk)] + signers, msg))


def _deploy(w, elf):
    auth_s, auth_pk = w["auth"]
    # account list: [faucet0, auth1, buf2, LOADER3]
    r = _run(w, [(auth_s, auth_pk), w["buf_kp"]],
             [up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_initialize_buffer())
    assert r.ok, r.err
    half = len(elf) // 2
    for off, chunk in ((0, elf[:half]), (half, elf[half:])):
        r = _run(w, [(auth_s, auth_pk)],
                 [w["buf"], up.UPGRADEABLE_LOADER_ID],
                 3, [2, 1], up.ix_write(off, chunk))
        assert r.ok, r.err
    # [faucet0, auth1, pdata2, prog3, buf4, LOADER5];
    # ix accounts: payer, programdata, program, buffer, authority
    r = _run(w, [(auth_s, auth_pk)],
             [w["pdata"], w["prog"], w["buf"], up.UPGRADEABLE_LOADER_ID],
             5, [0, 2, 3, 4, 1],
             up.ix_deploy_with_max_data_len(len(elf) + 256))
    assert r.ok, r.err


def test_buffer_deploy_invoke_upgrade(world):
    w = world
    rt, b = w["rt"], w["b"]
    auth_s, auth_pk = w["auth"]
    _deploy(w, _mini_elf(PROG_V1))

    pa = rt.accdb.load(b.xid, w["prog"])
    assert pa.executable and pa.owner == up.UPGRADEABLE_LOADER_ID
    st, s = up._state_of(pa.data)
    assert st == up.PROGRAM and bytes(s["programdata_address"]) == w["pdata"]
    pd = rt.accdb.load(b.xid, w["pdata"])
    std, sd = up._state_of(pd.data)
    assert std == up.PROGRAMDATA
    assert bytes(sd["upgrade_authority"]) == auth_pk

    # invoke: programdata must ride along for resolution
    # [faucet0, data1, prog2, pdata3]
    magic = struct.pack("<Q", 0xABCD1234)
    r = _run(w, [], [w["data"], w["prog"], w["pdata"]], 2, [1], magic,
             n_ro=2)
    assert r.ok, r.err
    assert rt.accdb.load(b.xid, w["data"]).data == magic

    # upgrade to v2 via a FRESH buffer (deploy drains the first one,
    # matching upstream's buffer close-on-deploy)
    elf2 = _mini_elf(PROG_V2)
    r = _run(w, [(auth_s, auth_pk), w["buf2_kp"]],
             [up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_initialize_buffer())
    assert r.ok, r.err
    r = _run(w, [(auth_s, auth_pk)],
             [w["buf2"], up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_write(0, elf2))
    assert r.ok, r.err
    # [faucet0, auth1, pdata2, prog3, buf2_4, data5, LOADER6];
    # ix: programdata, program, buffer, spill, authority
    r = _run(w, [(auth_s, auth_pk)],
             [w["pdata"], w["prog"], w["buf2"], w["data"],
              up.UPGRADEABLE_LOADER_ID],
             6, [2, 3, 4, 5, 1], up.ix_upgrade())
    assert r.ok, r.err

    r = _run(w, [], [w["data"], w["prog"], w["pdata"]], 2, [1], magic,
             n_ro=2)
    assert r.ok, r.err
    want = struct.pack("<Q", 0xABCD1235)  # v2 adds 1
    assert rt.accdb.load(b.xid, w["data"]).data == want


def test_write_requires_authority_signature(world):
    w = world
    mallory_s, mallory_pk = _keypair(9)
    auth_s, auth_pk = w["auth"]
    r = _run(w, [(auth_s, auth_pk), w["buf_kp"]],
             [up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_initialize_buffer())
    assert r.ok, r.err
    # mallory signs instead of the recorded authority
    # [faucet0, mallory1, buf2, LOADER3]
    r = _run(w, [(mallory_s, mallory_pk)],
             [w["buf"], up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_write(0, b"\x7fELF"))
    assert not r.ok and "authority" in r.err


def test_set_authority_and_close(world):
    w = world
    auth_s, auth_pk = w["auth"]
    new_s, new_pk = _keypair(10)
    r = _run(w, [(auth_s, auth_pk), w["buf_kp"]],
             [up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_initialize_buffer())
    assert r.ok, r.err
    # [faucet0, auth1, new2, buf3, LOADER4]; ix: buffer, cur auth, new
    r = _run(w, [(auth_s, auth_pk), (new_s, new_pk)],
             [w["buf"], up.UPGRADEABLE_LOADER_ID],
             4, [3, 1, 2], up.ix_set_authority())
    assert r.ok, r.err
    # old authority can no longer write: [faucet0, auth1, buf2, L3]
    r = _run(w, [(auth_s, auth_pk)],
             [w["buf"], up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_write(0, b"x"))
    assert not r.ok
    # close: [faucet0, new1, buf2, data3, L4]; ix: buffer, recipient, auth
    rt, b = w["rt"], w["b"]
    before = rt.accdb.load(b.xid, w["data"]).lamports
    r = _run(w, [(new_s, new_pk)],
             [w["buf"], w["data"], up.UPGRADEABLE_LOADER_ID],
             4, [2, 3, 1], up.ix_close())
    assert r.ok, r.err
    assert rt.accdb.load(b.xid, w["data"]).lamports > before
    closed = rt.accdb.load(b.xid, w["buf"])
    assert closed is None or closed.lamports == 0  # reaped at 0 lamports


def test_hijack_attempts_rejected(world):
    """The review-identified attack shapes must all fail: buffer hijack
    without the account's signature, deploy over live programdata,
    close-to-self, unauthorized extend."""
    w = world
    auth_s, auth_pk = w["auth"]
    _deploy(w, _mini_elf(PROG_V1))

    # 1. InitializeBuffer on a third-party account WITHOUT its signature
    #    (victim = the data account): [faucet0, auth1, data2, LOADER3]
    r = _run(w, [(auth_s, auth_pk)],
             [w["data"], up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_initialize_buffer())
    assert not r.ok and "signature" in r.err

    # 2. deploy over the LIVE programdata from a fresh attacker buffer
    r = _run(w, [(auth_s, auth_pk), w["buf2_kp"]],
             [up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_initialize_buffer())
    assert r.ok, r.err
    r = _run(w, [(auth_s, auth_pk)],
             [w["buf2"], up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_write(0, _mini_elf(PROG_V2)))
    assert r.ok, r.err
    # [faucet0, auth1, pdata2, prog3(fresh? use data acct), buf2_4, L5]
    fresh_prog = w["data"]
    r = _run(w, [(auth_s, auth_pk)],
             [w["pdata"], fresh_prog, w["buf2"], up.UPGRADEABLE_LOADER_ID],
             5, [0, 2, 3, 4, 1],
             up.ix_deploy_with_max_data_len(4096))
    assert not r.ok and "already in use" in r.err

    # 3. close programdata into itself must be rejected
    r = _run(w, [(auth_s, auth_pk)],
             [w["pdata"], up.UPGRADEABLE_LOADER_ID],
             3, [2, 2, 1], up.ix_close())
    assert not r.ok and "itself" in r.err

    # 4. extend without the upgrade authority's signature
    mallory_s, mallory_pk = _keypair(11)
    r = _run(w, [(mallory_s, mallory_pk)],
             [w["pdata"], w["prog"], up.UPGRADEABLE_LOADER_ID],
             4, [2, 3, 1], up.ix_extend_program(64))
    assert not r.ok and ("authority" in r.err or "signature" in r.err)
