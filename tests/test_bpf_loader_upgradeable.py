"""Upgradeable BPF loader (v3) lifecycle: buffer -> deploy -> invoke ->
upgrade -> authority/close (ref fd_bpf_loader_v3_program.c behaviors)."""

import struct

import pytest

from firedancer_tpu.ballet import txn as txn_lib
from firedancer_tpu.ballet.sbpf import asm
from firedancer_tpu.flamenco import bpf_loader_upgradeable as up
from firedancer_tpu.flamenco import genesis as gen_mod
from firedancer_tpu.flamenco.runtime import Runtime
from firedancer_tpu.flamenco.types import Account
from firedancer_tpu.ops import ed25519 as ed
from tests.test_sbpf_vm import _mini_elf


def _keypair(i):
    seed = i.to_bytes(32, "little")
    return seed, ed.keypair_from_seed(seed)[0]


def _signed(signers, msg):
    return txn_lib.assemble([ed.sign(s, msg) for s, _ in signers], msg)


PROG_V1 = asm("""
    mov r6, r1
    ldxdw r2, [r6+112]
    stxdw [r6+90], r2
    mov r0, 0
    exit""")

# v2 stores instr data + 1 (observable difference after upgrade)
PROG_V2 = asm("""
    mov r6, r1
    ldxdw r2, [r6+112]
    add r2, 1
    stxdw [r6+90], r2
    mov r0, 0
    exit""")


@pytest.fixture
def world():
    faucet_seed, faucet_pk = _keypair(1)
    auth_seed, auth_pk = _keypair(2)
    buf_seed, buf_pk = _keypair(3)
    buf2_seed, buf2_pk = _keypair(7)
    prog_pk = _keypair(5)[1]
    pdata_pk = up.programdata_address(prog_pk)  # deploy enforces the PDA
    data_pk = _keypair(6)[1]
    g = gen_mod.create(faucet_pk, creation_time=1)
    elf_cap = len(_mini_elf(PROG_V1)) + 128
    g.accounts[buf_pk] = Account(
        lamports=1_000_000, data=bytes(up.BUFFER_META_SZ + elf_cap))
    g.accounts[buf2_pk] = Account(
        lamports=1_000_000, data=bytes(up.BUFFER_META_SZ + elf_cap))
    g.accounts[pdata_pk] = Account(lamports=1_000_000)
    # the program account is created loader-owned (system create_account
    # with owner = loader needs prog's signature; modeled at genesis here)
    g.accounts[prog_pk] = Account(lamports=1_000_000, data=bytes(36),
                                  owner=up.UPGRADEABLE_LOADER_ID)
    g.accounts[data_pk] = Account(lamports=1_000_000, data=bytes(8),
                                  owner=prog_pk)
    rt = Runtime(g)
    b = rt.new_bank(1)
    return dict(rt=rt, b=b, faucet=(faucet_seed, faucet_pk),
                auth=(auth_seed, auth_pk), buf=buf_pk, buf2=buf2_pk,
                buf_kp=(buf_seed, buf_pk), buf2_kp=(buf2_seed, buf2_pk),
                pdata=pdata_pk, prog=prog_pk, data=data_pk)


def _run(w, signers, extra, prog_index, ix_accounts, data, n_ro=1):
    """One instruction; account list = [faucet] + signers + extra;
    prog_index / ix_accounts are explicit indices into that list."""
    rt, b = w["rt"], w["b"]
    fs, fpk = w["faucet"]
    msg = txn_lib.build_unsigned(
        [fpk] + [pk for _, pk in signers], rt.root_hash,
        [(prog_index, bytes(ix_accounts), data)],
        extra_accounts=extra, readonly_unsigned_cnt=n_ro)
    return b.execute_txn(_signed([(fs, fpk)] + signers, msg))


def _deploy(w, elf):
    auth_s, auth_pk = w["auth"]
    # account list: [faucet0, auth1, buf2, LOADER3]
    r = _run(w, [(auth_s, auth_pk), w["buf_kp"]],
             [up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_initialize_buffer())
    assert r.ok, r.err
    half = len(elf) // 2
    for off, chunk in ((0, elf[:half]), (half, elf[half:])):
        r = _run(w, [(auth_s, auth_pk)],
                 [w["buf"], up.UPGRADEABLE_LOADER_ID],
                 3, [2, 1], up.ix_write(off, chunk))
        assert r.ok, r.err
    # [faucet0, auth1, pdata2, prog3, buf4, LOADER5];
    # ix accounts: payer, programdata, program, buffer, authority
    r = _run(w, [(auth_s, auth_pk)],
             [w["pdata"], w["prog"], w["buf"], up.UPGRADEABLE_LOADER_ID],
             5, [0, 2, 3, 4, 1],
             up.ix_deploy_with_max_data_len(len(elf) + 256))
    assert r.ok, r.err


def test_buffer_deploy_invoke_upgrade(world):
    w = world
    rt, b = w["rt"], w["b"]
    auth_s, auth_pk = w["auth"]
    _deploy(w, _mini_elf(PROG_V1))

    pa = rt.accdb.load(b.xid, w["prog"])
    assert pa.executable and pa.owner == up.UPGRADEABLE_LOADER_ID
    st, s = up._state_of(pa.data)
    assert st == up.PROGRAM and bytes(s["programdata_address"]) == w["pdata"]
    pd = rt.accdb.load(b.xid, w["pdata"])
    std, sd = up._state_of(pd.data)
    assert std == up.PROGRAMDATA
    assert bytes(sd["upgrade_authority"]) == auth_pk

    # invoke: programdata must ride along for resolution
    # [faucet0, data1, prog2, pdata3]
    magic = struct.pack("<Q", 0xABCD1234)
    r = _run(w, [], [w["data"], w["prog"], w["pdata"]], 2, [1], magic,
             n_ro=2)
    assert r.ok, r.err
    assert rt.accdb.load(b.xid, w["data"]).data == magic

    # upgrade to v2 via a FRESH buffer (deploy drains the first one,
    # matching upstream's buffer close-on-deploy)
    elf2 = _mini_elf(PROG_V2)
    r = _run(w, [(auth_s, auth_pk), w["buf2_kp"]],
             [up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_initialize_buffer())
    assert r.ok, r.err
    r = _run(w, [(auth_s, auth_pk)],
             [w["buf2"], up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_write(0, elf2))
    assert r.ok, r.err
    # [faucet0, auth1, pdata2, prog3, buf2_4, data5, LOADER6];
    # ix: programdata, program, buffer, spill, authority
    r = _run(w, [(auth_s, auth_pk)],
             [w["pdata"], w["prog"], w["buf2"], w["data"],
              up.UPGRADEABLE_LOADER_ID],
             6, [2, 3, 4, 5, 1], up.ix_upgrade())
    assert r.ok, r.err

    r = _run(w, [], [w["data"], w["prog"], w["pdata"]], 2, [1], magic,
             n_ro=2)
    assert r.ok, r.err
    want = struct.pack("<Q", 0xABCD1235)  # v2 adds 1
    assert rt.accdb.load(b.xid, w["data"]).data == want


def test_write_requires_authority_signature(world):
    w = world
    mallory_s, mallory_pk = _keypair(9)
    auth_s, auth_pk = w["auth"]
    r = _run(w, [(auth_s, auth_pk), w["buf_kp"]],
             [up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_initialize_buffer())
    assert r.ok, r.err
    # mallory signs instead of the recorded authority
    # [faucet0, mallory1, buf2, LOADER3]
    r = _run(w, [(mallory_s, mallory_pk)],
             [w["buf"], up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_write(0, b"\x7fELF"))
    assert not r.ok and "authority" in r.err


def test_set_authority_and_close(world):
    w = world
    auth_s, auth_pk = w["auth"]
    new_s, new_pk = _keypair(10)
    r = _run(w, [(auth_s, auth_pk), w["buf_kp"]],
             [up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_initialize_buffer())
    assert r.ok, r.err
    # [faucet0, auth1, new2, buf3, LOADER4]; ix: buffer, cur auth, new
    r = _run(w, [(auth_s, auth_pk), (new_s, new_pk)],
             [w["buf"], up.UPGRADEABLE_LOADER_ID],
             4, [3, 1, 2], up.ix_set_authority())
    assert r.ok, r.err
    # old authority can no longer write: [faucet0, auth1, buf2, L3]
    r = _run(w, [(auth_s, auth_pk)],
             [w["buf"], up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_write(0, b"x"))
    assert not r.ok
    # close: [faucet0, new1, buf2, data3, L4]; ix: buffer, recipient, auth
    rt, b = w["rt"], w["b"]
    before = rt.accdb.load(b.xid, w["data"]).lamports
    r = _run(w, [(new_s, new_pk)],
             [w["buf"], w["data"], up.UPGRADEABLE_LOADER_ID],
             4, [2, 3, 1], up.ix_close())
    assert r.ok, r.err
    assert rt.accdb.load(b.xid, w["data"]).lamports > before
    closed = rt.accdb.load(b.xid, w["buf"])
    assert closed is None or closed.lamports == 0  # reaped at 0 lamports


def test_hijack_attempts_rejected(world):
    """The review-identified attack shapes must all fail: buffer hijack
    without the account's signature, deploy over live programdata,
    close-to-self, unauthorized extend."""
    w = world
    auth_s, auth_pk = w["auth"]
    _deploy(w, _mini_elf(PROG_V1))

    # 1. InitializeBuffer on a third-party account WITHOUT its signature
    #    (victim = the data account): [faucet0, auth1, data2, LOADER3]
    r = _run(w, [(auth_s, auth_pk)],
             [w["data"], up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_initialize_buffer())
    assert not r.ok and "signature" in r.err

    # 2. deploy over the LIVE programdata from a fresh attacker buffer
    r = _run(w, [(auth_s, auth_pk), w["buf2_kp"]],
             [up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_initialize_buffer())
    assert r.ok, r.err
    r = _run(w, [(auth_s, auth_pk)],
             [w["buf2"], up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_write(0, _mini_elf(PROG_V2)))
    assert r.ok, r.err
    # [faucet0, auth1, pdata2, prog3(fresh? use data acct), buf2_4, L5]
    fresh_prog = w["data"]
    r = _run(w, [(auth_s, auth_pk)],
             [w["pdata"], fresh_prog, w["buf2"], up.UPGRADEABLE_LOADER_ID],
             5, [0, 2, 3, 4, 1],
             up.ix_deploy_with_max_data_len(4096))
    # rejected twice over: victim program isn't loader-owned, and the
    # live programdata is not fresh_prog's derived address
    assert not r.ok and ("owned" in r.err or "derived" in r.err
                         or "already in use" in r.err)

    # 3. close programdata into itself must be rejected
    r = _run(w, [(auth_s, auth_pk)],
             [w["pdata"], up.UPGRADEABLE_LOADER_ID],
             3, [2, 2, 1], up.ix_close())
    assert not r.ok and "itself" in r.err

    # 4. extend without the upgrade authority's signature
    mallory_s, mallory_pk = _keypair(11)
    r = _run(w, [(mallory_s, mallory_pk)],
             [w["pdata"], w["prog"], up.UPGRADEABLE_LOADER_ID],
             4, [2, 3, 1], up.ix_extend_program(64))
    assert not r.ok and ("authority" in r.err or "signature" in r.err)


def test_deploy_requires_loader_owned_program_and_derived_pdata(world):
    """Seizure shapes the advisor found: deploy must reject (a) a program
    account not already owned by the loader (a merely-writable victim),
    (b) a programdata account that is not the program's derived PDA, and
    (c) recycling a CLOSED programdata under a live Program (Close now
    returns the account to the system program and the PDA binding makes
    it unreachable from any other program id)."""
    import firedancer_tpu.flamenco.bpf_loader_upgradeable as up_mod
    w = world
    rt, b = w["rt"], w["b"]
    auth_s, auth_pk = w["auth"]

    # stage a valid buffer
    r = _run(w, [(auth_s, auth_pk), w["buf_kp"]],
             [up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_initialize_buffer())
    assert r.ok, r.err
    r = _run(w, [(auth_s, auth_pk)],
             [w["buf"], up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_write(0, _mini_elf(PROG_V1)))
    assert r.ok, r.err

    # (a) victim program account: system-owned, writable, but NOT loader-
    # owned -> seizure rejected even with a matching derived programdata
    victim = w["data"]
    victim_pda = up.programdata_address(victim)
    rt.genesis.accounts  # (fixture accounts live in accdb already)
    # fund the would-be pda via faucet? deploy only writes it, needs it to
    # exist: reuse the prepared pdata slot by deriving for the victim is
    # impossible — the account doesn't exist, so deploy fails on lookup
    # or on the ownership guard; either way the victim is never seized
    r = _run(w, [(auth_s, auth_pk)],
             [victim_pda, victim, w["buf"], up.UPGRADEABLE_LOADER_ID],
             5, [0, 2, 3, 4, 1], up.ix_deploy_with_max_data_len(4096))
    assert not r.ok
    assert rt.accdb.load(b.xid, victim).owner != up.UPGRADEABLE_LOADER_ID

    # (b) correct loader-owned program but WRONG (non-derived) programdata
    r = _run(w, [(auth_s, auth_pk)],
             [w["buf2"], w["prog"], w["buf"], up.UPGRADEABLE_LOADER_ID],
             5, [0, 2, 3, 4, 1], up.ix_deploy_with_max_data_len(4096))
    assert not r.ok and "derived" in r.err

    # (c) deploy properly, close programdata, then try to redeploy into
    # it from a different program id: PDA binding must reject
    r = _run(w, [(auth_s, auth_pk)],
             [w["pdata"], w["prog"], w["buf"], up.UPGRADEABLE_LOADER_ID],
             5, [0, 2, 3, 4, 1],
             up.ix_deploy_with_max_data_len(len(_mini_elf(PROG_V1)) + 256))
    assert r.ok, r.err
    # close the live programdata (authority allows it upstream too)
    r = _run(w, [(auth_s, auth_pk)],
             [w["pdata"], w["data"], up.UPGRADEABLE_LOADER_ID],
             4, [2, 3, 1], up.ix_close())
    assert r.ok, r.err
    closed = rt.accdb.load(b.xid, w["pdata"])
    if closed is not None:  # not reaped: ownership must have been reset
        assert closed.owner != up.UPGRADEABLE_LOADER_ID
    # attacker's own loader-owned program account tries to claim the
    # closed programdata
    atk_pk = _keypair(12)[1]
    from firedancer_tpu.flamenco.types import Account as _Acct
    rt.accdb.store(b.xid, atk_pk, _Acct(
        lamports=1_000_000, data=bytes(36),
        owner=up.UPGRADEABLE_LOADER_ID))
    r = _run(w, [(auth_s, auth_pk), w["buf2_kp"]],
             [up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_initialize_buffer())
    assert r.ok, r.err
    r = _run(w, [(auth_s, auth_pk)],
             [w["buf2"], up.UPGRADEABLE_LOADER_ID],
             3, [2, 1], up.ix_write(0, _mini_elf(PROG_V2)))
    assert r.ok, r.err
    r = _run(w, [(auth_s, auth_pk)],
             [w["pdata"], atk_pk, w["buf2"], up.UPGRADEABLE_LOADER_ID],
             5, [0, 2, 3, 4, 1], up.ix_deploy_with_max_data_len(4096))
    # closed-at-0-lamports programdata is reaped (missing) OR, if it
    # survived, the PDA binding rejects the foreign program id
    assert not r.ok and ("derived" in r.err or "missing" in r.err)
