"""Smoke-level strict-verify coverage for the per-commit gate (VERDICT r4
weak #4: most crypto coverage hid behind `slow`, so the fast tier barely
exercised the hot path).  Small batch, always-primed shape (16, 256);
runs in seconds against a primed cache, defers to the slow tier cold
(conftest PRIMED_ONLY_MODULES)."""

import numpy as np

from firedancer_tpu.models.verifier import SigVerifier, VerifierConfig


def test_strict_verify_smoke_per_lane_bits():
    v = SigVerifier(VerifierConfig(batch=16, msg_maxlen=256))
    msgs, lens, sigs, pubs = v.example_args()
    sigs = np.asarray(sigs).copy()
    bad = (0, 7, 15)
    for i in bad:
        sigs[i, 40] ^= 0x42
    ok = np.asarray(v(msgs, lens, sigs, pubs))
    assert ok.shape == (16,)
    for i in range(16):
        assert bool(ok[i]) == (i not in bad), i


def test_packed_dispatch_smoke():
    v = SigVerifier(VerifierConfig(batch=16, msg_maxlen=256))
    msgs, lens, sigs, pubs = v.example_args()
    ok = np.asarray(v.packed_dispatch(msgs, lens, sigs, pubs))
    assert ok.all()
