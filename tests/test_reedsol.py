"""Reed-Solomon FEC tests: field axioms, device/host agreement, round trips,
erasure recovery, and corruption detection (the reference's
SUCCESS/ERR_PARTIAL/ERR_CORRUPT contract, fd_reedsol.h:41-43)."""

import numpy as np
import pytest

from firedancer_tpu.ballet import reedsol as rs


def test_gf_axioms():
    rng = np.random.default_rng(0)
    for _ in range(200):
        a, b, c = (int(x) for x in rng.integers(1, 256, 3))
        assert rs.gf_mul(a, b) == rs.gf_mul(b, a)
        assert rs.gf_mul(a, rs.gf_mul(b, c)) == rs.gf_mul(rs.gf_mul(a, b), c)
        assert rs.gf_mul(a, rs.gf_inv(a)) == 1
        # distributes over xor (field addition)
        assert rs.gf_mul(a, b ^ c) == rs.gf_mul(a, b) ^ rs.gf_mul(a, c)
    assert rs.gf_mul(0, 5) == 0 and rs.gf_mul(7, 0) == 0
    assert rs.gf_pow(2, 255) == 1  # generator order


def test_generator_is_systematic():
    A = rs.generator_matrix(5, 9)
    assert np.array_equal(A[:5], np.eye(5, dtype=np.uint8))
    # k=1: constant polynomial -> every parity byte equals the data byte
    A1 = rs.generator_matrix(1, 4)
    assert np.array_equal(A1, np.ones((4, 1), dtype=np.uint8))


def test_device_matches_host_encode():
    rng = np.random.default_rng(1)
    for k, p, sz in [(1, 3, 64), (4, 2, 100), (32, 32, 1003), (67, 67, 64)]:
        data = rng.integers(0, 256, size=(k, sz), dtype=np.uint8)
        assert np.array_equal(
            rs.encode(data, p, device=True), rs.encode(data, p, device=False)
        ), (k, p)


def test_roundtrip_recover_erasures():
    rng = np.random.default_rng(2)
    k, p, sz = 8, 6, 200
    data = rng.integers(0, 256, size=(k, sz), dtype=np.uint8)
    parity = rs.encode(data, p)
    full = list(data) + list(parity)

    for trial in range(10):
        erased = rng.choice(k + p, size=p, replace=False)
        shreds = [None if i in erased else full[i] for i in range(k + p)]
        rec = rs.recover(shreds, k, sz)
        for i in range(k + p):
            assert np.array_equal(rec[i], full[i]), (trial, i)


def test_recover_parity_only():
    # all data shreds lost; recover purely from parity
    rng = np.random.default_rng(3)
    k, p, sz = 4, 5, 64
    data = rng.integers(0, 256, size=(k, sz), dtype=np.uint8)
    full = list(data) + list(rs.encode(data, p))
    shreds = [None] * k + full[k:]
    rec = rs.recover(shreds, k, sz)
    assert all(np.array_equal(rec[i], full[i]) for i in range(k))


def test_recover_partial_raises():
    k, p, sz = 5, 2, 32
    data = np.zeros((k, sz), dtype=np.uint8)
    full = list(data) + list(rs.encode(data, p))
    shreds = [full[0], full[1], None, None, None, full[5], None]  # only 3 < k
    with pytest.raises(ValueError, match="unrecoverable"):
        rs.recover(shreds, k, sz)


def test_recover_detects_corruption():
    rng = np.random.default_rng(4)
    k, p, sz = 4, 3, 50
    data = rng.integers(0, 256, size=(k, sz), dtype=np.uint8)
    full = list(data) + list(rs.encode(data, p))
    bad = [s.copy() for s in full]
    bad[5][10] ^= 0xFF  # corrupt a parity shred that recovery won't use
    shreds = [bad[0], bad[1], bad[2], bad[3], None, bad[5], bad[6]]
    with pytest.raises(ValueError, match="corrupt"):
        rs.recover(shreds, k, sz)


def test_limits_enforced():
    with pytest.raises(ValueError):
        rs.encode(np.zeros((68, 8), dtype=np.uint8), 1)
    with pytest.raises(ValueError):
        rs.encode(np.zeros((2, 8), dtype=np.uint8), 68)
