"""Reed-Solomon FEC tests: field axioms, device/host agreement, round trips,
erasure recovery, and corruption detection (the reference's
SUCCESS/ERR_PARTIAL/ERR_CORRUPT contract, fd_reedsol.h:41-43)."""

import numpy as np
import pytest

from firedancer_tpu.ballet import reedsol as rs


def test_gf_axioms():
    rng = np.random.default_rng(0)
    for _ in range(200):
        a, b, c = (int(x) for x in rng.integers(1, 256, 3))
        assert rs.gf_mul(a, b) == rs.gf_mul(b, a)
        assert rs.gf_mul(a, rs.gf_mul(b, c)) == rs.gf_mul(rs.gf_mul(a, b), c)
        assert rs.gf_mul(a, rs.gf_inv(a)) == 1
        # distributes over xor (field addition)
        assert rs.gf_mul(a, b ^ c) == rs.gf_mul(a, b) ^ rs.gf_mul(a, c)
    assert rs.gf_mul(0, 5) == 0 and rs.gf_mul(7, 0) == 0
    assert rs.gf_pow(2, 255) == 1  # generator order


def test_generator_is_systematic():
    A = rs.generator_matrix(5, 9)
    assert np.array_equal(A[:5], np.eye(5, dtype=np.uint8))
    # k=1: constant polynomial -> every parity byte equals the data byte
    A1 = rs.generator_matrix(1, 4)
    assert np.array_equal(A1, np.ones((4, 1), dtype=np.uint8))


def test_device_matches_host_encode():
    rng = np.random.default_rng(1)
    for k, p, sz in [(1, 3, 64), (4, 2, 100), (32, 32, 1003), (67, 67, 64)]:
        data = rng.integers(0, 256, size=(k, sz), dtype=np.uint8)
        assert np.array_equal(
            rs.encode(data, p, device=True), rs.encode(data, p, device=False)
        ), (k, p)


def test_roundtrip_recover_erasures():
    rng = np.random.default_rng(2)
    k, p, sz = 8, 6, 200
    data = rng.integers(0, 256, size=(k, sz), dtype=np.uint8)
    parity = rs.encode(data, p)
    full = list(data) + list(parity)

    for trial in range(10):
        erased = rng.choice(k + p, size=p, replace=False)
        shreds = [None if i in erased else full[i] for i in range(k + p)]
        rec = rs.recover(shreds, k, sz)
        for i in range(k + p):
            assert np.array_equal(rec[i], full[i]), (trial, i)


def test_recover_parity_only():
    # all data shreds lost; recover purely from parity
    rng = np.random.default_rng(3)
    k, p, sz = 4, 5, 64
    data = rng.integers(0, 256, size=(k, sz), dtype=np.uint8)
    full = list(data) + list(rs.encode(data, p))
    shreds = [None] * k + full[k:]
    rec = rs.recover(shreds, k, sz)
    assert all(np.array_equal(rec[i], full[i]) for i in range(k))


def test_recover_partial_raises():
    k, p, sz = 5, 2, 32
    data = np.zeros((k, sz), dtype=np.uint8)
    full = list(data) + list(rs.encode(data, p))
    shreds = [full[0], full[1], None, None, None, full[5], None]  # only 3 < k
    with pytest.raises(ValueError, match="unrecoverable"):
        rs.recover(shreds, k, sz)


def test_recover_detects_corruption():
    rng = np.random.default_rng(4)
    k, p, sz = 4, 3, 50
    data = rng.integers(0, 256, size=(k, sz), dtype=np.uint8)
    full = list(data) + list(rs.encode(data, p))
    bad = [s.copy() for s in full]
    bad[5][10] ^= 0xFF  # corrupt a parity shred that recovery won't use
    shreds = [bad[0], bad[1], bad[2], bad[3], None, bad[5], bad[6]]
    with pytest.raises(ValueError, match="corrupt"):
        rs.recover(shreds, k, sz)


def test_limits_enforced():
    with pytest.raises(ValueError):
        rs.encode(np.zeros((68, 8), dtype=np.uint8), 1)
    with pytest.raises(ValueError):
        rs.encode(np.zeros((2, 8), dtype=np.uint8), 68)


# ---------------------------------------------------------------------------
# round 13: batched multi-set recovery (recover_batch) + the cached
# reconstruction-matrix machinery it rides on


def _mk_set(rng, k, p, sz):
    data = rng.integers(0, 256, size=(k, sz), dtype=np.uint8)
    return list(data) + list(rs.encode(data, p, device=False))


def test_recover_batch_bit_identity_equal_patterns():
    # every set shares one erasure pattern: the stacked device path must
    # be BIT-IDENTICAL to the per-set host golden model
    rng = np.random.default_rng(10)
    k, p, sz = 8, 8, 96
    sets = []
    for _ in range(6):
        full = _mk_set(rng, k, p, sz)
        shreds = list(full)
        shreds[1] = shreds[6] = shreds[k + 2] = None
        sets.append((shreds, k, sz))
    golden = rs.recover_batch(sets, device=False)
    got = rs.recover_batch(sets)
    for g, w in zip(golden, got):
        assert not isinstance(w, ValueError)
        assert all(np.array_equal(a, b) for a, b in zip(g, w))


def test_recover_batch_bit_identity_ragged_patterns():
    # per-set erasure counts AND positions differ (including zero
    # erasures): padding/stacking must stay self-consistent
    rng = np.random.default_rng(11)
    k, p, sz = 8, 6, 64
    n = k + p
    sets = []
    for i in range(7):
        full = _mk_set(rng, k, p, sz)
        shreds = list(full)
        for e in range(i % (p - 1)):
            shreds[(3 * e + i) % n] = None
        sets.append((shreds, k, sz))
    golden = rs.recover_batch(sets, device=False)
    got = rs.recover_batch(sets)
    for i, (g, w) in enumerate(zip(golden, got)):
        assert not isinstance(w, ValueError), (i, w)
        assert all(np.array_equal(a, b) for a, b in zip(g, w)), i


def test_recover_batch_mixed_geometry():
    # sets with different (k, n, sz) pad to the batch maxima and still
    # come back bit-identical, trimmed to their own geometry
    rng = np.random.default_rng(12)
    sets = []
    for k, p, sz in [(4, 3, 32), (8, 8, 96), (2, 5, 64)]:
        full = _mk_set(rng, k, p, sz)
        shreds = list(full)
        shreds[0] = None
        sets.append((shreds, k, sz))
    golden = rs.recover_batch(sets, device=False)
    got = rs.recover_batch(sets)
    for i, (g, w) in enumerate(zip(golden, got)):
        assert not isinstance(w, ValueError), (i, w)
        assert len(w) == len(g)
        assert all(np.array_equal(a, b) for a, b in zip(g, w)), i


def test_recover_all_data_fast_path_skips_inversion(monkeypatch):
    # no data erasures -> the reconstruction is the systematic generator
    # itself and _mat_inv must never run
    rs.recover_cache_clear()
    rng = np.random.default_rng(13)
    k, p, sz = 6, 4, 48
    full = _mk_set(rng, k, p, sz)            # caches the generator first
    monkeypatch.setattr(rs, "_mat_inv", lambda M: (_ for _ in ()).throw(
        AssertionError("_mat_inv ran on the all-data fast path")))
    shreds = list(full)
    shreds[k + 1] = None                     # parity-only erasure
    out = rs.recover_batch([(shreds, k, sz)])[0]
    assert not isinstance(out, ValueError)
    assert all(np.array_equal(a, b) for a, b in zip(out, full))
    R = rs._recover_gfmat(k, k + p, tuple(range(k)))
    assert np.array_equal(R, rs.generator_matrix(k, k + p))


def test_recover_batch_per_set_failures_isolated():
    # one unrecoverable set and one corrupt set must come back as
    # per-set ValueErrors; their neighbors recover untouched
    rng = np.random.default_rng(14)
    k, p, sz = 5, 4, 40
    n = k + p
    good = _mk_set(rng, k, p, sz)
    gsh = list(good)
    gsh[2] = None

    starved = [None] * (n - 2) + _mk_set(rng, k, p, sz)[n - 2:]

    corrupt_full = _mk_set(rng, k, p, sz)
    csh = [s.copy() for s in corrupt_full]
    csh[1] = None
    csh[n - 1][7] ^= 0x80                    # surviving but inconsistent

    out = rs.recover_batch([(gsh, k, sz), (starved, k, sz), (csh, k, sz)])
    assert all(np.array_equal(a, b) for a, b in zip(out[0], good))
    assert isinstance(out[1], ValueError)
    assert "unrecoverable" in str(out[1])
    assert isinstance(out[2], ValueError)
    assert "corrupt" in str(out[2])


def test_recover_batch_rejects_over_limit():
    sz = 8
    shreds = [np.zeros(sz, dtype=np.uint8)] * 70
    out = rs.recover_batch([(shreds, 68, sz)])
    assert isinstance(out[0], ValueError)
    assert "protocol limits" in str(out[0])


def test_recover_matrix_cache_accounting():
    rs.recover_cache_clear()
    rng = np.random.default_rng(15)
    k, p, sz = 4, 4, 32
    full = _mk_set(rng, k, p, sz)
    shreds = list(full)
    shreds[1] = None
    sets = [(list(shreds), k, sz)] * 5       # one pattern, five sets
    rs.recover_batch(sets, device=False)
    ci = rs.recover_cache_info()
    assert ci.misses == 1 and ci.hits == 4, ci
    rs.recover_batch(sets, device=False)     # steady state: all hits
    ci = rs.recover_cache_info()
    assert ci.misses == 1 and ci.hits == 9, ci
    shreds[2] = None                         # new pattern -> one new miss
    rs.recover_batch([(shreds, k, sz)], device=False)
    ci = rs.recover_cache_info()
    assert ci.misses == 2, ci
