"""Closed-loop topology autotuner: attribution verdicts -> bounded,
post-mortemable actuation (ROADMAP item 4 — the reference solves this
statically with hand-tuned topologies; a JAX serving stack can close the
loop adaptively).

Two halves live here:

**Knob pods** — the actuation transport.  Every tile gets a small shm
region next to its metrics block (allocated by the same deterministic
layout replay in disco/topo.py): one u64 generation counter + one f64
slot per live-tunable knob of that tile kind (KNOBS below).  The
supervisor writes values then bumps the generation; the tile's mux
housekeeping compares the generation once per interval (~20 ms) and, on
change, hands the non-zero slots to the tile's `apply_knobs(ctx, vals)`
callback.  Unarmed cost is one integer compare per housekeeping — the
same zero-overhead invariant as faultinject.  Pods outlive tile
processes, so a respawned tile re-applies the current knob set at its
first housekeeping (its mux starts with generation-seen = 0).

**Autotuner** — the supervisor-resident policy loop.  Each control
period it senses the bottleneck verdict (disco/attrib.py), the SLO burn
rate over the period's trace window (disco/slo.py), and the shed gauges
(disco/metrics.py), then fires at most ONE rule.  Safety is the design
center, in this order:

  * per-knob [lo, hi] clamps and bounded multiplicative steps — no rule
    can move a knob more than its step fraction per period or past its
    clamp, ever;
  * hysteresis (act above `burn_hi`, relax below `burn_lo`) + per-rule
    cooldowns so the loop cannot flap;
  * a monotone do-no-harm guard: if the burn rate worsens for two
    consecutive periods after an action, the action is reverted and the
    rule quarantined — a wrong (or deliberately poisoned, see the
    `poison` config hook) rule cannot keep hurting the topology;
  * every decision (inputs, rule, old -> new, outcome) appends to an
    in-memory ring mirrored to <flight_dir>/autotune.jsonl; the flight
    recorder bundles it and `fdtpuctl autotune` / `postmortem` render
    it, so a bad actuation is always explainable after the fact.

The loop is wired into TopoRun.supervise() (disco/run.py) and armed by
the `[autotune]` config section (enabled default-off; with the flag off
nothing constructs an Autotuner and no pod is ever written, so behavior
is bit-identical to the pre-autotune topology).
"""

import json
import os
import time

import numpy as np

from ..utils import log

# -- knob schema ------------------------------------------------------------
# Per tile kind, the ordered live-tunable knobs (the order IS the pod
# slot layout — append only).  Every knob here is read each call on its
# tile's hot path, so a pod write takes effect within one housekeeping
# interval without a respawn:
#   verify   deadline_us / lat_max_inflight (pipeline lat lane),
#            max_inflight (dispatch-ahead window), flush_age_ns
#            (partial-batch age flush)
#   source   burst_splits (packed-frag fan-out per loop)
#   net      pps_per_source / pps_burst (per-source token bucket)
#   quic_server  conn_txn_rate / conn_txn_burst (per-conn token bucket,
#            read live by QuicEndpoint._txn_admit via ep.cfg)
KNOBS: dict[str, tuple[str, ...]] = {
    "verify": ("deadline_us", "lat_max_inflight", "max_inflight",
               "flush_age_ns"),
    "source": ("burst_splits",),
    "net": ("pps_per_source", "pps_burst"),
    "quic_server": ("conn_txn_rate", "conn_txn_burst"),
}

# knob -> (kind, lo, hi, step_frac, is_int, default).  step_frac bounds
# ONE period's move: new = old * (1 +/- step_frac) (int knobs move at
# least 1).  Defaults mirror the boot-time config defaults so the tuner
# can seed current values for knobs a tile cfg leaves unset.
KNOB_SPECS: dict[str, tuple[str, float, float, float, bool, float]] = {
    "deadline_us":      ("verify",      200.0,    50_000.0, 0.25, True, 2000),
    "lat_max_inflight": ("verify",        1.0,        16.0, 0.50, True, 2),
    "max_inflight":     ("verify",        2.0,        64.0, 0.50, True, 8),
    "flush_age_ns":     ("verify",   200_000.0, 2.0e9, 0.50, True, 2_000_000),
    "burst_splits":     ("source",        1.0,        16.0, 0.50, True, 2),
    "pps_per_source":   ("net",          64.0, 1_000_000.0, 0.25, False, 0),
    "pps_burst":        ("net",          64.0, 2_000_000.0, 0.25, False, 0),
    "conn_txn_rate":    ("quic_server",   1.0, 1_000_000.0, 0.25, False, 0),
    "conn_txn_burst":   ("quic_server",   8.0, 1_000_000.0, 0.25, True, 32),
}

POD_SLOTS = 8       # f64 value slots per pod (max knobs per kind, room)
RING_MAX = 256      # in-memory decision ring bound
LOG_NAME = "autotune.jsonl"


def pod_footprint() -> int:
    """Uniform per-tile pod size (gen u64 + POD_SLOTS f64), padded so the
    deterministic layout replay never depends on tile kind."""
    return 128


class KnobPod:
    """One tile's knob mailbox in the workspace.  Writer = supervisor,
    reader = the tile's mux housekeeping; the u64 generation store is the
    publish barrier (aligned 8-byte stores are atomic on our platforms,
    and f64 is exact for every integer knob value we carry)."""

    def __init__(self, buf, off: int, kind: str):
        self._gen = np.frombuffer(buf, dtype=np.uint64, count=1, offset=off)
        self._vals = np.frombuffer(buf, dtype=np.float64, count=POD_SLOTS,
                                   offset=off + 8)
        self.names = KNOBS.get(kind, ())

    @property
    def gen(self) -> int:
        return int(self._gen[0])

    def write(self, name: str, value: float):
        """Stage one knob value (visible to the tile after commit())."""
        self._vals[self.names.index(name)] = float(value)

    def commit(self):
        self._gen[0] += np.uint64(1)

    def read_set(self) -> dict[str, float]:
        """The armed knobs: every slot a supervisor ever wrote (zero =
        never touched; no real knob value here is zero)."""
        return {n: float(self._vals[i]) for i, n in enumerate(self.names)
                if self._vals[i] != 0.0}


def _tile_initial(kind: str, cfg: dict, knob: str) -> float:
    """Boot-time value of `knob` for a tile, from its spec cfg (mirrors
    how tiles.py reads the same keys at init)."""
    _, lo, hi, _, _, dflt = KNOB_SPECS[knob]
    if kind == "verify" and knob in ("deadline_us", "lat_max_inflight"):
        latc = cfg.get("latency") or {}
        key = "max_inflight" if knob == "lat_max_inflight" else knob
        v = latc.get(key, dflt)
    else:
        v = cfg.get(knob, dflt)
    try:
        v = float(v)
    except (TypeError, ValueError):
        v = float(dflt)
    # a zero default marks an unarmed limiter (rate knobs): keep the 0 so
    # the rule set knows to leave it off rather than seeding the clamp lo
    return v if v > 0 else float(dflt)


class Autotuner:
    """The supervisor-resident policy loop.  Construct with a TopoRun (or
    run=None plus `tiles`/`sense_fn`/`apply_fn` for modeled harnesses —
    tools/chaos_smoke.py drives the policy against a synthetic plant the
    same way the latency smoke drives dispatch policy against a modeled
    verifier)."""

    def __init__(self, run, cfg: dict | None = None, *,
                 target_ms: float | None = None, tiles=None,
                 sense_fn=None, apply_fn=None, log_dir: str = ""):
        acfg = dict(cfg or {})
        self.run = run
        self.enabled = bool(int(acfg.get("enabled", 0) or 0))
        self.period_s = float(acfg.get("period_s", 2.0))
        self.burn_hi = float(acfg.get("burn_hi", 0.35))
        self.burn_lo = float(acfg.get("burn_lo", 0.10))
        self.cooldown_periods = int(acfg.get("cooldown_periods", 3))
        self.relax_after = int(acfg.get("relax_after", 10))
        self.quarantine_periods = int(acfg.get("quarantine_periods", 64))
        self.respawn_after = int(acfg.get("respawn_after", 0))  # 0 = never
        self.poison = str(acfg.get("poison", ""))
        self.target_ms = (target_ms if target_ms is not None
                          else float(getattr(run, "slo_target_ms", 2.0)))
        self.bounds = dict(KNOB_SPECS)
        for knob, b in (acfg.get("bounds") or {}).items():
            if knob not in self.bounds:
                raise ValueError(f"[autotune.bounds] unknown knob {knob!r}")
            kind, lo, hi, step, is_int, dflt = self.bounds[knob]
            lo, hi = float(b[0]), float(b[1])
            step = float(b[2]) if len(b) > 2 else step
            self.bounds[knob] = (kind, lo, hi, step, is_int, dflt)
        self._sense_fn = sense_fn
        self._apply_fn = apply_fn
        self.log_path = os.path.join(log_dir, LOG_NAME) if log_dir else ""

        if tiles is None and run is not None:
            tiles = [(t.name, t.kind, dict(t.cfg))
                     for t in run.jt.spec.tiles]
        self._tiles = [(n, k) for n, k, _ in (tiles or ())]
        # (tile, knob) -> live value; seeded from boot-time cfg so the
        # first step moves from where the topology actually is
        self.current: dict[tuple[str, str], float] = {}
        self.baseline: dict[tuple[str, str], float] = {}
        for name, kind, tcfg in (tiles or ()):
            for knob in KNOBS.get(kind, ()):
                v = _tile_initial(kind, tcfg, knob)
                self.current[(name, knob)] = v
                self.baseline[(name, knob)] = v

        self.period = 0
        self.decision_cnt = 0
        self.revert_cnt = 0
        self.clamp_cnt = 0
        self.converged_at: int | None = None
        self.decisions: list[dict] = []
        self._next_t = 0.0
        self._prev_sample = None
        self._win_ts = 0
        self._cooldown: dict[str, int] = {}   # rule -> period it frees up
        self._last: dict | None = None        # do-no-harm watch state
        self._ok_streak = 0      # periods with burn < burn_hi (convergence)
        self._calm_streak = 0    # periods with burn <= burn_lo (relax gate)
        self._burn_hi_streak = 0

    # -- sensing ----------------------------------------------------------
    def sense(self) -> dict:
        if self._sense_fn is not None:
            return self._sense_fn(self)
        from . import attrib
        from . import slo
        jt = self.run.jt
        sample = attrib.link_sample(jt)
        label, reason = "none", ""
        if self._prev_sample is not None:
            label, reason = attrib.bottleneck(self._prev_sample, sample)
        self._prev_sample = sample
        spans, kind_of = slo.collect(jt)
        if self._win_ts:  # grade THIS period's completions, not history
            spans = {t: r[r["ts"] > self._win_ts]
                     for t, r in spans.items()}
        self._win_ts = time.monotonic_ns()
        b = slo.burn(spans, kind_of, self.target_ms)
        shed = any(blk.has("shedding") and blk.get("shedding")
                   for blk in jt.metrics.values())
        return {"burn": b["rate"], "trend": b["trend"], "n": b["n"],
                "bottleneck": label, "reason": reason, "shedding": shed}

    # -- actuation --------------------------------------------------------
    def _tiles_of(self, kind: str) -> list[str]:
        return [n for n, k in self._tiles if k == kind]

    def _actuate(self, tile: str, knob: str, value: float):
        self.current[(tile, knob)] = value
        if self._apply_fn is not None:
            self._apply_fn(tile, knob, value)
            return
        pod = self.run.jt.knobs.get(tile)
        if pod is not None:
            pod.write(knob, value)
            pod.commit()

    def _record(self, rule: str, tile: str, knob: str, old, new,
                outcome: str, inputs: dict):
        d = {"t": round(time.time(), 3), "period": self.period,
             "rule": rule, "tile": tile, "knob": knob,
             "old": old, "new": new, "outcome": outcome,
             "burn": round(inputs.get("burn", 0.0), 4),
             "trend": inputs.get("trend", ""),
             "bottleneck": inputs.get("bottleneck", ""),
             "reason": inputs.get("reason", "")}
        self.decisions.append(d)
        del self.decisions[:-RING_MAX]
        self.decision_cnt += 1
        if self.log_path:
            try:
                with open(self.log_path, "a") as f:
                    f.write(json.dumps(d) + "\n")
            except OSError:  # a full disk must not take the loop down
                pass
        return d

    def _step_value(self, knob: str, old: float, direction: int):
        """One bounded move: old * (1 +/- step_frac), clamped.  Returns
        (new, clamped_flag)."""
        _, lo, hi, step, is_int, _ = self.bounds[knob]
        delta = abs(old) * step
        if is_int:
            delta = max(1.0, delta)
        raw = old + direction * delta
        new = min(max(raw, lo), hi)
        if is_int:
            new = float(int(round(new)))
        return new, (new != raw if not is_int
                     else abs(new - min(max(raw, lo), hi)) > 0.5 or raw != new)

    # -- the rule set -----------------------------------------------------
    # Each rule: (name, want(inputs) -> bool, kind, knob, direction).
    # Evaluated in order; the FIRST eligible (not cooling down, not
    # quarantined, has a target tile, step not already pinned at its
    # clamp) rule fires — one bounded action per period, never more.
    def _rules(self):
        return [
            # a consumer charging slow diags faster than anyone else:
            # deepen the verify dispatch-ahead window so the device lane
            # absorbs bursts instead of stalling the producer
            ("slow_consumer_depth",
             lambda i: "slow consumer" in i.get("reason", ""),
             "verify", "max_inflight", +1),
            # fan packed bursts wider when the slow consumer persists
            ("slow_consumer_splits",
             lambda i: "slow consumer" in i.get("reason", ""),
             "source", "burst_splits", +1),
            # SLO burn high: partial batches are aging out too slowly —
            # close them sooner (the coalesce stage owns 20% of budget)
            ("coalesce_flush",
             lambda i: i["burn"] >= self.burn_hi,
             "verify", "flush_age_ns", -1),
            # burn still high: tighten the lat-lane close deadline
            ("lat_deadline",
             lambda i: i["burn"] >= self.burn_hi,
             "verify", "deadline_us", -1),
            ("lat_inflight",
             lambda i: i["burn"] >= self.burn_hi,
             "verify", "lat_max_inflight", +1),
            # burn high and the front door is NOT already shedding:
            # admit less (shed earlier) so queues drain
            ("front_door_shed",
             lambda i: i["burn"] >= self.burn_hi and not i.get("shedding"),
             "quic_server", "conn_txn_rate", -1),
            ("net_shed",
             lambda i: i["burn"] >= self.burn_hi and not i.get("shedding"),
             "net", "pps_per_source", -1),
            # healthy but shedding: capacity is there, admit more
            ("front_door_admit",
             lambda i: i["burn"] <= self.burn_lo and i.get("shedding"),
             "quic_server", "conn_txn_rate", +1),
            ("net_admit",
             lambda i: i["burn"] <= self.burn_lo and i.get("shedding"),
             "net", "pps_per_source", +1),
        ]

    def _eligible(self, rule: str) -> bool:
        return self.period >= self._cooldown.get(rule, 0)

    def _pick_action(self, inputs: dict):
        """First eligible rule with headroom -> (rule, tile, knob, new)."""
        for rule, want, kind, knob, direction in self._rules():
            if not self._eligible(rule) or not want(inputs):
                continue
            if self.poison and rule == self.poison:
                direction = -direction
            for tile in self._tiles_of(kind):
                old = self.current.get((tile, knob))
                if old is None:
                    continue
                if kind in ("net", "quic_server") and old <= 0:
                    continue  # rate limiter unarmed at boot: leave it off
                new, _ = self._step_value(knob, old, direction)
                if new == old:
                    self.clamp_cnt += 1
                    self._record(rule, tile, knob, old, old, "clamped",
                                 inputs)
                    self._cooldown[rule] = (self.period
                                            + self.cooldown_periods)
                    return None  # pinned at clamp: done this period
                return rule, tile, knob, old, new
        return None

    def _relax(self, inputs: dict):
        """Healthy for `relax_after` periods: walk the most-displaced
        knob one step back toward its boot baseline, so a transient storm
        doesn't leave permanent scar tissue in the tuning."""
        worst, worst_frac = None, 0.0
        for key, base in self.baseline.items():
            cur = self.current.get(key, base)
            if base <= 0 or cur == base:
                continue
            frac = abs(cur - base) / base
            if frac > worst_frac:
                worst, worst_frac = key, frac
        if worst is None:
            return None
        tile, knob = worst
        base = self.baseline[worst]
        old = self.current[worst]
        direction = +1 if base > old else -1
        new, _ = self._step_value(knob, old, direction)
        # never overshoot the baseline while relaxing
        new = min(new, base) if direction > 0 else max(new, base)
        _, _, _, _, is_int, _ = self.bounds[knob]
        if is_int:
            new = float(int(round(new)))
        if new == old:
            return None
        return "relax", tile, knob, old, new

    # -- the control loop -------------------------------------------------
    def maybe_step(self):
        """Rate-limited entry point for the supervise() loop; a policy
        bug must never take the supervisor down."""
        if not self.enabled:
            return
        now = time.monotonic()
        if now < self._next_t:
            return
        self._next_t = now + self.period_s
        try:
            self.step()
        except Exception as e:  # pragma: no cover - defensive
            log.warning("autotune step failed: %s", e)

    def step(self):
        """One control period: sense -> do-no-harm audit -> at most one
        bounded rule action (or relax-toward-baseline when healthy)."""
        self.period += 1
        inputs = self.sense()
        burn = inputs["burn"]

        # convergence is graded against the ACT threshold: inside the
        # hysteresis deadband the loop rests, and resting with burn under
        # burn_hi IS the converged state (relax eligibility is stricter)
        if burn < self.burn_hi:
            self._ok_streak += 1
            self._burn_hi_streak = 0
            if self._ok_streak >= 2 and self.converged_at is None:
                self.converged_at = self.period
        else:
            self._ok_streak = 0
            self._burn_hi_streak += 1
            self.converged_at = None
        self._calm_streak = (self._calm_streak + 1
                             if burn <= self.burn_lo else 0)

        # do-no-harm: audit the last action against the burn it saw
        if self._last is not None:
            w = self._last
            if inputs["n"] and burn > w["burn0"] + 0.01:
                w["worse"] += 1
            elif inputs["n"]:
                w["worse"] = 0
            if w["worse"] >= 2:
                self._actuate(w["tile"], w["knob"], w["old"])
                self.revert_cnt += 1
                self._cooldown[w["rule"]] = (self.period
                                             + self.quarantine_periods)
                self._record("do_no_harm", w["tile"], w["knob"],
                             w["new"], w["old"], "reverted", inputs)
                log.warning("autotune: reverted %s (%s.%s %s -> %s); "
                            "rule quarantined %d periods", w["rule"],
                            w["tile"], w["knob"], w["new"], w["old"],
                            self.quarantine_periods)
                self._last = None
                return
            if self.period - w["period"] >= max(2, self.cooldown_periods):
                self._last = None  # action held: keep it

        # last resort: sustained critical burn with the window already
        # maxed -> respawn the verify tile with the bigger dispatch-ahead
        # window armed in its pod (n_buffers and bucket state rebuild)
        if (self.respawn_after > 0 and self.run is not None
                and self._burn_hi_streak >= self.respawn_after):
            for tile in self._tiles_of("verify"):
                key = (tile, "max_inflight")
                hi = self.bounds["max_inflight"][2]
                old = self.current.get(key, 0)
                if old >= hi:
                    continue  # window already maxed: respawning again
                    # would just crash-loop the tile to no effect
                self._actuate(tile, "max_inflight", hi)
                self._burn_hi_streak = 0
                if (getattr(getattr(self.run, "policy", None),
                            "drain_timeout_s", 0.0) > 0
                        and hasattr(self.run, "rolling_restart")):
                    # drain configured: escalate through the graceful
                    # envelope instead — the restart also actuates a
                    # RESTART-REQUIRED knob (one more packed-blob pool
                    # buffer widens upload/compute overlap alongside the
                    # bigger dispatch window), bounded like every pod
                    # knob, and the drain keeps the restart zero-loss.
                    # Timeout inside rolling_restart degrades to the
                    # plain respawn below by itself.
                    try:
                        nb_old = int(self.run.jt.tile_spec(tile)
                                     .cfg.get("n_buffers", 3))
                    except KeyError:
                        nb_old = 3
                    nb_new = min(nb_old + 1, 8)  # hard cap: blob pools
                    # are device memory, not free
                    self._record("rolling_restart", tile, "n_buffers",
                                 nb_old, nb_new, "rolling_restart",
                                 inputs)
                    self.run.rolling_restart(
                        tile, {"n_buffers": nb_new}
                        if nb_new != nb_old else None)
                else:
                    self._record("respawn_window", tile, "max_inflight",
                                 old, hi, "respawned", inputs)
                    self.run.respawn(tile)
                return

        # one action in flight at a time: while a do-no-harm watch is
        # active, the loop only measures — acting again before the last
        # move is judged would compound a bad move and orphan its watch
        act = None
        if self._last is not None:
            return
        if burn >= self.burn_hi or inputs.get("shedding") \
                or "slow consumer" in inputs.get("reason", ""):
            act = self._pick_action(inputs)
        elif (self._calm_streak >= self.relax_after
              and self._eligible("relax")):
            act = self._relax(inputs)
            if act is not None:
                self._cooldown["relax"] = self.period + self.cooldown_periods
        if act is None:
            return
        rule, tile, knob, old, new = act
        self._actuate(tile, knob, new)
        self._cooldown[rule] = self.period + self.cooldown_periods
        self._record(rule, tile, knob, old, new, "applied", inputs)
        self._last = {"rule": rule, "tile": tile, "knob": knob,
                      "old": old, "new": new, "period": self.period,
                      "burn0": burn, "worse": 0}

    # -- observability ----------------------------------------------------
    @property
    def converge_s(self) -> float:
        """Periods-to-healthy in seconds (0 = never converged)."""
        if self.converged_at is None:
            return 0.0
        return self.converged_at * self.period_s

    def families(self):
        """fdtpu_autotune_* samples for prometheus_render(extra=...)."""
        out = [
            ("fdtpu_autotune_decision_cnt", "counter",
             "autotune decisions recorded", {}, self.decision_cnt),
            ("fdtpu_autotune_revert_cnt", "counter",
             "autotune do-no-harm reverts", {}, self.revert_cnt),
            ("fdtpu_autotune_clamp_cnt", "counter",
             "autotune steps stopped at a clamp", {}, self.clamp_cnt),
            ("fdtpu_autotune_converged", "gauge",
             "1 = burn under the act threshold (loop at rest)", {},
             int(self._ok_streak >= 2)),
        ]
        for (tile, knob), v in sorted(self.current.items()):
            out.append(("fdtpu_autotune_knob", "gauge",
                        "current autotuned knob value",
                        {"tile": tile, "knob": knob}, v))
        return out


# -- decision-log rendering (fdtpuctl autotune / postmortem) ----------------
def load_decisions(path: str) -> list[dict]:
    """Parse an autotune.jsonl mirror (skipping torn tail lines)."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return out


def render_decisions(decisions: list[dict], limit: int = 50) -> str:
    """Terminal decision-history table: one line per decision with the
    inputs that fired it — the explainability surface the do-no-harm
    guard exists for."""
    if not decisions:
        return "no autotune decisions recorded"
    lines = [f"{'PERIOD':>6} {'RULE':<20} {'TILE':<12} {'KNOB':<16}"
             f"{'OLD':>12} {'NEW':>12}  {'OUTCOME':<9} BURN  WHY"]

    def _v(x):
        if x is None:
            return "-"
        x = float(x)
        return f"{x:,.0f}" if x == int(x) else f"{x:,.2f}"

    for d in decisions[-limit:]:
        why = d.get("reason") or d.get("bottleneck") or ""
        lines.append(
            f"{d.get('period', 0):>6} {d.get('rule', ''):<20} "
            f"{d.get('tile', ''):<12} {d.get('knob', ''):<16}"
            f"{_v(d.get('old')):>12} {_v(d.get('new')):>12}  "
            f"{d.get('outcome', ''):<9} {d.get('burn', 0.0):.2f}  "
            f"{why[:48]}")
    reverts = sum(1 for d in decisions if d.get("outcome") == "reverted")
    lines.append(f"{len(decisions)} decisions, {reverts} reverted")
    return "\n".join(lines)
