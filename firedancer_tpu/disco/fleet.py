"""Fleet supervisor (round 17): N host-scoped topologies under one
control plane, with host-loss failover and exactly-once verdicts.

One "host" = one supervisor subprocess running its own full topology
(own shm workspace via a per-host app name, own metrics port, own
drain-manifest dir, own sink capture ledger) — the in-container stand-in
for a real machine.  The fleet layer on top wires them into one system:

  * steering — a consistent-hash SteerRing (waltz/pkteng.py) maps peers
    and sig-prefix tcache shards to hosts; ownership depends only on
    host identity, so a host that re-joins owns exactly its old ranges,
    and removing a host hands each arc to the next survivor clockwise.
  * control ring — every host supervisor runs a flamenco GossipNode
    over loopback UDP, flooding KIND_SIG_DIGEST values: its recently
    verdicted sig tags per tcache shard (exact u64 chunks + a Bloom).
    Survivors fold them into a RecentSigCache — the reject surface a
    failover host consults so already-verified sigs never re-verdict.
  * failover — when a host dies, the fleet picks the ring's next owner
    and commands adoption: the survivor preloads its dedup tcache with
    the dead host's exported ledger (capture file ∪ gossiped digests)
    via a PR-12 rolling restart, then re-runs the dead host's txn
    stream (SourceTile adopt_streams).  Verdicted-but-unexported work
    re-verifies; exported work is rejected at dedup — the fleet-wide
    ledger stays exactly-once.
  * fleet rolling restart — the PR-12 drain protocol promoted to fleet
    scope: one host at a time drains its whole topology in dependency
    order, exits, and reboots with its own ledger preloaded, so a full
    fleet upgrade loses and duplicates nothing.

The verdict ledger is the union of per-host sink capture files
(u64 sig | u32 len | payload, unbuffered appends): a verdict "exists"
fleet-wide once exported there.  SIGKILL mid-record leaves a torn tail;
capture_tags() stops at it, and the un-parseable record's txn simply
re-verifies elsewhere — once.
"""

from __future__ import annotations

import copy
import json
import multiprocessing as mp
import os
import signal
import socket
import threading
import time
import urllib.request

from ..utils import log


# -- verdict ledger ----------------------------------------------------------

def capture_tags(path: str) -> list[int]:
    """Parse a sink capture file -> ordered sig tags.  Tolerates a torn
    tail (the writer may have been SIGKILLed mid-append): parsing stops
    at the first truncated record."""
    out = []
    try:
        with open(path, "rb") as f:
            buf = f.read()
    except OSError:
        return out
    off, n = 0, len(buf)
    while off + 12 <= n:
        tag = int.from_bytes(buf[off:off + 8], "little")
        ln = int.from_bytes(buf[off + 8:off + 12], "little")
        if off + 12 + ln > n:
            break                      # torn tail record
        out.append(tag)
        off += 12 + ln
    return out


def stream_universe(host_specs: list[dict]) -> dict[int, int]:
    """tag -> host_idx for every txn the fleet's sources will inject
    (the exactly-once assertion's ground truth).  host_specs entries:
    {"seed", "keys", "count", "idx"}."""
    from .tiles import source_txn_stream
    uni: dict[int, int] = {}
    for hs in host_specs:
        for tag, _wire in source_txn_stream(
                int(hs["seed"]), int(hs.get("keys", 4)),
                int(hs["count"])):
            uni[tag] = int(hs["idx"])
    return uni


# -- per-host config ---------------------------------------------------------

def host_name(idx: int) -> str:
    return f"h{idx}"


def host_cfg(base: dict, idx: int, workdir: str, boot_gen: int = 0) -> dict:
    """Derive host `idx`'s topology config from the fleet base config:
    distinct workspace name (shm isolation), seeded per-host source
    stream, per-host capture ledger + drain-manifest dir."""
    cfg = copy.deepcopy(base)
    cfg["name"] = f"{base.get('name', 'fdtpu')}_h{idx}"
    dev = cfg.setdefault("development", {})
    dev["bench_seed"] = int(dev.get("bench_seed", 42)) + 1000 * idx
    sup = cfg.setdefault("supervision", {})
    # fleet failover/upgrade leans on graceful drains (adopt restarts,
    # drain_exit); a 0.0 budget would demote every one to crash-respawn
    if float(sup.get("drain_timeout_s", 0.0) or 0.0) <= 0.0:
        sup["drain_timeout_s"] = 10.0
    man_dir = os.path.join(workdir, f"h{idx}_manifests")
    os.makedirs(man_dir, exist_ok=True)
    sup["drain_manifest_dir"] = man_dir
    tiles = cfg.setdefault("tiles", {})
    tiles.setdefault("sink", {})["capture_path"] = \
        os.path.join(workdir, f"h{idx}.cap")
    fl = cfg.setdefault("fleet", {})
    fl["host_idx"] = idx
    fl["boot_gen"] = int(boot_gen)
    fl["workdir"] = workdir
    # sharded dedup: this host owns the shards the ring assigns it
    sb = int(fl.get("shard_bits", 4))
    if sb:
        from ..waltz.pkteng import SteerRing
        ring = SteerRing([host_name(i)
                          for i in range(int(fl.get("hosts", 1)))],
                         vnodes=int(fl.get("vnodes", 64)))
        tiles.setdefault("dedup", {}).update(
            shard_bits=sb,
            shard_own=sorted(ring.owned_shards(host_name(idx), sb)))
    return cfg


def host_stream_spec(base: dict, idx: int) -> dict:
    """The (seed, keys, count) stream host `idx`'s source publishes —
    what a failover host adopts and the chaos universe regenerates."""
    dev = base.get("development", {})
    return {"seed": int(dev.get("bench_seed", 42)) + 1000 * idx,
            "keys": 4, "count": int(dev.get("source_count", 0)),
            "idx": idx}


# -- host supervisor process -------------------------------------------------

def _gossip_identity(idx: int, fleet_seed: int):
    """Deterministic per-host gossip identity (seeded like everything
    else in the chaos harness)."""
    import hashlib
    from ..ops import ed25519 as ed
    seed = hashlib.sha256(
        b"fdtpu-fleet-%d-%d" % (int(fleet_seed), int(idx))).digest()
    pub, _, _ = ed.keypair_from_seed(seed)
    return seed, pub


class _HostGossip:
    """The control-ring half of a host supervisor: a GossipNode over a
    loopback UDP socket, publishing this host's verdicted sig tags as
    per-shard digest chunks and folding peers' digests into a
    RecentSigCache."""

    def __init__(self, idx: int, fleet_seed: int, shard_bits: int,
                 chunk_max: int = 512):
        import random
        from ..flamenco import gossip as g
        from ..ops import ed25519 as ed
        self.idx = idx
        self.shard_bits = int(shard_bits)
        self.chunk_max = int(chunk_max)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.setblocking(False)
        self.port = self.sock.getsockname()[1]
        seed, pub = _gossip_identity(idx, fleet_seed)
        self.node = g.GossipNode(
            pub, lambda m: ed.sign(seed, m),
            lambda s, m, p: ed.verify_one_host(s, m, p),
            g.contact_info_body("127.0.0.1", self.port, 0, 0),
            rng=random.Random(0x5EED ^ idx))
        self.sigcache = g.RecentSigCache()
        self._g = g
        self._chunk_seq: dict[int, int] = {}
        self._drop_addrs: set[tuple] = set()   # partitioned peer addrs
        self.rx_cnt = 0
        self.drop_cnt = 0
        self.publish_cnt = 0

    def set_partitions(self, addrs) -> None:
        self._drop_addrs = {tuple(a) for a in addrs}

    def bootstrap(self, peer_addrs) -> None:
        """Introduce ourselves: push our own contact value straight at
        each peer (the receiver upserts it, pings, and from the pong on
        we are a validated flood target)."""
        me = self.node.crds.table.get(
            (self._g.KIND_CONTACT_INFO, self.node.identity))
        if me is None:
            return
        pkt = self._g.encode_push([me])
        for addr in peer_addrs:
            if tuple(addr) in self._drop_addrs:
                continue
            try:
                self.sock.sendto(pkt, tuple(addr))
            except OSError:
                pass

    def publish_tags(self, tags) -> int:
        """Publish freshly-captured sig tags as per-shard digest chunks."""
        if not tags:
            return 0
        by_shard: dict[int, list[int]] = {}
        shift = 64 - self.shard_bits if self.shard_bits else 64
        for t in tags:
            by_shard.setdefault((int(t) >> shift) if self.shard_bits
                                else 0, []).append(int(t))
        n = 0
        for shard, ts in by_shard.items():
            for i in range(0, len(ts), self.chunk_max):
                seq = self._chunk_seq.get(shard, 0)
                self._chunk_seq[shard] = seq + 1
                self.node.publish(
                    self._g.KIND_SIG_DIGEST,
                    self._g.sig_digest_body(
                        shard, seq, ts[i:i + self.chunk_max],
                        bloom_seed=0x51D ^ (self.idx << 20) ^ seq))
                n += 1
        self.publish_cnt += n
        return n

    def pump(self) -> None:
        """Drain rx, fold digests, run one gossip tick's tx."""
        for _ in range(256):
            try:
                pkt, src = self.sock.recvfrom(65535)
            except (BlockingIOError, OSError):
                break
            if src in self._drop_addrs:
                self.drop_cnt += 1      # injected partition: drop on rx
                continue
            self.rx_cnt += 1
            try:
                replies = self.node.handle(pkt, src)
            except Exception:
                continue
            for payload, addr in replies:
                if tuple(addr) in self._drop_addrs:
                    continue
                try:
                    self.sock.sendto(payload, tuple(addr))
                except OSError:
                    pass
        for payload, addr in self.node.tick():
            if tuple(addr) in self._drop_addrs:
                continue
            try:
                self.sock.sendto(payload, tuple(addr))
            except OSError:
                pass
        # fold every sig-digest value currently in the table (fold() is
        # idempotent per (origin, shard, chunk))
        for v in self.node.crds.values():
            if v.kind == self._g.KIND_SIG_DIGEST \
                    and v.origin != self.node.identity:
                self.sigcache.fold(v)

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


def _host_main(cfg: dict, idx: int, conn) -> None:
    """Entry point of one host supervisor process: boot the topology,
    run the control ring, serve fleet commands over the pipe."""
    os.setpgid(0, 0)                   # own group: fleet killpg = host loss
    from ..app import config as config_mod
    from .run import SupervisionPolicy, TopoRun
    fl = cfg.get("fleet", {})
    run = None
    gos = None
    try:
        spec = config_mod.build_topology(cfg)
        policy = SupervisionPolicy.from_cfg(cfg)
        run = TopoRun(spec, metrics_port=0, policy=policy, config=cfg)
        run.wait_ready(timeout=float(fl.get("host_boot_timeout_s", 120.0)))
        sup = threading.Thread(target=run.supervise,
                               kwargs={"poll_s": 0.05}, daemon=True)
        sup.start()
        gos = _HostGossip(idx, int(fl.get("fleet_seed", 42)),
                          int(fl.get("shard_bits", 4)),
                          int(fl.get("digest_chunk", 512)))
        conn.send(("ready", idx, {"metrics_port": run.metrics_port,
                                  "gossip_port": gos.port,
                                  "pid": os.getpid(),
                                  "boot_gen": int(fl.get("boot_gen", 0))}))
        cap_path = cfg["tiles"]["sink"]["capture_path"]
        cap_off = 0
        peer_addrs: list[tuple] = []
        last_digest = 0.0
        last_stats = 0.0
        period = float(fl.get("digest_period_s", 0.5))
        while True:
            # fleet commands
            while conn.poll(0.02):
                msg = conn.recv()
                cmd = msg.get("cmd")
                if cmd == "peers":
                    peer_addrs = [tuple(a) for i, a in
                                  msg["addrs"].items() if int(i) != idx]
                    gos.set_partitions(
                        tuple(msg["addrs"][i]) for i in
                        msg.get("partition_peers", ())
                        if i in msg["addrs"])
                    gos.bootstrap(peer_addrs)
                elif cmd == "adopt":
                    dead = int(msg["dead_idx"])
                    pre = set(capture_tags(msg["dead_capture"]))
                    from_disk = len(pre)
                    gossip_tags = gos.sigcache.exact_tags()
                    pre |= gossip_tags
                    pre_path = os.path.join(
                        fl["workdir"], f"h{idx}_adopt_h{dead}.tags")
                    with open(pre_path, "w") as f:
                        f.write("".join("%016x\n" % t for t in sorted(pre)))
                    ok_d = run.rolling_restart(
                        "dedup", {"preload_tags_path": pre_path})
                    ok_s = run.rolling_restart(
                        "source", {"adopt_streams": [msg["stream"]]})
                    conn.send(("adopted", idx, {
                        "dead_idx": dead, "preload": len(pre),
                        "from_disk": from_disk,
                        "from_gossip": len(gossip_tags),
                        "graceful": bool(ok_d and ok_s)}))
                elif cmd == "drain_exit":
                    # fleet rolling restart: whole-topology graceful
                    # drain in dependency order, then exit 0; the fleet
                    # reboots us with our ledger preloaded
                    ok = run.drain(float(msg.get("timeout_s", 60.0)))
                    run.halt()
                    run.close()
                    run = None
                    conn.send(("drained", idx, {"graceful": bool(ok)}))
                    return
                elif cmd == "halt":
                    return
            gos.pump()
            now = time.monotonic()
            if now - last_digest >= period:
                last_digest = now
                try:
                    sz = os.path.getsize(cap_path)
                except OSError:
                    sz = 0
                if sz > cap_off:
                    # publish only the tags appended since last scan
                    tags = capture_tags(cap_path)
                    new = tags[getattr(gos, "_pub_cnt", 0):]
                    gos.publish_tags(new)
                    gos._pub_cnt = len(tags)
                    cap_off = sz
            if now - last_stats >= 0.25:
                last_stats = now
                try:
                    st = urllib.request.urlopen(
                        "http://127.0.0.1:%d/healthz" % run.metrics_port,
                        timeout=2.0).read().decode()
                    state = st.split()[0] if st else "unknown"
                except Exception as e:
                    state = "unhealthy" if "503" in str(e) else "unknown"
                conn.send(("stats", idx, {
                    "captured": getattr(gos, "_pub_cnt", 0),
                    "state": state,
                    "gossip_rx": gos.rx_cnt,
                    "gossip_drop": gos.drop_cnt,
                    "digest_exact": len(gos.sigcache.exact_tags()),
                    "digest_publish": gos.publish_cnt}))
    except Exception as e:      # pragma: no cover - surfaced to the fleet
        try:
            conn.send(("error", idx, {"err": repr(e)[:300]}))
        except Exception:
            pass
        raise
    finally:
        if gos is not None:
            gos.close()
        if run is not None:
            try:
                run.halt()
                run.close()
            except Exception:
                pass


# -- the fleet supervisor ----------------------------------------------------

_STATE_RANK = {"ok": 0, "shedding": 1, "degraded": 2, "draining": 3,
               "unknown": 4, "unhealthy": 5, "lost": 6}


class FleetRun:
    """Boots and supervises an N-host fleet (cfg [fleet] hosts >= 2;
    hosts = 1 is single-host mode and this class refuses it — the
    fleet layer must stay fully inert there)."""

    def __init__(self, cfg: dict, workdir: str, faults=None,
                 start: bool = True):
        fl = cfg.get("fleet", {})
        self.n = int(fl.get("hosts", 1))
        if self.n < 2:
            raise ValueError("FleetRun needs [fleet] hosts >= 2")
        self.cfg = cfg
        self.workdir = workdir
        os.makedirs(workdir, exist_ok=True)
        self.faults = faults
        from ..waltz.pkteng import SteerRing
        self.ring = SteerRing([host_name(i) for i in range(self.n)],
                              vnodes=int(fl.get("vnodes", 64)))
        self._mp = mp.get_context("spawn")
        self.procs: dict[int, mp.Process] = {}
        self.conns: dict[int, object] = {}
        self.info: dict[int, dict] = {}      # ready info per host
        self.stats: dict[int, dict] = {}     # latest stats per host
        self.boot_gen: dict[int, int] = {i: 0 for i in range(self.n)}
        self.lost: set[int] = set()
        self.adopting: dict[int, int] = {}   # dead idx -> adopter idx
        self.adopted: dict[int, dict] = {}   # dead idx -> adoption report
        self.events: list[str] = []
        self.failover_ms: dict[int, float] = {}
        self._expected_exit: set[int] = set()
        # control-plane files: fdtpuctl `fleet top` reads the state
        # file, `fleet rolling_restart` drops a seq-gated command file
        self.state_path = os.path.join(workdir, "fleet_state.json")
        self._cmd_path = os.path.join(workdir, "fleet_cmd.json")
        self._ack_path = os.path.join(workdir, "fleet_cmd_ack.json")
        self._cmd_seq = 0
        self._state_stamp = 0.0
        if start:
            self.start()

    # -- lifecycle --------------------------------------------------------
    def _log(self, msg: str):
        self.events.append(msg)
        log.info("fleet: %s", msg)

    def _spawn(self, idx: int):
        cfg_h = host_cfg(self.cfg, idx, self.workdir,
                         boot_gen=self.boot_gen[idx])
        cfg_h["fleet"]["fleet_seed"] = int(
            self.cfg.get("development", {}).get("bench_seed", 42))
        # host reboot resume: preload the host's OWN exported ledger so
        # the re-generated source stream can't double-verdict
        if self.boot_gen[idx] > 0:
            cap = os.path.join(self.workdir, f"h{idx}.cap")
            own = capture_tags(cap)
            if own:
                pre = os.path.join(self.workdir,
                                   f"h{idx}_resume_g{self.boot_gen[idx]}"
                                   ".tags")
                with open(pre, "w") as f:
                    f.write("".join("%016x\n" % t for t in own))
                cfg_h["tiles"]["dedup"]["preload_tags_path"] = pre
        parent, child = self._mp.Pipe()
        p = self._mp.Process(target=_host_main, args=(cfg_h, idx, child),
                             name=f"fleet-host-{idx}")
        p.start()
        child.close()
        self.procs[idx] = p
        self.conns[idx] = parent
        self._log(f"host h{idx} spawned gen={self.boot_gen[idx]} "
                  f"pid={p.pid}")

    def start(self):
        for i in range(self.n):
            self._spawn(i)

    def wait_ready(self, timeout: float = 300.0):
        deadline = time.monotonic() + timeout
        pending = set(self.procs)
        while pending:
            if time.monotonic() > deadline:
                raise TimeoutError(f"fleet hosts not ready: {pending}")
            for i in list(pending):
                got = self._drain_conn(i, block_s=0.1)
                if i in self.info:
                    pending.discard(i)
                del got
        self._broadcast_peers()

    def _broadcast_peers(self):
        addrs = {i: ("127.0.0.1", self.info[i]["gossip_port"])
                 for i in self.info if i not in self.lost}
        for i, c in self.conns.items():
            if i in self.lost or i not in self.info:
                continue
            part = sorted(self.faults.partition_peers(i)) \
                if self.faults is not None else []
            try:
                c.send({"cmd": "peers", "addrs": addrs,
                        "partition_peers": [p for p in part if p in addrs]})
            except (OSError, BrokenPipeError):
                pass

    def _drain_conn(self, i: int, block_s: float = 0.0):
        c = self.conns.get(i)
        if c is None:
            return []
        out = []
        try:
            while c.poll(block_s):
                block_s = 0.0
                kind, idx, data = c.recv()
                out.append((kind, data))
                if kind == "ready":
                    self.info[idx] = data
                elif kind == "stats":
                    self.stats[idx] = data
                elif kind == "adopted":
                    self.adopted[data["dead_idx"]] = data
                    self._log(f"host h{idx} adopted h{data['dead_idx']}: "
                              f"preload={data['preload']} "
                              f"(gossip={data['from_gossip']})")
                elif kind == "error":
                    self._log(f"host h{idx} error: {data['err']}")
        except (EOFError, OSError):
            pass
        return out

    # -- control-plane files ----------------------------------------------
    def _write_state(self):
        """Publish fleet state for out-of-process observers (fdtpuctl
        fleet top).  Atomic tmp+rename: a reader never sees a torn file."""
        st = {"n": self.n,
              "hosts": {str(i): {
                  "metrics_port": self.info.get(i, {}).get("metrics_port"),
                  "pid": self.info.get(i, {}).get("pid"),
                  "boot_gen": self.boot_gen[i],
                  "state": ("lost" if i in self.lost else
                            self.stats.get(i, {}).get("state", "unknown")),
                  "captured": self.stats.get(i, {}).get("captured", 0),
              } for i in range(self.n)},
              "lost": sorted(self.lost),
              "adopting": {str(d): a for d, a in self.adopting.items()},
              "failover_ms": {str(i): round(v, 1)
                              for i, v in self.failover_ms.items()}}
        tmp = self.state_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(st, f)
            os.replace(tmp, self.state_path)
        except OSError:
            pass

    def _check_cmd_file(self):
        """Serve seq-gated control commands dropped by fdtpuctl."""
        try:
            with open(self._cmd_path) as f:
                cmd = json.load(f)
            seq = int(cmd["seq"])
        except (OSError, ValueError, KeyError, TypeError):
            return
        if seq <= self._cmd_seq:
            return
        self._cmd_seq = seq
        ok = False
        if cmd.get("cmd") == "rolling_restart":
            self._log(f"control: rolling_restart (seq={seq})")
            try:
                ok = self.rolling_restart(
                    float(cmd.get("timeout_s", 120.0)))
            except Exception as e:
                self._log(f"control: rolling_restart failed: {e!r}")
        try:
            with open(self._ack_path + ".tmp", "w") as f:
                json.dump({"seq": seq, "ok": bool(ok)}, f)
            os.replace(self._ack_path + ".tmp", self._ack_path)
        except OSError:
            pass

    # -- supervision ------------------------------------------------------
    def poll(self):
        """One supervision scan: drain host pipes, detect host loss,
        drive injected faults, run failover, serve control commands."""
        for i in list(self.conns):
            self._drain_conn(i)
        self._check_cmd_file()
        now = time.monotonic()
        if now - self._state_stamp >= 0.25:
            self._state_stamp = now
            self._write_state()
        if self.faults is not None and not self.faults.fired:
            k = self.faults.host_kill
            if k is not None and k in self.procs and k not in self.lost:
                cap = self.stats.get(k, {}).get("captured", 0)
                if self.faults.should_kill(k, cap):
                    self._log(f"fault: host_kill h{k} (captured={cap})")
                    self.kill_host(k)
        for i, p in list(self.procs.items()):
            if i in self.lost or p.is_alive():
                continue
            if i in self._expected_exit:
                continue
            self._host_lost(i, f"exitcode={p.exitcode}")

    def kill_host(self, idx: int):
        """SIGKILL the whole host process group — tiles included."""
        p = self.procs.get(idx)
        if p is None or p.pid is None:
            return
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            try:
                p.kill()
            except Exception:
                pass
        p.join(10.0)
        self._host_lost(idx, "killed")

    def _host_lost(self, idx: int, why: str):
        if idx in self.lost:
            return
        t0 = time.monotonic()
        self.lost.add(idx)
        self._log(f"host h{idx} LOST ({why}); re-steering")
        self.ring.remove_host(host_name(idx))
        self.stats.setdefault(idx, {})["state"] = "lost"
        self.stats[idx]["state"] = "lost"
        self._failover(idx)
        self.failover_ms[idx] = (time.monotonic() - t0) * 1e3
        self._broadcast_peers()
        self._write_state()

    def _failover(self, dead_idx: int):
        """Adopt the dead host's in-flight stream on the steering ring's
        next owner: preload its exported ledger, replay its stream."""
        survivors = [i for i in range(self.n)
                     if i not in self.lost and i in self.conns]
        if not survivors:
            self._log("no survivors to adopt; fleet dead")
            return
        # deterministic: the ring's new owner of the dead host's primary
        # steering key adopts (falls to any survivor if unmapped)
        try:
            owner = self.ring.owner_of_peer(host_name(dead_idx), 0)
            adopter = next((i for i in survivors
                            if host_name(i) == owner), survivors[0])
        except LookupError:
            adopter = survivors[0]
        self.adopting[dead_idx] = adopter
        stream = host_stream_spec(self.cfg, dead_idx)
        stream.pop("idx", None)
        try:
            self.conns[adopter].send({
                "cmd": "adopt", "dead_idx": dead_idx,
                "dead_capture": os.path.join(self.workdir,
                                             f"h{dead_idx}.cap"),
                "stream": stream})
            self._log(f"host h{adopter} adopting h{dead_idx} "
                      f"(stream seed={stream['seed']} "
                      f"count={stream['count']})")
        except (OSError, BrokenPipeError):
            self._log(f"adopter h{adopter} unreachable")

    def rolling_restart(self, timeout_s: float = 120.0) -> bool:
        """Fleet-scope zero-loss upgrade: one host at a time, drain the
        whole topology (PR-12 dependency-order drain), reboot it with
        its own ledger preloaded, wait ready, re-publish the peer map."""
        ok = True
        for i in range(self.n):
            if i in self.lost:
                continue
            self._log(f"rolling restart: draining host h{i}")
            self._expected_exit.add(i)
            try:
                self.conns[i].send({"cmd": "drain_exit",
                                    "timeout_s": timeout_s / 2})
            except (OSError, BrokenPipeError):
                ok = False
                continue
            deadline = time.monotonic() + timeout_s
            graceful = False
            while time.monotonic() < deadline:
                for kind, data in self._drain_conn(i, block_s=0.1):
                    if kind == "drained":
                        graceful = bool(data.get("graceful"))
                if not self.procs[i].is_alive():
                    break
            self.procs[i].join(10.0)
            if self.procs[i].is_alive():
                self.kill_host(i)
                self.lost.discard(i)
                ok = False
            ok = ok and graceful
            self.info.pop(i, None)
            self.boot_gen[i] += 1
            self._spawn(i)
            self._expected_exit.discard(i)
            deadline = time.monotonic() + timeout_s
            while i not in self.info:
                if time.monotonic() > deadline:
                    raise TimeoutError(f"host h{i} reboot not ready")
                self._drain_conn(i, block_s=0.1)
            self._log(f"rolling restart: host h{i} back "
                      f"(gen={self.boot_gen[i]}, graceful={graceful})")
            self._broadcast_peers()
        return ok

    # -- control plane ----------------------------------------------------
    def scrape(self, idx: int) -> dict:
        """One host's /metrics, parsed to {family{labels}: value}."""
        port = self.info.get(idx, {}).get("metrics_port")
        if port is None or idx in self.lost:
            return {}
        out: dict[str, float] = {}
        try:
            body = urllib.request.urlopen(
                "http://127.0.0.1:%d/metrics" % port, timeout=2.0
            ).read().decode()
        except Exception:
            return {}
        for line in body.splitlines():
            if not line or line.startswith("#"):
                continue
            try:
                key, val = line.rsplit(None, 1)
                out[key] = float(val)
            except ValueError:
                continue
        return out

    def top(self) -> dict:
        """The `fdtpuctl fleet top` aggregation: per-host health +
        verdict/dedup/autotune counters + the fleet rollup."""
        hosts = {}
        agg = {"captured": 0, "dup_drop": 0, "uniq": 0, "foreign": 0,
               "preload": 0, "adopt_pub": 0, "manifest_corrupt": 0,
               "autotune_decisions": 0}
        worst = "ok"
        for i in range(self.n):
            st = dict(self.stats.get(i, {}))
            state = "lost" if i in self.lost else st.get("state", "unknown")
            m = self.scrape(i)
            h = {"state": state,
                 "boot_gen": self.boot_gen[i],
                 "metrics_port": self.info.get(i, {}).get("metrics_port"),
                 "captured": st.get("captured", 0),
                 "gossip_rx": st.get("gossip_rx", 0),
                 "digest_exact": st.get("digest_exact", 0)}
            for key, val in m.items():
                if "fdtpu_frag_cnt" in key and 'tile="sink"' in key:
                    h["sink_frags"] = int(val)
                elif "fdtpu_dup_drop_cnt" in key:
                    agg["dup_drop"] += int(val)
                elif "fdtpu_uniq_cnt" in key:
                    agg["uniq"] += int(val)
                elif "fdtpu_shard_foreign_cnt" in key:
                    agg["foreign"] += int(val)
                elif "fdtpu_preload_cnt" in key:
                    agg["preload"] += int(val)
                elif "fdtpu_adopt_pub_cnt" in key:
                    agg["adopt_pub"] += int(val)
                elif key.startswith("fdtpu_manifest_corrupt_cnt"):
                    agg["manifest_corrupt"] += int(val)
                elif key.startswith("fdtpu_autotune_decision"):
                    agg["autotune_decisions"] += int(val)
            agg["captured"] += int(h.get("captured", 0))
            if _STATE_RANK.get(state, 4) > _STATE_RANK.get(worst, 0):
                worst = state
            hosts[f"h{i}"] = h
        return {"state": worst, "hosts": hosts, "agg": agg,
                "live": self.n - len(self.lost), "lost": sorted(
                    f"h{i}" for i in self.lost),
                "adopting": {f"h{d}": f"h{a}"
                             for d, a in self.adopting.items()},
                "failover_ms": {f"h{i}": round(v, 1)
                                for i, v in self.failover_ms.items()}}

    @staticmethod
    def render_top(t: dict) -> str:
        lines = [f"FLEET state={t['state']} live={t['live']} "
                 f"lost={','.join(t['lost']) or '-'} "
                 f"captured={t['agg']['captured']} "
                 f"dup_drop={t['agg']['dup_drop']} "
                 f"foreign={t['agg']['foreign']} "
                 f"manifest_corrupt={t['agg']['manifest_corrupt']} "
                 f"autotune={t['agg']['autotune_decisions']}"]
        for name, h in sorted(t["hosts"].items()):
            lines.append(
                f"  {name:<4} state={h['state']:<10} "
                f"gen={h['boot_gen']} "
                f"captured={h.get('captured', 0):<6} "
                f"sink={h.get('sink_frags', '-'):<6} "
                f"gossip_rx={h.get('gossip_rx', 0):<5} "
                f"digest={h.get('digest_exact', 0)}")
        for d, a in t["adopting"].items():
            lines.append(f"  failover {d} -> {a} "
                         f"({t['failover_ms'].get(d, '?')} ms)")
        return "\n".join(lines)

    # -- ledger -----------------------------------------------------------
    def ledger(self) -> list[int]:
        """All exported verdict tags fleet-wide (every host's capture
        file, dead hosts included)."""
        out = []
        for i in range(self.n):
            out += capture_tags(os.path.join(self.workdir, f"h{i}.cap"))
        return out

    def close(self):
        for i, c in self.conns.items():
            try:
                c.send({"cmd": "halt"})
            except (OSError, BrokenPipeError):
                pass
        for i, p in self.procs.items():
            p.join(15.0)
            if p.is_alive():
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except Exception:
                    p.kill()
                p.join(5.0)
        for c in self.conns.values():
            try:
                c.close()
            except Exception:
                pass
