"""Topology model + builder (ref: src/disco/topo/fd_topo.h:8-140,
fd_topob.c).

A topology is a static graph of one workspace (named shared memory), links
(mcache + optional dcache, single-producer / multi-consumer), and tiles (one
process each).  The layout inside the workspace is computed by replaying the
same deterministic allocation sequence in every process — the reference's
trick of materializing the identical fd_topo_t in each tile process
(src/disco/topo/fd_topo.c) so nothing needs serializing beyond the spec.

Specs are plain picklable dataclasses; the materialized view (Topology.join)
holds live ring objects from firedancer_tpu.tango.ring.
"""

from dataclasses import dataclass, field

from ..tango.ring import Workspace, MCache, Dcache, FSeq, Cnc
from . import autotune as autotune_mod
from . import metrics as metrics_mod
from . import trace as trace_mod


@dataclass(frozen=True)
class LinkSpec:
    """One frag stream (fd_topo_link_t, fd_topo.h:46-77)."""
    name: str
    depth: int          # mcache depth, power of two
    mtu: int = 0        # max payload bytes; 0 = metadata-only link (no dcache)
    burst: int = 1      # frags producible beyond depth before wrap


@dataclass(frozen=True)
class InLink:
    """A tile's subscription to a link (fd_topo.h:93-103)."""
    link: str
    reliable: bool = True   # reliable consumers backpressure the producer
    polled: bool = True


@dataclass(frozen=True)
class TileSpec:
    """One tile process (fd_topo_tile_t, fd_topo.h:79-140)."""
    name: str                       # unique instance name, e.g. "verify:0"
    kind: str                       # registry key into disco.tiles.TILES
    in_links: tuple[InLink, ...] = ()
    out_links: tuple[str, ...] = ()  # links this tile produces (it owns them)
    cfg: dict = field(default_factory=dict)

    def __post_init__(self):
        # freeze cfg content hazards early: it must pickle to children
        if not isinstance(self.cfg, dict):
            raise TypeError("tile cfg must be a dict")


@dataclass(frozen=True)
class TopoSpec:
    """The whole static graph; picklable, hashable by app name."""
    app: str
    links: tuple[LinkSpec, ...]
    tiles: tuple[TileSpec, ...]
    wksp_mb: int = 64

    def validate(self) -> "TopoSpec":
        lnames = [l.name for l in self.links]
        if len(set(lnames)) != len(lnames):
            raise ValueError("duplicate link names")
        tnames = [t.name for t in self.tiles]
        if len(set(tnames)) != len(tnames):
            raise ValueError("duplicate tile names")
        producers: dict[str, str] = {}
        for t in self.tiles:
            for ln in t.out_links:
                if ln not in lnames:
                    raise ValueError(f"tile {t.name} produces unknown link {ln}")
                if ln in producers:
                    raise ValueError(
                        f"link {ln} has two producers: {producers[ln]}, {t.name}")
                producers[ln] = t.name
            for il in t.in_links:
                if il.link not in lnames:
                    raise ValueError(f"tile {t.name} consumes unknown link {il.link}")
        for ln in lnames:
            if ln not in producers:
                raise ValueError(f"link {ln} has no producer")
        # bank tiles each own a private Runtime/Funk built from genesis;
        # until an accountsdb shared across processes exists, >1 bank lane
        # would execute against divergent chains (the reference's N bank
        # tiles share one Agave bank via FFI — tiles.h:36-64)
        if sum(1 for t in self.tiles if t.kind == "bank") > 1:
            raise ValueError("at most one bank tile per topology for now "
                             "(bank tiles do not yet share an accounts db)")
        return self


class TopoBuilder:
    """Programmatic topology construction (fd_topob_* builders,
    src/disco/topo/fd_topob.c)."""

    def __init__(self, app: str, wksp_mb: int = 64):
        self.app = app
        self.wksp_mb = wksp_mb
        self._links: list[LinkSpec] = []
        self._tiles: list[TileSpec] = []

    def link(self, name: str, depth: int, mtu: int = 0, burst: int = 1):
        self._links.append(LinkSpec(name, depth, mtu, burst))
        return self

    def tile(self, name: str, kind: str, ins=(), outs=(), **cfg):
        in_links = tuple(
            i if isinstance(i, InLink) else InLink(i) for i in ins)
        self._tiles.append(
            TileSpec(name, kind, in_links, tuple(outs), cfg))
        return self

    def build(self) -> TopoSpec:
        return TopoSpec(self.app, tuple(self._links),
                        tuple(self._tiles), self.wksp_mb).validate()


class JoinedLink:
    def __init__(self, spec: LinkSpec, mcache: MCache, dcache: Dcache | None):
        self.spec = spec
        self.mcache = mcache
        self.dcache = dcache


class JoinedTopology:
    """Live view after mapping the workspace.  Offsets are identical in every
    process because the allocation replay below is deterministic."""

    def __init__(self, spec: TopoSpec, create: bool):
        self.spec = spec
        self.created = create
        self.ws = Workspace(f"fdtpu_{spec.app}", spec.wksp_mb << 20,
                            create=create)
        try:
            self._layout(create)
        except BaseException:
            self.ws.close()
            if create:
                self.ws.unlink()
            raise

    def _layout(self, create: bool):
        ws = self.ws
        self.links: dict[str, JoinedLink] = {}
        for ls in self.spec.links:
            if create:
                mc = MCache.new(ws, ls.depth)
                dc = Dcache.new(ws, ls.mtu, ls.depth, ls.burst) if ls.mtu else None
            else:
                mc = MCache.join(ws, ws.alloc(MCache.footprint(ls.depth)))
                dc = (Dcache.join(
                        ws, ws.alloc(Dcache.footprint(ls.mtu, ls.depth, ls.burst)))
                      if ls.mtu else None)
            self.links[ls.name] = JoinedLink(ls, mc, dc)

        self.cnc: dict[str, Cnc] = {}
        self.metrics: dict[str, metrics_mod.MetricsBlock] = {}
        self.trace: dict[str, trace_mod.TraceRing] = {}
        # per-tile autotune knob mailbox (supervisor-writer, mux-reader)
        self.knobs: dict[str, autotune_mod.KnobPod] = {}
        # (tile_name, link_name) -> consumer fseq
        self.fseq: dict[tuple[str, str], FSeq] = {}
        for t in self.spec.tiles:
            if create:
                self.cnc[t.name] = Cnc.new(ws)
            else:
                from .. import native
                self.cnc[t.name] = Cnc.join(
                    ws, ws.alloc(native.lib().fd_cnc_footprint()))
            moff = ws.alloc(metrics_mod.footprint())
            if create:
                import numpy as np
                np.frombuffer(ws.buf, dtype=np.uint64,
                              count=metrics_mod.footprint() // 8,
                              offset=moff)[:] = 0
            self.metrics[t.name] = metrics_mod.MetricsBlock(ws.buf, moff, t.kind)
            # per-tile fdtrace span ring, laid out next to the metrics
            # block (same single-writer shm contract)
            toff = ws.alloc(trace_mod.footprint())
            self.trace[t.name] = trace_mod.TraceRing(ws.buf, toff,
                                                     create=create)
            koff = ws.alloc(autotune_mod.pod_footprint())
            if create:
                import numpy as np
                np.frombuffer(ws.buf, dtype=np.uint64,
                              count=autotune_mod.pod_footprint() // 8,
                              offset=koff)[:] = 0
            self.knobs[t.name] = autotune_mod.KnobPod(ws.buf, koff, t.kind)
            for il in t.in_links:
                if create:
                    self.fseq[(t.name, il.link)] = FSeq.new(ws)
                else:
                    from .. import native
                    self.fseq[(t.name, il.link)] = FSeq.join(
                        ws, ws.alloc(native.lib().fd_fseq_footprint()))

    def reliable_consumers(self, link_name: str) -> list[FSeq]:
        """FSeqs of every reliable consumer of a link — the producer's credit
        sources (fd_mux.c:233-310)."""
        out = []
        for t in self.spec.tiles:
            for il in t.in_links:
                if il.link == link_name and il.reliable:
                    out.append(self.fseq[(t.name, il.link)])
        return out

    def tile_spec(self, name: str) -> TileSpec:
        for t in self.spec.tiles:
            if t.name == name:
                return t
        raise KeyError(name)

    def consumer_edges(self, tile_name: str) -> list:
        """(in_link, fseq, producer mcache) per in-link of `tile_name` —
        the supervisor's eviction surface for a dead consumer: while the
        tile is down, its reliable fseqs get fast-forwarded to the
        producer cursors (fctl.Fctl.evict_dead_consumer) so upstream
        credits don't freeze on the corpse."""
        t = self.tile_spec(tile_name)
        return [(il, self.fseq[(tile_name, il.link)],
                 self.links[il.link].mcache) for il in t.in_links]

    def close(self):
        # numpy views (dcache/metrics) export pointers into the shm buffer;
        # drop them before closing or SharedMemory.close raises BufferError
        self.links = {}
        self.metrics = {}
        self.trace = {}
        self.knobs = {}
        self.fseq = {}
        self.cnc = {}
        import gc
        gc.collect()
        try:
            self.ws.close()
        except BufferError:
            pass  # a stray view outlived us; the mapping dies with the process

    def unlink(self):
        self.ws.unlink()


def assign_affinity(spec: TopoSpec, affinity: str | None) -> TopoSpec:
    """Thread per-tile CPU pins through tile cfgs (ref: the [layout]
    affinity string in fdctl's config, src/app/fdctl/config.c — there a
    cpu list consumed tile-by-tile in topology order).

    affinity: "" / None = no pinning; "auto" = tiles round-robin over all
    CPUs in topology order; "3,1,5" = explicit cpu per tile in topology
    order (shorter lists wrap).  Tiles with an explicit cfg cpu_idx keep
    it.  Returns a NEW spec (specs are frozen)."""
    if not affinity:
        return spec
    import os as _os
    if affinity == "auto":
        cpus = list(range(_os.cpu_count() or 1))
    else:
        cpus = [int(c) for c in affinity.split(",") if c.strip() != ""]
    if not cpus:
        return spec
    tiles = []
    for idx, t in enumerate(spec.tiles):
        cfg = dict(t.cfg)
        cfg.setdefault("cpu_idx", cpus[idx % len(cpus)])
        tiles.append(TileSpec(t.name, t.kind, t.in_links, t.out_links, cfg))
    return TopoSpec(spec.app, spec.links, tuple(tiles), spec.wksp_mb)


def create(spec: TopoSpec) -> JoinedTopology:
    return JoinedTopology(spec, create=True)


def join(spec: TopoSpec) -> JoinedTopology:
    return JoinedTopology(spec, create=False)
