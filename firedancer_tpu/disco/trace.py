"""fdtrace — per-frag pipeline span tracing (ref: the reference's
tsorig/tspub frag-meta stamps, src/tango/fd_tango_base.h:140-170, rendered
by fd_monitor; plus the trace_event JSON the Chrome/Perfetto UI loads).

Each tile owns a fixed-size SINGLE-WRITER span ring in the workspace,
allocated by the topology layout next to the tile's metrics block.  The
mux run loop records one span per frag (scalar path) or per burst (native
path); the verify pipeline adds coalesce/device/compile spans through the
same writer.  `fdtpuctl trace` drains every ring read-only and exports
Chrome `trace_event` JSON (loadable in Perfetto / chrome://tracing) plus
a terminal p50/p99-per-hop table.

Concurrency contract (same as disco/metrics.py): one writer per ring,
aligned 8-byte stores, readers snapshot without coordination and drop
records the cursor may have overwritten mid-copy.

This module must stay import-light (numpy only): the topology layout and
every tile process import it.
"""

import json

import numpy as np

# -- span record ------------------------------------------------------------

TRACE_REC_DTYPE = np.dtype([
    ("ts", "<u8"),       # span start, monotonic ns (full width)
    ("dur", "<u8"),      # span duration ns
    ("seq", "<u8"),      # first frag seq covered (0 if not frag-bound)
    ("hop_ns", "<u4"),   # producer tspub -> our consume (one hop)
    ("age_ns", "<u4"),   # chain origin tsorig -> our consume (whole chain)
    ("iidx", "<u2"),     # in-link index (or bucket index for device spans)
    ("kind", "<u2"),     # KIND_* below
    ("cnt", "<u4"),      # frags / txns covered by the span
])
assert TRACE_REC_DTYPE.itemsize == 40  # 8-byte aligned, no padding

# span kinds (the pipeline stages of ISSUE's span chain: ingest -> dedup ->
# coalesce -> dispatch -> device -> readback -> pack all reduce to these)
KIND_FRAG = 1       # scalar on_frag callback (one frag)
KIND_BURST = 2      # native on_burst callback (cnt frags)
KIND_COALESCE = 3   # verify bucket: first txn in -> dispatch
KIND_DEVICE = 4     # verify bucket: dispatch -> verdict harvested
KIND_COMPILE = 5    # first dispatch of a (batch, maxlen) shape (XLA compile)
KIND_STAGE = 6      # named offline stage (tools/profile_verify.py)
KIND_DISPATCH = 7   # verify bucket: dispatch call + over-budget queue drain
KIND_PUBLISH = 8    # verify: verdicted txns -> downstream publish
KIND_HARVEST = 9    # verify: verdict materialize -> passing txns rebuilt

KIND_NAMES = {
    KIND_FRAG: "frag", KIND_BURST: "burst", KIND_COALESCE: "coalesce",
    KIND_DEVICE: "device", KIND_COMPILE: "compile", KIND_STAGE: "stage",
    KIND_DISPATCH: "dispatch", KIND_PUBLISH: "publish",
    KIND_HARVEST: "harvest",
}

# lane tag (round 9): the iidx field's top bit marks spans from the
# verify pipeline's low-latency lane, so the Chrome trace and hop table
# separate the deadline-driven lane from the throughput lane on the
# same tile row.  In-link and bucket indexes stay far below 2^15, and
# SpanRecorder's stage indexes never set the bit, so the split is
# lossless.
LANE_LAT = 1 << 15


def _lane_split(iidx: int) -> tuple[int, bool]:
    """(index, is_low_latency_lane) from a raw span iidx."""
    return iidx & (LANE_LAT - 1), bool(iidx & LANE_LAT)

DEPTH = 4096        # spans retained per tile (~160 KiB: DEPTH * 40B + header)
_HDR = 64           # [magic, depth, cursor, reserved...] as u64
_MAGIC = 0xFD7ACE0000000001


def footprint(depth: int = DEPTH) -> int:
    return _HDR + depth * TRACE_REC_DTYPE.itemsize


class TraceRing:
    """Single-writer span ring over a workspace byte range (the same
    static-offset contract as MetricsBlock: every process computes the
    identical offset by allocation replay)."""

    def __init__(self, buf: memoryview, off: int, create: bool = False,
                 depth: int = DEPTH):
        self._hdr = np.frombuffer(buf, dtype=np.uint64, count=_HDR // 8,
                                  offset=off)
        if create:
            self._hdr[1] = depth
            self._hdr[2] = 0
            self._hdr[0] = _MAGIC  # magic last: joiners see a full header
        if int(self._hdr[0]) != _MAGIC:
            raise ValueError("no trace ring at offset")
        self.depth = int(self._hdr[1])
        self._recs = np.frombuffer(buf, dtype=TRACE_REC_DTYPE,
                                   count=self.depth, offset=off + _HDR)
        if create:
            self._recs[:] = 0
        self._cursor = int(self._hdr[2])  # writer-side cache

    # -- writer (one per tile) ---------------------------------------------
    def record(self, kind: int, ts: int, dur: int, *, iidx: int = 0,
               hop_ns: int = 0, age_ns: int = 0, cnt: int = 1, seq: int = 0):
        c = self._cursor
        self._recs[c % self.depth] = (
            ts, dur, seq, min(hop_ns, 0xFFFFFFFF), min(age_ns, 0xFFFFFFFF),
            iidx & 0xFFFF, kind & 0xFFFF, min(cnt, 0xFFFFFFFF))
        self._cursor = c + 1
        self._hdr[2] = c + 1  # cursor store AFTER the record (readers gate)

    # -- reader (monitor / fdtpuctl trace) ---------------------------------
    def snapshot(self, since: int = 0):
        """Records published in [since, cursor), oldest first; returns
        (cursor, records).  Records the writer may have overwritten while
        we copied are dropped (re-read the cursor, discard anything below
        the new lapped floor)."""
        cur = int(self._hdr[2])
        lo = max(since, cur - self.depth)
        if lo >= cur:
            return cur, self._recs[:0].copy()
        idx = np.arange(lo, cur, dtype=np.int64) % self.depth
        out = self._recs[idx].copy()
        lapped = int(self._hdr[2]) - self.depth
        if lapped > lo:
            out = out[lapped - lo:]
        return cur, out


# -- chrome trace_event export ---------------------------------------------

def chrome_trace(spans_by_tile: dict[str, np.ndarray]) -> dict:
    """Build a Chrome trace_event JSON object (Perfetto-loadable): one
    pid per app, one tid per tile, "X" complete events with microsecond
    timestamps.  Span args carry hop/age/cnt for drill-down."""
    events = []
    for tid, (tile, recs) in enumerate(sorted(spans_by_tile.items())):
        events.append({"ph": "M", "name": "thread_name", "pid": 1,
                       "tid": tid, "args": {"name": tile}})
        for r in recs:
            kind = KIND_NAMES.get(int(r["kind"]), str(int(r["kind"])))
            idx, is_lat = _lane_split(int(r["iidx"]))
            events.append({
                "ph": "X",
                "name": f"{kind}:in{idx}" + (":lat" if is_lat else ""),
                "cat": kind,
                "pid": 1,
                "tid": tid,
                "ts": int(r["ts"]) / 1e3,
                "dur": max(int(r["dur"]), 1) / 1e3,
                "args": {"hop_ns": int(r["hop_ns"]),
                         "age_ns": int(r["age_ns"]),
                         "cnt": int(r["cnt"]),
                         "seq": int(r["seq"]),
                         "lane": "lat" if is_lat else "bulk"},
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, spans_by_tile: dict) -> None:
    with open(path, "w") as f:
        json.dump(chrome_trace(spans_by_tile), f)


# -- terminal per-hop table ------------------------------------------------

def hop_table(spans_by_tile: dict[str, np.ndarray]) -> str:
    """p50/p99 per (tile, kind, in-link) over hop latency and span
    duration — the terminal companion of the mux's in*_hop gauges,
    computed from the SAME samples through the same Histf percentile."""
    from ..utils.hist import Histf
    rows = []
    for tile, recs in sorted(spans_by_tile.items()):
        for kind in np.unique(recs["kind"]) if len(recs) else []:
            km = recs[recs["kind"] == kind]
            for iidx in np.unique(km["iidx"]):
                sel = km[km["iidx"] == iidx]
                hh, dh = Histf(100, 10e9), Histf(100, 10e9)
                frags = 0
                for r in sel:
                    if int(r["hop_ns"]):
                        hh.sample(int(r["hop_ns"]))
                    dh.sample(max(int(r["dur"]), 1))
                    frags += int(r["cnt"])
                idx, is_lat = _lane_split(int(iidx))
                kname = KIND_NAMES.get(int(kind), str(int(kind)))
                rows.append((
                    tile, kname + (":lat" if is_lat else ""),
                    idx, len(sel), frags,
                    hh.percentile(0.50) if hh.count() else 0.0,
                    hh.percentile(0.99) if hh.count() else 0.0,
                    dh.percentile(0.50), dh.percentile(0.99)))
    lines = [f"{'TILE':<14}{'SPAN':<10}{'IN':>3}{'SPANS':>8}{'FRAGS':>9}"
             f"{'HOP p50':>10}{'HOP p99':>10}{'DUR p50':>10}{'DUR p99':>10}"]
    for t, k, i, n, fr, h50, h99, d50, d99 in rows:
        def _us(v):
            return f"{v / 1e3:,.0f}us" if v else "-"
        lines.append(f"{t:<14}{k:<10}{i:>3}{n:>8}{fr:>9}"
                     f"{_us(h50):>10}{_us(h99):>10}"
                     f"{_us(d50):>10}{_us(d99):>10}")
    return "\n".join(lines)


# -- in-process recorder (tools/profile_verify.py, bench decomposition) ----

class SpanRecorder:
    """Offline span sink with the same record shape as TraceRing but
    string stage names: tools use it so their stage timings export
    through the SAME chrome_trace/hop_table renderers (one
    instrumentation source, no drift vs the live pipeline)."""

    def __init__(self, tile: str = "offline"):
        self.tile = tile
        self._names: list[str] = []
        self._recs: list[tuple] = []

    def _stage_idx(self, name: str) -> int:
        try:
            return self._names.index(name)
        except ValueError:
            self._names.append(name)
            return len(self._names) - 1

    def record(self, name: str, ts: int, dur: int, cnt: int = 1):
        self._recs.append((ts, dur, 0, 0, 0, self._stage_idx(name),
                           KIND_STAGE, cnt))

    def span(self, name: str, cnt: int = 1):
        """Context manager timing one stage into the recorder."""
        import time

        class _Span:
            def __enter__(s):
                s.t0 = time.perf_counter_ns()
                return s

            def __exit__(s, *exc):
                self.record(name, s.t0, time.perf_counter_ns() - s.t0, cnt)

        return _Span()

    def records(self) -> np.ndarray:
        return np.array(self._recs, dtype=TRACE_REC_DTYPE)

    def stage_name(self, iidx: int) -> str:
        return self._names[iidx] if iidx < len(self._names) else str(iidx)

    def chrome(self) -> dict:
        """chrome_trace with stage names substituted for in-link labels."""
        out = chrome_trace({self.tile: self.records()})
        for ev in out["traceEvents"]:
            if ev["ph"] == "X":
                ev["name"] = self.stage_name(
                    int(ev["name"].rsplit(":in", 1)[1]))
        return out

    def table(self) -> str:
        """Per-stage p50/p99/mean, through the same Histf percentile the
        mux hop gauges use."""
        from ..utils.hist import Histf
        recs = self.records()
        lines = [f"{'STAGE':<28}{'SPANS':>7}{'p50':>12}{'p99':>12}"
                 f"{'mean':>12}"]
        for i, name in enumerate(self._names):
            sel = recs[recs["iidx"] == i] if len(recs) else recs
            if not len(sel):
                continue
            h = Histf(100, 60e9)
            for r in sel:
                h.sample(max(int(r["dur"]), 1))
            mean = float(sel["dur"].mean())
            lines.append(
                f"{name:<28}{len(sel):>7}"
                f"{h.percentile(0.50) / 1e6:>10.2f}ms"
                f"{h.percentile(0.99) / 1e6:>10.2f}ms"
                f"{mean / 1e6:>10.2f}ms")
        return "\n".join(lines)


# -- compile-event registry ------------------------------------------------
# Process-local first-dispatch/recompile bookkeeping shared by the verify
# pipeline and ops.ed25519.verify_one; tiles mirror it into their metrics
# block so bench.py / fdtpuctl monitor / /metrics all see the same counts.
# Must not import jax at module import time (topo layout imports us).

_compile_events: dict[tuple, dict] = {}


def record_compile(key: tuple, ns: int) -> None:
    ev = _compile_events.setdefault(key, {"cnt": 0, "ns": 0})
    ev["cnt"] += 1
    ev["ns"] += int(ns)


def compile_events() -> dict[tuple, dict]:
    return dict(_compile_events)


def compile_totals() -> tuple[int, int]:
    cnt = sum(e["cnt"] for e in _compile_events.values())
    ns = sum(e["ns"] for e in _compile_events.values())
    return cnt, ns


def install_jax_compile_listener() -> bool:
    """Route jax.monitoring's compile-duration events into the registry
    (best-effort: the API is version-dependent; first-dispatch timing in
    the pipeline is the primary source)."""
    try:
        import jax.monitoring as jm

        def _on_event(event: str, duration: float, **kw):
            if "compil" in event:
                record_compile(("jax", event), int(duration * 1e9))

        jm.register_event_duration_secs_listener(_on_event)
        return True
    except Exception:
        return False
