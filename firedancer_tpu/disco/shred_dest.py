"""Turbine shred destinations: who to send each shred to.

Reference role: src/disco/shred/fd_shred_dest.c (the Turbine tree) +
src/disco/shred/fd_stake_ci.c (the epoch stake/contact view behind it).

The tree, per shred:

  1. seed = sha256( slot u64le | type byte (0xA5 data / 0x5A code) |
                    idx u32le | leader_pubkey ), fd_shred_dest.c:26-31.
  2. The seed keys a ChaCha20Rng driving a stake-weighted shuffle of all
     known validators minus the leader: staked nodes first (weighted
     sampling without replacement over lamports), then unstaked nodes
     (uniform Fisher-Yates), fd_shred_dest.c:139-212.
  3. Position in the shuffle decides duties (fd_shred_dest.c:388-394):
       leader          -> sends to shuffle[0] (the "first"/root)
       my_idx == 0     -> children are shuffle[1..fanout]
       my_idx in [1,F] -> children are my_idx + l*F, l = 1..F
       my_idx > F      -> bottom of the tree, send to nobody
     (a flat high-radix tree; the reference deliberately drops Solana's
     "neighborhood" quirk the same way, fd_shred_dest.h:160-165).

Wire-exact (round 5, VERDICT r4 #7): every draw rides the reference's
MODE_SHIFT bounded-rand (fd_chacha20rng_ulong_roll with the power-of-two
rejection zone, fd_chacha20rng.h:196-201), so the shuffle — staked
weighted draws drained into unstaked swap-sampling on one stream —
matches the reference tree-for-tree.  Fixture-tested against the
compiled reference algorithm in tests/test_wsample_ref_conformance.py.
"""

import hashlib
import struct
from dataclasses import dataclass, field

from ..ballet import shred as shred_lib
from ..ballet.chacha20 import ChaCha20Rng
from ..ballet.wsample import WSample

NO_DEST = 0xFFFF
MAX_SHRED_CNT = 134  # DATA_SHREDS_MAX + PARITY_SHREDS_MAX (fd_shred_dest.h:23)


@dataclass
class Dest:
    """One potential shred destination (fd_shred_dest_weighted_t minus the
    mac field — routing below IP is the kernel's job here)."""

    pubkey: bytes
    stake: int = 0
    ip: str = ""
    port: int = 0

    @property
    def addr(self):
        return (self.ip, self.port)


def shred_seed(slot: int, idx: int, is_data: bool, leader_pubkey: bytes) -> bytes:
    """The 45-byte seed preimage (shred_dest_input_t, fd_shred_dest.c:26)."""
    return hashlib.sha256(
        struct.pack("<QBI", slot, 0xA5 if is_data else 0x5A, idx)
        + leader_pubkey).digest()


class ShredDest:
    """Turbine destination computer for one epoch's stake view.

    dests must be sorted stake-descending (ties by pubkey descending),
    unstaked (stake 0) at the end — the canonical Solana ordering the
    reference requires (fd_shred_dest.h:96-102).  source is this
    validator's identity pubkey and must appear in dests.
    """

    def __init__(self, dests: list[Dest], leaders, source: bytes):
        stakes = [d.stake for d in dests]
        if any(s > 0 and stakes[i - 1] < s for i, s in enumerate(stakes) if i):
            raise ValueError("dests not sorted stake-descending")
        self.dests = dests
        self.leaders = leaders  # slot -> leader pubkey (flamenco.leaders API)
        self.staked_cnt = sum(1 for d in dests if d.stake > 0)
        self.pubkey_to_idx = {d.pubkey: i for i, d in enumerate(dests)}
        if source not in self.pubkey_to_idx:
            raise ValueError("source pubkey not in dests")
        self.source = source
        self.source_idx = self.pubkey_to_idx[source]

    # -- the shuffle ----------------------------------------------------

    def _leader_for(self, slot: int) -> bytes:
        lead = self.leaders(slot) if callable(self.leaders) else \
            self.leaders.leader(slot)
        if lead is None:
            raise ValueError(f"no leader known for slot {slot}")
        return bytes(lead)

    def _shuffle(self, seed: bytes, leader_idx: int | None,
                 upto: int) -> list[int]:
        """First `upto` positions of the seeded shuffle of all dests with
        the leader removed: weighted staked prefix, then uniform unstaked
        (fd_shred_dest.c's wsample + swap-sampling, as one list)."""
        rng = ChaCha20Rng(seed)
        order: list[int] = []
        weights = [d.stake for d in self.dests[: self.staked_cnt]]
        if leader_idx is not None and leader_idx < self.staked_cnt:
            weights[leader_idx] = 0
        if any(w > 0 for w in weights):
            ws = WSample(weights, mode=ChaCha20Rng.MODE_SHIFT)
            n_staked = sum(1 for w in weights if w > 0)
            for _ in range(min(upto, n_staked)):
                order.append(ws.sample_and_remove(rng))
        if len(order) < upto:
            # unstaked tail: uniform sampling without replacement via the
            # reference's swap trick (fd_shred_dest.c:204-212)
            pool = [i for i in range(self.staked_cnt, len(self.dests))
                    if i != leader_idx]
            while pool and len(order) < upto:
                j = rng.roll_u64(len(pool), ChaCha20Rng.MODE_SHIFT)
                pool[j], pool[-1] = pool[-1], pool[j]
                order.append(pool.pop())
        return order

    # -- public API -----------------------------------------------------

    def compute_first(self, shreds: list[shred_lib.Shred]) -> list[int]:
        """Leader side: the Turbine root dest index for each shred
        (fd_shred_dest_compute_first)."""
        if not shreds:
            return []
        if len(self.dests) <= 1:
            return [NO_DEST] * len(shreds)
        slot = shreds[0].slot
        leader = self._leader_for(slot)
        out = []
        for s in shreds:
            if s.slot != slot:
                raise ValueError("shreds span slots")
            seed = shred_seed(slot, s.idx, s.is_data, leader)
            order = self._shuffle(seed, self.source_idx, 1)
            out.append(order[0] if order else NO_DEST)
        return out

    def compute_children(self, shreds: list[shred_lib.Shred], fanout: int,
                         dest_cnt: int | None = None) -> list[list[int]]:
        """Non-leader side: my children in each shred's tree
        (fd_shred_dest_compute_children; flat-tree duty table above)."""
        if dest_cnt is None:
            dest_cnt = fanout
        if not shreds or dest_cnt == 0:
            return [[] for _ in shreds]
        slot = shreds[0].slot
        leader = self._leader_for(slot)
        leader_idx = self.pubkey_to_idx.get(leader)
        if leader_idx == self.source_idx:
            raise ValueError("I am the leader: use compute_first")
        if len(self.dests) <= 1:
            return [[] for _ in shreds]
        out = []
        for s in shreds:
            if s.slot != slot:
                raise ValueError("shreds span slots")
            seed = shred_seed(slot, s.idx, s.is_data, leader)
            # worst case we need positions through my_idx + fanout^2
            upto = min(len(self.dests), fanout * fanout + fanout + 1)
            order = self._shuffle(seed, leader_idx, upto)
            try:
                my_idx = order.index(self.source_idx)
            except ValueError:
                out.append([])      # beyond the shuffled prefix: bottom
                continue
            if my_idx == 0:
                picks = order[1 : 1 + min(fanout, dest_cnt)]
            elif my_idx <= fanout:
                picks = [order[my_idx + l * fanout]
                         for l in range(1, fanout + 1)
                         if my_idx + l * fanout < len(order)][:dest_cnt]
            else:
                picks = []
            out.append(picks)
        return out

    def idx_to_dest(self, idx: int) -> Dest | None:
        return None if idx == NO_DEST or idx >= len(self.dests) \
            else self.dests[idx]


def sort_dests(dests: list[Dest]) -> list[Dest]:
    """Canonical Solana stake ordering: stake descending, ties by pubkey
    DESCENDING (fd_shred_dest.h:98-99); unstaked land at the end."""
    return sorted(dests, key=lambda d: (-d.stake, [-b for b in d.pubkey]))


class StakeCI:
    """Epoch-keyed stake + contact-info view (fd_stake_ci.c's role): stake
    weights arrive from replay/epoch boundaries, contact info from gossip;
    the product is a ShredDest for any slot whose epoch is known."""

    def __init__(self, identity: bytes, slots_per_epoch: int = 432_000):
        self.identity = identity
        self.slots_per_epoch = slots_per_epoch
        self.stakes: dict[int, dict[bytes, int]] = {}   # epoch -> stakes
        self.contact: dict[bytes, tuple[str, int]] = {}  # pubkey -> addr
        self._cache: dict[int, "ShredDest"] = {}

    def epoch_of(self, slot: int) -> int:
        return slot // self.slots_per_epoch

    def set_stakes(self, epoch: int, stakes: dict[bytes, int]):
        self.stakes[epoch] = dict(stakes)
        self._cache.pop(epoch, None)
        # retain a bounded history (the reference keeps 2 epochs)
        for e in sorted(self.stakes):
            if e < epoch - 1:
                del self.stakes[e]

    def set_contact(self, pubkey: bytes, ip: str, port: int):
        if self.contact.get(pubkey) != (ip, port):
            self.contact[pubkey] = (ip, port)
            self._cache.clear()

    def sdest_for(self, slot: int, leaders) -> ShredDest | None:
        epoch = self.epoch_of(slot)
        sd = self._cache.get(epoch)
        if sd is not None:
            return sd
        stakes = self.stakes.get(epoch)
        if stakes is None:
            return None
        keys = set(stakes) | set(self.contact) | {self.identity}
        dests = sort_dests([
            Dest(pk, stakes.get(pk, 0), *(self.contact.get(pk, ("", 0))))
            for pk in keys])
        sd = ShredDest(dests, leaders, self.identity)
        self._cache[epoch] = sd
        return sd
