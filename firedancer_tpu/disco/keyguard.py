"""Key isolation (ref: src/disco/keyguard — fd_keyguard.h:4-23, fd_keyload.c,
fd_keyguard_client.c).

Only the sign tile's process ever maps the private key; every other tile
sends role-typed signing requests over a dedicated link pair and receives a
64-byte signature back.  The sign tile validates that the payload shape is
legal for the requesting role before signing — a compromised requester tile
must not be able to extract signatures over arbitrary messages of another
role's type (the reference's core keyguard property).

Roles (fd_keyguard.h:19-23): leader (shred merkle roots), voter (vote txns),
gossip (crds values), tls (handshake transcripts).
"""

import json
import os
import time

ROLE_LEADER = 1    # 32-byte shred merkle root
ROLE_VOTER = 2     # serialized vote txn message
ROLE_GOSSIP = 3    # crds value pre-image
ROLE_TLS = 4       # TLS 1.3 transcript hash pre-image (130 bytes)
ROLE_REPAIR = 5    # domain-prefixed repair request pre-image

# domain || from[32] | type u8 | nonce u32 | slot u64 | idx u32.  The
# domain prefix (flamenco.repair.SIGN_DOMAIN) makes the set disjoint by
# construction: no CRDS signable can start with it without grinding an
# ed25519 pubkey whose first 13 bytes match (~2^104 work).
_REPAIR_DOMAIN = b"FDTPU_REPAIR\0"
_REPAIR_PREIMAGE_SZ = len(_REPAIR_DOMAIN) + 49


def _is_repair_preimage(msg: bytes) -> bool:
    return (len(msg) == _REPAIR_PREIMAGE_SZ
            and msg.startswith(_REPAIR_DOMAIN)
            and msg[len(_REPAIR_DOMAIN) + 32] in (0, 1, 2))

SIG_SZ = 64


def keypair_write(path: str, seed: bytes, pubkey: bytes):
    """Write an Agave-style JSON keypair file: 64 ints (seed || pubkey)."""
    with open(path, "w") as f:
        json.dump(list(seed + pubkey), f)
    os.chmod(path, 0o600)


def keypair_read(path: str) -> tuple[bytes, bytes]:
    """(seed, pubkey) from a JSON keypair file (ref fd_keyload_load: the
    reference also mlocks and guards the page; process isolation is our
    boundary here)."""
    with open(path) as f:
        raw = bytes(json.load(f))
    if len(raw) != 64:
        raise ValueError(f"bad keypair file {path}: {len(raw)} bytes")
    return raw[:32], raw[32:]


_TLS_PREFIX = b"\x20" * 64  # CertificateVerify context padding (RFC 8446)


def _parses_as_txn_message(msg: bytes):
    """Parse `msg` as the signed message region of a txn by prepending the
    signature vector its header demands (dummy sig bytes — the parse is
    structural); returns (txn, payload) or None."""
    from ..ballet import txn as txn_lib

    if not msg:
        return None
    # legacy message: byte 0 is num_required_signatures; versioned (V0+):
    # byte 0 is 0x80|version and num_required_signatures is byte 1
    if msg[0] & 0x80:
        if len(msg) < 2:
            return None
        n = msg[1]
    else:
        n = msg[0]
    if n == 0 or n > 12:  # FD_TXN_ACTUAL_SIG_MAX
        return None
    payload = bytes([n]) + bytes(64 * n) + msg
    try:
        return txn_lib.parse(payload), payload
    except txn_lib.TxnParseError:
        return None


def role_payload_ok(role: int, msg: bytes) -> bool:
    """The sign tile's request filter (fd_keyguard_payload_authorize
    analogue).  The sets accepted per role are mutually disjoint so a
    compromised tile of one role can never obtain a signature that is
    meaningful to another role's verifiers:

      LEADER  — exactly a 20/32-byte merkle root
      VOTER   — a txn message whose every instruction targets the vote
                program (so it can never move funds or sign gossip data)
      GOSSIP  — bounded blob that is NOT a merkle-root length, NOT a
                parseable txn message, NOT TLS-context-shaped, NOT a
                repair request pre-image
      TLS     — CertificateVerify content: 64 pad spaces + label + hash
      REPAIR  — exactly the 49-byte repair request pre-image
    """
    if role == ROLE_LEADER:
        return len(msg) in (20, 32)
    if role == ROLE_VOTER:
        from ..flamenco.types import VOTE_PROGRAM_ID

        parsed = _parses_as_txn_message(msg)
        if parsed is None:
            return False
        t, payload = parsed
        if not t.instrs:
            return False
        addrs = t.account_addrs(payload)
        return all(
            addrs[ix.program_id] == VOTE_PROGRAM_ID for ix in t.instrs
        )
    if role == ROLE_GOSSIP:
        if not 0 < len(msg) <= 1232 or len(msg) in (20, 32):
            return False
        # exclude the repair DOMAIN (not a length shape): CRDS signables
        # of any length stay signable — only a blob claiming the repair
        # signing domain is refused
        if msg.startswith(_TLS_PREFIX) or msg.startswith(_REPAIR_DOMAIN):
            return False
        return _parses_as_txn_message(msg) is None
    if role == ROLE_TLS:
        return 64 < len(msg) <= 130 and msg.startswith(_TLS_PREFIX)
    if role == ROLE_REPAIR:
        return _is_repair_preimage(msg)
    return False


class KeyguardClient:
    """Synchronous signing RPC over a request/response link pair
    (fd_keyguard_client_sign): publish role||msg on `req_out`, spin on the
    `resp_link` mcache for the signature frag.  One request in flight."""

    def __init__(self, ctx, req_out: str, resp_link: str):
        self._ctx = ctx
        self._out = ctx.out_index(req_out)
        jl = ctx.topo.links[resp_link]
        self._mc, self._dc = jl.mcache, jl.dcache
        self._seq = self._mc.seq_query()

    def sign(self, role: int, msg: bytes, timeout_s: float = 10.0) -> bytes:
        self._ctx.publish(bytes([role]) + msg, sig=role, out=self._out)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            rc, meta = self._mc.query(self._seq)
            if rc == 0:
                sz = int(meta["sz"])
                sig = self._dc.read(int(meta["chunk"]), sz)
                rc2, _ = self._mc.query(self._seq)  # seqlock re-check
                if rc2 != 0:
                    raise RuntimeError("keyguard response overrun")
                self._seq += 1
                if sz != SIG_SZ:
                    raise RuntimeError("keyguard refused request")
                return sig
            if rc == 1:  # overrun: resync (shouldn't happen 1-in-flight)
                self._seq = self._mc.seq_query()
            time.sleep(20e-6)
        raise TimeoutError("keyguard sign timed out")
