"""TPU stream reassembly (ref: src/disco/quic/fd_tpu.h:1-82,
fd_tpu_reasm.c): QUIC-stream/datagram payloads -> whole-txn publication
directly into the verify link.

Fixed slot pool with FIFO eviction of in-progress reassemblies and no
backpressure (fd_tpu.h:53-69: a slow verify consumer loses oldest partials,
never stalls the QUIC service loop).  The UDP "legacy TPU" path is the
degenerate case: prepare+append+publish per datagram.

DoS bound: `conn_budget` caps the buffered bytes any single conn (key[0])
may hold across its in-progress slots — evict-oldest of that conn's slots,
never grow — so one hostile peer cannot own the whole pool's memory.
Every lost slot is accounted: dup_cnt + evict_cnt + oversz_cnt cover each
prepare()d slot that never reached publish()/cancel().
"""

from collections import OrderedDict

TXN_MTU = 1232  # max serialized txn (fd_txn.h:92)


class TpuReasm:
    def __init__(self, depth: int, publish_fn, mtu: int = TXN_MTU,
                 conn_budget: int = 0):
        """publish_fn(payload: bytes) is called for each completed txn
        (the direct-into-mcache publication of the reference).
        conn_budget > 0 bounds buffered bytes per conn key[0]."""
        self.depth = depth
        self.mtu = mtu
        self.conn_budget = conn_budget
        self.publish_fn = publish_fn
        # key -> bytearray; ordered oldest-first for FIFO eviction
        self._slots: OrderedDict[tuple, bytearray] = OrderedDict()
        self._conn_bytes: dict = {}  # key[0] -> buffered bytes
        self.metrics = {"pub_cnt": 0, "evict_cnt": 0, "oversz_cnt": 0,
                        "dup_cnt": 0, "empty_cnt": 0}

    def _pop(self, key: tuple):
        """Every slot removal goes through here so the per-conn byte
        accounting never leaks."""
        buf = self._slots.pop(key, None)
        if buf is not None and len(buf):
            ck = key[0]
            left = self._conn_bytes.get(ck, 0) - len(buf)
            if left > 0:
                self._conn_bytes[ck] = left
            else:
                self._conn_bytes.pop(ck, None)
        return buf

    def prepare(self, key: tuple) -> bool:
        """Open a reassembly slot for stream `key` (conn_uid, stream_id).
        Evicts the oldest in-progress slot when full."""
        if key in self._slots:
            self.metrics["dup_cnt"] += 1
            self._pop(key)
        while len(self._slots) >= self.depth:
            self._pop(next(iter(self._slots)))
            self.metrics["evict_cnt"] += 1
        self._slots[key] = bytearray()
        return True

    def append(self, key: tuple, data: bytes) -> bool:
        buf = self._slots.get(key)
        if buf is None:
            return False  # evicted mid-stream; frags dropped
        if len(buf) + len(data) > self.mtu:
            self.metrics["oversz_cnt"] += 1
            self._pop(key)
            return False
        ck = key[0]
        if self.conn_budget:
            used = self._conn_bytes.get(ck, 0)
            if used + len(data) > self.conn_budget:
                # evict-oldest among THIS conn's other slots; never grow
                for old in list(self._slots):
                    if used + len(data) <= self.conn_budget:
                        break
                    if old == key or old[0] != ck:
                        continue
                    used -= len(self._slots[old])
                    self._pop(old)
                    self.metrics["evict_cnt"] += 1
                if used + len(data) > self.conn_budget:
                    # the stream itself busts the budget
                    self._pop(key)
                    self.metrics["evict_cnt"] += 1
                    return False
        buf += data
        self._conn_bytes[ck] = self._conn_bytes.get(ck, 0) + len(data)
        return True

    def publish(self, key: tuple) -> bool:
        """Stream finished: emit the txn downstream."""
        buf = self._pop(key)
        if buf is None:
            return False
        self.publish_fn(bytes(buf))
        self.metrics["pub_cnt"] += 1
        return True

    def cancel(self, key: tuple):
        self._pop(key)

    def publish_datagram(self, data: bytes) -> bool:
        """Legacy UDP TPU: one datagram = one whole txn
        (run/tiles/fd_quic.c:155-165 during_frag fast path)."""
        if not data:
            self.metrics["empty_cnt"] += 1
            return False
        if len(data) > self.mtu:
            self.metrics["oversz_cnt"] += 1
            return False
        self.publish_fn(data)
        self.metrics["pub_cnt"] += 1
        return True
