"""TPU stream reassembly (ref: src/disco/quic/fd_tpu.h:1-82,
fd_tpu_reasm.c): QUIC-stream/datagram payloads -> whole-txn publication
directly into the verify link.

Fixed slot pool with FIFO eviction of in-progress reassemblies and no
backpressure (fd_tpu.h:53-69: a slow verify consumer loses oldest partials,
never stalls the QUIC service loop).  The UDP "legacy TPU" path is the
degenerate case: prepare+append+publish per datagram.
"""

from collections import OrderedDict

TXN_MTU = 1232  # max serialized txn (fd_txn.h:92)


class TpuReasm:
    def __init__(self, depth: int, publish_fn, mtu: int = TXN_MTU):
        """publish_fn(payload: bytes) is called for each completed txn
        (the direct-into-mcache publication of the reference)."""
        self.depth = depth
        self.mtu = mtu
        self.publish_fn = publish_fn
        # key -> bytearray; ordered oldest-first for FIFO eviction
        self._slots: OrderedDict[tuple, bytearray] = OrderedDict()
        self.metrics = {"pub_cnt": 0, "evict_cnt": 0, "oversz_cnt": 0,
                        "dup_cnt": 0, "empty_cnt": 0}

    def prepare(self, key: tuple) -> bool:
        """Open a reassembly slot for stream `key` (conn_uid, stream_id).
        Evicts the oldest in-progress slot when full."""
        if key in self._slots:
            self.metrics["dup_cnt"] += 1
            self._slots.pop(key)
        while len(self._slots) >= self.depth:
            self._slots.popitem(last=False)
            self.metrics["evict_cnt"] += 1
        self._slots[key] = bytearray()
        return True

    def append(self, key: tuple, data: bytes) -> bool:
        buf = self._slots.get(key)
        if buf is None:
            return False  # evicted mid-stream; frags dropped
        if len(buf) + len(data) > self.mtu:
            self.metrics["oversz_cnt"] += 1
            self._slots.pop(key)
            return False
        buf += data
        return True

    def publish(self, key: tuple) -> bool:
        """Stream finished: emit the txn downstream."""
        buf = self._slots.pop(key, None)
        if buf is None:
            return False
        self.publish_fn(bytes(buf))
        self.metrics["pub_cnt"] += 1
        return True

    def cancel(self, key: tuple):
        self._slots.pop(key, None)

    def publish_datagram(self, data: bytes) -> bool:
        """Legacy UDP TPU: one datagram = one whole txn
        (run/tiles/fd_quic.c:155-165 during_frag fast path)."""
        if not data:
            self.metrics["empty_cnt"] += 1
            return False
        if len(data) > self.mtu:
            self.metrics["oversz_cnt"] += 1
            return False
        self.publish_fn(data)
        self.metrics["pub_cnt"] += 1
        return True
