"""The minimum end-to-end verify slice (SURVEY.md §7.4): txn bytes in,
per-txn verdicts out.

Mirrors the verify tile's processing contract
(src/app/fdctl/run/tiles/fd_verify.c after_frag -> fd_txn_verify,
fd_verify.h:43-88): parse -> tcache pre-dedup on the first 64 sig bits ->
batched ed25519 verify -> per-txn accept iff every signature passes.

The TPU twist vs the reference's synchronous in-tile loop: signatures from
many txns are coalesced into ONE fixed-shape device batch (wiredancer's
async-offload insertion point, SURVEY.md §3.2), so per-batch latency is
device round-trip + coalescing window, amortized over thousands of lanes.
"""

from dataclasses import dataclass, field
import time

import jax.numpy as jnp
import numpy as np

from ..ballet import txn as txn_lib
from ..tango.tcache import TCache
from ..utils.hist import Histf


@dataclass
class VerifyMetrics:
    """Counter block, the shape of the reference's per-tile metrics region
    (src/disco/metrics/metrics.xml verify tile)."""

    txns_in: int = 0
    parse_fail: int = 0
    dedup_drop: int = 0
    too_long_drop: int = 0
    sig_overflow_drop: int = 0
    verify_fail: int = 0
    verify_pass: int = 0
    batches: int = 0
    batch_ns: Histf = field(default_factory=lambda: Histf(1_000, 60_000_000_000))

    def snapshot(self) -> dict:
        d = {k: getattr(self, k) for k in (
            "txns_in", "parse_fail", "dedup_drop", "too_long_drop",
            "sig_overflow_drop", "verify_fail", "verify_pass", "batches")}
        d["batch_ns_p50"] = self.batch_ns.percentile(0.50)
        d["batch_ns_p99"] = self.batch_ns.percentile(0.99)
        return d


@dataclass
class _Pending:
    payload: bytes
    parsed: txn_lib.Txn
    lanes: list[int]  # indices into the open batch
    tag: int  # dedup tag (low 64 bits of first sig), computed once in submit()


class VerifyPipeline:
    """Fixed-shape batching verify pipeline.

    batch:      device lanes per verify call (one lane = one signature)
    msg_maxlen: message-byte bucket; txns with longer messages are dropped
                (production would use multiple buckets; MTU-sized messages
                need msg_maxlen >= 1231)
    tcache_depth: dedup window in distinct signatures (fd_dedup tile default
                is ~2M; tests use small windows)
    """

    def __init__(self, verify_fn, batch: int, msg_maxlen: int, tcache_depth: int = 1 << 16):
        self.verify_fn = verify_fn
        self.batch = batch
        self.msg_maxlen = msg_maxlen
        self.tcache = TCache(tcache_depth)
        self.metrics = VerifyMetrics()
        self._reset_open_batch()

    def _reset_open_batch(self):
        self._msgs = np.zeros((self.batch, self.msg_maxlen), dtype=np.uint8)
        self._lens = np.zeros((self.batch,), dtype=np.int32)
        self._sigs = np.zeros((self.batch, 64), dtype=np.uint8)
        self._pubs = np.zeros((self.batch, 32), dtype=np.uint8)
        self._used = 0
        self._pending: list[_Pending] = []

    def submit(self, payload: bytes) -> list[tuple[bytes, txn_lib.Txn]]:
        """Feed one serialized txn.  Returns verified txns flushed by this
        submit (empty unless the open batch filled and was dispatched)."""
        self.metrics.txns_in += 1
        try:
            parsed = txn_lib.parse(payload)
        except txn_lib.TxnParseError:
            self.metrics.parse_fail += 1
            return []

        msg = parsed.message(payload)
        if len(msg) > self.msg_maxlen:
            self.metrics.too_long_drop += 1
            return []

        sigs = parsed.signatures(payload)
        if len(sigs) > self.batch:
            # a txn's sig lanes must fit one device batch; batch >= 12
            # (FD_TXN_ACTUAL_SIG_MAX) covers every wire-valid txn
            self.metrics.sig_overflow_drop += 1
            return []
        # pre-dedup on the low 64 bits of the first signature
        # (fd_verify.h:64-71; the full-sig dedup tile runs downstream).
        # Query-only here; the tag is inserted only after verify PASSES in
        # flush() — inserting pre-verify would let an attacker poison the
        # window with a mangled copy and block the valid retransmission.
        tag = int.from_bytes(sigs[0][:8], "little")
        if self.tcache.query(tag):
            self.metrics.dedup_drop += 1
            return []

        out = []
        if self._used + len(sigs) > self.batch:
            out = self.flush()
        pubs = parsed.signer_pubkeys(payload)
        lanes = []
        for s, p in zip(sigs, pubs):
            lane = self._used
            self._msgs[lane, : len(msg)] = np.frombuffer(msg, dtype=np.uint8)
            self._lens[lane] = len(msg)
            self._sigs[lane] = np.frombuffer(s, dtype=np.uint8)
            self._pubs[lane] = np.frombuffer(p, dtype=np.uint8)
            lanes.append(lane)
            self._used += 1
        self._pending.append(_Pending(payload, parsed, lanes, tag))
        if self._used == self.batch:
            out += self.flush()
        return out

    def flush(self) -> list[tuple[bytes, txn_lib.Txn]]:
        """Dispatch the open batch; returns [(payload, parsed)] that passed."""
        if not self._pending:
            return []
        t0 = time.perf_counter_ns()
        ok = np.asarray(
            self.verify_fn(
                jnp.asarray(self._msgs),
                jnp.asarray(self._lens),
                jnp.asarray(self._sigs),
                jnp.asarray(self._pubs),
            )
        )
        self.metrics.batches += 1
        self.metrics.batch_ns.sample(time.perf_counter_ns() - t0)

        out = []
        for p in self._pending:
            if all(ok[lane] for lane in p.lanes):
                if self.tcache.insert(p.tag):
                    # same tag verified twice inside one open batch window
                    self.metrics.dedup_drop += 1
                    continue
                self.metrics.verify_pass += 1
                out.append((p.payload, p.parsed))
            else:
                self.metrics.verify_fail += 1
        self._reset_open_batch()
        return out
