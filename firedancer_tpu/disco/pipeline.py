"""The minimum end-to-end verify slice (SURVEY.md §7.4): txn bytes in,
per-txn verdicts out.

Mirrors the verify tile's processing contract
(src/app/fdctl/run/tiles/fd_verify.c after_frag -> fd_txn_verify,
fd_verify.h:43-88): parse -> tcache pre-dedup on the first 64 sig bits ->
batched ed25519 verify -> per-txn accept iff every signature passes.

The TPU twist vs the reference's synchronous in-tile loop: signatures from
many txns are coalesced into fixed-shape device batches (wiredancer's
async-offload insertion point, SURVEY.md §3.2), so per-batch latency is
device round-trip + coalescing window, amortized over thousands of lanes.

Message-length buckets: XLA graphs are fixed-shape, so the pipeline keeps
several compiled (batch, msg_maxlen) buckets and routes each txn to the
smallest bucket that fits its message — small transfers fill the wide
fast bucket while full-MTU txns (wire MTU 1232, ref
src/ballet/txn/fd_txn.h:92-103) go to a narrower full-width bucket instead
of being dropped.  This is the same compile-time-batch-specialization game
the reference plays with SIMD widths (fd_sha512.h:266-361).
"""

from collections import deque
from dataclasses import dataclass, field
import time

import jax.numpy as jnp
import numpy as np

from ..ballet import txn as txn_lib
from ..tango.tcache import TCache
from ..utils.hist import Histf


def _is_ready(dev) -> bool:
    """Non-blocking completion poll on a dispatched device array (jax
    arrays grew .is_ready() long ago; anything without it is host data
    and trivially ready)."""
    fn = getattr(dev, "is_ready", None)
    return True if fn is None else bool(fn())

# default bucket ladder: (lanes, msg_maxlen); covers through the wire MTU
DEFAULT_BUCKETS = ((2048, 256), (256, 768), (64, 1232))


@dataclass
class VerifyMetrics:
    """Counter block, the shape of the reference's per-tile metrics region
    (src/disco/metrics/metrics.xml verify tile)."""

    txns_in: int = 0
    parse_fail: int = 0
    dedup_drop: int = 0
    too_long_drop: int = 0
    sig_overflow_drop: int = 0
    verify_fail: int = 0
    verify_pass: int = 0
    batches: int = 0
    batch_ns: Histf = field(default_factory=lambda: Histf(1_000, 60_000_000_000))

    def snapshot(self) -> dict:
        d = {k: getattr(self, k) for k in (
            "txns_in", "parse_fail", "dedup_drop", "too_long_drop",
            "sig_overflow_drop", "verify_fail", "verify_pass", "batches")}
        d["batch_ns_p50"] = self.batch_ns.percentile(0.50)
        d["batch_ns_p99"] = self.batch_ns.percentile(0.99)
        return d


@dataclass
class _Pending:
    payload: bytes
    parsed: txn_lib.Txn
    lanes: list[int]  # indices into the bucket's open batch
    tag: int  # dedup tag (low 64 bits of first sig), computed once in submit()


@dataclass
class _Inflight:
    """A dispatched-but-unharvested device batch (wiredancer's in-flight
    request set, src/wiredancer/c/wd_f1.h:85-113: results come back
    asynchronously and are matched to requests on completion)."""

    ok_dev: object            # jax array future of per-lane pass bits
    pending: list             # the _Pending txns of that batch
    t0: int                   # dispatch timestamp (ns)


class _Bucket:
    """One compiled (batch, msg_maxlen) shape with its open batch."""

    def __init__(self, batch: int, maxlen: int):
        self.batch = batch
        self.maxlen = maxlen
        self.reset()

    def reset(self):
        self.msgs = np.zeros((self.batch, self.maxlen), dtype=np.uint8)
        self.lens = np.zeros((self.batch,), dtype=np.int32)
        self.sigs = np.zeros((self.batch, 64), dtype=np.uint8)
        self.pubs = np.zeros((self.batch, 32), dtype=np.uint8)
        self.used = 0
        self.pending: list[_Pending] = []


class VerifyPipeline:
    """Fixed-shape batching verify pipeline.

    Single-bucket form (tests, latency tiers):
        VerifyPipeline(fn, batch=B, msg_maxlen=L)
    Multi-bucket form (production: full-MTU coverage):
        VerifyPipeline(fn, buckets=[(2048, 256), (256, 768), (64, 1232)])

    verify_fn must be shape-polymorphic (a jitted ed.verify_batch / a
    SigVerifier recompiles per bucket shape on first use).
    tcache_depth: dedup window in distinct signatures (fd_dedup tile default
    is ~2M; tests use small windows).
    """

    def __init__(self, verify_fn, batch: int | None = None,
                 msg_maxlen: int | None = None, tcache_depth: int = 1 << 16,
                 buckets=None, max_inflight: int = 0):
        if buckets is None:
            if batch is None or msg_maxlen is None:
                raise ValueError("need either (batch, msg_maxlen) or buckets")
            buckets = ((batch, msg_maxlen),)
        self.verify_fn = verify_fn
        self.buckets = [
            _Bucket(b, m) for b, m in sorted(buckets, key=lambda t: t[1])
        ]
        # legacy single-bucket attributes (tests introspect these)
        self.batch = self.buckets[0].batch
        self.msg_maxlen = self.buckets[-1].maxlen
        self.tcache = TCache(tcache_depth)
        self.metrics = VerifyMetrics()
        # max_inflight > 0 enables the ASYNC data plane (wiredancer's
        # contract): a filled batch is dispatched without waiting, up to
        # max_inflight batches ride the device queue, and completed
        # batches are harvested in order by harvest() / submit().  0 =
        # synchronous (verdicts returned by the submit that fills a
        # batch — the simple form tests use).
        self.max_inflight = max_inflight
        self.inflight: deque[_Inflight] = deque()

    @property
    def has_pending(self) -> bool:
        return any(bk.pending for bk in self.buckets) or bool(self.inflight)

    def _bucket_for(self, msg_len: int) -> _Bucket | None:
        for bk in self.buckets:  # sorted by maxlen: smallest fitting bucket
            if msg_len <= bk.maxlen:
                return bk
        return None

    def submit(self, payload: bytes) -> list[tuple[bytes, txn_lib.Txn]]:
        """Feed one serialized txn.  Returns verified txns flushed by this
        submit (empty unless an open batch filled and was dispatched)."""
        self.metrics.txns_in += 1
        try:
            parsed = txn_lib.parse(payload)
        except txn_lib.TxnParseError:
            self.metrics.parse_fail += 1
            return []

        msg = parsed.message(payload)
        bk = self._bucket_for(len(msg))
        if bk is None:
            self.metrics.too_long_drop += 1
            return []

        sigs = parsed.signatures(payload)
        if len(sigs) > bk.batch:
            # a txn's sig lanes must fit one device batch; batch >= 12
            # (FD_TXN_ACTUAL_SIG_MAX) covers every wire-valid txn
            self.metrics.sig_overflow_drop += 1
            return []
        # pre-dedup on the low 64 bits of the first signature
        # (fd_verify.h:64-71; the full-sig dedup tile runs downstream).
        # Query-only here; the tag is inserted only after verify PASSES in
        # flush() — inserting pre-verify would let an attacker poison the
        # window with a mangled copy and block the valid retransmission.
        tag = int.from_bytes(sigs[0][:8], "little")
        if self.tcache.query(tag):
            self.metrics.dedup_drop += 1
            return []

        out = []
        if bk.used + len(sigs) > bk.batch:
            out = self._flush_bucket(bk)
        pubs = parsed.signer_pubkeys(payload)
        lanes = []
        for s, p in zip(sigs, pubs):
            lane = bk.used
            bk.msgs[lane, : len(msg)] = np.frombuffer(msg, dtype=np.uint8)
            bk.lens[lane] = len(msg)
            bk.sigs[lane] = np.frombuffer(s, dtype=np.uint8)
            bk.pubs[lane] = np.frombuffer(p, dtype=np.uint8)
            lanes.append(lane)
            bk.used += 1
        bk.pending.append(_Pending(payload, parsed, lanes, tag))
        if bk.used == bk.batch:
            out += self._flush_bucket(bk)
        return out

    def flush(self) -> list[tuple[bytes, txn_lib.Txn]]:
        """Dispatch every bucket with pending txns and harvest EVERYTHING
        (blocking); returns passing txns."""
        out = []
        for bk in self.buckets:
            out += self._flush_bucket(bk)
        out += self.harvest(block=True)
        return out

    def dispatch_open(self) -> list[tuple[bytes, txn_lib.Txn]]:
        """Age-flush for the async tile: dispatch partially-filled buckets
        WITHOUT waiting for their results (they surface via harvest());
        any already-completed batches are returned."""
        out = []
        for bk in self.buckets:
            out += self._flush_bucket(bk)
        return out

    def harvest(self, block: bool = False) -> list[tuple[bytes, txn_lib.Txn]]:
        """Collect verdicts of completed in-flight batches, in dispatch
        order.  block=False stops at the first still-running batch (the
        tile's after_credit poll); block=True drains the queue."""
        out = []
        while self.inflight:
            if not block and not _is_ready(self.inflight[0].ok_dev):
                break
            out += self._finish(self.inflight.popleft())
        return out

    def _flush_bucket(self, bk: _Bucket) -> list[tuple[bytes, txn_lib.Txn]]:
        if not bk.pending:
            return []
        t0 = time.perf_counter_ns()
        # jax dispatch is asynchronous: this returns a device future
        # without waiting for the TPU
        ok_dev = self.verify_fn(
            jnp.asarray(bk.msgs),
            jnp.asarray(bk.lens),
            jnp.asarray(bk.sigs),
            jnp.asarray(bk.pubs),
        )
        fl = _Inflight(ok_dev, bk.pending, t0)
        bk.reset()
        if self.max_inflight <= 0:
            return self._finish(fl)          # synchronous mode
        self.inflight.append(fl)
        out = []
        while len(self.inflight) > self.max_inflight:
            # bounded queue: retire the oldest before accepting more
            out += self._finish(self.inflight.popleft())
        return out + self.harvest()

    def _finish(self, fl: _Inflight) -> list[tuple[bytes, txn_lib.Txn]]:
        ok = np.asarray(fl.ok_dev)           # blocks only if still running
        self.metrics.batches += 1
        self.metrics.batch_ns.sample(time.perf_counter_ns() - fl.t0)
        out = []
        for p in fl.pending:
            if all(ok[lane] for lane in p.lanes):
                if self.tcache.insert(p.tag):
                    # same tag verified twice inside one open batch window
                    self.metrics.dedup_drop += 1
                    continue
                self.metrics.verify_pass += 1
                out.append((p.payload, p.parsed))
            else:
                self.metrics.verify_fail += 1
        return out
